#
# Selection plane — THE top-k module for the whole search stack.
#
# Every top-k in the kNN/ANN family (exact_knn_*, ivfflat/ivfpq/cagra search,
# the streamed ANN probe scans, the pairwise item-tile merges, and the kmeans/
# tree score picks) routes through here; the analyzer (fence/topk-off-plane) bans direct
# jax.lax.top_k / jax.lax.approx_max_k anywhere else under ops/. Three
# strategies behind one API, picked by `knn.selection` (config.py):
#
#   exact_full   one full-width lax.top_k over the candidate axis (the
#                pre-selection-plane behavior, bit-for-bit).
#   exact_tiled  two-stage: reshape the candidate axis into tiles, a small
#                per-tile top-k, then a second top-k over the (tiles*k) pool.
#                EXACT — bit-for-bit equal to exact_full including tie order
#                (ties resolve lowest-index-first in both: within a tile the
#                per-tile top-k is index-stable, and pool positions are
#                tile-major so cross-tile ties also resolve by global index).
#                On TPU the small fixed-width per-tile selects vectorize on
#                the VPU where the full-width top_k lowers to sort passes; on
#                CPU the XLA TopK custom call is per-call-overhead-bound, so
#                the auto tile keeps the tile count small (see _auto_tile).
#   approx       jax.lax.approx_max_k (the TPU's native approximate-selection
#                unit, PartialReduce) at `knn.recall_target`. Callers that owe
#                the user exact distances (exact_knn_single and everything
#                stacked on it) follow with a parity-precision re-rank of the
#                winner pool (ops/knn.py::parity_rerank_sq) so returned
#                distances stay exact; recall of the id set is >= the target.
#   pallas_fused the fused Pallas distance+select scan (ops/pallas_select.py,
#                docs/design.md §5c): the (block, n_items) distance tile and
#                the running top-k/argmin/count live in VMEM registers, so the
#                distance matrix is NEVER materialized in HBM — X streams
#                through once per scan. Only FUSABLE call sites (the host
#                wrappers that hold Q and X, not a materialized d2) can run
#                it: `resolve(fusable=True)` marks them, and a d2-level
#                select asked for `pallas_fused` degrades to exact_full.
#                Exact-f32 mode is bit-identical to exact_full (tie order
#                included); `knn.pallas_precision` bf16/int8 modes select an
#                approximate candidate pool and the parity_rerank_sq
#                invariant restores exact returned distances.
#
# MERGES STAY EXACT: a running top-k merge (pairwise tile sweeps, the ring
# hop merge, the all-gather candidate merge) must never lose carried
# candidates, so merge pools always select with exact_full — the configured
# strategy applies to the per-tile/per-shard candidate selection feeding the
# pool, where the width (and the win) is.
#
# Invalid-entry convention: masked/padded candidates are set to INVALID_D2, a
# LARGE FINITE sentinel (f32max/2), never jnp.inf — inf entries surviving into
# a downstream recomputation (inf - inf) are NaN factories, and NaN never
# sorts. select_topk additionally clamps its input at INVALID_D2 so even a
# caller-provided inf (e.g. an overflowed distance) keeps exact_full and
# exact_tiled bit-identical. The -1-id / inf-distance OUTPUT contract of the
# search entry points is unchanged: they restore inf at the boundary from the
# id mask, not from the selection values.
#

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Large-finite invalid sentinel: big enough that no real squared distance on
# f32 inputs reaches it before the clamp, small enough that sums/differences
# of two sentinels stay finite (f32max/2 + f32max/2 == f32max, no overflow).
INVALID_D2 = np.float32(np.finfo(np.float32).max / 2)

STRATEGIES = ("auto", "exact_full", "exact_tiled", "approx", "pallas_fused")

# distance-accumulation modes of the fused pallas scan (knn.pallas_precision):
# float32 is bit-exact; bfloat16/int8 pair with the parity_rerank_sq re-rank
FUSED_PRECISIONS = ("float32", "bfloat16", "int8")


def mask_invalid(d2: jax.Array, valid: jax.Array) -> jax.Array:
    """Mask invalid candidate positions with the large-finite sentinel (NOT
    inf — see module header). `valid` broadcasts against d2."""
    return jnp.where(valid, d2, INVALID_D2)


def _backend() -> str:
    try:
        return jax.default_backend()
    except Exception:  # pragma: no cover - backend probe must never fail a fit
        return "cpu"


def _auto_tile(n: int, backend: str) -> int:
    """Platform tile DEFAULT when no tuning-table entry covers the bucket:
    on TPU small fixed tiles vectorize the per-tile select on the VPU; on CPU
    each TopK custom call pays per-call overhead, so keep the tile count
    small. The values live in autotune/defaults.py (the knob-registry
    defaults module); measured per-bucket choices live in the tuning table,
    whose entries carry their own `provenance` field (docs/design.md §6i)."""
    from ..autotune.defaults import default_select_tile

    return default_select_tile(n, backend)


def _fused_auto(n: int) -> bool:
    """Should `auto` hand a FUSABLE width-n scan to the fused pallas kernel?
    TPU only (off-TPU the kernel runs the Pallas interpreter — a correctness
    tool, not a fast path), and only once the scanned item width clears the
    `pallas.min_items` threshold (tuning table, else `knn.pallas_min_items`;
    small scans don't pay back the kernel's in-register selection work)."""
    if _backend() != "tpu":
        return False
    from .. import autotune as _autotune
    from .. import config as _config

    min_items = _autotune.lookup("pallas.min_items")
    if min_items is None:
        min_items = int(_config.get("knn.pallas_min_items"))
    return n >= int(min_items)


def resolve_fused_precision(precision: Optional[str] = None) -> str:
    """Resolve the fused scan's distance-accumulation mode
    (`knn.pallas_precision` unless the caller pinned one). Host-side — like
    `resolve`, so a config change can never be baked stale into a cached
    trace. Resolution order: caller-pinned > config set()/env > tuning table
    > default (the table may only steer this knob because every consuming
    site pairs non-f32 modes with the parity_rerank_sq exactness invariant —
    returned distances stay exact-f32 either way). Non-float32 modes REQUIRE
    the caller to follow with that re-rank."""
    from .. import autotune as _autotune
    from .. import config as _config

    if precision is None:
        precision = _autotune.lookup("pallas.precision")
    if precision is None:
        precision = str(_config.get("knn.pallas_precision"))
    if precision not in FUSED_PRECISIONS:
        raise ValueError(
            f"knn.pallas_precision must be one of {FUSED_PRECISIONS}, "
            f"got '{precision}'"
        )
    return precision


def resolve(
    n: int,
    k: int,
    strategy: Optional[str] = None,
    tile: Optional[int] = None,
    recall_target: Optional[float] = None,
    fusable: bool = False,
) -> Tuple[str, int, float]:
    """Resolve (strategy, tile, recall_target) for a width-n, top-k select.

    Reads config only for the pieces the caller left None, so jitted kernels
    that receive the resolved triple as static arguments never consult config
    at trace time (a stale traced strategy could otherwise outlive a config
    change). Degradations keep small selects on the fused exact path:
    tiled/approx fall back to exact_full when the width is a single tile or
    within 4x of k (the pool would be the whole input).

    `fusable=True` marks call sites that hold Q and X (not a materialized d2
    matrix) and can therefore run the fused pallas distance+select scan
    (ops/pallas_select.py): under `auto` on TPU such a site picks
    `pallas_fused` once n >= knn.pallas_min_items. A NON-fusable site asked
    for `pallas_fused` (explicitly or via a threaded resolved value) degrades
    to exact_full — there is nothing left to fuse once d2 exists, and
    exact_full preserves the fused scan's bit-exact contract."""
    from .. import config as _config

    if strategy is None:
        strategy = str(_config.get("knn.selection"))
    if strategy not in STRATEGIES:
        raise ValueError(
            f"knn.selection must be one of {STRATEGIES}, got '{strategy}'"
        )
    if strategy == "auto":
        if fusable and _fused_auto(n):
            strategy = "pallas_fused"
        else:
            # tuning table first (docs/design.md §6i): a measured per-bucket
            # strategy beats the platform heuristic. A REAL set()/env pin on
            # knn.selection never reaches here (strategy wasn't "auto"), and
            # lookup() itself treats a pin to the literal sentinel "auto" as
            # "choose for me" — the table slots between env and the default
            from .. import autotune as _autotune

            tuned = _autotune.lookup("selection.strategy", n=n, k=k)
            if tuned is not None and (fusable or tuned != "pallas_fused"):
                strategy = tuned
            else:
                strategy = "approx" if _backend() == "tpu" else "exact_tiled"
    if strategy == "pallas_fused" and not fusable:
        strategy = "exact_full"
    # degradations: k-of-n selects with no real pool reduction run fused
    # exact. The tile term applies ONLY to exact_tiled — tying approx to the
    # tile width would silently disable the approx path (and its parity
    # re-rank) everywhere the platform auto-tile exceeds the data, leaving it
    # untested off-TPU and surprising users who asked for it explicitly.
    if k >= n or n <= 4 * k:
        strategy = "exact_full"
    if strategy == "exact_tiled":
        if tile is None:
            tile = int(_config.get("knn.select_tile") or 0)
        if tile <= 0:
            # tuning table between config and the platform heuristic: a
            # nonzero knn.select_tile (set()/env) took the branch above
            from .. import autotune as _autotune

            tuned = _autotune.lookup("selection.tile", n=n, k=k)
            tile = int(tuned) if tuned is not None else _auto_tile(n, _backend())
        if n <= tile:
            strategy = "exact_full"
    # knn.recall_target is read/validated ONLY when approx actually runs:
    # exact modes documentedly ignore it (a bad value must not crash exact
    # searches), and the forced-exact calls inside jitted kernels
    # (merge_topk, loop-carried selects) must not consult config at trace
    # time at all.
    if strategy == "approx":
        if recall_target is None:
            recall_target = float(_config.get("knn.recall_target"))
        if not 0.0 < recall_target <= 1.0:
            raise ValueError(
                f"knn.recall_target must be in (0, 1], got {recall_target}"
            )
    if tile is None:
        tile = 0  # unused by exact_full/approx; keep the static arg stable
    if recall_target is None:
        recall_target = 1.0  # unused outside approx
    return strategy, int(tile), float(recall_target)


def _tiled_topk_neg(neg: jax.Array, k: int, tile: int) -> Tuple[jax.Array, jax.Array]:
    """Two-stage largest-k of `neg` along the last axis (exact, tie order ==
    lax.top_k's lowest-index-first). Padding uses -INVALID_D2 and pads sit at
    the highest indices of the last tile, so they lose every tie."""
    *lead, n = neg.shape
    pad = (-n) % tile
    if pad:
        neg = jnp.pad(neg, [(0, 0)] * len(lead) + [(0, pad)],
                      constant_values=-INVALID_D2)
    nt = (n + pad) // tile
    kk = min(k, tile)
    negt = neg.reshape(*lead, nt, tile)
    v, i = jax.lax.top_k(negt, kk)  # selection-plane primitive home (fence-exempt file)
    base = (jnp.arange(nt, dtype=jnp.int32) * tile).reshape(
        (1,) * len(lead) + (nt, 1)
    )
    pool_v = v.reshape(*lead, nt * kk)
    pool_i = (i.astype(jnp.int32) + base).reshape(*lead, nt * kk)
    v2, p2 = jax.lax.top_k(pool_v, k)  # selection-plane primitive home (fence-exempt file)
    return v2, jnp.take_along_axis(pool_i, p2, axis=-1)


def select_topk(
    d2: jax.Array,
    k: int,
    *,
    strategy: str,
    tile: Optional[int] = None,
    recall_target: Optional[float] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Smallest-k along the last axis: returns (d2_topk, indices), distances
    ascending. TRACE-PURE by contract (tools/analysis purity/*): this
    function reads no config and consults no tuning table — `strategy` must
    arrive CONCRETE from a host-side `resolve()` call, so a cached trace can
    never bake a stale choice. Only the pure degradations live here: a
    k-of-n select with no real pool reduction (k >= n, n <= 4k, n within one
    tile) runs fused exact, and `pallas_fused` degrades to exact_full (a
    d2-level select can't fuse — the matrix already exists)."""
    n = d2.shape[-1]
    k = min(int(k), n)
    if strategy is None or strategy == "auto":
        raise ValueError(
            "select_topk requires a concrete strategy — call "
            "ops.selection.resolve() in the HOST wrapper and pass the "
            "resolved triple down (trace-purity contract, docs/design.md §6j)"
        )
    if strategy not in STRATEGIES:
        raise ValueError(
            f"knn.selection must be one of {STRATEGIES}, got '{strategy}'"
        )
    if strategy == "pallas_fused" or k >= n or n <= 4 * k:
        strategy = "exact_full"
    if strategy == "exact_tiled" and (not tile or n <= tile):
        strategy = "exact_full"
    if strategy == "approx":
        if recall_target is None:
            raise ValueError(
                "select_topk(strategy='approx') requires a concrete "
                "recall_target — resolve() in the host wrapper provides one"
            )
        if not 0.0 < recall_target <= 1.0:
            raise ValueError(
                f"knn.recall_target must be in (0, 1], got {recall_target}"
            )
    # clamp: inf (or beyond-sentinel) entries would rank after tiled padding
    # and break exact_full/exact_tiled bit-parity; after the clamp every
    # strategy sees identical values and ties resolve identically
    d2 = jnp.minimum(d2, INVALID_D2)
    if strategy == "exact_tiled":
        neg, idx = _tiled_topk_neg(-d2, k, tile)
    elif strategy == "approx":
        neg, idx = jax.lax.approx_max_k(  # selection-plane primitive home (fence-exempt file)
            -d2, k, recall_target=recall_target
        )
    else:
        neg, idx = jax.lax.top_k(-d2, k)  # selection-plane primitive home (fence-exempt file)
    return -neg, idx


def merge_topk(
    pool_d2: jax.Array, pool_ids: jax.Array, k: int
) -> Tuple[jax.Array, jax.Array]:
    """Exact top-k over an already-selected candidate pool (running-merge
    steps: ring hops, all-gather merges, pairwise tile folds). ALWAYS
    exact_full — an approximate merge can silently drop carried candidates,
    which no recall target bounds (the loss compounds per merge step)."""
    k = min(int(k), pool_d2.shape[-1])
    d2, pos = select_topk(pool_d2, k, strategy="exact_full")
    return d2, jnp.take_along_axis(pool_ids, pos, axis=-1)


def top_k_max(
    scores: jax.Array, k: int, *, strategy: str = "exact_full",
    tile: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Largest-k along the last axis: (values, indices), values descending.
    The non-distance score picks (kmeans|| candidate sampling, tree feature
    subsampling) route through here; they are deterministic-seeded, so the
    default stays exact."""
    d2, idx = select_topk(-scores, k, strategy=strategy, tile=tile)
    return -d2, idx


def record_selection(strategy: str, site: str, model: Optional[str] = None) -> None:
    """Host-side strategy telemetry: one `knn.select_strategy{...}` count per
    search-plane entry call. Callers skip this under tracing (a trace-time
    count would fire once per compile, not per search)."""
    from .. import observability as _obs

    labels = {"strategy": strategy, "site": site}
    if model:
        labels["model"] = model
    _obs.counter_inc("knn.select_strategy", 1, **labels)


def is_tracing(*arrays: Any) -> bool:
    """True when any argument is a tracer — host-side instrumentation
    (counters, spans) must not fire from inside a trace."""
    return any(isinstance(a, jax.core.Tracer) for a in arrays)
