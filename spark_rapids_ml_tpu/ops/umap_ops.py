#
# UMAP kernels — the TPU-native replacement for cuml.manifold.UMAP
# (reference umap.py:923-1298: single-worker cuML fit on sampled data; the model is
# the embedding + raw data, broadcast for the distributed transform).
#
# Pipeline (standard UMAP, re-expressed with static shapes for XLA):
#   1. exact kNN graph from ops/knn.py (the sharded all-to-all scan),
#   2. smooth-kNN calibration: per-point rho (nearest-neighbor distance) and sigma via
#      a vectorized 64-step binary search to hit log2(k) effective neighbors,
#   3. fuzzy simplicial set: w = exp(-(d - rho)/sigma), symmetrized by probabilistic
#      t-conorm  W = P + Pᵀ - P∘Pᵀ  (host scipy.sparse; edge list is tiny: n·k),
#   4. layout optimization: batched SGD epochs under one jitted lax.fori_loop —
#      every epoch applies weight-scaled attractive gradients on ALL edges plus
#      uniform negative samples, accumulated with segment_sum and applied with a
#      linearly-decaying learning rate. (The reference's cuML kernel applies
#      per-edge asynchronous updates; the batched form is the deterministic,
#      MXU/VPU-friendly equivalent.)
# transform() embeds new points at the fuzzy-weighted mean of their kNN's embeddings
# (cuML's transform init), which is the broadcastable map-side operation the
# reference's distributed transform performs.
#

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def find_ab_params(spread: float = 1.0, min_dist: float = 0.1) -> Tuple[float, float]:
    """Fit the (a, b) of the rational output kernel 1/(1+a d^{2b}) to the desired
    min_dist/spread curve — same curve-fit UMAP performs at fit time."""
    from scipy.optimize import curve_fit

    def curve(x, a, b):
        return 1.0 / (1.0 + a * x ** (2 * b))

    xv = np.linspace(0, spread * 3, 300)
    yv = np.zeros(xv.shape)
    yv[xv < min_dist] = 1.0
    yv[xv >= min_dist] = np.exp(-(xv[xv >= min_dist] - min_dist) / spread)
    params, _ = curve_fit(curve, xv, yv)
    return float(params[0]), float(params[1])


@jax.jit
def smooth_knn(knn_dists: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-point (rho, sigma): rho = nearest nonzero neighbor distance; sigma solves
    Σⱼ exp(-(dⱼ-rho)/σ) = log2(k) by bisection (64 steps, vectorized)."""
    k = knn_dists.shape[1]
    target = jnp.log2(jnp.array(float(k)))
    nonzero = jnp.where(knn_dists > 0, knn_dists, jnp.inf)
    rho = jnp.min(nonzero, axis=1)
    rho = jnp.where(jnp.isfinite(rho), rho, 0.0)

    def psum_of(sigma):
        d = jnp.maximum(knn_dists - rho[:, None], 0.0)
        return jnp.sum(jnp.exp(-d / sigma[:, None]), axis=1)

    lo = jnp.full(rho.shape, 1e-8)
    hi = jnp.full(rho.shape, 1e4)

    def body(i, state):
        lo, hi = state
        mid = 0.5 * (lo + hi)
        val = psum_of(mid)
        too_big = val > target
        return jnp.where(too_big, lo, mid), jnp.where(too_big, mid, hi)

    lo, hi = jax.lax.fori_loop(0, 64, body, (lo, hi))
    return rho, 0.5 * (lo + hi)


def fuzzy_simplicial_set(
    knn_ids: np.ndarray, knn_dists: np.ndarray
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Symmetrized edge list (heads, tails, weights) of the fuzzy graph."""
    import scipy.sparse as sp

    n, k = knn_ids.shape
    rho, sigma = smooth_knn(jnp.asarray(knn_dists))
    rho_h, sigma_h = np.asarray(rho), np.asarray(sigma)
    d = np.maximum(knn_dists - rho_h[:, None], 0.0)
    w = np.exp(-d / sigma_h[:, None])
    rows = np.repeat(np.arange(n), k)
    cols = knn_ids.reshape(-1)
    keep = rows != cols
    P = sp.coo_matrix(
        (w.reshape(-1)[keep], (rows[keep], cols[keep])), shape=(n, n)
    ).tocsr()
    W = P + P.T - P.multiply(P.T)
    W = W.tocoo()
    return (
        W.row.astype(np.int32),
        W.col.astype(np.int32),
        W.data.astype(np.float32),
    )


@functools.partial(
    jax.jit, static_argnames=("n_epochs", "n_vertices", "neg_samples")
)
def optimize_layout(
    emb0: jax.Array,  # (n, dim) initial embedding
    heads: jax.Array,  # (E,)
    tails: jax.Array,
    weights: jax.Array,  # (E,) in [0,1]
    key: jax.Array,
    a: float,
    b: float,
    n_epochs: int,
    n_vertices: int,
    neg_samples: int = 5,
    initial_lr: float = 1.0,
) -> jax.Array:
    E = heads.shape[0]
    wsum_per_vertex = jax.ops.segment_sum(weights, heads, num_segments=n_vertices)
    deg_norm = 1.0 / jnp.maximum(wsum_per_vertex, 1e-6)

    def epoch(e, state):
        emb, key = state
        lr = initial_lr * (1.0 - e / n_epochs)

        yh = emb[heads]
        yt = emb[tails]
        diff = yh - yt
        d2 = jnp.sum(diff * diff, axis=1)
        # attractive gradient (UMAP cross-entropy, weight-scaled batch form)
        g_att = (-2.0 * a * b * d2 ** jnp.maximum(b - 1.0, 0.0)) / (
            1.0 + a * d2**b
        )
        f_att = jnp.clip(g_att[:, None] * diff, -4.0, 4.0) * weights[:, None]

        key, sub = jax.random.split(key)
        neg = jax.random.randint(sub, (E, neg_samples), 0, n_vertices)
        yn = emb[neg]  # (E, S, dim)
        diff_n = yh[:, None, :] - yn
        d2n = jnp.sum(diff_n * diff_n, axis=-1)
        g_rep = (2.0 * b) / ((0.001 + d2n) * (1.0 + a * d2n**b))
        f_rep = jnp.clip(g_rep[..., None] * diff_n, -4.0, 4.0) * weights[:, None, None]

        grad_h = f_att + jnp.sum(f_rep, axis=1) / neg_samples
        upd = jnp.zeros_like(emb)
        upd = upd.at[heads].add(grad_h * deg_norm[heads][:, None])
        upd = upd.at[tails].add(-f_att * deg_norm[tails][:, None])
        return emb + lr * upd, key

    emb, _ = jax.lax.fori_loop(0, n_epochs, epoch, (emb0, key))
    return emb


def categorical_intersection(
    heads: np.ndarray,
    tails: np.ndarray,
    weights: np.ndarray,
    y: np.ndarray,
    unknown_dist: float = 1.0,
    far_dist: float = 5.0,
) -> np.ndarray:
    """Supervised (categorical-target) intersection of the fuzzy graph: edges between
    differently-labeled points are attenuated by exp(-far_dist), edges touching an
    unknown label (y < 0) by exp(-unknown_dist), same-label edges untouched — the
    standard categorical simplicial-set intersection the reference exposes via
    labelCol (reference umap.py fit path; cuML target_metric='categorical')."""
    yh, yt = y[heads], y[tails]
    factor = np.where(
        (yh < 0) | (yt < 0),
        np.exp(-unknown_dist),
        np.where(yh == yt, 1.0, np.exp(-far_dist)),
    ).astype(np.float32)
    return weights * factor


def spectral_init(
    heads: np.ndarray,
    tails: np.ndarray,
    weights: np.ndarray,
    n: int,
    n_components: int,
    seed: int,
) -> np.ndarray:
    """Spectral embedding initialization: the first non-trivial eigenvectors of the
    symmetric-normalized graph Laplacian of the fuzzy graph (umap-learn/cuML's
    default init, absent in round 1). Falls back to scaled random on solver failure
    (disconnected graphs, convergence)."""
    import scipy.sparse as sp
    import scipy.sparse.linalg as spla

    rng = np.random.default_rng(seed & 0x7FFFFFFF)
    try:
        W = sp.coo_matrix((weights, (heads, tails)), shape=(n, n)).tocsr()
        deg = np.asarray(W.sum(axis=1)).ravel()
        dinv = 1.0 / np.sqrt(np.maximum(deg, 1e-12))
        L = sp.identity(n) - sp.diags(dinv) @ W @ sp.diags(dinv)
        k_eig = n_components + 1
        # shift-invert around 0 finds the smallest eigenvalues fast on kNN graphs
        vals, vecs = spla.eigsh(
            L, k=k_eig, sigma=0.0, which="LM",
            v0=rng.normal(size=n), maxiter=2000, tol=1e-4,
        )
        order = np.argsort(vals)
        emb = vecs[:, order[1 : n_components + 1]]  # drop the trivial eigenvector
        # scale to the +-10 box the SGD expects
        emb = emb / np.maximum(np.abs(emb).max(axis=0, keepdims=True), 1e-12) * 10.0
        noise = rng.normal(0, 1e-4, size=emb.shape)
        return (emb + noise).astype(np.float32)
    except Exception:
        return rng.uniform(-10, 10, size=(n, n_components)).astype(np.float32)


def sparse_knn_graph(
    X_csr, k: int, block: int = 1024
) -> Tuple[np.ndarray, np.ndarray]:
    """Exact kNN over a scipy CSR matrix WITHOUT densifying the data: blocked
    sparse-sparse cross products (Qb @ Xᵀ) give the distance matrix one query block
    at a time — memory is O(block·n + nnz), never O(n·d). This is the sparse-fit
    path the reference supports via cuML's sparse UMAP (reference umap.py:955-972)."""
    n = X_csr.shape[0]
    x2 = np.asarray(X_csr.multiply(X_csr).sum(axis=1)).ravel()
    XT = X_csr.T.tocsc()
    k_eff = min(k, n)
    ids = np.zeros((n, k_eff), np.int64)
    dists = np.zeros((n, k_eff), np.float32)
    for s in range(0, n, block):
        e = min(s + block, n)
        cross = np.asarray((X_csr[s:e] @ XT).todense())
        d2 = np.maximum(x2[s:e, None] - 2.0 * cross + x2[None, :], 0.0)
        part = np.argpartition(d2, k_eff - 1, axis=1)[:, :k_eff]
        pd2 = np.take_along_axis(d2, part, axis=1)
        order = np.argsort(pd2, axis=1, kind="stable")
        ids[s:e] = np.take_along_axis(part, order, axis=1)
        dists[s:e] = np.sqrt(np.take_along_axis(pd2, order, axis=1))
    return ids, dists


def umap_fit(
    X,
    n_neighbors: int,
    n_components: int,
    n_epochs: int,
    min_dist: float,
    spread: float,
    negative_sample_rate: int,
    learning_rate: float,
    seed: int,
    mesh=None,
    y: "np.ndarray | None" = None,
    init: str = "spectral",
) -> Dict[str, np.ndarray]:
    """Full UMAP fit; X may be dense (n, d) or scipy CSR (sparse stays sparse
    end-to-end: sparse kNN graph + device SGD on the edge list). `y` switches on the
    supervised categorical intersection; `init` is 'spectral' or 'random'."""
    from .knn import exact_knn_single
    import jax.numpy as jnp

    try:
        import scipy.sparse as sp

        is_sparse = sp.issparse(X)
    except ImportError:  # pragma: no cover
        is_sparse = False

    n = X.shape[0]
    k = min(n_neighbors + 1, n)
    if is_sparse:
        knn_ids, knn_dists = sparse_knn_graph(X.tocsr(), k)
    else:
        d2, ids = exact_knn_single(
            jnp.asarray(X), jnp.asarray(X), jnp.ones((n,), bool), k
        )
        knn_dists = np.sqrt(np.asarray(d2))
        knn_ids = np.asarray(ids)

    heads, tails, weights = fuzzy_simplicial_set(knn_ids, knn_dists)
    if y is not None:
        weights = categorical_intersection(heads, tails, weights, np.asarray(y))
    a, b = find_ab_params(spread, min_dist)

    rng = np.random.default_rng(seed & 0x7FFFFFFF)
    if init == "spectral":
        emb0 = spectral_init(heads, tails, weights, n, n_components, seed)
    else:
        emb0 = rng.uniform(-10, 10, size=(n, n_components)).astype(np.float32)

    emb = optimize_layout(
        jnp.asarray(emb0),
        jnp.asarray(heads),
        jnp.asarray(tails),
        jnp.asarray(weights),
        jax.random.PRNGKey(seed & 0x7FFFFFFF),
        a=a,
        b=b,
        n_epochs=int(n_epochs),
        n_vertices=n,
        neg_samples=int(negative_sample_rate),
        initial_lr=float(learning_rate),
    )
    return {
        "embedding": np.asarray(emb),
        "raw_data": X if is_sparse else X.astype(np.float32),
        "a": a,
        "b": b,
        "n_neighbors": n_neighbors,
    }


def umap_transform(
    Q: np.ndarray, raw_data, embedding: np.ndarray, n_neighbors: int
) -> np.ndarray:
    """Embed new points at the fuzzy-weighted mean of their neighbors' embeddings.
    `raw_data` may be dense or CSR (sparse-fitted models transform without ever
    densifying the training data)."""
    from .knn import exact_knn_single
    import jax.numpy as jnp

    try:
        import scipy.sparse as sp

        rd_sparse = sp.issparse(raw_data)
    except ImportError:  # pragma: no cover
        rd_sparse = False

    n = raw_data.shape[0]
    k = min(n_neighbors, n)
    if rd_sparse:
        Qs = Q if sp.issparse(Q) else sp.csr_matrix(np.asarray(Q))
        x2 = np.asarray(raw_data.multiply(raw_data).sum(axis=1)).ravel()
        q2 = np.asarray(Qs.multiply(Qs).sum(axis=1)).ravel()
        cross = np.asarray((Qs @ raw_data.T).todense())
        d2_full = np.maximum(q2[:, None] - 2.0 * cross + x2[None, :], 0.0)
        part = np.argpartition(d2_full, k - 1, axis=1)[:, :k]
        pd2 = np.take_along_axis(d2_full, part, axis=1)
        order = np.argsort(pd2, axis=1, kind="stable")
        ids_h = np.take_along_axis(part, order, axis=1)
        dists = np.sqrt(np.take_along_axis(pd2, order, axis=1)).astype(np.float32)
    else:
        d2, ids = exact_knn_single(
            jnp.asarray(Q), jnp.asarray(raw_data), jnp.ones((n,), bool), k
        )
        dists = np.sqrt(np.asarray(d2))
        ids_h = np.asarray(ids)
    rho, sigma = smooth_knn(jnp.asarray(dists))
    w = np.exp(
        -np.maximum(dists - np.asarray(rho)[:, None], 0.0)
        / np.asarray(sigma)[:, None]
    )
    w = w / np.maximum(w.sum(axis=1, keepdims=True), 1e-12)
    return np.einsum("qk,qkd->qd", w, embedding[ids_h]).astype(np.float32)
