#
# UMAP kernels — the TPU-native replacement for cuml.manifold.UMAP
# (reference umap.py:923-1298: single-worker cuML fit on sampled data; the model is
# the embedding + raw data, broadcast for the distributed transform).
#
# Pipeline (standard UMAP, re-expressed with static shapes for XLA):
#   1. exact kNN graph from ops/knn.py (the sharded all-to-all scan),
#   2. smooth-kNN calibration: per-point rho (nearest-neighbor distance) and sigma via
#      a vectorized 64-step binary search to hit log2(k) effective neighbors,
#   3. fuzzy simplicial set: w = exp(-(d - rho)/sigma), symmetrized by probabilistic
#      t-conorm  W = P + Pᵀ - P∘Pᵀ  (host scipy.sparse; edge list is tiny: n·k),
#   4. layout optimization: batched SGD epochs under one jitted lax.fori_loop —
#      every epoch applies weight-scaled attractive gradients on ALL edges plus
#      uniform negative samples, accumulated with segment_sum and applied with a
#      linearly-decaying learning rate. (The reference's cuML kernel applies
#      per-edge asynchronous updates; the batched form is the deterministic,
#      MXU/VPU-friendly equivalent.)
# transform() embeds new points at the fuzzy-weighted mean of their kNN's embeddings
# (cuML's transform init), which is the broadcastable map-side operation the
# reference's distributed transform performs.
#

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def find_ab_params(spread: float = 1.0, min_dist: float = 0.1) -> Tuple[float, float]:
    """Fit the (a, b) of the rational output kernel 1/(1+a d^{2b}) to the desired
    min_dist/spread curve — same curve-fit UMAP performs at fit time."""
    from scipy.optimize import curve_fit

    def curve(x, a, b):
        return 1.0 / (1.0 + a * x ** (2 * b))

    xv = np.linspace(0, spread * 3, 300)
    yv = np.zeros(xv.shape)
    yv[xv < min_dist] = 1.0
    yv[xv >= min_dist] = np.exp(-(xv[xv >= min_dist] - min_dist) / spread)
    params, _ = curve_fit(curve, xv, yv)
    return float(params[0]), float(params[1])


@jax.jit
def smooth_knn(knn_dists: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-point (rho, sigma): rho = nearest nonzero neighbor distance; sigma solves
    Σⱼ exp(-(dⱼ-rho)/σ) = log2(k) by bisection (64 steps, vectorized)."""
    k = knn_dists.shape[1]
    target = jnp.log2(jnp.array(float(k)))
    nonzero = jnp.where(knn_dists > 0, knn_dists, jnp.inf)
    rho = jnp.min(nonzero, axis=1)
    rho = jnp.where(jnp.isfinite(rho), rho, 0.0)

    def psum_of(sigma):
        d = jnp.maximum(knn_dists - rho[:, None], 0.0)
        return jnp.sum(jnp.exp(-d / sigma[:, None]), axis=1)

    lo = jnp.full(rho.shape, 1e-8)
    hi = jnp.full(rho.shape, 1e4)

    def body(i, state):
        lo, hi = state
        mid = 0.5 * (lo + hi)
        val = psum_of(mid)
        too_big = val > target
        return jnp.where(too_big, lo, mid), jnp.where(too_big, mid, hi)

    lo, hi = jax.lax.fori_loop(0, 64, body, (lo, hi))
    return rho, 0.5 * (lo + hi)


def fuzzy_simplicial_set(
    knn_ids: np.ndarray, knn_dists: np.ndarray
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Symmetrized edge list (heads, tails, weights) of the fuzzy graph."""
    import scipy.sparse as sp

    n, k = knn_ids.shape
    rho, sigma = smooth_knn(jnp.asarray(knn_dists))
    rho_h, sigma_h = np.asarray(rho), np.asarray(sigma)
    d = np.maximum(knn_dists - rho_h[:, None], 0.0)
    w = np.exp(-d / sigma_h[:, None])
    rows = np.repeat(np.arange(n), k)
    cols = knn_ids.reshape(-1)
    keep = rows != cols
    P = sp.coo_matrix(
        (w.reshape(-1)[keep], (rows[keep], cols[keep])), shape=(n, n)
    ).tocsr()
    W = P + P.T - P.multiply(P.T)
    W = W.tocoo()
    return (
        W.row.astype(np.int32),
        W.col.astype(np.int32),
        W.data.astype(np.float32),
    )


@functools.partial(
    jax.jit, static_argnames=("n_epochs", "n_vertices", "neg_samples")
)
def optimize_layout(
    emb0: jax.Array,  # (n, dim) initial embedding
    heads: jax.Array,  # (E,)
    tails: jax.Array,
    weights: jax.Array,  # (E,) in [0,1]
    key: jax.Array,
    a: float,
    b: float,
    n_epochs: int,
    n_vertices: int,
    neg_samples: int = 5,
    initial_lr: float = 1.0,
) -> jax.Array:
    E = heads.shape[0]
    wsum_per_vertex = jax.ops.segment_sum(weights, heads, num_segments=n_vertices)
    deg_norm = 1.0 / jnp.maximum(wsum_per_vertex, 1e-6)

    def epoch(e, state):
        emb, key = state
        lr = initial_lr * (1.0 - e / n_epochs)

        yh = emb[heads]
        yt = emb[tails]
        diff = yh - yt
        d2 = jnp.sum(diff * diff, axis=1)
        # attractive gradient (UMAP cross-entropy, weight-scaled batch form)
        g_att = (-2.0 * a * b * d2 ** jnp.maximum(b - 1.0, 0.0)) / (
            1.0 + a * d2**b
        )
        f_att = jnp.clip(g_att[:, None] * diff, -4.0, 4.0) * weights[:, None]

        key, sub = jax.random.split(key)
        neg = jax.random.randint(sub, (E, neg_samples), 0, n_vertices)
        yn = emb[neg]  # (E, S, dim)
        diff_n = yh[:, None, :] - yn
        d2n = jnp.sum(diff_n * diff_n, axis=-1)
        g_rep = (2.0 * b) / ((0.001 + d2n) * (1.0 + a * d2n**b))
        f_rep = jnp.clip(g_rep[..., None] * diff_n, -4.0, 4.0) * weights[:, None, None]

        grad_h = f_att + jnp.sum(f_rep, axis=1) / neg_samples
        upd = jnp.zeros_like(emb)
        upd = upd.at[heads].add(grad_h * deg_norm[heads][:, None])
        upd = upd.at[tails].add(-f_att * deg_norm[tails][:, None])
        return emb + lr * upd, key

    emb, _ = jax.lax.fori_loop(0, n_epochs, epoch, (emb0, key))
    return emb


def umap_fit(
    X: np.ndarray,
    n_neighbors: int,
    n_components: int,
    n_epochs: int,
    min_dist: float,
    spread: float,
    negative_sample_rate: int,
    learning_rate: float,
    seed: int,
    mesh=None,
) -> Dict[str, np.ndarray]:
    """Full UMAP fit on host-resident X; kNN + SGD run on device."""
    from .knn import exact_knn_single
    import jax.numpy as jnp

    n = X.shape[0]
    k = min(n_neighbors + 1, n)
    d2, ids = exact_knn_single(
        jnp.asarray(X), jnp.asarray(X), jnp.ones((n,), bool), k
    )
    knn_dists = np.sqrt(np.asarray(d2))
    knn_ids = np.asarray(ids)

    heads, tails, weights = fuzzy_simplicial_set(knn_ids, knn_dists)
    a, b = find_ab_params(spread, min_dist)

    rng = np.random.default_rng(seed & 0x7FFFFFFF)
    emb0 = rng.uniform(-10, 10, size=(n, n_components)).astype(np.float32)

    emb = optimize_layout(
        jnp.asarray(emb0),
        jnp.asarray(heads),
        jnp.asarray(tails),
        jnp.asarray(weights),
        jax.random.PRNGKey(seed & 0x7FFFFFFF),
        a=a,
        b=b,
        n_epochs=int(n_epochs),
        n_vertices=n,
        neg_samples=int(negative_sample_rate),
        initial_lr=float(learning_rate),
    )
    return {
        "embedding": np.asarray(emb),
        "raw_data": X.astype(np.float32),
        "a": a,
        "b": b,
        "n_neighbors": n_neighbors,
    }


def umap_transform(
    Q: np.ndarray, raw_data: np.ndarray, embedding: np.ndarray, n_neighbors: int
) -> np.ndarray:
    """Embed new points at the fuzzy-weighted mean of their neighbors' embeddings."""
    from .knn import exact_knn_single
    import jax.numpy as jnp

    n = raw_data.shape[0]
    k = min(n_neighbors, n)
    d2, ids = exact_knn_single(
        jnp.asarray(Q), jnp.asarray(raw_data), jnp.ones((n,), bool), k
    )
    dists = np.sqrt(np.asarray(d2))
    ids_h = np.asarray(ids)
    rho, sigma = smooth_knn(jnp.asarray(dists))
    w = np.exp(
        -np.maximum(dists - np.asarray(rho)[:, None], 0.0)
        / np.asarray(sigma)[:, None]
    )
    w = w / np.maximum(w.sum(axis=1, keepdims=True), 1e-12)
    return np.einsum("qk,qkd->qd", w, embedding[ids_h]).astype(np.float32)
