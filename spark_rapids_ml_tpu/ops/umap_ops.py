#
# UMAP kernels — the TPU-native replacement for cuml.manifold.UMAP
# (reference umap.py:923-1298: single-worker cuML fit on sampled data; the model is
# the embedding + raw data, broadcast for the distributed transform).
#
# Pipeline (standard UMAP, re-expressed with static shapes for XLA):
#   1. exact kNN graph from ops/knn.py (the sharded all-to-all scan),
#   2. smooth-kNN calibration: per-point rho (nearest-neighbor distance) and sigma via
#      a vectorized 64-step binary search to hit log2(k) effective neighbors,
#   3. fuzzy simplicial set: w = exp(-(d - rho)/sigma), symmetrized by probabilistic
#      t-conorm  W = P + Pᵀ - P∘Pᵀ  (host scipy.sparse; edge list is tiny: n·k),
#   4. layout optimization: batched SGD epochs under one jitted lax.fori_loop —
#      every epoch applies weight-scaled attractive gradients on ALL edges plus
#      uniform negative samples, accumulated with segment_sum and applied with a
#      linearly-decaying learning rate. (The reference's cuML kernel applies
#      per-edge asynchronous updates; the batched form is the deterministic,
#      MXU/VPU-friendly equivalent.)
# transform() embeds new points at the fuzzy-weighted mean of their kNN's embeddings
# (cuML's transform init), which is the broadcastable map-side operation the
# reference's distributed transform performs.
#

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..observability.device import compiled_kernel
from .selection import (
    INVALID_D2 as _INVALID_D2,
    mask_invalid as _mask_invalid,
    merge_topk as _merge_topk,
)


def find_ab_params(spread: float = 1.0, min_dist: float = 0.1) -> Tuple[float, float]:
    """Fit the (a, b) of the rational output kernel 1/(1+a d^{2b}) to the desired
    min_dist/spread curve — same curve-fit UMAP performs at fit time."""
    from scipy.optimize import curve_fit

    def curve(x, a, b):
        return 1.0 / (1.0 + a * x ** (2 * b))

    xv = np.linspace(0, spread * 3, 300)
    yv = np.zeros(xv.shape)
    yv[xv < min_dist] = 1.0
    yv[xv >= min_dist] = np.exp(-(xv[xv >= min_dist] - min_dist) / spread)
    params, _ = curve_fit(curve, xv, yv)
    return float(params[0]), float(params[1])


@compiled_kernel("umap.smooth_knn", static_argnames=("local_connectivity",))
def smooth_knn(
    knn_dists: jax.Array, local_connectivity: float = 1.0
) -> Tuple[jax.Array, jax.Array]:
    """Per-point (rho, sigma): rho = distance to the local_connectivity-th nearest
    nonzero neighbor (fractional values interpolate between the surrounding ranks —
    the standard UMAP local-connectivity semantics; the reference exposes it as the
    cuML param `local_connectivity`, umap.py:114-137); sigma solves
    Σⱼ exp(-(dⱼ-rho)/σ) = log2(k) by bisection (64 steps, vectorized)."""
    k = knn_dists.shape[1]
    target = jnp.log2(jnp.array(float(k)))
    nonzero = jnp.where(knn_dists > 0, knn_dists, jnp.inf)
    sorted_nz = jnp.sort(nonzero, axis=1)  # ascending, inf-padded
    n_nz = jnp.sum(jnp.isfinite(sorted_nz), axis=1)
    lc = max(float(local_connectivity), 1.0)
    lo_rank = int(np.floor(lc)) - 1  # 0-based rank of the lower surrounding rank
    frac = lc - np.floor(lc)
    lo_idx = jnp.minimum(lo_rank, jnp.maximum(n_nz - 1, 0))
    hi_idx = jnp.minimum(lo_rank + 1, jnp.maximum(n_nz - 1, 0))
    d_lo = jnp.take_along_axis(sorted_nz, lo_idx[:, None], axis=1)[:, 0]
    d_hi = jnp.take_along_axis(sorted_nz, hi_idx[:, None], axis=1)[:, 0]
    rho = d_lo + frac * (d_hi - d_lo)
    # fewer nonzero neighbors than requested -> farthest nonzero; none -> 0
    rho = jnp.where(n_nz > lo_rank, rho, d_lo)
    rho = jnp.where((n_nz > 0) & jnp.isfinite(rho), rho, 0.0)

    def psum_of(sigma):
        d = jnp.maximum(knn_dists - rho[:, None], 0.0)
        return jnp.sum(jnp.exp(-d / sigma[:, None]), axis=1)

    lo = jnp.full(rho.shape, 1e-8)
    hi = jnp.full(rho.shape, 1e4)

    def body(i, state):
        lo, hi = state
        mid = 0.5 * (lo + hi)
        val = psum_of(mid)
        too_big = val > target
        return jnp.where(too_big, lo, mid), jnp.where(too_big, mid, hi)

    lo, hi = jax.lax.fori_loop(0, 64, body, (lo, hi))
    return rho, 0.5 * (lo + hi)


def fuzzy_simplicial_set(
    knn_ids: np.ndarray,
    knn_dists: np.ndarray,
    set_op_mix_ratio: float = 1.0,
    local_connectivity: float = 1.0,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Symmetrized edge list (heads, tails, weights) of the fuzzy graph.

    set_op_mix_ratio blends the probabilistic t-conorm (fuzzy union, 1.0) with the
    product t-norm (fuzzy intersection, 0.0):
        W = mix·(P + Pᵀ - P∘Pᵀ) + (1-mix)·(P∘Pᵀ)
    (cuML/umap-learn semantics; reference surfaces it as `set_op_mix_ratio`)."""
    import scipy.sparse as sp

    n, k = knn_ids.shape
    rho, sigma = smooth_knn(
        jnp.asarray(knn_dists), local_connectivity=float(local_connectivity)
    )
    rho_h, sigma_h = np.asarray(rho), np.asarray(sigma)
    d = np.maximum(knn_dists - rho_h[:, None], 0.0)
    w = np.exp(-d / sigma_h[:, None])
    rows = np.repeat(np.arange(n), k)
    cols = knn_ids.reshape(-1)
    keep = rows != cols
    P = sp.coo_matrix(
        (w.reshape(-1)[keep], (rows[keep], cols[keep])), shape=(n, n)
    ).tocsr()
    prod = P.multiply(P.T)
    mix = float(np.clip(set_op_mix_ratio, 0.0, 1.0))
    W = (P + P.T - prod) * mix + prod * (1.0 - mix)
    W = W.tocoo()
    return (
        W.row.astype(np.int32),
        W.col.astype(np.int32),
        W.data.astype(np.float32),
    )


@compiled_kernel("umap.optimize_layout",
                 static_argnames=("n_epochs", "n_vertices", "neg_samples"))
def optimize_layout(
    emb0: jax.Array,  # (n, dim) initial embedding
    heads: jax.Array,  # (E,)
    tails: jax.Array,
    weights: jax.Array,  # (E,) in [0,1]
    key: jax.Array,
    a: float,
    b: float,
    n_epochs: int,
    n_vertices: int,
    neg_samples: int = 5,
    initial_lr: float = 1.0,
    gamma: float = 1.0,
) -> jax.Array:
    E = heads.shape[0]
    # SEGMENT-SORTED edge layout (round-5, VERDICT r4 task #8): TPU scatter-add
    # (`.at[].add`) lowers to a serialized/sort-per-epoch scatter — the slowest
    # op class on this hardware. Sorting the edge list ONCE by head (and keeping
    # a head-order→tail-order permutation) turns both per-epoch accumulations
    # into `segment_sum(indices_are_sorted=True)`, which XLA lowers to dense
    # scans. Edge order is math-irrelevant (the epoch sums all edge forces), so
    # results are an equally-valid UMAP run; only float summation order changes.
    order_h = jnp.argsort(heads)
    heads = heads[order_h]
    tails = tails[order_h]
    weights = weights[order_h]
    order_t = jnp.argsort(tails)  # canonical(head-sorted) order -> tail-sorted
    tails_sorted = tails[order_t]

    wsum_per_vertex = jax.ops.segment_sum(
        weights, heads, num_segments=n_vertices, indices_are_sorted=True
    )
    deg_norm = 1.0 / jnp.maximum(wsum_per_vertex, 1e-6)

    def epoch(e, state):
        emb, key = state
        lr = initial_lr * (1.0 - e / n_epochs)

        yh = emb[heads]
        yt = emb[tails]
        diff = yh - yt
        d2 = jnp.sum(diff * diff, axis=1)
        # attractive gradient (UMAP cross-entropy, weight-scaled batch form)
        g_att = (-2.0 * a * b * d2 ** jnp.maximum(b - 1.0, 0.0)) / (
            1.0 + a * d2**b
        )
        f_att = jnp.clip(g_att[:, None] * diff, -4.0, 4.0) * weights[:, None]

        key, sub = jax.random.split(key)
        neg = jax.random.randint(sub, (E, neg_samples), 0, n_vertices)
        yn = emb[neg]  # (E, S, dim)
        diff_n = yh[:, None, :] - yn
        d2n = jnp.sum(diff_n * diff_n, axis=-1)
        # gamma = repulsion_strength scales the negative-sample force (cuML param)
        g_rep = (2.0 * gamma * b) / ((0.001 + d2n) * (1.0 + a * d2n**b))
        f_rep = jnp.clip(g_rep[..., None] * diff_n, -4.0, 4.0) * weights[:, None, None]

        grad_h = f_att + jnp.sum(f_rep, axis=1) / neg_samples
        upd = jax.ops.segment_sum(
            grad_h * deg_norm[heads][:, None],
            heads,
            num_segments=n_vertices,
            indices_are_sorted=True,
        )
        upd = upd + jax.ops.segment_sum(
            (-f_att * deg_norm[tails][:, None])[order_t],
            tails_sorted,
            num_segments=n_vertices,
            indices_are_sorted=True,
        )
        return emb + lr * upd, key

    emb, _ = jax.lax.fori_loop(0, n_epochs, epoch, (emb0, key))
    return emb


@compiled_kernel("umap.optimize_transform_layout",
                 static_argnames=("n_epochs", "neg_samples"))
def optimize_transform_layout(
    q_emb0: jax.Array,  # (nq, dim) init (fuzzy-weighted mean)
    ref_emb: jax.Array,  # (n_ref, dim) FROZEN reference embedding
    ids: jax.Array,  # (nq, k) neighbor indices into ref_emb
    w: jax.Array,  # (nq, k) membership strengths (unnormalized)
    key: jax.Array,
    a: float,
    b: float,
    n_epochs: int,
    neg_samples: int = 5,
    initial_lr: float = 1.0,
    gamma: float = 1.0,
) -> jax.Array:
    """SGD refinement of NEW points against a fixed reference embedding — the
    transform-side optimization cuML's UMAP.transform runs after the weighted-mean
    init (reference umap.py:1368-1446 broadcasts embedding+raw to feed it). Only
    the query embeddings move: attraction along the (query → ref neighbor) edges,
    repulsion against uniform negative samples from the reference vertices. Same
    cross-entropy gradients and linear lr decay as the fit-side optimize_layout."""
    nq, k = ids.shape
    n_ref = ref_emb.shape[0]
    heads = jnp.repeat(jnp.arange(nq, dtype=jnp.int32), k)  # (E,)
    tails = ids.reshape(-1)
    weights = w.reshape(-1)
    deg_norm = 1.0 / jnp.maximum(jnp.sum(w, axis=1), 1e-6)  # (nq,)

    def epoch(e, state):
        qe, key = state
        lr = initial_lr * (1.0 - e / n_epochs)

        yh = qe[heads]
        yt = ref_emb[tails]
        diff = yh - yt
        d2 = jnp.sum(diff * diff, axis=1)
        g_att = (-2.0 * a * b * d2 ** jnp.maximum(b - 1.0, 0.0)) / (
            1.0 + a * d2**b
        )
        f_att = jnp.clip(g_att[:, None] * diff, -4.0, 4.0) * weights[:, None]

        key, sub = jax.random.split(key)
        neg = jax.random.randint(sub, (heads.shape[0], neg_samples), 0, n_ref)
        yn = ref_emb[neg]  # (E, S, dim)
        diff_n = yh[:, None, :] - yn
        d2n = jnp.sum(diff_n * diff_n, axis=-1)
        g_rep = (2.0 * gamma * b) / ((0.001 + d2n) * (1.0 + a * d2n**b))
        f_rep = (
            jnp.clip(g_rep[..., None] * diff_n, -4.0, 4.0) * weights[:, None, None]
        )

        grad_h = f_att + jnp.sum(f_rep, axis=1) / neg_samples
        # heads = repeat(arange(nq), k) is CONTIGUOUS by construction: the
        # per-query accumulation is a dense (nq, k, dim) reshape-sum — no
        # scatter at all (scatter-add is the slowest op class on TPU)
        upd = jnp.sum(grad_h.reshape(nq, k, -1), axis=1) * deg_norm[:, None]
        return qe + lr * upd, key

    qe, _ = jax.lax.fori_loop(0, n_epochs, epoch, (q_emb0, key))
    return qe


def categorical_intersection(
    heads: np.ndarray,
    tails: np.ndarray,
    weights: np.ndarray,
    y: np.ndarray,
    unknown_dist: float = 1.0,
    far_dist: float = 5.0,
) -> np.ndarray:
    """Supervised (categorical-target) intersection of the fuzzy graph: edges between
    differently-labeled points are attenuated by exp(-far_dist), edges touching an
    unknown label (y < 0) by exp(-unknown_dist), same-label edges untouched — the
    standard categorical simplicial-set intersection the reference exposes via
    labelCol (reference umap.py fit path; cuML target_metric='categorical')."""
    yh, yt = y[heads], y[tails]
    factor = np.where(
        (yh < 0) | (yt < 0),
        np.exp(-unknown_dist),
        np.where(yh == yt, 1.0, np.exp(-far_dist)),
    ).astype(np.float32)
    return weights * factor


def spectral_init(
    heads: np.ndarray,
    tails: np.ndarray,
    weights: np.ndarray,
    n: int,
    n_components: int,
    seed: int,
) -> np.ndarray:
    """Spectral embedding initialization: the first non-trivial eigenvectors of the
    symmetric-normalized graph Laplacian of the fuzzy graph (umap-learn/cuML's
    default init, absent in round 1). Falls back to scaled random on solver failure
    (disconnected graphs, convergence)."""
    import scipy.sparse as sp
    import scipy.sparse.linalg as spla

    rng = np.random.default_rng(seed & 0x7FFFFFFF)
    try:
        W = sp.coo_matrix((weights, (heads, tails)), shape=(n, n)).tocsr()
        deg = np.asarray(W.sum(axis=1)).ravel()
        dinv = 1.0 / np.sqrt(np.maximum(deg, 1e-12))
        L = sp.identity(n) - sp.diags(dinv) @ W @ sp.diags(dinv)
        k_eig = n_components + 1
        # Smallest-eigenvector solve, sized to n. Shift-invert (sigma=0) is
        # instant below ~10k but its sparse-LU fill-in takes MINUTES at n>=20k
        # (observed hang at 20k/50k). Above that: the Laplacian spectrum lives
        # in [0, 2], so the largest-algebraic eigenvectors of 2I - L are the
        # smallest of L and Lanczos needs only cheap spmv products — with a
        # widened Krylov basis (ncv), because a k-cluster graph has ~k
        # near-degenerate eigenvalues at 0 and the default ncv=20 can stall
        # exactly on the clustered datasets spectral init matters for.
        if n < 10_000:
            vals, vecs = spla.eigsh(
                L, k=k_eig, sigma=0.0, which="LM",
                v0=rng.normal(size=n), maxiter=2000, tol=1e-4,
            )
        else:
            B = 2.0 * sp.identity(n) - L
            vals_b, vecs = spla.eigsh(
                B, k=k_eig, which="LA",
                v0=rng.normal(size=n), maxiter=n,
                ncv=min(n, max(6 * k_eig, 64)), tol=1e-4,
            )
            vals = 2.0 - vals_b
        order = np.argsort(vals)
        emb = vecs[:, order[1 : n_components + 1]]  # drop the trivial eigenvector
        # scale to the +-10 box the SGD expects
        emb = emb / np.maximum(np.abs(emb).max(axis=0, keepdims=True), 1e-12) * 10.0
        noise = rng.normal(0, 1e-4, size=emb.shape)
        return (emb + noise).astype(np.float32)
    except Exception as e:
        import warnings

        warnings.warn(
            f"UMAP spectral init failed ({type(e).__name__}: {e}); falling back "
            f"to random init — embedding quality may degrade",
            stacklevel=2,
        )
        return rng.uniform(-10, 10, size=(n, n_components)).astype(np.float32)


def sparse_knn_graph(
    X_csr, k: int, block: int = 1024
) -> Tuple[np.ndarray, np.ndarray]:
    """Exact kNN over a scipy CSR matrix WITHOUT densifying the data: blocked
    sparse-sparse cross products (Qb @ Xᵀ) give the distance matrix one query block
    at a time — memory is O(block·n + nnz), never O(n·d). This is the sparse-fit
    path the reference supports via cuML's sparse UMAP (reference umap.py:955-972)."""
    n = X_csr.shape[0]
    x2 = np.asarray(X_csr.multiply(X_csr).sum(axis=1)).ravel()
    XT = X_csr.T.tocsc()
    k_eff = min(k, n)
    ids = np.zeros((n, k_eff), np.int64)
    dists = np.zeros((n, k_eff), np.float32)
    for s in range(0, n, block):
        e = min(s + block, n)
        cross = np.asarray((X_csr[s:e] @ XT).todense())
        d2 = np.maximum(x2[s:e, None] - 2.0 * cross + x2[None, :], 0.0)
        part = np.argpartition(d2, k_eff - 1, axis=1)[:, :k_eff]
        pd2 = np.take_along_axis(d2, part, axis=1)
        order = np.argsort(pd2, axis=1, kind="stable")
        ids[s:e] = np.take_along_axis(part, order, axis=1)
        dists[s:e] = np.sqrt(np.take_along_axis(pd2, order, axis=1))
    return ids, dists


UMAP_METRICS = (
    "euclidean", "l2", "sqeuclidean", "cosine", "manhattan", "l1", "taxicab",
    "minkowski",
)


@compiled_kernel("umap.minkowski_knn",
                 static_argnames=("k", "p", "qblock", "xblock"))
def _minkowski_knn(
    Q: jax.Array, X: jax.Array, k: int, p: float, qblock: int = 256,
    xblock: int = 2048,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN under the Minkowski-p metric (p=1 manhattan). No matmul expansion
    exists for p≠2, so this is a doubly-blocked elementwise scan with a running
    top-k merge — VPU-bound, used only when the user asks for a non-dot-product
    metric (cuML brute-force kNN does the same on GPU)."""
    nq, d = Q.shape
    nx = X.shape[0]
    Qp = jnp.pad(Q, ((0, (-nq) % qblock), (0, 0)))
    Xp = jnp.pad(X, ((0, (-nx) % xblock), (0, 0)))
    n_xb = Xp.shape[0] // xblock
    x_chunks = Xp.reshape(n_xb, xblock, d)
    base_ids = jnp.arange(Xp.shape[0]).reshape(n_xb, xblock)
    valid = base_ids < nx

    def per_qblock(qb):
        def scan_chunk(carry, chunk):
            best_d, best_i = carry
            xc, ids_c, valid_c = chunk
            diff = jnp.abs(qb[:, None, :] - xc[None, :, :])  # (qblock, xblock, d)
            dist = jnp.sum(diff if p == 1.0 else diff**p, axis=-1)
            dist = _mask_invalid(dist, valid_c[None, :])
            cat_d = jnp.concatenate([best_d, dist], axis=1)
            cat_i = jnp.concatenate(
                [best_i, jnp.broadcast_to(ids_c[None, :], dist.shape)], axis=1
            )
            return _merge_topk(cat_d, cat_i, k), None

        init = (
            jnp.full((qb.shape[0], k), _INVALID_D2),
            jnp.zeros((qb.shape[0], k), jnp.int32),
        )
        (bd, bi), _ = jax.lax.scan(
            scan_chunk, init, (x_chunks, base_ids, valid)
        )
        return bd, bi

    db, ib = jax.lax.map(per_qblock, Qp.reshape(-1, qblock, d))
    dists = db.reshape(-1, k)[:nq]
    if p != 1.0:
        dists = dists ** (1.0 / p)
    return dists, ib.reshape(-1, k)[:nq]


def _dense_knn_graph(
    X, k: int, metric: str, metric_kwds, build_algo: str, build_kwds, seed: int
) -> Tuple[np.ndarray, np.ndarray]:
    """kNN graph of a dense matrix under the requested metric. Euclidean-family and
    cosine ride the MXU matmul path; manhattan/minkowski use the blocked VPU scan.
    build_algo='nn_descent' (cuML's approximate graph build) maps to the IVF-Flat
    approximate index — same role: an approximate kNN graph much faster than brute
    force at large n (reference umap.py:114-137 `build_algo`/`build_kwds`).

    Above stream_threshold_bytes the euclidean-family exact graph goes OUT OF
    CORE through the blocked pairwise scan (ops/pairwise_streaming.py): the
    dataset stays host-resident and the HBM batch cache replays the item tiles
    across query-block sweeps instead of re-uploading the matrix
    ceil(n/query_block) times — same neighbors rank-for-rank (the streamed scan
    shares `_block_sq_dists` with the in-core one)."""
    from .knn import exact_knn_single, ivfflat_build, ivfflat_search
    import jax.numpy as jnp

    from .. import config as _config

    Xh = np.asarray(X, dtype=np.float32)
    if (
        metric in ("euclidean", "l2", "sqeuclidean")
        and build_algo != "nn_descent"
        and int(_config.get("stream_threshold_bytes") or 0)
        and Xh.nbytes > int(_config.get("stream_threshold_bytes"))
    ):
        from .pairwise_streaming import streaming_exact_knn

        dists, ids = streaming_exact_knn(Xh, Xh, k)
        dists = dists.astype(np.float32)
        if metric == "sqeuclidean":
            dists = dists**2
        return ids, dists

    Xj = jnp.asarray(Xh)
    n = Xj.shape[0]
    valid = jnp.ones((n,), bool)
    if build_algo == "nn_descent" and metric not in (
        "euclidean", "l2", "sqeuclidean"
    ):
        from ..utils import get_logger

        get_logger("umap").warning(
            "build_algo='nn_descent' (IVF-backed approximate graph) supports only "
            "euclidean-family metrics; using the exact scan for metric '%s'.",
            metric,
        )
    if metric == "cosine":
        norms = jnp.linalg.norm(Xj, axis=1, keepdims=True)
        Xn = Xj / jnp.maximum(norms, 1e-12)
        d2, ids = exact_knn_single(Xn, Xn, valid, k)
        # unit vectors: d2 = 2(1 - cos)  =>  cosine distance = d2 / 2
        return np.asarray(ids), (np.asarray(d2) / 2.0).astype(np.float32)
    if metric in ("manhattan", "l1", "taxicab", "minkowski"):
        p = 1.0 if metric != "minkowski" else float((metric_kwds or {}).get("p", 2.0))
        dists, ids = _minkowski_knn(Xj, Xj, k, p)
        return np.asarray(ids), np.asarray(dists).astype(np.float32)
    # euclidean family
    if build_algo == "nn_descent" and n > 4 * k:
        kw = dict(build_kwds or {})
        nlist = int(kw.get("nlist", max(int(np.sqrt(n)), 8)))
        nprobe = int(kw.get("nprobe", max(nlist // 8, 2)))
        idx = ivfflat_build(
            Xj, jnp.ones((n,), jnp.float32), nlist=min(nlist, n), max_iter=8,
            seed=seed,
        )
        d, ids = ivfflat_search(
            Xj, jnp.asarray(idx["centers"]), jnp.asarray(idx["cells"]),
            jnp.asarray(idx["cell_ids"]), k=k, nprobe=min(nprobe, nlist),
            center_norms=jnp.asarray(idx["center_norms"]),
        )
        dists = np.asarray(d).astype(np.float32)
        ids_h = np.asarray(ids)
        # unfilled slots (-1 ids) -> self-loops with 0 distance (dropped later)
        rows = np.arange(n)[:, None]
        ids_h = np.where(ids_h < 0, rows, ids_h)
        dists = np.where(ids_h == rows, 0.0, dists)
        if metric == "sqeuclidean":
            dists = dists**2
        return ids_h, dists
    d2, ids = exact_knn_single(Xj, Xj, valid, k)
    d2_h = np.asarray(d2)
    dists = d2_h if metric == "sqeuclidean" else np.sqrt(d2_h)
    return np.asarray(ids), dists.astype(np.float32)


def umap_fit(
    X,
    n_neighbors: int,
    n_components: int,
    n_epochs: int,
    min_dist: float,
    spread: float,
    negative_sample_rate: int,
    learning_rate: float,
    seed: int,
    mesh=None,
    y: "np.ndarray | None" = None,
    init: str = "spectral",
    metric: str = "euclidean",
    metric_kwds: "Dict | None" = None,
    a: "float | None" = None,
    b: "float | None" = None,
    local_connectivity: float = 1.0,
    set_op_mix_ratio: float = 1.0,
    repulsion_strength: float = 1.0,
    build_algo: str = "auto",
    build_kwds: "Dict | None" = None,
) -> Dict[str, np.ndarray]:
    """Full UMAP fit; X may be dense (n, d) or scipy CSR (sparse stays sparse
    end-to-end: sparse kNN graph + device SGD on the edge list). `y` switches on the
    supervised categorical intersection; `init` is 'spectral' or 'random'. The cuML
    surface params (metric/metric_kwds, a/b override, local_connectivity,
    set_op_mix_ratio, repulsion_strength, build_algo/build_kwds — reference
    umap.py:114-137) are honored natively."""
    import jax.numpy as jnp

    if metric not in UMAP_METRICS:
        raise ValueError(
            f"Unsupported UMAP metric '{metric}'; supported: {UMAP_METRICS}"
        )
    if build_algo not in ("auto", "brute_force_knn", "nn_descent"):
        raise ValueError(
            "build_algo must be one of 'auto', 'brute_force_knn', 'nn_descent'"
        )

    try:
        import scipy.sparse as sp

        is_sparse = sp.issparse(X)
    except ImportError:  # pragma: no cover
        is_sparse = False

    n = X.shape[0]
    k = min(n_neighbors + 1, n)
    if is_sparse:
        Xs = X.tocsr()
        if metric == "cosine":
            # row-normalize the CSR (cheap, host): euclidean kNN of unit rows
            # yields d^2 = 2(1-cos)
            norms = np.sqrt(np.asarray(Xs.multiply(Xs).sum(axis=1))).ravel()
            inv = 1.0 / np.maximum(norms, 1e-12)
            Xs = sp.diags(inv) @ Xs
            knn_ids, knn_d = sparse_knn_graph(Xs, k)
            knn_dists = (knn_d**2) / 2.0
        elif metric in ("euclidean", "l2", "sqeuclidean"):
            knn_ids, knn_dists = sparse_knn_graph(Xs, k)
            if metric == "sqeuclidean":
                knn_dists = knn_dists**2
        else:
            raise ValueError(
                f"Sparse UMAP fit supports euclidean/sqeuclidean/cosine, got "
                f"'{metric}'"
            )
    else:
        knn_ids, knn_dists = _dense_knn_graph(
            np.asarray(X), k, metric, metric_kwds, build_algo, build_kwds, seed
        )

    heads, tails, weights = fuzzy_simplicial_set(
        knn_ids, knn_dists,
        set_op_mix_ratio=set_op_mix_ratio,
        local_connectivity=local_connectivity,
    )
    if y is not None:
        weights = categorical_intersection(heads, tails, weights, np.asarray(y))
    if a is None or b is None:
        a, b = find_ab_params(spread, min_dist)
    else:
        a, b = float(a), float(b)

    rng = np.random.default_rng(seed & 0x7FFFFFFF)
    if init == "spectral":
        emb0 = spectral_init(heads, tails, weights, n, n_components, seed)
    else:
        emb0 = rng.uniform(-10, 10, size=(n, n_components)).astype(np.float32)

    emb = optimize_layout(
        jnp.asarray(emb0),
        jnp.asarray(heads),
        jnp.asarray(tails),
        jnp.asarray(weights),
        jax.random.PRNGKey(seed & 0x7FFFFFFF),
        a=a,
        b=b,
        n_epochs=int(n_epochs),
        n_vertices=n,
        neg_samples=int(negative_sample_rate),
        initial_lr=float(learning_rate),
        gamma=float(repulsion_strength),
    )
    return {
        "embedding": np.asarray(emb),
        "raw_data": X if is_sparse else X.astype(np.float32),
        "a": a,
        "b": b,
        "n_neighbors": n_neighbors,
        "metric": metric,
        "metric_kwds": dict(metric_kwds) if metric_kwds else {},
        "local_connectivity": float(local_connectivity),
        # transform-side SGD refinement settings (cuML transform optimizes new
        # points with the fit hyperparameters; epochs = fit epochs // 3)
        "n_epochs": int(n_epochs),
        "negative_sample_rate": int(negative_sample_rate),
        "learning_rate": float(learning_rate),
        "repulsion_strength": float(repulsion_strength),
        "random_state": int(seed),
    }


def umap_transform(
    Q: np.ndarray,
    raw_data,
    embedding: np.ndarray,
    n_neighbors: int,
    metric: str = "euclidean",
    metric_kwds: "Dict | None" = None,
    local_connectivity: float = 1.0,
    a: "float | None" = None,
    b: "float | None" = None,
    n_epochs: int = 0,
    negative_sample_rate: int = 5,
    learning_rate: float = 1.0,
    repulsion_strength: float = 1.0,
    seed: int = 42,
) -> np.ndarray:
    """Embed new points: fuzzy-weighted-mean init at their neighbors' embeddings,
    then (n_epochs > 0) SGD refinement against the FROZEN reference embedding —
    cuML's UMAP.transform optimizes new points the same way (the reference
    broadcasts embedding+raw data to feed it, umap.py:1368-1446). `raw_data` may
    be dense or CSR (sparse-fitted models transform without ever densifying the
    training data). Distances use the fit-time metric."""
    from .knn import exact_knn_single
    import jax.numpy as jnp

    try:
        import scipy.sparse as sp

        rd_sparse = sp.issparse(raw_data)
    except ImportError:  # pragma: no cover
        rd_sparse = False

    n = raw_data.shape[0]
    k = min(n_neighbors, n)
    if rd_sparse:
        Qs = Q if sp.issparse(Q) else sp.csr_matrix(np.asarray(Q))
        Xs = raw_data
        if metric == "cosine":
            qn = np.sqrt(np.asarray(Qs.multiply(Qs).sum(axis=1))).ravel()
            xn = np.sqrt(np.asarray(Xs.multiply(Xs).sum(axis=1))).ravel()
            Qs = sp.diags(1.0 / np.maximum(qn, 1e-12)) @ Qs
            Xs = sp.diags(1.0 / np.maximum(xn, 1e-12)) @ Xs
        x2 = np.asarray(Xs.multiply(Xs).sum(axis=1)).ravel()
        q2 = np.asarray(Qs.multiply(Qs).sum(axis=1)).ravel()
        cross = np.asarray((Qs @ Xs.T).todense())
        d2_full = np.maximum(q2[:, None] - 2.0 * cross + x2[None, :], 0.0)
        part = np.argpartition(d2_full, k - 1, axis=1)[:, :k]
        pd2 = np.take_along_axis(d2_full, part, axis=1)
        order = np.argsort(pd2, axis=1, kind="stable")
        ids_h = np.take_along_axis(part, order, axis=1)
        if metric == "cosine":
            dists = (np.take_along_axis(pd2, order, axis=1) / 2.0).astype(np.float32)
        elif metric == "sqeuclidean":
            dists = np.take_along_axis(pd2, order, axis=1).astype(np.float32)
        else:
            dists = np.sqrt(np.take_along_axis(pd2, order, axis=1)).astype(np.float32)
    elif metric in ("manhattan", "l1", "taxicab", "minkowski"):
        p = 1.0 if metric != "minkowski" else float((metric_kwds or {}).get("p", 2.0))
        d_j, ids = _minkowski_knn(jnp.asarray(Q), jnp.asarray(raw_data), k, p)
        dists = np.asarray(d_j).astype(np.float32)
        ids_h = np.asarray(ids)
    elif metric == "cosine":
        Qj = jnp.asarray(Q)
        Xj = jnp.asarray(raw_data)
        Qj = Qj / jnp.maximum(jnp.linalg.norm(Qj, axis=1, keepdims=True), 1e-12)
        Xj = Xj / jnp.maximum(jnp.linalg.norm(Xj, axis=1, keepdims=True), 1e-12)
        d2, ids = exact_knn_single(Qj, Xj, jnp.ones((n,), bool), k)
        dists = (np.asarray(d2) / 2.0).astype(np.float32)
        ids_h = np.asarray(ids)
    else:
        d2, ids = exact_knn_single(
            jnp.asarray(Q), jnp.asarray(raw_data), jnp.ones((n,), bool), k
        )
        d2_h = np.asarray(d2)
        dists = d2_h if metric == "sqeuclidean" else np.sqrt(d2_h)
        ids_h = np.asarray(ids)
    # membership strengths must use the same local-connectivity kernel the
    # embedding was trained with
    rho, sigma = smooth_knn(
        jnp.asarray(dists), local_connectivity=float(local_connectivity)
    )
    w = np.exp(
        -np.maximum(dists - np.asarray(rho)[:, None], 0.0)
        / np.asarray(sigma)[:, None]
    )
    w_norm = w / np.maximum(w.sum(axis=1, keepdims=True), 1e-12)
    emb0 = np.einsum("qk,qkd->qd", w_norm, embedding[ids_h]).astype(np.float32)
    if n_epochs <= 0:
        return emb0
    if a is None or b is None:
        # callers always pass the fit-time (a, b); this is a permissive fallback
        # for direct op users with the find_ab_params defaults
        a, b = find_ab_params()
    refined = optimize_transform_layout(
        jnp.asarray(emb0),
        jnp.asarray(embedding, dtype=np.float32),
        jnp.asarray(ids_h, dtype=np.int32),
        jnp.asarray(w, dtype=np.float32),  # raw membership strengths drive SGD
        jax.random.PRNGKey(seed & 0x7FFFFFFF),
        a=float(a),
        b=float(b),
        n_epochs=int(n_epochs),
        neg_samples=int(negative_sample_rate),
        initial_lr=float(learning_rate),
        gamma=float(repulsion_strength),
    )
    return np.asarray(refined).astype(np.float32)
