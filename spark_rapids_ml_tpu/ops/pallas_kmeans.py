#
# Pallas TPU kernel: fused Lloyd iteration (assignment + centroid accumulation).
#
# The XLA formulation of one Lloyd step reads X twice per iteration from HBM: once
# for the (n, k) distance matmul and once for the one-hotT @ X centroid update —
# plus it materializes the (n, k) distance/one-hot intermediates. This kernel fuses
# the whole step per row block in VMEM:
#     for each block of rows:  d2 = x2 - 2 Xb Ct + c2      (MXU)
#                              assign = argmin d2
#                              onehot = (iota == assign)    (VPU, never leaves VMEM)
#                              sums   += onehotT @ Xb       (MXU)
#                              counts += sum onehot
#                              inertia+= sum w * min d2
# so X streams through HBM exactly once per iteration and no (n, k) tensor exists.
#
# Single-device form (pallas_call has no GSPMD rule); the multi-device path wraps it
# per-shard under shard_map with a psum merge, exactly like the histogram kernel
# (ops/pallas_histogram.py). Off by default: enable with SRML_TPU_PALLAS_KMEANS=1
# (a TPU-measured win should flip the default in a later round — this image has no
# live TPU to profile).
#

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

BLOCK_ROWS = 1024


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _lloyd_kernel(x_ref, w_ref, c_ref, c2_ref, sums_ref, counts_ref, inertia_ref):
    """One row block: fused distances + argmin + weighted accumulation."""
    b = pl.program_id(0)

    @pl.when(b == 0)
    def _():
        sums_ref[...] = jnp.zeros_like(sums_ref)
        counts_ref[...] = jnp.zeros_like(counts_ref)
        inertia_ref[...] = jnp.zeros_like(inertia_ref)

    Xb = x_ref[...]  # (B, d)
    w = w_ref[...]  # (B, 1)
    C = c_ref[...]  # (k, d)
    c2 = c2_ref[...]  # (1, k)

    cross = jax.lax.dot_general(
        Xb, C, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # (B, k)
    # x2 cancels in the argmin; only the inertia needs it
    part = c2 - 2.0 * cross  # (B, k)
    assign = jnp.argmin(part, axis=1)  # (B,)
    k = C.shape[0]
    cols = jax.lax.broadcasted_iota(jnp.int32, (Xb.shape[0], k), 1)
    onehot = (cols == assign[:, None]).astype(jnp.float32) * w  # (B, k) weighted

    sums_ref[...] += jax.lax.dot_general(
        onehot, Xb, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )  # (k, d)
    counts_ref[...] += jnp.sum(onehot, axis=0)[None, :]  # (1, k)
    x2 = jnp.sum(Xb * Xb, axis=1, keepdims=True)  # (B, 1)
    min_part = jnp.min(part, axis=1, keepdims=True)  # (B, 1)
    d2min = jnp.maximum(x2 + min_part, 0.0)
    inertia_ref[...] += jnp.sum(w * d2min)[None, None]


@functools.partial(jax.jit, static_argnames=("interpret",))
def lloyd_step_pallas(
    X: jax.Array,  # (n, d) f32
    w: jax.Array,  # (n,) f32 — 0 for padding rows
    centers: jax.Array,  # (k, d) f32
    interpret: bool = False,
):
    """One fused Lloyd accumulation pass. Returns (sums (k,d), counts (k,),
    inertia scalar) — the caller forms new centers as sums/counts."""
    n, d = X.shape
    k = centers.shape[0]
    pad = (-n) % BLOCK_ROWS
    if pad:
        X = jnp.pad(X, ((0, pad), (0, 0)))
        w = jnp.pad(w, ((0, pad),))
    c2 = jnp.sum(centers * centers, axis=1)[None, :]  # (1, k)

    sums, counts, inertia = pl.pallas_call(
        _lloyd_kernel,
        grid=(X.shape[0] // BLOCK_ROWS,),
        in_specs=[
            pl.BlockSpec((BLOCK_ROWS, d), lambda b: (b, 0)),
            pl.BlockSpec((BLOCK_ROWS, 1), lambda b: (b, 0)),
            pl.BlockSpec((k, d), lambda b: (0, 0)),
            pl.BlockSpec((1, k), lambda b: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((k, d), lambda b: (0, 0)),
            pl.BlockSpec((1, k), lambda b: (0, 0)),
            pl.BlockSpec((1, 1), lambda b: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(X, w[:, None], centers, c2)
    return sums, counts[0], inertia[0, 0]


def lloyd_fit_pallas(
    X: jax.Array,
    w: jax.Array,
    init_centers: jax.Array,
    tol: float,
    max_iter: int,
    mesh=None,
    interpret: bool = False,
):
    """Full Lloyd loop over the fused kernel; identical convergence semantics to
    ops/kmeans.lloyd_fit (movement^2 <= tol^2). With a multi-device mesh the kernel
    runs per-shard under shard_map and the (sums, counts, inertia) partials psum."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS

    if mesh is not None and mesh.devices.size > 1:
        from jax import shard_map

        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P()),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )
        def _step(x_local, w_local, centers):
            s, c, i = lloyd_step_pallas(x_local, w_local, centers, interpret=interpret)
            return (
                jax.lax.psum(s, DATA_AXIS),
                jax.lax.psum(c, DATA_AXIS),
                jax.lax.psum(i, DATA_AXIS),
            )

        step = _step
    else:
        step = functools.partial(lloyd_step_pallas, interpret=interpret)

    centers = init_centers
    inertia = np.inf
    n_iter = 0
    for it in range(max_iter):
        sums, counts, inertia_j = step(X, w, centers)
        new_centers = jnp.where(
            counts[:, None] > 0,
            sums / jnp.maximum(counts, 1.0)[:, None],
            centers,
        )
        shift2 = float(jnp.max(jnp.sum((new_centers - centers) ** 2, axis=1)))
        centers = new_centers
        inertia = float(inertia_j)
        n_iter = it + 1
        if shift2 <= tol * tol:
            break
    return centers, inertia, n_iter
