#
# Pallas TPU kernel: fused Lloyd iteration (assignment + centroid accumulation).
#
# The XLA formulation of one Lloyd step reads X twice per iteration from HBM: once
# for the (n, k) distance matmul and once for the one-hotT @ X centroid update —
# plus it materializes the (n, k) distance/one-hot intermediates. This kernel fuses
# the whole step per row block in VMEM:
#     for each block of rows:  d2 = x2 - 2 Xb Ct + c2      (MXU)
#                              assign = argmin d2
#                              onehot = (iota == assign)    (VPU, never leaves VMEM)
#                              sums   += onehotT @ Xb       (MXU)
#                              counts += sum onehot
#                              inertia+= sum w * min d2
# so X streams through HBM exactly once per iteration and no (n, k) tensor exists.
#
# Single-device form (pallas_call has no GSPMD rule); the multi-device path wraps it
# per-shard under shard_map with a psum merge, exactly like the histogram kernel
# (ops/pallas_histogram.py).
#
# MEASURED (v5e, 12M x 128, k=20, steady-state marginal per-iteration): XLA
# lloyd_fit 18.7 ms/iter (~92% of its two-X-reads HBM roofline) vs this kernel at
# 26.3 (1-pass) / 37.5 (6-pass parity) ms/iter. At small k the two MXU matmuls pad
# k to the 128-lane width, so halving HBM traffic buys nothing — the kernel is
# VPU/MXU-bound, not DMA-bound. SRML_TPU_PALLAS_KMEANS therefore AUTO-resolves
# (the default since the §5c fused-selection PR): on TPU at k >= 128 — where
# lane padding vanishes and XLA's (n, k) distance/one-hot intermediates approach
# the size of X itself — the fused kernel engages (masked form under unit
# weights); below that, or off-TPU, the XLA path runs. "1"/"mask" force the
# kernel unconditionally, "0" forces XLA; `kmeans.lloyd_path{path=}` counts
# which path ran (ops/kmeans.py::kmeans_fit owns the routing). The ASSIGNMENT
# half of the win region is served by the lighter fused distance+argmin scan
# in ops/pallas_select.py (kmeans_predict routes there under the same gate).
#

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

BLOCK_ROWS = 0  # 0 = adaptive (see _block_rows); tests may pin a fixed size

# MXU passes emulating each f32 precision tier via bf16 splitting (_dot_multipass)
_N_SPLIT = {
    jax.lax.Precision.DEFAULT: 1,
    jax.lax.Precision.HIGH: 2,
    jax.lax.Precision.HIGHEST: 3,
}


def _block_rows(d: int, n_split: int = 1) -> int:
    """Row-block size targeting ~2 MiB of X per block: big enough to amortize DMA
    issue latency (TPU-measured: 1024-row blocks pay ~10% over 4096 at d=128),
    small enough that double-buffered blocks + the (B, 128-lane-padded) distance/
    one-hot intermediates stay inside the 16 MiB scoped-VMEM budget at any d
    (a lax.cond variant at 4096x512 was observed to blow exactly that limit).
    Multipass precision (n_split>1) materializes n_split bf16 copies of the X
    block and the one-hot, so the block shrinks with it (3-split at 4096x128
    was observed 2.56 MiB over the scoped-vmem limit)."""
    if BLOCK_ROWS:
        return BLOCK_ROWS
    target = 2 * 1024 * 1024 // (max(d, 1) * 4)
    blk = int(min(8192, max(512, 1 << (target.bit_length() - 1))))
    if n_split > 1:
        blk = max(512, blk // 2)
    return blk


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def lloyd_fits_vmem(k: int, d: int, n_split: int) -> bool:
    """Can the fused Lloyd place its VMEM residents at this (k, d, n_split)?
    The kernel keeps C and the sums accumulator (k, d) resident (bf16
    splitting materializes n_split operand copies of C and the one-hot) next
    to one _block_rows-sized X block and the (blk, k) distance/one-hot
    intermediates. The routing gate (ops/kmeans.py::kmeans_fit auto mode)
    asks THIS predicate instead of hand-rolling a formula, so the knowledge
    of the kernel's working set lives with the kernel — a (k, d) that fails
    here stays on the XLA path rather than handing Mosaic an unplaceable
    compile."""
    from .pallas_select import _VMEM_BUDGET_BYTES  # one budget, one source

    copies = max(1, int(n_split))
    blk = _block_rows(d, copies)
    # f32 operands carry 2-byte bf16 split copies when n_split > 1
    split_b = 2 * copies if copies > 1 else 0
    resident = k * d * (8 + split_b)  # C (+splits) and the f32 sums
    working = (
        blk * d * (4 + split_b)  # X block (+splits)
        + blk * k * (8 + split_b)  # distance tile + one-hot (+splits)
    )
    return resident + working <= _VMEM_BUDGET_BYTES


def _split_bf16(x, n_split: int):
    """Decompose f32 into n_split bf16 terms (x ≈ Σ parts): the classic
    hi/lo residual split behind XLA's HIGH/HIGHEST f32 matmul emulation."""
    parts = []
    r = x
    for _ in range(n_split):
        p = r.astype(jnp.bfloat16)
        parts.append(p)
        r = r - p.astype(jnp.float32)
    return parts


def _dot_multipass(a, b, dims, n_split: int):
    """dot_general with f32 operands emulated at higher precision via bf16
    splitting: n_split=1 → single-pass MXU (DEFAULT numerics), 2 → 3 passes
    (≙ Precision.HIGH), 3 → 6 passes (≙ Precision.HIGHEST ≈ full f32).
    Mosaic rejects precision=HIGH/HIGHEST on this toolchain (NotImplementedError /
    compile-helper crash, observed on v5e), so the decomposition is done by hand;
    each pass is a native bf16×bf16→f32 MXU matmul."""
    if n_split <= 1:
        return jax.lax.dot_general(
            a, b, dims, preferred_element_type=jnp.float32
        )
    pa = _split_bf16(a, n_split)
    pb = _split_bf16(b, n_split)
    acc = None
    # terms ordered smallest-magnitude first so the f32 accumulation loses the
    # least; skip terms whose combined order i+j >= n_split (below f32 ulp)
    for i in range(n_split - 1, -1, -1):
        for j in range(n_split - 1 - i, -1, -1):
            t = jax.lax.dot_general(
                pa[i], pb[j], dims, preferred_element_type=jnp.float32
            )
            acc = t if acc is None else acc + t
    return acc


def _lloyd_kernel(
    n_rows, n_split, x_ref, w_ref, c_ref, c2_ref, sums_ref, counts_ref, inertia_ref
):
    """One row block: fused distances + argmin + weighted accumulation.

    The grid covers ceil(n / BLOCK_ROWS) blocks with NO host-side padding of X —
    padding would copy the whole design matrix inside the jit, doubling HBM at
    exactly the HBM-filling sizes this kernel exists for (observed OOM at 12M x 128
    on a 16 GiB v5e). The ragged tail block is masked here instead: rows past
    n_rows load unspecified values from the edge block, so both X and w are zeroed
    before any arithmetic can propagate them (0 * garbage stays finite only when
    the garbage never reaches a matmul — hence masking X itself, not just w)."""
    b = pl.program_id(0)

    @pl.when(b == 0)
    def _():
        sums_ref[...] = jnp.zeros_like(sums_ref)
        counts_ref[...] = jnp.zeros_like(counts_ref)
        inertia_ref[...] = jnp.zeros_like(inertia_ref)

    Xb = x_ref[...]  # (B, d)
    w = w_ref[...]  # (B, 1)
    C = c_ref[...]  # (k, d)
    c2 = c2_ref[...]  # (1, k)

    row0 = b * Xb.shape[0]
    rows = row0 + jax.lax.broadcasted_iota(jnp.int32, (Xb.shape[0], 1), 0)
    valid = rows < n_rows  # (B, 1) bool
    # select, don't multiply: the edge block's unspecified region can be NaN
    # (interpret mode fills it so) and 0 * NaN is NaN
    Xb = jnp.where(valid, Xb, 0.0)
    w = jnp.where(valid, w, 0.0)

    cross = _dot_multipass(Xb, C, (((1,), (1,)), ((), ())), n_split)  # (B, k)
    # x2 cancels in the argmin; only the inertia needs it
    part = c2 - 2.0 * cross  # (B, k)
    assign = jnp.argmin(part, axis=1)  # (B,)
    k = C.shape[0]
    cols = jax.lax.broadcasted_iota(jnp.int32, (Xb.shape[0], k), 1)
    onehot = (cols == assign[:, None]).astype(jnp.float32) * w  # (B, k) weighted

    sums_ref[...] += _dot_multipass(
        onehot, Xb, (((0,), (0,)), ((), ())), n_split
    )  # (k, d)
    counts_ref[...] += jnp.sum(onehot, axis=0)[None, :]  # (1, k)
    x2 = jnp.sum(Xb * Xb, axis=1, keepdims=True)  # (B, 1)
    min_part = jnp.min(part, axis=1, keepdims=True)  # (B, 1)
    d2min = jnp.maximum(x2 + min_part, 0.0)
    inertia_ref[...] += jnp.sum(w * d2min)[None, None]


def _lloyd_kernel_masked(
    n_split, nv_ref, x_ref, c_ref, c2_ref, sums_ref, counts_ref, inertia_ref
):
    """Unit-weight variant of _lloyd_kernel: NO weight vector operand. A (blk, 1)
    w block tile-pads to 128 lanes in VMEM and forces a layout-converting DMA —
    measured 3x slower on the sibling Gram kernel (ops/pallas_xtwx.py header).
    Row validity is the runtime scalar nv_ref (the pad_rows prefix-mask
    contract); sample-weighted fits keep the weighted kernel."""
    b = pl.program_id(0)

    @pl.when(b == 0)
    def _():
        sums_ref[...] = jnp.zeros_like(sums_ref)
        counts_ref[...] = jnp.zeros_like(counts_ref)
        inertia_ref[...] = jnp.zeros_like(inertia_ref)

    Xb = x_ref[...]  # (B, d)
    C = c_ref[...]  # (k, d)
    c2 = c2_ref[...]  # (1, k)

    row0 = b * Xb.shape[0]
    rows = row0 + jax.lax.broadcasted_iota(jnp.int32, (Xb.shape[0], 1), 0)
    valid = rows < nv_ref[0, 0]
    # select, don't multiply: unspecified edge-block values can be NaN
    Xb = jnp.where(valid, Xb, 0.0)

    cross = _dot_multipass(Xb, C, (((1,), (1,)), ((), ())), n_split)  # (B, k)
    part = c2 - 2.0 * cross
    assign = jnp.argmin(part, axis=1)
    k = C.shape[0]
    cols = jax.lax.broadcasted_iota(jnp.int32, (Xb.shape[0], k), 1)
    onehot = jnp.where(
        valid, (cols == assign[:, None]).astype(jnp.float32), 0.0
    )  # (B, k)

    sums_ref[...] += _dot_multipass(onehot, Xb, (((0,), (0,)), ((), ())), n_split)
    counts_ref[...] += jnp.sum(onehot, axis=0)[None, :]
    x2 = jnp.sum(Xb * Xb, axis=1, keepdims=True)
    min_part = jnp.min(part, axis=1, keepdims=True)
    d2min = jnp.maximum(x2 + min_part, 0.0)
    inertia_ref[...] += jnp.sum(jnp.where(valid, d2min, 0.0))[None, None]


@functools.partial(jax.jit, static_argnames=("interpret", "blk", "n_split"))
def _lloyd_step_masked_jit(X, n_valid, centers, interpret: bool, blk: int, n_split: int):
    n, d = X.shape
    k = centers.shape[0]
    c2 = jnp.sum(centers * centers, axis=1)[None, :]

    sums, counts, inertia = pl.pallas_call(
        functools.partial(_lloyd_kernel_masked, n_split),
        grid=((n + blk - 1) // blk,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda b: (0, 0)),
            pl.BlockSpec((blk, d), lambda b: (b, 0)),
            pl.BlockSpec((k, d), lambda b: (0, 0)),
            pl.BlockSpec((1, k), lambda b: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((k, d), lambda b: (0, 0)),
            pl.BlockSpec((1, k), lambda b: (0, 0)),
            pl.BlockSpec((1, 1), lambda b: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(jnp.asarray(n_valid, jnp.int32).reshape(1, 1), X, centers, c2)
    return sums, counts[0], inertia[0, 0]


def lloyd_step_pallas_masked(
    X: jax.Array,
    n_valid,
    centers: jax.Array,
    interpret: bool = False,
    blk: int | None = None,
    precision: jax.lax.Precision = jax.lax.Precision.DEFAULT,
):
    """Unit-weight fused Lloyd pass over the first n_valid rows (runtime scalar);
    one X read, no weight stream. Returns (sums, counts, inertia)."""
    n_split = _N_SPLIT[precision]
    return _lloyd_step_masked_jit(
        X, n_valid, centers, interpret,
        blk if blk else _block_rows(X.shape[1], n_split), n_split,
    )


def lloyd_step_pallas(
    X: jax.Array,  # (n, d) f32
    w: jax.Array,  # (n,) f32 — 0 for padding rows
    centers: jax.Array,  # (k, d) f32
    interpret: bool = False,
    blk: int | None = None,
    precision: jax.lax.Precision = jax.lax.Precision.DEFAULT,
):
    """One fused Lloyd accumulation pass. Returns (sums (k,d), counts (k,),
    inertia scalar) — the caller forms new centers as sums/counts.

    blk resolves OUTSIDE the jitted inner so a test pinning the module-level
    BLOCK_ROWS actually takes effect — the jit cache is keyed on the static blk,
    never on the module global.

    precision sets both MXU matmuls (assignment cross-term and one-hot update):
    DEFAULT = single-pass bf16 class (fast_math numerics), HIGH = 3-pass,
    HIGHEST = 6-pass f32 parity (emulated in-kernel via bf16 splitting — Mosaic
    rejects the precision attribute itself on this toolchain). The kernel is
    HBM-streaming-bound at the shapes it exists for, so the extra parity passes
    ride mostly under the DMA floor."""
    n_split = _N_SPLIT[precision]
    return _lloyd_step_jit(
        X, w, centers, interpret,
        blk if blk else _block_rows(X.shape[1], n_split), n_split,
    )


@functools.partial(jax.jit, static_argnames=("interpret", "blk", "n_split"))
def _lloyd_step_jit(
    X: jax.Array,
    w: jax.Array,
    centers: jax.Array,
    interpret: bool,
    blk: int,
    n_split: int,
):
    n, d = X.shape
    k = centers.shape[0]
    c2 = jnp.sum(centers * centers, axis=1)[None, :]  # (1, k)

    sums, counts, inertia = pl.pallas_call(
        functools.partial(_lloyd_kernel, n, n_split),
        grid=((n + blk - 1) // blk,),
        in_specs=[
            pl.BlockSpec((blk, d), lambda b: (b, 0)),
            pl.BlockSpec((blk, 1), lambda b: (b, 0)),
            pl.BlockSpec((k, d), lambda b: (0, 0)),
            pl.BlockSpec((1, k), lambda b: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((k, d), lambda b: (0, 0)),
            pl.BlockSpec((1, k), lambda b: (0, 0)),
            pl.BlockSpec((1, 1), lambda b: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(X, w[:, None], centers, c2)
    return sums, counts[0], inertia[0, 0]


@functools.lru_cache(maxsize=None)
def _fit_fn(
    mesh,
    interpret: bool,
    blk: int,
    precision=jax.lax.Precision.DEFAULT,
    unit_mask: bool = False,
):
    """Build (and cache) the jitted full-loop fit for a mesh/interpret/blk combo.

    The whole Lloyd loop runs ON DEVICE as a lax.while_loop around the fused step —
    a host-driven loop costs one host<->device round trip per iteration, which under
    a remote-relay tunnel dominates everything (measured: 0.2 s/iter host-driven vs
    the ~40 ms/iter kernel). One dispatch for the whole fit, like ops/kmeans.lloyd_fit.

    The REPORTED inertia is recomputed against the final centers at parity
    precision (pdot) outside the kernel — the kernel's own inertia accumulator
    (default-precision matmul) only steers the convergence loop. This keeps the
    fast_math contract from ops/kmeans.lloyd_fit: ranking-class matmuls may run
    at bf16, anything reported as a model attribute stays parity-precision."""
    from ..parallel.mesh import DATA_AXIS
    from ..parallel.partitioner import partitioner_for
    from ._precision import pdot

    if mesh is not None and mesh.devices.size > 1:
        from ..utils.jax_compat import shard_map

        part = partitioner_for(mesh)

        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(part.data_spec(2), part.data_spec(1), part.state_spec()),
            out_specs=(part.state_spec(), part.state_spec(), part.state_spec()),
            check_vma=False,
        )
        def step(x_local, w_local, centers):
            if unit_mask:
                # per-shard valid-prefix count: one cheap read of w vs streaming
                # a (blk, 1) weight block through VMEM every grid step
                s, c, i = lloyd_step_pallas_masked(
                    x_local, jnp.sum(w_local.astype(jnp.int32)), centers,
                    interpret=interpret, blk=blk, precision=precision,
                )
            else:
                s, c, i = lloyd_step_pallas(
                    x_local, w_local, centers, interpret=interpret, blk=blk,
                    precision=precision,
                )
            return (
                jax.lax.psum(s, DATA_AXIS),
                jax.lax.psum(c, DATA_AXIS),
                jax.lax.psum(i, DATA_AXIS),
            )

    elif unit_mask:

        def step(X, w, centers):
            return lloyd_step_pallas_masked(
                X, jnp.sum(w.astype(jnp.int32)), centers,
                interpret=interpret, blk=blk, precision=precision,
            )

    else:
        step = functools.partial(
            lloyd_step_pallas, interpret=interpret, blk=blk, precision=precision
        )

    def fit(X, w, init_centers, tol, max_iter):
        def cond(state):
            _, _, it, shift2 = state
            return jnp.logical_and(it < max_iter, shift2 > tol * tol)

        def body(state):
            centers, _, it, _ = state
            sums, counts, inertia = step(X, w, centers)
            new_centers = jnp.where(
                counts[:, None] > 0,
                sums / jnp.maximum(counts, 1.0)[:, None],
                centers,
            )
            shift2 = jnp.max(jnp.sum((new_centers - centers) ** 2, axis=1))
            return new_centers, inertia, it + 1, shift2

        state = (
            init_centers,
            jnp.array(0.0, X.dtype),
            jnp.array(0, jnp.int32),
            jnp.array(jnp.inf, X.dtype),
        )
        centers, _, n_iter, _ = jax.lax.while_loop(cond, body, state)
        # reported inertia: final centers, PARITY precision (see docstring)
        x2 = jnp.sum(X * X, axis=1)
        c2 = jnp.sum(centers * centers, axis=1)
        d2 = x2[:, None] - 2.0 * pdot(X, centers.T) + c2[None, :]
        inertia = jnp.sum(w * jnp.maximum(jnp.min(d2, axis=1), 0.0))
        return centers, inertia, n_iter

    return jax.jit(fit, static_argnames=("max_iter",))


def lloyd_fit_pallas(
    X: jax.Array,
    w: jax.Array,
    init_centers: jax.Array,
    tol: float,
    max_iter: int,
    mesh=None,
    interpret: bool = False,
    precision: jax.lax.Precision = jax.lax.Precision.DEFAULT,
    unit_mask: bool = False,
):
    """Full Lloyd loop over the fused kernel; identical convergence semantics to
    ops/kmeans.lloyd_fit (movement^2 <= tol^2). With a multi-device mesh the kernel
    runs per-shard under shard_map and the (sums, counts, inertia) partials psum.

    precision=HIGHEST makes the in-loop numerics match lloyd_fit's parity path
    (f32 assignment + f32 update accumulation); DEFAULT matches fast_math.

    unit_mask=True requires w to be the pad_rows {1…1,0…0} prefix mask per shard
    (FitInputs.unit_weight) and runs the weight-stream-free kernel — the same
    (blk, 1)-operand elimination that took the Gram kernel from 25.7 to
    8.2 ms/pass (ops/pallas_xtwx.py header)."""
    n_split = _N_SPLIT[precision]
    centers, inertia, n_iter = _fit_fn(
        mesh, interpret, _block_rows(X.shape[1], n_split), precision, unit_mask
    )(X, w, init_centers, jnp.asarray(tol, X.dtype), max_iter)
    return centers, float(inertia), int(n_iter)
