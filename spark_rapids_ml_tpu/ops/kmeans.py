#
# KMeans fit/predict kernels — the TPU-native replacement for
# cuml.cluster.kmeans_mg.KMeansMG (reference clustering.py:376-456; the centroid
# allreduce happens inside cuML over NCCL).
#
# TPU formulation: Lloyd iterations as one jitted lax.while_loop over row-sharded data.
# Per iteration:
#   * assignment: pairwise squared distances via the ‖x‖² - 2x·c + ‖c‖² expansion —
#     an (n,k) matmul on the MXU,
#   * update: one-hot(assign)ᵀ @ X — another MXU matmul whose contraction over the
#     sharded row axis makes XLA emit the psum over ICI (exactly where cuML put its
#     NCCL allreduce).
# Empty clusters keep their previous center (cuML/Spark behavior for stability).
#
# Initialization: "random" picks k real rows; "k-means||" (Spark's default initMode)
# runs `initSteps` rounds of distance-weighted oversampling. The reference delegates to
# cuML's scalable-k-means++; the TPU version keeps shapes static by sampling a fixed
# 2k candidates per round via the Gumbel-top-k trick on log(d²) (sampling without
# replacement ∝ d², same distribution as k-means|| oversampling with l=2k), then runs
# weighted k-means++ on the small candidate set host-side — the same
# cluster-then-reduce structure as scalable k-means++.
#

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..observability.device import compiled_kernel
from ._precision import FAST, pdot
from .selection import top_k_max


@functools.partial(jax.jit, static_argnames=("fast",))
def _sq_dists(X: jax.Array, centers: jax.Array, fast: bool = False) -> jax.Array:
    """(n, k) squared euclidean distances; the MXU hot loop. `fast=True` runs the
    cross-term matmul at MXU bf16 precision — valid for ASSIGNMENT (ranking) use;
    anything feeding model attributes stays at parity precision."""
    x2 = jnp.sum(X * X, axis=1, keepdims=True)
    c2 = jnp.sum(centers * centers, axis=1)
    cross = jnp.matmul(X, centers.T, precision=FAST) if fast else pdot(X, centers.T)
    d2 = x2 - 2.0 * cross + c2
    return jnp.maximum(d2, 0.0)


def _normalize_rows(X: jax.Array) -> jax.Array:
    norms = jnp.linalg.norm(X, axis=1, keepdims=True)
    return X / jnp.maximum(norms, 1e-12)


@compiled_kernel("kmeans.lloyd_fit",
                 static_argnames=("max_iter", "cosine", "fast_math"))
def lloyd_fit(
    X: jax.Array,
    w: jax.Array,
    init_centers: jax.Array,
    tol: float,
    max_iter: int,
    cosine: bool = False,
    fast_math: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Lloyd iterations until max center movement² <= tol² or max_iter.

    Returns (centers, inertia, n_iter). Convergence on per-center movement matches
    Spark's KMeans semantics (the reference remaps tol=0 to a tiny epsilon,
    clustering.py:84-141 — callers do that remap).

    cosine=True runs spherical kmeans (Spark's distanceMeasure='cosine'): callers
    pass row-normalized X; centers are re-normalized every update and the cost is
    Σ w·(1 - x̂·ĉ).

    fast_math=True runs the ASSIGNMENT distance matmul at MXU bf16 (single-pass)
    precision — the centroid-update contraction and the final reported inertia stay
    at parity precision, so model attributes remain fp32-exact while the hot loop's
    dominant matmul runs at full MXU throughput (config key `fast_math`)."""
    k = init_centers.shape[0]
    if cosine:
        init_centers = _normalize_rows(init_centers)

    def _dists(centers, fast=False):
        if cosine:
            if fast:
                return 1.0 - jnp.matmul(X, centers.T, precision=FAST)
            return 1.0 - pdot(X, centers.T)
        return _sq_dists(X, centers, fast=fast)

    def cond(state):
        _, _, it, shift2 = state
        return jnp.logical_and(it < max_iter, shift2 > tol * tol)

    def body(state):
        centers, _, it, _ = state
        d2 = _dists(centers, fast=fast_math)
        assign = jnp.argmin(d2, axis=1)
        min_d2 = jnp.min(d2, axis=1)
        onehot = jax.nn.one_hot(assign, k, dtype=X.dtype) * w[:, None]
        counts = jnp.sum(onehot, axis=0)
        sums = pdot(onehot.T, X)
        new_centers = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], centers
        )
        if cosine:
            new_centers = _normalize_rows(new_centers)
        inertia = jnp.sum(w * min_d2)
        shift2 = jnp.max(jnp.sum((new_centers - centers) ** 2, axis=1))
        return new_centers, inertia, it + 1, shift2

    init_state = (init_centers, jnp.array(0.0, X.dtype), 0, jnp.array(jnp.inf, X.dtype))
    centers, inertia, n_iter, _ = jax.lax.while_loop(cond, body, init_state)
    # inertia reported against the final centers
    inertia = jnp.sum(w * jnp.min(_dists(centers), axis=1))
    return centers, inertia, n_iter


@compiled_kernel("kmeans.predict", static_argnames=("cosine",))
def _kmeans_predict_xla(
    X: jax.Array, centers: jax.Array, cosine: bool = False
) -> jax.Array:
    if cosine:
        return jnp.argmax(pdot(_normalize_rows(X), _normalize_rows(centers).T), axis=1)
    return jnp.argmin(_sq_dists(X, centers), axis=1)


def kmeans_predict(
    X: jax.Array, centers: jax.Array, cosine: bool = False
) -> jax.Array:
    """Nearest-center assignment. Host wrapper (the PR-5 contract: strategy
    resolves OUTSIDE any trace): euclidean assignment routes to the fused
    pallas distance+argmin scan (ops/pallas_select.py — X streams through
    once, no (n, k) distance matrix in HBM, bit-identical argmin) when
    `knn.selection` is `pallas_fused`, or under `auto` on TPU at k >= 128
    (below that the lane-padded MXU tiles erase the fusion win — the
    documented ops/pallas_kmeans.py small-k region). Cosine keeps the XLA
    kernel: its ranking is a normalized argMAX, not this kernel's reduction.
    `kmeans.assign_path{path=}` proves which path ran."""
    from ..ops import pallas_select as _ps
    from . import selection as _sel

    tracing = _sel.is_tracing(X, centers)
    if (
        not cosine
        and not tracing
        and _ps.use_fused_assign(centers.shape[0], centers.shape[1])
    ):
        from .. import observability as _obs

        _obs.counter_inc("kmeans.assign_path", 1, path="pallas_fused")
        return _ps.fused_assign(X, centers)
    if not tracing:
        from .. import observability as _obs

        _obs.counter_inc("kmeans.assign_path", 1, path="xla")
    return _kmeans_predict_xla(X, centers, cosine)


@compiled_kernel("kmeans.inertia")
def kmeans_inertia(X: jax.Array, w: jax.Array, centers: jax.Array) -> jax.Array:
    return jnp.sum(w * jnp.min(_sq_dists(X, centers), axis=1))


def _random_real_rows(
    X: jax.Array, w: jax.Array, n_pick: int, key: jax.Array
) -> jax.Array:
    """Pick n_pick distinct real (w>0) rows via Gumbel-top-k on the mask."""
    g = jax.random.gumbel(key, (X.shape[0],), dtype=X.dtype)
    score = jnp.where(w > 0, g, -jnp.inf)
    _, idx = top_k_max(score, n_pick)  # exact: seeded init determinism
    return X[idx]


@functools.partial(jax.jit, static_argnames=("n_pick",))
def _sample_by_d2(
    X: jax.Array, w: jax.Array, centers: jax.Array, n_pick: int, key: jax.Array
) -> jax.Array:
    """Sample n_pick rows without replacement with probability ∝ d²(x, centers):
    Gumbel-top-k over log d² (k-means|| oversampling with static shapes)."""
    d2 = jnp.min(_sq_dists(X, centers), axis=1)
    logits = jnp.where(w > 0, jnp.log(d2 + 1e-30), -jnp.inf)
    g = jax.random.gumbel(key, logits.shape, dtype=X.dtype)
    _, idx = top_k_max(logits + g, n_pick)  # exact: seeded sampling
    return X[idx]


@functools.partial(jax.jit, static_argnames=("l", "steps"))
def _oversample_rounds(
    X: jax.Array, w: jax.Array, first: jax.Array, key: jax.Array, l: int, steps: int
) -> jax.Array:
    """All k-means|| oversampling rounds in ONE dispatch: the former host loop
    synced candidates to host every round (2 relay round trips per step) and
    recomputed distances against the WHOLE candidate set each time; here the
    min-distance vector updates incrementally against only the new candidates
    (O(steps·l·n·d) instead of O(steps²·l·n·d)). Returns (1 + steps·l, d)
    candidates; already-chosen rows get d²=0 so they are ~never re-drawn, same
    as the host version's behavior."""
    n_c = 1 + steps * l
    buf = jnp.zeros((n_c, X.shape[1]), X.dtype).at[0].set(first)
    d2 = jnp.sum((X - first[None, :]) ** 2, axis=1)
    for r in range(steps):
        key, sub = jax.random.split(key)
        logits = jnp.where(w > 0, jnp.log(d2 + 1e-30), -jnp.inf)
        g = jax.random.gumbel(sub, logits.shape, dtype=X.dtype)
        _, idx = top_k_max(logits + g, l)  # exact: seeded sampling
        newc = X[idx]
        buf = jax.lax.dynamic_update_slice(buf, newc, (1 + r * l, 0))
        d2 = jnp.minimum(d2, jnp.min(_sq_dists(X, newc), axis=1))
    return buf


def _cand_sq_dists(candidates: np.ndarray, centers: np.ndarray) -> np.ndarray:
    """(n_cand, k) squared distances via the matmul expansion — never materializes
    the (n_cand, k, d) broadcast (IVF builds call this with k in the thousands)."""
    c2 = np.sum(centers * centers, axis=1)
    x2 = np.sum(candidates * candidates, axis=1)
    return np.maximum(
        x2[:, None] - 2.0 * (candidates @ centers.T) + c2[None, :], 0.0
    )


def _weighted_kmeans_pp_once(
    candidates: np.ndarray, weights: np.ndarray, k: int, rng: np.random.Generator
):
    n = candidates.shape[0]
    centers = np.empty((k, candidates.shape[1]), dtype=candidates.dtype)
    p = weights / weights.sum()
    centers[0] = candidates[rng.choice(n, p=p)]
    d2 = np.sum((candidates - centers[0]) ** 2, axis=1)
    # greedy k-means++ (sklearn-style): draw several d²-weighted trials per step
    # and keep the one that minimizes the resulting potential — a single
    # non-greedy draw can seed two centers in one heavy cluster and the local
    # refinement below cannot always escape that basin
    n_local_trials = 2 + int(np.log(k))
    for i in range(1, k):
        probs = weights * d2
        s = probs.sum()
        if s <= 0:
            centers[i] = candidates[rng.integers(n)]
            d2 = np.minimum(
                d2, np.sum((candidates - centers[i]) ** 2, axis=1)
            )
            continue
        trial_ids = rng.choice(n, size=n_local_trials, p=probs / s)
        trial_d2 = _cand_sq_dists(candidates, candidates[trial_ids])  # (n, t)
        new_d2 = np.minimum(d2[:, None], trial_d2)
        potentials = (weights[:, None] * new_d2).sum(axis=0)
        best_t = int(np.argmin(potentials))
        centers[i] = candidates[trial_ids[best_t]]
        d2 = new_d2[:, best_t]

    # local weighted Lloyd refinement over the (tiny) candidate set — Spark's
    # LocalKMeans runs the same after its ++ seeding; empty centers reseed at the
    # worst-covered candidate
    for _ in range(10):
        d2_all = _cand_sq_dists(candidates, centers)  # (n_cand, k)
        a = np.argmin(d2_all, axis=1)
        sums = np.zeros_like(centers)
        np.add.at(sums, a, candidates * weights[:, None])
        cnts = np.zeros(k, dtype=weights.dtype)
        np.add.at(cnts, a, weights)
        for j in np.nonzero(cnts <= 0)[0]:
            far = np.argmax(np.min(d2_all, axis=1))
            centers[j] = candidates[far]
            d2_all[far] = 0.0
        ok = cnts > 0
        centers[ok] = sums[ok] / cnts[ok, None]
    # score the FINAL centers (the in-loop d2_all predates the last update)
    cost = float(
        np.sum(weights * np.min(_cand_sq_dists(candidates, centers), axis=1))
    )
    return centers, cost


def _weighted_kmeans_pp(
    candidates: np.ndarray,
    weights: np.ndarray,
    k: int,
    rng: np.random.Generator,
    restarts: int = 8,
) -> np.ndarray:
    """Host-side weighted k-means++ over the small candidate set (the final reduce
    of scalable k-means++). Even the greedy ++ draw can land a poor basin the
    refinement cannot escape; restarts scored by weighted candidate inertia make
    that mode vanishingly unlikely at negligible cost (the candidate set is
    ~(1 + steps·2k) rows). Large k (IVF coarse quantizers call this with
    k=nlist in the thousands, candidates ~4k) caps restarts at 2: the greedy
    trials already remove most of the need for restarts, and the per-restart
    cost there is O(k²·t·d) host work."""
    if k > 64:
        restarts = min(restarts, 2)
    best = None
    best_cost = np.inf
    for _ in range(max(restarts, 1)):
        centers, cost = _weighted_kmeans_pp_once(candidates, weights, k, rng)
        # `best is None` guard: NaN costs (NaN features in the candidate set)
        # compare false against everything and must not leave best unset
        if best is None or cost < best_cost:
            best, best_cost = centers, cost
    return best


def kmeans_init(
    X: jax.Array,
    w: jax.Array,
    k: int,
    init: str,
    init_steps: int,
    seed: int,
) -> np.ndarray:
    """Compute initial centers (host-side result).

    init == "random": k distinct real rows.
    init == "k-means||" (or "scalable-k-means++"): Gumbel-top-k oversampling rounds,
    then weighted k-means++ on the ~(1 + steps·2k) candidates."""
    key = jax.random.PRNGKey(seed & 0x7FFFFFFF)
    if init == "random":
        return np.asarray(_random_real_rows(X, w, k, key))

    rng = np.random.default_rng(seed & 0x7FFFFFFF)
    n_real = int(jnp.sum(w > 0))
    l = max(2, min(2 * k, n_real))  # never oversample past the real rows (padding)
    key, sub = jax.random.split(key)
    first = _random_real_rows(X, w, 1, sub)[0]
    key, sub = jax.random.split(key)
    candidates = np.asarray(
        _oversample_rounds(X, w, first, sub, l, max(init_steps, 1))
    )
    # weight candidates by how many points they attract (one cheap pass)
    assign = np.asarray(kmeans_predict(X, jnp.asarray(candidates)))
    wh = np.asarray(w)
    weights = np.bincount(assign, weights=wh, minlength=candidates.shape[0]).astype(
        candidates.dtype
    )
    weights = np.maximum(weights, 1e-12)
    return _weighted_kmeans_pp(candidates, weights, k, rng)


def kmeans_fit(
    X: jax.Array,
    w: jax.Array,
    k: int,
    max_iter: int,
    tol: float,
    init: str,
    init_steps: int,
    seed: int,
    metric: str = "euclidean",
    unit_weight: bool = False,
) -> Dict[str, object]:
    cosine = metric == "cosine"
    if cosine:
        # Spark raises on zero-norm vectors with cosine distance; match it rather
        # than silently assigning an arbitrary direction
        min_norm = float(jnp.min(jnp.where(w > 0, jnp.linalg.norm(X, axis=1), jnp.inf)))
        if min_norm <= 0.0:
            raise ValueError(
                "Cosine distance is not defined for zero-length vectors; the input "
                "contains an all-zero feature row."
            )
        X = _normalize_rows(X)  # spherical kmeans operates on the unit sphere
    init_centers = jnp.asarray(kmeans_init(X, w, k, init, init_steps, seed))
    from .. import config as _config

    # Fused pallas Lloyd routing (SRML_TPU_PALLAS_KMEANS). Steady-state TPU
    # measurement at the bench shape (12M x 128, k=20, v5e) puts the XLA path
    # at 18.7 ms/iter (~92% of the two-X-reads HBM roofline) vs 26.3/37.5
    # ms/iter for the WEIGHTED fused kernel at 1-pass/6-pass precision — at
    # small k both fused matmuls pad k to the 128-lane MXU width and the
    # per-block argmin/one-hot VPU work dominates, so streaming X once does
    # not pay. Values:
    #   "auto" (default) self-resolves the documented small-k loss region:
    #          the fused kernel engages ONLY on TPU at k >= 128 (the lane
    #          padding vanishes and XLA's (n, k) intermediates approach the
    #          size of X); masked form when the weights are the unit
    #          prefix-mask, weighted otherwise. Off-TPU / small k: XLA.
    #   "1"    weighted kernel (any w), unconditional
    #   "mask" weight-stream-free kernel — requires unit_weight (the pad_rows
    #          prefix-mask contract); the (blk,1)-operand elimination measured
    #          3x on the Gram kernel (ops/pallas_xtwx.py); falls back to "1"
    #          when sample weights are present
    #   "0"/"" XLA always
    # `kmeans.lloyd_path{path=}` counts which path actually ran.
    from ..autotune.defaults import LLOYD_FUSED_MIN_K as _FUSED_MIN_K

    _pallas_env = __import__("os").environ.get("SRML_TPU_PALLAS_KMEANS", "auto")
    if _pallas_env == "auto":
        # upper bound on the auto gate: the kernel module's own VMEM
        # predicate (lloyd_fits_vmem — C+sums residents plus the (blk, k)
        # one-hot working set at the precision's split count) decides
        # placeability; an unplaceable (k, d) stays on XLA rather than
        # handing Mosaic a compile it cannot place. Forced "1"/"mask" stay
        # unconditional (explicit opt-in, as before).
        from ._precision import parity_precision
        from .pallas_kmeans import _N_SPLIT, lloyd_fits_vmem

        _n_split = (
            1 if bool(_config.get("fast_math"))
            else _N_SPLIT[parity_precision()]
        )
        # the k-threshold of the auto gate is a tuning-table knob
        # (`lloyd.fused_min_k`, docs/design.md §6i): a platform where the
        # fused win boundary sits elsewhere ships a table entry instead of a
        # code change; the default stays the measured v5e boundary. Off-TPU
        # the gate is closed anyway, so the table is never consulted there.
        _min_k = _FUSED_MIN_K
        if jax.default_backend() == "tpu":
            from .. import autotune as _autotune

            _tuned_min_k = _autotune.lookup(
                "lloyd.fused_min_k", d=int(X.shape[1])
            )
            if _tuned_min_k is not None:
                _min_k = int(_tuned_min_k)
        use_fused = (
            not cosine
            and jax.default_backend() == "tpu"
            and k >= _min_k
            and lloyd_fits_vmem(k, int(X.shape[1]), _n_split)
        )
        _pallas_env = "mask" if unit_weight else "1"
    else:
        use_fused = not cosine and _pallas_env in ("1", "mask")
    from .. import observability as _obs

    if use_fused:
        from ..parallel.partitioner import mesh_of
        from ._precision import parity_precision
        from .pallas_kmeans import lloyd_fit_pallas

        mesh = mesh_of(X)
        prec = (
            jax.lax.Precision.DEFAULT
            if bool(_config.get("fast_math"))
            else parity_precision()
        )
        unit_mask = _pallas_env == "mask" and unit_weight
        _obs.counter_inc(
            "kmeans.lloyd_path", 1,
            path="pallas_masked" if unit_mask else "pallas_weighted",
        )
        centers, inertia, n_iter = lloyd_fit_pallas(
            X, w, init_centers, float(tol), int(max_iter), mesh=mesh,
            interpret=(jax.default_backend() != "tpu"),
            precision=prec,
            unit_mask=unit_mask,
        )
    else:
        _obs.counter_inc("kmeans.lloyd_path", 1, path="xla")
        centers, inertia, n_iter = lloyd_fit(
            X, w, init_centers, float(tol), int(max_iter), cosine=cosine,
            fast_math=bool(_config.get("fast_math")),
        )
    return {
        "cluster_centers": np.asarray(centers),
        "inertia": float(inertia),
        "n_iter": int(n_iter),
    }
