#
# Zero-copy ingest plane (docs/design.md §6k).
#
# Every streamed fit used to stage each batch through
# `np.ascontiguousarray(X[s:e], dtype=dt)` — a host copy (and often a host
# dtype conversion) per batch even when the slice was already contiguous with
# the right layout. This module is the single staging point that replaces
# those calls (a tools/analysis fence bans new ones elsewhere in ops/):
#
#   * `stage_block` hands a CONTIGUOUS, device-castable slice straight to the
#     device-put path as a VIEW — no host copy, no host conversion; the
#     consuming accumulator kernels cast to the compute dtype as their first
#     in-program op (ops/streaming.py::_apply_chain / .astype), so layout and
#     dtype conversion ride the device, not the host.
#   * Exotic inputs (non-contiguous strides, dtypes whose device cast is not
#     bit-equal to the host cast) fall back to a COUNTED copy through a
#     reusable staging-buffer pool.
#
# The returned view is never written by this library, but on backends whose
# `device_put` ALIASES host memory (CPU jax shares the numpy buffer with the
# device array) a staging buffer must not be reused either — a later batch
# would overwrite the HBM-cache-resident tensor of an earlier one. The pool
# therefore only reuses buffers where device_put copies (TPU/GPU); on CPU it
# allocates per block, which is exactly what the pre-§6k path did.
#
# Telemetry (docs/metrics.md): `ingest.bytes_zero_copy` / `ingest.bytes_copied`
# / `ingest.copies_avoided` / `ingest.host_convert_s` / `ingest.rows_staged`,
# plus the run report's `ingest` section with the §6f before/after
# bytes-per-row cost analysis.
#

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .. import config as _config
from ..observability import counter_inc as obs_counter_inc

__all__ = [
    "StagingPool",
    "process_local_span",
    "report_section",
    "resolve_staging_pool_rows",
    "stage_block",
    "stage_local_block",
]

_device_put_copies_cache: Optional[bool] = None


def _device_put_copies() -> bool:
    """Whether this backend's device_put COPIES host memory (TPU/GPU) rather
    than aliasing it (CPU). Gates staging-buffer reuse — see module header."""
    global _device_put_copies_cache
    if _device_put_copies_cache is None:
        try:
            import jax

            _device_put_copies_cache = jax.default_backend() != "cpu"
        except Exception:  # conservative: unknown backend -> no reuse
            _device_put_copies_cache = False
    return _device_put_copies_cache


def resolve_staging_pool_rows(n: Optional[int] = None,
                              d: Optional[int] = None) -> int:
    """`ingest.staging_pool_rows` resolution (host-side only, so cached traces
    never bake a stale choice): a non-zero config pin wins, then the tuning
    table (per (n, d) shape bucket), then the defaults-module geometry."""
    from .. import autotune as _autotune
    from ..autotune.defaults import INGEST_STAGING_POOL_ROWS

    pinned = int(_config.get("ingest.staging_pool_rows") or 0)
    if pinned > 0:
        return pinned
    tuned = _autotune.lookup("ingest.staging_pool_rows", n=n, d=d)
    if tuned:
        return int(tuned)
    return int(INGEST_STAGING_POOL_ROWS)


class StagingPool:
    """Reusable host staging buffers for the counted copy fallback: per
    (slot, dtype, trailing-shape) key, a ring of TWO buffers sized
    `resolve_staging_pool_rows()` rows (growing to the largest block seen),
    alternated per call — the double-buffer discipline of
    ops/ann_streaming._pipelined_run, so with prefetch depth 1 the buffer a
    block is DMA-ing from is never the one the next block stages into. Reuse
    is disabled entirely where device_put aliases host memory (CPU) — there
    every `buffer()` call allocates fresh, preserving the pre-pool semantics
    HBM batch caching depends on."""

    _RING = 2

    def __init__(self, pool_rows: Optional[int] = None) -> None:
        self._pool_rows = pool_rows
        self._bufs: Dict[Tuple, list] = {}
        self._turn: Dict[Tuple, int] = {}

    def buffer(self, shape: Tuple[int, ...], dtype: Any,
               slot: Any = None) -> np.ndarray:
        rows = int(shape[0])
        tail = tuple(int(x) for x in shape[1:])
        if not _device_put_copies():
            return np.empty((rows,) + tail, dtype)
        if self._pool_rows is None:
            self._pool_rows = resolve_staging_pool_rows()
        key = (slot, np.dtype(dtype), tail)
        ring = self._bufs.setdefault(key, [None] * self._RING)
        turn = self._turn.get(key, 0)
        self._turn[key] = (turn + 1) % self._RING
        buf = ring[turn]
        if buf is None or buf.shape[0] < rows:
            buf = np.empty((max(rows, self._pool_rows),) + tail, dtype)
            ring[turn] = buf
        return buf[:rows]


def _device_castable(src: np.dtype, dst: np.dtype) -> bool:
    """Dtypes the accumulator kernels may cast IN-PROGRAM with results
    bit-identical to the host `astype` they replace: the identity cast, exact
    widenings, and small ints (<= 32 bit — both numpy and XLA convert with
    IEEE round-to-nearest-even, and int64 would be silently narrowed by dtype
    canonicalization before the kernel ever saw it)."""
    src, dst = np.dtype(src), np.dtype(dst)
    if src == dst:
        return True
    if src == np.bool_:
        return True
    if src.kind in ("i", "u") and src.itemsize <= 4:
        return True
    if src.kind == "f" and dst.kind == "f" and src.itemsize < dst.itemsize:
        return True  # exact widening (f16->f32, f32->f64)
    return False


def stage_block(arr: np.ndarray, s: int, e: int, dtype: Any,
                pool: Optional[StagingPool] = None, *, slot: Any = None,
                force_copy: bool = False) -> np.ndarray:
    """Stage rows [s, e) of a host array for device upload.

    Fast path: the slice is contiguous and `_device_castable` to the compute
    dtype -> return it as a zero-copy VIEW (the consumer casts on device).
    Fallback (counted): copy/convert into a staging-pool buffer. Callers that
    must OWN the block (host-side mutation, e.g. cosine normalization) pass
    `force_copy=True`."""
    blk = np.asarray(arr[s:e])
    dt = np.dtype(dtype)
    if blk.ndim >= 2:
        obs_counter_inc("ingest.rows_staged", blk.shape[0])
    if (
        not force_copy
        and bool(_config.get("ingest.zero_copy"))
        and blk.flags.c_contiguous
        and _device_castable(blk.dtype, dt)
    ):
        obs_counter_inc("ingest.copies_avoided", 1)
        obs_counter_inc("ingest.bytes_zero_copy", blk.nbytes)
        return blk
    t0 = time.perf_counter()
    if pool is not None:
        out = pool.buffer(blk.shape, dt, slot)
        np.copyto(out, blk, casting="unsafe")
    else:
        out = np.ascontiguousarray(blk, dtype=dt)
        if out is blk:
            # ascontiguousarray no-ops on a conforming block, but this branch
            # promises caller-owned memory (force_copy mutators, kill switch)
            out = blk.copy()
    obs_counter_inc("ingest.bytes_copied", out.nbytes)
    obs_counter_inc("ingest.host_convert_s", time.perf_counter() - t0)
    return out


def process_local_span(s: int, e: int, partitioner: Any = None
                       ) -> Tuple[int, int]:
    """The sub-range of global rows [s, e) owned by THIS process under the
    active Partitioner's contiguous rank layout (docs/design.md §10): rank r
    of P stages rows [s + r*ceil(rows/P), ...) — so in a multi-host fit no
    host ever materializes a global batch; each process feeds only its slice
    to `stage_block` and `Partitioner.shard_inputs` assembles the global
    array from the per-process pieces. Single-process this is [s, e)."""
    from ..parallel.partitioner import active_partitioner

    part = partitioner if partitioner is not None else active_partitioner()
    rows = max(0, int(e) - int(s))
    p = max(1, int(part.process_count))
    r = int(part.process_index)
    per = -(-rows // p)
    ls = min(rows, r * per)
    le = min(rows, ls + per)
    return int(s) + ls, int(s) + le


def stage_local_block(arr: np.ndarray, s: int, e: int, dtype: Any,
                      pool: Optional[StagingPool] = None, *, slot: Any = None,
                      force_copy: bool = False,
                      partitioner: Any = None) -> np.ndarray:
    """`stage_block` restricted to this process's slice of global rows
    [s, e) — the per-process local-batch ingest step of the multi-host path
    (the zero-copy/counted-copy accounting applies unchanged to the slice)."""
    ls, le = process_local_span(s, e, partitioner)
    return stage_block(arr, ls, le, dtype, pool, slot=slot,
                       force_copy=force_copy)


def count_conversion(nbytes: int, seconds: float) -> None:
    """Count a host conversion copy made OUTSIDE stage_block (the Arrow/pandas
    extraction fallbacks in core/dataset.py) into the same ingest ledger."""
    obs_counter_inc("ingest.bytes_copied", int(nbytes))
    obs_counter_inc("ingest.host_convert_s", float(seconds))


def report_section(registry: Any) -> Optional[Dict[str, Any]]:
    """The run report's `ingest` section (observability/runs.py): this run's
    zero-copy vs copied byte split and the §6f cost analysis — bytes-per-row
    BEFORE is what the pre-§6k path would have staged through host copies
    (every byte), AFTER is what actually copied."""
    try:
        zc = float(registry.counter("ingest.bytes_zero_copy").value())
        cp = float(registry.counter("ingest.bytes_copied").value())
        avoided = int(registry.counter("ingest.copies_avoided").value())
        secs = float(registry.counter("ingest.host_convert_s").value())
        rows = int(registry.counter("ingest.rows_staged").value())
    except Exception:  # report assembly is best-effort
        return None
    if rows <= 0 and zc == 0.0 and cp == 0.0:
        return None
    total = zc + cp
    return {
        "bytes_zero_copy": zc,
        "bytes_copied": cp,
        "copies_avoided": avoided,
        "host_convert_s": secs,
        "rows_staged": rows,
        "bytes_per_row_before": (total / rows) if rows else 0.0,
        "bytes_per_row_after": (cp / rows) if rows else 0.0,
    }
