#
# DBSCAN kernels — the TPU-native replacement for cuml.cluster.dbscan_mg.DBSCANMG
# (reference clustering.py:1018-1092: the whole dataset is broadcast to every worker
# (P3), cuML MG partitions the adjacency computation internally, rank 0 emits labels).
#
# TPU formulation:
#   * core-point detection: blocked pairwise-distance scan over row-sharded data
#     (an (block, n) matmul per block on the MXU), counting eps-neighbors,
#   * cluster formation = connected components of the core-core eps-graph, computed by
#     iterative min-label propagation with pointer jumping (O(log n) rounds, each one
#     blocked distance pass + a gather) — the XLA-friendly union-find,
#   * border points take the label of their minimum-label core neighbor; noise = -1,
#   * labels are finally compacted to 0..n_clusters-1 in first-appearance order
#     (cuML/sklearn convention).
#

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from .knn import _block_sq_dists
from ..observability.device import compiled_kernel


@compiled_kernel("dbscan.core_mask", static_argnames=("block",))
def _core_mask_xla(
    X: jax.Array, valid: jax.Array, eps2: float, min_samples: int, block: int = 512
) -> jax.Array:
    """Bool mask of core points (eps-neighbor count incl. self >= min_samples).
    The item-norm term is hoisted out of the per-block scan (computed once,
    not once per lax.map iteration — the selection-plane norm hoist)."""
    n = X.shape[0]
    pad = (-n) % block
    Xp = jnp.pad(X, ((0, pad), (0, 0)))
    x2 = jnp.sum(X * X, axis=1)

    def count_block(qb):
        d2 = _block_sq_dists(qb, X, x2)
        return jnp.sum((d2 <= eps2) & valid[None, :], axis=1)

    counts = jax.lax.map(count_block, Xp.reshape(-1, block, X.shape[1]))
    return (counts.reshape(-1)[:n] >= min_samples) & valid


def _core_mask(
    X: jax.Array, valid: jax.Array, eps2: float, min_samples: int, block: int = 512
) -> jax.Array:
    """Core-point detection, host wrapper (the PR-5 resolution contract):
    routes to the fused pallas distance+count scan (ops/pallas_select.py —
    the (block, n) distance tile never leaves VMEM, counts bit-identical)
    when `knn.selection` is `pallas_fused`, or under `auto` on TPU once n
    clears knn.pallas_min_items; XLA blocked scan otherwise."""
    from .pallas_select import fused_count_below, use_fused_count

    if use_fused_count(X.shape[0]):
        counts = fused_count_below(X, X, valid, eps2)
        return (counts >= min_samples) & valid
    return _core_mask_xla(X, valid, eps2, min_samples, block)


@compiled_kernel("dbscan.min_core_neighbor_labels",
                 static_argnames=("block",))
def _min_core_neighbor_labels(
    X: jax.Array, labels: jax.Array, core: jax.Array, eps2: float, block: int = 512
) -> jax.Array:
    """For every row: min label among its CORE eps-neighbors (int32 max if none)."""
    n = X.shape[0]
    pad = (-n) % block
    Xp = jnp.pad(X, ((0, pad), (0, 0)))
    big = jnp.iinfo(jnp.int32).max
    x2 = jnp.sum(X * X, axis=1)  # hoisted out of the per-block scan

    def min_label_block(qb):
        d2 = _block_sq_dists(qb, X, x2)
        neigh = (d2 <= eps2) & core[None, :]
        return jnp.min(jnp.where(neigh, labels[None, :], big), axis=1)

    mins = jax.lax.map(min_label_block, Xp.reshape(-1, block, X.shape[1]))
    return mins.reshape(-1)[:n]


@compiled_kernel("dbscan.hook_and_jump")
def _hook_and_jump(
    labels: jax.Array, mins: jax.Array, core: jax.Array
) -> jax.Array:
    """Hook: core points take the min neighbor label; then two pointer-jumping steps
    compress label chains (labels index rows)."""
    new_labels = jnp.where(core, jnp.minimum(labels, mins), labels)
    new_labels = new_labels[new_labels]
    new_labels = new_labels[new_labels]
    return new_labels


@compiled_kernel("dbscan.propagate_labels", static_argnames=("max_rounds",))
def _propagate_labels(
    X: jax.Array, core: jax.Array, eps2: float, max_rounds: int
) -> jax.Array:
    """Min-label propagation with pointer jumping as ONE on-device lax.while_loop.

    The previous host-driven loop dispatched each round separately and synced
    labels to host every 4 rounds for the convergence check — up to 64 relay
    round trips per fit on a remote-attached TPU. On-device the convergence
    check (any label changed) runs every round for free and the whole
    propagation is a single dispatch."""
    n = X.shape[0]
    labels0 = jnp.arange(n, dtype=jnp.int32)

    def cond(state):
        _, r, changed = state
        return jnp.logical_and(r < max_rounds, changed)

    def body(state):
        labels, r, _ = state
        mins = _min_core_neighbor_labels(X, labels, core, eps2)
        new = _hook_and_jump(labels, mins, core)
        return new, r + 1, jnp.any(new != labels)

    labels, _, _ = jax.lax.while_loop(cond, body, (labels0, 0, jnp.bool_(True)))
    return labels


def dbscan_fit_predict(
    X: jax.Array,
    valid: jax.Array,
    eps: float,
    min_samples: int,
    max_rounds: int = 64,
    metric: str = "euclidean",
) -> np.ndarray:
    """Full DBSCAN labeling; returns int labels (noise = -1) for all rows
    (padding rows get -1).

    metric='cosine' reduces exactly to the euclidean scan on row-normalized data:
    for unit vectors ||a-b||^2 = 2(1 - cos(a,b)), so cosine distance <= eps is the
    squared-euclidean threshold 2*eps (the same reduction cuML's cosine DBSCAN
    applies; reference exposes it via the metric param, clustering.py)."""
    n = X.shape[0]
    if metric == "cosine":
        norms = jnp.linalg.norm(X, axis=1, keepdims=True)
        min_norm = float(jnp.min(jnp.where(valid[:, None], norms, jnp.inf)))
        if min_norm <= 0.0:
            raise ValueError(
                "Cosine distance is not defined for zero-length vectors; the input "
                "contains an all-zero feature row."
            )
        X = X / jnp.maximum(norms, 1e-30)
        eps2 = 2.0 * float(eps)
    else:
        eps2 = float(eps) * float(eps)
    core = _core_mask(X, valid, eps2, int(min_samples))
    labels = _propagate_labels(X, core, eps2, max_rounds)

    labels_h = np.asarray(labels)
    core_h = np.asarray(core)
    valid_h = np.asarray(valid)

    # border points: min-label core neighbor (one more pass)
    border_min = np.asarray(
        _min_core_neighbor_labels(X, jnp.asarray(labels_h), jnp.asarray(core_h), eps2)
    )
    out = np.full((n,), -1, dtype=np.int64)
    out[core_h] = labels_h[core_h]
    border = (~core_h) & valid_h & (border_min < np.iinfo(np.int32).max)
    out[border] = border_min[border]

    return _compact_labels(out)


def _compact_labels(out: np.ndarray) -> np.ndarray:
    """Compact labels to 0..k-1 in first-appearance order (sklearn/cuML
    convention), vectorized: order cluster representatives by their first row of
    appearance. Shared by the in-core and out-of-core (pairwise_streaming) paths."""
    n = out.shape[0]
    clustered = out >= 0
    if clustered.any():
        uniq, first_idx = np.unique(out[clustered], return_index=True)
        order = np.argsort(np.nonzero(clustered)[0][first_idx])
        rank = np.empty(len(uniq), dtype=np.int64)
        rank[order] = np.arange(len(uniq))
        final = np.full((n,), -1, dtype=np.int64)
        final[clustered] = rank[np.searchsorted(uniq, out[clustered])]
        return final
    return out
