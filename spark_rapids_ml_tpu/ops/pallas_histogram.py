#
# Pallas TPU kernel: segment histogram via one-hot matmuls.
#
# The forest builder's hot op is the (node, feature, bin, stat) histogram
# (ops/trees.py _histogram). XLA lowers jax.ops.segment_sum to sort/scatter — the
# weakest op class on TPU (no hardware scatter). The TPU-native formulation is an
# MXU one-hot contraction: for each feature and each tile of segment ids,
#     hist_tile = onehot(seg_ids_block)ᵀ @ values_block
# with the one-hot built on the fly in VMEM (never materialized in HBM) and the
# output tile accumulated across row blocks by grid revisiting.
#
# Grid: (features, segment-tiles, row-blocks) — row-blocks innermost so each output
# tile is revisited consecutively and zeroed on the first visit. Block shapes follow
# Mosaic tiling rules: every minor dimension is either a multiple of the lane width
# or the full array dimension (seg ids travel transposed (d, n) with a full-d block;
# the kernel selects its feature row with program_id).
#
# The segment tile adapts to the level width (min(2048, n_segments rounded up to
# 128)) so shallow tree levels don't pay for a 2048-wide one-hot.
#
# Dispatch is an explicit `use_pallas` static argument threaded from forest_fit —
# NOT read from the environment inside traced code (jit caches would make a
# trace-time env read sticky). Multi-device note: pallas_call has no GSPMD
# partitioning rule, so the pallas path is only selected for single-device runs;
# sharded multichip fits keep the segment_sum path whose replicated output makes XLA
# psum partial histograms (shard_map-wrapped pallas is the round-2 upgrade).
#

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

BLOCK_ROWS = 512
MAX_SEG_TILE = 2048


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _hist_kernel(seg_ref, val_ref, out_ref, *, seg_tile: int):
    """seg_ref: (d, BLOCK_ROWS) int32 (all features for this row block);
    val_ref: (BLOCK_ROWS, s); out_ref: (1, seg_tile, s), revisited across row
    blocks."""
    b = pl.program_id(2)

    @pl.when(b == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    j = pl.program_id(0)
    c = pl.program_id(1)
    seg = seg_ref[j, :]  # (BLOCK_ROWS,)
    local = seg - c * seg_tile
    cols = jax.lax.broadcasted_iota(jnp.int32, (BLOCK_ROWS, seg_tile), 1)
    onehot = (cols == local[:, None]).astype(val_ref.dtype)  # (BLOCK_ROWS, seg_tile)
    partial = jax.lax.dot_general(
        onehot,
        val_ref[...],
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (seg_tile, s)
    out_ref[...] += partial[None, :, :]


@functools.partial(jax.jit, static_argnames=("n_segments", "interpret"))
def segment_histogram_pallas(
    seg_ids: jax.Array,  # (n, d) int32: per-feature segment id in [0, n_segments)
    values: jax.Array,  # (n, s) float32
    n_segments: int,
    interpret: bool = False,
) -> jax.Array:
    """Returns (d, n_segments, s)."""
    n, d = seg_ids.shape
    s = values.shape[1]

    pad_rows = (-n) % BLOCK_ROWS
    if pad_rows:
        # padded rows carry zero values, so whatever segment they point at gains 0
        seg_ids = jnp.pad(seg_ids, ((0, pad_rows), (0, 0)), constant_values=0)
        values = jnp.pad(values, ((0, pad_rows), (0, 0)))
    n_padded = seg_ids.shape[0]
    seg_t = seg_ids.T  # (d, n): minor dim = rows, blocked at BLOCK_ROWS (128-aligned)

    seg_tile = min(MAX_SEG_TILE, _round_up(n_segments, 128))
    c_tiles = _round_up(n_segments, seg_tile) // seg_tile

    out = pl.pallas_call(
        functools.partial(_hist_kernel, seg_tile=seg_tile),
        grid=(d, c_tiles, n_padded // BLOCK_ROWS),
        in_specs=[
            pl.BlockSpec((d, BLOCK_ROWS), lambda j, c, b: (0, b)),
            pl.BlockSpec((BLOCK_ROWS, s), lambda j, c, b: (b, 0)),
        ],
        out_specs=pl.BlockSpec((1, seg_tile, s), lambda j, c, b: (j, c, 0)),
        out_shape=jax.ShapeDtypeStruct((d, c_tiles * seg_tile, s), jnp.float32),
        interpret=interpret,
    )(seg_t, values)
    return out[:, :n_segments, :]


def default_use_pallas() -> bool:
    """Pallas histogram is the TPU path for any device count: single-device it is a
    plain pallas_call; on a mesh it runs per-shard under shard_map with a psum merge
    (segment_histogram below). SRML_TPU_PALLAS_HISTOGRAM=1/0 forces it on/off."""
    import os

    forced = os.environ.get("SRML_TPU_PALLAS_HISTOGRAM", "")
    if forced == "1":
        return True
    if forced == "0":
        return False
    return jax.default_backend() == "tpu"


def segment_histogram(
    seg_ids: jax.Array,
    values: jax.Array,
    n_segments: int,
    use_pallas: bool = False,
    mesh=None,
) -> jax.Array:
    """Returns (d, n_segments, s). `use_pallas` must be decided OUTSIDE traced code
    (see default_use_pallas). With a multi-device `mesh`, the pallas kernel runs on
    each device's row shard under shard_map and the partial histograms psum over the
    mesh — the same merge point where the segment_sum path's replicated output makes
    XLA psum (so multi-chip RF keeps the MXU kernel; VERDICT r1 weak #6)."""
    if use_pallas:
        interpret = jax.default_backend() != "tpu"
        if mesh is not None and mesh.devices.size > 1:
            from jax import shard_map
            from jax.sharding import PartitionSpec as P

            from ..parallel.mesh import DATA_AXIS

            @functools.partial(
                shard_map,
                mesh=mesh,
                in_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None)),
                out_specs=P(),
                check_vma=False,
            )
            def _local_hist(seg_local, val_local):
                h = segment_histogram_pallas(
                    seg_local, val_local, n_segments, interpret=interpret
                )
                return jax.lax.psum(h, DATA_AXIS)

            return _local_hist(seg_ids, values)
        return segment_histogram_pallas(seg_ids, values, n_segments, interpret=interpret)

    def per_feature(seg_j):
        return jax.ops.segment_sum(values, seg_j, num_segments=n_segments)

    return jax.vmap(per_feature, in_axes=1)(seg_ids)
