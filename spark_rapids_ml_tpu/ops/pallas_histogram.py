#
# Pallas TPU kernel: segment histogram via one-hot matmuls.
#
# The forest builder's hot op is the (node, feature, bin, stat) histogram
# (ops/trees.py _histogram). XLA lowers jax.ops.segment_sum to sort/scatter — the
# weakest op class on TPU (no hardware scatter). The TPU-native formulation is an
# MXU one-hot contraction: for each feature and each tile of segment ids,
#     hist_tile = onehot(seg_ids_block)ᵀ @ values_block
# with the one-hot built on the fly in VMEM (never materialized in HBM) and the
# output tile accumulated across row blocks by grid revisiting.
#
# Grid: (features, segment-tiles, row-blocks) — row-blocks innermost so each output
# tile is revisited consecutively and zeroed on the first visit. Block shapes follow
# Mosaic tiling rules: every minor dimension is either a multiple of the lane width
# or the full array dimension (seg ids travel transposed (d, n) with a full-d block;
# the kernel selects its feature row with program_id).
#
# The segment tile adapts to the level width (min(2048, n_segments rounded up to
# 128)) so shallow tree levels don't pay for a 2048-wide one-hot.
#
# Dispatch is an explicit `use_pallas` static argument threaded from forest_fit —
# NOT read from the environment inside traced code (jit caches would make a
# trace-time env read sticky). Multi-device note: pallas_call has no GSPMD
# partitioning rule, so the pallas path is only selected for single-device runs;
# sharded multichip fits keep the segment_sum path whose replicated output makes XLA
# psum partial histograms (shard_map-wrapped pallas is the round-2 upgrade).
#

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# tile defaults live in the knob-registry defaults module (docs/design.md
# §6i; the analyzer's fence/hardcoded-tunable rule bans new literals in ops/)
from ..autotune.defaults import (  # re-exported tile defaults
    PALLAS_HISTOGRAM_BLOCK_ROWS as BLOCK_ROWS,
    PALLAS_HISTOGRAM_MAX_SEG_TILE as MAX_SEG_TILE,
)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _hist_kernel(seg_ref, val_ref, out_ref, *, seg_tile: int):
    """seg_ref: (d, BLOCK_ROWS) int32 (all features for this row block);
    val_ref: (BLOCK_ROWS, s); out_ref: (1, seg_tile, s), revisited across row
    blocks."""
    b = pl.program_id(2)

    @pl.when(b == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    j = pl.program_id(0)
    c = pl.program_id(1)
    seg = seg_ref[j, :]  # (BLOCK_ROWS,)
    local = seg - c * seg_tile
    cols = jax.lax.broadcasted_iota(jnp.int32, (BLOCK_ROWS, seg_tile), 1)
    onehot = (cols == local[:, None]).astype(val_ref.dtype)  # (BLOCK_ROWS, seg_tile)
    partial = jax.lax.dot_general(
        onehot,
        val_ref[...],
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (seg_tile, s)
    out_ref[...] += partial[None, :, :]


@functools.partial(jax.jit, static_argnames=("n_segments", "interpret"))
def segment_histogram_pallas(
    seg_ids: jax.Array,  # (n, d) int32: per-feature segment id in [0, n_segments)
    values: jax.Array,  # (n, s) float32
    n_segments: int,
    interpret: bool = False,
) -> jax.Array:
    """Returns (d, n_segments, s)."""
    n, d = seg_ids.shape
    s = values.shape[1]

    pad_rows = (-n) % BLOCK_ROWS
    if pad_rows:
        # padded rows carry zero values, so whatever segment they point at gains 0
        seg_ids = jnp.pad(seg_ids, ((0, pad_rows), (0, 0)), constant_values=0)
        values = jnp.pad(values, ((0, pad_rows), (0, 0)))
    n_padded = seg_ids.shape[0]
    seg_t = seg_ids.T  # (d, n): minor dim = rows, blocked at BLOCK_ROWS (128-aligned)

    seg_tile = min(MAX_SEG_TILE, _round_up(n_segments, 128))
    c_tiles = _round_up(n_segments, seg_tile) // seg_tile

    out = pl.pallas_call(
        functools.partial(_hist_kernel, seg_tile=seg_tile),
        grid=(d, c_tiles, n_padded // BLOCK_ROWS),
        in_specs=[
            pl.BlockSpec((d, BLOCK_ROWS), lambda j, c, b: (0, b)),
            pl.BlockSpec((BLOCK_ROWS, s), lambda j, c, b: (b, 0)),
        ],
        out_specs=pl.BlockSpec((1, seg_tile, s), lambda j, c, b: (j, c, 0)),
        out_shape=jax.ShapeDtypeStruct((d, c_tiles * seg_tile, s), jnp.float32),
        interpret=interpret,
    )(seg_t, values)
    return out[:, :n_segments, :]


def _shard_psum(mesh, in_specs, local_fn):
    """shard_map wrapper shared by both histogram entry points: run local_fn on
    each device's row shard, psum the partial histograms over the mesh."""
    from ..utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=P(),
        check_vma=False,
    )
    def _wrapped(*args):
        return jax.lax.psum(local_fn(*args), DATA_AXIS)

    return _wrapped


def _nb_hist_kernel(
    n_rows,
    d_tile,
    w_tile,
    nbins,
    s,
    x_ref,  # (d_tile, B) int32 bin ids, this feature tile
    node_ref,  # (B, 1) int32 node ids
    val_ref,  # (B, s)
    out_ref,  # (d_tile, w_tile, nbins * s) accumulated across row blocks
):
    """Factored node x bin histogram block: one MXU contraction per feature.

    The v1 kernel one-hots the flattened (node*nbins+bin) segment id, whose cost
    scales with width*nbins per row — at depth 8 that is ~0.5e15 compares for a
    4M x 64 input (TPU-measured 6 s/tree). Here the one-hot factorizes:
        out[j, w, b*s+si] = sum_r [node==w] * [X[r,j]==b] * val[r,si]
    with the bin membership and the stat values fused into ONE (B, nbins*s)
    right-hand side (tile val nbins times along lanes, mask by bin equality), so
    each feature contributes a single (w_tile, B) @ (B, nbins*s) MXU dot."""
    b = pl.program_id(2)

    @pl.when(b == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    c = pl.program_id(1)
    B = val_ref.shape[0]

    rows = b * B + jax.lax.broadcasted_iota(jnp.int32, (B, 1), 0)
    valid = rows < n_rows  # ragged tail: no host-side pad copy (NaN-safe select)
    val = jnp.where(valid, val_ref[...], 0.0)  # (B, s)
    nodes = jnp.where(valid, node_ref[...], -1)  # (B, 1); -1 matches no node

    local = nodes - c * w_tile  # (B, 1)
    wcols = jax.lax.broadcasted_iota(jnp.int32, (B, w_tile), 1)
    onehot_n = (wcols == local).astype(val.dtype)  # (B, w_tile)

    cols = jax.lax.broadcasted_iota(jnp.int32, (B, nbins * s), 1)
    bin_of = cols // s  # static pattern: [0,0,0,1,1,1,...] for s=3
    val_tiled = jnp.tile(val, (1, nbins))  # (B, nbins*s), si = cols % s

    for j in range(d_tile):
        bins_j = x_ref[j, :][:, None]  # (B, 1)
        rhs = jnp.where(bin_of == bins_j, val_tiled, 0.0)  # (B, nbins*s)
        out_ref[j, ...] += jax.lax.dot_general(
            onehot_n,
            rhs,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (w_tile, nbins*s)


@functools.partial(
    jax.jit, static_argnames=("width", "nbins", "interpret", "blk")
)
def node_bin_histogram_pallas(
    Xb: jax.Array,  # (n, d) int32 bin ids in [0, nbins)
    node_id: jax.Array,  # (n,) int32 in [0, width)
    values: jax.Array,  # (n, s) f32, zero rows contribute nothing
    width: int,
    nbins: int,
    interpret: bool = False,
    blk: int = 512,
) -> jax.Array:
    """Returns (width, d, nbins, s) — the forest builder's level histogram.

    blk=512 is the VMEM-safe default: Mosaic allocates the d_tile unrolled
    per-feature (blk, lane) rhs buffers WITHOUT reuse, so scoped-VMEM usage is
    ~d_tile*blk*512B — blk=2048 at d_tile=32 was observed to blow the 16 MiB
    limit (38 MiB stack)."""
    n, d = Xb.shape
    s = values.shape[1]

    # tiles: two VMEM constraints bound d_tile. (a) the output block
    # (d_tile, w_tile, lane) stays <=4 MiB; (b) Mosaic materializes the d_tile
    # unrolled per-feature (blk, lane) rhs buffers WITHOUT reuse, so their stack
    # must stay <=6 MiB — (a) alone explodes at shallow levels (w_tile=1 gives
    # budget 8192 -> d_tile=d -> 25 MiB of rhs at d=128, a hardware-only OOM
    # interpret-mode tests can never catch).
    w_tile = min(width, 256)
    c_tiles = _round_up(width, w_tile) // w_tile
    lane = nbins * s
    lane_pad = _round_up(lane, 128)
    out_budget = 4 * 1024 * 1024 // (w_tile * lane_pad * 4)
    rhs_budget = 6 * 1024 * 1024 // (blk * lane_pad * 4)
    d_tile = max(1, min(d, out_budget, rhs_budget))
    d_tiles = _round_up(d, d_tile) // d_tile
    d_pad = d_tiles * d_tile - d
    Xt = Xb.T  # (d, n)
    if d_pad:
        # padded features histogram into real bins but are sliced off below
        Xt = jnp.pad(Xt, ((0, d_pad), (0, 0)))

    out = pl.pallas_call(
        functools.partial(_nb_hist_kernel, n, d_tile, w_tile, nbins, s),
        grid=(d_tiles, c_tiles, (n + blk - 1) // blk),
        in_specs=[
            pl.BlockSpec((d_tile, blk), lambda j, c, b: (j, b)),
            pl.BlockSpec((blk, 1), lambda j, c, b: (b, 0)),
            pl.BlockSpec((blk, s), lambda j, c, b: (b, 0)),
        ],
        out_specs=pl.BlockSpec(
            (d_tile, w_tile, nbins * s), lambda j, c, b: (j, c, 0)
        ),
        out_shape=jax.ShapeDtypeStruct(
            (d_tiles * d_tile, c_tiles * w_tile, nbins * s), jnp.float32
        ),
        interpret=interpret,
    )(Xt, node_id[:, None], values)
    out = out[:d, :width, :].reshape(d, width, nbins, s)
    return out.transpose(1, 0, 2, 3)  # (width, d, nbins, s)


def node_bin_histogram(
    Xb: jax.Array,
    node_id: jax.Array,
    values: jax.Array,
    width: int,
    nbins: int,
    use_pallas: bool = False,
    mesh=None,
) -> jax.Array:
    """(width, d, nbins, s) level histogram; pallas factored kernel on TPU, with
    the same shard_map+psum wrapping as segment_histogram for a multi-device mesh."""
    if use_pallas:
        interpret = jax.default_backend() != "tpu"

        def _local_hist(x_local, node_local, val_local):
            return node_bin_histogram_pallas(
                x_local, node_local, val_local, width, nbins, interpret=interpret
            )

        if mesh is not None and mesh.devices.size > 1:
            from ..parallel.partitioner import partitioner_for

            part = partitioner_for(mesh)
            return _shard_psum(
                mesh,
                (part.data_spec(2), part.data_spec(1), part.data_spec(2)),
                _local_hist,
            )(Xb, node_id, values)
        return _local_hist(Xb, node_id, values)

    seg_ids = node_id[:, None] * nbins + Xb  # (n, d)
    hist = segment_histogram(seg_ids, values, width * nbins, use_pallas=False)
    d = Xb.shape[1]
    return hist.reshape(d, width, nbins, values.shape[1]).transpose(1, 0, 2, 3)


def default_use_pallas() -> bool:
    """Pallas histogram is the TPU path for any device count: single-device it is a
    plain pallas_call; on a mesh it runs per-shard under shard_map with a psum merge
    (segment_histogram below). SRML_TPU_PALLAS_HISTOGRAM=1/0 forces it on/off."""
    import os

    forced = os.environ.get("SRML_TPU_PALLAS_HISTOGRAM", "")
    if forced == "1":
        return True
    if forced == "0":
        return False
    return jax.default_backend() == "tpu"


def segment_histogram(
    seg_ids: jax.Array,
    values: jax.Array,
    n_segments: int,
    use_pallas: bool = False,
    mesh=None,
) -> jax.Array:
    """Returns (d, n_segments, s). `use_pallas` must be decided OUTSIDE traced code
    (see default_use_pallas). With a multi-device `mesh`, the pallas kernel runs on
    each device's row shard under shard_map and the partial histograms psum over the
    mesh — the same merge point where the segment_sum path's replicated output makes
    XLA psum (so multi-chip RF keeps the MXU kernel; VERDICT r1 weak #6)."""
    if use_pallas:
        interpret = jax.default_backend() != "tpu"

        def _local_hist(seg_local, val_local):
            return segment_histogram_pallas(
                seg_local, val_local, n_segments, interpret=interpret
            )

        if mesh is not None and mesh.devices.size > 1:
            from ..parallel.partitioner import partitioner_for

            part = partitioner_for(mesh)
            return _shard_psum(
                mesh, (part.data_spec(2), part.data_spec(2)), _local_hist
            )(seg_ids, values)
        return _local_hist(seg_ids, values)

    def all_features(s, v):
        return jax.vmap(
            lambda seg_j: jax.ops.segment_sum(v, seg_j, num_segments=n_segments),
            in_axes=1,
        )(s)

    # the vmapped scatter's update tensor holds n*d*s elements; past ~2^31 the
    # XLA CPU scatter thunk overflows its 32-bit element indexing and SEGFAULTS
    # (observed twice, deterministically, at 2e7 x 64 x 2). Chunk the rows so
    # each scatter stays far below that — zero-padded tail rows hit segment 0
    # with zero values, contributing nothing.
    n, d = seg_ids.shape
    s_dim = values.shape[1]
    chunk = max(1, (1 << 28) // max(d * s_dim, 1))
    if n > chunk:
        pad = (-n) % chunk
        seg_p = jnp.pad(seg_ids, ((0, pad), (0, 0)))
        val_p = jnp.pad(values, ((0, pad), (0, 0)))
        segs = seg_p.reshape(-1, chunk, d)
        vals = val_p.reshape(-1, chunk, s_dim)

        def chunk_step(carry, sv):
            sc, vc = sv
            return carry + all_features(sc, vc), None

        init = jnp.zeros((d, n_segments, s_dim), values.dtype)
        out, _ = jax.lax.scan(chunk_step, init, (segs, vals))
        return out
    return all_features(seg_ids, values)
