#
# Histogram-based decision-tree / random-forest builder — the TPU-native replacement
# for cuml.RandomForest{Classifier,Regressor} + treelite (reference tree.py:383-457:
# each Spark worker trains its share of trees with cuML's CUDA histogram builder, the
# serialized forests are allGathered and concatenated by treelite).
#
# TPU formulation (the reference's data-dependent CUDA tree kernels cannot be
# translated; this is the standard way to make trees XLA-friendly):
#   * features are quantile-binned once (LightGBM-style, max_bins buckets) — trees
#     then only ever touch uint8/int32 bin ids,
#   * trees grow LEVEL-WISE over a perfect binary heap layout (static shapes: level t
#     has 2^t node slots),
#   * per level, ONE segment-sum pass builds the (node, feature, bin, stat) histogram;
#     with row-sharded inputs XLA reduces the per-shard partial histograms across the
#     mesh — the cross-device "histogram merge" is a psum, not a treelite concat,
#   * split selection is a cumulative-sum + argmax over the histogram (all dense math),
#   * child statistics are carried from the winning split, so each level costs exactly
#     one data pass.
# Prediction walks the heap with gathers, vmapped over trees.
#
# Impurities: gini / entropy (classification, stats = per-class weighted counts) and
# variance (regression, stats = [w, wy, wyy]), with Spark's weighted information-gain
# semantics (minInstancesPerNode, minInfoGain).
#

from __future__ import annotations

import functools
import math
import time
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from ..observability.device import compiled_kernel


# ---------------------------------------------------------------------------
# Binning
# ---------------------------------------------------------------------------


def quantile_bin_edges(
    X: np.ndarray, max_bins: int, sample_limit: int = 200_000, seed: int = 0
) -> np.ndarray:
    """Per-feature quantile thresholds, (d, max_bins-1). Bin b holds x <= edges[b]
    (last bin open). Computed host-side on a row sample, like every histogram GBM."""
    n = X.shape[0]
    if n > sample_limit:
        idx = np.random.default_rng(seed).choice(n, sample_limit, replace=False)
        Xs = X[idx]
    else:
        Xs = X
    qs = np.linspace(0, 1, max_bins + 1)[1:-1]
    return np.quantile(Xs, qs, axis=0).T.astype(np.float32)  # (d, max_bins-1)


def bin_features(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Digitize to int32 bins (n, d): bin = #edges < x, in [0, max_bins-1].
    Dispatches to the native OpenMP kernel when built (spark_rapids_ml_tpu/native.py),
    numpy searchsorted otherwise."""
    from ..native import bin_features as _native_bin

    return _native_bin(X, edges)


# ---------------------------------------------------------------------------
# Impurity algebra on stat vectors
# ---------------------------------------------------------------------------


def _stat_weight(stats: jax.Array, impurity: str) -> jax.Array:
    if impurity == "variance":
        return stats[..., 0]
    return jnp.sum(stats, axis=-1)


def _impurity_times_w(stats: jax.Array, impurity: str) -> jax.Array:
    """w * impurity(stats) — the additive form used for gain computation."""
    w = _stat_weight(stats, impurity)
    safe_w = jnp.maximum(w, 1e-12)
    if impurity == "variance":
        wy, wyy = stats[..., 1], stats[..., 2]
        return wyy - wy * wy / safe_w
    p_sq_sum = jnp.sum(stats * stats, axis=-1) / safe_w
    if impurity == "gini":
        return w - p_sq_sum
    # entropy
    p = stats / safe_w[..., None]
    ent = -jnp.sum(jnp.where(p > 0, p * jnp.log2(jnp.maximum(p, 1e-12)), 0.0), axis=-1)
    return w * ent


def _leaf_value(stats: jax.Array, impurity: str) -> jax.Array:
    """Leaf payload: class distribution (classification) or [mean] (regression)."""
    if impurity == "variance":
        w = jnp.maximum(stats[..., 0], 1e-12)
        return (stats[..., 1] / w)[..., None]
    w = jnp.maximum(jnp.sum(stats, axis=-1, keepdims=True), 1e-12)
    return stats / w


# ---------------------------------------------------------------------------
# Level-wise builder
# ---------------------------------------------------------------------------


def _histogram(
    Xb: jax.Array,
    values: jax.Array,
    node_id: jax.Array,
    n_nodes: int,
    nbins: int,
    use_pallas: bool = False,
    mesh=None,
) -> jax.Array:
    """(n_nodes, d, nbins, s) histogram. On TPU this runs the FACTORED pallas
    node x bin one-hot-matmul kernel (ops/pallas_histogram.py
    node_bin_histogram_pallas — one MXU contraction per feature per row block,
    cost independent of the flattened segment count): single-device as a plain
    pallas_call, multi-device per-shard under shard_map with a psum merge. The
    segment_sum fallback's replicated output makes XLA psum partial histograms
    the same way — but note that XLA's scatter lowering has been observed to
    crash the TPU compiler outright at >=1M rows, so on TPU the pallas path is
    the production path, not an optimization."""
    from .pallas_histogram import node_bin_histogram

    return node_bin_histogram(
        Xb, node_id, values, n_nodes, nbins, use_pallas, mesh=mesh
    )


# Opt-in per-level wall-clock collection: a test/bench sets
# `ops.trees._LEVEL_TIMING = []` before fitting and reads (level, seconds)
# tuples back. While set, _grow_forest routes through _build_tree_impl, which
# runs each level as ONE AOT-compiled program (_level_step_jit.lower().compile()
# outside the timed window) with a sync after it — real device wall-clock,
# compile excluded, and no full-eager slowdown. The jitted build_tree entry
# point never times (hooks inside a jit body would record trace time).
_LEVEL_TIMING: "List | None" = None


def _level_step(
    state,
    Xb: jax.Array,
    values: jax.Array,
    edges: jax.Array,
    t: int,
    nbins: int,
    impurity: str,
    k_features: int,
    min_instances: int,
    min_info_gain: float,
    use_pallas: bool,
    mesh,
):
    """One tree level (width = 2**t): histogram, split selection, heap writes,
    row routing, child-stat carry. Pure state -> state so it can run either
    INLINED inside the jitted build_tree trace (the fast path — identical
    program to the old unrolled loop) or as its own jitted program per level
    (timing mode: one compiled dispatch + sync per level measures real device
    wall-clock without making the whole tree eager — a full-eager 2e7-row level
    was measured 3-10x slower on the 1-core CPU tier and unusable)."""
    (feat_arr, thr_arr, leaf_arr, val_arr, gain_arr, wgt_arr, node_id, T, key) = state
    n, d = Xb.shape
    s = values.shape[1]
    width = 2**t
    hist = _histogram(Xb, values, node_id, width, nbins, use_pallas, mesh)  # (w, d, b, s)
    cum = jnp.cumsum(hist, axis=2)
    L = cum[:, :, :-1, :]  # split at bin 0..b-2
    R = T[:, None, None, :] - L

    wT = _stat_weight(T, impurity)  # (w,)
    wL = _stat_weight(L, impurity)  # (w, d, b-1)
    wR = _stat_weight(R, impurity)
    gain = (
        _impurity_times_w(T, impurity)[:, None, None]
        - _impurity_times_w(L, impurity)
        - _impurity_times_w(R, impurity)
    ) / jnp.maximum(wT, 1e-12)[:, None, None]

    valid = (wL >= min_instances) & (wR >= min_instances)
    if k_features < d:
        key, sub = jax.random.split(key)
        scores = jax.random.uniform(sub, (width, d))
        from .selection import top_k_max

        kth = top_k_max(scores, k_features)[0][:, -1]
        valid = valid & (scores >= kth[:, None])[:, :, None]
    gain = jnp.where(valid, gain, -jnp.inf)

    flat = gain.reshape(width, -1)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
    best_feat = (best // (nbins - 1)).astype(jnp.int32)
    best_bin = (best % (nbins - 1)).astype(jnp.int32)

    is_leaf_t = ~(best_gain > min_info_gain)  # also catches all -inf / NaN
    slots = width + jnp.arange(width)
    feat_arr = feat_arr.at[slots].set(jnp.where(is_leaf_t, -1, best_feat))
    thr_arr = thr_arr.at[slots].set(edges[best_feat, best_bin])
    leaf_arr = leaf_arr.at[slots].set(is_leaf_t)
    val_arr = val_arr.at[slots].set(_leaf_value(T, impurity))
    gain_arr = gain_arr.at[slots].set(
        jnp.where(is_leaf_t, 0.0, jnp.maximum(best_gain, 0.0))
    )
    wgt_arr = wgt_arr.at[slots].set(wT)

    # route rows; leaf rows stay in the left child slot (unreachable at predict).
    # The naive per-row lane gather (take_along_axis by best_feat[node]) is the
    # slowest op class on TPU — measured 164 ms/level at 4M x 64, w=256. Two
    # gather-free formulations (both bit-identical to the gather on hardware):
    #  - matmul route: G=onehot(node) bf16, picked = rowsum((G @ onehot(feat)) * X)
    #    (23.8 ms measured) — exact while the per-row one-hot sums and the bin
    #    ids stay <= 256 (bf16 integer range) and G (n x width) fits HBM;
    #  - row-gather route: A[node] for A=(width,d) one-hot + mask-sum (77 ms) —
    #    no (n, width) intermediate, used for deep/wide levels.
    leaf_f = is_leaf_t.astype(jnp.float32)
    # n * width bound: G is a materialized (n, width) bf16 array — cap it at
    # ~2.5 GiB so flagship-scale fits (12M rows) fall back to the row-gather
    # route at deep levels instead of OOMing HBM
    if width <= 256 and nbins <= 256 and n * width * 2 <= 2_500_000_000:
        G = jax.nn.one_hot(node_id, width, dtype=jnp.bfloat16)
        A = jax.nn.one_hot(best_feat, d, dtype=jnp.bfloat16)
        picked = jnp.sum(
            jnp.matmul(G, A).astype(jnp.float32) * Xb.astype(jnp.float32), axis=1
        )
        thr_r = jnp.matmul(G, best_bin.astype(jnp.bfloat16)[:, None])[:, 0]
        leaf_r = jnp.matmul(G, leaf_f.astype(jnp.bfloat16)[:, None])[:, 0] > 0.5
        go_right = (picked > thr_r.astype(jnp.float32)) & ~leaf_r
    else:
        A = jax.nn.one_hot(best_feat, d, dtype=jnp.float32)
        picked = jnp.sum(A[node_id] * Xb.astype(jnp.float32), axis=1)
        go_right = (picked > best_bin[node_id].astype(jnp.float32)) & ~(  # noqa: fence/host-staging-copy
            is_leaf_t[node_id]
        )
    node_id = node_id * 2 + go_right.astype(jnp.int32)

    # children stats carried from the winning split
    Lbest = cum[jnp.arange(width), best_feat, best_bin, :]  # (w, s)
    Rbest = T - Lbest
    T = jnp.stack([Lbest, Rbest], axis=1).reshape(2 * width, s)
    return (feat_arr, thr_arr, leaf_arr, val_arr, gain_arr, wgt_arr, node_id, T, key)


_level_step_jit = functools.partial(
    jax.jit,
    static_argnames=(
        "t",
        "nbins",
        "impurity",
        "k_features",
        "min_instances",
        "min_info_gain",
        "use_pallas",
        "mesh",
    ),
)(_level_step)


def _build_tree_impl(
    Xb: jax.Array,  # (n, d) int32 bins, rows may be sharded
    values: jax.Array,  # (n, s) per-row stats already weighted (0 rows contribute 0)
    edges: jax.Array,  # (d, nbins-1) real thresholds
    key: jax.Array,  # per-tree PRNG key (feature subsets)
    max_depth: int,
    nbins: int,
    impurity: str,
    k_features: int,
    min_instances: int,
    min_info_gain: float,
    use_pallas: bool = False,
    mesh=None,
    level_timing=None,
) -> Dict[str, jax.Array]:
    """Grow one tree; returns heap arrays of size 2^(max_depth+1):
    feature (int32, -1 for leaf), threshold (f32), is_leaf (bool), value (slots, v)."""
    n, d = Xb.shape
    s = values.shape[1]
    n_slots = 2 ** (max_depth + 1)
    v_dim = 1 if impurity == "variance" else s

    state = (
        jnp.full((n_slots,), -1, jnp.int32),  # feature (-1 = leaf)
        jnp.zeros((n_slots,), jnp.float32),  # threshold
        jnp.zeros((n_slots,), bool),  # is_leaf
        jnp.zeros((n_slots, v_dim), jnp.float32),  # value
        # per-node split gain and weighted row count — the inputs to impurity-
        # based featureImportances (Spark TreeEnsembleModel semantics)
        jnp.zeros((n_slots,), jnp.float32),  # gain
        jnp.zeros((n_slots,), jnp.float32),  # node weight
        jnp.zeros((n,), jnp.int32),  # node_id
        jnp.sum(values, axis=0)[None, :],  # (1, s) root stats
        key,
    )

    step_kw = dict(
        nbins=nbins, impurity=impurity, k_features=k_features,
        min_instances=min_instances, min_info_gain=min_info_gain,
        use_pallas=use_pallas, mesh=mesh,
    )
    for t in range(max_depth):
        if level_timing is not None:
            # AOT-compile OUTSIDE the timed window, then time the executable:
            # otherwise each level's first run per process times trace+compile
            # (seconds of XLA work) instead of device wall-clock
            exe = _level_step_jit.lower(
                state, Xb, values, edges, t=t, **step_kw
            ).compile()
            t0 = time.perf_counter()
            state = exe(state, Xb, values, edges)
            state[7].block_until_ready()  # T — the sync exists only in timing mode
            level_timing.append((t, time.perf_counter() - t0))
        else:
            state = _level_step(state, Xb, values, edges, t, **step_kw)
    (feat_arr, thr_arr, leaf_arr, val_arr, gain_arr, wgt_arr, node_id, T, key) = state

    # deepest level: all leaves
    width = 2**max_depth
    slots = width + jnp.arange(width)
    leaf_arr = leaf_arr.at[slots].set(True)
    val_arr = val_arr.at[slots].set(_leaf_value(T, impurity))
    wgt_arr = wgt_arr.at[slots].set(_stat_weight(T, impurity))
    return {
        "feature": feat_arr,
        "threshold": thr_arr,
        "is_leaf": leaf_arr,
        "value": val_arr,
        "gain": gain_arr,
        "node_weight": wgt_arr,
    }


@compiled_kernel("trees.predict_forest", static_argnames=("max_depth",))
def predict_forest(
    X: jax.Array,  # (n, d) raw features
    feature: jax.Array,  # (n_trees, n_slots)
    threshold: jax.Array,
    is_leaf: jax.Array,
    value: jax.Array,  # (n_trees, n_slots, v)
    max_depth: int,
) -> jax.Array:
    """Average of per-tree leaf payloads, (n, v)."""

    d = X.shape[1]
    n_slots = feature.shape[1]
    # the mask-sum route builds a (n_slots, d) one-hot table per tree — fine for
    # trained forests (depth <= 12ish) but a vmapped OOM for deep imported
    # forests (depth-20 heap = 2M slots); those keep the lane gather
    use_mask_sum = n_slots * d <= (1 << 22)

    def one_tree(feat_t, thr_t, leaf_t, val_t):
        # feature one-hot table rows instead of a per-row lane gather on X
        # (same rewrite as build_tree routing: the lane gather is 2x slower
        # than the table-row + mask-sum form on TPU). SELECT, don't multiply:
        # 0 * NaN = NaN would let a NaN in any UNTESTED feature poison the
        # picked value; with where() only the tested feature's value flows
        # through, so NaN-in-tested-feature still compares False and routes
        # LEFT — the documented treelite default_left=True contract.
        if use_mask_sum:
            A = jax.nn.one_hot(jnp.maximum(feat_t, 0), d, dtype=X.dtype) > 0

        def walk(carry, _):
            p = carry
            stop = leaf_t[p]
            if use_mask_sum:
                picked = jnp.sum(jnp.where(A[p], X, 0.0), axis=1)
            else:
                f = jnp.maximum(feat_t[p], 0)
                picked = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
            go_right = picked > thr_t[p]
            p_next = p * 2 + go_right.astype(jnp.int32)
            return jnp.where(stop, p, p_next), None

        p0 = jnp.ones((X.shape[0],), jnp.int32)
        p, _ = jax.lax.scan(walk, p0, None, length=max_depth)
        return val_t[p]  # (n, v)

    vals = jax.vmap(one_tree)(feature, threshold, is_leaf, value)  # (trees, n, v)
    return jnp.mean(vals, axis=0)


# ---------------------------------------------------------------------------
# Forest driver
# ---------------------------------------------------------------------------


def resolve_feature_subset(strategy: str, d: int, is_classification: bool) -> int:
    """Spark featureSubsetStrategy resolution (auto/all/sqrt/log2/onethird/number)."""
    s = str(strategy)
    if s == "auto":
        return max(1, int(math.sqrt(d))) if is_classification else max(1, d // 3)
    if s == "all":
        return d
    if s == "sqrt":
        return max(1, int(math.sqrt(d)))
    if s == "log2":
        return max(1, int(math.log2(d)))
    if s == "onethird":
        return max(1, d // 3)
    try:
        val = float(s)
        if val.is_integer() and val >= 1:
            return min(d, int(val))
        if 0 < val <= 1:
            return max(1, int(val * d))
    except ValueError:
        pass
    raise ValueError(f"Unsupported featureSubsetStrategy: {strategy}")


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_depth",
        "nbins",
        "impurity",
        "k_features",
        "min_instances",
        "min_info_gain",
        "use_pallas",
        "mesh",  # jax.sharding.Mesh is hashable; static so shard_map can close over it
    ),
)
def build_tree(
    Xb: jax.Array,
    values: jax.Array,
    edges: jax.Array,
    key: jax.Array,
    max_depth: int,
    nbins: int,
    impurity: str,
    k_features: int,
    min_instances: int,
    min_info_gain: float,
    use_pallas: bool = False,
    mesh=None,
) -> Dict[str, jax.Array]:
    """Jitted tree growth (see _build_tree_impl). The jitted path NEVER times —
    the level-timing hooks would record trace time, not device time — so
    _grow_forest calls _build_tree_impl directly when _LEVEL_TIMING is set."""
    return _build_tree_impl(
        Xb, values, edges, key, max_depth, nbins, impurity, k_features,
        min_instances, min_info_gain, use_pallas, mesh, level_timing=None,
    )


def forest_fit(
    X_host: np.ndarray,
    raw_stats_host: np.ndarray,  # (n, s) unweighted per-row stats (already include sample weight)
    n_trees: int,
    max_depth: int,
    max_bins: int,
    impurity: str,
    feature_subset: int,
    min_instances: int,
    min_info_gain: float,
    subsampling_rate: float,
    bootstrap: bool,
    seed: int,
    shard_fn=None,
    mesh=None,
) -> Dict[str, np.ndarray]:
    """Bin once, then grow the forest tree-by-tree (one XLA compile; trees differ
    only in their bootstrap weights and PRNG key). `shard_fn` optionally places the
    binned arrays on the mesh so histograms psum across devices."""
    if n_trees < 1:
        raise ValueError(f"numTrees must be >= 1, got {n_trees}")
    if max_depth < 0:
        raise ValueError(f"maxDepth must be >= 0, got {max_depth}")
    n, d = X_host.shape
    edges = quantile_bin_edges(X_host, max_bins, seed=seed)
    Xb_host = bin_features(X_host, edges)

    Xb = jnp.asarray(Xb_host) if shard_fn is None else shard_fn(Xb_host)
    raw_stats = (
        jnp.asarray(raw_stats_host) if shard_fn is None else shard_fn(raw_stats_host)
    )
    return _grow_forest(
        Xb, raw_stats, edges, n, n_trees, max_depth, max_bins, impurity,
        feature_subset, min_instances, min_info_gain, subsampling_rate,
        bootstrap, seed, shard_fn, mesh,
    )


def _grow_forest(
    Xb: jax.Array,
    raw_stats: jax.Array,
    edges: np.ndarray,
    n: int,
    n_trees: int,
    max_depth: int,
    max_bins: int,
    impurity: str,
    feature_subset: int,
    min_instances: int,
    min_info_gain: float,
    subsampling_rate: float,
    bootstrap: bool,
    seed: int,
    shard_fn=None,
    mesh=None,
) -> Dict[str, np.ndarray]:
    """The per-tree growth loop over ALREADY-BINNED device arrays — shared by the
    in-core forest_fit and the out-of-core streaming_forest_fit so a parity test
    between them exercises only the ingest path. `n` is the REAL row count (the
    binned arrays may carry padded rows whose stats are zero)."""
    from .pallas_histogram import default_use_pallas

    use_pallas = default_use_pallas()
    edges_j = jnp.asarray(edges)
    rng = np.random.default_rng(seed & 0x7FFFFFFF)
    trees: List[Dict[str, np.ndarray]] = []
    for i in range(n_trees):
        if bootstrap:
            w_tree = rng.poisson(subsampling_rate, size=n).astype(np.float32)
        elif subsampling_rate < 1.0:
            w_tree = (rng.random(n) < subsampling_rate).astype(np.float32)
        else:
            w_tree = np.ones((n,), np.float32)
        w_j = jnp.asarray(w_tree) if shard_fn is None else shard_fn(w_tree)
        if _LEVEL_TIMING is not None:
            build_fn = functools.partial(_build_tree_impl, level_timing=_LEVEL_TIMING)
        else:
            build_fn = build_tree
        tree = build_fn(
            Xb,
            raw_stats * w_j[:, None],
            edges_j,
            jax.random.PRNGKey((seed + 7919 * i) & 0x7FFFFFFF),
            max_depth=max_depth,
            nbins=max_bins,
            impurity=impurity,
            k_features=feature_subset,
            min_instances=min_instances,
            min_info_gain=min_info_gain,
            use_pallas=use_pallas,
            mesh=mesh if (mesh is not None and mesh.devices.size > 1) else None,
        )
        trees.append({k: np.asarray(v) for k, v in tree.items()})

    return {
        "feature": np.stack([t["feature"] for t in trees]),
        "threshold": np.stack([t["threshold"] for t in trees]),
        "is_leaf": np.stack([t["is_leaf"] for t in trees]),
        "value": np.stack([t["value"] for t in trees]),
        "gain": np.stack([t["gain"] for t in trees]),
        "node_weight": np.stack([t["node_weight"] for t in trees]),
        "bin_edges": edges,
    }


def streaming_forest_fit(
    X_host: np.ndarray,
    raw_stats_host: np.ndarray,
    n_trees: int,
    max_depth: int,
    max_bins: int,
    impurity: str,
    feature_subset: int,
    min_instances: int,
    min_info_gain: float,
    subsampling_rate: float,
    bootstrap: bool,
    seed: int,
    batch_rows: int,
    shard_fn=None,
    mesh=None,
) -> Dict[str, np.ndarray]:
    """Out-of-core forest fit: X streams through BINNING in host row blocks, and
    only the binned uint8 matrix (4x smaller than f32; max_bins <= 256) plus the
    per-row stats reside on device for the growth loop — the RandomForest analog
    of the reference's UVM/SAM larger-than-memory fitting (reference
    utils.py:184-241). BASELINE config 4 (50M x 64) is ~12.8 GiB as f32 but
    ~3.1 GiB binned, which fits a 16 GiB chip.

    Residency bound: n x d uint8 + n x s f32 stats + one (n,) f32 weight vector
    per tree placement. Quantile edges come from a strided row subsample (the
    same sample-bounded estimate quantile_bin_edges applies in-core)."""
    if n_trees < 1:
        raise ValueError(f"numTrees must be >= 1, got {n_trees}")
    if max_depth < 0:
        raise ValueError(f"maxDepth must be >= 0, got {max_depth}")
    if max_bins > 256:
        raise ValueError(
            f"streaming forest bins to uint8: maxBins must be <= 256, got {max_bins}"
        )
    n, d = X_host.shape
    # edges from a strided subsample: rows are not assumed shuffled
    step = max(1, n // 200_000)
    edges = quantile_bin_edges(
        np.ascontiguousarray(X_host[::step], dtype=np.float32), max_bins, seed=seed  # noqa: fence/host-staging-copy
    )

    Xb_host = np.empty((n, d), np.uint8)
    for s in range(0, n, batch_rows):
        e = min(s + batch_rows, n)
        Xb_host[s:e] = bin_features(
            np.ascontiguousarray(X_host[s:e], dtype=np.float32), edges  # noqa: fence/host-staging-copy
        ).astype(np.uint8)

    Xb = jnp.asarray(Xb_host) if shard_fn is None else shard_fn(Xb_host)
    raw_stats = (
        jnp.asarray(raw_stats_host.astype(np.float32))
        if shard_fn is None
        else shard_fn(raw_stats_host.astype(np.float32))
    )
    return _grow_forest(
        Xb, raw_stats, edges, n, n_trees, max_depth, max_bins, impurity,
        feature_subset, min_instances, min_info_gain, subsampling_rate,
        bootstrap, seed, shard_fn, mesh,
    )


def forest_to_json(model_attrs: Dict[str, np.ndarray], is_classification: bool) -> List[Dict]:
    """Portable nested-dict dump of the forest — the role of the reference's
    treelite JSON dump for Spark-tree interop (reference tree.py:534-559,
    utils.py:585-809)."""
    feature = model_attrs["feature"]
    threshold = model_attrs["threshold"]
    is_leaf = model_attrs["is_leaf"]
    value = model_attrs["value"]

    def node(tree_idx: int, p: int) -> Dict:
        if is_leaf[tree_idx, p] or feature[tree_idx, p] < 0 or 2 * p >= feature.shape[1]:
            payload = value[tree_idx, p].tolist()
            return (
                {"leaf_value": payload}
                if not is_classification
                else {"leaf_class_probs": payload}
            )
        return {
            "split_feature": int(feature[tree_idx, p]),
            "threshold": float(threshold[tree_idx, p]),
            "default_left": True,
            "left_child": node(tree_idx, 2 * p),
            "right_child": node(tree_idx, 2 * p + 1),
        }

    return [
        {"tree_id": i, "root": node(i, 1)} for i in range(feature.shape[0])
    ]


def forest_from_json(
    trees_json: List[Dict], n_features: int, is_classification: bool
) -> Dict[str, np.ndarray]:
    """Inverse of forest_to_json: rebuild the heap-layout forest arrays from the
    portable nested-dict dump, so forests exported by this framework (or translated
    from treelite/cuML dumps into the same shape) can be imported as models — the
    import half of the reference's treelite interop (reference tree.py:439-449)."""
    leaf_key = "leaf_class_probs" if is_classification else "leaf_value"

    def depth_of(node: Dict) -> int:
        if leaf_key in node or "left_child" not in node:
            return 0
        return 1 + max(depth_of(node["left_child"]), depth_of(node["right_child"]))

    if not trees_json:
        raise ValueError("empty forest JSON")
    roots = [t["root"] for t in trees_json]
    max_depth = max(depth_of(r) for r in roots)
    if max_depth > 20:
        # the heap layout allocates 2^(depth+1) slots per tree: one depth-25
        # branch in an imported (e.g. cuML-trained) forest would inflate every
        # array by 2^26 slots — fail with the number instead of a MemoryError
        raise ValueError(
            f"forest depth {max_depth} exceeds the dense-heap import limit (20); "
            f"re-train/dump with a bounded max_depth to import"
        )
    v_dims = set()

    def leaf_dim(node: Dict) -> None:
        if leaf_key in node:
            v_dims.add(len(node[leaf_key]))
        else:
            leaf_dim(node["left_child"])
            leaf_dim(node["right_child"])

    for r in roots:
        leaf_dim(r)
    if len(v_dims) != 1:
        raise ValueError(f"inconsistent leaf payload dims: {sorted(v_dims)}")
    v_dim = v_dims.pop()

    n_trees = len(roots)
    n_slots = 2 ** (max_depth + 1)
    feature = np.full((n_trees, n_slots), -1, np.int32)
    threshold = np.zeros((n_trees, n_slots), np.float32)
    is_leaf = np.zeros((n_trees, n_slots), bool)
    value = np.zeros((n_trees, n_slots, v_dim), np.float32)

    def fill(tree_idx: int, node: Dict, p: int) -> None:
        if leaf_key in node:
            is_leaf[tree_idx, p] = True
            value[tree_idx, p] = np.asarray(node[leaf_key], np.float32)
            return
        f = int(node["split_feature"])
        if not 0 <= f < n_features:
            raise ValueError(f"split_feature {f} out of range for d={n_features}")
        feature[tree_idx, p] = f
        threshold[tree_idx, p] = float(node["threshold"])
        fill(tree_idx, node["left_child"], 2 * p)
        fill(tree_idx, node["right_child"], 2 * p + 1)

    for i, r in enumerate(roots):
        fill(i, r, 1)
    return {
        "feature": feature,
        "threshold": threshold,
        "is_leaf": is_leaf,
        "value": value,
        "bin_edges": np.zeros((n_features, 1), np.float32),
    }


def _prev_f32_ftz(t: float) -> float:
    """Largest float32 strictly below t UNDER XLA's flush-to-zero semantics.

    nextafter(0.0, -inf) is a denormal, and XLA flushes denormals to +-0.0 — the
    nudge silently vanishes and equality routes the wrong way (caught by driving
    a '<' split at threshold 0.0). Denormal results are therefore snapped to the
    nearest FTZ-representable neighbor: -tiny below zero, 0.0 for positive
    denormals (consistent with denormal INPUTS also flushing to zero)."""
    p = np.nextafter(np.float32(t), np.float32(-np.inf))
    tiny = np.finfo(np.float32).tiny
    if p != 0.0 and abs(p) < tiny:
        p = np.float32(-tiny) if p < 0 else np.float32(0.0)
    return float(p)


def _treelite_tree_to_nested(tree: Dict, is_classification: bool) -> Dict:
    """One treelite-JSON tree (flat `nodes` list keyed by node_id — the schema the
    reference translates at utils.py:700-809) -> this module's nested dict.

    Routing semantics: this framework's predict goes LEFT iff x[f] <= threshold.
    Treelite records a comparison_op per split; for "<" the equality case must go
    right, so the threshold is nudged to the previous float32 (x <= prev(t) iff
    x < t for float32 inputs). "<=" imports unchanged.

    Missing values: predict routes NaN LEFT (NaN > t is false), which matches
    treelite's default_left=True. Nodes dumped with default_left=False would
    misroute NaN features — flagged with a warning on import since this engine
    has no per-node missing-direction bit.
    """
    nodes = {n["node_id"]: n for n in tree["nodes"]}
    leaf_key = "leaf_class_probs" if is_classification else "leaf_value"
    if any(
        n.get("default_left") is False
        for n in tree["nodes"]
        if "left_child" in n
    ):
        import warnings

        warnings.warn(
            "treelite dump contains default_left=False splits; this engine "
            "routes NaN/missing features LEFT, so predictions on rows with "
            "missing values may differ from the source model",
            stacklevel=3,
        )

    def conv(node_id: int) -> Dict:
        n = nodes[node_id]
        if "leaf_value" in n or "leaf_vector" in n:
            v = n.get("leaf_vector", n.get("leaf_value"))
            payload = list(v) if isinstance(v, (list, tuple)) else [float(v)]
            if is_classification and len(payload) < 2:
                raise ValueError(
                    "classification import needs per-class leaf_vector "
                    "probabilities (cuML RF dumps these); scalar leaves are "
                    "margin/regression outputs"
                )
            return {leaf_key: payload}
        op = n.get("comparison_op", "<=")
        thr = float(n["threshold"])
        if op == "<":
            thr = _prev_f32_ftz(thr)
        elif op != "<=":
            raise ValueError(f"unsupported treelite comparison_op {op!r}")
        return {
            "split_feature": int(n["split_feature_id"]),
            "threshold": thr,
            "default_left": bool(n.get("default_left", True)),
            "left_child": conv(n["left_child"]),
            "right_child": conv(n["right_child"]),
        }

    return {"root": conv(int(tree.get("root_id", 0)))}


def forest_from_treelite_json(
    model_json: Dict | List[Dict],
    is_classification: bool,
    n_features: int | None = None,
) -> Dict[str, np.ndarray]:
    """Import a treelite JSON dump (cuML `dump_as_json`, what the reference's
    models carry as `treelite_model` JSON, reference tree.py:534-559) into the
    heap-layout forest arrays. Accepts either the full model dict (with `trees`
    and `num_feature`) or a bare list of tree dicts (then n_features is required)."""
    if isinstance(model_json, dict):
        trees = model_json["trees"]
        if n_features is None:
            n_features = int(model_json.get("num_feature", 0)) or None
    else:
        trees = model_json
    if n_features is None:
        raise ValueError(
            "n_features is required when the dump carries no num_feature"
        )
    nested = [
        {"tree_id": i, **_treelite_tree_to_nested(t, is_classification)}
        for i, t in enumerate(trees)
    ]
    return forest_from_json(nested, int(n_features), is_classification)
