#
# Linear regression fit kernels (OLS / Ridge / ElasticNet) — the TPU-native replacement
# for cuml.linear_model.{linear_regression_mg, ridge_mg} and cuml.solvers.cd_mg
# (reference regression.py:528-606 dispatches among the three by regularization; the
# gradient/Gram allreduce happens inside cuML over NCCL).
#
# TPU formulation: ONE sharded data pass builds the normal-equation sufficient
# statistics (XᵀWX, XᵀWy) — the contraction over the sharded row axis is where XLA
# inserts the psum (the cuML NCCL allreduce's place). Everything after is d×d and
# replicated:
#   * no L1  -> direct solve of (XᵀWX/n + λI) w = XᵀWy/n   (OLS: λ=0; Ridge)
#   * L1 > 0 -> FISTA proximal gradient on the Gram form — all matrix-vector work,
#     MXU/VPU-friendly with a statically-bounded lax.while_loop, where the reference
#     uses cuML's sequential coordinate descent (CD's per-coordinate data dependence is
#     hostile to wide-vector hardware; FISTA optimizes the same objective).
#
# Objective (Spark parity): 1/(2n)·Σ wᵢ(yᵢ - xᵢ·β - b)² + λ(α‖β‖₁ + (1-α)/2·‖β‖²),
# with `standardization=True` applying the penalty to σ-scaled coefficients
# (implemented by solving in X/σ space and unscaling, the reference's approach at
# regression.py:534-544,634-648).
#

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..observability.device import compiled_kernel
from ._precision import pdot
from .linalg import power_iteration_lmax


@compiled_kernel("linear.sufficient_stats")
def linreg_sufficient_stats(
    X: jax.Array, y: jax.Array, w: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """One sharded pass: (XᵀWX, XᵀWy, x̄, ȳ, Σw). The only distributed step."""
    wsum = jnp.sum(w)
    xbar = pdot(w, X) / wsum
    ybar = jnp.sum(w * y) / wsum
    Xw = X * w[:, None]
    A = pdot(Xw.T, X)
    b = pdot(Xw.T, y)
    return A, b, xbar, ybar, wsum


def _center_stats(A, b, xbar, ybar, n, fit_intercept):
    """Convert raw moments to centered (about the weighted mean) moments."""
    if fit_intercept:
        A = A - n * jnp.outer(xbar, xbar)
        b = b - n * xbar * ybar
    return A, b


@compiled_kernel("linear.solve_l2", static_argnames=("fit_intercept",))
def solve_l2(
    A: jax.Array,
    b: jax.Array,
    xbar: jax.Array,
    ybar: jax.Array,
    n: jax.Array,
    scale: jax.Array,
    reg: float,
    fit_intercept: bool,
) -> Tuple[jax.Array, jax.Array]:
    """Closed-form OLS/Ridge in (optionally σ-scaled) space; returns (coef, intercept)
    in the ORIGINAL feature space."""
    Ac, bc = _center_stats(A, b, xbar, ybar, n, fit_intercept)
    # scale to standardized space: As = D⁻¹ Ac D⁻¹, bs = D⁻¹ bc, D = diag(scale)
    As = Ac / jnp.outer(scale, scale)
    bs = bc / scale
    d = As.shape[0]
    lhs = As / n + reg * jnp.eye(d, dtype=As.dtype)
    coef_s = jnp.linalg.solve(lhs, bs / n)
    coef = coef_s / scale
    intercept = jnp.where(fit_intercept, ybar - jnp.dot(xbar, coef), 0.0)
    return coef, intercept


@compiled_kernel("linear.solve_elastic_net",
                 static_argnames=("fit_intercept", "max_iter"))
def solve_elastic_net(
    A: jax.Array,
    b: jax.Array,
    xbar: jax.Array,
    ybar: jax.Array,
    n: jax.Array,
    scale: jax.Array,
    reg: float,
    l1_ratio: float,
    fit_intercept: bool,
    max_iter: int,
    tol: float,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """FISTA on  f(β) = 1/(2n)·βᵀAβ - bᵀβ/n (+ L2)  with prox for λ·α‖β‖₁.

    Returns (coef, intercept, n_iter) in the original feature space."""
    Ac, bc = _center_stats(A, b, xbar, ybar, n, fit_intercept)
    As = (Ac / jnp.outer(scale, scale)) / n
    bs = (bc / scale) / n
    l1 = reg * l1_ratio
    l2 = reg * (1.0 - l1_ratio)

    # Lipschitz constant of ∇f: λ_max(As) + l2, bounded via a few power iterations
    L = power_iteration_lmax(As) + l2 + 1e-12
    step = 1.0 / L

    def soft(x, t):
        return jnp.sign(x) * jnp.maximum(jnp.abs(x) - t, 0.0)

    def cond(state):
        _, _, _, it, delta = state
        return jnp.logical_and(it < max_iter, delta > tol)

    def body(state):
        wk, zk, tk, it, _ = state
        grad = pdot(As, zk) - bs + l2 * zk
        w_next = soft(zk - step * grad, step * l1)
        t_next = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * tk * tk))
        z_next = w_next + ((tk - 1.0) / t_next) * (w_next - wk)
        delta = jnp.max(jnp.abs(w_next - wk)) / (jnp.max(jnp.abs(w_next)) + 1e-12)
        return w_next, z_next, t_next, it + 1, delta

    w0 = jnp.zeros((As.shape[0],), As.dtype)
    state = (w0, w0, jnp.array(1.0, As.dtype), 0, jnp.array(jnp.inf, As.dtype))
    coef_s, _, _, n_iter, _ = jax.lax.while_loop(cond, body, state)
    coef = coef_s / scale
    intercept = jnp.where(fit_intercept, ybar - jnp.dot(xbar, coef), 0.0)
    return coef, intercept, n_iter


def linreg_fit(
    X: jax.Array,
    y: jax.Array,
    w: jax.Array,
    reg: float,
    l1_ratio: float,
    fit_intercept: bool,
    standardize: bool,
    max_iter: int,
    tol: float,
    extra_param_sets: Optional[List[Dict[str, Any]]] = None,
    mesh=None,
    unit_weight: bool = False,
) -> List[Dict[str, Any]]:
    """Full fit: one distributed stats pass, then per-param-map host-replicated solves.

    `extra_param_sets` reuses the SAME sufficient statistics for every param map — the
    single-pass fitMultiple the reference implements by looping cuML fits over the
    concatenated data (regression.py:657-674); here the data pass itself is shared.
    Returns one attribute dict per model.

    Unit-weight fits on TPU take the fused one-X-read pallas stats pass
    (ops/pallas_xtwx.py::normal_eq_prefix_mask — halves the HBM traffic of the
    XLA two-read Gram); the same `use_fused_gram` gate as the PCA covariance."""
    from .pca import use_fused_gram

    if use_fused_gram(X.shape[1], unit_weight, dtype=X.dtype):
        from ._precision import parity_precision
        from .pallas_xtwx import normal_eq_prefix_mask

        interpret = jax.devices()[0].platform != "tpu"
        A, b, xbar, ybar, n, _yty = normal_eq_prefix_mask(
            X, y, w, mesh=mesh, precision=parity_precision(), interpret=interpret
        )
    else:
        A, b, xbar, ybar, n = linreg_sufficient_stats(X, y, w)
    return solve_from_stats(
        A, b, xbar, ybar, n,
        reg=reg, l1_ratio=l1_ratio, fit_intercept=fit_intercept,
        standardize=standardize, max_iter=max_iter, tol=tol,
        extra_param_sets=extra_param_sets,
    )


def solve_from_stats(
    A: jax.Array,
    b: jax.Array,
    xbar: jax.Array,
    ybar: jax.Array,
    n: jax.Array,
    reg: float,
    l1_ratio: float,
    fit_intercept: bool,
    standardize: bool,
    max_iter: int,
    tol: float,
    extra_param_sets: Optional[List[Dict[str, Any]]] = None,
) -> List[Dict[str, Any]]:
    """Solve per param map from sufficient statistics (shared by the in-core and
    streaming out-of-core paths; ops/streaming.py accumulates the same stats). The
    column std for standardization comes from diag(A): var = (ΣwX² - n·x̄²)/(n-1)."""
    if standardize:
        # unbiased column std, Spark's Summarizer convention (reference utils.py:876-982)
        var = (jnp.diagonal(A) - n * xbar * xbar) / jnp.maximum(n - 1.0, 1.0)
        scale = jnp.sqrt(jnp.maximum(var, 0.0))
        scale = jnp.where(scale <= 0.0, 1.0, scale)
    else:
        scale = jnp.ones_like(xbar)

    param_sets = extra_param_sets if extra_param_sets is not None else [
        {"alpha": reg, "l1_ratio": l1_ratio, "fit_intercept": fit_intercept,
         "max_iter": max_iter, "tol": tol}
    ]
    results = []
    for p in param_sets:
        p_reg = float(p.get("alpha", reg))
        p_l1r = float(p.get("l1_ratio", l1_ratio))
        p_fi = bool(p.get("fit_intercept", fit_intercept))
        p_mi = int(p.get("max_iter", max_iter))
        p_tol = float(p.get("tol", tol))
        if p_reg == 0.0 or p_l1r == 0.0:
            coef, intercept = solve_l2(A, b, xbar, ybar, n, scale, p_reg, p_fi)
            n_iter = 1
        else:
            coef, intercept, n_iter = solve_elastic_net(
                A, b, xbar, ybar, n, scale, p_reg, p_l1r, p_fi, p_mi, p_tol
            )
            n_iter = int(n_iter)
        results.append(
            {
                "coefficients": np.asarray(coef),
                "intercept": float(intercept),
                "n_iter": int(n_iter),
            }
        )
    return results


@compiled_kernel("linear.predict")
def linreg_predict(X: jax.Array, coef: jax.Array, intercept: jax.Array) -> jax.Array:
    return pdot(X, coef) + intercept


# ---------------------------------------------------------------------------
# Huber regression (robust loss) — NATIVE on the mesh.
#
# The reference has no device path at all for loss='huber' (cuML lacks it; the
# reference falls back to Spark, regression.py:183-215 maps loss to squared only).
# Here the jointly-convex concomitant-scale formulation (Huber 1981, the same
# objective sklearn's HuberRegressor and Spark's HuberAggregator optimize)
#     L(beta, b, sigma) = sum_i w_i [ sigma + H_eps((y_i - x_i.beta - b)/sigma) sigma ]
#                         + reg * ||beta_s||^2
# is minimized by the shared optax L-BFGS loop (ops/logistic._run_lbfgs): the
# residual matvec over the sharded row axis is where XLA inserts the psum.
# sigma is parameterized as exp(s) for positivity; `standardize` applies the
# penalty to sigma-scaled coefficients like the squared-loss path.
# ---------------------------------------------------------------------------


@compiled_kernel("linear.huber_qn",
                 static_argnames=("fit_intercept", "standardize", "max_iter"))
def _huber_qn(
    X: jax.Array,
    y: jax.Array,
    w: jax.Array,
    epsilon: jax.Array,
    reg: jax.Array,
    fit_intercept: bool,
    standardize: bool,
    max_iter: int,
    tol: jax.Array,
):
    from .linalg import weighted_moments
    from .logistic import _run_lbfgs

    d = X.shape[1]
    wsum = jnp.sum(w)
    if standardize:
        _, var, _ = weighted_moments(X, w)
        scale = jnp.sqrt(jnp.maximum(var, 0.0))
        # zero-variance columns pass through unscaled (solve_from_stats convention)
        scale = jnp.where(scale <= 0.0, 1.0, scale)
    else:
        scale = jnp.ones((d,), X.dtype)

    ybar = jnp.sum(w * y) / wsum
    b0 = jnp.where(fit_intercept, ybar, 0.0)
    resid0 = y - b0
    sigma0 = jnp.sqrt(jnp.sum(w * resid0 * resid0) / wsum) + 1e-6
    params0 = jnp.concatenate(
        [jnp.zeros((d,), X.dtype), jnp.array([b0, jnp.log(sigma0)], X.dtype)]
    )

    def loss(params):
        coef_s, b, s = params[:d], params[d], params[d + 1]
        sigma = jnp.exp(s)
        r = y - pdot(X, coef_s / scale) - jnp.where(fit_intercept, b, 0.0)
        z = r / sigma
        az = jnp.abs(z)
        Hz = jnp.where(az <= epsilon, z * z, 2.0 * epsilon * az - epsilon * epsilon)
        # Spark HuberCostFun convention: mean data term + (lambda/2)||beta_s||^2
        # (same regParam meaning as the squared-loss path's A/n + reg*I)
        return jnp.sum(w * (sigma + Hz * sigma)) / wsum + 0.5 * reg * jnp.sum(
            coef_s * coef_s
        )

    params, n_iter = _run_lbfgs(loss, params0, max_iter, tol)
    coef = params[:d] / scale
    return coef, params[d], jnp.exp(params[d + 1]), n_iter


def huber_fit(
    X: jax.Array,
    y: jax.Array,
    w: jax.Array,
    epsilon: float,
    reg: float,
    fit_intercept: bool,
    standardize: bool,
    max_iter: int,
    tol: float,
    extra_param_sets: Optional[List[Dict[str, Any]]] = None,
) -> List[Dict[str, Any]]:
    """Native huber fits — one result per param set, the solve_from_stats
    convention (extra sets are full backend-param dicts; None => one base fit).
    No sufficient-statistics shortcut exists for the robust loss, but the jitted
    program is compiled once and reused across maps."""
    param_sets = (
        extra_param_sets if extra_param_sets is not None else [{}]
    )
    results = []
    for ps in param_sets:
        coef, b, sigma, n_iter = _huber_qn(
            X, y, w,
            jnp.asarray(float(ps.get("epsilon", epsilon)), X.dtype),
            jnp.asarray(float(ps.get("alpha", reg)), X.dtype),
            fit_intercept=bool(ps.get("fit_intercept", fit_intercept)),
            standardize=bool(ps.get("normalize", standardize)),
            max_iter=int(ps.get("max_iter", max_iter)),
            tol=jnp.asarray(float(ps.get("tol", tol)), X.dtype),
        )
        results.append(
            {
                "coefficients": np.asarray(coef, np.float32),
                "intercept": float(b),
                "n_iter": int(n_iter),
                "scale": float(sigma),
            }
        )
    return results
