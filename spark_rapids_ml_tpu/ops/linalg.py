#
# Shared distributed linear-algebra kernels (L1).
#
# These replace the reference's cuML sufficient-statistics machinery: weighted moments
# and Gram/covariance accumulation with the allreduce that cuML MG runs over NCCL
# (e.g. PCAMG covariance, reference feature.py:228-253; distributed standardization via
# allGather-sum, reference utils.py:876-982). Here the inputs are row-sharded jax arrays
# and XLA inserts the psum over the mesh when the contraction crosses the sharded axis —
# the matmuls land on the MXU, the reduction rides ICI.
#
# All kernels are weight-aware: `w` is the {0,1} padding mask times any sample weight
# (parallel/partition.py), so padded rows contribute nothing.
#

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..observability.device import compiled_kernel
from ._precision import pdot


@compiled_kernel("linalg.weighted_mean")
def weighted_mean(X: jax.Array, w: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Returns (mean, wsum). One pass; psum over the data axis is implicit."""
    wsum = jnp.sum(w)
    mean = pdot(w, X) / wsum
    return mean, wsum


@compiled_kernel("linalg.weighted_moments")
def weighted_moments(X: jax.Array, w: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Returns (mean, var, wsum) with the unbiased (wsum-1) variance denominator,
    matching Spark's Summarizer semantics used by the reference's standardization
    (utils.py:876-982)."""
    wsum = jnp.sum(w)
    mean = pdot(w, X) / wsum
    sq = pdot(w, X * X)
    var = (sq - wsum * mean * mean) / (wsum - 1.0)
    return mean, jnp.maximum(var, 0.0), wsum


@compiled_kernel("scaler.transform")
def scaler_transform(X: jax.Array, shift: jax.Array, scale: jax.Array) -> jax.Array:
    """StandardScalerModel's column standardization. Bit-parity contract with the
    fused pipeline's "scale" chain op (ops/streaming.py::_apply_chain): identical
    expression, identical cast discipline — the staged transform->refit path and
    the fused featurize->fit chain must agree BITWISE (docs/design.md §6k)."""
    return (X.astype(shift.dtype) - shift) / scale


@compiled_kernel("linalg.weighted_covariance")
def weighted_covariance(X: jax.Array, w: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Centered covariance C = Σ w_i (x_i-μ)(x_i-μ)ᵀ / (Σw - 1) via sufficient
    statistics (single data pass: S2 = Xᵀ diag(w) X, then mean correction)."""
    wsum = jnp.sum(w)
    mean = pdot(w, X) / wsum
    S2 = pdot((X * w[:, None]).T, X)
    cov = (S2 - wsum * jnp.outer(mean, mean)) / (wsum - 1.0)
    return cov, mean, wsum


@compiled_kernel("linalg.gram_and_xty")
def gram_and_xty(
    X: jax.Array, y: jax.Array, w: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Normal-equation sufficient statistics: (XᵀWX, XᵀWy, Σw) in one sharded pass —
    the TPU form of the reference's LinearRegressionMG/RidgeMG allreduce."""
    Xw = X * w[:, None]
    return pdot(Xw.T, X), pdot(Xw.T, y), jnp.sum(w)


def power_iteration_lmax(G: jax.Array, n_steps: int = 16) -> jax.Array:
    """Largest eigenvalue of a symmetric PSD matrix via power iteration — used for
    FISTA Lipschitz constants in ops/linear.py and ops/logistic.py."""

    def body(i, v):
        v = pdot(G, v)
        return v / (jnp.linalg.norm(v) + 1e-30)

    d = G.shape[0]
    v = jax.lax.fori_loop(0, n_steps, body, jnp.ones((d,), G.dtype) / jnp.sqrt(d))
    return jnp.dot(v, pdot(G, v))


def standardize_columns(
    X: jax.Array, w: jax.Array, with_mean: bool = True
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Return (X_standardized, mean, scale): the reference's distributed
    standardization workaround (classification.py:1018-1028, utils.py:876-982) as a
    sharded kernel. Columns with zero variance get scale 1 to avoid division blowup.
    Padded rows are standardized too (they are masked at use sites via w)."""
    mean, var, _ = weighted_moments(X, w)
    scale = jnp.sqrt(var)
    scale = jnp.where(scale <= 0.0, 1.0, scale)
    if with_mean:
        Xs = (X - mean) / scale
    else:
        Xs = X / scale
    return Xs, mean, scale
