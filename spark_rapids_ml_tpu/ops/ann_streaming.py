#
# Out-of-core IVF-Flat: the ANN leg of the UVM/SAM replacement tier
# (reference utils.py:184-241 lets cuVS index datasets beyond device memory via
# managed memory; reference ANN role: knn.py:1538-1690).
#
# TPU formulation: the ITEM SET stays host-resident end to end.
#   * build: coarse centers fit in-core on a bounded row subsample, then the
#     full dataset streams through the device in batches only to be ASSIGNED to
#     cells (one (batch, nlist) distance matmul per batch); the dense
#     cell layout is materialized host-side.
#   * search: per query block, only the PROBED cells travel host->device —
#     device residency is (block, nprobe, max_cell, d) + centers, never the
#     dataset. This is the managed-memory access pattern made explicit: the
#     probe list is the page table, the gathered cells are the pages.
#
# In-core ivfflat (ops/knn.py) remains the fast path when cells fit HBM; the
# estimator (models/knn.py) picks this module above the stream threshold.
#

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def streaming_ivfflat_build(
    X: np.ndarray,
    nlist: int,
    max_iter: int,
    seed: int,
    batch_rows: int,
    sample_rows: int = 1 << 18,
) -> Dict[str, np.ndarray]:
    """Build the IVF layout with the dataset host-resident: centers from an
    in-core kmeans on a strided subsample (rows are not assumed shuffled), then
    streamed batch assignment. Returns the same dict shape as ops/knn.py::
    ivfflat_build but with `cells`/`cell_ids` as HOST arrays."""
    from .kmeans import kmeans_fit, kmeans_predict

    n, d = X.shape
    step = max(1, n // min(n, sample_rows))
    Xs = np.ascontiguousarray(X[::step], dtype=np.float32)
    # the coarse kmeans trains on the SUBSAMPLE: k must fit it, not just n
    nlist = min(nlist, len(Xs))
    fitted = kmeans_fit(
        jnp.asarray(Xs), jnp.ones((len(Xs),), jnp.float32), k=nlist,
        max_iter=max_iter, tol=1e-4, init="k-means||", init_steps=2, seed=seed,
        unit_weight=True,
    )
    centers = fitted["cluster_centers"]
    centers_j = jnp.asarray(centers)

    assign = np.empty((n,), np.int32)
    for s in range(0, n, batch_rows):
        e = min(s + batch_rows, n)
        assign[s:e] = np.asarray(
            kmeans_predict(
                jnp.asarray(np.ascontiguousarray(X[s:e], dtype=np.float32)),
                centers_j,
            )
        )

    from .knn import layout_cells

    cells, cell_ids, cell_sizes = layout_cells(
        np.asarray(X, dtype=np.float32), assign, nlist
    )
    return {
        "centers": centers,
        "cells": cells,
        "cell_ids": cell_ids,
        "cell_sizes": cell_sizes,
    }


@functools.partial(jax.jit, static_argnames=("nprobe",))
def _probe_cells(Q: jax.Array, centers: jax.Array, nprobe: int):
    from .knn import _block_sq_dists

    cd2 = _block_sq_dists(Q, centers)
    _, probe = jax.lax.top_k(-cd2, nprobe)
    return probe


@functools.partial(jax.jit, static_argnames=("k",))
def _scan_probed(qb, probed_items, probed_ids, k):
    """(bq, nprobe, max_cell, d) probed cells -> per-query top-k. EXACT f32
    difference-form distances, matching ops/knn.py::ivfflat_search's in-core
    cell scan rank-for-rank (the candidate set per query is small, so the exact
    form costs nothing; the expanded bf16 form was observed to reorder
    near-duplicate candidates vs the in-core path)."""
    bq, nprobe, max_cell, d = probed_items.shape
    flat = probed_items.reshape(bq, nprobe * max_cell, d)
    flat_ids = probed_ids.reshape(bq, nprobe * max_cell)
    d2 = jnp.sum((flat - qb[:, None, :]) ** 2, axis=2)
    d2 = jnp.where(flat_ids >= 0, d2, jnp.inf)
    neg, pos = jax.lax.top_k(-d2, k)
    ids = jnp.take_along_axis(flat_ids, pos, axis=1)
    dists = jnp.sqrt(jnp.maximum(-neg, 0.0))
    return jnp.where(ids >= 0, dists, jnp.inf), ids


def streaming_ivfflat_search(
    Q: np.ndarray,
    index: Dict[str, np.ndarray],
    k: int,
    nprobe: int,
    block: int = 256,
) -> Tuple[np.ndarray, np.ndarray]:
    """Search with host-resident cells: per query block the probe list is
    computed on device, then ONLY the probed cells are gathered host-side and
    device_put — (block, nprobe, max_cell, d) device residency. Returns
    (euclidean distances, item ids) of width k_eff = min(k, nprobe*max_cell),
    id -1 where fewer than k found — the SAME width contract as the in-core
    ivfflat_search, so results are byte-identical across the threshold."""
    centers_j = jnp.asarray(index["centers"])
    cells = index["cells"]
    cell_ids = index["cell_ids"]
    nlist, max_cell, d = cells.shape
    nq = Q.shape[0]
    k_eff = min(k, nprobe * max_cell)

    out_d = np.full((nq, k_eff), np.inf, np.float32)
    out_i = np.full((nq, k_eff), -1, np.int64)
    for s in range(0, nq, block):
        e = min(s + block, nq)
        qb = jnp.asarray(np.ascontiguousarray(Q[s:e], dtype=np.float32))
        probe = np.asarray(_probe_cells(qb, centers_j, nprobe))  # (bq, nprobe)
        # the host gather IS the out-of-core page-in
        probed_items = jnp.asarray(cells[probe])
        probed_ids = jnp.asarray(cell_ids[probe])
        dists, ids = _scan_probed(qb, probed_items, probed_ids, k_eff)
        out_d[s:e] = np.asarray(dists)
        out_i[s:e] = np.asarray(ids)
    return out_d, out_i
