#
# Out-of-core IVF-Flat: the ANN leg of the UVM/SAM replacement tier
# (reference utils.py:184-241 lets cuVS index datasets beyond device memory via
# managed memory; reference ANN role: knn.py:1538-1690).
#
# TPU formulation: the ITEM SET stays host-resident end to end.
#   * build: coarse centers fit in-core on a bounded row subsample, then the
#     full dataset streams through the device in batches only to be ASSIGNED to
#     cells (one (batch, nlist) distance matmul per batch); the dense
#     cell layout is materialized host-side.
#   * search: per query block, only the PROBED cells travel host->device —
#     device residency is (block, nprobe, max_cell, d) + centers, never the
#     dataset. This is the managed-memory access pattern made explicit: the
#     probe list is the page table, the gathered cells are the pages.
#
# In-core ivfflat (ops/knn.py) remains the fast path when cells fit HBM; the
# estimator (models/knn.py) picks this module above the stream threshold.
#

from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import span as obs_span
from ..parallel.partitioner import put_device_local
from ..observability.runs import (
    WorkerScope,
    counter_inc as obs_counter_inc,
    current_run,
    observe as obs_observe,
)
from ..reliability import RetryPolicy, fault_point
from . import selection as _sel
from .selection import mask_invalid, merge_topk, select_topk
from ..observability.device import compiled_kernel
from .streaming import _prefetch

# per-batch rank/phase timeline entries are recorded only for builds/searches
# up to this many batches: the timeline is a forensic surface (which batch
# dragged?), not an accounting one, and a million-batch build must not grow a
# million-row worker list on the run
_TIMELINE_BATCHES_CAP = 64


def _normalize_batch_or_raise(Xb: np.ndarray) -> np.ndarray:
    """Cosine-tier batch normalization — one shared zero-row contract
    (ops/knn.py::normalize_rows_or_raise)."""
    from .knn import normalize_rows_or_raise

    return normalize_rows_or_raise(Xb)


def _strided_sample_indices(n: int, sample_rows: int) -> np.ndarray:
    """Deterministic strided row subsample of EXACTLY min(n, sample_rows)
    rows, evenly spanning [0, n) — rows are not assumed shuffled, so the
    sample must cover the tail too. The old `step = max(1, n // min(n,
    sample_rows))` form kept every stride hit and returned up to ~2x
    sample_rows rows whenever n is just under a multiple of the step; a
    truncated arange would instead clamp the count but silently drop the tail
    distribution. `(i * n) // m` is strictly increasing for n >= m, so the
    indices are unique, exactly m, and reach within n/m rows of the end."""
    m = min(int(n), int(sample_rows))
    if m <= 0:
        return np.arange(0, 0)
    return (np.arange(m, dtype=np.int64) * int(n)) // m


def resolve_build_batch_rows(n: int, d: int) -> int:
    """`ann.build_batch_rows` resolution for the pipelined builds: a non-zero
    config pin wins, then the tuning table (per (n, d) shape bucket), then an
    EXPLICITLY-configured `stream_batch_rows` (a deployment that sized batches
    for its streamed fits keeps that geometry), then the defaults-module build
    geometry (ANN_BUILD_BATCH_ROWS — two 64k-row staging buffers in flight,
    not the 1M-row streamed-fit default)."""
    from .. import autotune as _autotune
    from .. import config as _config
    from ..autotune.defaults import ANN_BUILD_BATCH_ROWS

    pinned = int(_config.get("ann.build_batch_rows") or 0)
    if pinned > 0:
        return pinned
    tuned = _autotune.lookup("ann.build_batch_rows", n=n, d=d)
    if tuned:
        return int(tuned)
    if _config.source("stream_batch_rows") != "default":
        return int(_config.get("stream_batch_rows"))
    return int(ANN_BUILD_BATCH_ROWS)


def _pipelined_run(
    total: int,
    batch_rows: int,
    site: str,
    dispatch: Callable[[int, int, int], object],
    finalize: Callable[[int, int, int, object], None],
    depth: Optional[int] = None,
) -> None:
    """THE pipelined out-of-core batch loop, shared by every streamed ANN
    build/search/refine stage. `dispatch(bi, s, e)` host-stages one batch
    (slice -> normalize -> device_put) and ASYNC-dispatches its device op(s),
    returning the in-flight device values; `finalize(bi, s, e, out)` performs
    the blocking host pull and the idempotent [s, e) host write. Routed
    through ops/streaming.py::_prefetch with `ann.prefetch_depth` extra
    batches in flight, host staging of batch i+1 overlaps device execution of
    batch i (jax dispatch is async; the DMA rides a separate engine on TPU).
    depth 0 degrades to the serial per-batch loop — the bench baseline.

    Retry contract (unchanged from the serial loops): `fault_point(site,
    batch=bi)` fires before each staging attempt, and BOTH halves run under
    the per-batch RetryPolicy. A drain-side failure re-runs `dispatch` for
    just that batch (the in-flight value died with the failed attempt) —
    writes target only [s, e), so a retried batch is bit-identical to a
    fault-free one.

    Telemetry: `ann.stage_s{site=}` / `ann.drain_s{site=}` histograms are the
    overlap evidence (pipelined wall << Σstage + Σdrain), and each batch of a
    small build lands as a rank row (rank = batch ordinal, phase = site) in
    the open run's §6h timeline, so a straggler batch is visible exactly like
    a straggler barrier rank."""
    from .. import config as _config

    if depth is None:
        depth = int(_config.get("ann.prefetch_depth"))
    policy = RetryPolicy.from_config()
    run = current_run()
    n_batches = -(-total // batch_rows) if total > 0 else 0
    timeline = run is not None and 1 < n_batches <= _TIMELINE_BATCHES_CAP
    t_loop0 = time.perf_counter()

    def gen():
        for bi, s in enumerate(range(0, total, batch_rows)):
            e = min(s + batch_rows, total)
            work = {"wall_s": 0.0}  # the batch's OWN stage+drain seconds

            def _stage(bi=bi, s=s, e=e, work=work):
                # timer opens BEFORE the fault point: an injected sleep= (a
                # deterministic straggler) is this batch's stall and must
                # land in ITS stage wall / timeline row
                t0 = time.perf_counter()
                fault_point(site, batch=bi)
                out = dispatch(bi, s, e)
                dt = time.perf_counter() - t0
                work["wall_s"] += dt
                obs_observe("ann.stage_s", dt, site=site)
                return out

            obs_counter_inc("ann.pipeline_batches", 1, site=site)
            yield bi, s, e, _stage, work, policy.run(_stage, site=site)

    stream = gen() if depth <= 0 else _prefetch(gen(), depth=depth)
    for bi, s, e, stage, work, out in stream:
        state = {"out": out, "fresh": True}

        def _drain(s=s, e=e, bi=bi, stage=stage, state=state, work=work):
            if not state["fresh"]:
                # the in-flight value died with the failed attempt: re-stage
                # and re-dispatch this batch (same idempotent write target)
                state["out"] = stage()
            state["fresh"] = False
            t0 = time.perf_counter()
            finalize(bi, s, e, state["out"])
            dt = time.perf_counter() - t0
            work["wall_s"] += dt
            obs_observe("ann.drain_s", dt, site=site)

        policy.run(_drain, site=site)
        if timeline:
            # batch-as-rank timeline row: same-process snapshots merge
            # breakdown-only (no metric double count), and the comm plane's
            # skew/straggler machinery applies to build batches for free.
            # wall_s is the batch's OWN stage+drain time — wall-clock from
            # staging would also count time parked in the prefetch buffer
            # behind a neighbor's drain and smear a straggler across two rows
            ws = WorkerScope(rank=bi, run_id=run.run_id)
            ws.note_phase(site, wall_s=work["wall_s"], rows=e - s)
            run.add_worker_snapshot(ws.snapshot())
    # whole-loop wall: the overlap denominator — Σstage + Σdrain exceeding
    # this is the proof that host staging hid behind device execution
    obs_observe("ann.pipeline_s", time.perf_counter() - t_loop0, site=site)


def streaming_ivfflat_build(
    X: np.ndarray,
    nlist: int,
    max_iter: int,
    seed: int,
    batch_rows: int,
    sample_rows: int = 1 << 18,
    return_assign: bool = False,
    cosine: bool = False,
) -> Dict[str, np.ndarray]:
    """Build the IVF layout with the dataset host-resident: centers from an
    in-core kmeans on a strided subsample (rows are not assumed shuffled), then
    streamed batch assignment. Returns the same dict shape as ops/knn.py::
    ivfflat_build but with `cells`/`cell_ids` as HOST arrays.

    `cosine=True` builds the index on the UNIT SPHERE without materializing a
    normalized copy of the dataset: the subsample, each assignment batch, and
    the cell layout's gather pass normalize on the fly (the in-core path
    instead normalizes the whole device array up front,
    models/knn.py::_normalize_or_raise). Queries must be normalized at search,
    which the model layer already does for cosine."""
    from .kmeans import kmeans_fit, kmeans_predict

    n, d = X.shape
    Xs = np.ascontiguousarray(X[_strided_sample_indices(n, sample_rows)],  # noqa: fence/host-staging-copy
                              dtype=np.float32)
    if cosine:
        Xs = _normalize_batch_or_raise(Xs)
    # the coarse kmeans trains on the SUBSAMPLE: k must fit it, not just n
    nlist = min(nlist, len(Xs))
    fitted = kmeans_fit(
        jnp.asarray(Xs), jnp.ones((len(Xs),), jnp.float32), k=nlist,
        max_iter=max_iter, tol=1e-4, init="k-means||", init_steps=2, seed=seed,
        unit_weight=True,
    )
    centers = fitted["cluster_centers"]
    centers_j = jnp.asarray(centers)

    # pipelined per-batch assignment: each batch writes only assign[s:e]
    # (idempotent), so a transient fault re-runs just that batch under the
    # retry policy — results are unchanged; host staging of batch i+1 overlaps
    # the device's assignment matmul of batch i (_pipelined_run)
    assign = np.empty((n,), np.int32)

    def _dispatch_assign(bi, s, e):
        Xb = np.ascontiguousarray(X[s:e], dtype=np.float32)  # noqa: fence/host-staging-copy
        if cosine:
            Xb = _normalize_batch_or_raise(Xb)
        return kmeans_predict(put_device_local(Xb), centers_j)

    def _finalize_assign(bi, s, e, out):
        assign[s:e] = np.asarray(out)

    _pipelined_run(n, batch_rows, "ann_assign", _dispatch_assign,
                   _finalize_assign)

    from .knn import layout_cells

    # X passes through UNconverted: layout_cells casts inside its row gather,
    # so the streamed path no longer materializes a second full-dense f32
    # copy of the dataset before laying out the cells
    cells, cell_ids, cell_sizes = layout_cells(
        np.asarray(X), assign, nlist,
        normalize=cosine,
    )
    from .knn import center_norms_sq

    out = {
        "centers": centers,
        "center_norms": center_norms_sq(centers),
        "cells": cells,
        "cell_ids": cell_ids,
        "cell_sizes": cell_sizes,
    }
    if return_assign:
        out["assign"] = assign
    return out


def streaming_ivfpq_build(
    X: np.ndarray,
    nlist: int,
    m_subvectors: int,
    n_bits: int,
    max_iter: int,
    seed: int,
    batch_rows: int,
    sample_rows: int = 1 << 18,
    cosine: bool = False,
) -> Dict[str, np.ndarray]:
    """Out-of-core IVF-PQ build (cuVS ivf_pq role, reference knn.py:1510-1524,
    under the managed-memory tier utils.py:184-241): coarse cells via the
    streamed IVF-Flat build, PQ codebooks trained in-core on a strided RESIDUAL
    subsample, then codes assigned in streamed encoding passes — the dataset
    itself never resides on device. Same index layout as ops/knn.py::ivfpq_build
    (codebooks (m, 2^bits, d/m), codes (nlist, max_cell, m) uint8)."""
    from .kmeans import kmeans_fit, kmeans_predict

    n, d = X.shape
    if d % m_subvectors != 0:
        raise ValueError(f"n features {d} not divisible by pq m={m_subvectors}")
    if not 1 <= n_bits <= 8:
        raise ValueError(f"n_bits must be in [1, 8] (uint8 codes), got {n_bits}")
    sub_d = d // m_subvectors
    n_codes = 2**n_bits
    flat = streaming_ivfflat_build(
        X, nlist, max_iter, seed, batch_rows, sample_rows, return_assign=True,
        cosine=cosine,
    )
    coarse = np.asarray(flat["centers"])
    assign = flat.pop("assign")

    # codebooks from a residual subsample (strided — rows are not assumed
    # shuffled); the in-core build trains on ALL residuals, so codebooks differ
    # in detail but the recall/quality contract is preserved (tested)
    sub_idx = _strided_sample_indices(n, sample_rows)
    X_sub = np.ascontiguousarray(X[sub_idx], np.float32)  # noqa: fence/host-staging-copy
    if cosine:
        X_sub = _normalize_batch_or_raise(X_sub)
    resid_s = X_sub - coarse[assign[sub_idx]]
    wv = jnp.ones((len(sub_idx),), jnp.float32)
    codebooks = np.zeros((m_subvectors, n_codes, sub_d), np.float32)
    for m_i in range(m_subvectors):
        sub = resid_s[:, m_i * sub_d : (m_i + 1) * sub_d]
        k_eff = min(n_codes, sub.shape[0])
        fitted = kmeans_fit(
            jnp.asarray(sub), wv, k=k_eff, max_iter=max_iter, tol=1e-4,
            init="k-means||", init_steps=2, seed=seed + m_i, unit_weight=True,
        )
        cb = np.zeros((n_codes, sub_d), np.float32)
        cb[:k_eff] = fitted["cluster_centers"]
        if k_eff < n_codes:
            cb[k_eff:] = 1e18  # unused codes: unreachable
        codebooks[m_i] = cb

    # pipelined streamed encoding: one batch upload covers all m
    # sub-encodings (dispatched async, pulled in the drain half); per-batch
    # retry as in the assignment loop (idempotent codes_flat[s:e] writes)
    cb_j = [jnp.asarray(codebooks[m_i]) for m_i in range(m_subvectors)]
    codes_flat = np.zeros((n, m_subvectors), np.uint8)

    def _dispatch_encode(bi, s, e):
        Xb_enc = np.ascontiguousarray(X[s:e], np.float32)  # noqa: fence/host-staging-copy
        if cosine:
            Xb_enc = _normalize_batch_or_raise(Xb_enc)
        resid_b = put_device_local(Xb_enc - coarse[assign[s:e]])
        return [
            kmeans_predict(
                resid_b[:, m_i * sub_d : (m_i + 1) * sub_d], cb_j[m_i]
            )
            for m_i in range(m_subvectors)
        ]

    def _finalize_encode(bi, s, e, outs):
        for m_i, out in enumerate(outs):
            codes_flat[s:e, m_i] = np.asarray(out).astype(np.uint8)

    _pipelined_run(n, batch_rows, "ann_encode", _dispatch_encode,
                   _finalize_encode)

    cell_ids = flat["cell_ids"]
    # size codes from the BUILT index, not the requested nlist: the IVF build
    # clamps nlist to the subsample size (streaming_ivfflat_build), so the
    # caller's nlist can exceed cell_ids.shape[0] — codes must match the
    # centers/cell layout actually built (ADVICE round-5 finding)
    nlist_eff, max_cell = cell_ids.shape
    codes = np.zeros((nlist_eff, max_cell, m_subvectors), np.uint8)
    pos = cell_ids >= 0
    codes[pos] = codes_flat[cell_ids[pos]]
    return {
        "centers": coarse,
        "center_norms": flat["center_norms"],
        "codebooks": codebooks,
        "codes": codes,
        "cell_ids": cell_ids,
        "cell_sizes": flat["cell_sizes"],
        "cells": flat["cells"],  # host-resident; kept for optional exact refine
    }


def streaming_cagra_build(
    X: np.ndarray,
    graph_degree: int = 32,
    nlist: int = 0,
    seed: int = 42,
    batch_rows: int = 1 << 16,
    sample_rows: int = 1 << 18,
    cosine: bool = False,
) -> Dict[str, np.ndarray]:
    """Out-of-core CAGRA-class graph build (cuVS cagra role, reference
    knn.py:1538-1690): the fixed-degree kNN graph comes from STREAMED IVF
    searches — items host-resident, each item batch queries the paged IVF index
    (streaming_ivfflat_search) for its deg+1 neighbors — then the same
    reverse-edge optimization as the in-core build runs on host. Search remains
    in-core (cagra_search walks the graph with random access; the returned
    {"items", "graph"} match ops/knn.py::cagra_build's contract)."""
    from .knn import _optimize_graph_reverse_edges

    X = np.ascontiguousarray(np.asarray(X), dtype=np.float32)  # noqa: fence/host-staging-copy
    if cosine:
        # the graph AND the returned items must live on the unit sphere (the
        # searcher walks euclidean distances over them) — one normalized copy,
        # exactly what the in-core estimator materializes before cagra_build
        X = _normalize_batch_or_raise(X)
    n = X.shape[0]
    deg = min(graph_degree, max(n - 1, 1))
    if nlist <= 0:
        nlist = max(int(np.sqrt(n)), 8)
    index = streaming_ivfflat_build(
        X, nlist=nlist, max_iter=10, seed=seed, batch_rows=batch_rows,
        sample_rows=sample_rows,
    )
    nprobe = min(nlist, max(2, nlist // 8))
    idx = np.full((n, deg + 1), -1, np.int64)
    for s in range(0, n, batch_rows):
        e = min(s + batch_rows, n)
        _, ib = streaming_ivfflat_search(X[s:e], index, k=deg + 1, nprobe=nprobe)
        # the paged search returns min(k, nprobe*max_cell) columns; leave any
        # shortfall as -1 (mapped to node 0 below, same as the in-core build)
        idx[s:e, : ib.shape[1]] = ib

    rows = np.arange(n)[:, None]
    not_self = idx != rows
    order = np.argsort(~not_self, axis=1, kind="stable")
    graph = np.take_along_axis(idx, order, axis=1)[:, :deg].astype(np.int32)  # noqa: fence/host-staging-copy
    graph = np.maximum(graph, 0)  # any -1 from an undersized probe -> node 0
    graph = _optimize_graph_reverse_edges(X, graph, deg)
    from .knn import center_norms_sq

    return {"items": X, "graph": graph, "item_norms_sq": center_norms_sq(X)}


@compiled_kernel("ann.probe_cells", static_argnames=("nprobe",))
def _probe_cells(
    Q: jax.Array, centers: jax.Array, nprobe: int, center_norms=None
):
    from .knn import _block_sq_dists

    cd2 = _block_sq_dists(Q, centers, center_norms)
    # coarse probe stays exact: nprobe already bounds recall; an approximate
    # probe would compound with the candidate-select approximation
    _, probe = select_topk(cd2, nprobe, strategy="exact_full")
    return probe


@compiled_kernel("ann.scan_probed",
                 static_argnames=("k", "strategy", "tile", "recall_target"))
def _scan_probed(qb, probed_items, probed_ids, k, strategy, tile, recall_target):
    """(bq, nprobe, max_cell, d) probed cells -> per-query top-k. EXACT f32
    difference-form distances, matching ops/knn.py::ivfflat_search's in-core
    cell scan rank-for-rank (the candidate set per query is small, so the exact
    form costs nothing; the expanded bf16 form was observed to reorder
    near-duplicate candidates vs the in-core path). The configured selection
    strategy applies to the candidate width; distances stay exact either way."""
    bq, nprobe, max_cell, d = probed_items.shape
    flat = probed_items.reshape(bq, nprobe * max_cell, d)
    flat_ids = probed_ids.reshape(bq, nprobe * max_cell)
    d2 = jnp.sum((flat - qb[:, None, :]) ** 2, axis=2)
    d2 = mask_invalid(d2, flat_ids >= 0)
    d2_sel, pos = select_topk(
        d2, k, strategy=strategy, tile=tile, recall_target=recall_target
    )
    ids = jnp.take_along_axis(flat_ids, pos, axis=1)
    dists = jnp.sqrt(d2_sel)
    return jnp.where(ids >= 0, dists, jnp.inf), ids


def streaming_ivfflat_search(
    Q: np.ndarray,
    index: Dict[str, np.ndarray],
    k: int,
    nprobe: int,
    block: int = 256,
) -> Tuple[np.ndarray, np.ndarray]:
    """Search with host-resident cells: per query block the probe list is
    computed on device, then ONLY the probed cells are gathered host-side and
    device_put — (block, nprobe, max_cell, d) device residency. Returns
    (euclidean distances, item ids) of width k_eff = min(k, nprobe*max_cell),
    id -1 where fewer than k found — the SAME width contract as the in-core
    ivfflat_search, so results are byte-identical across the threshold."""
    centers_j = jnp.asarray(index["centers"])
    center_norms = index.get("center_norms")
    cn_j = jnp.asarray(center_norms) if center_norms is not None else None
    cells = index["cells"]
    cell_ids = index["cell_ids"]
    nlist, max_cell, d = cells.shape
    nq = Q.shape[0]
    k_eff = min(k, nprobe * max_cell)
    strategy, tile, rt = _sel.resolve(nprobe * max_cell, k_eff, None)
    _sel.record_selection(strategy, site="ann_streaming_search")
    # the COARSE probe is a fusable scan (Q vs resident centers): route it
    # through the fused pallas distance+select kernel when `pallas_fused`
    # resolves for the nlist width (explicit, or auto on TPU past
    # knn.pallas_min_items). The probe stays exact-f32 either way — the probe
    # list bounds recall for the whole search, so knn.pallas_precision never
    # applies to it; ids are bit-identical to the exact_full probe.
    probe_fused = (
        _sel.resolve(nlist, min(nprobe, nlist), None, fusable=True)[0]
        == "pallas_fused"
    )
    if probe_fused:
        _sel.record_selection(
            "pallas_fused", site="ann_streaming_probe"
        )
    from .knn import _count_x2

    _count_x2(cn_j, "ann_streaming_search", False)

    out_d = np.full((nq, k_eff), np.inf, np.float32)
    out_i = np.full((nq, k_eff), -1, np.int64)

    def _dispatch_search(bi, s, e):
        qb = put_device_local(np.ascontiguousarray(Q[s:e], dtype=np.float32))  # noqa: fence/host-staging-copy
        if probe_fused:
            from .pallas_select import fused_probe

            probe = np.asarray(
                fused_probe(qb, centers_j, nprobe, center_norms=cn_j)
            )  # (bq, nprobe) — bit-identical to the exact probe
        else:
            probe = np.asarray(
                _probe_cells(qb, centers_j, nprobe, cn_j)
            )  # (bq, nprobe)
        # the host gather IS the out-of-core page-in; placement goes through
        # the active Partitioner's local-device path (process-local staging)
        probed_items = put_device_local(cells[probe])
        probed_ids = put_device_local(cell_ids[probe])
        # span covers the fused scan+select kernel dispatch — named for what
        # it times (the standalone `knn.select`/`knn.rerank` spans are
        # reserved for separately-dispatched selection/re-rank programs)
        with obs_span("ann.scan_select", {"start": s, "rows": e - s}):
            return _scan_probed(
                qb, probed_items, probed_ids, k_eff, strategy, tile, rt
            )

    def _finalize_search(bi, s, e, out):
        dists, ids = out
        out_d[s:e] = np.asarray(dists)
        out_i[s:e] = np.asarray(ids)

    # pipelined per-block retry: each block only writes out_d/out_i[s:e]
    # (idempotent); the host gather/page-in of block i+1 overlaps the device
    # scan of block i
    _pipelined_run(nq, block, "ann_search", _dispatch_search, _finalize_search)
    return out_d, out_i


@compiled_kernel("ann.refine_exact_tile", static_argnames=("k",))
def _refine_exact_tile(qb, vecs, item_ids, k: int):
    """Exact re-rank tile (always exact_full — this IS the re-rank stage)."""
    d2 = jnp.sum((vecs - qb[:, None, :]) ** 2, axis=-1)
    d2 = mask_invalid(d2, item_ids >= 0)
    d2_sel, ids = merge_topk(d2, item_ids, k)
    dists = jnp.sqrt(d2_sel)
    return jnp.where(ids >= 0, dists, jnp.inf), ids


def streaming_pq_refine(
    Q: np.ndarray,
    cells: np.ndarray,
    cand_ids_flat: np.ndarray,
    cand_item_ids: np.ndarray,
    k: int,
    block: int = 1024,
) -> Tuple[np.ndarray, np.ndarray]:
    """Host-paged exact re-rank of ADC candidates (ops/knn.py::pq_refine with the
    cell layout HOST-RESIDENT): the candidate gather is the page-in — only
    (block, kc, d) candidate vectors ever reach the device, never the full
    cell-padded dataset. Same result contract as pq_refine."""
    flat = cells.reshape(-1, cells.shape[-1])
    nq, kc = cand_item_ids.shape
    k_eff = min(k, kc)
    out_d = np.empty((nq, k_eff), np.float32)
    out_i = np.empty((nq, k_eff), np.int64)
    cand_pos = np.maximum(np.asarray(cand_ids_flat), 0)
    cand_ids = np.asarray(cand_item_ids)

    def _dispatch_refine(bi, s, e):
        vecs = put_device_local(flat[cand_pos[s:e]])  # the host page-in
        with obs_span("knn.rerank", {"start": s, "rows": e - s}):
            return _refine_exact_tile(
                put_device_local(np.ascontiguousarray(Q[s:e], np.float32)),  # noqa: fence/host-staging-copy
                vecs,
                put_device_local(cand_ids[s:e]),
                k_eff,
            )

    def _finalize_refine(bi, s, e, out):
        d_b, i_b = out
        out_d[s:e] = np.asarray(d_b)
        out_i[s:e] = np.asarray(i_b)

    # pipelined per-block retry (idempotent out_d/out_i[s:e] writes), same
    # site as the paged IVF search — both are search-phase page-ins
    _pipelined_run(nq, block, "ann_search", _dispatch_refine, _finalize_refine)
    return out_d, out_i
