# Public API module mirroring the reference's `spark_rapids_ml.feature`
# (reference python/src/spark_rapids_ml/feature.py).
from .models.feature import (
    PCA,
    PCAModel,
    StandardScaler,
    StandardScalerModel,
    VectorAssembler,
)

__all__ = [
    "PCA",
    "PCAModel",
    "StandardScaler",
    "StandardScalerModel",
    "VectorAssembler",
]
