# Public API module mirroring the reference's `spark_rapids_ml.clustering`
# (reference python/src/spark_rapids_ml/clustering.py: KMeans + DBSCAN).
from .models.clustering import KMeans, KMeansModel

try:  # DBSCAN arrives with models/dbscan.py
    from .models.dbscan import DBSCAN, DBSCANModel  # re-exported surface

    __all__ = ["KMeans", "KMeansModel", "DBSCAN", "DBSCANModel"]
except ImportError:  # pragma: no cover
    __all__ = ["KMeans", "KMeansModel"]
