#
# Hyperparameter tuning — pyspark.ml.tuning-compatible ParamGridBuilder /
# CrossValidator / CrossValidatorModel with the reference's GPU acceleration strategy
# (reference python/src/spark_rapids_ml/tuning.py:92-157):
#   * all param maps of a fold fit in ONE data pass via fitMultiple
#     (P6 "multi-model-in-one-pass", SURVEY.md §2.7)
#   * transform+evaluate runs per fitted model on the held-out fold
# The k-fold split, metric averaging and best-model refit semantics match pyspark.
#

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .core.params import (
    HasCollectSubModels,
    HasParallelism,
    HasSeed,
    Param,
    ParamMap,
    TypeConverters,
)
from .utils import get_logger


class ParamGridBuilder:
    """Builder for a param grid used in grid search (pyspark.ml.tuning surface)."""

    def __init__(self) -> None:
        self._param_grid: Dict[Param, List[Any]] = {}

    def addGrid(self, param: Param, values: List[Any]) -> "ParamGridBuilder":
        if isinstance(param, Param):
            self._param_grid[param] = list(values)
            return self
        raise TypeError("param must be an instance of Param")

    def baseOn(self, *args: Tuple[Param, Any], **kwargs: Any) -> "ParamGridBuilder":
        if isinstance(args[0], dict) if args else False:
            args = tuple(args[0].items())
        for param, value in args:
            self.addGrid(param, [value])
        return self

    def build(self) -> List[ParamMap]:
        keys = list(self._param_grid.keys())
        grid_values = [self._param_grid[k] for k in keys]
        return [
            dict(zip(keys, combo)) for combo in itertools.product(*grid_values)
        ]


def _evaluate_fold(models: List[Any], test: Any, evaluator: Any,
                   fold: Optional[int] = None) -> List[float]:
    """Evaluate a fold's models in ONE transform scan when they all support the fused
    path (reference one-scan transform+evaluate with model_index, core.py:1572-1693);
    per-model two-step otherwise. Eval spans carry the fold/candidate labels so a
    CV parent run's trace attributes time per trial (docs/design.md §6e)."""
    from .observability import span as _obs_span

    fused = (
        models
        and all(
            getattr(m, "_supportsTransformEvaluate", lambda: False)() for m in models
        )
        and len({type(m) for m in models}) == 1
    )
    if fused:
        from .core.estimator import transform_evaluate_multi

        with _obs_span(
            "cv.eval_fused", {"fold": fold, "candidates": len(models)}
        ):
            return transform_evaluate_multi(models, test, evaluator)
    scores: List[float] = []
    for i, m in enumerate(models):
        with _obs_span("cv.eval_candidate", {"fold": fold, "candidate": i}):
            scores.append(evaluator.evaluate(m.transform(test)))
    return scores


class _CrossValidatorParams(HasSeed, HasParallelism, HasCollectSubModels):
    numFolds: Param[int] = Param(
        "undefined",
        "numFolds",
        "number of folds for cross validation (>= 2).",
        TypeConverters.toInt,
    )
    foldCol: Param[str] = Param(
        "undefined",
        "foldCol",
        "Param for the column name of user specified fold number.",
        TypeConverters.toString,
    )

    def getNumFolds(self) -> int:
        return self.getOrDefault("numFolds")


class CrossValidator(_CrossValidatorParams):
    """K-fold cross validation accelerated the reference's way: one fitMultiple pass
    per fold (reference tuning.py:92-157)."""

    def __init__(
        self,
        estimator: Any = None,
        estimatorParamMaps: Optional[List[ParamMap]] = None,
        evaluator: Any = None,
        numFolds: int = 3,
        seed: Optional[int] = None,
        parallelism: int = 1,
        collectSubModels: bool = False,
        foldCol: str = "",
    ) -> None:
        super().__init__()
        self._setDefault(numFolds=3, foldCol="", parallelism=1, collectSubModels=False, seed=42)
        self._set(
            numFolds=numFolds,
            foldCol=foldCol,
            parallelism=parallelism,
            collectSubModels=collectSubModels,
        )
        if seed is not None:
            self._set(seed=seed)
        self._estimator = estimator
        self._estimatorParamMaps = estimatorParamMaps or []
        self._evaluator = evaluator
        self.logger = get_logger(self.__class__)

    # pyspark getters/setters

    def getEstimator(self) -> Any:
        return self._estimator

    def setEstimator(self, value: Any) -> "CrossValidator":
        self._estimator = value
        return self

    def getEstimatorParamMaps(self) -> List[ParamMap]:
        return self._estimatorParamMaps

    def setEstimatorParamMaps(self, value: List[ParamMap]) -> "CrossValidator":
        self._estimatorParamMaps = value
        return self

    def getEvaluator(self) -> Any:
        return self._evaluator

    def setEvaluator(self, value: Any) -> "CrossValidator":
        self._evaluator = value
        return self

    def _kFold(self, dataset: Any) -> List[Tuple[Any, Any]]:
        """Random (or foldCol-driven) k-fold split of a pandas dataset."""
        n_folds = self.getNumFolds()
        fold_col = self.getOrDefault("foldCol")
        n = len(dataset)
        if fold_col:
            fold_ids = dataset[fold_col].to_numpy().astype(int) % n_folds
        else:
            rng = np.random.default_rng(self.getOrDefault("seed"))
            fold_ids = rng.integers(0, n_folds, size=n)
        pairs = []
        for f in range(n_folds):
            test_mask = fold_ids == f
            pairs.append(
                (
                    dataset.iloc[~test_mask].reset_index(drop=True),
                    dataset.iloc[test_mask].reset_index(drop=True),
                )
            )
        return pairs

    def fit(self, dataset: Any) -> "CrossValidatorModel":
        return self._fit(dataset)

    def _fit(self, dataset: Any) -> "CrossValidatorModel":
        est = self._estimator
        maps = self._estimatorParamMaps
        evaluator = self._evaluator
        if est is None or evaluator is None or not maps:
            raise ValueError(
                "CrossValidator requires an estimator, a non-empty "
                "estimatorParamMaps, and an evaluator."
            )
        if self.getNumFolds() < 2:
            raise ValueError(
                f"Param numFolds={self.getNumFolds()} must be >= 2."
            )
        import time as _time

        from .observability import fit_run, span as _obs_span

        n_models = len(maps)
        metrics = np.zeros((n_models,), dtype=np.float64)
        sub_models: Optional[List[List[Any]]] = (
            [] if self.getOrDefault("collectSubModels") else None
        )
        trials: List[Dict[str, Any]] = []

        # parent run over the whole search: every per-fold fit/eval span — and
        # the nested per-candidate FitRuns' spans — land in ONE trace, exported
        # like any fit report (algo=CrossValidator); the structured per-trial
        # summary attaches to the fitted model as `cv_report_` (§6e)
        with fit_run(algo=type(self).__name__) as run:
            for fold, (train, test) in enumerate(self._kFold(dataset)):
                fold_models: List[Any] = [None] * n_models
                cand_fit_s: List[Optional[float]] = [None] * n_models
                with _obs_span("cv.fold", {"fold": fold}):
                    t0 = _time.perf_counter()
                    with _obs_span(
                        "cv.fit", {"fold": fold, "candidates": n_models}
                    ):
                        # ONE fit pass per fold when the estimator supports it
                        # (fitMultiple). Per-candidate wall times come from the
                        # iterator pulls; in single-pass mode the first pull
                        # carries the shared data pass (deliberately honest —
                        # that IS where the time goes).
                        it = iter(est.fitMultiple(train, maps))
                        while True:
                            t_c = _time.perf_counter()
                            try:
                                index, model = next(it)
                            except StopIteration:
                                break
                            cand_fit_s[index] = _time.perf_counter() - t_c
                            fold_models[index] = model
                    fit_s = _time.perf_counter() - t0
                    t1 = _time.perf_counter()
                    scores = _evaluate_fold(fold_models, test, evaluator, fold=fold)
                    eval_s = _time.perf_counter() - t1
                metrics += np.asarray(scores)
                trials.append(
                    {
                        "fold": fold,
                        "fit_s": round(fit_s, 6),
                        "eval_s": round(eval_s, 6),
                        "candidate_fit_s": [
                            round(s, 6) if s is not None else None
                            for s in cand_fit_s
                        ],
                        "scores": [float(s) for s in scores],
                    }
                )
                if sub_models is not None:
                    sub_models.append(fold_models)

            metrics /= self.getNumFolds()
            best_index = (
                int(np.argmax(metrics))
                if evaluator.isLargerBetter()
                else int(np.argmin(metrics))
            )
            self.logger.info(
                "CrossValidator metrics=%s best_index=%d", metrics.tolist(), best_index
            )
            with _obs_span("cv.refit", {"candidate": best_index}):
                best_model = est.fit(dataset, maps[best_index])
        cv_model = CrossValidatorModel(
            best_model, metrics.tolist(), sub_models=sub_models
        )
        cv_model.cv_report_ = {
            "schema": 1,
            "kind": "cv",
            "run_id": run.run_id if run is not None else None,
            "estimator": type(est).__name__,
            "evaluator": type(evaluator).__name__,
            "num_folds": self.getNumFolds(),
            "num_candidates": n_models,
            "avg_metrics": metrics.tolist(),
            "best_index": best_index,
            "trials": trials,
            # the winning refit's full trace — the "best candidate" drill-down
            "best_fit_report": getattr(best_model, "fit_report_", None),
        }
        cv_model._resetUid(self.uid)
        self._copyValues(cv_model)
        return cv_model

    def copy(self, extra: Optional[ParamMap] = None) -> "CrossValidator":
        that = super().copy(extra)
        that._estimator = self._estimator.copy()
        that._estimatorParamMaps = list(self._estimatorParamMaps)
        that._evaluator = self._evaluator.copy()
        return that  # type: ignore[return-value]


class _TrainValidationSplitParams(HasSeed, HasParallelism, HasCollectSubModels):
    trainRatio: Param[float] = Param(
        "undefined",
        "trainRatio",
        "Param for ratio between train and validation data. Must be between 0 and 1.",
        TypeConverters.toFloat,
    )

    def getTrainRatio(self) -> float:
        return self.getOrDefault("trainRatio")


class TrainValidationSplit(_TrainValidationSplitParams):
    """Single train/validation split tuning (pyspark.ml.tuning surface) with the same
    one-pass fitMultiple acceleration as CrossValidator."""

    def __init__(
        self,
        estimator: Any = None,
        estimatorParamMaps: Optional[List[ParamMap]] = None,
        evaluator: Any = None,
        trainRatio: float = 0.75,
        seed: Optional[int] = None,
        parallelism: int = 1,
    ) -> None:
        super().__init__()
        self._setDefault(trainRatio=0.75, parallelism=1, collectSubModels=False, seed=42)
        self._set(trainRatio=trainRatio, parallelism=parallelism)
        if seed is not None:
            self._set(seed=seed)
        self._estimator = estimator
        self._estimatorParamMaps = estimatorParamMaps or []
        self._evaluator = evaluator
        self.logger = get_logger(self.__class__)

    def fit(self, dataset: Any) -> "TrainValidationSplitModel":
        est, maps, evaluator = self._estimator, self._estimatorParamMaps, self._evaluator
        if est is None or evaluator is None or not maps:
            raise ValueError(
                "TrainValidationSplit requires an estimator, a non-empty "
                "estimatorParamMaps, and an evaluator."
            )
        ratio = self.getTrainRatio()
        if not (0.0 < ratio < 1.0):
            raise ValueError(f"trainRatio must be in (0, 1), got {ratio}")
        rng = np.random.default_rng(self.getOrDefault("seed"))
        mask = rng.random(len(dataset)) < ratio
        if mask.all() or not mask.any():
            raise ValueError(
                f"train/validation split produced an empty side "
                f"(n={len(dataset)}, trainRatio={ratio}); use more data or a "
                "less extreme ratio."
            )
        train = dataset.iloc[mask].reset_index(drop=True)
        val = dataset.iloc[~mask].reset_index(drop=True)

        models: List[Any] = [None] * len(maps)
        for index, model in est.fitMultiple(train, maps):
            models[index] = model
        metrics = np.asarray(_evaluate_fold(models, val, evaluator), dtype=np.float64)
        best_index = (
            int(np.argmax(metrics)) if evaluator.isLargerBetter() else int(np.argmin(metrics))
        )
        best_model = est.fit(dataset, maps[best_index])
        tvs_model = TrainValidationSplitModel(best_model, metrics.tolist())
        self._copyValues(tvs_model)
        return tvs_model


class TrainValidationSplitModel(_TrainValidationSplitParams):
    def __init__(self, bestModel: Any, validationMetrics: Optional[List[float]] = None):
        super().__init__()
        self._setDefault(trainRatio=0.75, parallelism=1, collectSubModels=False, seed=42)
        self.bestModel = bestModel
        self.validationMetrics = validationMetrics or []

    def transform(self, dataset: Any) -> Any:
        return self.bestModel.transform(dataset)


class CrossValidatorModel(_CrossValidatorParams):
    """Holds the best model + averaged metrics (pyspark surface)."""

    def __init__(
        self,
        bestModel: Any,
        avgMetrics: Optional[List[float]] = None,
        sub_models: Optional[List[List[Any]]] = None,
    ) -> None:
        super().__init__()
        self._setDefault(numFolds=3, foldCol="", parallelism=1, collectSubModels=False, seed=42)
        self.bestModel = bestModel
        self.avgMetrics = avgMetrics or []
        self.subModels = sub_models

    def transform(self, dataset: Any) -> Any:
        return self.bestModel.transform(dataset)
