#
# Global config/flag system — the TPU analog of the reference's Spark-conf tier
# (SURVEY.md §5.6; reference reads spark.rapids.ml.{uvm.enabled, sam.enabled,
# cpu.fallback.enabled, verbose, float32_inputs, num_workers} at fit time,
# core.py:776-812 / params.py:275-286; documented in docs/site/configuration.md).
#
# Three tiers, mirroring the reference:
#   1. estimator Params / backend kwargs        (per-estimator, core/backend_params)
#   2. THIS module: process-wide defaults, settable programmatically or via
#      SRML_TPU_* environment variables         (the spark-conf analog)
#   3. hard defaults below
#
# Keys:
#   fallback.enabled   (bool, env SRML_TPU_FALLBACK_ENABLED)  — CPU fallback on
#                      unsupported params (reference spark.rapids.ml.cpu.fallback.enabled)
#   float32_inputs     (bool, env SRML_TPU_FLOAT32_INPUTS)
#   num_workers        (int,  env SRML_TPU_NUM_WORKERS)       — default mesh width
#   verbose            (bool, env SRML_TPU_VERBOSE)
#   trace_dir          (str,  env SRML_TPU_TRACE_DIR)         — xplane capture per fit
#

from __future__ import annotations

import os
from typing import Any, Dict

_DEFAULTS: Dict[str, Any] = {
    "fallback.enabled": True,
    "float32_inputs": True,
    "num_workers": None,
    "verbose": False,
    "trace_dir": None,
    # streamed out-of-core fit (ops/streaming.py): estimators with a streaming path
    # switch to it when the design matrix exceeds this many bytes (the TPU analog of
    # the reference's UVM/SAM managed memory, utils.py:184-241)
    "stream_threshold_bytes": 4 << 30,
    "stream_batch_rows": 1 << 20,
    # Spark-input fit data plane: "barrier" fans the fit out as barrier tasks over
    # TPU hosts (spark/integration.py), "collect" materializes on the driver (local
    # mode / tiny data), "auto" picks barrier when a usable pyspark is importable
    "spark_fit_mode": "auto",
    # fast_math=True lets ranking-only matmuls (KMeans assignment distances) run at
    # MXU bf16 single-pass precision; model attributes stay parity-precision
    "fast_math": False,
    # precision of PARITY matmuls (the ones feeding model attributes):
    #   highest = 6-pass bf16 (full f32, the default)
    #   high    = 3-pass bf16 (~2x faster on MXU, error ~2^-22 vs ~2^-24)
    # a TPU-measured accuracy/throughput tradeoff knob; tests pin highest
    "parity_precision": "highest",
    # fused one-X-read pallas Gram kernels: the PCA covariance AND the
    # normal-equation LinReg stats (ops/pallas_xtwx.py — the label rides as a
    # tile-aligned operand so XᵀX/Xᵀy/yᵀy come from one X read): "auto" = on for
    # TPU unit-weight f32 fits (measured 6x the XLA path at 12M x 128), "0" =
    # force XLA, "1" = skip the platform check (tests — runs the kernel's
    # interpreter off-TPU)
    "pallas_xtwx": "auto",
    # selection plane (ops/selection.py): THE top-k strategy for the whole
    # search family (exact kNN, IVF-Flat/PQ, CAGRA, streamed ANN, pairwise
    # sweeps). auto = approx on TPU (native approximate-selection unit +
    # parity re-rank keeps returned distances exact), exact_tiled elsewhere
    # (bit-for-bit equal to exact_full; two-stage vectorized select)
    "knn.selection": "auto",  # auto | exact_full | exact_tiled | approx
    # per-element expected recall of the approx strategy's winner pool
    # (jax.lax.approx_max_k recall_target); exact modes ignore it
    "knn.recall_target": 0.95,
    # exact_tiled tile width; 0 = platform auto (TPU: 2048 — small fixed tiles
    # vectorize on the VPU; CPU: max(8192, n/4) — the XLA CPU TopK custom call
    # is per-call-overhead-bound, so few large tiles win)
    "knn.select_tile": 0,
    # fused pallas distance+select scans (ops/pallas_select.py, design.md §5c):
    # the `pallas_fused` selection strategy fuses the (block, n_items) distance
    # tile with an in-register running top-k/argmin/count so the distance
    # matrix never materializes in HBM. `auto` engages it on TPU at FUSABLE
    # call sites (exact kNN scans, IVF coarse probes, DBSCAN neighborhood
    # counts, KMeans assignment) once the scanned item width reaches this
    # threshold; below it (or off-TPU) auto keeps the PR-5 strategies
    "knn.pallas_min_items": 1 << 16,
    # distance-ACCUMULATION precision of the fused scan: float32 is exact
    # (bit-identical to the XLA path); bfloat16/int8 compute an approximate
    # candidate pool on the fast MXU paths and the parity_rerank_sq invariant
    # restores exact-f32 returned distances (only the id set is approximate)
    "knn.pallas_precision": "float32",
    # HBM-resident batch cache (ops/device_cache.py): multi-pass streamed fits
    # retain pass-1 device batches and replay passes 2..N from HBM (the TPU
    # analog of the reference's cross-pass cuDF/UVM residency). The budget
    # bounds cache HBM; datasets above it cache a prefix and stream the tail
    "cache.enabled": True,
    "cache.hbm_budget_bytes": 2 << 30,
    # reliability subsystem (reliability/): retry/backoff policy, deterministic
    # fault injection, streamed-fit checkpoint-resume, and the
    # barrier->collect->CPU degradation ladder (docs/design.md "Reliability")
    "reliability.enabled": True,
    "reliability.max_attempts": 3,          # total attempts per retried unit
    "reliability.backoff_base_s": 0.05,     # exponential backoff base
    "reliability.backoff_max_s": 2.0,       # backoff cap
    "reliability.backoff_jitter": 0.1,      # +/- jitter/2, deterministic (hashed)
    "reliability.deadline_s": None,         # per-stage wall-clock deadline
    "reliability.checkpoint_batches": 16,   # streamed-fit snapshot cadence
    "reliability.fault_spec": "",           # fault grammar, reliability/faults.py
    "reliability.chaos_spec": "",           # replica chaos grammar, reliability/chaos.py
    "reliability.degrade_to_collect": True, # barrier fit failure -> collect mode
    # observability subsystem (observability/): typed metrics registry, per-fit
    # FitRun trace trees (model.fit_report_), driver-side aggregation of
    # barrier-worker metrics, JSONL + Prometheus exporters (docs/design.md §6d)
    "observability.enabled": True,          # FitRun scopes + trace collection
    "observability.metrics_dir": None,      # JSONL fit_reports.jsonl directory
    "observability.max_spans": 1024,        # trace-tree node cap per run
    # inference plane (observability/inference.py): TransformRun scopes, the
    # instrumented predict dispatch, and the recompile sentinel — warn (and
    # count transform.recompile_storm) once one model's predict has seen more
    # distinct (rows, cols, dtype) shape signatures than this; un-bucketed
    # pandas-UDF batch sizes silently force one XLA compile per batch
    "observability.recompile_warn_threshold": 8,
    # fraction of transform batches whose latency lands in the
    # transform.batch_s/predict_s histograms (counters always count); lower it
    # on hot serving paths where even histogram writes show up in profiles
    "observability.transform_sample_rate": 1.0,
    # JSONL report rotation (observability/export.py): rotate the live file at
    # max_report_bytes, keep max_report_files rotated generations
    "observability.max_report_bytes": 32 << 20,
    "observability.max_report_files": 4,
    # device-performance plane (observability/device.py, docs/design.md §6f):
    # compiled_kernel AOT cost/memory-analysis capture + compile accounting +
    # roofline span attribution. Off = kernels run as plain jax.jit calls.
    "observability.device_enabled": True,
    # HBM telemetry: sample local_devices() memory_stats() at span boundaries
    # (gauges are simply absent on platforms without memory_stats — CPU)
    "observability.hbm_sampling": True,
    "observability.hbm_sample_interval_s": 0.05,  # span-boundary rate limit
    # roofline peak overrides (FLOP/s and bytes/s PER CHIP); 0 = auto from the
    # per-platform peak table keyed on device_kind
    "observability.peak_flops": 0.0,
    "observability.peak_bw": 0.0,
    # communication plane (observability/comm.py, docs/design.md §6h):
    # per-chip ICI/interconnect peak bytes/s override for the comm_frac /
    # comm_bound verdicts; 0 = auto from the peak table's ICI column
    "observability.peak_ici_bw": 0.0,
    # per-rank skew above which a rank is flagged a straggler (its phase wall
    # time vs the rank median): fires a `straggler` event into the run's event
    # log + flight recorder and counts comm.stragglers{phase=}
    "observability.straggler_threshold": 1.5,
    # absolute per-phase wall-time floor for straggler flags: ratios over
    # millisecond-scale phases are scheduler jitter, not stragglers
    "observability.straggler_min_wall_s": 0.25,
    # opt-in jax.profiler capture of ONE designated pass of a streamed fit:
    # set profile_dir to enable; profile_pass picks the pass (default 2 — the
    # first post-compile steady-state pass); one capture per site per process
    "observability.profile_dir": None,
    "observability.profile_pass": 2,
    # live telemetry plane (observability/server.py, docs/design.md §6g):
    # opt-in driver-resident HTTP endpoint serving /metrics (Prometheus pull),
    # /healthz and /runs[/<run_id>] (live JSON view of open runs). None = no
    # server thread is ever started; 0 = bind an ephemeral port (exposed via
    # observability.server.server_address()); the server runs only while at
    # least one run scope is open (or start_metrics_server() pins it)
    "observability.http_port": None,
    # bind host for the telemetry endpoint. Default loopback: the endpoint is
    # unauthenticated, so exposing it beyond the driver host is an explicit
    # operator decision ("0.0.0.0" for cluster-visible scraping)
    "observability.http_host": "127.0.0.1",
    # failure flight recorder (observability/flight.py): bounded per-process
    # ring buffer of recent span opens/closes, events, HBM samples and
    # retry/fault/degrade transitions, dumped as postmortem_<run_id>.json on
    # unhandled fit/transform failure or degradation-ladder entry; <=0 disables
    "observability.flight_recorder_events": 256,
    # per-run cap on streamed-fit convergence records (kmeans inertia/shift,
    # logreg/linreg loss/grad-norm per iteration) kept in the run and exported
    # in the report's `convergence` section; overflow is counted, not kept
    "observability.max_convergence_records": 512,
    # online serving plane (serving/, docs/design.md §7): the driver-resident
    # inference server that turns per-request predict calls into fixed-shape
    # device batches. A batch closes when it reaches max_batch_rows OR the
    # OLDEST queued request has waited max_wait_ms — the classic latency/size
    # cutoff pair (Podracer decoupled feed threads, arXiv:2104.06272)
    "serving.max_batch_rows": 4096,
    "serving.max_wait_ms": 2.0,
    # smallest padding bucket: coalesced batches pad UP to the next power-of-
    # two row count >= this, so the set of predict shape signatures is fixed
    # and finite — bucketing IS the built-in fix for the recompile storms the
    # PR-4 sentinel detects (one XLA compile per ragged batch size)
    "serving.bucket_min_rows": 16,
    # AOT pre-warm on model registration: compile one executable per
    # (model, bucket) up front through the compiled_kernel cache so steady-
    # state serving never compiles
    "serving.prewarm": True,
    # HBM byte budget of the serving model registry (weights of hot models
    # stay device-resident; cold models evict LRU — pinned-while-serving —
    # and reload transparently, counted as serving.model_reloads)
    "serving.hbm_budget_bytes": 1 << 30,
    # backpressure: max requests queued per served model before submit/POST
    # rejects (HTTP 429); a bounded queue keeps tail latency bounded too
    "serving.queue_depth": 1024,
    # per-request wall-clock budget the HTTP handler waits on a future before
    # answering 504 (the request may still complete; its slot is not replayed)
    "serving.request_timeout_s": 30.0,
    # fault-tolerant serving fleet (serving/fleet.py + serving/router.py,
    # docs/design.md §7c). replicas: dispatcher replicas per served model
    # (0 = auto: tuning table, else 1 — the single-dispatcher plane);
    # heartbeat_timeout_s: how long a replica may go without a dispatcher
    # heartbeat before the health monitor marks it DEAD and replays its queue
    # onto survivors; hedge_after_p99_frac: issue a duplicate of a still-
    # queued request to a second replica once its queue wait exceeds this
    # fraction of the observed p99 latency (0 disables hedging)
    "serving.replicas": 0,
    "serving.hedge_after_p99_frac": 0.0,
    "serving.heartbeat_timeout_s": 2.0,
    # ANN index lifecycle (ops/ann_streaming.py + ops/ann_lifecycle.py,
    # docs/design.md §7b). build_batch_rows: row-batch geometry of the
    # pipelined out-of-core builds; 0 = auto (tuning table, else
    # stream_batch_rows). prefetch_depth: staged batches kept in flight so
    # host staging of batch i+1 overlaps device execution of batch i; 0 runs
    # the serial (pre-pipeline) loop — the bench baseline mode
    "ann.build_batch_rows": 0,
    "ann.prefetch_depth": 1,
    # incremental maintenance: IVF list capacity rounds UP to a power-of-two
    # bucket >= list_bucket_rows so in-slack adds never change the search
    # executable's shapes (0 = auto: tuning table, else the defaults-module
    # floor); compaction re-layouts the lists once tombstoned slots exceed
    # this percentage of occupied slots
    "ann.list_bucket_rows": 0,
    "ann.compact_tombstone_pct": 30,
    # lazy device residency of loaded/served indexes (ops/ann_lifecycle.py::
    # DeviceIndexCache): per-segment HBM budget; a segment uploads on FIRST
    # search, not at load — cold-start never stages the whole index
    "ann.index_cache_bytes": 1 << 30,
    # zero-copy ingest plane (ops/ingest.py, docs/design.md §6k): contiguous
    # right-dtype host blocks enter the device DMA path as views (no host
    # staging copy); exotic inputs fall back to a counted staging copy. Off =
    # every batch slice staged through np.ascontiguousarray, the pre-§6k path
    "ingest.zero_copy": True,
    # staging-buffer pool geometry (rows per pooled buffer) for the counted
    # copy fallback; 0 = auto (tuning table, else autotune/defaults.py).
    # Buffer REUSE engages only on backends whose device_put copies (TPU/GPU);
    # CPU jax aliases host memory, so reuse there would corrupt cached batches
    "ingest.staging_pool_rows": 0,
    # whole-pipeline fusion (pipeline.py, docs/design.md §6k): compile
    # featurize->fit chains (scale/PCA feeding KMeans/logreg/linreg) into one
    # streamed program per batch — intermediates never round-trip to host.
    # Bit-parity with the staged path is the contract; off = staged fits
    "pipeline.fuse": True,
    # rows below which fusion is skipped (staged fit overhead is negligible
    # and the staged trace is simpler to debug); 0 = auto (tuning table, else
    # autotune/defaults.py)
    "pipeline.fuse_min_rows": 0,
    # partitioner plane (parallel/partitioner.py, docs/design.md §10): the
    # single owner of mesh + shardings. feature_axis: width of the 2-D
    # SPMDPartitioner's feature axis (wide-k kNN / feature-sharded
    # covariance); 0 = auto (tuning table per (n, d) bucket, else 1 = pure
    # data-parallel). batch_rows_per_process: LOCAL rows each process stages
    # per streamed batch on multi-host runs; 0 = auto (tuning table, else
    # stream_batch_rows split evenly across the pod). Both resolve at host
    # resolution points only — never inside a trace
    "partition.feature_axis": 0,
    "partition.batch_rows_per_process": 0,
    # continuous-learning plane (spark_rapids_ml_tpu/continual/, docs/
    # design.md §7d): streamed partial_fit + drift detection + governed
    # promotion. decay: per-update discount on the persistent sufficient-
    # statistics carry (1.0 = infinite memory, the 1505.06807 a=1 default;
    # 0.0 = auto: tuning table, else autotune/defaults.py). update_batch_rows:
    # fixed block geometry of partial_fit ingest — every update batch is
    # re-blocked to this row count (zero-weight padding) so a steady update
    # stream re-enters ONE compiled executable per kernel (0 = auto).
    # drift_mads: MADs above the baseline median a per-row signal must land
    # to fire `continual.drift` (0.0 = auto). promote_every: attempt a
    # governed promotion after this many updates even without drift.
    # min_baseline: self-calibration floor — observations absorbed into the
    # noise baseline before the detector may fire (when no fit-time
    # convergence tail seeded it)
    "continual.decay": 0.0,
    "continual.update_batch_rows": 0,
    "continual.drift_mads": 0.0,
    "continual.promote_every": 4,
    "continual.min_baseline": 8,
    # trace plane (observability/tracing.py, docs/design.md §6l): per-request
    # causal traces with tail-based sampling. sample_rate: deterministic
    # hash-of-trace_id keep probability for unflagged, not-slow traces (the
    # flagged classes — error/hedged/failover/expired/shed — ALWAYS keep).
    # ring_traces: bounded per-process kept-trace ring served by /traces.
    # slow_frac: rolling slowest fraction that keeps regardless of sampling.
    "tracing.enabled": True,
    "tracing.sample_rate": 1.0,
    "tracing.ring_traces": 256,
    "tracing.slow_frac": 0.05,
    # closed-loop autotuner (spark_rapids_ml_tpu/autotune/, docs/design.md
    # §6i): telemetry-driven knob search persisted as per-platform tuning
    # tables. mode:
    #   off    never consult tables (every knob resolves to its built-in
    #          default unless config pins it)
    #   load   (default) consult the tuning table at the host-wrapper
    #          resolution points; misses fall through to defaults
    #   search on first sight of an uncovered (knob, shape-bucket) at a
    #          searchable knob, run the measurement loop, persist the winner,
    #          and use it — the opt-in online mode
    "autotune.mode": "load",
    # tuning-table directory (versioned tuning_<platform>_<device_kind>.json
    # files, atomic writes). None = in-memory tables only: lookups/searches
    # work for the life of the process but nothing persists
    "autotune.dir": None,
    # measurement-loop replication: timed reps per candidate (round-robin
    # across candidates so warming drift cannot favor late candidates), and
    # how many MADs of separation a challenger needs to displace the default
    # (the ci/bench_check.py lesson: judging two noise samples is not a win)
    "autotune.replicates": 5,
    "autotune.noise_mads": 3.0,
}

_ENV_KEYS: Dict[str, str] = {
    "fallback.enabled": "SRML_TPU_FALLBACK_ENABLED",
    "float32_inputs": "SRML_TPU_FLOAT32_INPUTS",
    "num_workers": "SRML_TPU_NUM_WORKERS",
    "verbose": "SRML_TPU_VERBOSE",
    "trace_dir": "SRML_TPU_TRACE_DIR",
    "stream_threshold_bytes": "SRML_TPU_STREAM_THRESHOLD_BYTES",
    "stream_batch_rows": "SRML_TPU_STREAM_BATCH_ROWS",
    "spark_fit_mode": "SRML_TPU_SPARK_FIT_MODE",
    "fast_math": "SRML_TPU_FAST_MATH",
    "parity_precision": "SRML_TPU_PARITY_PRECISION",
    "pallas_xtwx": "SRML_TPU_PALLAS_XTWX",
    "knn.selection": "SRML_TPU_KNN_SELECTION",
    "knn.recall_target": "SRML_TPU_KNN_RECALL_TARGET",
    "knn.select_tile": "SRML_TPU_KNN_SELECT_TILE",
    "knn.pallas_min_items": "SRML_TPU_KNN_PALLAS_MIN_ITEMS",
    "knn.pallas_precision": "SRML_TPU_KNN_PALLAS_PRECISION",
    "cache.enabled": "SRML_TPU_CACHE_ENABLED",
    "cache.hbm_budget_bytes": "SRML_TPU_CACHE_BUDGET",
    "reliability.enabled": "SRML_TPU_RELIABILITY_ENABLED",
    "reliability.max_attempts": "SRML_TPU_MAX_ATTEMPTS",
    "reliability.backoff_base_s": "SRML_TPU_BACKOFF_BASE_S",
    "reliability.backoff_max_s": "SRML_TPU_BACKOFF_MAX_S",
    "reliability.backoff_jitter": "SRML_TPU_BACKOFF_JITTER",
    "reliability.deadline_s": "SRML_TPU_DEADLINE_S",
    "reliability.checkpoint_batches": "SRML_TPU_CHECKPOINT_BATCHES",
    "reliability.fault_spec": "SRML_TPU_FAULT_SPEC",
    "reliability.chaos_spec": "SRML_TPU_CHAOS_SPEC",
    "reliability.degrade_to_collect": "SRML_TPU_DEGRADE_TO_COLLECT",
    "observability.enabled": "SRML_TPU_OBSERVABILITY_ENABLED",
    "observability.metrics_dir": "SRML_TPU_METRICS_DIR",
    "observability.max_spans": "SRML_TPU_MAX_SPANS",
    "observability.recompile_warn_threshold": "SRML_TPU_RECOMPILE_WARN_THRESHOLD",
    "observability.transform_sample_rate": "SRML_TPU_TRANSFORM_SAMPLE_RATE",
    "observability.max_report_bytes": "SRML_TPU_MAX_REPORT_BYTES",
    "observability.max_report_files": "SRML_TPU_MAX_REPORT_FILES",
    "observability.device_enabled": "SRML_TPU_DEVICE_OBSERVABILITY",
    "observability.hbm_sampling": "SRML_TPU_HBM_SAMPLING",
    "observability.hbm_sample_interval_s": "SRML_TPU_HBM_SAMPLE_INTERVAL_S",
    "observability.peak_flops": "SRML_TPU_PEAK_FLOPS",
    "observability.peak_bw": "SRML_TPU_PEAK_BW",
    "observability.peak_ici_bw": "SRML_TPU_PEAK_ICI_BW",
    "observability.straggler_threshold": "SRML_TPU_STRAGGLER_THRESHOLD",
    "observability.straggler_min_wall_s": "SRML_TPU_STRAGGLER_MIN_WALL_S",
    "observability.profile_dir": "SRML_TPU_PROFILE_DIR",
    "observability.profile_pass": "SRML_TPU_PROFILE_PASS",
    "observability.http_port": "SRML_TPU_METRICS_PORT",
    "observability.http_host": "SRML_TPU_METRICS_HOST",
    "observability.flight_recorder_events": "SRML_TPU_FLIGHT_RECORDER_EVENTS",
    "observability.max_convergence_records": "SRML_TPU_MAX_CONVERGENCE_RECORDS",
    "serving.max_batch_rows": "SRML_TPU_SERVING_MAX_BATCH_ROWS",
    "serving.max_wait_ms": "SRML_TPU_SERVING_MAX_WAIT_MS",
    "serving.bucket_min_rows": "SRML_TPU_SERVING_BUCKET_MIN_ROWS",
    "serving.prewarm": "SRML_TPU_SERVING_PREWARM",
    "serving.hbm_budget_bytes": "SRML_TPU_SERVING_HBM_BUDGET",
    "serving.queue_depth": "SRML_TPU_SERVING_QUEUE_DEPTH",
    "serving.request_timeout_s": "SRML_TPU_SERVING_REQUEST_TIMEOUT_S",
    "serving.replicas": "SRML_TPU_SERVING_REPLICAS",
    "serving.hedge_after_p99_frac": "SRML_TPU_SERVING_HEDGE_AFTER_P99_FRAC",
    "serving.heartbeat_timeout_s": "SRML_TPU_SERVING_HEARTBEAT_TIMEOUT_S",
    "ann.build_batch_rows": "SRML_TPU_ANN_BUILD_BATCH_ROWS",
    "ann.prefetch_depth": "SRML_TPU_ANN_PREFETCH_DEPTH",
    "ann.list_bucket_rows": "SRML_TPU_ANN_LIST_BUCKET_ROWS",
    "ann.compact_tombstone_pct": "SRML_TPU_ANN_COMPACT_TOMBSTONE_PCT",
    "ann.index_cache_bytes": "SRML_TPU_ANN_INDEX_CACHE_BYTES",
    "ingest.zero_copy": "SRML_TPU_INGEST_ZERO_COPY",
    "ingest.staging_pool_rows": "SRML_TPU_INGEST_STAGING_POOL_ROWS",
    "pipeline.fuse": "SRML_TPU_PIPELINE_FUSE",
    "pipeline.fuse_min_rows": "SRML_TPU_PIPELINE_FUSE_MIN_ROWS",
    "partition.feature_axis": "SRML_TPU_PARTITION_FEATURE_AXIS",
    "partition.batch_rows_per_process": "SRML_TPU_PARTITION_BATCH_ROWS_PER_PROCESS",
    "continual.decay": "SRML_TPU_CONTINUAL_DECAY",
    "continual.update_batch_rows": "SRML_TPU_CONTINUAL_UPDATE_BATCH_ROWS",
    "continual.drift_mads": "SRML_TPU_CONTINUAL_DRIFT_MADS",
    "continual.promote_every": "SRML_TPU_CONTINUAL_PROMOTE_EVERY",
    "continual.min_baseline": "SRML_TPU_CONTINUAL_MIN_BASELINE",
    "tracing.enabled": "SRML_TPU_TRACING_ENABLED",
    "tracing.sample_rate": "SRML_TPU_TRACING_SAMPLE_RATE",
    "tracing.ring_traces": "SRML_TPU_TRACING_RING_TRACES",
    "tracing.slow_frac": "SRML_TPU_TRACING_SLOW_FRAC",
    "autotune.mode": "SRML_TPU_AUTOTUNE_MODE",
    "autotune.dir": "SRML_TPU_TUNE_DIR",
    "autotune.replicates": "SRML_TPU_AUTOTUNE_REPLICATES",
    "autotune.noise_mads": "SRML_TPU_AUTOTUNE_NOISE_MADS",
}

_overrides: Dict[str, Any] = {}


def _coerce(key: str, raw: str) -> Any:
    default = _DEFAULTS[key]
    if isinstance(default, bool) or key in ("fallback.enabled", "float32_inputs", "verbose"):
        return raw.strip().lower() in ("1", "true", "yes", "on")
    if isinstance(default, int) or key in ("num_workers", "observability.http_port"):
        return int(raw)
    if isinstance(default, float) or key == "reliability.deadline_s":
        return float(raw)
    return raw


def get(key: str) -> Any:
    """Resolution order: programmatic set() > environment > default."""
    if key not in _DEFAULTS:
        raise KeyError(f"Unknown config key '{key}'; known: {sorted(_DEFAULTS)}")
    if key in _overrides:
        return _overrides[key]
    env = os.environ.get(_ENV_KEYS[key])
    if env is not None and env != "":
        return _coerce(key, env)
    return _DEFAULTS[key]


def source(key: str) -> str:
    """Where `get(key)` currently resolves from: 'set' (programmatic
    override), 'env', or 'default'. The autotuner's tuning tables slot in
    BETWEEN env and default (docs/design.md §6i): a knob's table entry is
    consulted only when this returns 'default' — set() and env always win."""
    if key not in _DEFAULTS:
        raise KeyError(f"Unknown config key '{key}'; known: {sorted(_DEFAULTS)}")
    if key in _overrides:
        return "set"
    env = os.environ.get(_ENV_KEYS[key])
    if env is not None and env != "":
        return "env"
    return "default"


_epoch = 0


def epoch() -> int:
    """Monotonic mutation counter, bumped by every set()/unset(). Hot paths
    (the trace plane's per-request config reads) cache derived values
    against it instead of re-resolving per call. Mutating os.environ
    directly without a set()/unset() in between does NOT bump it — export
    env before process start, or go through set()."""
    return _epoch


def set(key: str, value: Any) -> None:  # spark-conf style name (shadows the builtin deliberately)
    global _epoch
    if key not in _DEFAULTS:
        raise KeyError(f"Unknown config key '{key}'; known: {sorted(_DEFAULTS)}")
    _overrides[key] = value
    _epoch += 1


def unset(key: str) -> None:
    global _epoch
    _overrides.pop(key, None)
    _epoch += 1


def all() -> Dict[str, Any]:  # spark-conf style name (shadows the builtin deliberately)
    return {k: get(k) for k in _DEFAULTS}
