#
# Online serving plane (docs/design.md §7): a driver-resident inference server
# for trained models — async dynamic micro-batching, bucketed shape padding
# with AOT pre-warm, an HBM-resident multi-tenant model registry, and HTTP
# endpoints mounted on the live telemetry plane's server.
#
#   batcher.py    per-model request queue + dispatcher thread: latency/size
#                 cutoffs, power-of-two row buckets, per-request scatter,
#                 client deadlines (batch-close expiry), drain-rate
#                 Retry-After hints, dispatcher heartbeats
#   registry.py   HBM-resident model registry over ops/device_cache.py
#                 (pin-while-serving, LRU eviction, transparent reloads) +
#                 bucketed AOT pre-warm through compiled_kernel
#   fleet.py      fault-tolerant replica fleet (serving.replicas > 1): health
#                 state machine, failover replay, hedging, restart-from-
#                 pinned-weights with zero warm-path compiles (§7c)
#   router.py     health-weighted least-outstanding routing + per-tenant fair
#                 admission + bounded shedding for the fleet
#   http.py       lifecycle (start_serving/stop_serving, ServingRun scope) +
#                 the /v1/ mount on observability/server.py
#
# Quick start:
#
#   from spark_rapids_ml_tpu import serving
#   serving.start_serving(port=0)                  # ephemeral loopback port
#   serving.register_model("km", fitted_kmeans)    # uploads + pre-warms
#   out = serving.predict("km", X_block)           # in-process
#   # or: curl -X POST http://127.0.0.1:<port>/v1/models/km:predict \
#   #          -d '{"instances": [[...], ...]}'
#   report = serving.stop_serving()                # serving_reports.jsonl
#

from .batcher import (
    DeadlineExpired,
    MicroBatcher,
    QueueFull,
    RequestTooLarge,
    ServingError,
    bucket_rows,
    bucket_table,
    pad_to_bucket,
)
from .fleet import ReplicaFleet, resolve_replicas
from .router import NoLiveReplicas, Router
from .http import (
    MOUNT_PREFIX,
    ServingRun,
    get_registry,
    mutate_model,
    predict,
    refresh_model,
    register_model,
    serving_address,
    serving_summary,
    start_serving,
    stop_serving,
    submit,
    unregister_model,
)
from .registry import ModelRegistry

__all__ = [
    "DeadlineExpired",
    "MOUNT_PREFIX",
    "MicroBatcher",
    "ModelRegistry",
    "NoLiveReplicas",
    "QueueFull",
    "ReplicaFleet",
    "RequestTooLarge",
    "Router",
    "ServingError",
    "ServingRun",
    "bucket_rows",
    "bucket_table",
    "resolve_replicas",
    "get_registry",
    "pad_to_bucket",
    "predict",
    "mutate_model",
    "refresh_model",
    "register_model",
    "serving_address",
    "serving_summary",
    "start_serving",
    "stop_serving",
    "submit",
    "unregister_model",
]
