#
# Fleet router — the admission + replica-selection half of the fault-tolerant
# serving tier (docs/design.md §7c; serving/fleet.py is the replica/health
# half).
#
# Three jobs, all bounded:
#
#   * ROUTING: health-weighted least-outstanding-requests. Every routable
#     replica (LIVE or DEGRADED — the fleet decides, the router only asks
#     `replica.routable()`) is scored by its in-flight + queued load times a
#     health weight (DEGRADED replicas cost more, so traffic drains away from
#     a replica that has started failing before it is declared DEAD); the
#     cheapest replica wins. Index-ordered tie-break keeps routing
#     deterministic under equal load.
#
#   * ADMISSION with per-tenant fairness: total outstanding work is capped at
#     `serving.queue_depth` across the whole fleet, and within that cap each
#     ACTIVE tenant (one with work in flight) is held to an equal share — a
#     tenant flooding the queue sheds against its own share, not against the
#     other tenants' latency. Untagged requests pool under the "-" tenant.
#
#   * BOUNDED SHEDDING: every rejection is a `QueueFull` carrying a
#     `retry_after_s` derived from the fleet's aggregate EMA drain rate (the
#     HTTP surface turns it into 429 + `Retry-After`), never an unbounded
#     queue or a bare reject. With no routable replica at all the router
#     raises `NoLiveReplicas` (503 + `Retry-After`) — distinct from
#     backpressure because the right client reaction differs: back off versus
#     fail over to another serving endpoint.
#

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import config as _config
from ..observability.runs import counter_inc
from .batcher import QueueFull, ServingError


class NoLiveReplicas(ServingError):
    """No LIVE or DEGRADED replica can take the request (all DEAD or
    RECOVERING). Maps to HTTP 503 + Retry-After: the condition is expected to
    clear as soon as the health monitor finishes a restart."""

    def __init__(self, msg: str, retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class Router:
    """Routing + admission over a fleet's replica list. The replica objects
    are duck-typed: `index`, `routable()`, `health_weight()`, `outstanding`,
    and `batcher` (for queued depth + drain rate) is all the router reads —
    it never imports the fleet, so the two halves stay cycle-free."""

    def __init__(self, name: str, replicas: Sequence[Any]):
        self._name = name
        self._replicas = replicas
        self._lock = threading.Lock()
        self._tenant_outstanding: Dict[str, int] = {}

    # ---------------------------------------------------------------- routing

    def pick(self, exclude: Tuple[int, ...] = ()) -> Optional[Any]:
        """The cheapest routable replica by health-weighted load, or None.
        Load = in-flight + still-queued requests; weight grows for DEGRADED
        replicas so they shed traffic while they still count as capacity."""
        best = None
        best_cost: Optional[float] = None
        for rep in self._replicas:
            if rep.index in exclude or not rep.routable():
                continue
            load = rep.outstanding + rep.batcher.pending()
            cost = (load + 1) * rep.health_weight()
            if best_cost is None or cost < best_cost:
                best, best_cost = rep, cost
        return best

    def has_routable(self, exclude: Tuple[int, ...] = ()) -> bool:
        return self.pick(exclude) is not None

    # -------------------------------------------------------------- admission

    def _fleet_retry_after_s(self) -> float:
        """Aggregate Retry-After hint: total backlog over the summed EMA
        drain rate of every routable replica, clamped like the per-batcher
        hint. Falls back to one latency-cutoff interval pre-history."""
        backlog = 0
        rate = 0.0
        for rep in self._replicas:
            backlog += rep.outstanding + rep.batcher.pending()
            if rep.routable():
                rate += rep.batcher.drain_rate() or 0.0
        if rate <= 0:
            return max(
                float(_config.get("serving.max_wait_ms")) / 1000.0, 0.05
            )
        return float(min(max(backlog / rate, 0.05), 30.0))

    def admit(self, tenant: str) -> None:
        """Admission control, called before dispatch. Raises QueueFull (with
        the drain-rate Retry-After) when the fleet-wide cap or this tenant's
        fair share is spent; on success the tenant's outstanding count is
        charged (release() refunds it exactly once per request)."""
        depth = int(_config.get("serving.queue_depth"))
        with self._lock:
            total = sum(self._tenant_outstanding.values())
            if total >= depth:
                counter_inc("serving.shed_total", 1, model=self._name)
                raise QueueFull(
                    f"fleet '{self._name}' is saturated "
                    f"({total} outstanding >= serving.queue_depth={depth})",
                    retry_after_s=self._fleet_retry_after_s(),
                )
            active = sum(1 for v in self._tenant_outstanding.values() if v > 0)
            if self._tenant_outstanding.get(tenant, 0) <= 0:
                active += 1  # this request would activate the tenant
            share = max(1, depth // max(1, active))
            if self._tenant_outstanding.get(tenant, 0) >= share:
                counter_inc("serving.shed_total", 1, model=self._name)
                counter_inc(
                    "serving.tenant_shed", 1, model=self._name, tenant=tenant,
                )
                raise QueueFull(
                    f"tenant '{tenant}' exceeded its fair share of fleet "
                    f"'{self._name}' ({share} of {depth} slots across "
                    f"{active} active tenants)",
                    retry_after_s=self._fleet_retry_after_s(),
                )
            self._tenant_outstanding[tenant] = (
                self._tenant_outstanding.get(tenant, 0) + 1
            )

    def release(self, tenant: str) -> None:
        """Refund one admitted request (terminal resolution — success, final
        failure, or shed after admission)."""
        with self._lock:
            left = self._tenant_outstanding.get(tenant, 0) - 1
            if left > 0:
                self._tenant_outstanding[tenant] = left
            else:
                self._tenant_outstanding.pop(tenant, None)

    def no_live(self) -> NoLiveReplicas:
        counter_inc("serving.no_live_replicas", 1, model=self._name)
        return NoLiveReplicas(
            f"fleet '{self._name}' has no live replica (all dead or "
            "recovering); retry shortly",
            retry_after_s=max(
                float(_config.get("serving.heartbeat_timeout_s")), 0.05
            ),
        )

    # ------------------------------------------------------------------ views

    def tenants(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._tenant_outstanding)


__all__: List[str] = ["NoLiveReplicas", "Router"]
