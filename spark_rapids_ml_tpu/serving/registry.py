#
# Multi-tenant model registry with HBM residency — the model-weights half of
# the serving plane (docs/design.md §7).
#
# Every predict kernel takes the fitted weight arrays as operands. Called with
# host numpy attributes (the batch-transform path), jax re-uploads them on
# every dispatch; at serving rates that is a host->device weight transfer per
# micro-batch. This registry uploads a model's device-consumed attributes ONCE
# at registration and keeps them HBM-resident in an eviction-aware,
# pin-while-serving extension of the HBM batch cache (ops/device_cache.py —
# the same budget/LRU/gauge machinery that already backs multi-pass fits):
#
#   * key = ("serving_model", name), one entry holding the device tuple;
#   * budget `serving.hbm_budget_bytes`: registering more hot models than fit
#     evicts the least-recently-served model's weights (LRU across entries);
#   * a model PINNED by an in-flight batch is never evicted
#     (DeviceBatchCache.pin/unpin; skipped evictions count
#     `cache.evict_skipped_pinned`);
#   * a cold (evicted) model reloads transparently on its next batch, counted
#     as `serving.model_reloads{model=}`.
#
# During a batch the device arrays are installed into the model's attribute
# dict (the predict kernels read attributes — reused un-forked) and the host
# originals restored afterwards, so the CACHE stays the only long-lived holder
# of device memory: eviction actually frees HBM.
#
# Registration also performs the bucketed AOT pre-warm: one predict execution
# per (model, bucket) through the existing `compiled_kernel` cache
# (observability/device.py), so every shape the batcher can emit is compiled
# before the first request and `device.compile{kernel=}` stays flat in steady
# state (CI-asserted).
#

from __future__ import annotations

import copy
import itertools
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import config as _config
from ..observability import tracing as _tracing
from ..observability.inference import (
    bucketed_signatures,
    suppress_transform_runs,
)
from ..observability.runs import counter_inc, gauge_set, observe, span
from ..ops.device_cache import DeviceBatchCache
from ..reliability.chaos import chaos_point
from ..reliability.faults import fault_point
from ..utils import get_logger
from .batcher import MicroBatcher, ServingError, bucket_table, pad_to_bucket
from .fleet import ReplicaFleet, ReplicaHandle, resolve_replicas

_logger = get_logger("serving.registry")


def _upload_attrs(entry: "_ServedModel") -> Tuple[Any, ...]:
    """Host->device weight upload for one entry, honoring its pinned device
    group (TPU fleets) or the default device (everything else)."""
    import jax
    import jax.numpy as jnp

    if entry.devices and entry.devices[0].platform == "tpu":
        dev = entry.devices[0]
        return tuple(
            jax.device_put(entry.host_attrs[n], dev) for n in entry.attr_names
        )
    return tuple(jnp.asarray(entry.host_attrs[n]) for n in entry.attr_names)


class _ServedModel:
    """One registered model: the live model object, host copies of its
    device-consumed attributes, the bucket table, and its micro-batcher."""

    def __init__(self, name: str, model: Any, attr_names: Tuple[str, ...],
                 n_cols: int, buckets: Tuple[int, ...],
                 devices: Optional[Tuple[Any, ...]] = None):
        self.name = name
        self.model = model
        self.attr_names = attr_names
        self.n_cols = int(n_cols)
        self.buckets = buckets
        # partitioner-drawn device group this entry's weight stream pins to
        # (fleet replicas; None = default device). Pinning engages on TPU
        # only: per-device executables are the price of real failure domains
        # there, while the CPU/emulated fleet keeps the shared default device
        # so replica pre-warms stay zero-compile (the §7c CI assertion)
        self.devices = devices
        self.cache_key = ("serving_model", name)
        # host originals: the reload source after eviction, and what the
        # model's attribute dict holds between batches
        self.host_attrs: Dict[str, Any] = {
            n: model._model_attributes[n] for n in attr_names
        }
        self.nbytes = int(sum(
            int(getattr(v, "nbytes", 0)) for v in self.host_attrs.values()
        ))
        self.uploads = 0
        self.reloads = 0
        # whether the last upload was RETAINED by the cache: a reload is a
        # re-upload after eviction; a model whose weights never fit the
        # budget streams every batch and must not masquerade as "reloading"
        self.was_cached = False
        self.warm: set = set()
        self.registered_ts = time.time()
        # monotone weight-version ordinal: bumped on EVERY refresh_weights
        # (so mutate_model and continual promotions too — both land through
        # refresh), exported as `serving.model_generation{model=}` and in
        # stats()/`/v1/models/<name>` — the audit key joining a promotion
        # event to the serving reports that observed its weights
        self.generation = 0
        self.batcher: Optional[MicroBatcher] = None
        # fault-tolerant fleet mode (serving.replicas > 1): the fleet replaces
        # the single batcher; this entry becomes the PINNED MASTER copy —
        # the host-attr + resident-weight source every replica (re)spawns
        # from — and replica_entries holds the per-replica clone entries
        self.fleet: Optional[ReplicaFleet] = None
        self.replica_entries: Dict[int, "_ServedModel"] = {}
        # request ordinal for the single-dispatcher serving_dispatch site
        # (the fleet keeps its own ordinal)
        self.dispatch_seq = itertools.count()
        # serializes the dispatcher's install->predict->restore window against
        # model mutation + weight refresh (§7b): an add/delete landing while
        # device arrays are installed would either raise (read-only views) or
        # be silently stomped by the restore
        self.exec_lock = threading.Lock()


class ModelRegistry:
    """Thread-safe registry of served models. One instance per serving
    session; `serving/http.py` owns the default process instance."""

    def __init__(self, hbm_budget_bytes: Optional[int] = None):
        budget = int(
            hbm_budget_bytes
            if hbm_budget_bytes is not None
            else _config.get("serving.hbm_budget_bytes")
        )
        self._cache = DeviceBatchCache(max(budget, 0))
        # DeviceBatchCache is single-owner by contract; the registry is the
        # owner and serializes access across per-model dispatcher threads
        self._cache_lock = threading.Lock()
        self._lock = threading.RLock()
        self._models: Dict[str, _ServedModel] = {}

    # ----------------------------------------------------------- registration

    def register(self, name: str, model: Any,
                 prewarm: Optional[bool] = None) -> Dict[str, Any]:
        """Serve `model` under `name`: validate servability, upload weights to
        HBM, pre-warm one executable per bucket, start the dispatcher thread.
        Returns the model's stats view. Re-registering a name replaces the
        previous model (its batcher drains first)."""
        if not hasattr(model, "_serving_predict"):
            raise ServingError(
                f"{type(model).__name__} is not a servable model"
            )
        if not model._serving_row_independent():
            raise ServingError(
                f"{type(model).__name__} predictions are not row-independent "
                "(the transform is a function of the whole query set); it "
                "cannot be served through the micro-batcher"
            )
        n_cols = model.n_cols
        if not n_cols:
            raise ServingError(
                f"cannot infer the feature width of {type(model).__name__}; "
                "is the model fitted?"
            )
        attr_names = tuple(
            n for n in model._serving_device_attrs()
            if n in model._model_attributes
            and model._model_attributes[n] is not None
        )
        entry = _ServedModel(
            name, model, attr_names, n_cols, bucket_table()
        )
        if entry.nbytes > int(self._cache.budget_bytes):
            # it still serves — but every batch re-uploads the weights, the
            # exact per-batch cost residency exists to remove; say so once
            _logger.warning(
                "model '%s' weights (%.1f MiB) exceed serving.hbm_budget_"
                "bytes (%.1f MiB); it will stream weights on every batch "
                "(counted as serving.weight_streams)",
                name, entry.nbytes / 2**20,
                self._cache.budget_bytes / 2**20,
            )
        old = None
        with self._lock:
            # one dispatcher per MODEL OBJECT: two entries sharing one model
            # would interleave install/restore on the same attribute dict and
            # leave device arrays installed permanently (pin/evict contract).
            # Re-registering the same name (replacement) is fine.
            dup = next(
                (e.name for e in self._models.values()
                 if e.model is model and e.name != name),
                None,
            )
            if dup is None:
                old = self._models.pop(name, None)
        if dup is not None:
            raise ServingError(
                f"this model object is already served as '{dup}'; "
                "register a separate copy to serve it under a second name"
            )
        if old is not None:
            self._retire(old)
        with self._cache_lock:
            self._ensure_resident(entry)
        do_warm = (
            bool(_config.get("serving.prewarm")) if prewarm is None else prewarm
        )
        n_replicas = resolve_replicas()
        if n_replicas > 1:
            # fault-tolerant fleet (docs/design.md §7c): the parent entry
            # stays the pinned master (host attrs + resident device tuple —
            # what dead replicas restart from); each replica serves its own
            # clone with its own weight stream and dispatcher. Replica
            # pre-warms replay through the process-wide compiled-kernel
            # cache, so replicas beyond the first — and every recovery
            # respawn — add zero compiles.
            entry.fleet = ReplicaFleet(
                name, n_cols, n_replicas,
                spawn=lambda i, devices=None, _e=entry, _w=do_warm:
                    self._spawn_replica(_e, i, _w, devices),
                retire=lambda i, _e=entry: self._drop_replica(_e, i),
            )
        else:
            if do_warm:
                self._prewarm(entry)
            entry.batcher = MicroBatcher(
                name, n_cols,
                execute=lambda stage, n_valid, _e=entry: self._predict_padded(
                    _e, stage
                ),
                warm_buckets=entry.warm,
            )
        with self._lock:
            self._models[name] = entry
            gauge_set("serving.models", len(self._models))
        counter_inc("serving.registered", 1, model=name)
        _logger.info(
            "serving model '%s' (%s, %d cols, %.1f KiB weights, buckets %s, "
            "%d replica%s)",
            name, type(model).__name__, n_cols, entry.nbytes / 1024,
            list(entry.buckets), n_replicas, "s" if n_replicas != 1 else "",
        )
        return self.stats(name)

    def unregister(self, name: str) -> bool:
        with self._lock:
            entry = self._models.pop(name, None)
            gauge_set("serving.models", len(self._models))
        if entry is None:
            return False
        self._retire(entry)
        return True

    def _retire(self, entry: _ServedModel) -> None:
        if entry.fleet is not None:
            # close() joins every replica dispatcher and calls our retire
            # callback per replica, dropping each clone's weight stream
            entry.fleet.close()
        if entry.batcher is not None:
            entry.batcher.stop()
        with self._cache_lock:
            self._cache.drop_stream(entry.cache_key)

    # ---------------------------------------------------------- fleet replicas

    def _spawn_replica(self, parent: _ServedModel, index: int,
                       do_warm: bool,
                       devices: Optional[Tuple[Any, ...]] = None) -> ReplicaHandle:
        """Fleet spawn callback: build replica `index` of a served model from
        the parent's CURRENT pinned weights — shallow model clone with its own
        attribute dict (install/restore never crosses replicas), its own HBM
        weight stream, and the full bucketed AOT pre-warm (cache hits after
        the first replica's compile, so respawn adds zero compiles)."""
        clone = copy.copy(parent.model)
        clone._model_attributes = dict(parent.model._model_attributes)
        attr_names = tuple(
            n for n in clone._serving_device_attrs()
            if n in clone._model_attributes
            and clone._model_attributes[n] is not None
        )
        rentry = _ServedModel(
            f"{parent.name}#r{index}", clone, attr_names,
            parent.n_cols, parent.buckets, devices=devices,
        )
        with self._cache_lock:
            self._ensure_resident(rentry)
        if do_warm:
            self._prewarm(rentry)
            parent.warm.update(rentry.warm)
        parent.replica_entries[index] = rentry
        return ReplicaHandle(
            execute=lambda stage, n_valid, _e=rentry, _p=parent:
                self._predict_padded(_e, stage, gen_entry=_p),
            warm=rentry.warm,
        )

    def _drop_replica(self, parent: _ServedModel, index: int) -> None:
        """Fleet retire callback: free a (dead or closing) replica's HBM
        weight stream. The parent master entry is untouched."""
        rentry = parent.replica_entries.pop(index, None)
        if rentry is None:
            return
        with self._cache_lock:
            self._cache.drop_stream(rentry.cache_key)

    def _resync_replica(self, parent: _ServedModel,
                        rentry: _ServedModel) -> None:
        """Propagate a parent mutation/refresh into one live replica: re-clone
        the attribute dict, re-derive the device attr set, and swap the
        replica's cached device tuple in place (replace() keeps in-flight
        pins, exactly like the parent refresh path)."""
        with rentry.exec_lock:
            rentry.model._model_attributes = dict(
                parent.model._model_attributes
            )
            rentry.attr_names = tuple(
                n for n in rentry.model._serving_device_attrs()
                if n in rentry.model._model_attributes
                and rentry.model._model_attributes[n] is not None
            )
            rentry.host_attrs = {
                n: rentry.model._model_attributes[n]
                for n in rentry.attr_names
            }
            rentry.nbytes = int(sum(
                int(getattr(v, "nbytes", 0))
                for v in rentry.host_attrs.values()
            ))
            with self._cache_lock:
                tup = _upload_attrs(rentry)
                rentry.uploads += 1
                rentry.was_cached = self._cache.replace(
                    rentry.cache_key, 0, tup
                )

    def close(self) -> None:
        """Unregister everything (serving session teardown): every dispatcher
        thread joined, every weight entry dropped, HBM gauge back to zero."""
        with self._lock:
            entries = list(self._models.values())
            self._models.clear()
            gauge_set("serving.models", 0)
        for entry in entries:
            self._retire(entry)

    # -------------------------------------------------------------- residency

    def _ensure_resident(self, entry: _ServedModel) -> Tuple[Any, ...]:
        """The model's device weight tuple, uploading (and counting a reload
        when this is not the first upload) if evicted. Caller holds
        _cache_lock."""
        tup = self._cache.get(entry.cache_key, 0)
        if tup is not None:
            return tup
        tup = _upload_attrs(entry)
        entry.uploads += 1
        if entry.was_cached:
            # it WAS resident and is gone: a genuine eviction-driven reload
            entry.reloads += 1
            counter_inc("serving.model_reloads", 1, model=entry.name)
        else:
            # never retained (budget too small / pinned pressure): this is a
            # per-batch weight stream, not a reload — count it as such
            if entry.uploads > 1:
                counter_inc("serving.weight_streams", 1, model=entry.name)
        entry.was_cached = self._cache.put(entry.cache_key, 0, tup)
        return tup

    def resident(self, name: str) -> bool:
        entry = self._entry(name)
        with self._cache_lock:
            return self._cache.contains(entry.cache_key, 0)

    def refresh_weights(self, name: str) -> Dict[str, Any]:
        """Re-sync a served model's HBM weights after an in-place mutation
        (the ANN lifecycle's incremental add/delete, docs/design.md §7b): the
        host attribute dict is re-snapshotted and the cached device tuple is
        dropped, so the NEXT batch re-uploads current weights. One upload, no
        dispatcher restart — and because the incremental tier mutates within
        a BUCKETED geometry, the refreshed weights keep every operand shape,
        so no new executable is compiled and no re-warm is needed. Counted as
        `serving.weight_refreshes{model=}`. Returns the model's stats view."""
        import jax.numpy as jnp

        entry = self._entry(name)
        # exec_lock: the re-derive/re-snapshot must not interleave with a
        # dispatcher batch's install->restore (a batch could otherwise zip a
        # refreshed attr_names against a stale device tuple)
        with entry.exec_lock:
            # re-derive the device attr set, not just the values: a mutation
            # can INTRODUCE device operands (enable_incremental/delete_items
            # create item_valid) that registration never saw — freezing
            # attr_names would leave the new mask streaming host->device on
            # every batch
            entry.attr_names = tuple(
                n for n in entry.model._serving_device_attrs()
                if n in entry.model._model_attributes
                and entry.model._model_attributes[n] is not None
            )
            entry.host_attrs = {
                n: entry.model._model_attributes[n] for n in entry.attr_names
            }
            entry.nbytes = int(sum(
                int(getattr(v, "nbytes", 0))
                for v in entry.host_attrs.values()
            ))
            with self._cache_lock:
                # replace(), not drop_stream(): in-flight batches may hold
                # pins on this stream — the swap keeps their pin counts, so
                # the fresh weights stay eviction-proof mid-batch. A refresh
                # is neither an eviction-driven reload nor a budget-starved
                # weight stream — it gets its own counter.
                tup = tuple(
                    jnp.asarray(entry.host_attrs[n]) for n in entry.attr_names
                )
                entry.uploads += 1
                entry.was_cached = self._cache.replace(entry.cache_key, 0, tup)
        if entry.fleet is not None:
            for rentry in list(entry.replica_entries.values()):
                self._resync_replica(entry, rentry)
        entry.generation += 1
        gauge_set("serving.model_generation", entry.generation, model=name)
        counter_inc("serving.weight_refreshes", 1, model=name)
        return self.stats(name)

    def mutate(self, name: str, fn) -> Dict[str, Any]:
        """Apply an in-place mutation to a LIVE served model safely:
        `fn(model)` runs under the entry's execution lock (no dispatcher
        batch is mid-install), then the HBM weights refresh. THE supported
        way to drive the §7b incremental add/delete APIs against a model
        that is actively serving — calling model.add_items() directly on a
        served model races the dispatcher's install/restore window."""
        entry = self._entry(name)
        with entry.exec_lock:
            fn(entry.model)
        return self.refresh_weights(name)

    def _predict_padded(self, entry: _ServedModel, stage: np.ndarray,
                        gen_entry: Optional[_ServedModel] = None
                        ) -> Dict[str, np.ndarray]:
        """Run one padded bucket through the model's predict path with the
        HBM-resident weights installed. The entry is PINNED for the duration:
        budget pressure from other models' uploads cannot evict weights an
        in-flight batch references. `gen_entry` names the entry whose
        `generation` answers for this batch (the parent master in fleet mode
        — replica clones keep generation 0); it lands as a thread-local batch
        annotation the calling dispatcher's trace plumbing picks up."""
        gen = gen_entry if gen_entry is not None else entry
        _tracing.annotate_batch(generation=gen.generation)
        with self._cache_lock:
            self._cache.pin(entry.cache_key)
            tup = self._ensure_resident(entry)
        try:
            # exec_lock: no mutation (registry.mutate / refresh_weights) may
            # interleave with the install->predict->restore window below
            with entry.exec_lock:
                saved = {
                    n: entry.model._model_attributes[n]
                    for n in entry.attr_names
                }
                entry.model._model_attributes.update(
                    zip(entry.attr_names, tup)
                )
                try:
                    # no nested TransformRun per batch (the ServingRun is the
                    # scope; predict_dispatch counters/spans still fan out),
                    # and the bucket-table signatures are storm-exempt — a
                    # finite bucket set is the fix the sentinel recommends
                    with suppress_transform_runs(), bucketed_signatures():
                        outputs = entry.model._serving_predict(stage)
                finally:
                    entry.model._model_attributes.update(saved)
            return {k: np.asarray(v) for k, v in outputs.items()}
        finally:
            with self._cache_lock:
                self._cache.unpin(entry.cache_key)

    # ---------------------------------------------------------------- prewarm

    def _prewarm(self, entry: _ServedModel) -> None:
        """Compile one executable per (model, bucket) up front: run the predict
        path on a synthetic batch of each bucket shape through the
        compiled_kernel AOT cache. All-ones features — a valid, finite input
        for every family (zeros would trip cosine's zero-vector guard)."""
        for bucket in entry.buckets:
            stage = np.ones((bucket, entry.n_cols), np.float32)
            t0 = time.perf_counter()
            with span("serving.prewarm",
                      {"model": entry.name, "bucket": bucket}):
                self._predict_padded(entry, stage)
            observe(
                "serving.prewarm_s", time.perf_counter() - t0,
                model=entry.name,
            )
            entry.warm.add(bucket)

    # ------------------------------------------------------------ client side

    def _entry(self, name: str) -> _ServedModel:
        with self._lock:
            entry = self._models.get(name)
        if entry is None:
            raise KeyError(f"no served model named '{name}'")
        return entry

    def models(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def submit(self, name: str, X: np.ndarray,
               deadline_ts: Optional[float] = None,
               tenant: Optional[str] = None,
               trace: Optional["_tracing.RequestTrace"] = None):
        """Enqueue one request; returns the Future of its output dict.
        `deadline_ts` is the client's absolute perf_counter() deadline (rides
        with the request — queue time counts against it); `tenant` feeds the
        fleet's fair admission (ignored in single-dispatcher mode, where
        there is one queue and no fairness to arbitrate). `trace` carries the
        caller's RequestTrace (HTTP ingress mints one); with no caller trace
        and tracing enabled, one is minted HERE and finished when the Future
        resolves — every request gets exactly one complete trace."""
        entry = self._entry(name)
        owns = False
        if trace is None:
            trace = _tracing.start_trace("serving.request", model=name)
            owns = trace is not None
        try:
            if entry.fleet is not None:
                fut = entry.fleet.submit(X, deadline_ts=deadline_ts,
                                         tenant=tenant, trace=trace)
            else:
                assert entry.batcher is not None
                seq = next(entry.dispatch_seq)
                fault_point("serving_dispatch", batch=seq)
                chaos_point("serving_dispatch", batch=seq)
                fut = entry.batcher.submit(X, deadline_ts=deadline_ts,
                                           trace=trace)
        except BaseException as e:
            if owns:
                trace.finish(status=type(e).__name__)
            raise
        if owns:
            _tracing.finish_future(trace, fut)
        return fut

    def predict(self, name: str, X: np.ndarray,
                timeout: Optional[float] = None,
                tenant: Optional[str] = None,
                trace: Optional["_tracing.RequestTrace"] = None
                ) -> Dict[str, np.ndarray]:
        """Blocking request: submit + wait (the in-process twin of the HTTP
        POST /v1/models/<name>:predict path). The timeout becomes the
        request's ABSOLUTE deadline, threaded into the queue: an overdue
        request expires at batch-close (DeadlineExpired) instead of being
        executed for a client that already hung up. The small grace on the
        Future wait lets that structured expiry win over a bare timeout."""
        if timeout is None:
            timeout = float(_config.get("serving.request_timeout_s"))
        deadline_ts = time.perf_counter() + float(timeout)
        fut = self.submit(name, X, deadline_ts=deadline_ts, tenant=tenant,
                          trace=trace)
        return fut.result(timeout=float(timeout) + 0.25)

    def generation(self, name: str) -> int:
        """Current weight-version ordinal of a served model — the value the
        HTTP surface echoes as `x-srml-generation` on every response."""
        return int(self._entry(name).generation)

    def stats(self, name: str) -> Dict[str, Any]:
        entry = self._entry(name)
        with self._cache_lock:
            is_resident = self._cache.contains(entry.cache_key, 0)
        if entry.fleet is not None:
            pending = entry.fleet.pending()
        else:
            pending = entry.batcher.pending() if entry.batcher else 0
        out = {
            "name": entry.name,
            "model": type(entry.model).__name__,
            "n_cols": entry.n_cols,
            "buckets": list(entry.buckets),
            "warm_buckets": sorted(entry.warm),
            "weight_bytes": entry.nbytes,
            "resident": is_resident,
            "uploads": entry.uploads,
            "reloads": entry.reloads,
            "pending": pending,
            "generation": entry.generation,
            "registered_ts": entry.registered_ts,
        }
        if entry.fleet is not None:
            out["replicas"] = entry.fleet.health_view()
            out["live_replicas"] = entry.fleet.live_count()
        return out

    def stats_all(self) -> List[Dict[str, Any]]:
        return [self.stats(name) for name in self.models()]


__all__ = [
    "ModelRegistry",
    "ServingError",
    "bucket_table",
    "pad_to_bucket",
]
