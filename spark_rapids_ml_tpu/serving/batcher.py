#
# Async dynamic micro-batcher — the request-coalescing half of the serving
# plane (docs/design.md §7).
#
# The Podracer architectures (arXiv:2104.06272) decouple request feeding from
# accelerator stepping: feed threads enqueue, the accelerator executes
# fixed-shape batched steps. This module is that split for model inference:
#
#   * HTTP handler threads (or in-process callers) `submit()` variable-size
#     requests and block on a Future;
#   * ONE dispatcher thread per served model drains the queue, closing a batch
#     when it reaches `serving.max_batch_rows` OR the oldest queued request
#     has waited `serving.max_wait_ms` (the latency/size cutoff pair);
#   * the coalesced rows are written into a REUSED per-bucket staging buffer,
#     padded to the power-of-two row bucket (padding rows replicate the last
#     real row — always a valid input, so cosine/normalization paths never see
#     a synthetic zero vector), executed ONCE through the model's predict
#     kernels, and per-request slices scatter back to the waiting futures.
#
# Because every executed shape is a bucket, the set of predict shape
# signatures is finite and pre-warmable: steady-state serving never compiles
# and the PR-4 recompile sentinel (`transform.recompile_storm`) cannot fire.
#
# Deadlines ride WITH the request (docs/design.md §7c): `submit()` takes the
# caller's absolute deadline, an already-expired request fails fast at submit,
# and a request whose deadline passes while queued is expired at batch-CLOSE
# time — never padded, dispatched, and then discarded (counted
# `serving.expired{model=}`). Backpressure is bounded and advisory: a full
# queue sheds with a `Retry-After` hint derived from the EMA drain rate
# (counted `serving.shed_total{model=}`), not a bare 429.
#
# Telemetry (all label-aware; `{model=}`, plus `{replica=}` when the batcher
# runs as a fleet replica): per-request `serving.queue_s` / `serving.total_s`
# histograms, per-batch `serving.pad_s` / `serving.execute_s`
# / `serving.batch_occupancy` (real rows / bucket rows — proof the batcher is
# actually coalescing), counters `serving.requests` / `serving.rows` /
# `serving.batches` / `serving.padded_rows` / `serving.errors` /
# `serving.bucket_hit` / `serving.bucket_miss` (pre-warmed bucket or not).
#

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import config as _config
from ..observability import tracing as _tracing
from ..observability.device import compiles_total as _compiles_total
from ..observability.device import kernel_cost as _kernel_cost
from ..observability.runs import counter_inc, observe, span
from ..reliability.faults import fault_point
from ..utils import get_logger

_logger = get_logger("serving.batcher")


class ServingError(RuntimeError):
    """Base class for request-rejection errors of the serving plane."""


class QueueFull(ServingError):
    """Backpressure: the per-model queue reached `serving.queue_depth`.
    Carries `retry_after_s` — the drain-rate-derived backoff hint the HTTP
    surface returns as a `Retry-After` header instead of a bare 429."""

    def __init__(self, msg: str, retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class RequestTooLarge(ServingError):
    """A single request exceeded `serving.max_batch_rows`."""


class DeadlineExpired(ServingError):
    """The request's client deadline passed before it could be dispatched
    (at submit, or while queued, checked at batch-close time). Deliberately
    NOT retryable: the client has already given up on the answer."""


def bucket_rows(n: int, min_rows: Optional[int] = None,
                max_rows: Optional[int] = None) -> int:
    """The power-of-two row bucket `n` pads to: smallest 2^i >= max(n,
    serving.bucket_min_rows), clamped to the bucket ceiling (the power of two
    covering serving.max_batch_rows). The bucket floor is a tuning-table knob
    (`serving.bucket_min_rows`, docs/design.md §6i) — resolved HERE, at
    registration/submit time, never inside a trace — so a platform can widen
    its pre-warmed bucket set by table entry; config set()/env still win."""
    if min_rows is None:
        from .. import autotune as _autotune

        tuned = _autotune.lookup("serving.bucket_min_rows")
        min_rows = (
            int(tuned) if tuned is not None
            else int(_config.get("serving.bucket_min_rows"))
        )
    if max_rows is None:
        max_rows = int(_config.get("serving.max_batch_rows"))
    n = max(int(n), max(int(min_rows), 1))
    bucket = 1 << (n - 1).bit_length()
    return min(bucket, 1 << (max(int(max_rows), 1) - 1).bit_length())


def bucket_table(min_rows: Optional[int] = None,
                 max_rows: Optional[int] = None) -> Tuple[int, ...]:
    """Every bucket the batcher can emit under the current config — the set
    registration pre-warms one executable for."""
    lo = bucket_rows(1, min_rows, max_rows)
    hi = bucket_rows(
        int(max_rows if max_rows is not None
            else _config.get("serving.max_batch_rows")),
        min_rows, max_rows,
    )
    out = []
    b = lo
    while b <= hi:
        out.append(b)
        b *= 2
    return tuple(out)


def pad_to_bucket(X: np.ndarray, bucket: int,
                  out: Optional[np.ndarray] = None) -> np.ndarray:
    """Pad a (n, d) float32 block to (bucket, d) by replicating the LAST real
    row (any real row is a valid model input; zeros would poison cosine /
    normalization paths). With `out` given, fills the reused staging buffer
    in place — steady-state serving allocates no per-batch host memory."""
    n = int(X.shape[0])
    if out is None:
        out = np.empty((bucket, X.shape[1]), np.float32)
    out[:n] = X
    if bucket > n:
        out[n:] = out[n - 1]
    return out


class _Request:
    __slots__ = ("X", "n_rows", "future", "enqueue_ts", "deadline_ts",
                 "trace")

    def __init__(self, X: np.ndarray, deadline_ts: Optional[float] = None,
                 trace: Optional["_tracing.RequestTrace"] = None):
        self.X = X
        self.n_rows = int(X.shape[0])
        self.future: "Future[Dict[str, np.ndarray]]" = Future()
        self.enqueue_ts = time.perf_counter()
        # absolute time.perf_counter() deadline, threaded from the client's
        # predict(..., timeout=) so queue time counts against the budget
        self.deadline_ts = deadline_ts
        # the request's causal trace (docs/design.md §6l), carried by
        # reference so queue/batch/execute/scatter spans land on it
        self.trace = trace


class MicroBatcher:
    """One served model's queue + dispatcher thread. `execute` is the bound
    predict closure the registry supplies (residency pin + padded predict);
    `warm_buckets` is the registry's set of pre-warmed bucket sizes (read-only
    here, used for the bucket_hit/bucket_miss counters). `labels` overrides
    the metric label set — the serving fleet runs one MicroBatcher per
    replica with `{"model": name, "replica": str(i)}` so every series splits
    per replica while still aggregating under the model label."""

    def __init__(self, name: str, n_cols: int,
                 execute: Callable[[np.ndarray, int], Dict[str, np.ndarray]],
                 warm_buckets: Optional[set] = None,
                 labels: Optional[Dict[str, str]] = None,
                 thread_suffix: str = ""):
        self.name = name
        self.n_cols = int(n_cols)
        self._execute = execute
        self.warm_buckets = warm_buckets if warm_buckets is not None else set()
        self.labels: Dict[str, str] = (
            dict(labels) if labels is not None else {"model": name}
        )
        self._queue: "deque[_Request]" = deque()
        self._cond = threading.Condition()
        self._stop = False
        self._staging: Dict[int, np.ndarray] = {}
        # dispatcher liveness: last_beat is stamped by the dispatcher loop on
        # every wakeup, so a thread hung inside execute (or dead) goes stale
        # and the fleet's health monitor can declare it within
        # serving.heartbeat_timeout_s. Drain-rate EMA feeds Retry-After.
        self.last_beat = time.perf_counter()
        self._drain_rate: Optional[float] = None  # requests/s, EMA
        self._last_drain_ts = time.perf_counter()
        self.batches_done = 0  # execute ordinal (the serving_execute site)
        self._thread = threading.Thread(
            target=self._loop,
            name=f"srml-serving-{name}{thread_suffix}", daemon=True,
        )
        self._thread.start()

    # ------------------------------------------------------------ client side

    def submit(self, X: np.ndarray,
               deadline_ts: Optional[float] = None,
               trace: Optional["_tracing.RequestTrace"] = None
               ) -> "Future[Dict[str, np.ndarray]]":
        """Enqueue one request; the returned Future resolves to this request's
        named output arrays (exactly `n_rows` leading rows each). A request
        whose `deadline_ts` has already passed fails fast HERE — it never
        occupies a queue slot it cannot use."""
        X = np.asarray(X, np.float32)
        if X.ndim == 1:
            X = X[None, :]
        if X.ndim != 2 or X.shape[1] != self.n_cols:
            raise ServingError(
                f"model '{self.name}' expects (n, {self.n_cols}) features; "
                f"got shape {tuple(X.shape)}"
            )
        if X.shape[0] < 1:
            raise ServingError("empty request (0 rows)")
        if X.shape[0] > int(_config.get("serving.max_batch_rows")):
            raise RequestTooLarge(
                f"request of {X.shape[0]} rows exceeds serving.max_batch_rows="
                f"{_config.get('serving.max_batch_rows')}; split it client-side"
            )
        if deadline_ts is not None and time.perf_counter() >= deadline_ts:
            counter_inc("serving.expired", 1, **self.labels)
            if trace is not None:
                trace.add_event("deadline_expired", at="submit", **self.labels)
            raise DeadlineExpired(
                f"request deadline expired before enqueue on '{self.name}'"
            )
        req = _Request(X, deadline_ts=deadline_ts, trace=trace)
        with self._cond:
            if self._stop:
                raise ServingError(f"model '{self.name}' is shutting down")
            if len(self._queue) >= int(_config.get("serving.queue_depth")):
                counter_inc("serving.rejected", 1, **self.labels)
                counter_inc("serving.shed_total", 1, **self.labels)
                raise QueueFull(
                    f"model '{self.name}' queue is full "
                    f"(serving.queue_depth={_config.get('serving.queue_depth')})",
                    retry_after_s=self.retry_after_s(locked=True),
                )
            self._queue.append(req)
            self._cond.notify()
        return req.future

    def pending(self) -> int:
        with self._cond:
            return len(self._queue)

    def heartbeat_age_s(self) -> float:
        """Seconds since the dispatcher loop last proved it was making
        progress — the fleet health monitor's staleness signal."""
        return time.perf_counter() - self.last_beat

    def alive(self) -> bool:
        return self._thread.is_alive() and not self._stop

    def drain_rate(self) -> Optional[float]:
        """EMA requests/second the dispatcher is completing (None until the
        first batch lands)."""
        return self._drain_rate

    def retry_after_s(self, locked: bool = False) -> float:
        """How long a shed client should wait before retrying: current queue
        depth over the EMA drain rate, clamped to a sane [0.05s, 30s] band.
        With no drain history yet, one latency-cutoff interval is the best
        available guess."""
        if locked:
            depth = len(self._queue)
        else:
            with self._cond:
                depth = len(self._queue)
        rate = self._drain_rate
        if not rate or rate <= 0:
            return max(float(_config.get("serving.max_wait_ms")) / 1000.0, 0.05)
        return float(min(max(depth / rate, 0.05), 30.0))

    def steal_pending(self) -> List[_Request]:
        """Pop every still-queued request. The fleet's failover path calls
        this on a replica declared DEAD so the stranded requests can be
        replayed onto surviving replicas instead of rotting in a queue no
        dispatcher will ever drain."""
        with self._cond:
            out = list(self._queue)
            self._queue.clear()
        return out

    def stop(self, timeout: float = 10.0) -> None:
        """Stop accepting requests, drain what is queued, join the thread."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout)

    # -------------------------------------------------------- dispatcher side

    def _loop(self) -> None:
        while True:
            self.last_beat = time.perf_counter()
            with self._cond:
                while not self._queue and not self._stop:
                    self._cond.wait(0.05)
                    self.last_beat = time.perf_counter()
                if not self._queue and self._stop:
                    return
                first = self._queue.popleft()
            self._run_batch(self._coalesce(first))

    def _coalesce(self, first: _Request) -> List[_Request]:
        """Drain until size or latency cutoff: the batch closes at
        max_batch_rows, or when the FIRST (oldest) request has waited
        max_wait_ms — later arrivals never extend the oldest request's wait."""
        batch = [first]
        rows = first.n_rows
        max_rows = int(_config.get("serving.max_batch_rows"))
        deadline = first.enqueue_ts + (
            float(_config.get("serving.max_wait_ms")) / 1000.0
        )
        while rows < max_rows:
            with self._cond:
                if self._queue and rows + self._queue[0].n_rows <= max_rows:
                    nxt = self._queue.popleft()
                    batch.append(nxt)
                    rows += nxt.n_rows
                    continue
                if self._queue:
                    break  # next request would overflow: close this batch
                remaining = deadline - time.perf_counter()
                if remaining <= 0 or self._stop:
                    break
                self._cond.wait(min(remaining, 0.05))
        return batch

    def _note_drain(self, n: int) -> None:
        """Fold `n` completed requests into the drain-rate EMA (dispatcher
        thread only; readers tolerate a torn float)."""
        now = time.perf_counter()
        dt = now - self._last_drain_ts
        self._last_drain_ts = now
        if dt <= 0:
            return
        inst = n / dt
        self._drain_rate = (
            inst if self._drain_rate is None
            else 0.8 * self._drain_rate + 0.2 * inst
        )

    def _expire_overdue(self, batch: List[_Request]) -> List[_Request]:
        """Batch-close deadline check: fail every request whose client
        deadline has already passed (the answer would be discarded anyway)
        and return the still-live remainder — expired rows are never padded
        or dispatched."""
        now = time.perf_counter()
        live: List[_Request] = []
        for r in batch:
            if r.deadline_ts is not None and now >= r.deadline_ts:
                counter_inc("serving.expired", 1, **self.labels)
                if r.trace is not None:
                    r.trace.add_span("serving.queue", r.enqueue_ts, now,
                                 parent_id=r.trace.root_span_id,
                                 attrs=dict(self.labels), status="expired")
                    r.trace.add_event("deadline_expired", at="batch_close",
                                      **self.labels)
                if r.future.set_running_or_notify_cancel():
                    r.future.set_exception(DeadlineExpired(
                        f"request deadline expired after "
                        f"{now - r.enqueue_ts:.3f}s in '{self.name}' queue"
                    ))
            else:
                live.append(r)
        return live

    def _trace_batch(self, traced: List[_Request], fan_in: List[Dict],
                     batch_sid: str, exec_sid: str, bnode: Any,
                     compiles0: int, anno: Dict[str, Any],
                     n: int, bucket: int,
                     t_start: float, t_padded: float, t_done: float) -> None:
        """Append the shared batch + execute spans to every member trace.
        The batch span is the fan-in point (links -> each member's root); the
        execute child joins the §6f kernel layer: executable signature,
        compile-vs-cached verdict, analyzed flops/bytes from the device plane
        attribution that landed on the `serving.batch` SpanNode."""
        batch_attrs: Dict[str, Any] = {
            "rows": n, "bucket": bucket,
            "occupancy": round(n / bucket, 6), **self.labels,
        }
        if anno:
            batch_attrs.update(anno)
        exec_attrs: Dict[str, Any] = {
            "compiled": _compiles_total() - compiles0,
        }
        dev = (bnode.attrs or {}).get("device") if bnode is not None else None
        if dev:
            for k in ("flops", "bytes", "comm_bytes", "calls",
                      "roofline", "intensity_flop_per_byte", "mfu"):
                if dev.get(k) is not None:
                    exec_attrs[k] = dev[k]
            kernels = dev.get("kernels") or {}
            if kernels:
                sigs = {}
                for kname in kernels:
                    rec = _kernel_cost(kname)
                    if rec is not None and rec.get("signature"):
                        sigs[kname] = rec["signature"]
                exec_attrs["kernels"] = dict(kernels)
                if sigs:
                    exec_attrs["signatures"] = sigs
        for r in traced:
            r.trace.add_span("serving.batch", t_start, t_done,
                         parent_id=r.trace.root_span_id,
                         attrs=batch_attrs, links=fan_in, span_id=batch_sid)
            r.trace.add_span("serving.execute", t_padded, t_done,
                         parent_id=batch_sid, attrs=exec_attrs,
                         span_id=exec_sid)
            if anno.get("generation") is not None:
                r.trace.add_event("model_generation",
                                  generation=anno["generation"],
                                  **self.labels)

    def _run_batch(self, batch: List[_Request]) -> None:
        n_closed = len(batch)
        batch = self._expire_overdue(batch)
        if not batch:
            self._note_drain(n_closed)
            return
        t_start = time.perf_counter()
        self.last_beat = t_start
        n = sum(r.n_rows for r in batch)
        for r in batch:
            observe("serving.queue_s", t_start - r.enqueue_ts, **self.labels)
        bucket = bucket_rows(n)
        # trace plumbing (§6l): members carrying a RequestTrace get a queue
        # span now; the micro-batch itself becomes ONE shared span (same
        # span_id across every member trace) with fan-in links to the N
        # request roots it coalesced — that link set is what attributes
        # padding/occupancy cost per request
        traced = [r for r in batch if r.trace is not None]
        batch_sid = _tracing.mint_span_id() if traced else None
        exec_sid = _tracing.mint_span_id() if traced else None
        fan_in = [
            {"trace_id": r.trace.trace_id, "span_id": r.trace.root_span_id}
            for r in traced
        ]
        for r in traced:
            # labels dict is frozen for the batcher's lifetime, so it is safe
            # to capture by reference (document() copies at export)
            r.trace.add_span("serving.queue", r.enqueue_ts, t_start,
                         parent_id=r.trace.root_span_id,
                         attrs=self.labels)
        compiles0 = _compiles_total() if traced else 0
        try:
            # the mid-batch failure site: an injected raise here fails exactly
            # this batch's futures (retryably, for OSError-class faults) and
            # the dispatcher loop carries on — the queue must never wedge
            b_ord = self.batches_done
            self.batches_done = b_ord + 1
            fault_point("serving_execute", batch=b_ord)
            stage = self._staging.get(bucket)
            if stage is None:
                stage = self._staging[bucket] = np.empty(
                    (bucket, self.n_cols), np.float32
                )
            off = 0
            for r in batch:
                stage[off: off + r.n_rows] = r.X
                off += r.n_rows
            if bucket > n:
                stage[n:] = stage[n - 1]
            t_padded = time.perf_counter()
            observe("serving.pad_s", t_padded - t_start, **self.labels)
            counter_inc("serving.padded_rows", bucket - n, **self.labels)
            counter_inc(
                "serving.bucket_hit" if bucket in self.warm_buckets
                else "serving.bucket_miss", 1, **self.labels,
            )
            with span("serving.batch",
                      {"rows": n, "bucket": bucket, **self.labels}) as bnode:
                outputs = self._execute(stage, n)
            t_done = time.perf_counter()
            observe("serving.execute_s", t_done - t_padded, **self.labels)
            observe("serving.batch_occupancy", n / bucket, **self.labels)
        except Exception as e:
            counter_inc("serving.errors", 1, **self.labels)
            _logger.warning("serving batch failed for %s: %s", self.name, e)
            t_err = time.perf_counter()
            _tracing.take_batch_annotations()  # don't leak onto a later batch
            for r in traced:
                r.trace.add_event("error", kind_detail=type(e).__name__,
                                  **self.labels)
                r.trace.add_span("serving.batch", t_start, t_err,
                             parent_id=r.trace.root_span_id,
                             attrs={"rows": n, "bucket": bucket,
                                    **self.labels},
                             links=fan_in, status="error",
                             span_id=batch_sid)
            for r in batch:
                if not r.future.set_running_or_notify_cancel():
                    continue
                r.future.set_exception(e)
            self._note_drain(n_closed)
            return
        anno = _tracing.take_batch_annotations()  # drained every batch
        if traced:
            self._trace_batch(traced, fan_in, batch_sid, exec_sid, bnode,
                              compiles0, anno, n, bucket,
                              t_start, t_padded, t_done)
        # scatter per-request slices back to the waiting futures: exact row
        # counts, no cross-request bleed (sliced COPIES so one request's
        # result does not keep the whole bucket's outputs alive)
        off = 0
        now = time.perf_counter()
        for r in batch:
            out_r: Dict[str, Any] = {}
            for key, v in outputs.items():
                arr = np.asarray(v)
                if arr.ndim >= 1 and arr.shape[0] == bucket:
                    out_r[key] = arr[off: off + r.n_rows].copy()
                else:  # per-model scalars/metadata ride along unsliced
                    out_r[key] = arr
            off += r.n_rows
            if r.trace is not None:
                # srml-metric: serving.scatter — trace span family (§6l)
                r.trace.add_span("serving.scatter", t_done, now,
                             parent_id=r.trace.root_span_id,
                             attrs={"rows": r.n_rows, **self.labels})
            if r.future.set_running_or_notify_cancel():
                r.future.set_result(out_r)
            total_s = now - r.enqueue_ts
            # exemplar iff the pointed-at trace will survive tail sampling —
            # a /metrics exemplar must resolve at /traces/<id>
            ex = (
                r.trace.trace_id
                if r.trace is not None and _tracing.would_keep(r.trace,
                                                               total_s)
                else None
            )
            observe("serving.total_s", total_s, exemplar=ex, **self.labels)
        counter_inc("serving.batches", 1, **self.labels)
        counter_inc("serving.requests", len(batch), **self.labels)
        counter_inc("serving.rows", n, **self.labels)
        self._note_drain(n_closed)
