#
# Async dynamic micro-batcher — the request-coalescing half of the serving
# plane (docs/design.md §7).
#
# The Podracer architectures (arXiv:2104.06272) decouple request feeding from
# accelerator stepping: feed threads enqueue, the accelerator executes
# fixed-shape batched steps. This module is that split for model inference:
#
#   * HTTP handler threads (or in-process callers) `submit()` variable-size
#     requests and block on a Future;
#   * ONE dispatcher thread per served model drains the queue, closing a batch
#     when it reaches `serving.max_batch_rows` OR the oldest queued request
#     has waited `serving.max_wait_ms` (the latency/size cutoff pair);
#   * the coalesced rows are written into a REUSED per-bucket staging buffer,
#     padded to the power-of-two row bucket (padding rows replicate the last
#     real row — always a valid input, so cosine/normalization paths never see
#     a synthetic zero vector), executed ONCE through the model's predict
#     kernels, and per-request slices scatter back to the waiting futures.
#
# Because every executed shape is a bucket, the set of predict shape
# signatures is finite and pre-warmable: steady-state serving never compiles
# and the PR-4 recompile sentinel (`transform.recompile_storm`) cannot fire.
#
# Telemetry (all label-aware `{model=}`): per-request `serving.queue_s` /
# `serving.total_s` histograms, per-batch `serving.pad_s` / `serving.execute_s`
# / `serving.batch_occupancy` (real rows / bucket rows — proof the batcher is
# actually coalescing), counters `serving.requests` / `serving.rows` /
# `serving.batches` / `serving.padded_rows` / `serving.errors` /
# `serving.bucket_hit` / `serving.bucket_miss` (pre-warmed bucket or not).
#

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import config as _config
from ..observability.runs import counter_inc, observe, span
from ..utils import get_logger

_logger = get_logger("serving.batcher")


class ServingError(RuntimeError):
    """Base class for request-rejection errors of the serving plane."""


class QueueFull(ServingError):
    """Backpressure: the per-model queue reached `serving.queue_depth`."""


class RequestTooLarge(ServingError):
    """A single request exceeded `serving.max_batch_rows`."""


def bucket_rows(n: int, min_rows: Optional[int] = None,
                max_rows: Optional[int] = None) -> int:
    """The power-of-two row bucket `n` pads to: smallest 2^i >= max(n,
    serving.bucket_min_rows), clamped to the bucket ceiling (the power of two
    covering serving.max_batch_rows). The bucket floor is a tuning-table knob
    (`serving.bucket_min_rows`, docs/design.md §6i) — resolved HERE, at
    registration/submit time, never inside a trace — so a platform can widen
    its pre-warmed bucket set by table entry; config set()/env still win."""
    if min_rows is None:
        from .. import autotune as _autotune

        tuned = _autotune.lookup("serving.bucket_min_rows")
        min_rows = (
            int(tuned) if tuned is not None
            else int(_config.get("serving.bucket_min_rows"))
        )
    if max_rows is None:
        max_rows = int(_config.get("serving.max_batch_rows"))
    n = max(int(n), max(int(min_rows), 1))
    bucket = 1 << (n - 1).bit_length()
    return min(bucket, 1 << (max(int(max_rows), 1) - 1).bit_length())


def bucket_table(min_rows: Optional[int] = None,
                 max_rows: Optional[int] = None) -> Tuple[int, ...]:
    """Every bucket the batcher can emit under the current config — the set
    registration pre-warms one executable for."""
    lo = bucket_rows(1, min_rows, max_rows)
    hi = bucket_rows(
        int(max_rows if max_rows is not None
            else _config.get("serving.max_batch_rows")),
        min_rows, max_rows,
    )
    out = []
    b = lo
    while b <= hi:
        out.append(b)
        b *= 2
    return tuple(out)


def pad_to_bucket(X: np.ndarray, bucket: int,
                  out: Optional[np.ndarray] = None) -> np.ndarray:
    """Pad a (n, d) float32 block to (bucket, d) by replicating the LAST real
    row (any real row is a valid model input; zeros would poison cosine /
    normalization paths). With `out` given, fills the reused staging buffer
    in place — steady-state serving allocates no per-batch host memory."""
    n = int(X.shape[0])
    if out is None:
        out = np.empty((bucket, X.shape[1]), np.float32)
    out[:n] = X
    if bucket > n:
        out[n:] = out[n - 1]
    return out


class _Request:
    __slots__ = ("X", "n_rows", "future", "enqueue_ts")

    def __init__(self, X: np.ndarray):
        self.X = X
        self.n_rows = int(X.shape[0])
        self.future: "Future[Dict[str, np.ndarray]]" = Future()
        self.enqueue_ts = time.perf_counter()


class MicroBatcher:
    """One served model's queue + dispatcher thread. `execute` is the bound
    predict closure the registry supplies (residency pin + padded predict);
    `warm_buckets` is the registry's set of pre-warmed bucket sizes (read-only
    here, used for the bucket_hit/bucket_miss counters)."""

    def __init__(self, name: str, n_cols: int,
                 execute: Callable[[np.ndarray, int], Dict[str, np.ndarray]],
                 warm_buckets: Optional[set] = None):
        self.name = name
        self.n_cols = int(n_cols)
        self._execute = execute
        self.warm_buckets = warm_buckets if warm_buckets is not None else set()
        self._queue: "deque[_Request]" = deque()
        self._cond = threading.Condition()
        self._stop = False
        self._staging: Dict[int, np.ndarray] = {}
        self._thread = threading.Thread(
            target=self._loop, name=f"srml-serving-{name}", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------ client side

    def submit(self, X: np.ndarray) -> "Future[Dict[str, np.ndarray]]":
        """Enqueue one request; the returned Future resolves to this request's
        named output arrays (exactly `n_rows` leading rows each)."""
        X = np.asarray(X, np.float32)
        if X.ndim == 1:
            X = X[None, :]
        if X.ndim != 2 or X.shape[1] != self.n_cols:
            raise ServingError(
                f"model '{self.name}' expects (n, {self.n_cols}) features; "
                f"got shape {tuple(X.shape)}"
            )
        if X.shape[0] < 1:
            raise ServingError("empty request (0 rows)")
        if X.shape[0] > int(_config.get("serving.max_batch_rows")):
            raise RequestTooLarge(
                f"request of {X.shape[0]} rows exceeds serving.max_batch_rows="
                f"{_config.get('serving.max_batch_rows')}; split it client-side"
            )
        req = _Request(X)
        with self._cond:
            if self._stop:
                raise ServingError(f"model '{self.name}' is shutting down")
            if len(self._queue) >= int(_config.get("serving.queue_depth")):
                counter_inc("serving.rejected", 1, model=self.name)
                raise QueueFull(
                    f"model '{self.name}' queue is full "
                    f"(serving.queue_depth={_config.get('serving.queue_depth')})"
                )
            self._queue.append(req)
            self._cond.notify()
        return req.future

    def pending(self) -> int:
        with self._cond:
            return len(self._queue)

    def stop(self, timeout: float = 10.0) -> None:
        """Stop accepting requests, drain what is queued, join the thread."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout)

    # -------------------------------------------------------- dispatcher side

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stop:
                    self._cond.wait(0.05)
                if not self._queue and self._stop:
                    return
                first = self._queue.popleft()
            self._run_batch(self._coalesce(first))

    def _coalesce(self, first: _Request) -> List[_Request]:
        """Drain until size or latency cutoff: the batch closes at
        max_batch_rows, or when the FIRST (oldest) request has waited
        max_wait_ms — later arrivals never extend the oldest request's wait."""
        batch = [first]
        rows = first.n_rows
        max_rows = int(_config.get("serving.max_batch_rows"))
        deadline = first.enqueue_ts + (
            float(_config.get("serving.max_wait_ms")) / 1000.0
        )
        while rows < max_rows:
            with self._cond:
                if self._queue and rows + self._queue[0].n_rows <= max_rows:
                    nxt = self._queue.popleft()
                    batch.append(nxt)
                    rows += nxt.n_rows
                    continue
                if self._queue:
                    break  # next request would overflow: close this batch
                remaining = deadline - time.perf_counter()
                if remaining <= 0 or self._stop:
                    break
                self._cond.wait(min(remaining, 0.05))
        return batch

    def _run_batch(self, batch: List[_Request]) -> None:
        t_start = time.perf_counter()
        n = sum(r.n_rows for r in batch)
        for r in batch:
            observe("serving.queue_s", t_start - r.enqueue_ts, model=self.name)
        bucket = bucket_rows(n)
        try:
            stage = self._staging.get(bucket)
            if stage is None:
                stage = self._staging[bucket] = np.empty(
                    (bucket, self.n_cols), np.float32
                )
            off = 0
            for r in batch:
                stage[off: off + r.n_rows] = r.X
                off += r.n_rows
            if bucket > n:
                stage[n:] = stage[n - 1]
            t_padded = time.perf_counter()
            observe("serving.pad_s", t_padded - t_start, model=self.name)
            counter_inc("serving.padded_rows", bucket - n, model=self.name)
            counter_inc(
                "serving.bucket_hit" if bucket in self.warm_buckets
                else "serving.bucket_miss", 1, model=self.name,
            )
            with span("serving.batch",
                      {"model": self.name, "rows": n, "bucket": bucket}):
                outputs = self._execute(stage, n)
            t_done = time.perf_counter()
            observe("serving.execute_s", t_done - t_padded, model=self.name)
            observe("serving.batch_occupancy", n / bucket, model=self.name)
        except Exception as e:
            counter_inc("serving.errors", 1, model=self.name)
            _logger.warning("serving batch failed for %s: %s", self.name, e)
            for r in batch:
                if not r.future.set_running_or_notify_cancel():
                    continue
                r.future.set_exception(e)
            return
        # scatter per-request slices back to the waiting futures: exact row
        # counts, no cross-request bleed (sliced COPIES so one request's
        # result does not keep the whole bucket's outputs alive)
        off = 0
        now = time.perf_counter()
        for r in batch:
            out_r: Dict[str, Any] = {}
            for key, v in outputs.items():
                arr = np.asarray(v)
                if arr.ndim >= 1 and arr.shape[0] == bucket:
                    out_r[key] = arr[off: off + r.n_rows].copy()
                else:  # per-model scalars/metadata ride along unsliced
                    out_r[key] = arr
            off += r.n_rows
            if r.future.set_running_or_notify_cancel():
                r.future.set_result(out_r)
            observe("serving.total_s", now - r.enqueue_ts, model=self.name)
        counter_inc("serving.batches", 1, model=self.name)
        counter_inc("serving.requests", len(batch), model=self.name)
        counter_inc("serving.rows", n, model=self.name)
