#
# Serving-plane lifecycle + HTTP surface (docs/design.md §7).
#
# The inference endpoints MOUNT on the live telemetry plane's existing HTTP
# server (observability/server.py, §6g) instead of starting a second one: the
# same loopback-by-default socket, the same refcounted lifecycle, and with
# serving never started there are zero extra threads and zero sockets.
#
#   POST /v1/models/<name>:predict   {"instances": [[...], ...]}
#       -> {"model", "rows", "outputs": {col: [...], ...}}
#   GET  /v1/models                  registry index with per-model stats
#   GET  /v1/models/<name>           one model's stats view
#
# A serving session is a ServingRun (a FitRun subclass, kind="serving"): every
# serving counter/histogram/span from every dispatcher and HTTP thread fans
# out into its scoped registry, and `stop_serving()` closes the scope and
# exports one line to `serving_reports.jsonl` — the run report the
# concurrency tests and the bench scenario read p50/p95/p99 and
# batch-occupancy from (`Histogram.quantile` plumbing, §6d).
#

from __future__ import annotations

import json
import threading
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import config as _config
from ..observability import server as _obs_server
from ..observability import tracing as _tracing
from ..observability.export import SERVING_REPORT_FILENAME
from ..observability.registry import interpolate_quantile, split_label_key
from ..observability.runs import FitRun, counter_inc
from ..utils import get_logger
from .batcher import (
    DeadlineExpired,
    QueueFull,
    RequestTooLarge,
    ServingError,
)
from .registry import ModelRegistry
from .router import NoLiveReplicas

_logger = get_logger("serving.http")

MOUNT_PREFIX = "/v1/"


class ServingRun(FitRun):
    """One serving session's observability scope — exports to
    `serving_reports.jsonl` (the serving mirror of Fit/TransformRun)."""

    kind = "serving"
    _id_prefix = "serving"
    _root_suffix = "serving_run"
    _report_filename = SERVING_REPORT_FILENAME


_lock = threading.RLock()
# serializes the whole start/stop transition (a check-then-act on _started
# under the state lock alone would let two concurrent start_serving calls
# both enter a ServingRun and leak the loser's server refcount forever)
_lifecycle_lock = threading.Lock()
_registry: Optional[ModelRegistry] = None
_run: Optional[ServingRun] = None
_started = False
_port_was_set = False


def get_registry() -> ModelRegistry:
    """The process serving registry (created on first use). Usable without the
    HTTP endpoint — tests and in-process callers register/predict directly."""
    global _registry
    with _lock:
        if _registry is None:
            _registry = ModelRegistry()
        return _registry


def register_model(name: str, model: Any,
                   prewarm: Optional[bool] = None) -> Dict[str, Any]:
    return get_registry().register(name, model, prewarm=prewarm)


def unregister_model(name: str) -> bool:
    with _lock:
        reg = _registry
    return reg.unregister(name) if reg is not None else False


def refresh_model(name: str) -> Dict[str, Any]:
    """Re-sync a served model's HBM weights after an in-place mutation (the
    ANN lifecycle's incremental add/delete, docs/design.md §7b)."""
    return get_registry().refresh_weights(name)


def mutate_model(name: str, fn) -> Dict[str, Any]:
    """Apply `fn(model)` to a LIVE served model under its execution lock and
    refresh its HBM weights — the race-free way to drive incremental
    add/delete (§7b) and continual-promotion weight swaps (§7d) against a
    model that is actively serving. The returned stats carry the bumped
    monotone `generation` ordinal (also `serving.model_generation{model=}`
    and `/v1/models/<name>`) — the audit key joining this mutation to the
    serving reports that observed its weights."""
    return get_registry().mutate(name, fn)


def predict(name: str, X: np.ndarray, timeout: Optional[float] = None,
            tenant: Optional[str] = None) -> Dict[str, np.ndarray]:
    return get_registry().predict(name, X, timeout=timeout, tenant=tenant)


def submit(name: str, X: np.ndarray, deadline_ts: Optional[float] = None,
           tenant: Optional[str] = None):
    return get_registry().submit(name, X, deadline_ts=deadline_ts,
                                 tenant=tenant)


def _serving_health() -> Dict[str, Any]:
    """The /healthz `serving` section: who is registered and — for fleets —
    which replicas are actually in rotation (the health state machine's view,
    serving/fleet.py)."""
    with _lock:
        reg = _registry
    if reg is None:
        return {"started": False}
    out: Dict[str, Any] = {"started": True, "models": {}}
    for name in reg.models():
        try:
            st = reg.stats(name)
        except KeyError:
            continue  # unregistered between models() and stats()
        view: Dict[str, Any] = {"pending": st.get("pending", 0)}
        if "replicas" in st:
            view["live_replicas"] = st.get("live_replicas")
            view["replicas"] = st.get("replicas")
        out["models"][name] = view
    return out


def start_serving(port: Optional[int] = None) -> Optional[Tuple[str, int]]:
    """Open the serving session: pin the telemetry HTTP endpoint up (binding
    `port`; None uses `observability.http_port`, falling back to an ephemeral
    port), mount the /v1/ handlers on it, and open the ServingRun scope.
    Returns the bound (host, port); None when the endpoint could not bind."""
    global _run, _started, _port_was_set
    with _lifecycle_lock:
        with _lock:
            if _started:
                return _obs_server.server_address()
        if port is None and _config.get("observability.http_port") is None:
            port = 0  # serving asked for an endpoint: ephemeral beats none
        addr = _obs_server.start_metrics_server(port)
        if addr is None:
            _logger.warning("serving endpoint could not bind; not starting")
            return None
        get_registry()
        run = ServingRun("serving", site="driver")
        run.__enter__()
        _obs_server.register_mount(MOUNT_PREFIX, _http_handler)
        _obs_server.register_health_provider("serving", _serving_health)
        with _lock:
            _run = run
            _started = True
            _port_was_set = port is not None
    _logger.info("serving endpoint mounted at http://%s:%d/v1/", *addr)
    return addr


def stop_serving() -> Optional[Dict[str, Any]]:
    """Tear the serving session down: unmount /v1/, drain and join every
    dispatcher thread, drop the HBM weight entries, close the ServingRun
    (exporting its report), and release the endpoint pin. Returns the session
    report (None when serving was never started)."""
    global _registry, _run, _started, _port_was_set
    with _lifecycle_lock:
        with _lock:
            was_started = _started
            registry, _registry = _registry, None
            run, _run = _run, None
            port_was_set = _port_was_set
            _started = False
            _port_was_set = False
        report = None
        if was_started:
            _obs_server.unregister_mount(MOUNT_PREFIX)
            _obs_server.unregister_health_provider("serving")
        if registry is not None:
            registry.close()
        if run is not None:
            run.__exit__(None, None, None)
            report = run.report()
        if was_started:
            _obs_server.stop_metrics_server()
            if port_was_set:
                # start_serving routed its port through config; no override
                # must outlive the session
                _config.unset("observability.http_port")
        return report


def serving_address() -> Optional[Tuple[str, int]]:
    return _obs_server.server_address()


# ------------------------------------------------------------------- handlers


def _model_from_path(path: str) -> str:
    """Best-effort model name for error labeling ("-" when the path carries
    none) — error metrics must label by model without trusting the request."""
    if not path.startswith("/v1/models/"):
        return "-"
    name = path[len("/v1/models/"):]
    if name.endswith(":predict"):
        name = name[: -len(":predict")]
    return name or "-"


def _retry_headers(retry_after_s: Optional[float]) -> Optional[Dict[str, str]]:
    """A `Retry-After` header from the shed path's drain-rate hint (HTTP
    wants integer seconds; round UP so the client never retries early into
    the same full queue)."""
    if retry_after_s is None:
        return None
    import math

    return {"Retry-After": str(max(1, int(math.ceil(retry_after_s))))}


def _http_handler(method: str, path: str, body: Optional[bytes],
                  headers: Optional[Dict[str, str]] = None):
    """The /v1/ mount (observability/server.py dispatches here). Never
    raises; every response — success AND 4xx/5xx — carries `traceparent`
    (the client's valid one echoed, a malformed one counted
    `tracing.bad_traceparent` and REPLACED, never 400'd) plus
    `x-srml-generation` (the served model's weight-version ordinal) when the
    path names a registered model. :predict POSTs additionally mint (or
    adopt) a full RequestTrace, finished here with the response code."""
    hdrs = {str(k).lower(): v for k, v in (headers or {}).items()}
    ctx = None
    raw = hdrs.get("traceparent")
    if raw is not None:
        ctx = _tracing.parse_traceparent(raw)
        if ctx is None:
            counter_inc("tracing.bad_traceparent", 1)
    rt = None
    if method == "POST" and path.endswith(":predict"):
        rt = _tracing.start_trace(
            "http.request", ctx=ctx, method=method, path=path,
            model=_model_from_path(path),
        )
    result = _dispatch_serving(method, path, body, rt)
    code, doc = result[0], result[1]
    extra = result[2] if len(result) > 2 and result[2] else {}
    base: Dict[str, str] = {}
    if rt is not None:
        base["traceparent"] = rt.traceparent
    elif ctx is not None:
        base["traceparent"] = _tracing.format_traceparent(
            ctx.trace_id, ctx.span_id, ctx.sampled)
    else:
        c = _tracing.mint_context()
        base["traceparent"] = _tracing.format_traceparent(
            c.trace_id, c.span_id)
    model = _model_from_path(path)
    if model != "-":
        with _lock:
            reg = _registry
        if reg is not None:
            try:
                base["x-srml-generation"] = str(reg.generation(model))
            except KeyError:
                pass
    if rt is not None:
        rt.add_event("http_response", code=code)
        rt.finish(status=(
            "ok" if code < 400
            else str((doc or {}).get("error_kind") or f"http_{code}")
        ))
    base.update(extra)
    return code, doc, base


def _dispatch_serving(method: str, path: str, body: Optional[bytes],
                      rt: Optional["_tracing.RequestTrace"]):
    """Route + error mapping: every error maps to a status + a JSON body
    carrying a structured `error_kind` (the exception class — what a client
    should branch on, instead of parsing the message), plus `Retry-After` on
    429/503 shedding. Unexpected 500s additionally count
    `serving.errors{model=,kind=}` so an error-rate alert can tell schema
    junk from handler bugs."""
    with _lock:
        reg = _registry
    if reg is None:
        return 503, {"error": "serving is not started",
                     "error_kind": "NotStarted"}
    try:
        if method == "GET" and path == "/v1/models":
            return 200, {"models": reg.stats_all()}
        if method == "GET" and path.startswith("/v1/models/"):
            return 200, reg.stats(path[len("/v1/models/"):])
        if method == "POST" and path.startswith("/v1/models/") \
                and path.endswith(":predict"):
            name = path[len("/v1/models/"): -len(":predict")]
            return _handle_predict(reg, name, body, rt)
        return 404, {"error": "unknown serving path", "paths": [
            "GET /v1/models", "GET /v1/models/<name>",
            "POST /v1/models/<name>:predict",
        ]}
    except KeyError as e:
        return 404, {"error": str(e.args[0]) if e.args else "not found",
                     "error_kind": "KeyError"}
    except QueueFull as e:
        return 429, {"error": str(e), "error_kind": "QueueFull",
                     "retry_after_s": e.retry_after_s}, \
            _retry_headers(e.retry_after_s)
    except NoLiveReplicas as e:
        return 503, {"error": str(e), "error_kind": "NoLiveReplicas",
                     "retry_after_s": e.retry_after_s}, \
            _retry_headers(e.retry_after_s)
    except DeadlineExpired as e:
        # the client's own deadline passed while the request queued: gone
        # before it could be served — a timeout, not a client-input error
        return 504, {"error": str(e), "error_kind": "DeadlineExpired"}
    except (RequestTooLarge, ServingError, ValueError) as e:
        return 400, {"error": str(e), "error_kind": type(e).__name__}
    except FutureTimeout:
        return 504, {"error": "request timed out "
                              f"(serving.request_timeout_s="
                              f"{_config.get('serving.request_timeout_s')})",
                     "error_kind": "Timeout"}
    except Exception as e:
        kind = type(e).__name__
        counter_inc("serving.errors", 1, model=_model_from_path(path),
                    kind=kind)
        _logger.warning("serving handler error: %s", e)
        return 500, {"error": f"{kind}: {e}", "error_kind": kind}


def _handle_predict(reg: ModelRegistry, name: str, body: Optional[bytes],
                    rt: Optional["_tracing.RequestTrace"] = None,
                    ) -> Tuple[int, Any]:
    if not body:
        return 400, {"error": "empty request body; send "
                              '{"instances": [[...], ...]}'}
    try:
        doc = json.loads(body)
    except json.JSONDecodeError as e:
        return 400, {"error": f"invalid JSON body: {e}"}
    if not isinstance(doc, dict):
        # a bare list of rows is the most natural malformed payload: a
        # client-input error, not a 500-worthy handler fault
        return 400, {"error": 'body must be a JSON object: '
                              '{"instances": [[...], ...]}'}
    inst = doc.get("instances", doc.get("inputs"))
    if inst is None:
        return 400, {"error": 'body must carry "instances" (list of feature '
                              "rows)"}
    X = np.asarray(inst, dtype=np.float32)
    # optional request metadata: "tenant" feeds the fleet's fair admission,
    # "timeout_s" becomes the request's deadline (queue time counts)
    tenant = doc.get("tenant")
    timeout = doc.get("timeout_s")
    out = reg.predict(
        name, X,
        timeout=float(timeout) if timeout is not None else None,
        tenant=str(tenant) if tenant is not None else None,
        trace=rt,
    )
    rows = 1 if X.ndim == 1 else int(X.shape[0])
    resp: Dict[str, Any] = {
        "model": name,
        "rows": rows,
        "outputs": {k: np.asarray(v).tolist() for k, v in out.items()},
    }
    if rt is not None:
        resp["trace_id"] = rt.trace_id
    return 200, resp


# ------------------------------------------------------------------ summaries


def serving_summary(report: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """Per-model latency/throughput digest of a serving-session report:
    p50/p95/p99 (ms) of `serving.total_s` via the exponential-bucket quantile
    plumbing, mean batch occupancy, request/batch/row counts, qps over the
    session wall. What the concurrency tests and the bench `serving_qps`
    scenario read."""
    out: Dict[str, Dict[str, Any]] = {}
    metrics = report.get("metrics") or {}
    hists = metrics.get("histograms") or {}
    counters = metrics.get("counters") or {}
    duration = float(report.get("duration_s") or 0.0)

    def _counter(name: str, want: Dict[str, str]) -> int:
        # label-set match, not exact-key match: fleet replicas add a
        # `replica` label to every series, and per-replica rows must read
        # their own counters while single-mode rows keep reading theirs
        total = 0
        for key, v in counters.items():
            cname, labels = split_label_key(key)
            if cname == name and labels == want:
                total += int(v)
        return total

    def _hist(name: str, want: Dict[str, str]):
        for key, st in hists.items():
            hname, labels = split_label_key(key)
            if hname == name and labels == want:
                return st
        return None

    for key, st in hists.items():
        hname, labels = split_label_key(key)
        if hname != "serving.total_s" or "model" not in labels:
            continue
        model = labels["model"]
        # fleet mode: one row per replica, keyed "<model>#r<i>"
        row_key = (
            f"{model}#r{labels['replica']}" if "replica" in labels else model
        )
        bounds = st.get("bounds") or []
        occ = _hist("serving.batch_occupancy", labels)
        requests = _counter("serving.requests", labels)
        out[row_key] = {
            "requests": requests,
            "batches": _counter("serving.batches", labels),
            "rows": _counter("serving.rows", labels),
            "reloads": _counter(
                "serving.model_reloads", {"model": row_key}
            ),
            "errors": _counter("serving.errors", labels),
            "p50_ms": round(interpolate_quantile(st, 0.50, bounds) * 1e3, 3),
            "p95_ms": round(interpolate_quantile(st, 0.95, bounds) * 1e3, 3),
            "p99_ms": round(interpolate_quantile(st, 0.99, bounds) * 1e3, 3),
            "batch_occupancy": (
                round(occ["sum"] / occ["count"], 4)
                if occ and occ.get("count") else None
            ),
            "qps": round(requests / duration, 2) if duration > 0 else None,
        }
    return out


__all__: List[str] = [
    "MOUNT_PREFIX",
    "ServingRun",
    "get_registry",
    "predict",
    "mutate_model",
    "refresh_model",
    "register_model",
    "serving_address",
    "serving_summary",
    "start_serving",
    "stop_serving",
    "submit",
    "unregister_model",
]
