#
# Fault-tolerant serving fleet — replicated dispatchers with health-driven
# failover (docs/design.md §7c).
#
# The single-dispatcher serving plane (batcher.py + registry.py) leaves one
# failure domain per model: a wedged or killed dispatcher strands every
# queued and in-flight request. This module replicates that domain N ways
# (`serving.replicas`), Podracer-style (arXiv:2104.06272 — decoupled feed
# threads fanning into replicated batched accelerator steps), and makes the
# MLlib failure-transparency contract (arXiv:1505.06807) hold for serving:
#
#   * N replicas per model, each its OWN MicroBatcher + model clone + HBM
#     weight stream ("serving_model", "<name>#r<i>" cache keys) over disjoint
#     local device groups (degenerating to the one local device on CPU);
#   * a router (router.py) in front: health-weighted least-outstanding
#     routing, per-tenant fair admission, bounded shedding with Retry-After;
#   * a per-replica health state machine LIVE -> DEGRADED -> DEAD ->
#     RECOVERING -> LIVE, fed by dispatcher heartbeats (batcher.last_beat),
#     consecutive-failure counts, and the chaos/fault sites
#     (`serving_execute`/`serving_heartbeat`); transitions are flight-recorded
#     and exported as the `serving.replica_state{model=,replica=}` gauge;
#   * FAILOVER: on replica death, still-queued requests are stolen from its
#     queue and in-flight requests are duplicated onto survivors — predict is
#     pure, so replay is idempotent; replays run under the
#     `reliability.RetryPolicy` attempt/deadline budget (counted
#     `serving.replayed{model=}`); with no survivor, requests PARK until the
#     monitor restarts a replica (bounded by the client deadline);
#   * HEDGING (optional): when a request has waited longer than
#     `serving.hedge_after_p99_frac` x the observed p99, a duplicate issues
#     to a second replica and the first resolution wins — the loser is
#     cancelled (counters `serving.hedges`/`serving.hedge_wins{model=}`);
#   * RECOVERY: dead replicas restart from the registry's pinned host
#     weights with the full bucketed AOT pre-warm BEFORE rejoining rotation,
#     so recovery never causes a warm-path compile (the pre-warm replays
#     through the process-wide compiled-kernel cache — CI-asserted).
#

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

from .. import config as _config
from ..observability import flight as _flight
from ..observability.runs import counter_inc, event as _obs_event, gauge_set
from ..reliability.chaos import ReplicaKilled, chaos_point
from ..reliability.faults import fault_point, is_transient
from ..reliability.policy import RetryPolicy
from ..utils import get_logger
from .batcher import DeadlineExpired, MicroBatcher, QueueFull, ServingError
from .router import NoLiveReplicas, Router

_logger = get_logger("serving.fleet")

# ------------------------------------------------------ health state machine

LIVE = "LIVE"  # in rotation, weight 1
DEGRADED = "DEGRADED"  # in rotation, weighted away from; failures mounting
DEAD = "DEAD"  # out of rotation; queue stolen, in-flight replayed
RECOVERING = "RECOVERING"  # restarting from pinned weights + pre-warm

_STATE_CODE = {LIVE: 0, DEGRADED: 1, DEAD: 2, RECOVERING: 3}

# consecutive batch failures that demote LIVE -> DEGRADED, and DEGRADED ->
# DEAD: a replica that keeps failing batches is indistinguishable from a sick
# device even when its thread still answers heartbeats
_DEGRADE_AFTER_FAILURES = 2
_DEAD_AFTER_FAILURES = 4

_LATENCY_WINDOW = 512  # client latencies kept for the hedge p99 estimate
_HEDGE_MIN_SAMPLES = 20


def resolve_replicas() -> int:
    """Replica count for a new fleet: tuning table (knob `serving.replicas`)
    unless config pins it; `0` (the default) means auto -> 1."""
    from .. import autotune as _autotune

    tuned = _autotune.lookup("serving.replicas")
    if tuned is not None:
        return max(1, int(tuned))
    cfg = int(_config.get("serving.replicas") or 0)
    return cfg if cfg >= 1 else 1


def _hedge_frac() -> float:
    from .. import autotune as _autotune

    tuned = _autotune.lookup("serving.hedge_after_p99_frac")
    if tuned is not None:
        return float(tuned)
    return float(_config.get("serving.hedge_after_p99_frac") or 0.0)


class ReplicaHandle(NamedTuple):
    """What the registry's spawn callback returns: the bound padded-predict
    closure for one fresh replica entry, and its pre-warmed bucket set."""

    execute: Callable[[Any, int], Dict[str, Any]]
    warm: set


class _Replica:
    """One replica's rotation state. Mutated only under the fleet lock
    (except `batches`, which only the replica's own dispatcher advances)."""

    __slots__ = ("index", "state", "batcher", "outstanding", "consec_failures",
                 "batches", "restarts", "inflight_reqs")

    def __init__(self, index: int):
        self.index = index
        self.state = RECOVERING
        self.batcher: Optional[MicroBatcher] = None
        self.outstanding = 0  # dispatched, not yet resolved
        self.consec_failures = 0
        self.batches = 0  # execute ordinal (persists across restarts)
        self.restarts = 0
        self.inflight_reqs: Dict[int, "_FleetRequest"] = {}

    # duck-typed surface the router reads (router.py stays fleet-free)
    def routable(self) -> bool:
        return self.state in (LIVE, DEGRADED)

    def health_weight(self) -> float:
        return 1.0 if self.state == LIVE else 3.0


class _FleetRequest:
    """One client request's fleet-side bookkeeping: the client Future, which
    replicas currently hold a copy, and the replay/hedge state."""

    __slots__ = ("X", "tenant", "deadline_ts", "enqueue_ts", "client", "lock",
                 "attempts", "hedged", "primary", "inflight", "released",
                 "trace")

    def __init__(self, X: Any, tenant: str, deadline_ts: Optional[float],
                 trace: Any = None):
        self.X = X
        self.tenant = tenant
        self.deadline_ts = deadline_ts
        self.trace = trace  # RequestTrace or None (§6l)
        self.enqueue_ts = time.perf_counter()
        self.client: "Future[Dict[str, Any]]" = Future()
        self.lock = threading.Lock()
        self.attempts = 0  # failed dispatches so far (RetryPolicy budget)
        self.hedged = False
        self.primary: Optional[int] = None
        self.inflight: Dict[int, Future] = {}  # replica index -> inner Future
        self.released = False


class ReplicaFleet:
    """N dispatcher replicas for one served model, fronted by a Router, kept
    honest by a health-monitor thread. The registry supplies `spawn(i)` (build
    a fresh replica entry from the pinned weights: clone, upload, pre-warm;
    returns a ReplicaHandle) and `retire(i)` (drop that replica's HBM
    stream) — the fleet never touches model internals itself."""

    def __init__(self, name: str, n_cols: int, n_replicas: int,
                 spawn: Callable[..., ReplicaHandle],
                 retire: Callable[[int], None]):
        self.name = name
        self.n_cols = int(n_cols)
        self._spawn = spawn
        self._retire = retire
        # disjoint device groups drawn from the active Partitioner's mesh —
        # NOT the raw local-device list — so a pod-sliced mesh hands each
        # replica its slice of this host (parallel/partitioner.py)
        from ..parallel.partitioner import active_partitioner

        self.device_groups = active_partitioner().replica_device_groups(
            max(1, int(n_replicas))
        )
        # spawn callbacks predating device groups take only the index
        import inspect

        try:
            self._spawn_takes_devices = (
                len(inspect.signature(spawn).parameters) >= 2
            )
        except (TypeError, ValueError):  # pragma: no cover — builtins
            self._spawn_takes_devices = False
        self._lock = threading.RLock()
        self._stop = False
        self._seq = 0
        self._outstanding: "set[_FleetRequest]" = set()
        self._parked: List[_FleetRequest] = []
        self._latencies: "deque[float]" = deque(maxlen=_LATENCY_WINDOW)
        self._replicas: List[_Replica] = []
        for i in range(max(1, int(n_replicas))):
            rep = _Replica(i)
            self._boot(rep)
            self._replicas.append(rep)
        self.router = Router(name, self._replicas)
        self._monitor = threading.Thread(
            target=self._monitor_loop,
            name=f"srml-serving-fleet-{name}", daemon=True,
        )
        self._monitor.start()

    # ------------------------------------------------------------- replica mgmt

    def _boot(self, rep: _Replica) -> None:
        """Build (or rebuild) one replica from the registry's pinned weights:
        spawn the entry (upload + AOT pre-warm), wrap its execute with the
        chaos/liveness guard, start a fresh dispatcher."""
        if self._spawn_takes_devices:
            handle = self._spawn(rep.index, self.device_groups[rep.index])
        else:
            handle = self._spawn(rep.index)
        rep.batcher = MicroBatcher(
            self.name, self.n_cols,
            execute=self._wrap_execute(rep, handle.execute),
            warm_buckets=handle.warm,
            labels={"model": self.name, "replica": str(rep.index)},
            thread_suffix=f"#r{rep.index}",
        )
        self._set_state(rep, LIVE)

    def _wrap_execute(self, rep: _Replica, execute: Callable) -> Callable:
        def _run(stage: Any, n_valid: int) -> Dict[str, Any]:
            b = rep.batches
            rep.batches += 1
            if rep.state == DEAD:
                # declared dead while this batch waited: fail it replayably
                # instead of executing on a replica out of rotation
                raise ReplicaKilled("serving_execute", rep.index, b)
            chaos_point("serving_execute", replica=rep.index, batch=b)
            return execute(stage, n_valid)

        return _run

    def _set_state(self, rep: _Replica, state: str) -> None:
        with self._lock:
            prev, rep.state = rep.state, state
        gauge_set(
            "serving.replica_state", _STATE_CODE[state],
            model=self.name, replica=str(rep.index),
        )
        if prev != state:
            _flight.note(
                "serving.replica_state", model=self.name, replica=rep.index,
                state=state, prev=prev,
            )

    def _declare_dead(self, rep: _Replica, cause: str) -> None:
        """Take a replica out of rotation and make its requests whole: steal
        its still-queued requests (their futures fail replayably) and
        duplicate its in-flight ones onto survivors. Idempotent."""
        with self._lock:
            if rep.state in (DEAD, RECOVERING):
                return
            rep.state = DEAD
            inflight = list(rep.inflight_reqs.values())
        gauge_set(
            "serving.replica_state", _STATE_CODE[DEAD],
            model=self.name, replica=str(rep.index),
        )
        counter_inc(
            "serving.replica_deaths", 1,
            model=self.name, replica=str(rep.index),
        )
        counter_inc("serving.failovers", 1, model=self.name)
        _flight.note(
            "serving.replica_dead", model=self.name, replica=rep.index,
            cause=cause,
        )
        _obs_event(
            "replica_dead", model=self.name, replica=rep.index, cause=cause,
        )
        _logger.warning(
            "serving replica %s#r%d declared DEAD (%s); failing over",
            self.name, rep.index, cause,
        )
        assert rep.batcher is not None
        steal_now = time.perf_counter()
        for r in rep.batcher.steal_pending():
            # the inner futures carry fleet callbacks: failing them with
            # ReplicaKilled routes each stolen request into the replay path
            if r.trace is not None:
                # the dead dispatcher will never close this queue span itself
                r.trace.add_span("serving.queue", r.enqueue_ts, steal_now,
                             parent_id=r.trace.root_span_id,
                             attrs={"model": self.name,
                                    "replica": str(rep.index)},
                             status="stolen")
                r.trace.add_event("queue_steal", model=self.name,
                                  replica=rep.index, cause=cause)
            if r.future.set_running_or_notify_cancel():
                r.future.set_exception(
                    ReplicaKilled("serving_dispatch", rep.index)
                )
        for freq in inflight:
            # the batch may be hung inside the dead replica; predict is pure,
            # so duplicate it now — first resolution wins, the loser is dropped
            self._try_replay(
                freq, rep.index, ReplicaKilled("serving_execute", rep.index),
            )

    def _restart(self, rep: _Replica) -> None:
        """DEAD -> RECOVERING -> LIVE: abandon the old dispatcher, drop the
        dead clone's weight stream, respawn from the registry's pinned
        weights with the full AOT pre-warm, rejoin rotation. A failed restart
        returns the replica to DEAD for the next monitor tick."""
        with self._lock:
            if rep.state != DEAD:
                return
            rep.state = RECOVERING
        gauge_set(
            "serving.replica_state", _STATE_CODE[RECOVERING],
            model=self.name, replica=str(rep.index),
        )
        _flight.note(
            "serving.replica_recovering", model=self.name, replica=rep.index,
        )
        if rep.batcher is not None:
            try:
                # short join: a hung dispatcher is a daemon thread we abandon
                rep.batcher.stop(timeout=0.2)
            except Exception:  # noqa: fence/silent-except — already dead
                pass
        try:
            self._retire(rep.index)
            self._boot(rep)
        except Exception as e:
            _logger.warning(
                "serving replica %s#r%d restart failed (%s: %s); will retry",
                self.name, rep.index, type(e).__name__, e,
            )
            self._set_state(rep, DEAD)
            return
        with self._lock:
            rep.consec_failures = 0
            rep.restarts += 1
        counter_inc(
            "serving.replica_restarts", 1,
            model=self.name, replica=str(rep.index),
        )
        _obs_event("replica_restarted", model=self.name, replica=rep.index)
        _logger.info(
            "serving replica %s#r%d recovered and rejoined rotation",
            self.name, rep.index,
        )

    def _note_failure(self, rep: _Replica, exc: BaseException) -> None:
        demote = False
        with self._lock:
            rep.consec_failures += 1
            if rep.state == LIVE and \
                    rep.consec_failures >= _DEGRADE_AFTER_FAILURES:
                rep.state = DEGRADED
                gauge_set(
                    "serving.replica_state", _STATE_CODE[DEGRADED],
                    model=self.name, replica=str(rep.index),
                )
                _flight.note(
                    "serving.replica_degraded", model=self.name,
                    replica=rep.index, error=type(exc).__name__,
                )
            elif rep.state == DEGRADED and \
                    rep.consec_failures >= _DEAD_AFTER_FAILURES:
                demote = True
        if demote:
            self._declare_dead(rep, f"failures:{type(exc).__name__}")

    def _note_success(self, rep: _Replica) -> None:
        with self._lock:
            rep.consec_failures = 0
            if rep.state == DEGRADED:
                rep.state = LIVE
            else:
                return
        gauge_set(
            "serving.replica_state", _STATE_CODE[LIVE],
            model=self.name, replica=str(rep.index),
        )

    # ------------------------------------------------------------- client side

    def submit(self, X: Any, deadline_ts: Optional[float] = None,
               tenant: Optional[str] = None,
               trace: Any = None) -> "Future[Dict[str, Any]]":
        """Admit + route one request; the returned Future survives replica
        death (replayed), hedging (first resolution wins), and restarts
        (parked until a replica recovers) — it fails only on non-retryable
        errors, an exhausted RetryPolicy, or the client's own deadline."""
        tenant = tenant or "-"
        try:
            self.router.admit(tenant)  # raises QueueFull (429 + Retry-After)
        except QueueFull:
            if trace is not None:
                trace.add_event("tenant_shed", model=self.name, tenant=tenant)
            raise
        freq = _FleetRequest(X, tenant, deadline_ts, trace=trace)
        with self._lock:
            self._outstanding.add(freq)
        try:
            self._dispatch(freq, first=True)
        except BaseException:
            self._finalize(freq)
            raise
        return freq.client

    def _next_seq(self) -> int:
        with self._lock:
            self._seq += 1
            return self._seq - 1

    def _dispatch(self, freq: _FleetRequest, exclude: Tuple[int, ...] = (),
                  first: bool = False) -> None:
        """Route + enqueue on the cheapest routable replica, skipping full
        queues. On the submit path (`first`) total failure raises to the
        caller; on replay/hedge paths it settles the client future or parks
        the request for the monitor."""
        seq = self._next_seq()
        try:
            fault_point("serving_dispatch", batch=seq)
            chaos_point("serving_dispatch", batch=seq)
        except Exception as e:
            if first:
                raise
            self._settle_err(freq, e)
            return
        tried = set(exclude)
        while True:
            rep = self.router.pick(tuple(tried))
            if rep is None:
                break
            try:
                if self._enqueue_on(rep, freq):
                    return
            except Exception as e:
                if first:
                    raise
                self._settle_err(freq, e)
                return
            tried.add(rep.index)  # that queue is full — try the next one
        if self.router.has_routable():
            counter_inc("serving.shed_total", 1, model=self.name)
            err = QueueFull(
                f"every replica queue of '{self.name}' is full",
                retry_after_s=self.router._fleet_retry_after_s(),
            )
            if first:
                raise err
            self._settle_err(freq, err)
            return
        if first:
            raise self.router.no_live()
        self._park(freq)

    def _enqueue_on(self, rep: _Replica, freq: _FleetRequest) -> bool:
        """One replica attempt; False on that replica's backpressure."""
        assert rep.batcher is not None
        try:
            inner = rep.batcher.submit(freq.X, deadline_ts=freq.deadline_ts,
                                       trace=freq.trace)
        except QueueFull:
            return False
        with self._lock:
            rep.outstanding += 1
            rep.inflight_reqs[id(freq)] = freq
        with freq.lock:
            freq.inflight[rep.index] = inner
            if freq.primary is None:
                freq.primary = rep.index
        inner.add_done_callback(
            lambda f, _r=rep: self._on_inner_done(freq, _r, f)
        )
        return True

    def _on_inner_done(self, freq: _FleetRequest, rep: _Replica,
                       fut: Future) -> None:
        with self._lock:
            rep.outstanding = max(0, rep.outstanding - 1)
            rep.inflight_reqs.pop(id(freq), None)
        with freq.lock:
            freq.inflight.pop(rep.index, None)
        if fut.cancelled():
            return  # hedge loser — already settled by the winner
        exc = fut.exception()
        if exc is None:
            with freq.lock:
                hedge_win = (
                    freq.hedged and freq.primary is not None
                    and rep.index != freq.primary and not freq.client.done()
                )
            if self._settle_ok(freq, fut.result(), rep.index):
                self._note_success(rep)
                self._latencies.append(time.perf_counter() - freq.enqueue_ts)
                if hedge_win:
                    counter_inc("serving.hedge_wins", 1, model=self.name)
                    if freq.trace is not None:
                        freq.trace.add_event("hedge_won", model=self.name,
                                             replica=rep.index)
            return
        if isinstance(exc, ReplicaKilled):
            self._declare_dead(rep, "killed")
        elif isinstance(exc, DeadlineExpired):
            self._settle_err(freq, exc)
            return
        else:
            self._note_failure(rep, exc)
        if isinstance(exc, ReplicaKilled) or is_transient(exc):
            self._try_replay(freq, rep.index, exc)
        else:
            self._settle_err(freq, exc)

    def _try_replay(self, freq: _FleetRequest, failed_idx: int,
                    exc: BaseException) -> None:
        """Replay one failed/stranded request under the RetryPolicy budget
        and the client deadline; exhaustion settles the client with the
        triggering failure. Cross-replica replay does NOT back off — the
        incident was the replica, not the request."""
        policy = RetryPolicy.from_config()
        now = time.perf_counter()
        with freq.lock:
            if freq.client.done():
                return
            freq.attempts += 1
            attempts = freq.attempts
        expired = freq.deadline_ts is not None and now >= freq.deadline_ts
        if expired or policy.give_up(
            attempts, now - freq.enqueue_ts, site="serving_replay"
        ):
            self._settle_err(freq, exc)
            return
        counter_inc("serving.replayed", 1, model=self.name)
        _obs_event(
            "serving_replay", model=self.name, replica=failed_idx,
            attempt=attempts, error=type(exc).__name__,
        )
        if freq.trace is not None:
            freq.trace.add_event(
                "failover_replay", model=self.name, replica=failed_idx,
                attempt=attempts, error=type(exc).__name__,
            )
        try:
            self._dispatch(freq, exclude=(failed_idx,))
        except Exception as e:
            self._settle_err(freq, e)

    # ------------------------------------------------------------- settlement

    def _settle_ok(self, freq: _FleetRequest, out: Dict[str, Any],
                   winner_idx: int) -> bool:
        losers: List[Future] = []
        with freq.lock:
            if freq.client.done():
                return False
            ok = freq.client.set_running_or_notify_cancel()
            if ok:
                freq.client.set_result(out)
            losers = [
                f for i, f in freq.inflight.items() if i != winner_idx
            ]
        self._finalize(freq)
        for f in losers:
            f.cancel()  # cancel the hedge/replay loser
        return ok

    def _settle_err(self, freq: _FleetRequest, exc: BaseException) -> None:
        with freq.lock:
            if not freq.client.done():
                if freq.client.set_running_or_notify_cancel():
                    freq.client.set_exception(exc)
        self._finalize(freq)

    def _finalize(self, freq: _FleetRequest) -> None:
        with self._lock:
            self._outstanding.discard(freq)
        with freq.lock:
            if freq.released:
                return
            freq.released = True
        self.router.release(freq.tenant)

    # ---------------------------------------------------------------- parking

    def _park(self, freq: _FleetRequest) -> None:
        """No routable replica: hold the request for the monitor to replay
        once a restart lands, bounded by the fleet-wide admission cap."""
        with self._lock:
            over = len(self._parked) >= int(_config.get("serving.queue_depth"))
            if not over:
                self._parked.append(freq)
        if over:
            self._settle_err(freq, self.router.no_live())
        else:
            counter_inc("serving.parked", 1, model=self.name)

    def _drain_parked(self) -> None:
        with self._lock:
            if not self._parked:
                return
            parked, self._parked = self._parked, []
        now = time.perf_counter()
        for freq in parked:
            with freq.lock:
                if freq.client.done():
                    continue
            if freq.deadline_ts is not None and now >= freq.deadline_ts:
                if freq.trace is not None:
                    freq.trace.add_event("deadline_expired", at="parked",
                                         model=self.name)
                self._settle_err(freq, DeadlineExpired(
                    "request deadline expired while no replica was live"
                ))
                continue
            if not self.router.has_routable():
                with self._lock:
                    self._parked.append(freq)
                continue
            try:
                self._dispatch(freq)
            except Exception as e:
                self._settle_err(freq, e)

    # ---------------------------------------------------------------- hedging

    def _p99_estimate(self) -> Optional[float]:
        lat = sorted(self._latencies)
        if len(lat) < _HEDGE_MIN_SAMPLES:
            return None
        return lat[min(len(lat) - 1, int(0.99 * len(lat)))]

    def _maybe_hedge(self) -> None:
        frac = _hedge_frac()
        if frac <= 0:
            return
        p99 = self._p99_estimate()
        if p99 is None:
            return
        cutoff = frac * p99
        now = time.perf_counter()
        with self._lock:
            outstanding = list(self._outstanding)
        for freq in outstanding:
            with freq.lock:
                if (
                    freq.hedged or freq.client.done()
                    or len(freq.inflight) != 1
                    or now - freq.enqueue_ts <= cutoff
                    or (freq.deadline_ts is not None
                        and now >= freq.deadline_ts)
                ):
                    continue
                current = next(iter(freq.inflight))
                freq.hedged = True
            rep2 = self.router.pick((current,))
            if rep2 is None:
                with freq.lock:
                    freq.hedged = False  # nobody to hedge onto; try later
                continue
            counter_inc("serving.hedges", 1, model=self.name)
            _obs_event(
                "serving_hedge", model=self.name, replica=rep2.index,
                waited_s=round(now - freq.enqueue_ts, 4),
            )
            if freq.trace is not None:
                freq.trace.add_event(
                    "hedge_issued", model=self.name, replica=rep2.index,
                    waited_s=round(now - freq.enqueue_ts, 4),
                )
            try:
                self._enqueue_on(rep2, freq)
            except Exception:  # hedge is optional: the primary is still live
                with freq.lock:
                    freq.hedged = False

    # ---------------------------------------------------------------- monitor

    def _tick_s(self) -> float:
        hb = float(_config.get("serving.heartbeat_timeout_s"))
        return min(max(hb / 4.0, 0.01), 0.1)

    def _monitor_loop(self) -> None:
        while not self._stop:
            time.sleep(self._tick_s())
            if self._stop:
                return
            try:
                self._monitor_once()
            except Exception as e:  # the monitor must outlive any incident
                _logger.warning(
                    "fleet monitor error for '%s': %s: %s",
                    self.name, type(e).__name__, e,
                )

    def _monitor_once(self) -> None:
        hb = float(_config.get("serving.heartbeat_timeout_s"))
        for rep in self._replicas:
            if self._stop:
                return
            if rep.state == DEAD:
                self._restart(rep)
                continue
            if rep.state == RECOVERING:
                continue
            try:
                fault_point("serving_heartbeat", batch=rep.index)
                chaos_point(
                    "serving_heartbeat", replica=rep.index, batch=rep.index
                )
            except ReplicaKilled:
                self._declare_dead(rep, "chaos-heartbeat")
                continue
            except Exception as e:
                # an unanswerable probe is indistinguishable from a hang
                self._declare_dead(rep, f"heartbeat-{type(e).__name__}")
                continue
            assert rep.batcher is not None
            stale = rep.batcher.heartbeat_age_s() > hb
            busy = rep.outstanding > 0 or rep.batcher.pending() > 0
            if not rep.batcher.alive() or (stale and busy):
                self._declare_dead(
                    rep,
                    "thread-death" if not rep.batcher.alive()
                    else "heartbeat-timeout",
                )
        self._maybe_hedge()
        self._drain_parked()

    # --------------------------------------------------------------- lifecycle

    def close(self) -> None:
        """Stop the monitor, drain+join every replica dispatcher, fail parked
        requests, drop every replica weight stream."""
        self._stop = True
        self._monitor.join(timeout=2.0)
        with self._lock:
            parked, self._parked = self._parked, []
        for freq in parked:
            self._settle_err(
                freq, ServingError(f"fleet '{self.name}' is shutting down")
            )
        for rep in self._replicas:
            if rep.batcher is not None:
                rep.batcher.stop()
            try:
                self._retire(rep.index)
            except Exception:  # noqa: fence/silent-except — teardown best-effort
                pass

    # -------------------------------------------------------------------- views

    def pending(self) -> int:
        with self._lock:
            parked = len(self._parked)
        return parked + sum(
            rep.batcher.pending() for rep in self._replicas
            if rep.batcher is not None
        )

    def health_view(self) -> List[Dict[str, Any]]:
        """Per-replica health for stats()/healthz: the state machine's word
        on who is serving."""
        out = []
        for rep in self._replicas:
            b = rep.batcher
            out.append({
                "replica": rep.index,
                "state": rep.state,
                "outstanding": rep.outstanding,
                "pending": b.pending() if b is not None else 0,
                "heartbeat_age_s": (
                    round(b.heartbeat_age_s(), 3) if b is not None else None
                ),
                "consec_failures": rep.consec_failures,
                "restarts": rep.restarts,
                "batches": rep.batches,
                "devices": [str(d) for d in self.device_groups[rep.index]],
            })
        return out

    def live_count(self) -> int:
        return sum(1 for r in self._replicas if r.routable())


__all__ = [
    "DEAD",
    "DEGRADED",
    "LIVE",
    "RECOVERING",
    "NoLiveReplicas",
    "ReplicaFleet",
    "ReplicaHandle",
    "resolve_replicas",
]
