# Public API module mirroring the reference's `spark_rapids_ml.knn`
# (reference python/src/spark_rapids_ml/knn.py).
from .models.knn import (
    ApproximateNearestNeighbors,
    ApproximateNearestNeighborsModel,
    NearestNeighbors,
    NearestNeighborsModel,
)

__all__ = [
    "ApproximateNearestNeighbors",
    "ApproximateNearestNeighborsModel",
    "NearestNeighbors",
    "NearestNeighborsModel",
]
