#
# `pyspark-tpu` launcher — role of the reference's `pyspark-rapids` CLI
# (reference pyspark_rapids.py:24-44): start a pyspark shell with the
# no-import-change interposer pre-imported via PYTHONSTARTUP.
#

from __future__ import annotations

import os
import shutil
import sys


def main() -> None:
    pyspark_bin = shutil.which("pyspark")
    if pyspark_bin is None:
        raise SystemExit(
            "pyspark not found on PATH; install pyspark to use the pyspark-tpu shell."
        )
    startup = os.path.join(os.path.dirname(os.path.abspath(__file__)), "install.py")
    env = dict(os.environ)
    env["PYTHONSTARTUP"] = startup
    os.execve(pyspark_bin, [pyspark_bin] + sys.argv[1:], env)


if __name__ == "__main__":
    main()
