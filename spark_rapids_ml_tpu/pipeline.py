#
# Pipeline — pyspark.ml.Pipeline-compatible surface with the reference's acceleration
# trick (reference python/src/spark_rapids_ml/pipeline.py:85-159): a
# VectorAssembler -> TPU-estimator pair is bypassed, feeding the scalar columns
# directly to the estimator via featuresCols and replacing the assembler with a
# NoOpTransformer — the vector column is never materialized.
#

from __future__ import annotations

from typing import Any, List, Optional

from .core.backend_params import _TpuParams
from .core.params import ParamMap, Params
from .utils import get_logger


class NoOpTransformer(Params):
    """Stage that passes data through unchanged (reference pipeline.py:37-49)."""

    def transform(self, dataset: Any, params: Optional[ParamMap] = None) -> Any:
        return dataset


class Transformer(Params):
    """Marker base for pure transformers (pyspark.ml.Transformer surface)."""

    def transform(self, dataset: Any, params: Optional[ParamMap] = None) -> Any:
        raise NotImplementedError


def _isTpuEstimator(stage: Any) -> bool:
    """reference pipeline.py:146-159 `_isGPUEstimator`."""
    return isinstance(stage, _TpuParams) and hasattr(stage, "_get_tpu_fit_func")


def _isVectorAssembler(stage: Any) -> bool:
    return type(stage).__name__ == "VectorAssembler" and stage.hasParam("inputCols")


class Pipeline(Params):
    """Sequential stages; estimators are fit then their models transform
    (pyspark.ml.Pipeline semantics + the assembler bypass)."""

    def __init__(self, stages: Optional[List[Any]] = None) -> None:
        super().__init__()
        self._stages = stages or []
        self.logger = get_logger(self.__class__)

    def getStages(self) -> List[Any]:
        return self._stages

    def setStages(self, value: List[Any]) -> "Pipeline":
        self._stages = value
        return self

    def fit(self, dataset: Any) -> "PipelineModel":
        return self._fit(dataset)

    def _fit(self, dataset: Any) -> "PipelineModel":
        stages = list(self._stages)

        # assembler bypass (reference pipeline.py:85-119): VectorAssembler feeding a
        # TPU estimator's featuresCol becomes featuresCols on the estimator directly
        for i in range(len(stages) - 1):
            a, b = stages[i], stages[i + 1]
            if (
                _isVectorAssembler(a)
                and _isTpuEstimator(b)
                and a.isDefined("outputCol")
                and b.hasParam("featuresCol")
                and b.getOrDefault("featuresCol") == a.getOrDefault("outputCol")
                and b.hasParam("featuresCols")
            ):
                self.logger.info(
                    "Bypassing VectorAssembler '%s' -> feeding %d scalar columns "
                    "directly to %s",
                    a.uid,
                    len(a.getOrDefault("inputCols")),
                    type(b).__name__,
                )
                # bypass on a COPY: mutating the user's estimator would corrupt its
                # reuse outside this pipeline (pyspark's Pipeline.fit also never
                # mutates the supplied stages)
                b = b.copy()
                b._set(featuresCols=a.getOrDefault("inputCols"))
                b._clear(b.getParam("featuresCol"))
                stages[i + 1] = b
                stages[i] = NoOpTransformer()

        fitted: List[Any] = []
        for stage in stages:
            if hasattr(stage, "_get_tpu_fit_func") or (
                hasattr(stage, "fit") and not hasattr(stage, "transform")
            ):
                model = stage.fit(dataset)
                fitted.append(model)
                dataset = model.transform(dataset)
            elif hasattr(stage, "transform"):
                fitted.append(stage)
                dataset = stage.transform(dataset)
            else:
                raise TypeError(f"Pipeline stage {stage} is neither fit-able nor transform-able")
        return PipelineModel(fitted)


class PipelineModel(Params):
    def __init__(self, stages: List[Any]) -> None:
        super().__init__()
        self.stages = stages

    def transform(self, dataset: Any, params: Optional[ParamMap] = None) -> Any:
        for stage in self.stages:
            dataset = stage.transform(dataset)
        return dataset
