#
# Pipeline — pyspark.ml.Pipeline-compatible surface with the reference's acceleration
# trick (reference python/src/spark_rapids_ml/pipeline.py:85-159): a
# VectorAssembler -> TPU-estimator pair is bypassed, feeding the scalar columns
# directly to the estimator via featuresCols and replacing the assembler with a
# NoOpTransformer — the vector column is never materialized.
#
# Whole-pipeline fusion (docs/design.md §6k): a featurize->fit suffix chain
# (StandardScaler / PCA feeding KMeans / LinearRegression / LogisticRegression /
# PCA) whose fits would stream out-of-core runs as ONE compiled program per
# batch — the featurizer transforms become in-program chain ops
# (ops/streaming.py::_apply_chain) applied by the downstream accumulator
# kernels, so intermediate feature matrices never round-trip to the host and
# raw input batches upload exactly once per pass (replayed from the HBM batch
# cache across passes AND across chain stages). Bit-parity with the staged
# transform->refit path is the contract, verified in
# tests/test_ingest_fusion.py.
#

from __future__ import annotations

import inspect
from typing import Any, Dict, List, Optional, Tuple

from .core.backend_params import _TpuParams
from .core.params import ParamMap, Params
from .utils import get_logger


class NoOpTransformer(Params):
    """Stage that passes data through unchanged (reference pipeline.py:37-49)."""

    def transform(self, dataset: Any, params: Optional[ParamMap] = None) -> Any:
        return dataset


class Transformer(Params):
    """Marker base for pure transformers (pyspark.ml.Transformer surface)."""

    def transform(self, dataset: Any, params: Optional[ParamMap] = None) -> Any:
        raise NotImplementedError


def _isTpuEstimator(stage: Any) -> bool:
    """reference pipeline.py:146-159 `_isGPUEstimator`."""
    return isinstance(stage, _TpuParams) and hasattr(stage, "_get_tpu_fit_func")


def _isVectorAssembler(stage: Any) -> bool:
    return type(stage).__name__ == "VectorAssembler" and stage.hasParam("inputCols")


def _resolve_fuse_min_rows(n: Optional[int] = None) -> int:
    """`pipeline.fuse_min_rows` resolution: a non-zero config pin wins, then
    the tuning table (per n-rows bucket), then the defaults-module geometry
    (autotune/defaults.py::PIPELINE_FUSE_MIN_ROWS)."""
    from . import autotune as _autotune
    from . import config as _config
    from .autotune.defaults import PIPELINE_FUSE_MIN_ROWS

    pinned = int(_config.get("pipeline.fuse_min_rows") or 0)
    if pinned > 0:
        return pinned
    tuned = _autotune.lookup("pipeline.fuse_min_rows", n=n)
    if tuned:
        return int(tuned)
    return int(PIPELINE_FUSE_MIN_ROWS)


def _chain_streaming_capable(stage: Any) -> bool:
    """Whether the stage's streamed fit can apply an upstream chain in-program."""
    fit = getattr(stage, "_streaming_fit", None)
    if fit is None:
        return False
    try:
        return "chain_ops" in inspect.signature(fit).parameters
    except (TypeError, ValueError):
        return False


def _terminal_fuse_eligible(stage: Any) -> bool:
    """Static (param-level) fuse-eligibility of a chain's terminal estimator.
    These mirror the conditions under which the estimator's own streamed fit
    would route in-core or run a non-fusable variant — the fuser must know
    BEFORE fitting, so the staged path can carry those configurations
    (docs/design.md §6k eligibility table)."""
    if not (_isTpuEstimator(stage) and _chain_streaming_capable(stage)):
        return False
    if stage._use_cpu_fallback():
        return False
    # cosine KMeans normalizes rows host-side per batch — not expressible as a
    # post-chain in-program op today
    if (
        stage.hasParam("distanceMeasure")
        and stage.getOrDefault("distanceMeasure") != "euclidean"
    ):
        return False
    # huber has no sufficient-statistics form; its fit is in-core
    if stage.hasParam("loss") and stage.getOrDefault("loss") == "huber":
        return False
    # box-constrained logistic fits route in-core
    for name in (
        "lowerBoundsOnCoefficients", "upperBoundsOnCoefficients",
        "lowerBoundsOnIntercepts", "upperBoundsOnIntercepts",
    ):
        if (
            stage.hasParam(name)
            and stage.isDefined(name)
            and stage.getOrDefault(name) is not None
        ):
            return False
    return True


def _featurizer_fuse_eligible(stage: Any) -> bool:
    """Whether a stage can contribute a chain op: a TPU featurizer estimator
    whose fitted model exposes `_chain_op` (StandardScaler, PCA — marked via
    the `_chain_featurizer` class attribute is unnecessary; the model contract
    is checked after fit, the estimator contract here)."""
    return (
        _isTpuEstimator(stage)
        and _chain_streaming_capable(stage)
        and not stage._use_cpu_fallback()
        and stage.hasParam("outputCol")
    )


def _stage_input_cols(stage: Any) -> Tuple[Optional[str], Optional[List[str]]]:
    getter = getattr(stage, "_get_input_columns", None)
    if getter is None:
        return None, None
    return getter()


class Pipeline(Params):
    """Sequential stages; estimators are fit then their models transform
    (pyspark.ml.Pipeline semantics + the assembler bypass + §6k chain fusion)."""

    def __init__(self, stages: Optional[List[Any]] = None) -> None:
        super().__init__()
        self._stages = stages or []
        self.logger = get_logger(self.__class__)

    def getStages(self) -> List[Any]:
        return self._stages

    def setStages(self, value: List[Any]) -> "Pipeline":
        self._stages = value
        return self

    def copy(self, extra: Optional[ParamMap] = None) -> "Pipeline":
        """Copy with `extra` routed to the stages that own each param (by the
        param's parent uid when it names a stage, by param name otherwise —
        fitMultiple/CrossValidator grids address stage params, not pipeline
        params)."""
        that = super().copy(None)
        extra = extra or {}
        stage_uids = {getattr(s, "uid", None) for s in self._stages}

        def stage_extra(s: Any) -> ParamMap:
            out: ParamMap = {}
            for p, v in extra.items():
                parent = getattr(p, "parent", None)
                if parent in stage_uids:
                    if parent == getattr(s, "uid", None):
                        out[p] = v
                elif hasattr(s, "hasParam") and s.hasParam(p.name):
                    out[p] = v
            return out

        that._stages = [
            s.copy(stage_extra(s)) if hasattr(s, "copy") else s
            for s in self._stages
        ]
        return that  # type: ignore[return-value]

    def fit(self, dataset: Any, params: Optional[ParamMap] = None) -> "PipelineModel":
        if params:
            return self.copy(params)._fit(dataset)
        return self._fit(dataset)

    def fitMultiple(self, dataset: Any, paramMaps: List[ParamMap]):
        """Fit one PipelineModel per param map. All candidates share ONE
        feature-extraction memo and ONE HBM batch-cache scope: when the
        candidates fuse (§6k), every fit streams the SAME pinned host arrays,
        so pass 1 of candidate 1 uploads each raw batch once and every later
        pass — of every candidate — replays it from HBM
        (ops/device_cache.py)."""
        from .core.estimator import _FitMultipleIterator
        from .ops.device_cache import batch_cache

        memo: Dict[Any, Any] = {}
        with batch_cache():
            models = [
                self.copy(m)._fit(dataset, _extract_memo=memo) for m in paramMaps
            ]
        return _FitMultipleIterator(lambda i: models[i], len(paramMaps))

    def _fit(
        self, dataset: Any, _extract_memo: Optional[Dict[Any, Any]] = None
    ) -> "PipelineModel":
        stages = list(self._stages)

        # assembler bypass (reference pipeline.py:85-119): VectorAssembler feeding a
        # TPU estimator's featuresCol becomes featuresCols on the estimator directly
        for i in range(len(stages) - 1):
            a, b = stages[i], stages[i + 1]
            if (
                _isVectorAssembler(a)
                and _isTpuEstimator(b)
                and a.isDefined("outputCol")
                and b.hasParam("featuresCol")
                and b.getOrDefault("featuresCol") == a.getOrDefault("outputCol")
                and b.hasParam("featuresCols")
            ):
                self.logger.info(
                    "Bypassing VectorAssembler '%s' -> feeding %d scalar columns "
                    "directly to %s",
                    a.uid,
                    len(a.getOrDefault("inputCols")),
                    type(b).__name__,
                )
                # bypass on a COPY: mutating the user's estimator would corrupt its
                # reuse outside this pipeline (pyspark's Pipeline.fit also never
                # mutates the supplied stages)
                b = b.copy()
                b._set(featuresCols=a.getOrDefault("inputCols"))
                b._clear(b.getParam("featuresCol"))
                stages[i + 1] = b
                stages[i] = NoOpTransformer()

        chain_start = self._fuse_chain_start(stages, dataset)

        fitted: List[Any] = []
        for idx, stage in enumerate(stages):
            if chain_start is not None and idx == chain_start:
                chain_models = self._fused_chain_fit(
                    stages[idx:], dataset, _extract_memo
                )
                if chain_models is not None:
                    fitted.extend(chain_models)
                    break
                # data-level gates declined (sparse input, below threshold):
                # fall through to the staged loop for the remaining stages
                chain_start = None
            if hasattr(stage, "_get_tpu_fit_func") or (
                hasattr(stage, "fit") and not hasattr(stage, "transform")
            ):
                model = stage.fit(dataset)
                fitted.append(model)
                dataset = model.transform(dataset)
            elif hasattr(stage, "transform"):
                fitted.append(stage)
                dataset = stage.transform(dataset)
            else:
                raise TypeError(f"Pipeline stage {stage} is neither fit-able nor transform-able")
        return PipelineModel(fitted)

    # ---- §6k whole-pipeline fusion ----

    def _fuse_chain_start(self, stages: List[Any], dataset: Any) -> Optional[int]:
        """Index where a fusable featurize->fit SUFFIX chain begins, or None.
        Structural + cheap gates only (stage types, column linkage, config,
        row count); data-level gates (sparsity, stream threshold) run after
        extraction in _fused_chain_fit."""
        from . import config as _config

        if len(stages) < 2 or not bool(_config.get("pipeline.fuse")):
            return None
        from .core.dataset import _is_spark_df

        if _is_spark_df(dataset):
            return None  # the barrier/collect planes own Spark inputs
        term = stages[-1]
        if not _terminal_fuse_eligible(term):
            return None
        start = len(stages) - 1
        while start > 0 and _featurizer_fuse_eligible(stages[start - 1]):
            prev = stages[start - 1]
            cur_in, cur_in_cols = _stage_input_cols(stages[start])
            if cur_in_cols is not None or cur_in != prev.getOrDefault("outputCol"):
                break  # not column-linked: the chain cannot absorb this stage
            start -= 1
        if start == len(stages) - 1:
            return None  # no featurizer feeds the terminal — nothing to fuse
        # uniform compute dtype across the chain: one in-program cast discipline
        f32 = {bool(s._float32_inputs) for s in stages[start:]}
        if len(f32) != 1:
            return None
        try:
            n_rows = len(dataset)
        except TypeError:
            n_rows = int(getattr(dataset, "num_rows", 0))
        if n_rows < _resolve_fuse_min_rows(n=n_rows):
            return None
        # degenerate single-class logistic fits route in-core; detect up front
        # so the staged path carries them instead of a mid-chain error
        if type(term).__name__ == "LogisticRegression":
            import numpy as np

            label_col = term.getOrDefault("labelCol")
            try:
                labels = np.asarray(dataset[label_col], dtype=np.float64)
            except Exception:
                return None
            if np.unique(labels[~np.isnan(labels)]).size <= 1:
                return None
        return start

    def _fused_chain_fit(
        self,
        chain: List[Any],
        dataset: Any,
        extract_memo: Optional[Dict[Any, Any]] = None,
    ) -> Optional[List[Any]]:
        """Fit a featurize->fit chain as one fused streamed program per batch.
        Returns the fitted models in stage order, or None when a data-level
        gate declines (caller falls back to the staged loop)."""
        from . import config as _config
        from .core.dataset import extract_feature_data
        from .observability import counter_inc as obs_counter_inc, fit_run
        from .ops.device_cache import batch_cache

        first, term = chain[0], chain[-1]
        for est in chain:
            est._validate_param_bounds()
        input_col, input_cols = _stage_input_cols(first)
        label_col = (
            term.getOrDefault("labelCol")
            if term._use_label() and term.hasParam("labelCol")
            else None
        )
        weight_col = (
            term.getOrDefault("weightCol") if term._use_sample_weight() else None
        )
        fd_key = (
            input_col,
            tuple(input_cols) if input_cols else None,
            label_col,
            weight_col,
            bool(first._float32_inputs),
        )
        fd = extract_memo.get(fd_key) if extract_memo is not None else None
        if fd is None:
            fd = extract_feature_data(
                dataset,
                input_col=input_col,
                input_cols=input_cols,
                label_col=label_col,
                weight_col=weight_col,
                float32=first._float32_inputs,
            )
            if extract_memo is not None:
                extract_memo[fd_key] = fd
        if fd.is_sparse:
            return None  # sparse chains stay staged (no dense chain ops)
        threshold = int(_config.get("stream_threshold_bytes") or 0)
        feature_bytes = fd.n_rows * fd.n_cols * (4 if first._float32_inputs else 8)
        if not threshold or feature_bytes <= threshold:
            return None  # in-core scale: the staged path is faster to compile
        chain_names = [type(est).__name__ for est in chain]
        self.logger.info(
            "fusing pipeline chain %s into one streamed program per batch "
            "(~%.0f MiB design matrix)",
            " -> ".join(chain_names),
            feature_bytes / 2**20,
        )
        # one parent run spans the chain so the §6f ingest section and the
        # fused-stage counter land in one exported report; each stage fit still
        # opens its own nested FitRun exactly like a staged fit would
        with fit_run(algo="Pipeline") as prun:
            fitted: List[Any] = []
            chain_ops: List[Tuple] = []
            kinds: List[str] = []
            # ONE batch-cache scope spans every stage: the chain's shared INPUT
            # batches upload once, later stages replay them from HBM
            with batch_cache():
                for est in chain:
                    model = _fused_stage_fit(est, fd, tuple(chain_ops))
                    fitted.append(model)
                    if est is not term:
                        op = model._chain_op()
                        chain_ops.append(op)
                        kinds.append(str(op[0]))
            label = ">".join(kinds + [type(term).__name__.lower()])
            obs_counter_inc("pipeline.fused_stages", len(chain), chain=label)
        report = prun.report() if prun is not None else None
        for model in fitted:
            model.pipeline_report_ = report
        return fitted


def _fused_stage_fit(est: Any, fd: Any, chain_ops: Tuple) -> Any:
    """One chain stage's fit, mirroring _TpuEstimator._fit/_fit_internal
    (core/estimator.py) with the streamed path forced and the upstream chain
    applied in-program."""
    from .observability import fit_run

    with fit_run(algo=type(est).__name__) as run:
        attrs = est._streaming_fit(fd, chain_ops=chain_ops or None)
        model = est._create_pyspark_model(attrs)
        model._num_workers = est._num_workers
        model._float32_inputs = est._float32_inputs
        model._has_training_summary = True
        est._copyValues(model)
    if run is not None:
        model.fit_report_ = run.report()
    return model


class PipelineModel(Params):
    def __init__(self, stages: List[Any]) -> None:
        super().__init__()
        self.stages = stages

    def transform(self, dataset: Any, params: Optional[ParamMap] = None) -> Any:
        for stage in self.stages:
            dataset = stage.transform(dataset)
        return dataset
