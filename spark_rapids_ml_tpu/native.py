#
# ctypes bindings for the native host-runtime library (native/src/srml_native.cpp) —
# the role the reference fills with cuDF/treelite/RMM native code on the host side
# (SURVEY.md §2.5). Every entry point has a numpy fallback so the pure-Python install
# keeps working when the .so has not been built (native/build.sh).
#

from __future__ import annotations

import ctypes
import os
from typing import Optional, Tuple

import numpy as np

from .utils import get_logger

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    path = os.path.join(os.path.dirname(__file__), "lib", "libsrml_native.so")
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.srml_bin_features.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ]
        lib.srml_csr_to_dense.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
        ]
        lib.srml_topk_merge.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.srml_csr_to_ell.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.srml_num_threads.restype = ctypes.c_int
        _lib = lib
        get_logger("native").info(
            "loaded libsrml_native.so (%d threads)", lib.srml_num_threads()
        )
    except OSError as e:  # pragma: no cover
        get_logger("native").warning("failed to load native library: %s", e)
        _lib = None
    return _lib


def available() -> bool:
    return _load() is not None


def bin_features(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Digitize X against per-feature edges; native when built, numpy otherwise.
    Semantics: searchsorted(side='left') per feature (ops/trees.py)."""
    lib = _load()
    X = np.ascontiguousarray(X, dtype=np.float32)
    edges = np.ascontiguousarray(edges, dtype=np.float32)
    n, d = X.shape
    if lib is not None:
        out = np.empty((n, d), dtype=np.int32)
        # X/edges are bound locals; they outlive the C call
        lib.srml_bin_features(
            X.ctypes.data, n, d, edges.ctypes.data, edges.shape[1] + 1, out.ctypes.data
        )
        return out
    out = np.empty((n, d), dtype=np.int32)
    for j in range(d):
        out[:, j] = np.searchsorted(edges[j], X[:, j], side="left")
    return out


def csr_to_dense(indptr: np.ndarray, indices: np.ndarray, data: np.ndarray,
                 n: int, d: int, dtype=np.float32) -> np.ndarray:
    lib = _load()
    if lib is not None and np.dtype(dtype) == np.float32:
        # the converted arrays MUST stay bound to locals until after the C call —
        # .ctypes.data is a bare pointer that does not keep its array alive
        indptr64 = np.ascontiguousarray(indptr, np.int64)
        indices32 = np.ascontiguousarray(indices, np.int32)
        data32 = np.ascontiguousarray(data, np.float32)
        out = np.empty((n, d), dtype=np.float32)
        lib.srml_csr_to_dense(
            indptr64.ctypes.data, indices32.ctypes.data, data32.ctypes.data,
            n, d, out.ctypes.data,
        )
        return out
    import scipy.sparse as sp

    return np.asarray(
        sp.csr_matrix((data, indices, indptr), shape=(n, d)).todense(), dtype
    )


def csr_to_ell(
    indptr: np.ndarray, indices: np.ndarray, data: np.ndarray, n: int, r_max: int
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Native CSR->ELL conversion (ops/sparse.py layout). Returns None when the
    library is absent or dtypes need int64 (the numpy path handles those)."""
    lib = _load()
    if lib is None:
        return None
    indptr64 = np.ascontiguousarray(indptr, np.int64)
    indices32 = np.ascontiguousarray(indices, np.int32)
    data32 = np.ascontiguousarray(data, np.float32)
    values = np.empty((n, r_max), np.float32)
    cols = np.empty((n, r_max), np.int32)
    lib.srml_csr_to_ell(
        indptr64.ctypes.data, indices32.ctypes.data, data32.ctypes.data,
        n, r_max, values.ctypes.data, cols.ctypes.data,
    )
    return values, cols


def topk_merge(dists: np.ndarray, ids: np.ndarray, k: int) -> Tuple[np.ndarray, np.ndarray]:
    """Merge per-shard candidates (nq, n_cand) into global ascending top-k."""
    lib = _load()
    dists = np.ascontiguousarray(dists, np.float32)
    ids = np.ascontiguousarray(ids, np.int64)
    nq, n_cand = dists.shape
    if lib is not None:
        out_d = np.empty((nq, k), np.float32)
        out_i = np.empty((nq, k), np.int64)
        # dists/ids are bound locals; they outlive the C call
        lib.srml_topk_merge(
            dists.ctypes.data, ids.ctypes.data, nq, n_cand, k,
            out_d.ctypes.data, out_i.ctypes.data,
        )
        return out_d, out_i
    order = np.argsort(dists, axis=1)[:, :k]
    return np.take_along_axis(dists, order, axis=1), np.take_along_axis(ids, order, axis=1)
