#
# Driver/worker utilities (structural equivalent of reference
# python/src/spark_rapids_ml/utils.py).  GPU/RMM-specific pieces of the reference have no
# TPU analog and are replaced by mesh/partition helpers in spark_rapids_ml_tpu.parallel.
#

from __future__ import annotations

import logging
import sys
from typing import Any, Iterator, List, Optional, Tuple, Union

import numpy as np

_LOG_FORMAT = "%(asctime)s %(levelname)s %(name)s: %(message)s"


def get_logger(cls: Any, level: Union[int, str] = logging.INFO) -> logging.Logger:
    """Per-class logger (reference utils.py:555-576)."""
    name = cls if isinstance(cls, str) else getattr(cls, "__name__", str(cls))
    logger = logging.getLogger(f"spark_rapids_ml_tpu.{name}")
    logger.setLevel(level)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_LOG_FORMAT))
        logger.addHandler(handler)
        logger.propagate = False
    return logger


def _get_default_params_from_func(func: Any, unsupported_set: Optional[set] = None) -> dict:
    """Introspect a callable's keyword defaults (reference utils.py:87-105 uses this to
    pull cuML constructor defaults; here used for sklearn fallback twins)."""
    import inspect

    unsupported_set = unsupported_set or set()
    sig = inspect.signature(func)
    return {
        name: p.default
        for name, p in sig.parameters.items()
        if p.default is not inspect.Parameter.empty and name not in unsupported_set
    }


def dtype_to_float32(arr: np.ndarray) -> np.ndarray:
    if arr.dtype != np.float32:
        return arr.astype(np.float32)
    return arr


def concat_arrays(chunks: List[np.ndarray], order: str = "C") -> np.ndarray:
    """Memory-aware concat of per-batch arrays into one contiguous array
    (reference utils.py:358-400 `_concat_and_free`)."""
    if len(chunks) == 1:
        arr = chunks[0]
        return np.asarray(arr, order=order)  # type: ignore[arg-type]
    total_rows = sum(c.shape[0] for c in chunks)
    if chunks[0].ndim == 1:
        out = np.empty((total_rows,), dtype=chunks[0].dtype)
    else:
        out = np.empty((total_rows, chunks[0].shape[1]), dtype=chunks[0].dtype, order=order)  # type: ignore[call-overload]
    offset = 0
    while chunks:
        c = chunks.pop(0)
        out[offset : offset + c.shape[0]] = c
        offset += c.shape[0]
        del c
    return out


def chunk_rows(n_rows: int, max_bytes: int, row_bytes: int) -> List[Tuple[int, int]]:
    """Split n_rows into (start, end) chunks of at most max_bytes
    (reference clustering.py:437-454 chunking of model rows vs the 2GB limit)."""
    rows_per_chunk = max(1, max_bytes // max(1, row_bytes))
    return [(s, min(s + rows_per_chunk, n_rows)) for s in range(0, n_rows, rows_per_chunk)]


def pad_to_multiple(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple
