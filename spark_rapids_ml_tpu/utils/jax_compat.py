#
# jax API compatibility shims. The tree targets current jax (top-level
# `jax.shard_map`, `check_vma=`), but hermetic CI images may pin an older
# release where shard_map still lives in jax.experimental and the replication
# check is spelled `check_rep`. Reliability starts with being runnable: every
# shard_map call site imports from here so one pinned-version delta doesn't
# take down the whole suite.
#

from __future__ import annotations

try:  # current jax: top-level export, check_vma kwarg
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # older jax: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, **kwargs):
    """`jax.shard_map` with the replication-check kwarg translated for the
    installed jax version. Call sites write `check_vma=` (the current name)."""
    if "check_vma" in kwargs and _CHECK_KW != "check_vma":
        kwargs[_CHECK_KW] = kwargs.pop("check_vma")
    return _shard_map(f, **kwargs)


def pvary(x, axis_names):
    """`jax.lax.pvary` (mark a value as varying over manual mesh axes, needed by
    the current varying-axes type system) — identity on older jax, whose
    shard_map with the replication check off never tracks variance."""
    import jax

    fn = getattr(jax.lax, "pvary", None)
    return x if fn is None else fn(x, axis_names)
