#
# Distributed transform data plane for Spark inputs — the structural replacement for
# the reference's per-partition pandas-UDF transform (reference core.py:1846-1899):
# the model is broadcast ONCE, each executor reconstructs it ONCE per python worker
# process, and partitions stream through `mapInPandas` without ever materializing the
# dataset on the driver (the pre-round-2 path collected the whole input via toPandas,
# which is a driver OOM at reference scale — VERDICT round 1, missing #2).
#
# Output schema is inferred from a ONE-ROW driver-side probe: `limit(1).toPandas()`
# runs the model's pandas transform on a single row and the resulting dtypes/cell
# shapes are translated to a Spark DDL schema string. This keeps the plane fully
# independent of pyspark imports (everything speaks the DataFrame protocol:
# limit/toPandas/mapInPandas/sparkSession.sparkContext.broadcast), so it is testable
# against a protocol mock in images without pyspark and runs unchanged on a real
# cluster.
#

from __future__ import annotations

import contextlib
from typing import Any, Dict

import numpy as np
import pandas as pd

from ..utils import get_logger

# per-python-worker-process model cache: one deserialization per broadcast, not per
# batch/partition (the reference caches via `_construct_cuml_object` once per task,
# core.py:1868-1878; caching per process is strictly better). FIFO-bounded: a
# CrossValidator broadcasts a fresh payload per fold, and an unbounded dict would
# pin every fold's deserialized model list in executor memory for the process
# lifetime.
_WORKER_MODELS: Dict[Any, Any] = {}
_WORKER_MODELS_MAX = 4

# Spark torrent broadcast caps a single value at 8 GiB; large models (UMAP holds
# embedding + raw data) ship as multiple chunked broadcasts the worker reassembles
# (the reference's <=8 GiB chunked model broadcast, umap.py:1404-1446)
BROADCAST_CHUNK_BYTES = (8 << 30) - (64 << 20)


def _broadcast_chunked(sc: Any, payload: bytes) -> list:
    return [
        sc.broadcast(payload[i : i + BROADCAST_CHUNK_BYTES])
        for i in range(0, len(payload), BROADCAST_CHUNK_BYTES)
    ]


@contextlib.contextmanager
def _without_reports(models: list):
    """Strip observability reports off models for the duration of a pickle:
    `fit_report_`/`transform_report_` are driver-side OUTPUT (trace trees,
    events, per-worker breakdowns) and would otherwise ride every executor
    broadcast — pure payload for the workers, who produce their own metrics."""
    stripped = []
    for m in models:
        s = {
            k: m.__dict__.pop(k)
            for k in ("fit_report_", "transform_report_")
            if k in m.__dict__
        }
        stripped.append((m, s))
    try:
        yield
    finally:
        for m, s in stripped:
            m.__dict__.update(s)


def _broadcast_key(b: Any) -> Any:
    """Stable per-broadcast cache key. Spark broadcast ids start at 0, so an
    `or`-style falsy fallback would silently key the FIRST broadcast of a context
    by Python object identity — which differs per task (the closure re-deserializes
    the Broadcast wrapper), defeating the cache and churning the FIFO.

    Executor-side real-pyspark Broadcast objects expose neither `id` nor `_bid`
    in Python — only `_path` (the spill file the driver wrote), which is unique
    per broadcast and stable across tasks on one executor, so it serves as the
    cache key there (without it every task would re-deserialize the full model
    payload — correct but slow for large UMAP models)."""
    for attr in ("id", "_bid"):
        v = getattr(b, attr, None)
        if v is not None:
            return ("bid", v)
    path = getattr(b, "_path", None)
    if path:
        return ("path", str(path))
    return None  # no stable id exposed


def _worker_model(bcasts: list) -> Any:
    import pickle

    keys = [_broadcast_key(b) for b in bcasts]
    if any(k is None for k in keys):
        # no stable broadcast id: do NOT cache — a python id() key can collide
        # after GC (reused worker, same malloc address) and silently return the
        # wrong model
        return pickle.loads(b"".join(bytes(b.value) for b in bcasts))
    key = tuple(keys)
    model = _WORKER_MODELS.get(key)
    if model is None:
        model = pickle.loads(b"".join(bytes(b.value) for b in bcasts))
        while len(_WORKER_MODELS) >= _WORKER_MODELS_MAX:
            _WORKER_MODELS.pop(next(iter(_WORKER_MODELS)))
        _WORKER_MODELS[key] = model
    return model


def _ddl_type_of(series: pd.Series) -> str:
    """Spark DDL type for a pandas column (cell-inspecting for array columns)."""
    from pandas.api import types as ptypes

    dt = series.dtype
    if ptypes.is_bool_dtype(dt):
        return "boolean"
    if ptypes.is_integer_dtype(dt):
        return "bigint"
    if dt == np.float32:
        return "float"
    if ptypes.is_float_dtype(dt):
        return "double"
    if ptypes.is_string_dtype(dt) and not ptypes.is_object_dtype(dt):
        return "string"
    if len(series) == 0:
        return "string"
    cell = series.iloc[0]
    if isinstance(cell, (list, tuple, np.ndarray)):
        inner = np.asarray(cell)
        if inner.dtype == np.float32:
            return "array<float>"
        if np.issubdtype(inner.dtype, np.integer):
            return "array<bigint>"
        return "array<double>"
    if isinstance(cell, (bytes, bytearray)):
        return "binary"
    return "string"


def infer_ddl_schema(pdf: pd.DataFrame) -> str:
    """DDL schema string for a pandas frame, e.g. 'id bigint, prediction double'."""
    return ", ".join(f"`{name}` {_ddl_type_of(pdf[name])}" for name in pdf.columns)


def transform_on_spark(model: Any, spark_df: Any) -> Any:
    """Run `model.transform` over a Spark DataFrame as a streaming per-partition
    pandas UDF (reference core.py:1846-1899). The input is never collected to the
    driver; only ONE row is, to infer the output schema.

    Inference-plane observability (docs/design.md §6e): the call runs under a
    driver-side TransformRun; each partition's UDF body opens a worker scope
    whose snapshot — rows/bytes/batches counters, per-batch latency histograms,
    predict shape-bucket telemetry — is delivered back as a metrics sidecar.
    When the partition executes in the driver process while the run is still
    open (the eager protocol-mock plane, local mode), it folds in through the
    same process-aware merge as barrier fit workers; otherwise it lands in the
    executor's global registry live and in the `transform_partials.jsonl`
    sidecar when a metrics dir is configured."""
    import pickle

    from .. import config as _config
    from ..observability import PROCESS_TOKEN
    from ..observability.inference import suppress_transform_runs, transform_run

    logger = get_logger("spark.transform")
    sample = spark_df.limit(1).toPandas()
    if len(sample) == 0:
        raise RuntimeError(
            "Cannot transform an empty DataFrame: the output schema is inferred from "
            "a one-row probe and no rows exist."
        )
    with suppress_transform_runs():
        # the one-row probe is plumbing, not serving traffic: no run of its own,
        # and its rows stay out of the distributed run's totals
        out_sample = model.transform(sample)
    schema = infer_ddl_schema(out_sample)

    sc = spark_df.sparkSession.sparkContext
    with _without_reports([model]):
        bcasts = _broadcast_chunked(sc, pickle.dumps(model))
    metrics_dir = _config.get("observability.metrics_dir")

    with transform_run(type(model).__name__, site="spark") as run:
        # the closure must stay picklable for real executors: primitives only,
        # never the run object itself
        run_id = run.run_id if run is not None else None
        run_traceparent = getattr(run, "traceparent", None)
        driver_token = PROCESS_TOKEN

        def transform_udf(pdf_iter):
            import time as _time

            from ..observability import note_rank_phase, worker_scope
            from ..observability.inference import (
                deliver_partition_snapshot,
                partition_rank,
                suppress_transform_runs as _suppress,
            )
            from ..observability.runs import counter_inc, span as _span

            m = _worker_model(bcasts)
            mname = type(m).__name__
            rank = partition_rank()
            # run_id = the driver TransformRun's trace context (§6g): stamped
            # on the scope so the snapshot — merged live or landed in the
            # transform_partials.jsonl sidecar — joins to exactly one run
            with worker_scope(rank=rank, run_id=run_id,
                              traceparent=run_traceparent) as wscope, \
                    _suppress():
                # delivery rides a finally: an early generator close (downstream
                # limit()) or a mid-partition transform error must still ship
                # the partial scope — the error case is exactly when the
                # telemetry matters most
                t0 = _time.perf_counter()
                rows_total = bytes_total = 0
                try:
                    with _span(
                        "transform.partition", {"model": mname, "rank": rank}
                    ):
                        for pdf in pdf_iter:
                            if len(pdf) == 0:
                                continue
                            nbytes = int(
                                pdf.memory_usage(index=False, deep=False).sum()
                            )
                            counter_inc("transform.bytes", nbytes, model=mname)
                            rows_total += len(pdf)
                            bytes_total += nbytes
                            # rows/batches/latency are counted by the nested
                            # local transform (core/estimator.py::
                            # transform_batch) — one definition, no double count
                            yield m.transform(pdf)
                finally:
                    # per-rank skew material (§6h): partition wall/rows/bytes
                    # feed the driver's comm.rank_skew{phase=} ratios and the
                    # /runs/<id>/ranks timeline, same as barrier fit tasks
                    note_rank_phase(
                        "transform_partition",
                        wall_s=_time.perf_counter() - t0,
                        rows=rows_total, nbytes=bytes_total,
                    )
                    deliver_partition_snapshot(
                        run_id, driver_token, wscope.snapshot(),
                        metrics_dir=metrics_dir,
                    )

        logger.info("distributed transform: schema inferred as [%s]", schema)
        result = spark_df.mapInPandas(transform_udf, schema=schema)
    if run is not None:
        model.transform_report_ = run.report()
    return result
