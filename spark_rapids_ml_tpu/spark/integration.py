#
# Spark barrier-task fan-out for TPU SPMD fits — the structural replacement for the
# reference's `dataset.mapInPandas(_train_udf).rdd.barrier()` execution pattern
# (reference core.py:845-1011) on a TPU-attached Spark cluster.
#
# Architecture (one barrier task per TPU HOST, not per chip — SURVEY.md §7 notes the
# worker=host topology change vs the reference's task↔GPU pinning):
#   1. each task concatenates its partition's Arrow batches to host arrays,
#   2. the barrier allGather carries (a) the jax.distributed coordinator address the
#      way the reference carries the NCCL uid (cuml_context.py:75-110), and (b) the
#      per-task PartitionInfo (row counts) the way the reference builds its
#      PartitionDescriptor (utils.py:325-355),
#   3. jax.distributed.initialize links the hosts; a global mesh spans the pod,
#   4. every task places its rows into the global array via
#      jax.make_array_from_process_local_data and runs the SAME jitted fit program —
#      collectives ride ICI/DCN; rank 0 yields the model-attribute row.
#
# pyspark is imported lazily: this module parses/serializes and orchestrates, and is
# testable without Spark down to the UDF boundary.
#

from __future__ import annotations

import json
import threading
from dataclasses import dataclass
from typing import Any, Callable, List

import numpy as np

from ..utils import get_logger


@dataclass
class PartitionInfo:
    """Per-barrier-task facts exchanged via allGather (the reference's
    PartitionDescriptor payload, utils.py:325-355). For sparse fits the ELL width
    travels too: every host must pad its ELL rows to the GLOBAL max nonzeros-per-row
    before the global array assembles (the sparse analog of the reference's nnz
    exchange, classification.py:1012-1016)."""

    rank: int
    n_rows: int
    coordinator: str = ""  # rank 0 advertises host:port for jax.distributed
    nnz: int = -1  # local nonzeros (sparse fits)
    ell_width: int = 0  # local max nonzeros/row (sparse fits)


def encode_partition_info(info: PartitionInfo) -> str:
    return json.dumps(
        {
            "rank": info.rank,
            "n_rows": info.n_rows,
            "coordinator": info.coordinator,
            "nnz": info.nnz,
            "ell_width": info.ell_width,
        }
    )


def decode_partition_info(payloads: List[str]) -> List[PartitionInfo]:
    infos = [PartitionInfo(**json.loads(p)) for p in payloads]
    return sorted(infos, key=lambda i: i.rank)


def _collect_partition(pdf_iter):
    """Concatenate a task's pandas batches into one DataFrame (the reference's
    executor-side HOT LOOP 1, core.py:906-941). A failure here (fault site
    `barrier_collect`) cannot be retried in-task — the Arrow iterator is
    consumed — so it aborts the stage and recovery happens one rung up:
    fit_on_spark re-runs the whole barrier stage under the RetryPolicy."""
    import pandas as pd

    from ..reliability import fault_point

    fault_point("barrier_collect")
    pdfs = [pdf for pdf in pdf_iter]
    if not pdfs:
        # an empty barrier partition would abort the whole stage with an opaque
        # error; match the reference's actionable empty-partition message
        # (core.py:959-962)
        raise RuntimeError(
            "A barrier task received an empty partition. Repartition the input so "
            "every task holds rows (fewer hosts than rows, avoid skewed keys)."
        )
    return pd.concat(pdfs, ignore_index=True) if len(pdfs) != 1 else pdfs[0]


# Serializes the jitted fit program when multiple barrier TASKS share one
# python process — which only happens in local-mode simulation (the test
# harness runs tasks as threads); production runs one task per TPU host
# process, so the lock is uncontended there. Concurrent XLA dispatch from
# many Python threads has been observed to wedge some jaxlib builds; the
# control plane (collect, allGather, init retry) stays fully concurrent.
_DEVICE_PROGRAM_LOCK = threading.Lock()


# schema of the barrier fit stage's output rows: rank 0 carries the pickled
# model attributes; EVERY rank carries its serialized observability snapshot
# (counters/gauges/histograms/spans/events captured by the task's
# worker_scope), which the driver merges into the fit report —
# `counter_totals()` on the driver is otherwise silently process-local under a
# real multi-host fit (observability/runs.py)
BARRIER_FIT_SCHEMA = "model binary, metrics binary"


def _barrier_train_udf(estimator_payload: bytes, run_id: str = None,
                       traceparent: str = None) -> Callable:
    """Build the barrier mapInPandas UDF. Runs on executors; requires pyspark.
    `run_id` is the driver FitRun's trace context (docs/design.md §6g): it
    travels inside the closure, is stamped on every task's worker scope, and
    comes back on the metrics snapshot so the driver-side merge joins each row
    to exactly one run. `traceparent` is the same run's W3C trace context
    (§6l) riding alongside, so a worker snapshot is joinable to the driver's
    causal trace plane as well."""
    import pickle

    def train_udf(pdf_iter):
        import json as _json

        import pandas as pd
        from pyspark import BarrierTaskContext

        from ..observability import span as _obs_span, worker_scope
        from ..parallel.bootstrap import init_process_group
        from ..parallel.partitioner import active_partitioner

        est = pickle.loads(estimator_payload)
        ctx = BarrierTaskContext.get()
        rank = ctx.partitionId()
        n_tasks = ctx.getTaskInfos().__len__()

        with worker_scope(rank=rank, run_id=run_id,
                          traceparent=traceparent) as wscope:
            attrs = _barrier_task_body(
                est, ctx, rank, n_tasks, pdf_iter, init_process_group,
                active_partitioner, _obs_span,
            )
        # every rank yields exactly one row: rank 0 the model payload, everyone
        # their metrics snapshot. A None in the binary `model` column is a null
        # to Arrow — unlike the empty-DataFrame-against-a-schema case, which is
        # a type-inference crash (the pre-observability rank!=0 behavior was to
        # yield nothing at all for that reason).
        yield pd.DataFrame(
            {
                "model": [pickle.dumps(attrs) if rank == 0 else None],
                "metrics": [_json.dumps(wscope.snapshot()).encode()],
            }
        )

    return train_udf


def _features_nbytes(features: Any) -> Any:
    """Best-effort byte size of a task's ingested feature block (dense ndarray,
    scipy sparse, or pandas) for the per-rank skew record — None when nothing
    exposes a size."""
    nb = getattr(features, "nbytes", None)
    if nb is not None:
        return int(nb)
    data_nb = getattr(getattr(features, "data", None), "nbytes", None)
    if data_nb is not None:  # scipy sparse: data + indices
        idx_nb = getattr(getattr(features, "indices", None), "nbytes", 0)
        return int(data_nb) + int(idx_nb or 0)
    try:
        return int(features.memory_usage(index=False, deep=False).sum())
    except (AttributeError, TypeError, ValueError):
        return None


def _barrier_task_body(est, ctx, rank, n_tasks, pdf_iter, init_process_group,
                       active_partitioner, _obs_span):
    """One barrier task's work, returning the fit-attribute dict (meaningful on
    rank 0). Split from the generator so the task's worker_scope closes — with a
    complete metrics snapshot — before any output row is yielded."""
    import time as _time

    from ..observability import note_rank_phase

    # column resolution/casting goes through the SAME prep as the local path
    # (_use_label gate, float32 handling, idCol — core/estimator.py)
    t_collect = _time.perf_counter()
    with _obs_span("barrier.collect", {"rank": rank}):
        fd = est._pre_process_data(_collect_partition(pdf_iter))
    # per-rank skew material (§6h): this task's ingest wall/rows/bytes travel
    # on the worker-scope snapshot; the driver merge turns them into
    # comm.rank_skew{phase=} ratios, straggler events and the barrier timeline
    note_rank_phase(
        "collect", wall_s=_time.perf_counter() - t_collect,
        rows=fd.n_rows, nbytes=_features_nbytes(fd.features),
    )
    sparse_fit = est._sparse_fit_wanted(fd)
    ell_vals = ell_idx = None
    if sparse_fit:
        from ..ops.sparse import csr_to_ell

        ell_vals, ell_idx = csr_to_ell(fd.features, float32=est._float32_inputs)
    elif fd.is_sparse:
        # no sparse kernel for this estimator: densify locally as usual
        from ..core.dataset import densify

        fd.features = densify(fd.features, est._float32_inputs)

    # control plane: coordinator + partition sizes in one allGather round,
    # then a status round after init so every rank agrees on the outcome.
    # rank 0's reachable address comes from Spark's own task info (hostname
    # resolution can map to loopback). The ephemeral port is probed, closed,
    # and only later bound by init_process_group — a TOCTOU window a
    # concurrent job can race. Losing the race is no longer fatal: the loop
    # re-probes a FRESH port and re-gathers under the RetryPolicy, so a
    # stolen port costs one round instead of the whole barrier stage.
    from .. import profiling
    from ..parallel.bootstrap import reset_process_group
    from ..reliability import RetryPolicy, fault_point

    policy = RetryPolicy.from_config()
    failures = 0
    init_t0 = _time.monotonic()
    while True:
        coordinator = ""
        if rank == 0:
            import socket

            host = ctx.getTaskInfos()[0].address.split(":")[0]
            probe = socket.socket()
            probe.bind(("", 0))
            port = probe.getsockname()[1]
            probe.close()
            coordinator = f"{host}:{port}"
        fault_point("barrier_allgather", batch=failures)
        payloads = ctx.allGather(
            encode_partition_info(
                PartitionInfo(
                    rank,
                    fd.n_rows,
                    coordinator,
                    nnz=int(fd.features.nnz) if sparse_fit else -1,
                    ell_width=int(ell_vals.shape[1]) if sparse_fit else 0,
                )
            )
        )
        infos = decode_partition_info(payloads)
        err = ""
        try:
            fault_point("barrier_init", batch=failures)
            init_process_group(
                coordinator_address=next(
                    i.coordinator for i in infos if i.coordinator
                ),
                num_processes=n_tasks,
                process_id=rank,
            )
        except Exception as e:
            err = f"rank {rank}: {type(e).__name__}: {e}"
        # status round: the outcome list is identical on every rank, so all
        # ranks take the same retry-or-proceed branch (no split-brain). The
        # deadline check uses the MAX gathered elapsed for the same reason —
        # per-rank clocks differ (partition collect times vary) and a
        # rank-local decision could strand peers in the next allGather.
        statuses = [
            json.loads(s)
            for s in ctx.allGather(
                json.dumps(
                    {"err": err, "elapsed": _time.monotonic() - init_t0}
                )
            )
        ]
        errors = [s["err"] for s in statuses if s["err"]]
        if not errors:
            break
        failures += 1
        shared_elapsed = max(s["elapsed"] for s in statuses)
        if policy.give_up(failures, shared_elapsed, "barrier_init"):
            raise RuntimeError(
                "jax.distributed process-group init failed after "
                f"{failures} attempt(s): " + "; ".join(errors)
            )
        profiling.count("reliability.retry")
        profiling.count("reliability.retry.barrier_init")
        from ..observability import event as _obs_event

        _obs_event(
            "retry", site="barrier_init", attempt=failures,
            errors=len(errors),
        )
        reset_process_group()  # drop any partial link before re-probing
        policy.sleep(failures, "barrier_init")

    # global mesh over the pod, owned by the active Partitioner; every host
    # pads its rows to the common local size (XLA needs equal shards), real
    # rows marked by the weight vector. shard_inputs stages ONLY this
    # process's local rows (make_array_from_process_local_data) — no host
    # ever gathers a global array.
    part = active_partitioner()
    mesh = part.mesh

    max_rows = max(i.n_rows for i in infos)
    pad_to = part.local_pad_rows(max_rows)
    w_local = np.zeros((pad_to,), np.float32)
    w_local[: fd.n_rows] = 1.0 if fd.weight is None else fd.weight
    total_rows = sum(i.n_rows for i in infos)

    label_local = None
    if fd.label is not None:
        label_local = np.zeros((pad_to,), np.float32)
        label_local[: fd.n_rows] = fd.label

    if sparse_fit:
        # pad the local ELL width to the GLOBAL max so every host contributes
        # equally-shaped shards, then assemble the global sparse arrays
        r_global = max(i.ell_width for i in infos)
        v_local = np.zeros((pad_to, r_global), ell_vals.dtype)
        i_local = np.zeros((pad_to, r_global), ell_idx.dtype)
        v_local[: fd.n_rows, : ell_vals.shape[1]] = ell_vals
        i_local[: fd.n_rows, : ell_idx.shape[1]] = ell_idx
        w_global, label_global, values_global, indices_global = part.shard_inputs(
            w_local, label_local, v_local, i_local
        )
        fit_inputs = est._build_sparse_fit_inputs_from_global(
            values_global, indices_global, w_global, label_global, total_rows,
            fd.n_cols, mesh,
            rank_rows=[i.n_rows for i in infos],
            nnz=sum(i.nnz for i in infos if i.nnz > 0),
            unit_weight=fd.weight is None,
        )
    else:
        X_local = np.zeros((pad_to, fd.n_cols), np.float32)
        X_local[: fd.n_rows] = np.asarray(fd.features, dtype=np.float32)
        w_global, label_global, X_global = part.shard_inputs(
            w_local, label_local, X_local
        )
        fit_inputs = est._build_fit_inputs_from_global(
            X_global, w_global, label_global, total_rows, mesh,
            rank_rows=[i.n_rows for i in infos],
            unit_weight=fd.weight is None,
        )

    # run the estimator's fit program (same SPMD program on every host). The
    # phase timer starts AFTER the lock, like the span: the lock only exists
    # for the threaded local-mode harness, and queue-position wait there is
    # not rank work — timing it would flag the last-scheduled rank of a
    # healthy fit as a straggler. The straggler injection site fires INSIDE
    # the timed window (batch = RANK), so a spec like
    # `barrier_rank:batch=3:sleep=0.5` drags exactly one chosen rank and the
    # delay lands in that rank's fit_program wall alone (§6h)
    with _DEVICE_PROGRAM_LOCK:
        t_fit = _time.perf_counter()
        fault_point("barrier_rank", batch=rank)
        with _obs_span("barrier.fit_program", {"rank": rank}):
            attrs = est._get_tpu_fit_func(None)(fit_inputs)
        note_rank_phase(
            "fit_program", wall_s=_time.perf_counter() - t_fit, rows=fd.n_rows,
        )

    return attrs


def skip_stage_level_scheduling(spark_version: str, conf: Any) -> bool:
    """Decision matrix for the stage-level-scheduling analog (P7) — mirrors the
    reference's gating (reference core.py:637-696) with TPU resource names: the goal
    is that each TRAINING barrier task pins a whole TPU host while ETL stages share
    executors freely. Returns True when stage-level scheduling must be skipped.

    `conf` needs only a .get(key, default=None) -> Optional[str] surface."""
    logger = get_logger("spark.integration")

    def _get(key: str):
        try:
            return conf.get(key, None)
        except TypeError:
            return conf.get(key)

    if spark_version < "3.4.0":
        logger.info("stage-level scheduling requires spark 3.4.0+")
        return True
    master = _get("spark.master") or ""
    if "3.4.0" <= spark_version < "3.5.1" and not (
        master.startswith("spark://") or master.startswith("local-cluster")
    ):
        logger.info(
            "spark %s requires standalone/local-cluster mode for stage-level "
            "scheduling", spark_version,
        )
        return True
    executor_cores = _get("spark.executor.cores")
    executor_tpus = _get("spark.executor.resource.tpu.amount")
    if executor_cores is None or executor_tpus is None:
        logger.info(
            "stage-level scheduling requires spark.executor.cores and "
            "spark.executor.resource.tpu.amount to be set"
        )
        return True
    if int(executor_cores) == 1:
        logger.info("stage-level scheduling requires spark.executor.cores > 1")
        return True
    if float(executor_tpus) > 1:
        # hosts exposing >1 TPU resource slot: the operator owns the mapping
        logger.info(
            "stage-level scheduling skipped for spark.executor.resource.tpu.amount>1"
        )
        return True
    task_tpus = _get("spark.task.resource.tpu.amount")
    if task_tpus is not None and float(task_tpus) == float(executor_tpus):
        # every task would already serialize on the TPU slot
        return True
    return False


def apply_stage_level_scheduling(rdd: Any, session: Any) -> Any:
    """Attach a ResourceProfile that makes each training task claim >half the
    executor cores + the host's TPU resource, so barrier tasks land one-per-host
    (reference _try_stage_level_scheduling, core.py:697-740). No-op in local mode or
    when the decision matrix says skip."""
    logger = get_logger("spark.integration")
    sc = session.sparkContext
    master = sc.getConf().get("spark.master") or ""
    if master.startswith("local") and not master.startswith("local-cluster"):
        return rdd
    if skip_stage_level_scheduling(session.version, sc.getConf()):
        return rdd

    from pyspark.resource.profile import ResourceProfileBuilder
    from pyspark.resource.requests import TaskResourceRequests

    executor_cores = int(sc.getConf().get("spark.executor.cores"))
    # >half the executor cores forces one training task per executor (the TPU host);
    # the tpu resource request keeps ETL tasks off the chips during training
    task_cores = executor_cores // 2 + 1
    treqs = TaskResourceRequests().cpus(task_cores).resource("tpu", 1.0)
    rp = ResourceProfileBuilder().require(treqs).build
    logger.info(
        "training tasks pinned with ResourceProfile(cores=%d, tpu=1.0)", task_cores
    )
    return rdd.withResources(rp)


def _merge_worker_metrics(rows: Any) -> None:
    """Driver-side aggregation: fold each barrier worker's serialized metrics
    snapshot into the active FitRun (per-worker breakdown + merged totals) and
    into the process-global registry for FOREIGN-process snapshots — on a real
    multi-host fit the executors' counters never touched the driver, which is
    exactly why driver `counter_totals()` used to under-report. Same-process
    snapshots (the threaded local-mode harness) already flowed through the live
    fan-out and are recorded for the breakdown only (observability/runs.py)."""
    from ..observability import PROCESS_TOKEN, current_run, find_run, global_registry

    logger = get_logger("spark.integration")
    fallback_run = current_run()
    for r in rows:
        try:
            blob = r["metrics"]
        except (KeyError, IndexError, TypeError):
            continue  # a foreign/legacy row shape carries no snapshot
        if blob is None:
            continue
        try:
            snap = json.loads(bytes(blob).decode())
            # trace-context join (§6g): a stamped snapshot goes to ITS run;
            # legacy/unstamped snapshots keep the current-run fallback
            run = find_run(snap.get("run_id") or "") or fallback_run
            if run is not None:
                run.add_worker_snapshot(snap)
            elif snap.get("process") != PROCESS_TOKEN:
                global_registry().merge_snapshot(snap.get("metrics") or {})
        except Exception as e:
            # a mis-shaped/version-skewed snapshot (bad JSON, missing keys, a
            # kind conflict with the driver registry) must never fail a fit
            # whose expensive barrier stage already SUCCEEDED — log and move on
            logger.warning(
                "skipping unusable worker metrics snapshot (%s: %s)",
                type(e).__name__, e,
            )


def fit_on_spark(estimator: Any, spark_df: Any, num_hosts: int) -> Any:
    """Driver-side: run a TPU estimator's fit as barrier tasks on a Spark cluster.

    `num_hosts` is the number of TPU HOSTS (== barrier tasks == jax processes), NOT
    the chip count: each host process owns all its local chips and the global mesh
    spans num_hosts × local_device_count devices (SURVEY.md §7's worker=host
    topology). Requires pyspark."""
    import pickle

    from ..reliability import RetryPolicy, is_stage_retryable

    if num_hosts < 1:
        raise ValueError(f"num_hosts must be >= 1, got {num_hosts}")
    logger = get_logger("spark.integration")
    df = spark_df.repartition(num_hosts)
    # trace context: the open FitRun's id rides the UDF closure so every worker
    # snapshot comes back stamped with it (§6g)
    from ..observability import current_run

    run = current_run()
    udf = _barrier_train_udf(
        pickle.dumps(estimator),
        run_id=run.run_id if run is not None else None,
        traceparent=getattr(run, "traceparent", None),
    )
    rdd = df.mapInPandas(udf, schema=BARRIER_FIT_SCHEMA).rdd
    try:
        rdd = apply_stage_level_scheduling(rdd, spark_df.sparkSession)
    except Exception:  # pragma: no cover — never fail a fit over scheduling sugar
        logger.warning("stage-level scheduling unavailable; continuing without")
    barrier_rdd = rdd.barrier().mapPartitions(lambda it: it)
    # whole-stage retry: a dropped barrier task / preempted host fails the stage
    # as one unit (Spark's own barrier semantics), so recovery re-runs the stage
    # under the RetryPolicy; param/programming errors propagate immediately.
    # Exhaustion raises — the caller (core/estimator.py::_fit) owns the next
    # rung of the degradation ladder (collect mode).
    rows = RetryPolicy.from_config().run(
        barrier_rdd.collect, site="barrier_stage", retryable=is_stage_retryable
    )
    payload = next(r["model"] for r in rows if r["model"] is not None)
    attrs = pickle.loads(bytes(payload))
    _merge_worker_metrics(rows)
    model = estimator._create_pyspark_model(attrs)
    model._num_workers = estimator._num_workers
    model._float32_inputs = estimator._float32_inputs
    # freshly-fit marker (same semantics as _fit_internal): training summaries
    # exist on fit() results regardless of the data plane
    model._has_training_summary = True
    estimator._copyValues(model)
    logger.info("fit_on_spark complete: %s", type(model).__name__)
    return model
