# Spark integration layer (L3 of the layer map): barrier-task fan-out glue that runs
# the TPU SPMD fit from inside a Spark cluster. Requires pyspark at call time; the
# pure bookkeeping helpers are importable (and tested) without it.
from .integration import (
    PartitionInfo,
    decode_partition_info,
    encode_partition_info,
    fit_on_spark,
)

__all__ = [
    "PartitionInfo",
    "decode_partition_info",
    "encode_partition_info",
    "fit_on_spark",
]
