#
# Distributed one-pass transform+evaluate for Spark inputs — the structural
# replacement for the reference's executor-side partial-metric scan
# (reference core.py:1572-1693 runs every model's predictions plus partial metric
# aggregation — confusion counts at classification.py:117-159, moment sums at
# regression.py:149-178 — inside ONE mapInPandas pass, merging partials on the
# driver). Here the models and evaluator are broadcast once, each partition
# computes a mergeable partial PER MODEL, and the driver merges — the fold is
# never collected.
#
# Like spark/transform.py, everything speaks the DataFrame protocol
# (mapInPandas/toPandas/sparkSession.sparkContext.broadcast), so the plane is
# testable against a protocol mock in images without pyspark and runs unchanged
# on a real cluster.
#

from __future__ import annotations

import pickle
from typing import Any, List, Sequence

import pandas as pd

from ..utils import get_logger
from .transform import _broadcast_chunked, _without_reports, _worker_model


def _unpersist(bcasts: Any) -> None:
    """Release broadcast blocks once the scan has been fully consumed. Safe here
    (both evaluate entry points execute their scan eagerly via toPandas) but NOT
    in transform_on_spark, whose returned DataFrame is lazy and still needs the
    broadcast at execution time."""
    for b in bcasts:
        unpersist = getattr(b, "unpersist", None)
        if unpersist is not None:
            try:
                unpersist()
            except Exception as e:  # best-effort; a failed release must not fail the scan
                get_logger("spark.evaluate").debug("broadcast unpersist failed: %s", e)


def evaluate_on_spark(evaluator: Any, spark_df: Any) -> float:
    """Distributed `evaluator.evaluate` over an ALREADY-TRANSFORMED Spark frame
    (prediction columns present): per-partition partials, driver merge. Requires
    `evaluator.supportsPartialAggregation()`.

    Observability (§6e): the driver-side scan runs under an `evaluate.scan`
    span; each partition records `evaluate.rows`/`evaluate.partitions` counters
    and an `evaluate.partition` span — the scan is eager (toPandas), so under
    an open Fit/Transform/CV run in this process they land in its trace live."""
    from ..observability import counter_inc as _count, span as _span

    sc = spark_df.sparkSession.sparkContext
    bcasts = _broadcast_chunked(sc, pickle.dumps(evaluator))
    ev_name = type(evaluator).__name__

    def partial_udf(pdf_iter):
        from ..observability import counter_inc, span

        ev = _worker_model(bcasts)
        acc = None
        with span("evaluate.partition", {"evaluator": type(ev).__name__}):
            counter_inc("evaluate.partitions", 1)
            for pdf in pdf_iter:
                if len(pdf) == 0:
                    continue
                counter_inc("evaluate.rows", len(pdf))
                p = ev._partial(pdf)
                acc = p if acc is None else acc.merge(p)
        if acc is not None:
            yield pd.DataFrame({"partial": [pickle.dumps(acc)]})

    try:
        with _span("evaluate.scan", {"evaluator": ev_name}):
            out = spark_df.mapInPandas(partial_udf, schema="partial binary").toPandas()
    finally:
        # always release the chunked broadcasts — an executor failure mid-scan
        # must not leak broadcast blocks on the cluster
        _unpersist(bcasts)
    if len(out) == 0:
        raise RuntimeError("Distributed evaluate produced no partials (empty input?).")
    _count("evaluate.partials", len(out))
    return float(
        evaluator._evaluate_partials(
            [pickle.loads(bytes(b)) for b in out["partial"]]
        )
    )


def transform_evaluate_on_spark(
    models: Sequence[Any], spark_df: Any, evaluator: Any
) -> List[float]:
    """Evaluate all models in one distributed scan; returns one score per model.

    Requires `evaluator.supportsPartialAggregation()`; the caller
    (core/estimator.transform_evaluate_multi) routes non-decomposable evaluators
    to the collect path instead."""
    logger = get_logger("spark.evaluate")
    sc = spark_df.sparkSession.sparkContext
    with _without_reports(list(models)):
        bcasts = _broadcast_chunked(sc, pickle.dumps((list(models), evaluator)))
    n_models = len(models)

    def evaluate_udf(pdf_iter):
        from ..core.estimator import model_eval_frames
        from ..observability import counter_inc, span

        ms, ev = _worker_model(bcasts)
        partials = [None] * len(ms)
        with span(
            "evaluate.partition",
            {"evaluator": type(ev).__name__, "models": len(ms)},
        ):
            counter_inc("evaluate.partitions", 1)
            for pdf in pdf_iter:
                if len(pdf) == 0:
                    continue
                counter_inc("evaluate.rows", len(pdf))
                for i, frame in enumerate(model_eval_frames(ms, pdf, ev)):
                    p = ev._partial(frame)
                    partials[i] = p if partials[i] is None else partials[i].merge(p)
        # one row per model per partition: the scan's whole output is
        # O(n_partitions * n_models) tiny blobs
        rows = [
            (i, pickle.dumps(p)) for i, p in enumerate(partials) if p is not None
        ]
        if rows:
            yield pd.DataFrame(
                {
                    "model_index": pd.array(
                        [r[0] for r in rows], dtype="int64"
                    ),
                    "partial": [r[1] for r in rows],
                }
            )

    logger.info(
        "distributed transform+evaluate: %d model(s), partial-merge scan", n_models
    )
    from ..observability import counter_inc as _count, span as _span

    try:
        with _span(
            "evaluate.scan",
            {"evaluator": type(evaluator).__name__, "models": n_models},
        ):
            out = spark_df.mapInPandas(
                evaluate_udf, schema="model_index bigint, partial binary"
            ).toPandas()
    finally:
        _unpersist(bcasts)
    if len(out) == 0:
        raise RuntimeError(
            "Distributed evaluate produced no partials (empty input?)."
        )
    _count("evaluate.partials", len(out))
    scores: List[float] = []
    for i in range(n_models):
        # every non-empty partition emits a partial for ALL models, so the outer
        # emptiness guard above already covers the no-partials case
        blobs = out[out["model_index"] == i]["partial"]
        scores.append(
            evaluator._evaluate_partials([pickle.loads(bytes(b)) for b in blobs])
        )
    return scores
