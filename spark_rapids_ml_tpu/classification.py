# Public API module mirroring the reference's `spark_rapids_ml.classification`
# (reference python/src/spark_rapids_ml/classification.py: LogisticRegression +
# RandomForestClassifier).
from .models.classification import LogisticRegression, LogisticRegressionModel

try:  # RandomForestClassifier arrives with models/tree.py
    from .models.tree import (  # re-exported surface
        RandomForestClassificationModel,
        RandomForestClassifier,
    )

    __all__ = [
        "LogisticRegression",
        "LogisticRegressionModel",
        "RandomForestClassifier",
        "RandomForestClassificationModel",
    ]
except ImportError:  # pragma: no cover
    __all__ = ["LogisticRegression", "LogisticRegressionModel"]
