#
# Spark Connect plugin, Python half — the operator-dispatch worker the JVM backend
# plugin spawns to run accelerated fits/transforms server-side with NO client code
# change (structural equivalent of reference
# python/src/spark_rapids_ml/connect_plugin.py:68-273).
#
# Wire protocol (framed UTF-8: 4-byte big-endian length + payload, the same framing
# pyspark's write_with_length uses):
#
#   request:  operator_name | params_json | dataset_key | [attributes_json]
#             (attributes_json present only for *Model operators, i.e. transform)
#   response: "OK" | payload            — fit: payload = model-attributes JSON
#                                       — transform: payload = result dataset key
#             "ERR" | message           — on any dispatch failure
#
# Deviation from the reference, by design: model attributes travel as a TAGGED JSON
# DICT (ndarray cells encoded as {"__nd__": nested-list, "dtype": ...}) rather than
# the reference's positional arrays (connect_plugin.py:131-236). The JVM half here is
# ours too (jvm/), so the richer self-describing format costs nothing and removes the
# order-coupling between the two halves.
#
# The pyspark/py4j session-rebuild wrapper (`main`) is only importable with pyspark
# present; `serve`/`dispatch_fit`/`dispatch_transform` below are pure and are
# exercised by the socket-protocol unit test (tests/test_connect_plugin.py).
#

from __future__ import annotations

import importlib
import json
import struct
from typing import Any, BinaryIO, Callable, Dict, Optional, Tuple

import numpy as np

from .utils import get_logger

# operator name -> (estimator "module:class", model "module:class"); the same five
# families the reference dispatches (connect_plugin.py:127-245)
SUPPORTED_OPERATORS: Dict[str, Tuple[str, str]] = {
    "LogisticRegression": (
        "spark_rapids_ml_tpu.classification:LogisticRegression",
        "spark_rapids_ml_tpu.classification:LogisticRegressionModel",
    ),
    "RandomForestClassifier": (
        "spark_rapids_ml_tpu.classification:RandomForestClassifier",
        "spark_rapids_ml_tpu.classification:RandomForestClassificationModel",
    ),
    "RandomForestRegressor": (
        "spark_rapids_ml_tpu.regression:RandomForestRegressor",
        "spark_rapids_ml_tpu.regression:RandomForestRegressionModel",
    ),
    "LinearRegression": (
        "spark_rapids_ml_tpu.regression:LinearRegression",
        "spark_rapids_ml_tpu.regression:LinearRegressionModel",
    ),
    "PCA": (
        "spark_rapids_ml_tpu.feature:PCA",
        "spark_rapids_ml_tpu.feature:PCAModel",
    ),
    "KMeans": (
        "spark_rapids_ml_tpu.clustering:KMeans",
        "spark_rapids_ml_tpu.clustering:KMeansModel",
    ),
}


def _load(path: str) -> type:
    mod, _, cls = path.partition(":")
    return getattr(importlib.import_module(mod), cls)


def _operator_for(name: str) -> Tuple[str, bool]:
    """Map 'KMeansModel' -> ('KMeans', True) and 'KMeans' -> ('KMeans', False)."""
    if name in SUPPORTED_OPERATORS:
        return name, False
    if name.endswith("Model"):
        for base, (_, model_path) in SUPPORTED_OPERATORS.items():
            if model_path.rsplit(":", 1)[1] == name:
                return base, True
    raise RuntimeError(
        f"Unsupported operator: {name}. Supported: {sorted(SUPPORTED_OPERATORS)}"
    )


# ---- tagged-JSON attribute codec ----


def _encode_value(v: Any) -> Any:
    if isinstance(v, np.ndarray):
        return {"__nd__": v.tolist(), "dtype": str(v.dtype)}
    if isinstance(v, (np.floating, np.integer, np.bool_)):
        return v.item()
    if isinstance(v, dict):
        return {k: _encode_value(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_encode_value(x) for x in v]
    return v


def _decode_value(v: Any) -> Any:
    if isinstance(v, dict):
        if "__nd__" in v:
            return np.asarray(v["__nd__"], dtype=np.dtype(v.get("dtype", "float64")))
        return {k: _decode_value(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_decode_value(x) for x in v]
    return v


def encode_model_attributes(attrs: Dict[str, Any]) -> str:
    return json.dumps(_encode_value(attrs))


def decode_model_attributes(payload: str) -> Dict[str, Any]:
    return _decode_value(json.loads(payload))


# ---- dispatch core (pyspark-free) ----


def dispatch_fit(operator_name: str, params: Dict[str, Any], dataset: Any) -> str:
    """Fit the named estimator on the dataset; returns the model-attributes JSON the
    JVM half stores (reference connect_plugin.py:127-139 et al.)."""
    base, is_model = _operator_for(operator_name)
    if is_model:
        raise RuntimeError(f"{operator_name} is a model operator; use dispatch_transform")
    est_cls = _load(SUPPORTED_OPERATORS[base][0])
    model = est_cls(**params).fit(dataset)
    return encode_model_attributes(model.get_model_attributes())


def dispatch_transform(
    operator_name: str, params: Dict[str, Any], attributes_json: str, dataset: Any
) -> Any:
    """Rebuild the named model from its attribute JSON and transform the dataset
    (reference connect_plugin.py:119-125)."""
    base, is_model = _operator_for(operator_name)
    if not is_model:
        raise RuntimeError(f"{operator_name} is an estimator operator; use dispatch_fit")
    model_cls = _load(SUPPORTED_OPERATORS[base][1])
    model = model_cls._from_row(decode_model_attributes(attributes_json))
    if params:
        model._set_params(**params)
    return model.transform(dataset)


# ---- framed wire protocol ----


def write_framed_utf8(out: BinaryIO, s: str) -> None:
    payload = s.encode("utf-8")
    out.write(struct.pack(">i", len(payload)))
    out.write(payload)


def read_framed_utf8(inp: BinaryIO) -> str:
    header = inp.read(4)
    if len(header) < 4:
        raise EOFError("connect-plugin stream closed mid-frame")
    (n,) = struct.unpack(">i", header)
    data = inp.read(n)
    if len(data) < n:
        raise EOFError("connect-plugin stream truncated payload")
    return data.decode("utf-8")


def serve(
    infile: BinaryIO,
    outfile: BinaryIO,
    dataset_resolver: Callable[[str], Any],
    result_registrar: Optional[Callable[[Any], str]] = None,
) -> None:
    """Serve ONE request over the framed protocol.

    `dataset_resolver(key)` materializes the input dataset from its key (py4j object
    id in production; anything the test harness chooses in tests).
    `result_registrar(df)` stores a transform result and returns the key handed back
    to the JVM (the reference returns `_jdf._target_id`, connect_plugin.py:145)."""
    logger = get_logger("connect_plugin")
    try:
        operator_name = read_framed_utf8(infile)
        params = json.loads(read_framed_utf8(infile))
        dataset_key = read_framed_utf8(infile)
        _, is_model = _operator_for(operator_name)
        attributes_json = read_framed_utf8(infile) if is_model else None
        dataset = dataset_resolver(dataset_key)
        logger.info("connect dispatch: %s (model=%s)", operator_name, is_model)
        if is_model:
            result = dispatch_transform(
                operator_name, params, attributes_json or "{}", dataset
            )
            if result_registrar is None:
                raise RuntimeError("transform dispatch requires a result_registrar")
            payload = result_registrar(result)
        else:
            payload = dispatch_fit(operator_name, params, dataset)
    except BaseException as e:  # deliberate: every failure must cross the wire
        logger.exception("connect dispatch failed")
        write_framed_utf8(outfile, "ERR")
        write_framed_utf8(outfile, f"{type(e).__name__}: {e}")
        outfile.flush()
        return
    write_framed_utf8(outfile, "OK")
    write_framed_utf8(outfile, payload)
    outfile.flush()


# ---- production wrapper (requires pyspark + py4j; mirrors reference main()) ----


def main(infile: BinaryIO, outfile: BinaryIO) -> None:
    """JVM-spawned entry: rebuild the SparkSession over the py4j gateway, resolve the
    DataFrame from its object key, then serve the framed request (reference
    connect_plugin.py:68-114 for the session-rebuild sequence)."""
    import py4j
    from py4j.java_gateway import GatewayParameters
    from pyspark import SparkConf, SparkContext
    from pyspark.sql import DataFrame, SparkSession

    auth_token = read_framed_utf8(infile)
    java_sc_key = read_framed_utf8(infile)

    gateway = py4j.java_gateway.JavaGateway(
        gateway_parameters=GatewayParameters(auth_token=auth_token, auto_convert=True)
    )
    jsc = py4j.java_gateway.JavaObject(java_sc_key, gateway._gateway_client)
    sc = SparkContext(conf=SparkConf(_jconf=jsc.sc().conf()), gateway=gateway, jsc=jsc)

    def resolver(dataset_key: str) -> Any:
        jdf = py4j.java_gateway.JavaObject(dataset_key, gateway._gateway_client)
        spark = SparkSession(sc, jdf.sparkSession())
        return DataFrame(jdf, spark)

    def registrar(df: Any) -> str:
        return df._jdf._target_id  # the JVM re-resolves the result by object id

    serve(infile, outfile, resolver, registrar)


if __name__ == "__main__":  # pragma: no cover — production socket bootstrap
    import os
    import socket

    port = int(os.environ["PYTHON_WORKER_FACTORY_PORT"])
    sock = socket.create_connection(("127.0.0.1", port))
    f = sock.makefile("rwb", 65536)
    main(f, f)
