# Public API module mirroring the reference's `spark_rapids_ml.regression`
# (reference python/src/spark_rapids_ml/regression.py: LinearRegression +
# RandomForestRegressor).
from .models.regression import LinearRegression, LinearRegressionModel

try:  # RandomForestRegressor arrives with models/tree.py
    from .models.tree import RandomForestRegressor, RandomForestRegressionModel  # re-exported surface

    __all__ = [
        "LinearRegression",
        "LinearRegressionModel",
        "RandomForestRegressor",
        "RandomForestRegressionModel",
    ]
except ImportError:  # pragma: no cover
    __all__ = ["LinearRegression", "LinearRegressionModel"]
