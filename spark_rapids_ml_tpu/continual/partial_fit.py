#
# Streamed `partial_fit`: persistent sufficient-statistics carries over the
# SAME accumulator kernels the out-of-core fits run (ops/streaming.py), so a
# model keeps learning from update batches after fit with no new math and —
# after warm-up — no new executables.
#
# The shape of every updater is the streaming-kmeans decomposition (arXiv
# 1505.06807): the model state is a small FUNCTIONAL carry of sufficient
# statistics; an update batch folds into it; a per-update `decay` in (0, 1]
# discounts history before each fold (decay = 0.5 ** (1 / half_life_updates);
# 1.0 = the paper's a=1 "infinite memory" setting). Because the carries are
# the checkpoint-resume carries, snapshot/restore reuses
# reliability/checkpoint.py::copy_carry verbatim and every update pass is
# fault-resumable (site "continual") with bit-identical results.
#
# Zero-compile contract (the §7b/§7d extension from index maintenance to
# learning): every update batch is re-blocked to ONE fixed geometry —
# `continual.update_batch_rows` rows, the ragged tail zero-weight padded to a
# full block — so a steady stream of arbitrarily-sized update batches re-enters
# one compiled executable per accumulator kernel. Zero-weight rows are exact
# no-ops in every accumulator (each statistic is a w-weighted sum), so the
# padding changes no bits. Warm-up (the first update + first candidate/score)
# compiles each kernel once; after that, `device.compile` stays flat.
#

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .. import config as _config
from ..observability import counter_inc, convergence as obs_convergence, span as obs_span
from ..observability.device import compiled_kernel
from ..ops._precision import pdot
from ..ops.ingest import StagingPool, stage_block
from ..ops.streaming import (
    _accum_cov,
    _accum_kmeans,
    _accum_linreg,
    _accumulate_stream,
    _finish_logreg,
    _logreg_accum_value_grad,
)
from ..reliability.checkpoint import copy_carry

# MAD scale factor for a normal distribution (sigma = 1.4826 * MAD) — the same
# constant the drift detector and ci/bench_check.py reason with.
_EPS_COUNT = 1e-12


# ------------------------------------------------------------ knob resolution


def resolve_decay() -> float:
    """`continual.decay` resolution: a non-auto config pin wins, then the
    tuning table, then the defaults-module constant (1.0 — forgetting is
    opt-in)."""
    from .. import autotune as _autotune
    from ..autotune.defaults import CONTINUAL_DECAY

    pinned = float(_config.get("continual.decay") or 0.0)
    if pinned > 0.0:
        return pinned
    tuned = _autotune.lookup("continual.decay")
    if tuned:
        return float(tuned)
    return float(CONTINUAL_DECAY)


def resolve_update_batch_rows(n: int, d: int) -> int:
    """`continual.update_batch_rows` resolution: config pin, then tuning table
    per (n, d) bucket, then the defaults-module fixed block geometry."""
    from .. import autotune as _autotune
    from ..autotune.defaults import CONTINUAL_UPDATE_BATCH_ROWS

    pinned = int(_config.get("continual.update_batch_rows") or 0)
    if pinned > 0:
        return pinned
    tuned = _autotune.lookup("continual.update_batch_rows", n=n, d=d)
    if tuned:
        return int(tuned)
    return int(CONTINUAL_UPDATE_BATCH_ROWS)


# ------------------------------------------------------------ residual kernels
#
# Small drift/validation statistics the fit-time kernels don't already
# produce: weighted squared residuals against a FIXED model. Each compiles
# once at warm-up (fixed block geometry) and is shared by the per-update drift
# signal and the holdout validation score.


@compiled_kernel("continual.resid_linear", donate_argnums=(0,))
def _accum_resid_linear(carry, X, y, w, coef, intercept):
    ssr, sw = carry
    dt = ssr.dtype
    X = X.astype(dt)
    y = y.astype(dt)
    w = w.astype(dt)
    r = y - (pdot(X, coef) + intercept)
    return ssr + jnp.sum(w * r * r), sw + jnp.sum(w)


@compiled_kernel("continual.resid_pca", donate_argnums=(0,))
def _accum_resid_pca(carry, X, w, components, mean):
    ssr, sw = carry
    dt = ssr.dtype
    X = X.astype(dt)
    w = w.astype(dt)
    Xc = X - mean
    proj = pdot(Xc, components.T)
    r2 = jnp.sum(Xc * Xc, axis=1) - jnp.sum(proj * proj, axis=1)
    return ssr + jnp.sum(w * jnp.maximum(r2, 0.0)), sw + jnp.sum(w)


# ----------------------------------------------------- fixed-geometry ingest


def _fixed_block_slicer(X, y, w, block_rows: int, dt, pool: StagingPool):
    """Slicer over the PADDED row range [0, ceil(n/block)·block): full natural
    blocks take the zero-copy `stage_block` fast path; the (at most one) tail
    block is staged through a pooled buffer, zero-filled past the valid rows
    with weight 0 — an exact no-op in every w-weighted accumulator, so the
    fixed geometry costs no bits and buys one executable per kernel."""
    n, d = X.shape

    def slicer(s, e):
        valid = min(e, n) - s
        if valid == e - s:
            out = [stage_block(X, s, e, dt, pool, slot="X")]
            if y is not None:
                out.append(stage_block(y, s, e, dt, pool, slot="y"))
            if w is None:
                wb = pool.buffer((e - s,), dt, slot="w1")
                wb[:] = 1.0
            else:
                wb = stage_block(w, s, e, dt, pool, slot="w")
            out.append(wb)
            return tuple(out)
        Xb = pool.buffer((e - s, d), dt, slot="Xpad")
        Xb[valid:] = 0.0
        Xb[:valid] = X[s:s + valid]
        out = [Xb]
        if y is not None:
            yb = pool.buffer((e - s,), dt, slot="ypad")
            yb[valid:] = 0.0
            yb[:valid] = y[s:s + valid]
            out.append(yb)
        wb = pool.buffer((e - s,), dt, slot="wpad")
        wb[valid:] = 0.0
        wb[:valid] = 1.0 if w is None else w[s:s + valid]
        out.append(wb)
        return tuple(out)

    return slicer


def _wsum(X, w) -> float:
    return float(np.sum(w)) if w is not None else float(X.shape[0])


# ------------------------------------------------------------------- updaters


class PartialFitUpdater:
    """Base streamed partial_fit: a persistent carry + the carry lifecycle.

    State machine (docs/design.md §7d): ANCHORED -(update*)-> PENDING
    -(candidate+validate)-> either PROMOTED (rebase: the candidate attrs
    become the new anchor) or REJECTED (carry keeps accumulating toward the
    next attempt). `snapshot()`/`restore()` bound any excursion; both reuse
    the checkpoint layer's donation-safe carry copy."""

    algo = ""
    signal = ""

    def __init__(self, model, name=None, decay=None, update_batch_rows=None,
                 mesh=None):
        self._model = model
        self.name = name or type(model).__name__
        self.decay = resolve_decay() if decay is None else float(decay)
        if not (0.0 < self.decay <= 1.0):
            raise ValueError(
                f"continual.decay must be in (0, 1], got {self.decay}"
            )
        self._ubr = update_batch_rows
        self._mesh = mesh
        self._pool = StagingPool()
        self._dt = np.float32
        self.updates = 0
        self.rows = 0
        self._carry = None
        self._anchor_attrs = None
        self.rebase(dict(model._model_attributes))

    # -- subclass surface -------------------------------------------------
    def _rebase_carry(self, attrs):
        raise NotImplementedError

    def _accum(self, carry, batch):
        raise NotImplementedError

    def _signal_total(self):
        """Host float of the carry's cumulative signal statistic."""
        raise NotImplementedError

    def candidate(self):
        """Model-attrs dict the current carry implies (what a promotion would
        install)."""
        raise NotImplementedError

    def score(self, attrs, X, y=None, w=None):
        """Holdout validation score for an attrs dict — lower is better."""
        raise NotImplementedError

    # -- carry lifecycle --------------------------------------------------
    def rebase(self, attrs) -> None:
        """Re-anchor on an attrs dict (at construction, and after every
        promotion): drift/residual statistics are measured against the
        anchor, so the anchor is always the last weights serving traffic."""
        self._anchor_attrs = dict(attrs)
        self._rebase_carry(self._anchor_attrs)

    def anchor_attrs(self):
        return dict(self._anchor_attrs)

    def snapshot(self):
        return {
            "carry": copy_carry(self._carry),
            "anchor": dict(self._anchor_attrs),
            "updates": self.updates,
            "rows": self.rows,
        }

    def restore(self, snap) -> None:
        self._carry = copy_carry(snap["carry"])
        self._anchor_attrs = dict(snap["anchor"])
        self.updates = int(snap["updates"])
        self.rows = int(snap["rows"])

    # -- the update fold --------------------------------------------------
    def update_batch_rows(self, n: int, d: int) -> int:
        if self._ubr is None:
            self._ubr = resolve_update_batch_rows(n, d)
        return self._ubr

    def _fold(self, carry, accum, X, y, w, block_rows):
        n = X.shape[0]
        padded = -(-n // block_rows) * block_rows
        slicer = _fixed_block_slicer(X, y, w, block_rows, self._dt, self._pool)
        return _accumulate_stream(
            carry, accum, padded, block_rows, self._mesh, slicer,
            site="continual", progress_phase="continual.batches",
        )

    def update(self, X, y=None, w=None):
        """Fold one update batch into the carry: decay history, stream the
        batch through the fixed-geometry blocks, and return the per-row
        signal (the drift detector's observation)."""
        X = np.asarray(X)
        n = int(X.shape[0])
        block_rows = self.update_batch_rows(n, X.shape[1])
        with obs_span("continual.update",
                      {"model": self.name, "rows": n}):
            if self.decay != 1.0:
                self._carry = jax.tree_util.tree_map(
                    lambda a: a * self.decay, self._carry
                )
            before = self._signal_total()
            self._carry = self._fold(self._carry, self._accum, X, y, w,
                                     block_rows)
            bw = _wsum(X, w)
            value = (self._signal_total() - before) / max(bw, _EPS_COUNT)
        self.updates += 1
        self.rows += n
        counter_inc("continual.updates", 1, model=self.name)
        counter_inc("continual.update_rows", n, model=self.name)
        # same convergence axis as the fit (satellite: records carry a
        # process-monotonic `seq` + run-relative `rel_s`), marked as the
        # partial_fit phase so trend windows can split fit vs update
        obs_convergence(self.algo, self.updates,
                        **{self.signal: value},
                        update_rows=n, phase="partial_fit")
        return {"rows": n, "updates": self.updates,
                "signal": self.signal, "value": float(value)}

    def apply_to(self, model=None, attrs=None) -> dict:
        """Install candidate attrs on a model object (the offline, unserved
        path; served models promote through serving.mutate_model)."""
        attrs = attrs if attrs is not None else self.candidate()
        (model or self._model)._model_attributes.update(attrs)
        return attrs


class KMeansUpdater(PartialFitUpdater):
    """Mini-batch KMeans with discounted center updates (arXiv 1505.06807):
    the carry is (Σ w·x per cluster, Σ w per cluster, Σ w·min-d²) against the
    ANCHOR centers, seeded with the anchor's mass (cluster_sizes) so candidate
    centers are the paper's discounted blend of history and fresh data."""

    algo = "kmeans"
    signal = "inertia"

    def _rebase_carry(self, attrs):
        dt = self._dt
        centers = np.asarray(attrs["cluster_centers"], dt)
        k = centers.shape[0]
        sizes = attrs.get("cluster_sizes")
        counts = (np.asarray(sizes, dt) if sizes is not None
                  else np.zeros((k,), dt))
        self._centers = jnp.asarray(centers)
        self._carry = (
            jnp.asarray(centers * counts[:, None]),
            jnp.asarray(counts),
            jnp.zeros((), dt),
        )

    def _accum(self, carry, batch):
        Xb, wb = batch
        return _accum_kmeans(carry, self._centers, Xb, wb)

    def _signal_total(self):
        return float(self._carry[2])

    def candidate(self):
        sums, counts, inertia = self._carry
        sums_h = np.asarray(sums)
        counts_h = np.asarray(counts)
        anchor = np.asarray(self._anchor_attrs["cluster_centers"], self._dt)
        centers = np.where(
            counts_h[:, None] > 0,
            sums_h / np.maximum(counts_h, _EPS_COUNT)[:, None],
            anchor,
        ).astype(self._dt)
        return {
            "cluster_centers": centers,
            "inertia": float(inertia),
            "n_iter": int(self.updates),
            "cluster_sizes": counts_h,
        }

    def score(self, attrs, X, y=None, w=None):
        dt = self._dt
        centers = jnp.asarray(np.asarray(attrs["cluster_centers"], dt))
        k, d = centers.shape
        carry = (jnp.zeros((k, d), dt), jnp.zeros((k,), dt),
                 jnp.zeros((), dt))
        carry = self._fold(
            carry,
            lambda c, b: _accum_kmeans(c, centers, b[0], b[1]),
            np.asarray(X), None, w, self.update_batch_rows(X.shape[0], d),
        )
        return float(carry[2]) / max(_wsum(X, w), _EPS_COUNT)


class LinearRegressionUpdater(PartialFitUpdater):
    """Exact-stats linear regression: the carry is the streamed normal-
    equation statistics (XᵀWX, XᵀWy, Σwx, Σwy, Σw); a candidate is an EXACT
    re-solve (ops/linear.solve_from_stats) from the decayed statistics — no
    SGD approximation needed when the sufficient statistics are this small.
    The served coefficients anchor the drift residual."""

    algo = "linreg"
    signal = "residual"

    def __init__(self, model, reg=None, l1_ratio=None, fit_intercept=None,
                 standardize=None, max_iter=100, tol=1e-6, **kw):
        self._reg = _param(model, "regParam", 0.0) if reg is None else reg
        self._l1r = (_param(model, "elasticNetParam", 0.0)
                     if l1_ratio is None else l1_ratio)
        self._fi = (_param(model, "fitIntercept", True)
                    if fit_intercept is None else fit_intercept)
        self._std = (_param(model, "standardization", True)
                     if standardize is None else standardize)
        self._max_iter = int(max_iter)
        self._tol = float(tol)
        super().__init__(model, **kw)

    def _rebase_carry(self, attrs):
        dt = self._dt
        d = int(np.asarray(attrs["coefficients"]).shape[0])
        self._coef = jnp.asarray(np.asarray(attrs["coefficients"], dt))
        self._intercept = jnp.asarray(np.asarray(attrs["intercept"], dt))
        # stats carry starts empty at construction only: across promotions the
        # exact statistics persist (decay is the only forgetting mechanism)
        if self._carry is None:
            self._carry = (
                (jnp.zeros((d, d), dt), jnp.zeros((d,), dt),
                 jnp.zeros((d,), dt), jnp.zeros((), dt), jnp.zeros((), dt)),
                (jnp.zeros((), dt), jnp.zeros((), dt)),
            )
        else:
            stats, _ = self._carry
            self._carry = (stats, (jnp.zeros((), dt), jnp.zeros((), dt)))

    def _accum(self, carry, batch):
        Xb, yb, wb = batch
        return (
            _accum_linreg(carry[0], Xb, yb, wb),
            _accum_resid_linear(carry[1], Xb, yb, wb, self._coef,
                                self._intercept),
        )

    def _signal_total(self):
        return float(self._carry[1][0])

    def candidate(self):
        from ..ops.linear import solve_from_stats

        (A, b, sx, sy, sw), _ = self._carry
        swf = float(sw)
        if swf <= 0.0:
            raise RuntimeError("partial_fit carry is empty: no update rows")
        res = solve_from_stats(
            A, b, sx / sw, sy / sw, sw,
            reg=float(self._reg), l1_ratio=float(self._l1r),
            fit_intercept=bool(self._fi), standardize=bool(self._std),
            max_iter=self._max_iter, tol=self._tol,
        )[0]
        return {
            "coefficients": np.asarray(res["coefficients"]),
            "intercept": float(res["intercept"]),
            "n_iter": int(res["n_iter"]),
        }

    def score(self, attrs, X, y=None, w=None):
        dt = self._dt
        coef = jnp.asarray(np.asarray(attrs["coefficients"], dt))
        intercept = jnp.asarray(np.asarray(attrs["intercept"], dt))
        carry = (jnp.zeros((), dt), jnp.zeros((), dt))
        carry = self._fold(
            carry,
            lambda c, b: _accum_resid_linear(c, b[0], b[1], b[2], coef,
                                             intercept),
            np.asarray(X), np.asarray(y), w,
            self.update_batch_rows(X.shape[0], X.shape[1]),
        )
        return float(carry[0]) / max(_wsum(X, w), _EPS_COUNT)


class LogisticRegressionUpdater(PartialFitUpdater):
    """Streamed proximal-gradient (FISTA-style single step) logistic
    regression warm-started from the served coefficients: each update folds
    the Kahan-compensated value+grad AT THE ANCHOR plus a Gram pass (the
    Lipschitz source, parameter-independent so it survives promotions); a
    candidate takes one prox step of the accumulated discounted full gradient
    from the anchor — streamed SGD whose minibatch is the whole
    inter-promotion window. The value/grad carry resets on rebase (a gradient
    at the OLD anchor is stale once the anchor moves); the Gram carry and its
    discounted mass persist."""

    algo = "logreg"
    signal = "loss"

    def __init__(self, model, reg=None, l1_ratio=None, fit_intercept=None,
                 **kw):
        self._reg = _param(model, "regParam", 0.0) if reg is None else reg
        self._l1r = (_param(model, "elasticNetParam", 0.0)
                     if l1_ratio is None else l1_ratio)
        self._fi = (_param(model, "fitIntercept", True)
                    if fit_intercept is None else fit_intercept)
        attrs = model._model_attributes
        self._num_classes = int(attrs["num_classes"])
        self._multinomial = np.asarray(attrs["coefficients"]).shape[0] > 1
        super().__init__(model, **kw)

    def _params_from_attrs(self, attrs):
        dt = self._dt
        coef = np.asarray(attrs["coefficients"], np.float64)
        inter = np.asarray(attrs["intercepts"], np.float64)
        if self._multinomial:
            p = np.concatenate([coef, inter[:, None]], axis=1)
        else:
            p = np.concatenate([coef[0], inter])
        return p.astype(dt)

    def _rebase_carry(self, attrs):
        dt = self._dt
        params_h = self._params_from_attrs(attrs)
        d = params_h.shape[-1] - 1
        self._shape = params_h.shape
        self._params_h = params_h.astype(np.float64)
        self._params = jnp.asarray(params_h)
        self._scale = jnp.ones((d,), dt)
        vg = (jnp.zeros((), dt), jnp.zeros((), dt),
              jnp.zeros(self._shape, dt), jnp.zeros(self._shape, dt))
        if self._carry is None:
            gram = (jnp.zeros((d, d), dt), jnp.zeros((d,), dt),
                    jnp.zeros((), dt))
        else:
            _, gram = self._carry
        self._carry = (vg, gram)

    def _accum(self, carry, batch):
        Xb, yb, wb = batch
        if self._multinomial:
            y_enc = (
                jax.nn.one_hot(yb.astype(jnp.int32), self._num_classes,
                               dtype=Xb.dtype)
                * (wb > 0)[:, None]
            )
        else:
            y_enc = yb
        vg = _logreg_accum_value_grad(
            *carry[0], self._params, Xb, y_enc, wb, self._scale, (),
            bool(self._fi), bool(self._multinomial), (),
        )
        return (vg, _accum_cov(carry[1], Xb, wb))

    def _signal_total(self):
        return float(self._carry[0][0])

    def candidate(self):
        from ..ops.linalg import power_iteration_lmax

        (acc_v, _, acc_g, _), (S2, _, sw) = self._carry
        swf = float(sw)
        if swf <= 0.0:
            raise RuntimeError("partial_fit carry is empty: no update rows")
        reg_l1 = float(self._reg) * float(self._l1r)
        reg_l2 = float(self._reg) * (1.0 - float(self._l1r))
        g = np.asarray(acc_g, np.float64) / swf
        coef_s = self._params_h[..., :-1]
        g[..., :-1] += reg_l2 * coef_s
        lmax = float(power_iteration_lmax(S2 / sw))
        lipschitz = (0.5 if self._multinomial else 0.25) * lmax \
            + reg_l2 + 1e-12
        step = 1.0 / lipschitz
        p = self._params_h - step * g
        if reg_l1 > 0.0:
            coef = p[..., :-1]
            p[..., :-1] = np.sign(coef) * np.maximum(
                np.abs(coef) - step * reg_l1, 0.0
            )
        new_coef = p[..., :-1]
        fx = float(acc_v) / swf \
            + 0.5 * reg_l2 * float(np.sum(coef_s * coef_s)) \
            + reg_l1 * float(np.sum(np.abs(new_coef)))
        attrs = _finish_logreg(
            p.reshape(-1), self._shape,
            np.ones((self._shape[-1] - 1,), np.float64),
            bool(self._fi), bool(self._multinomial), self.updates, fx,
        )
        attrs["num_classes"] = self._num_classes
        return attrs

    def score(self, attrs, X, y=None, w=None):
        dt = self._dt
        params = jnp.asarray(self._params_from_attrs(attrs))
        carry = (jnp.zeros((), dt), jnp.zeros((), dt),
                 jnp.zeros(self._shape, dt), jnp.zeros(self._shape, dt))

        def accum(c, b):
            Xb, yb, wb = b
            if self._multinomial:
                y_enc = (
                    jax.nn.one_hot(yb.astype(jnp.int32), self._num_classes,
                                   dtype=Xb.dtype)
                    * (wb > 0)[:, None]
                )
            else:
                y_enc = yb
            return _logreg_accum_value_grad(
                *c, params, Xb, y_enc, wb, self._scale, (),
                bool(self._fi), bool(self._multinomial), (),
            )

        carry = self._fold(
            carry, accum, np.asarray(X), np.asarray(y), w,
            self.update_batch_rows(X.shape[0], X.shape[1]),
        )
        reg_l1 = float(self._reg) * float(self._l1r)
        reg_l2 = float(self._reg) * (1.0 - float(self._l1r))
        coef = np.asarray(attrs["coefficients"], np.float64)
        return float(carry[0]) / max(_wsum(X, w), _EPS_COUNT) \
            + 0.5 * reg_l2 * float(np.sum(coef * coef)) \
            + reg_l1 * float(np.sum(np.abs(coef)))


class PCAUpdater(PartialFitUpdater):
    """Incremental PCA via the streamed covariance accumulator: the carry is
    (Σ wxxᵀ, Σ wx, Σ w) over the update stream (a rank-k model cannot seed the
    full covariance, so the carry is exact statistics of the updates; decay is
    the forgetting mechanism). Drift is the off-subspace residual against the
    served components."""

    algo = "pca"
    signal = "residual"

    def _rebase_carry(self, attrs):
        dt = self._dt
        comps = np.asarray(attrs["components"], dt)
        self._k, d = comps.shape
        self._components = jnp.asarray(comps)
        self._mean = jnp.asarray(np.asarray(attrs["mean"], dt))
        if self._carry is None:
            cov = (jnp.zeros((d, d), dt), jnp.zeros((d,), dt),
                   jnp.zeros((), dt))
        else:
            cov, _ = self._carry
        self._carry = (cov, (jnp.zeros((), dt), jnp.zeros((), dt)))

    def _accum(self, carry, batch):
        Xb, wb = batch
        return (
            _accum_cov(carry[0], Xb, wb),
            _accum_resid_pca(carry[1], Xb, wb, self._components, self._mean),
        )

    def _signal_total(self):
        return float(self._carry[1][0])

    def candidate(self):
        from ..ops.pca import pca_attrs_from_cov

        (S2, sx, sw), _ = self._carry
        swf = float(sw)
        if swf <= 1.0:
            raise RuntimeError(
                "partial_fit carry needs weight > 1 for a covariance"
            )
        mean = sx / sw
        cov = (S2 - sw * jnp.outer(mean, mean)) / (sw - 1.0)
        return pca_attrs_from_cov(cov, mean, sw, self._k)

    def score(self, attrs, X, y=None, w=None):
        dt = self._dt
        comps = jnp.asarray(np.asarray(attrs["components"], dt))
        mean = jnp.asarray(np.asarray(attrs["mean"], dt))
        carry = (jnp.zeros((), dt), jnp.zeros((), dt))
        carry = self._fold(
            carry,
            lambda c, b: _accum_resid_pca(c, b[0], b[1], comps, mean),
            np.asarray(X), None, w,
            self.update_batch_rows(X.shape[0], X.shape[1]),
        )
        return float(carry[0]) / max(_wsum(X, w), _EPS_COUNT)


# ------------------------------------------------------------------- factory


def _param(model, name, default):
    try:
        return model.getOrDefault(name)
    except Exception:
        return default


def partial_fit_updater(model, **kwargs) -> PartialFitUpdater:
    """Dispatch a model object to its updater class by model attributes (the
    models' own `partial_fit_updater()` convenience methods land here)."""
    attrs = getattr(model, "_model_attributes", {})
    if "cluster_centers" in attrs:
        return KMeansUpdater(model, **kwargs)
    if "components" in attrs:
        return PCAUpdater(model, **kwargs)
    if "intercepts" in attrs:
        return LogisticRegressionUpdater(model, **kwargs)
    if "coefficients" in attrs:
        return LinearRegressionUpdater(model, **kwargs)
    raise TypeError(
        f"no partial_fit updater for {type(model).__name__}: expected a "
        "KMeans / PCA / LogisticRegression / LinearRegression model"
    )


__all__ = [
    "KMeansUpdater",
    "LinearRegressionUpdater",
    "LogisticRegressionUpdater",
    "PCAUpdater",
    "PartialFitUpdater",
    "partial_fit_updater",
    "resolve_decay",
    "resolve_update_batch_rows",
]
