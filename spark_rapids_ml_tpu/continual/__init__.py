#
# Continuous-learning plane (docs/design.md §7d): streamed `partial_fit` on
# the out-of-core estimators, drift detection over the convergence plane, and
# governed live promotion through the serving mutate path.
#
# Three layers, composed from finished planes rather than new machinery:
#   partial_fit  persistent sufficient-statistics carries folded by the SAME
#                accumulator kernels the streamed fits run (ops/streaming.py),
#                snapshot/restore via reliability/checkpoint.py, fixed block
#                geometry so a steady update stream adds zero new
#                `device.compile` entries after warm-up
#   drift        median + MAD-floor judgment (the bench_check/autotune
#                measurement discipline) over per-update inertia/loss/
#                residual, emitting `continual.drift{model=,signal=}` into
#                run reports and the flight recorder
#   promotion    validate-on-holdout then swap through serving.mutate_model
#                under the per-entry exec lock (fleet fan-out, monotone
#                `serving.model_generation` bump, never a recompile)
#

from .drift import DriftDetector, baseline_from_convergence, resolve_drift_mads
from .partial_fit import (
    KMeansUpdater,
    LinearRegressionUpdater,
    LogisticRegressionUpdater,
    PCAUpdater,
    PartialFitUpdater,
    partial_fit_updater,
    resolve_decay,
    resolve_update_batch_rows,
)
from .promote import ContinualLoop, PromotionGovernor

__all__ = [
    "ContinualLoop",
    "DriftDetector",
    "KMeansUpdater",
    "LinearRegressionUpdater",
    "LogisticRegressionUpdater",
    "PCAUpdater",
    "PartialFitUpdater",
    "PromotionGovernor",
    "baseline_from_convergence",
    "partial_fit_updater",
    "resolve_decay",
    "resolve_drift_mads",
    "resolve_update_batch_rows",
]
