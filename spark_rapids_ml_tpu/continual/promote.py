#
# Governed promotion: the update -> validate -> promote loop that closes the
# continuous-learning plane (docs/design.md §7d).
#
# A candidate (the attrs the updater's carry implies) must BEAT the incumbent
# anchor on a fixed holdout slice before it touches traffic; the swap then
# rides `serving.mutate_model` — fn(model) under the per-entry exec lock,
# weight refresh, fleet replica fan-out, and a monotone
# `serving.model_generation{model=}` bump — and never recompiles: the
# promoted attrs keep every operand shape, and the holdout scores reuse the
# warmed update kernels at the same fixed block geometry. Rejected candidates
# leave the carry accumulating toward the next attempt; `rollback()` restores
# the pre-promotion attrs through the same governed path.
#

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from .. import config as _config
from ..observability import counter_inc, event, gauge_set, span as obs_span
from ..observability import tracing as _tracing
from .drift import DriftDetector
from .partial_fit import PartialFitUpdater


class PromotionGovernor:
    """Validate-then-promote for one (served model, updater) pair.

    `holdout` is the fixed validation slice: (X,), (X, y) or (X, y, w) —
    whatever the updater's score() needs. `served=False` runs the same
    contract against the bare model object (no registry) for offline use."""

    def __init__(self, name: str, updater: PartialFitUpdater, holdout,
                 registry=None, served: bool = True, tolerance: float = 0.0):
        self.name = name
        self.updater = updater
        self.holdout = tuple(holdout)
        self._registry = registry
        self._served = bool(served)
        self.tolerance = float(tolerance)
        self._previous: Optional[Dict[str, Any]] = None

    def _mutate(self, attrs: Dict[str, Any]) -> Dict[str, Any]:
        def fn(model):
            model._model_attributes.update(attrs)

        if not self._served:
            fn(self.updater._model)
            return {}
        if self._registry is not None:
            return self._registry.mutate(self.name, fn)
        from ..serving.http import mutate_model

        return mutate_model(self.name, fn)

    def try_promote(self) -> Dict[str, Any]:
        """One validate->promote attempt. Returns the decision record."""
        with obs_span("continual.promote", {"model": self.name}):
            try:
                attrs = self.updater.candidate()
            except RuntimeError as e:
                counter_inc("continual.rejected", 1, model=self.name)
                return {"promoted": False, "reason": str(e)}
            cand = self.updater.score(attrs, *self.holdout)
            incumbent = self.updater.anchor_attrs()
            cur = self.updater.score(incumbent, *self.holdout)
            if cand > cur * (1.0 + self.tolerance):
                counter_inc("continual.rejected", 1, model=self.name)
                return {
                    "promoted": False, "reason": "holdout_regression",
                    "candidate_score": cand, "incumbent_score": cur,
                }
            stats = self._mutate(attrs)
            self._previous = incumbent
            self.updater.rebase(attrs)
            counter_inc("continual.promotions", 1, model=self.name)
            event("continual.promotion", model=self.name,
                  generation=stats.get("generation"),
                  candidate_score=cand, incumbent_score=cur)
            return {
                "promoted": True,
                "generation": stats.get("generation"),
                "candidate_score": cand,
                "incumbent_score": cur,
            }

    def rollback(self) -> Dict[str, Any]:
        """Restore the pre-promotion attrs through the same governed mutate
        path (exec lock, refresh, replica fan-out, generation bump)."""
        if self._previous is None:
            raise RuntimeError("nothing to roll back: no promotion recorded")
        attrs = self._previous
        stats = self._mutate(attrs)
        self.updater.rebase(attrs)
        self._previous = None
        counter_inc("continual.rollbacks", 1, model=self.name)
        return {"rolled_back": True, "generation": stats.get("generation")}


class ContinualLoop:
    """The scheduled feed loop: update -> drift-check -> (maybe) promote.

    Synchronous and deterministic — `feed()` folds one update batch, feeds
    the drift detector, and attempts a governed promotion either on drift or
    every `continual.promote_every` updates. `continual.staleness_s{model=}`
    records data-to-traffic latency: the age of the oldest unpromoted update
    at the moment a promotion lands."""

    def __init__(self, name: str, updater: PartialFitUpdater, holdout,
                 registry=None, served: bool = True,
                 detector: Optional[DriftDetector] = None,
                 promote_every: Optional[int] = None,
                 tolerance: float = 0.0):
        self.name = name
        self.updater = updater
        # explicit None-check: a freshly-seeded detector has len() == 0 and
        # would read as falsy under `or`
        self.detector = (detector if detector is not None
                         else DriftDetector(model=name, signal=updater.signal))
        self.governor = PromotionGovernor(name, updater, holdout,
                                          registry=registry, served=served,
                                          tolerance=tolerance)
        self.promote_every = (
            int(_config.get("continual.promote_every"))
            if promote_every is None else int(promote_every)
        )
        self._since_promote = 0
        self._pending_since: Optional[float] = None

    def feed(self, X, y=None, w=None) -> Dict[str, Any]:
        # one trace per feed cycle (§6l): update -> drift -> promote as child
        # spans of a "continual.feed" root, so a generation bump seen by the
        # serving plane is causally joinable back to the batch that caused it
        rt = _tracing.start_trace("continual.feed", model=self.name)
        t0 = time.perf_counter()
        try:
            rep = self.updater.update(X, y=y, w=w)
            t_update = time.perf_counter()
            if self._pending_since is None:
                self._pending_since = time.time()
            drift = self.detector.observe(rep["value"])
            t_drift = time.perf_counter()
            if rt is not None:
                rt.add_span("continual.update", t0, t_update,
                        parent_id=rt.root_span_id,
                        attrs={"rows": rep.get("rows"),
                               "value": rep.get("value")})
                rt.add_span("continual.drift", t_update, t_drift,
                        parent_id=rt.root_span_id)
                if drift is not None:
                    rt.add_event("drift_detected", model=self.name, **drift)
                    rt.flag("drift")
            self._since_promote += 1
            out: Dict[str, Any] = {"update": rep, "drift": drift,
                                   "promotion": None}
            if drift is not None or self._since_promote >= self.promote_every:
                res = self.governor.try_promote()
                t_promote = time.perf_counter()
                self._since_promote = 0
                if res.get("promoted"):
                    staleness = time.time() - self._pending_since
                    gauge_set("continual.staleness_s", round(staleness, 6),
                              model=self.name)
                    res["staleness_s"] = staleness
                    self._pending_since = None
                if rt is not None:
                    rt.add_span("continual.promote", t_drift, t_promote,
                            parent_id=rt.root_span_id,
                            attrs={"promoted": bool(res.get("promoted")),
                                   "reason": res.get("reason")})
                    if res.get("promoted"):
                        rt.add_event("model_generation", model=self.name,
                                     generation=res.get("generation"))
                        rt.flag("promotion")
                out["promotion"] = res
            if rt is not None:
                out["trace_id"] = rt.trace_id
                rt.finish()
            return out
        except BaseException as e:
            if rt is not None:
                rt.finish(status=type(e).__name__)
            raise

    def run(self, batches) -> list:
        """Drain an iterable of update batches: each item is X, (X, y) or
        (X, y, w)."""
        results = []
        for item in batches:
            if isinstance(item, tuple):
                results.append(self.feed(*item))
            else:
                results.append(self.feed(item))
        return results


__all__ = ["ContinualLoop", "PromotionGovernor"]
