#
# Drift detection over the convergence plane: is a fresh update batch's
# per-row signal (inertia / loss / residual) the fit-time distribution's
# noise, or a new distribution?
#
# The judgment is the tree's one measurement discipline (ci/bench_check.py,
# `autotune.noise_mads`): a robust location (median) plus a MAD noise floor,
# and a challenger only counts as DIFFERENT beyond `continual.drift_mads`
# MADs of separation. The baseline seeds from the fit-time convergence tail
# when a fit report is available (`baseline_from_convergence`); otherwise the
# detector self-calibrates on the first `continual.min_baseline` observations
# before it may fire. In-distribution observations keep extending the rolling
# window (trends adapt); drifted observations are NOT absorbed, so a sustained
# shift keeps firing instead of normalizing itself away.
#
# A firing emits `continual.drift{model=,signal=}` (counter) and a
# `continual.drift` event — event() fans into every open run report AND the
# flight recorder, so a post-mortem ring dump carries the drift history.
#

from __future__ import annotations

from collections import deque
from typing import Any, Dict, Iterable, List, Optional

import numpy as np

from .. import config as _config
from ..observability import counter_inc, event

# sigma = _MAD_TO_SIGMA * MAD under normality — the same constant
# ci/bench_check.py's noise gate reasons with.
_MAD_TO_SIGMA = 1.4826
# relative noise floor: identical-to-the-ulp baselines (tiny synthetic
# streams) would otherwise make ANY deviation "drift"
_REL_FLOOR = 1e-3
_ABS_FLOOR = 1e-12


def resolve_drift_mads() -> float:
    """`continual.drift_mads` resolution: config pin, then tuning table, then
    the defaults-module constant (3.0 — the bench_check separation rule)."""
    from .. import autotune as _autotune
    from ..autotune.defaults import CONTINUAL_DRIFT_MADS

    pinned = float(_config.get("continual.drift_mads") or 0.0)
    if pinned > 0.0:
        return pinned
    tuned = _autotune.lookup("continual.drift_mads")
    if tuned:
        return float(tuned)
    return float(CONTINUAL_DRIFT_MADS)


def baseline_from_convergence(records: Iterable[Dict[str, Any]], algo: str,
                              field: str, n_rows: int = 1,
                              tail: int = 8) -> List[float]:
    """Per-row baseline from a fit report's convergence tail: the last `tail`
    records of `algo` carrying `field`, normalized by the fit's row count so
    they compare against partial_fit's per-row signals."""
    vals = [
        float(r[field]) for r in records
        if r.get("algo") == algo and field in r
        and r.get("phase") != "partial_fit"
    ]
    return [v / max(int(n_rows), 1) for v in vals[-int(tail):]]


class DriftDetector:
    """Median + MAD-floor threshold over per-update signals (lower = better
    signals only: inertia, loss, residual — all per-row)."""

    def __init__(self, model: str = "", signal: str = "",
                 baseline: Optional[Iterable[float]] = None,
                 mads: Optional[float] = None,
                 min_baseline: Optional[int] = None, window: int = 64):
        self.model = model
        self.signal = signal
        self.mads = resolve_drift_mads() if mads is None else float(mads)
        self.min_baseline = (
            int(_config.get("continual.min_baseline"))
            if min_baseline is None else int(min_baseline)
        )
        self._window: deque = deque(maxlen=int(window))
        for v in baseline or ():
            self._window.append(float(v))

    def __len__(self) -> int:
        return len(self._window)

    def threshold(self) -> Optional[float]:
        """Current firing threshold; None while the baseline is still
        calibrating."""
        if len(self._window) < max(self.min_baseline, 2):
            return None
        vals = np.asarray(self._window, np.float64)
        med = float(np.median(vals))
        mad = float(np.median(np.abs(vals - med)))
        noise = max(_MAD_TO_SIGMA * mad, _REL_FLOOR * abs(med), _ABS_FLOOR)
        return med + self.mads * noise

    def observe(self, value: float) -> Optional[Dict[str, float]]:
        """Feed one per-update signal. Returns the drift record when it
        fires, else None (and extends the rolling baseline)."""
        value = float(value)
        thr = self.threshold()
        if thr is not None and value > thr:
            counter_inc("continual.drift", 1, model=self.model,
                        signal=self.signal)
            event("continual.drift", model=self.model, signal=self.signal,
                  value=value, threshold=thr)
            return {"value": value, "threshold": thr}
        self._window.append(value)
        return None


__all__ = ["DriftDetector", "baseline_from_convergence", "resolve_drift_mads"]
