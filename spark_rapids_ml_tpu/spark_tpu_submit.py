#
# `spark-tpu-submit` launcher — role of the reference's `spark-rapids-submit` CLI
# (reference spark_rapids_submit.py:23-49): spark-submit wrapper that inserts the
# package's __main__ runner as the driver script so user scripts get the
# no-import-change interposer.
#

from __future__ import annotations

import os
import shutil
import sys


def main() -> None:
    submit_bin = shutil.which("spark-submit")
    if submit_bin is None:
        raise SystemExit(
            "spark-submit not found on PATH; install Spark to use spark-tpu-submit."
        )
    runner = os.path.join(os.path.dirname(os.path.abspath(__file__)), "__main__.py")
    # find the application script: the first non-option argument, skipping the VALUES
    # of value-taking spark-submit options (--py-files deps.py must not match)
    args = sys.argv[1:]
    i = 0
    app_idx = None
    while i < len(args):
        a = args[i]
        if a.startswith("-"):
            # all spark-submit long options except --verbose/--supervise take a value
            if "=" not in a and a not in (
                "--verbose", "-v", "--supervise", "--help", "-h", "--version",
            ):
                i += 1  # skip the option's value
        elif a.endswith(".py"):
            app_idx = i
            break
        else:  # non-.py application (jar) — not ours to wrap
            raise SystemExit("spark-tpu-submit requires a .py application")
        i += 1
    if app_idx is None:
        raise SystemExit("no .py application found in arguments")
    args = args[:app_idx] + [runner, args[app_idx]] + args[app_idx + 1 :]
    os.execv(submit_bin, [submit_bin] + args)


if __name__ == "__main__":
    main()
