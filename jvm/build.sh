#!/usr/bin/env bash
# Reproducible JVM build attempt (VERDICT r3 task #10): detect a Scala toolchain,
# try compile + test, and record the outcome to ci/jvm_build_status.json so every
# round documents exactly why the 637-LoC Scala half is or is not compiled.
# The development image ships no sbt/scala/coursier and no network; on a machine
# with either, this script completes the build unattended.
set -u
HERE="$(cd "$(dirname "$0")" && pwd)"
REPO="$(dirname "$HERE")"
OUT="$REPO/ci/jvm_build_status.json"
ts="$(date -u +%Y-%m-%dT%H:%M:%SZ)"

record() {
  # record <status> <tool> <detail>
  printf '{"timestamp": "%s", "status": "%s", "toolchain": "%s", "detail": "%s"}\n' \
    "$ts" "$1" "$2" "$3" > "$OUT"
  echo "jvm/build.sh: $1 ($2) — $3"
}

SBT=""
found_launchers=""
if command -v sbt >/dev/null 2>&1; then
  SBT="sbt"
fi
# coursier can bootstrap sbt without a system install (needs network once);
# try BOTH launchers independently — a present-but-broken `cs` must not mask a
# working `coursier`
for launcher in cs coursier; do
  [ -n "$SBT" ] && break
  if command -v "$launcher" >/dev/null 2>&1; then
    found_launchers="$found_launchers $launcher"
    if "$launcher" launch sbt -- --version >/dev/null 2>&1; then
      SBT="$launcher launch sbt --"
    fi
  fi
done

if [ -z "$SBT" ]; then
  if [ -n "$found_launchers" ]; then
    record "toolchain-missing" "none" \
      "launcher(s)$found_launchers present but sbt bootstrap failed (likely no network)"
  else
    record "toolchain-missing" "none" \
      "no sbt/coursier on PATH (image ships no Scala toolchain; network installs unavailable)"
  fi
  exit 0
fi

cd "$HERE"
if $SBT -batch compile > /tmp/srml_jvm_compile.log 2>&1; then
  if $SBT -batch test > /tmp/srml_jvm_test.log 2>&1; then
    ntests="$(grep -Eo 'Tests: succeeded [0-9]+' /tmp/srml_jvm_test.log | head -1 || true)"
    record "ok" "$SBT" "compile + test passed (${ntests:-see /tmp/srml_jvm_test.log})"
  else
    record "test-failed" "$SBT" "compile passed, tests failed: see /tmp/srml_jvm_test.log"
    exit 1
  fi
else
  record "compile-failed" "$SBT" "see /tmp/srml_jvm_compile.log"
  exit 1
fi
