// JVM half of the spark-rapids-ml-tpu Spark Connect plugin.
// Build: sbt package  (requires Spark 4.0+ on the classpath for the
// MLBackendPlugin / PythonPlannerRunner Connect APIs).
name := "spark-rapids-ml-tpu-jvm"
version := "0.2.0"
scalaVersion := "2.13.14"

libraryDependencies ++= Seq(
  "org.apache.spark" %% "spark-sql" % "4.0.0" % "provided",
  "org.apache.spark" %% "spark-mllib" % "4.0.0" % "provided",
  "org.apache.spark" %% "spark-connect" % "4.0.0" % "provided",
  "org.scalatest" %% "scalatest" % "3.2.18" % Test
)
