/*
 * Parses the TPU backend's tagged-JSON model attributes into Spark linalg objects
 * (structural counterpart of reference jvm/src/main/scala/org/apache/spark/ml/
 * rapids/ModelHelper.scala, re-designed for the dict format: ndarrays are encoded
 * as {"__nd__": nested-list, "dtype": str} by
 * spark_rapids_ml_tpu/connect_plugin.py encode_model_attributes).
 */
package org.apache.spark.ml.tpu

import org.apache.spark.ml.linalg.{DenseMatrix, DenseVector, Matrices, Matrix, Vector, Vectors}
import org.apache.spark.ml.param.{Param, Params}
import org.json4s._
import org.json4s.jackson.JsonMethods

object ModelHelper {

  private implicit val formats: Formats = DefaultFormats

  /** Serialize the user-set params of an estimator to the JSON dict the Python half
   * feeds to `Estimator(**params)` (reference RapidsUtils.getUserDefinedParams). */
  def userParamsJson(est: Params): String = {
    val fields = est.params.flatMap { p: Param[_] =>
      if (est.isSet(p)) Some(JField(p.name, anyToJson(est.get(p).get))) else None
    }
    JsonMethods.compact(JsonMethods.render(JObject(fields.toList)))
  }

  private def anyToJson(v: Any): JValue = v match {
    case b: Boolean => JBool(b)
    case i: Int => JInt(i)
    case l: Long => JInt(l)
    case f: Float => JDouble(f)
    case d: Double => JDouble(d)
    case s: String => JString(s)
    case a: Array[_] => JArray(a.map(anyToJson).toList)
    case other => JString(other.toString)
  }

  private def parse(attributesJson: String): JValue =
    JsonMethods.parse(attributesJson)

  /** Decode a tagged {"__nd__": ...} cell as a 1-D double array. */
  private def nd1(v: JValue): Array[Double] =
    (v \ "__nd__").extract[List[Double]].toArray

  /** Decode a tagged {"__nd__": ...} cell as a 2-D row-major matrix. */
  private def nd2(v: JValue): Array[Array[Double]] =
    (v \ "__nd__").extract[List[List[Double]]].map(_.toArray).toArray

  private def denseMatrix(rows: Array[Array[Double]]): Matrix = {
    val m = rows.length
    val n = if (m == 0) 0 else rows(0).length
    // Spark DenseMatrix is column-major
    val values = new Array[Double](m * n)
    var i = 0
    while (i < m) {
      var j = 0
      while (j < n) {
        values(j * m + i) = rows(i)(j)
        j += 1
      }
      i += 1
    }
    new DenseMatrix(m, n, values)
  }

  /** (coefficients, intercepts, numClasses) from a LogisticRegressionModel dict
   * {"coefficients": nd2, "intercepts": nd1, "num_classes": int, ...}. */
  def logisticRegressionAttributes(json: String): (Matrix, Vector, Int) = {
    val root = parse(json)
    val coef = denseMatrix(nd2(root \ "coefficients"))
    val icpt = new DenseVector(nd1(root \ "intercepts"))
    val k = (root \ "num_classes").extract[Int]
    (coef, icpt, k)
  }

  /** (coefficients, intercept) from a LinearRegressionModel dict
   * {"coefficients": nd1, "intercept": double, ...}. */
  def linearRegressionAttributes(json: String): (Vector, Double) = {
    val root = parse(json)
    (new DenseVector(nd1(root \ "coefficients")), (root \ "intercept").extract[Double])
  }

  /** Cluster centers from a KMeansModel dict {"cluster_centers": nd2, ...}. */
  def kmeansCenters(json: String): Array[Vector] =
    nd2(parse(json) \ "cluster_centers").map(r => Vectors.dense(r))

  /** (principal components (n x k), explained variance) from a PCAModel dict
   * {"components": nd2 (k x n), "explained_variance_ratio": nd1, ...}. */
  def pcaAttributes(json: String): (Matrix, Vector) = {
    val root = parse(json)
    val rows = nd2(root \ "components") // k x n, rows are components
    val k = rows.length
    val n = if (k == 0) 0 else rows(0).length
    // pc matrix is n x k with components as columns
    val values = new Array[Double](n * k)
    var c = 0
    while (c < k) {
      var r = 0
      while (r < n) {
        values(c * n + r) = rows(c)(r)
        r += 1
      }
      c += 1
    }
    val pc = new DenseMatrix(n, k, values)
    (pc, new DenseVector(nd1(root \ "explained_variance_ratio")))
  }

  /** (numFeatures, numClasses) from a forest dict {"forest": {...}, "num_classes"}. */
  def forestShape(json: String, classification: Boolean): (Int, Int) = {
    val root = parse(json)
    val numFeatures = (root \ "num_features").extractOpt[Int].getOrElse(-1)
    val numClasses =
      if (classification) (root \ "num_classes").extractOpt[Int].getOrElse(2) else 0
    (numFeatures, numClasses)
  }

  /** Inverse of userParamsJson: restore user-set params onto `target` from the
   * persisted JSON dict (type-coerced per the concrete Param subclass — json4s
   * surfaces every number as JInt/JDouble regardless of the param's type). */
  def applyParamsJson(target: Params, paramsJson: String): Unit =
    JsonMethods.parse(paramsJson) match {
      case JObject(fields) =>
        fields.foreach { case JField(name, v) =>
          if (target.hasParam(name)) setCoerced(target, target.getParam(name), v)
        }
      case _ =>
    }

  private def setCoerced(target: Params, p: Param[_], v: JValue): Unit = {
    import org.apache.spark.ml.param._
    val value: Option[Any] = (p, v) match {
      case (_: IntParam, JInt(i)) => Some(i.toInt)
      case (_: IntParam, JDouble(d)) => Some(d.toInt)
      case (_: LongParam, JInt(i)) => Some(i.toLong)
      // json4s round-trips a long-typed seed as JDouble (3.0): coerce it back
      // instead of letting the generic fallthrough box a Double into a
      // Param[Long] (which only failed later, at getSeed time)
      case (_: LongParam, JDouble(d)) => Some(d.toLong)
      case (_: DoubleParam, JInt(i)) => Some(i.toDouble)
      case (_: DoubleParam, JDouble(d)) => Some(d)
      case (_: FloatParam, JInt(i)) => Some(i.toFloat)
      case (_: FloatParam, JDouble(d)) => Some(d.toFloat)
      case (_: BooleanParam, JBool(b)) => Some(b)
      case (_: StringArrayParam, JArray(a)) =>
        Some(a.map(_.extract[String]).toArray)
      case (_: DoubleArrayParam, JArray(a)) =>
        Some(a.map(_.extract[Double]).toArray)
      case (_: IntArrayParam, JArray(a)) => Some(a.map(_.extract[Int]).toArray)
      // a TYPED param reaching this point holds a JSON value its type cannot
      // represent: fail AT LOAD with the param name, not later (and not
      // silently via the untyped fallthroughs below, which would defer the
      // failure to a ClassCastException at get<Param> time)
      case (_: IntParam | _: LongParam | _: DoubleParam | _: FloatParam |
            _: BooleanParam | _: StringArrayParam | _: DoubleArrayParam |
            _: IntArrayParam, _) =>
        throw new IllegalArgumentException(
          s"cannot coerce persisted JSON value $v into param '${p.name}' " +
            s"(${p.getClass.getSimpleName})")
      // untyped Param[_]: string-valued params plus the plain-Param numerics
      case (_, JString(s)) => Some(s)
      case (_, JInt(i)) => Some(i.toInt)
      case (_, JDouble(d)) => Some(d)
      case (_, JBool(b)) => Some(b)
      case _ => None
    }
    value.foreach(x => target.set(p.asInstanceOf[Param[Any]], x))
  }
}
