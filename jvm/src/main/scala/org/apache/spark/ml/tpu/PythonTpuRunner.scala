/*
 * JVM ⇄ Python bridge for the TPU ML backend (structural counterpart of reference
 * jvm/src/main/scala/org/apache/spark/ml/rapids/PythonEstimatorRunner.scala:40-67,
 * re-designed around this repo's framed protocol).
 *
 * The runner extends Spark's PythonPlannerRunner so worker lifecycle, auth and
 * faulthandler plumbing are inherited. On the wire it speaks the
 * spark_rapids_ml_tpu.connect_plugin protocol:
 *
 *   JVM -> Python : auth_token | java_sc_key            (session rebuild, main())
 *                   operator | params_json | dataset_key | [attributes_json]
 *   Python -> JVM : "OK" | payload    (fit: model-attributes JSON;
 *                                      transform: result DataFrame object key)
 *                   "ERR" | message
 *
 * All frames are 4-byte big-endian length + UTF-8 payload.
 */
package org.apache.spark.ml.tpu

import java.io.{DataInputStream, DataOutputStream}
import java.nio.charset.StandardCharsets

import org.apache.spark.api.python.PythonPlannerRunner
import org.apache.spark.sql.DataFrame

sealed trait TpuRequest {
  def operator: String
  def paramsJson: String
}
case class Fit(operator: String, paramsJson: String) extends TpuRequest
case class Transform(operator: String, paramsJson: String, attributesJson: String)
    extends TpuRequest

/** Result of a fit: the model-attribute JSON produced by the Python estimator. */
case class TrainedModel(modelAttributes: String)

object Framing {
  def write(out: DataOutputStream, s: String): Unit = {
    val bytes = s.getBytes(StandardCharsets.UTF_8)
    out.writeInt(bytes.length)
    out.write(bytes)
  }

  def read(in: DataInputStream): String = {
    val n = in.readInt()
    val buf = new Array[Byte](n)
    in.readFully(buf)
    new String(buf, StandardCharsets.UTF_8)
  }
}

class PythonTpuRunner(request: TpuRequest, dataset: DataFrame)
    extends PythonPlannerRunner[String](null) with AutoCloseable {

  override protected val workerModule: String = "spark_rapids_ml_tpu.connect_plugin"

  private val jdf = dataset.queryExecution.analyzed
  private var datasetKey: String = _

  override protected def writeToPython(out: DataOutputStream, authToken: String): Unit = {
    val session = dataset.sparkSession
    val jscKey = org.apache.spark.api.java.JavaSparkContext
      .fromSparkContext(session.sparkContext)
    datasetKey = PythonObjectRegistry.register(dataset)
    Framing.write(out, authToken)
    Framing.write(out, PythonObjectRegistry.register(jscKey))
    Framing.write(out, request.operator)
    Framing.write(out, request.paramsJson)
    Framing.write(out, datasetKey)
    request match {
      case Transform(_, _, attrs) => Framing.write(out, attrs)
      case _ => ()
    }
    out.flush()
  }

  override protected def receiveFromPython(in: DataInputStream): String = {
    val status = Framing.read(in)
    val payload = Framing.read(in)
    if (status != "OK") {
      throw new RuntimeException(s"spark-rapids-ml-tpu python worker failed: $payload")
    }
    payload
  }

  def close(): Unit = {
    if (datasetKey != null) PythonObjectRegistry.unregister(datasetKey)
  }
}

/**
 * Keeps JVM objects addressable by string key across the py4j boundary (the
 * reference passes raw py4j target ids; an explicit registry survives GC cycles
 * between the two protocol legs).
 */
object PythonObjectRegistry {
  private val objects = new java.util.concurrent.ConcurrentHashMap[String, AnyRef]()
  private val counter = new java.util.concurrent.atomic.AtomicLong(0)

  def register(obj: AnyRef): String = {
    val key = s"srml-tpu-${counter.incrementAndGet()}"
    objects.put(key, obj)
    key
  }

  def lookup(key: String): AnyRef = objects.get(key)

  def unregister(key: String): Unit = objects.remove(key)
}
