/*
 * Session-free persistence for the Tpu model wrappers (role of the reference's
 * RapidsModel write/read, jvm/src/main/scala/org/apache/spark/ml/rapids/
 * RapidsModel.scala:47-95, which rides Spark's MLWriter/Hadoop FS). Re-designed
 * as one JSON document via java.nio so model save/load — and its unit tests —
 * need no SparkSession or Hadoop classpath: the TPU backend's model state is
 * fully captured by (uid, class, user params, Python attribute JSON).
 */
package org.apache.spark.ml.tpu

import java.nio.charset.StandardCharsets
import java.nio.file.{Files, Paths}

import org.json4s._
import org.json4s.jackson.JsonMethods

object TpuModelIO {

  private implicit val formats: Formats = DefaultFormats

  /** Everything needed to rebuild a Tpu model wrapper. */
  case class Loaded(
      uid: String,
      className: String,
      paramsJson: String,
      attributesJson: String)

  def save(
      path: String,
      uid: String,
      className: String,
      paramsJson: String,
      attributesJson: String): Unit = {
    val dir = Paths.get(path)
    Files.createDirectories(dir)
    val doc = JObject(
      List(
        JField("uid", JString(uid)),
        JField("class", JString(className)),
        JField("params", JsonMethods.parse(paramsJson)),
        JField("attributes", JString(attributesJson))))
    Files.write(
      dir.resolve("tpu_model.json"),
      JsonMethods.compact(JsonMethods.render(doc)).getBytes(StandardCharsets.UTF_8))
  }

  def load(path: String): Loaded = {
    val bytes = Files.readAllBytes(Paths.get(path).resolve("tpu_model.json"))
    val root = JsonMethods.parse(new String(bytes, StandardCharsets.UTF_8))
    Loaded(
      (root \ "uid").extract[String],
      (root \ "class").extract[String],
      JsonMethods.compact(JsonMethods.render(root \ "params")),
      (root \ "attributes").extract[String])
  }
}
