/*
 * Model wrappers returned by the Tpu* estimators (structural counterparts of
 * reference jvm/src/main/scala/org/apache/spark/ml/rapids/Rapids*Model.scala and
 * RapidsModel.scala:47-95, re-designed for the TPU backend).
 *
 * Each wrapper IS a real Spark model (so downstream pipelines type-check) while
 * retaining the Python model-attribute JSON. transform() dispatches to the Python
 * TPU worker when `spark.rapids.ml.tpu.python.transform.enabled` (default true),
 * else falls back to the in-JVM parent implementation built from the parsed
 * attributes. Persistence stores the parent model format plus the attribute JSON
 * alongside, so either side can reload it.
 */
package org.apache.spark.ml.tpu

import org.apache.spark.ml.classification.{LogisticRegressionModel, ProbabilisticClassificationModel, RandomForestClassificationModel, RandomForestRegressionModel}
import org.apache.spark.ml.linalg.{Matrix, Vector}
import org.apache.spark.ml.param.Params
import org.apache.spark.ml.util.Identifiable
import org.apache.spark.sql.{DataFrame, Dataset}

trait TpuModel extends Params {
  /** Attribute JSON produced by the Python fit (tagged-ndarray dict). */
  def modelAttributes: String

  /** Operator name of the MODEL on the Python side, e.g. "KMeansModel". */
  def modelOperatorName: String

  protected def pythonTransformEnabled(dataset: Dataset[_]): Boolean =
    dataset.sparkSession.conf
      .get("spark.rapids.ml.tpu.python.transform.enabled", "true").toBoolean

  protected def transformOnPython(dataset: Dataset[_]): DataFrame = {
    val params = ModelHelper.userParamsJson(this)
    val runner = new PythonTpuRunner(
      Transform(modelOperatorName, params, modelAttributes), dataset.toDF)
    try {
      val resultKey = runner.runInPython(useDaemon = false)
      PythonObjectRegistry.lookup(resultKey).asInstanceOf[DataFrame]
    } finally {
      runner.close()
    }
  }

  /** Session-free persistence (TpuModelIO): uid + user params + the Python
   * attribute JSON fully determine the wrapper; companion `load`s rebuild it.
   * The reference persists through Spark's MLWriter (RapidsModel.scala:47-95);
   * this form also works without a SparkSession, which the unit tier exploits. */
  def saveTpu(path: String): Unit =
    TpuModelIO.save(
      path, uid, getClass.getName, ModelHelper.userParamsJson(this), modelAttributes)
}

class TpuLogisticRegressionModel(
    override val uid: String,
    coefficientMatrix: Matrix,
    interceptVector: Vector,
    numClasses: Int,
    override val modelAttributes: String)
  extends LogisticRegressionModel(
    uid, coefficientMatrix, interceptVector, numClasses,
    coefficientMatrix.numRows > 1) with TpuModel {

  override def modelOperatorName: String = "LogisticRegressionModel"

  override def transform(dataset: Dataset[_]): DataFrame =
    if (pythonTransformEnabled(dataset)) transformOnPython(dataset)
    else super.transform(dataset)
}

private[tpu] object TpuModelLoadCheck {
  /** Loading a path persisted by a DIFFERENT model type must fail loudly — the
   * attribute parsers degrade to defaults (e.g. forestShape -> (-1, 2)) and
   * would otherwise hand back a silently-corrupt model. TpuModelIO persists the
   * class name exactly for this check. */
  def requireClass(doc: TpuModelIO.Loaded, expected: Class[_]): Unit =
    require(
      doc.className == expected.getName,
      s"model at path was saved as ${doc.className}, not ${expected.getName}")
}

object TpuLogisticRegressionModel {
  def load(path: String): TpuLogisticRegressionModel = {
    val doc = TpuModelIO.load(path)
    TpuModelLoadCheck.requireClass(doc, classOf[TpuLogisticRegressionModel])
    val (coef, icpt, k) = ModelHelper.logisticRegressionAttributes(doc.attributesJson)
    val m = new TpuLogisticRegressionModel(doc.uid, coef, icpt, k, doc.attributesJson)
    ModelHelper.applyParamsJson(m, doc.paramsJson)
    m
  }
}

class TpuLinearRegressionModel(
    override val uid: String,
    coefficients: Vector,
    intercept: Double,
    override val modelAttributes: String)
  extends org.apache.spark.ml.regression.LinearRegressionModel(
    uid, coefficients, intercept) with TpuModel {

  override def modelOperatorName: String = "LinearRegressionModel"

  override def transform(dataset: Dataset[_]): DataFrame =
    if (pythonTransformEnabled(dataset)) transformOnPython(dataset)
    else super.transform(dataset)
}

object TpuLinearRegressionModel {
  def load(path: String): TpuLinearRegressionModel = {
    val doc = TpuModelIO.load(path)
    TpuModelLoadCheck.requireClass(doc, classOf[TpuLinearRegressionModel])
    val (coef, icpt) = ModelHelper.linearRegressionAttributes(doc.attributesJson)
    val m = new TpuLinearRegressionModel(doc.uid, coef, icpt, doc.attributesJson)
    ModelHelper.applyParamsJson(m, doc.paramsJson)
    m
  }
}

class TpuRandomForestClassificationModel(
    override val uid: String,
    numFeaturesIn: Int,
    numClassesIn: Int,
    override val modelAttributes: String)
  extends RandomForestClassificationModel(
    uid, Array.empty, numFeaturesIn, numClassesIn) with TpuModel {

  override def modelOperatorName: String = "RandomForestClassificationModel"

  // the JVM side holds no trees; transform must go through Python
  override def transform(dataset: Dataset[_]): DataFrame = transformOnPython(dataset)
}

object TpuRandomForestClassificationModel {
  def load(path: String): TpuRandomForestClassificationModel = {
    val doc = TpuModelIO.load(path)
    TpuModelLoadCheck.requireClass(doc, classOf[TpuRandomForestClassificationModel])
    val (nf, nc) = ModelHelper.forestShape(doc.attributesJson, classification = true)
    val m = new TpuRandomForestClassificationModel(doc.uid, nf, nc, doc.attributesJson)
    ModelHelper.applyParamsJson(m, doc.paramsJson)
    m
  }
}

class TpuRandomForestRegressionModel(
    override val uid: String,
    numFeaturesIn: Int,
    override val modelAttributes: String)
  extends RandomForestRegressionModel(uid, Array.empty, numFeaturesIn) with TpuModel {

  override def modelOperatorName: String = "RandomForestRegressionModel"

  override def transform(dataset: Dataset[_]): DataFrame = transformOnPython(dataset)
}

object TpuRandomForestRegressionModel {
  def load(path: String): TpuRandomForestRegressionModel = {
    val doc = TpuModelIO.load(path)
    TpuModelLoadCheck.requireClass(doc, classOf[TpuRandomForestRegressionModel])
    val (nf, _) = ModelHelper.forestShape(doc.attributesJson, classification = false)
    val m = new TpuRandomForestRegressionModel(doc.uid, nf, doc.attributesJson)
    ModelHelper.applyParamsJson(m, doc.paramsJson)
    m
  }
}

/*
 * KMeansModel / PCAModel have private[ml] constructors; the wrappers are built via
 * factory objects living in this org.apache.spark.ml.* package for access (the
 * reference solves this the same way with
 * org/apache/spark/ml/clustering/rapids/RapidsKMeansModel.scala).
 */
object TpuKMeansModel {
  def create(
      uid: String,
      centers: Array[Vector],
      attributes: String,
      parent: Params): org.apache.spark.ml.clustering.KMeansModel = {
    val mllibCenters = centers.map(v =>
      org.apache.spark.mllib.linalg.Vectors.fromML(v))
    val mllibModel = new org.apache.spark.mllib.clustering.KMeansModel(mllibCenters)
    val model = new org.apache.spark.ml.clustering.KMeansModel(uid, mllibModel)
    parent.asInstanceOf[org.apache.spark.ml.Estimator[_]].copyValues(
      model.asInstanceOf[org.apache.spark.ml.Model[_]])
    model
  }
}

object TpuPCAModel {
  def create(
      uid: String,
      pc: Matrix,
      explainedVariance: Vector,
      attributes: String,
      parent: Params): org.apache.spark.ml.feature.PCAModel = {
    val model = new org.apache.spark.ml.feature.PCAModel(
      uid,
      pc.asInstanceOf[org.apache.spark.ml.linalg.DenseMatrix],
      explainedVariance.asInstanceOf[org.apache.spark.ml.linalg.DenseVector])
    parent.asInstanceOf[org.apache.spark.ml.Estimator[_]].copyValues(
      model.asInstanceOf[org.apache.spark.ml.Model[_]])
    model
  }
}
