/*
 * Model wrappers returned by the Tpu* estimators (structural counterparts of
 * reference jvm/src/main/scala/org/apache/spark/ml/rapids/Rapids*Model.scala and
 * RapidsModel.scala:47-95, re-designed for the TPU backend).
 *
 * Each wrapper IS a real Spark model (so downstream pipelines type-check) while
 * retaining the Python model-attribute JSON. transform() dispatches to the Python
 * TPU worker when `spark.rapids.ml.tpu.python.transform.enabled` (default true),
 * else falls back to the in-JVM parent implementation built from the parsed
 * attributes. Persistence stores the parent model format plus the attribute JSON
 * alongside, so either side can reload it.
 */
package org.apache.spark.ml.tpu

import org.apache.spark.ml.classification.{LogisticRegressionModel, ProbabilisticClassificationModel, RandomForestClassificationModel, RandomForestRegressionModel}
import org.apache.spark.ml.linalg.{Matrix, Vector}
import org.apache.spark.ml.param.Params
import org.apache.spark.ml.util.Identifiable
import org.apache.spark.sql.{DataFrame, Dataset}

trait TpuModel extends Params {
  /** Attribute JSON produced by the Python fit (tagged-ndarray dict). */
  def modelAttributes: String

  /** Operator name of the MODEL on the Python side, e.g. "KMeansModel". */
  def modelOperatorName: String

  protected def pythonTransformEnabled(dataset: Dataset[_]): Boolean =
    dataset.sparkSession.conf
      .get("spark.rapids.ml.tpu.python.transform.enabled", "true").toBoolean

  protected def transformOnPython(dataset: Dataset[_]): DataFrame = {
    val params = ModelHelper.userParamsJson(this)
    val runner = new PythonTpuRunner(
      Transform(modelOperatorName, params, modelAttributes), dataset.toDF)
    try {
      val resultKey = runner.runInPython(useDaemon = false)
      PythonObjectRegistry.lookup(resultKey).asInstanceOf[DataFrame]
    } finally {
      runner.close()
    }
  }
}

class TpuLogisticRegressionModel(
    override val uid: String,
    coefficientMatrix: Matrix,
    interceptVector: Vector,
    numClasses: Int,
    override val modelAttributes: String)
  extends LogisticRegressionModel(
    uid, coefficientMatrix, interceptVector, numClasses,
    coefficientMatrix.numRows > 1) with TpuModel {

  override def modelOperatorName: String = "LogisticRegressionModel"

  override def transform(dataset: Dataset[_]): DataFrame =
    if (pythonTransformEnabled(dataset)) transformOnPython(dataset)
    else super.transform(dataset)
}

class TpuLinearRegressionModel(
    override val uid: String,
    coefficients: Vector,
    intercept: Double,
    override val modelAttributes: String)
  extends org.apache.spark.ml.regression.LinearRegressionModel(
    uid, coefficients, intercept) with TpuModel {

  override def modelOperatorName: String = "LinearRegressionModel"

  override def transform(dataset: Dataset[_]): DataFrame =
    if (pythonTransformEnabled(dataset)) transformOnPython(dataset)
    else super.transform(dataset)
}

class TpuRandomForestClassificationModel(
    override val uid: String,
    numFeaturesIn: Int,
    numClassesIn: Int,
    override val modelAttributes: String)
  extends RandomForestClassificationModel(
    uid, Array.empty, numFeaturesIn, numClassesIn) with TpuModel {

  override def modelOperatorName: String = "RandomForestClassificationModel"

  // the JVM side holds no trees; transform must go through Python
  override def transform(dataset: Dataset[_]): DataFrame = transformOnPython(dataset)
}

class TpuRandomForestRegressionModel(
    override val uid: String,
    numFeaturesIn: Int,
    override val modelAttributes: String)
  extends RandomForestRegressionModel(uid, Array.empty, numFeaturesIn) with TpuModel {

  override def modelOperatorName: String = "RandomForestRegressionModel"

  override def transform(dataset: Dataset[_]): DataFrame = transformOnPython(dataset)
}

/*
 * KMeansModel / PCAModel have private[ml] constructors; the wrappers are built via
 * factory objects living in this org.apache.spark.ml.* package for access (the
 * reference solves this the same way with
 * org/apache/spark/ml/clustering/rapids/RapidsKMeansModel.scala).
 */
object TpuKMeansModel {
  def create(
      uid: String,
      centers: Array[Vector],
      attributes: String,
      parent: Params): org.apache.spark.ml.clustering.KMeansModel = {
    val mllibCenters = centers.map(v =>
      org.apache.spark.mllib.linalg.Vectors.fromML(v))
    val mllibModel = new org.apache.spark.mllib.clustering.KMeansModel(mllibCenters)
    val model = new org.apache.spark.ml.clustering.KMeansModel(uid, mllibModel)
    parent.asInstanceOf[org.apache.spark.ml.Estimator[_]].copyValues(
      model.asInstanceOf[org.apache.spark.ml.Model[_]])
    model
  }
}

object TpuPCAModel {
  def create(
      uid: String,
      pc: Matrix,
      explainedVariance: Vector,
      attributes: String,
      parent: Params): org.apache.spark.ml.feature.PCAModel = {
    val model = new org.apache.spark.ml.feature.PCAModel(
      uid,
      pc.asInstanceOf[org.apache.spark.ml.linalg.DenseMatrix],
      explainedVariance.asInstanceOf[org.apache.spark.ml.linalg.DenseVector])
    parent.asInstanceOf[org.apache.spark.ml.Estimator[_]].copyValues(
      model.asInstanceOf[org.apache.spark.ml.Model[_]])
    model
  }
}
