/*
 * Spark Connect ML backend plugin, JVM half — swaps Spark's built-in estimators for
 * the spark-rapids-ml-tpu Python implementations on the Connect server, so Connect
 * clients accelerate with zero code change.
 *
 * Structural counterpart of reference jvm/src/main/scala/com/nvidia/rapids/ml/
 * Plugin.scala:26-57 (class-name remap via MLBackendPlugin), re-written for the TPU
 * backend: the Python process it ultimately launches is
 * spark_rapids_ml_tpu.connect_plugin, speaking the framed OK/ERR protocol
 * (connect_plugin.py in this repo).
 */
package com.srml.tpu

import java.util.Optional

import org.apache.spark.sql.connect.plugin.MLBackendPlugin

class Plugin extends MLBackendPlugin {

  private val remap: Map[String, String] = Map(
    "org.apache.spark.ml.classification.LogisticRegression" ->
      "com.srml.tpu.TpuLogisticRegression",
    "org.apache.spark.ml.classification.LogisticRegressionModel" ->
      "org.apache.spark.ml.tpu.TpuLogisticRegressionModel",
    "org.apache.spark.ml.classification.RandomForestClassifier" ->
      "com.srml.tpu.TpuRandomForestClassifier",
    "org.apache.spark.ml.classification.RandomForestClassificationModel" ->
      "org.apache.spark.ml.tpu.TpuRandomForestClassificationModel",
    "org.apache.spark.ml.regression.RandomForestRegressor" ->
      "com.srml.tpu.TpuRandomForestRegressor",
    "org.apache.spark.ml.regression.RandomForestRegressionModel" ->
      "org.apache.spark.ml.tpu.TpuRandomForestRegressionModel",
    "org.apache.spark.ml.regression.LinearRegression" ->
      "com.srml.tpu.TpuLinearRegression",
    "org.apache.spark.ml.regression.LinearRegressionModel" ->
      "org.apache.spark.ml.tpu.TpuLinearRegressionModel",
    "org.apache.spark.ml.feature.PCA" ->
      "com.srml.tpu.TpuPCA",
    "org.apache.spark.ml.feature.PCAModel" ->
      "org.apache.spark.ml.tpu.TpuPCAModel",
    "org.apache.spark.ml.clustering.KMeans" ->
      "com.srml.tpu.TpuKMeans",
    "org.apache.spark.ml.clustering.KMeansModel" ->
      "org.apache.spark.ml.tpu.TpuKMeansModel"
  )

  override def transform(mlName: String): Optional[String] =
    remap.get(mlName).map(Optional.of[String]).getOrElse(Optional.empty[String]())
}
