/*
 * Estimator wrappers the Connect plugin substitutes for Spark's built-ins
 * (structural counterparts of reference jvm/src/main/scala/com/nvidia/rapids/ml/
 * Rapids{LogisticRegression,LinearRegression,KMeans,PCA,RandomForest*}.scala and
 * RapidsTraits.scala:46-61, re-designed for the TPU backend's dict-JSON attribute
 * protocol).
 *
 * Each wrapper extends the REAL Spark estimator (so Params, schema validation and
 * persistence behave identically), overrides train() to run the Python TPU fit, and
 * wraps the returned attribute JSON in a Tpu*Model.
 */
package com.srml.tpu

import org.apache.commons.logging.LogFactory
import org.apache.spark.ml.classification.{LogisticRegression, RandomForestClassifier}
import org.apache.spark.ml.clustering.KMeans
import org.apache.spark.ml.feature.PCA
import org.apache.spark.ml.param.Params
import org.apache.spark.ml.regression.{LinearRegression, RandomForestRegressor}
import org.apache.spark.ml.tpu._
import org.apache.spark.ml.util.{DefaultParamsReadable, DefaultParamsWritable, Identifiable}
import org.apache.spark.sql.Dataset
import org.apache.spark.sql.types.StructType

trait TpuEstimator extends Params {
  protected val log = LogFactory.getLog("spark-rapids-ml-tpu plugin")

  /** Operator name understood by spark_rapids_ml_tpu.connect_plugin. */
  def operatorName: String

  def trainOnPython(dataset: Dataset[_]): TrainedModel = {
    log.info(s"Dispatching $operatorName fit to the TPU python backend")
    val params = ModelHelper.userParamsJson(this)
    val runner = new PythonTpuRunner(Fit(operatorName, params), dataset.toDF)
    try {
      TrainedModel(runner.runInPython(useDaemon = false))
    } finally {
      runner.close()
    }
  }
}

class TpuLogisticRegression(override val uid: String) extends LogisticRegression
    with DefaultParamsWritable with TpuEstimator {
  def this() = this(Identifiable.randomUID("tpu-logreg"))
  override def operatorName: String = "LogisticRegression"
  // features may arrive as array<float> rather than VectorUDT; skip strict checks
  override def transformSchema(schema: StructType): StructType = schema

  override def train(dataset: Dataset[_]): TpuLogisticRegressionModel = {
    val trained = trainOnPython(dataset)
    val (coefficients, intercepts, numClasses) =
      ModelHelper.logisticRegressionAttributes(trained.modelAttributes)
    copyValues(new TpuLogisticRegressionModel(
      uid, coefficients, intercepts, numClasses, trained.modelAttributes))
  }
}

object TpuLogisticRegression extends DefaultParamsReadable[TpuLogisticRegression]

class TpuLinearRegression(override val uid: String) extends LinearRegression
    with DefaultParamsWritable with TpuEstimator {
  def this() = this(Identifiable.randomUID("tpu-linreg"))
  override def operatorName: String = "LinearRegression"
  override def transformSchema(schema: StructType): StructType = schema

  override def train(dataset: Dataset[_]): TpuLinearRegressionModel = {
    val trained = trainOnPython(dataset)
    val (coefficients, intercept) =
      ModelHelper.linearRegressionAttributes(trained.modelAttributes)
    copyValues(new TpuLinearRegressionModel(
      uid, coefficients, intercept, trained.modelAttributes))
  }
}

object TpuLinearRegression extends DefaultParamsReadable[TpuLinearRegression]

class TpuKMeans(override val uid: String) extends KMeans
    with DefaultParamsWritable with TpuEstimator {
  def this() = this(Identifiable.randomUID("tpu-kmeans"))
  override def operatorName: String = "KMeans"
  override def transformSchema(schema: StructType): StructType = schema

  override def fit(dataset: Dataset[_]): org.apache.spark.ml.clustering.KMeansModel = {
    val trained = trainOnPython(dataset)
    val centers = ModelHelper.kmeansCenters(trained.modelAttributes)
    TpuKMeansModel.create(uid, centers, trained.modelAttributes, this)
  }
}

object TpuKMeans extends DefaultParamsReadable[TpuKMeans]

class TpuPCA(override val uid: String) extends PCA
    with DefaultParamsWritable with TpuEstimator {
  def this() = this(Identifiable.randomUID("tpu-pca"))
  override def operatorName: String = "PCA"
  override def transformSchema(schema: StructType): StructType = schema

  override def fit(dataset: Dataset[_]): org.apache.spark.ml.feature.PCAModel = {
    val trained = trainOnPython(dataset)
    val (pc, explainedVariance) = ModelHelper.pcaAttributes(trained.modelAttributes)
    TpuPCAModel.create(uid, pc, explainedVariance, trained.modelAttributes, this)
  }
}

object TpuPCA extends DefaultParamsReadable[TpuPCA]

class TpuRandomForestClassifier(override val uid: String) extends RandomForestClassifier
    with DefaultParamsWritable with TpuEstimator {
  def this() = this(Identifiable.randomUID("tpu-rfc"))
  override def operatorName: String = "RandomForestClassifier"
  override def transformSchema(schema: StructType): StructType = schema

  override def train(dataset: Dataset[_]): TpuRandomForestClassificationModel = {
    val trained = trainOnPython(dataset)
    val (numFeatures, numClasses) =
      ModelHelper.forestShape(trained.modelAttributes, classification = true)
    copyValues(new TpuRandomForestClassificationModel(
      uid, numFeatures, numClasses, trained.modelAttributes))
  }
}

object TpuRandomForestClassifier extends DefaultParamsReadable[TpuRandomForestClassifier]

class TpuRandomForestRegressor(override val uid: String) extends RandomForestRegressor
    with DefaultParamsWritable with TpuEstimator {
  def this() = this(Identifiable.randomUID("tpu-rfr"))
  override def operatorName: String = "RandomForestRegressor"
  override def transformSchema(schema: StructType): StructType = schema

  override def train(dataset: Dataset[_]): TpuRandomForestRegressionModel = {
    val trained = trainOnPython(dataset)
    val (numFeatures, _) =
      ModelHelper.forestShape(trained.modelAttributes, classification = false)
    copyValues(new TpuRandomForestRegressionModel(
      uid, numFeatures, trained.modelAttributes))
  }
}

object TpuRandomForestRegressor extends DefaultParamsReadable[TpuRandomForestRegressor]
