/*
 * JVM-half test suite (role of reference jvm/src/test/scala/.../
 * SparkRapidsMLSuite.scala): plugin remap coverage, params JSON serialization,
 * attribute-JSON parsing, and — when a Connect-enabled session with the Python
 * backend is available — estimator roundtrips. Runs under `sbt test` where Spark 4
 * is on the classpath (no Scala toolchain ships in the development image).
 */
package com.srml.tpu

import org.apache.spark.ml.tpu.ModelHelper
import org.scalatest.funsuite.AnyFunSuite

class TpuPluginSuite extends AnyFunSuite {

  test("plugin remaps every accelerated estimator and model") {
    val plugin = new Plugin
    val expected = Seq(
      "org.apache.spark.ml.classification.LogisticRegression" ->
        "com.srml.tpu.TpuLogisticRegression",
      "org.apache.spark.ml.classification.LogisticRegressionModel" ->
        "org.apache.spark.ml.tpu.TpuLogisticRegressionModel",
      "org.apache.spark.ml.clustering.KMeans" -> "com.srml.tpu.TpuKMeans",
      "org.apache.spark.ml.feature.PCA" -> "com.srml.tpu.TpuPCA",
      "org.apache.spark.ml.regression.LinearRegression" ->
        "com.srml.tpu.TpuLinearRegression",
      "org.apache.spark.ml.classification.RandomForestClassifier" ->
        "com.srml.tpu.TpuRandomForestClassifier",
      "org.apache.spark.ml.regression.RandomForestRegressor" ->
        "com.srml.tpu.TpuRandomForestRegressor"
    )
    expected.foreach { case (sparkName, tpuName) =>
      assert(plugin.transform(sparkName).get() == tpuName, sparkName)
    }
    assert(!plugin.transform("org.apache.spark.ml.feature.Imputer").isPresent)
  }

  test("user param JSON contains only explicitly-set params") {
    val est = new TpuKMeans().setK(7).setMaxIter(11)
    val json = ModelHelper.userParamsJson(est)
    assert(json.contains("\"k\":7"))
    assert(json.contains("\"maxIter\":11"))
    assert(!json.contains("seed")) // defaults are not user-set
  }

  test("logistic regression attributes parse from the tagged-JSON dict") {
    val json =
      """{"coefficients": {"__nd__": [[1.0, 2.0, 3.0]], "dtype": "float32"},
         |"intercepts": {"__nd__": [0.25], "dtype": "float32"},
         |"num_classes": 2, "n_iter": 9}""".stripMargin
    val (coef, icpt, k) = ModelHelper.logisticRegressionAttributes(json)
    assert(coef.numRows == 1 && coef.numCols == 3)
    assert(coef(0, 1) == 2.0)
    assert(icpt(0) == 0.25)
    assert(k == 2)
  }

  test("kmeans centers parse row-major") {
    val json = """{"cluster_centers": {"__nd__": [[0.0, 1.0], [2.0, 3.0]]}}"""
    val centers = ModelHelper.kmeansCenters(json)
    assert(centers.length == 2)
    assert(centers(1)(0) == 2.0 && centers(1)(1) == 3.0)
  }

  test("pca components transpose to an n x k pc matrix") {
    // 2 components over 3 features -> pc is 3x2 with components as columns
    val json =
      """{"components": {"__nd__": [[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]]},
         |"explained_variance_ratio": {"__nd__": [0.7, 0.2]}}""".stripMargin
    val (pc, ev) = ModelHelper.pcaAttributes(json)
    assert(pc.numRows == 3 && pc.numCols == 2)
    assert(pc(0, 0) == 1.0 && pc(1, 1) == 1.0)
    assert(ev(0) == 0.7)
  }

  test("linear regression attributes parse") {
    val json =
      """{"coefficients": {"__nd__": [1.5, -2.5]}, "intercept": 0.5, "n_iter": 1}"""
    val (coef, icpt) = ModelHelper.linearRegressionAttributes(json)
    assert(coef.size == 2 && coef(1) == -2.5)
    assert(icpt == 0.5)
  }
}
