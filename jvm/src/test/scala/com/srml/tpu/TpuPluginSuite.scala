/*
 * JVM-half test suite (role of reference jvm/src/test/scala/.../
 * SparkRapidsMLSuite.scala): plugin remap coverage, params JSON serialization,
 * attribute-JSON parsing, model construction from attributes, and — when a
 * Connect-enabled session with the Python backend is available
 * (SRML_TPU_CONNECT_TEST=1) — full estimator roundtrips per accelerated family.
 * Runs under `sbt test` / jvm/build.sh where Spark 4 is on the classpath (no
 * Scala toolchain ships in the development image; ci/jvm_build_status.json
 * records each attempt).
 */
package com.srml.tpu

import java.nio.file.Files

import org.apache.spark.ml.linalg.Vectors
import org.apache.spark.ml.tpu.{ModelHelper, TpuKMeansModel, TpuLinearRegressionModel, TpuLogisticRegressionModel, TpuModelIO, TpuPCAModel, TpuRandomForestClassificationModel, TpuRandomForestRegressionModel}
import org.apache.spark.sql.SparkSession
import org.scalatest.funsuite.AnyFunSuite

class TpuPluginSuite extends AnyFunSuite {

  private def tempDir(): String =
    Files.createTempDirectory("tpu-plugin-suite").toString

  // ---- gated Connect-session roundtrips (reference SparkRapidsMLSuite runs
  // these unconditionally; here the Python backend + Connect jars may be absent,
  // so they cancel cleanly instead of failing the unit tier) ----

  private lazy val maybeSpark: Option[SparkSession] =
    if (sys.env.get("SRML_TPU_CONNECT_TEST").contains("1")) {
      Some(
        SparkSession
          .builder()
          .master("local[2]")
          .appName("TpuPluginSuite")
          .config("spark.connect.ml.backend.classes", "com.srml.tpu.Plugin")
          .getOrCreate())
    } else None

  private def withSession(body: SparkSession => Unit): Unit =
    maybeSpark match {
      case Some(spark) => body(spark)
      case None => cancel("set SRML_TPU_CONNECT_TEST=1 with a Connect-enabled Spark")
    }

  private def binaryDf(spark: SparkSession) = {
    val rows = (0 until 64).map { i =>
      val x = i.toDouble / 64.0
      (Vectors.dense(x, 1.0 - x, (i % 3).toDouble), if (x > 0.5) 1.0 else 0.0)
    }
    spark.createDataFrame(rows).toDF("features", "label")
  }

  test("plugin remaps every accelerated estimator and model") {
    val plugin = new Plugin
    val expected = Seq(
      "org.apache.spark.ml.classification.LogisticRegression" ->
        "com.srml.tpu.TpuLogisticRegression",
      "org.apache.spark.ml.classification.LogisticRegressionModel" ->
        "org.apache.spark.ml.tpu.TpuLogisticRegressionModel",
      "org.apache.spark.ml.clustering.KMeans" -> "com.srml.tpu.TpuKMeans",
      "org.apache.spark.ml.feature.PCA" -> "com.srml.tpu.TpuPCA",
      "org.apache.spark.ml.regression.LinearRegression" ->
        "com.srml.tpu.TpuLinearRegression",
      "org.apache.spark.ml.classification.RandomForestClassifier" ->
        "com.srml.tpu.TpuRandomForestClassifier",
      "org.apache.spark.ml.regression.RandomForestRegressor" ->
        "com.srml.tpu.TpuRandomForestRegressor"
    )
    expected.foreach { case (sparkName, tpuName) =>
      assert(plugin.transform(sparkName).get() == tpuName, sparkName)
    }
    assert(!plugin.transform("org.apache.spark.ml.feature.Imputer").isPresent)
  }

  test("user param JSON contains only explicitly-set params") {
    val est = new TpuKMeans().setK(7).setMaxIter(11)
    val json = ModelHelper.userParamsJson(est)
    assert(json.contains("\"k\":7"))
    assert(json.contains("\"maxIter\":11"))
    assert(!json.contains("seed")) // defaults are not user-set
  }

  test("logistic regression attributes parse from the tagged-JSON dict") {
    val json =
      """{"coefficients": {"__nd__": [[1.0, 2.0, 3.0]], "dtype": "float32"},
         |"intercepts": {"__nd__": [0.25], "dtype": "float32"},
         |"num_classes": 2, "n_iter": 9}""".stripMargin
    val (coef, icpt, k) = ModelHelper.logisticRegressionAttributes(json)
    assert(coef.numRows == 1 && coef.numCols == 3)
    assert(coef(0, 1) == 2.0)
    assert(icpt(0) == 0.25)
    assert(k == 2)
  }

  test("kmeans centers parse row-major") {
    val json = """{"cluster_centers": {"__nd__": [[0.0, 1.0], [2.0, 3.0]]}}"""
    val centers = ModelHelper.kmeansCenters(json)
    assert(centers.length == 2)
    assert(centers(1)(0) == 2.0 && centers(1)(1) == 3.0)
  }

  test("pca components transpose to an n x k pc matrix") {
    // 2 components over 3 features -> pc is 3x2 with components as columns
    val json =
      """{"components": {"__nd__": [[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]]},
         |"explained_variance_ratio": {"__nd__": [0.7, 0.2]}}""".stripMargin
    val (pc, ev) = ModelHelper.pcaAttributes(json)
    assert(pc.numRows == 3 && pc.numCols == 2)
    assert(pc(0, 0) == 1.0 && pc(1, 1) == 1.0)
    assert(ev(0) == 0.7)
  }

  test("linear regression attributes parse") {
    val json =
      """{"coefficients": {"__nd__": [1.5, -2.5]}, "intercept": 0.5, "n_iter": 1}"""
    val (coef, icpt) = ModelHelper.linearRegressionAttributes(json)
    assert(coef.size == 2 && coef(1) == -2.5)
    assert(icpt == 0.5)
  }

  test("forest shape parses for classifier and regressor dicts") {
    val cls = """{"num_features": 12, "num_classes": 3, "forest": {}}"""
    assert(ModelHelper.forestShape(cls, classification = true) == ((12, 3)))
    val reg = """{"num_features": 7, "forest": {}}"""
    assert(ModelHelper.forestShape(reg, classification = false) == ((7, 0)))
    // missing num_features degrades to -1 rather than throwing (model transform
    // goes through Python anyway; the shape is advisory)
    assert(ModelHelper.forestShape("{}", classification = true) == ((-1, 2)))
  }

  test("user param JSON covers every accelerated estimator type") {
    val ests: Seq[(org.apache.spark.ml.param.Params, String)] = Seq(
      new TpuLogisticRegression().setMaxIter(3) -> "\"maxIter\":3",
      new TpuLinearRegression().setRegParam(0.5) -> "\"regParam\":0.5",
      new TpuKMeans().setK(4) -> "\"k\":4",
      new TpuPCA().setK(2) -> "\"k\":2",
      new TpuRandomForestClassifier().setNumTrees(9) -> "\"numTrees\":9",
      new TpuRandomForestRegressor().setMaxDepth(6) -> "\"maxDepth\":6"
    )
    ests.foreach { case (est, expect) =>
      val json = ModelHelper.userParamsJson(est)
      assert(json.contains(expect), s"${est.getClass.getSimpleName}: $json")
    }
  }

  test("kmeans model builds from parsed centers with parent params copied") {
    val json = """{"cluster_centers": {"__nd__": [[0.0, 1.0], [2.0, 3.0]]}}"""
    val est = new TpuKMeans().setK(2).setPredictionCol("cluster")
    val model = TpuKMeansModel.create(
      est.uid, ModelHelper.kmeansCenters(json), json, est)
    assert(model.clusterCenters.length == 2)
    assert(model.clusterCenters(1)(1) == 3.0)
    assert(model.getPredictionCol == "cluster")
  }

  test("pca model builds from parsed components with parent params copied") {
    val json =
      """{"components": {"__nd__": [[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]]},
         |"explained_variance_ratio": {"__nd__": [0.7, 0.2]}}""".stripMargin
    val est = new TpuPCA().setK(2).setOutputCol("pcs")
    val (pc, ev) = ModelHelper.pcaAttributes(json)
    val model = TpuPCAModel.create(est.uid, pc, ev, json, est)
    assert(model.pc.numRows == 3 && model.pc.numCols == 2)
    assert(model.explainedVariance(0) == 0.7)
    assert(model.getOutputCol == "pcs")
  }

  test("logistic regression attribute parse rejects malformed dicts") {
    intercept[Exception] {
      ModelHelper.logisticRegressionAttributes("""{"not_coefficients": 1}""")
    }
  }

  // ---- session-free model persistence roundtrips (the reference's per-family
  // model.write/save + Model.load + modelAttributes-equality assertions,
  // SparkRapidsMLSuite.scala:100-105 etc., portable to the unit tier because
  // TpuModelIO needs no SparkSession) ----

  test("persistence: LogisticRegression model roundtrips with attributes") {
    val json =
      """{"coefficients": {"__nd__": [[1.0, 2.0, 3.0]], "dtype": "float32"},
         |"intercepts": {"__nd__": [0.25], "dtype": "float32"},
         |"num_classes": 2, "n_iter": 9}""".stripMargin
    val (coef, icpt, k) = ModelHelper.logisticRegressionAttributes(json)
    val model = new org.apache.spark.ml.tpu.TpuLogisticRegressionModel(
      "lr-uid-1", coef, icpt, k, json)
    model.set(model.featuresCol, "test_feature")
    model.set(model.maxIter, 23)
    model.set(model.tol, 0.03)
    val path = tempDir()
    model.saveTpu(path)
    val loaded = TpuLogisticRegressionModel.load(path)
    assert(loaded.uid == model.uid)
    assert(loaded.modelAttributes == model.modelAttributes)
    assert(loaded.getFeaturesCol == "test_feature")
    assert(loaded.getMaxIter == 23)
    assert(loaded.getTol == 0.03)
    assert(loaded.numClasses == 2)
    assert(loaded.coefficientMatrix(0, 1) == 2.0)
    assert(loaded.interceptVector(0) == 0.25)
  }

  test("persistence: LinearRegression model roundtrips with attributes") {
    val json =
      """{"coefficients": {"__nd__": [1.5, -2.5]}, "intercept": 0.5, "n_iter": 1}"""
    val (coef, icpt) = ModelHelper.linearRegressionAttributes(json)
    val model = new org.apache.spark.ml.tpu.TpuLinearRegressionModel(
      "linreg-uid-1", coef, icpt, json)
    model.set(model.labelCol, "class")
    model.set(model.regParam, 0.5)
    val path = tempDir()
    model.saveTpu(path)
    val loaded = TpuLinearRegressionModel.load(path)
    assert(loaded.uid == model.uid)
    assert(loaded.modelAttributes == model.modelAttributes)
    assert(loaded.getLabelCol == "class")
    assert(loaded.getRegParam == 0.5)
    assert(loaded.coefficients(1) == -2.5)
    assert(loaded.intercept == 0.5)
  }

  test("persistence: RandomForestClassification model roundtrips with attributes") {
    val json = """{"num_features": 12, "num_classes": 3, "forest": {"trees": []}}"""
    val model = new org.apache.spark.ml.tpu.TpuRandomForestClassificationModel(
      "rfc-uid-1", 12, 3, json)
    model.set(model.maxDepth, 4)
    model.set(model.maxBins, 7)
    val path = tempDir()
    model.saveTpu(path)
    val loaded = TpuRandomForestClassificationModel.load(path)
    assert(loaded.uid == model.uid)
    assert(loaded.modelAttributes == model.modelAttributes)
    assert(loaded.getMaxDepth == 4)
    assert(loaded.getMaxBins == 7)
    assert(loaded.numFeatures == 12)
    assert(loaded.numClasses == 3)
  }

  test("persistence: RandomForestRegression model roundtrips with attributes") {
    val json = """{"num_features": 7, "forest": {"trees": []}}"""
    val model = new org.apache.spark.ml.tpu.TpuRandomForestRegressionModel(
      "rfr-uid-1", 7, json)
    model.set(model.numTrees, 5)
    val path = tempDir()
    model.saveTpu(path)
    val loaded = TpuRandomForestRegressionModel.load(path)
    assert(loaded.uid == model.uid)
    assert(loaded.modelAttributes == model.modelAttributes)
    assert(loaded.numFeatures == 7)
  }

  test("persistence: load surfaces the persisted class name") {
    val json = """{"coefficients": {"__nd__": [1.0]}, "intercept": 0.0}"""
    val (coef, icpt) = ModelHelper.linearRegressionAttributes(json)
    val model = new org.apache.spark.ml.tpu.TpuLinearRegressionModel(
      "cls-uid", coef, icpt, json)
    val path = tempDir()
    model.saveTpu(path)
    val doc = TpuModelIO.load(path)
    assert(doc.className.endsWith("TpuLinearRegressionModel"))
    assert(doc.uid == "cls-uid")
  }

  test("persistence: missing file fails loudly, not with a default model") {
    intercept[Exception] {
      TpuLinearRegressionModel.load(tempDir() + "/nonexistent")
    }
  }

  test("persistence: loading a path saved by another model type is rejected") {
    // forestShape would degrade missing fields to (-1, 2): without the class
    // check the caller would get a silently-corrupt RF model
    val json = """{"coefficients": {"__nd__": [1.0, 2.0]}, "intercept": 0.0}"""
    val (coef, icpt) = ModelHelper.linearRegressionAttributes(json)
    val model = new org.apache.spark.ml.tpu.TpuLinearRegressionModel(
      "xtype-uid", coef, icpt, json)
    val path = tempDir()
    model.saveTpu(path)
    val e = intercept[IllegalArgumentException] {
      TpuRandomForestClassificationModel.load(path)
    }
    assert(e.getMessage.contains("TpuLinearRegressionModel"))
  }

  // ---- param JSON restore (the load half of the persisted-params contract) ----

  test("applyParamsJson restores every user-set param with type coercion") {
    val src = new TpuKMeans().setK(7).setMaxIter(11).setTol(0.5).setSeed(99L)
    val json = ModelHelper.userParamsJson(src)
    val dst = new TpuKMeans()
    ModelHelper.applyParamsJson(dst, json)
    assert(dst.getK == 7)
    assert(dst.getMaxIter == 11)
    assert(dst.getTol == 0.5)
    assert(dst.getSeed == 99L)
  }

  test("applyParamsJson coerces ints into double params") {
    // json4s parses 1 as JInt even when the target param is a DoubleParam
    val dst = new TpuLinearRegression()
    ModelHelper.applyParamsJson(dst, """{"regParam": 1, "maxIter": 5}""")
    assert(dst.getRegParam == 1.0)
    assert(dst.getMaxIter == 5)
  }

  test("applyParamsJson coerces a double-encoded seed into the long param") {
    // json4s re-parses a persisted long as JDouble (99.0): the LongParam case
    // must coerce it at load time — pre-fix the generic JDouble fallthrough
    // boxed a Double into Param[Long] and getSeed threw ClassCastException
    val dst = new TpuKMeans()
    ModelHelper.applyParamsJson(dst, """{"seed": 99.0, "k": 4}""")
    assert(dst.getSeed == 99L)
    assert(dst.getK == 4)
  }

  test("applyParamsJson fails AT LOAD on a non-coercible typed param value") {
    val dst = new TpuKMeans()
    intercept[IllegalArgumentException] {
      ModelHelper.applyParamsJson(dst, """{"seed": "not-a-number"}""")
    }
  }

  test("applyParamsJson ignores unknown params instead of throwing") {
    val dst = new TpuPCA()
    ModelHelper.applyParamsJson(dst, """{"k": 3, "not_a_param": "x"}""")
    assert(dst.getK == 3)
    assert(!dst.isSet(dst.inputCol))
  }

  test("param JSON roundtrips for every accelerated estimator type") {
    val pairs: Seq[(org.apache.spark.ml.param.Params,
                    org.apache.spark.ml.param.Params)] = Seq(
      new TpuLogisticRegression().setMaxIter(3).setRegParam(0.1) ->
        new TpuLogisticRegression(),
      new TpuLinearRegression().setRegParam(0.5).setElasticNetParam(0.2) ->
        new TpuLinearRegression(),
      new TpuKMeans().setK(4).setMaxIter(7) -> new TpuKMeans(),
      new TpuPCA().setK(2).setInputCol("f") -> new TpuPCA(),
      new TpuRandomForestClassifier().setNumTrees(9).setMaxDepth(3) ->
        new TpuRandomForestClassifier(),
      new TpuRandomForestRegressor().setMaxDepth(6).setMaxBins(15) ->
        new TpuRandomForestRegressor()
    )
    pairs.foreach { case (src, dst) =>
      ModelHelper.applyParamsJson(dst, ModelHelper.userParamsJson(src))
      src.params.filter(src.isSet(_)).foreach { p =>
        assert(dst.isSet(dst.getParam(p.name)), s"${src.getClass.getSimpleName}.${p.name}")
        assert(
          dst.get(dst.getParam(p.name)).get == src.get(p).get,
          s"${src.getClass.getSimpleName}.${p.name}")
      }
    }
  }

  // ---- Connect-session roundtrips (one per accelerated family; the reference
  // suite's RapidsLogisticRegression/RapidsKMeans/RapidsPCA/... tests) ----

  test("roundtrip: LogisticRegression via the plugin") {
    withSession { spark =>
      val df = binaryDf(spark)
      val model = new TpuLogisticRegression().setMaxIter(20).train(df)
      assert(model.numClasses == 2)
      assert(model.coefficientMatrix.numCols == 3)
      val out = model.transform(df)
      assert(out.columns.contains("prediction"))
      assert(out.count() == 64)
    }
  }

  test("roundtrip: KMeans via the plugin") {
    withSession { spark =>
      val df = binaryDf(spark)
      val model = new TpuKMeans().setK(2).setSeed(1).fit(df)
      assert(model.clusterCenters.length == 2)
      val preds = model.transform(df).select("prediction").distinct().count()
      assert(preds <= 2)
    }
  }

  test("roundtrip: PCA via the plugin") {
    withSession { spark =>
      val df = binaryDf(spark)
      val model = new TpuPCA().setK(2).setInputCol("features").setOutputCol("pca").fit(df)
      assert(model.pc.numCols == 2)
      assert(model.transform(df).columns.contains("pca"))
    }
  }

  test("roundtrip: LinearRegression via the plugin") {
    withSession { spark =>
      val df = binaryDf(spark)
      val model = new TpuLinearRegression().setMaxIter(10).train(df)
      assert(model.coefficients.size == 3)
      assert(model.transform(df).columns.contains("prediction"))
    }
  }

  test("roundtrip: RandomForestClassifier via the plugin") {
    withSession { spark =>
      val df = binaryDf(spark)
      val model = new TpuRandomForestClassifier().setNumTrees(5).train(df)
      assert(model.numClasses == 2)
      assert(model.transform(df).columns.contains("prediction"))
    }
  }

  test("roundtrip: RandomForestRegressor via the plugin") {
    withSession { spark =>
      val df = binaryDf(spark)
      val model = new TpuRandomForestRegressor().setNumTrees(5).train(df)
      assert(model.numFeatures == 3)
      assert(model.transform(df).columns.contains("prediction"))
    }
  }

  // ---- estimator persistence through Spark's writer (the reference's
  // lr.write.overwrite().save + Estimator.load half, SparkRapidsMLSuite.scala:
  // 82-89 — needs a session for the Hadoop FS path) ----

  test("estimator persistence: LogisticRegression save/load keeps user params") {
    withSession { _ =>
      val est = new TpuLogisticRegression()
        .setFeaturesCol("test_feature")
        .setLabelCol("class")
        .setMaxIter(23)
        .setTol(0.03)
      val path = tempDir() + "/LogisticRegression"
      est.write.overwrite().save(path)
      val loaded = TpuLogisticRegression.load(path)
      assert(loaded.getFeaturesCol == "test_feature")
      assert(loaded.getLabelCol == "class")
      assert(loaded.getMaxIter == 23)
      assert(loaded.getTol == 0.03)
    }
  }

  test("estimator persistence: RandomForestClassifier save/load keeps user params") {
    withSession { _ =>
      val est = new TpuRandomForestClassifier()
        .setFeaturesCol("test_feature")
        .setLabelCol("class")
        .setMaxDepth(4)
        .setMaxBins(7)
      val path = tempDir() + "/RandomForestClassifier"
      est.write.overwrite().save(path)
      val loaded = TpuRandomForestClassifier.load(path)
      assert(loaded.getMaxDepth == 4)
      assert(loaded.getMaxBins == 7)
    }
  }

  test("estimator persistence: KMeans and PCA save/load keep user params") {
    withSession { _ =>
      val km = new TpuKMeans().setK(6).setSeed(3L)
      val kmPath = tempDir() + "/KMeans"
      km.write.overwrite().save(kmPath)
      assert(TpuKMeans.load(kmPath).getK == 6)

      val pca = new TpuPCA().setK(2).setInputCol("test_feature").setOutputCol("pca_feature")
      val pcaPath = tempDir() + "/PCA"
      pca.write.overwrite().save(pcaPath)
      val loadedPca = TpuPCA.load(pcaPath)
      assert(loadedPca.getK == 2)
      assert(loadedPca.getInputCol == "test_feature")
      assert(loadedPca.getOutputCol == "pca_feature")
    }
  }

  // ---- Python-vs-JVM transform parity (the reference's
  // "spark.rapids.ml.python.transform.enabled" toggle cases,
  // SparkRapidsMLSuite.scala:107-120: same columns up to order, both collect) ----

  test("transform toggle: LogisticRegression python and JVM paths agree on schema") {
    withSession { spark =>
      val df = binaryDf(spark)
      val model = new TpuLogisticRegression().setMaxIter(20).train(df)
      val dfPython = model.transform(df)
      spark.conf.set("spark.rapids.ml.tpu.python.transform.enabled", "false")
      try {
        val dfJvm = model.transform(df)
        assert(dfPython.schema.names.sorted sameElements dfJvm.schema.names.sorted)
        dfPython.collect()
        dfJvm.collect()
      } finally {
        spark.conf.set("spark.rapids.ml.tpu.python.transform.enabled", "true")
      }
    }
  }

  test("transform toggle: LinearRegression python and JVM paths agree on schema") {
    withSession { spark =>
      val df = binaryDf(spark)
      val model = new TpuLinearRegression().setMaxIter(10).train(df)
      val dfPython = model.transform(df)
      spark.conf.set("spark.rapids.ml.tpu.python.transform.enabled", "false")
      try {
        val dfJvm = model.transform(df)
        assert(dfPython.schema.names.sorted sameElements dfJvm.schema.names.sorted)
        dfPython.collect()
        dfJvm.collect()
      } finally {
        spark.conf.set("spark.rapids.ml.tpu.python.transform.enabled", "true")
      }
    }
  }

  // ---- array<double> features input (the reference's "array input" case,
  // SparkRapidsMLSuite.scala:395-424: accelerated estimators accept raw array
  // columns, which plain Spark ML rejects) ----

  test("array input: KMeans fits on array<double> features") {
    withSession { spark =>
      val rows = (0 until 32).map { i =>
        Tuple1(Array(i.toDouble / 32.0, 1.0 - i.toDouble / 32.0))
      }
      val df = spark.createDataFrame(rows).toDF("features")
      val model = new TpuKMeans().setK(2).setSeed(1).fit(df)
      assert(model.clusterCenters.length == 2)
    }
  }

  test("array input: LogisticRegression fits on array<double> features") {
    withSession { spark =>
      val rows = (0 until 32).map { i =>
        val x = i.toDouble / 32.0
        (Array(x, 1.0 - x), if (x > 0.5) 1.0 else 0.0)
      }
      val df = spark.createDataFrame(rows).toDF("features", "label")
      val model = new TpuLogisticRegression().setMaxIter(10).train(df)
      assert(model.numClasses == 2)
    }
  }
}
