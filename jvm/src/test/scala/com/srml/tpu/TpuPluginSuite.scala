/*
 * JVM-half test suite (role of reference jvm/src/test/scala/.../
 * SparkRapidsMLSuite.scala): plugin remap coverage, params JSON serialization,
 * attribute-JSON parsing, model construction from attributes, and — when a
 * Connect-enabled session with the Python backend is available
 * (SRML_TPU_CONNECT_TEST=1) — full estimator roundtrips per accelerated family.
 * Runs under `sbt test` / jvm/build.sh where Spark 4 is on the classpath (no
 * Scala toolchain ships in the development image; ci/jvm_build_status.json
 * records each attempt).
 */
package com.srml.tpu

import org.apache.spark.ml.linalg.Vectors
import org.apache.spark.ml.tpu.{ModelHelper, TpuKMeansModel, TpuPCAModel}
import org.apache.spark.sql.SparkSession
import org.scalatest.funsuite.AnyFunSuite

class TpuPluginSuite extends AnyFunSuite {

  // ---- gated Connect-session roundtrips (reference SparkRapidsMLSuite runs
  // these unconditionally; here the Python backend + Connect jars may be absent,
  // so they cancel cleanly instead of failing the unit tier) ----

  private lazy val maybeSpark: Option[SparkSession] =
    if (sys.env.get("SRML_TPU_CONNECT_TEST").contains("1")) {
      Some(
        SparkSession
          .builder()
          .master("local[2]")
          .appName("TpuPluginSuite")
          .config("spark.connect.ml.backend.classes", "com.srml.tpu.Plugin")
          .getOrCreate())
    } else None

  private def withSession(body: SparkSession => Unit): Unit =
    maybeSpark match {
      case Some(spark) => body(spark)
      case None => cancel("set SRML_TPU_CONNECT_TEST=1 with a Connect-enabled Spark")
    }

  private def binaryDf(spark: SparkSession) = {
    val rows = (0 until 64).map { i =>
      val x = i.toDouble / 64.0
      (Vectors.dense(x, 1.0 - x, (i % 3).toDouble), if (x > 0.5) 1.0 else 0.0)
    }
    spark.createDataFrame(rows).toDF("features", "label")
  }

  test("plugin remaps every accelerated estimator and model") {
    val plugin = new Plugin
    val expected = Seq(
      "org.apache.spark.ml.classification.LogisticRegression" ->
        "com.srml.tpu.TpuLogisticRegression",
      "org.apache.spark.ml.classification.LogisticRegressionModel" ->
        "org.apache.spark.ml.tpu.TpuLogisticRegressionModel",
      "org.apache.spark.ml.clustering.KMeans" -> "com.srml.tpu.TpuKMeans",
      "org.apache.spark.ml.feature.PCA" -> "com.srml.tpu.TpuPCA",
      "org.apache.spark.ml.regression.LinearRegression" ->
        "com.srml.tpu.TpuLinearRegression",
      "org.apache.spark.ml.classification.RandomForestClassifier" ->
        "com.srml.tpu.TpuRandomForestClassifier",
      "org.apache.spark.ml.regression.RandomForestRegressor" ->
        "com.srml.tpu.TpuRandomForestRegressor"
    )
    expected.foreach { case (sparkName, tpuName) =>
      assert(plugin.transform(sparkName).get() == tpuName, sparkName)
    }
    assert(!plugin.transform("org.apache.spark.ml.feature.Imputer").isPresent)
  }

  test("user param JSON contains only explicitly-set params") {
    val est = new TpuKMeans().setK(7).setMaxIter(11)
    val json = ModelHelper.userParamsJson(est)
    assert(json.contains("\"k\":7"))
    assert(json.contains("\"maxIter\":11"))
    assert(!json.contains("seed")) // defaults are not user-set
  }

  test("logistic regression attributes parse from the tagged-JSON dict") {
    val json =
      """{"coefficients": {"__nd__": [[1.0, 2.0, 3.0]], "dtype": "float32"},
         |"intercepts": {"__nd__": [0.25], "dtype": "float32"},
         |"num_classes": 2, "n_iter": 9}""".stripMargin
    val (coef, icpt, k) = ModelHelper.logisticRegressionAttributes(json)
    assert(coef.numRows == 1 && coef.numCols == 3)
    assert(coef(0, 1) == 2.0)
    assert(icpt(0) == 0.25)
    assert(k == 2)
  }

  test("kmeans centers parse row-major") {
    val json = """{"cluster_centers": {"__nd__": [[0.0, 1.0], [2.0, 3.0]]}}"""
    val centers = ModelHelper.kmeansCenters(json)
    assert(centers.length == 2)
    assert(centers(1)(0) == 2.0 && centers(1)(1) == 3.0)
  }

  test("pca components transpose to an n x k pc matrix") {
    // 2 components over 3 features -> pc is 3x2 with components as columns
    val json =
      """{"components": {"__nd__": [[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]]},
         |"explained_variance_ratio": {"__nd__": [0.7, 0.2]}}""".stripMargin
    val (pc, ev) = ModelHelper.pcaAttributes(json)
    assert(pc.numRows == 3 && pc.numCols == 2)
    assert(pc(0, 0) == 1.0 && pc(1, 1) == 1.0)
    assert(ev(0) == 0.7)
  }

  test("linear regression attributes parse") {
    val json =
      """{"coefficients": {"__nd__": [1.5, -2.5]}, "intercept": 0.5, "n_iter": 1}"""
    val (coef, icpt) = ModelHelper.linearRegressionAttributes(json)
    assert(coef.size == 2 && coef(1) == -2.5)
    assert(icpt == 0.5)
  }

  test("forest shape parses for classifier and regressor dicts") {
    val cls = """{"num_features": 12, "num_classes": 3, "forest": {}}"""
    assert(ModelHelper.forestShape(cls, classification = true) == ((12, 3)))
    val reg = """{"num_features": 7, "forest": {}}"""
    assert(ModelHelper.forestShape(reg, classification = false) == ((7, 0)))
    // missing num_features degrades to -1 rather than throwing (model transform
    // goes through Python anyway; the shape is advisory)
    assert(ModelHelper.forestShape("{}", classification = true) == ((-1, 2)))
  }

  test("user param JSON covers every accelerated estimator type") {
    val ests: Seq[(org.apache.spark.ml.param.Params, String)] = Seq(
      new TpuLogisticRegression().setMaxIter(3) -> "\"maxIter\":3",
      new TpuLinearRegression().setRegParam(0.5) -> "\"regParam\":0.5",
      new TpuKMeans().setK(4) -> "\"k\":4",
      new TpuPCA().setK(2) -> "\"k\":2",
      new TpuRandomForestClassifier().setNumTrees(9) -> "\"numTrees\":9",
      new TpuRandomForestRegressor().setMaxDepth(6) -> "\"maxDepth\":6"
    )
    ests.foreach { case (est, expect) =>
      val json = ModelHelper.userParamsJson(est)
      assert(json.contains(expect), s"${est.getClass.getSimpleName}: $json")
    }
  }

  test("kmeans model builds from parsed centers with parent params copied") {
    val json = """{"cluster_centers": {"__nd__": [[0.0, 1.0], [2.0, 3.0]]}}"""
    val est = new TpuKMeans().setK(2).setPredictionCol("cluster")
    val model = TpuKMeansModel.create(
      est.uid, ModelHelper.kmeansCenters(json), json, est)
    assert(model.clusterCenters.length == 2)
    assert(model.clusterCenters(1)(1) == 3.0)
    assert(model.getPredictionCol == "cluster")
  }

  test("pca model builds from parsed components with parent params copied") {
    val json =
      """{"components": {"__nd__": [[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]]},
         |"explained_variance_ratio": {"__nd__": [0.7, 0.2]}}""".stripMargin
    val est = new TpuPCA().setK(2).setOutputCol("pcs")
    val (pc, ev) = ModelHelper.pcaAttributes(json)
    val model = TpuPCAModel.create(est.uid, pc, ev, json, est)
    assert(model.pc.numRows == 3 && model.pc.numCols == 2)
    assert(model.explainedVariance(0) == 0.7)
    assert(model.getOutputCol == "pcs")
  }

  test("logistic regression attribute parse rejects malformed dicts") {
    intercept[Exception] {
      ModelHelper.logisticRegressionAttributes("""{"not_coefficients": 1}""")
    }
  }

  // ---- Connect-session roundtrips (one per accelerated family; the reference
  // suite's RapidsLogisticRegression/RapidsKMeans/RapidsPCA/... tests) ----

  test("roundtrip: LogisticRegression via the plugin") {
    withSession { spark =>
      val df = binaryDf(spark)
      val model = new TpuLogisticRegression().setMaxIter(20).train(df)
      assert(model.numClasses == 2)
      assert(model.coefficientMatrix.numCols == 3)
      val out = model.transform(df)
      assert(out.columns.contains("prediction"))
      assert(out.count() == 64)
    }
  }

  test("roundtrip: KMeans via the plugin") {
    withSession { spark =>
      val df = binaryDf(spark)
      val model = new TpuKMeans().setK(2).setSeed(1).fit(df)
      assert(model.clusterCenters.length == 2)
      val preds = model.transform(df).select("prediction").distinct().count()
      assert(preds <= 2)
    }
  }

  test("roundtrip: PCA via the plugin") {
    withSession { spark =>
      val df = binaryDf(spark)
      val model = new TpuPCA().setK(2).setInputCol("features").setOutputCol("pca").fit(df)
      assert(model.pc.numCols == 2)
      assert(model.transform(df).columns.contains("pca"))
    }
  }

  test("roundtrip: LinearRegression via the plugin") {
    withSession { spark =>
      val df = binaryDf(spark)
      val model = new TpuLinearRegression().setMaxIter(10).train(df)
      assert(model.coefficients.size == 3)
      assert(model.transform(df).columns.contains("prediction"))
    }
  }

  test("roundtrip: RandomForestClassifier via the plugin") {
    withSession { spark =>
      val df = binaryDf(spark)
      val model = new TpuRandomForestClassifier().setNumTrees(5).train(df)
      assert(model.numClasses == 2)
      assert(model.transform(df).columns.contains("prediction"))
    }
  }

  test("roundtrip: RandomForestRegressor via the plugin") {
    withSession { spark =>
      val df = binaryDf(spark)
      val model = new TpuRandomForestRegressor().setNumTrees(5).train(df)
      assert(model.numFeatures == 3)
      assert(model.transform(df).columns.contains("prediction"))
    }
  }
}
