#!/usr/bin/env python
"""Flagship benchmark: distributed KMeans fit throughput on the local device(s).

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Protocol follows the reference harness (reference python/benchmark/benchmark/base.py:
232-285: timed fit with quality score). The metric is Lloyd-iteration row throughput —
rows * iterations / wall-clock — on a dataset sized to the available memory, which is
the quantity the north-star target tracks (BASELINE.json: rows/sec/chip).

`vs_baseline`: the reference publishes no machine-readable numbers (BASELINE.md), so
the ratio is computed against a locally-recorded baseline in BENCH_BASELINE.json when
present (first run writes it), else 1.0.
"""

import functools
import json
import os
import subprocess
import sys
import time

import numpy as np


def _probe_once(timeout_s: float) -> int:
    probe = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices()"],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        return probe.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        probe.kill()
        probe.wait()
        return -1


def _device_init_watchdog(attempts: int = 2, timeout_s: float = 90.0) -> None:
    """The axon TPU tunnel can wedge so hard that `import jax` hangs every process.
    Probe device init in a subprocess with retry+backoff (the tunnel can recover
    between probes); only after all probes fail, re-exec ourselves on the CPU
    backend so the driver still gets a benchmark line (clearly labeled)."""
    if os.environ.get("SRML_BENCH_NO_WATCHDOG") == "1":
        return
    marker = "/tmp/.srml_bench_device_ok"
    try:
        # only trust a recent healthy probe: the tunnel can wedge minutes after a
        # good run (observed), and a stale marker would skip the probe and let the
        # un-watchdogged jax import hang the whole benchmark
        if os.path.exists(marker) and time.time() - os.path.getmtime(marker) < 600:
            return
    except OSError:
        pass
    # budget note: the whole probe sequence must leave room for the CPU-fallback
    # compute inside a ~300 s driver timeout (2 x 90 s + 10 s backoff + ~60 s run)
    rc = -1
    for attempt in range(attempts):
        if attempt:
            time.sleep(10.0 * attempt)  # linear backoff
        rc = _probe_once(timeout_s)
        if rc == 0:
            break
        print(
            f"bench watchdog: device probe attempt {attempt + 1}/{attempts} "
            f"failed (rc={rc})",
            file=sys.stderr,
        )
    if rc == 0:
        try:
            open(marker, "w").close()
        except OSError:
            pass
        return
    if rc != 0:
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS=(env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8").strip(),
            PALLAS_AXON_POOL_IPS="",
            SRML_BENCH_NO_WATCHDOG="1",
        )
        os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)


def main() -> None:
    # total wall budget: anchored BEFORE the watchdog probes and carried through
    # the CPU-fallback re-exec (SRML_BENCH_DEADLINE_TS), so wedged-tunnel probe
    # time counts against the same driver timeout. Families are deadline-guarded
    # (benchmark/chip_bench.py); unfinished ones land in `skipped`.
    budget_s = float(os.environ.get("SRML_BENCH_BUDGET_S", "240"))
    if "SRML_BENCH_DEADLINE_TS" in os.environ:
        deadline_ts = float(os.environ["SRML_BENCH_DEADLINE_TS"])
    else:
        deadline_ts = time.time() + budget_s
        os.environ["SRML_BENCH_DEADLINE_TS"] = str(deadline_ts)
    _device_init_watchdog()

    import jax
    import jax.numpy as jnp

    try:
        # persistent compile cache: family benches compile ~10 programs; repeat
        # runs (and the driver's run after this session's) skip all of it
        jax.config.update("jax_compilation_cache_dir", "/tmp/srml_jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    from spark_rapids_ml_tpu.ops.kmeans import lloyd_fit
    from spark_rapids_ml_tpu.parallel.mesh import get_mesh, shard_array

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"

    # size to platform: HBM-filling on TPU (~6 GiB f32 design matrix per chip on a
    # 16 GiB v5e, leaving headroom for the one-hot update and compiler scratch),
    # small on CPU
    if on_tpu:
        n_rows, n_cols, k, iters = 12_000_000, 128, 20, 10
    else:
        n_rows, n_cols, k, iters = 100_000, 64, 8, 10

    # synthesize blobs ON DEVICE: host→device transfer is the enemy (and the metric
    # tracks compute, not ingest — the reference times cuML fit after cudf ingest too).
    # The init is k REAL ROWS of X (what k-means|| reduces to), NOT the true centers:
    # a near-optimal init converges in ~2 Lloyd iterations and the whole-fit metric
    # then measures per-fit constants instead of iteration throughput (this exact
    # distortion made the round-2 headline read 101M when the steady-state rate of
    # the same code was ~640M rows*iters/s).
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = get_mesh()
    rowsh = NamedSharding(mesh, P("data", None))

    @functools.partial(jax.jit, out_shardings=(rowsh, None))
    def make_data(key):
        k1, k2, k3 = jax.random.split(key, 3)
        centers_true = jax.random.normal(k1, (k, n_cols), jnp.float32) * 5.0
        assign = jax.random.randint(k2, (n_rows,), 0, k)
        X = centers_true[assign] + jax.random.normal(k3, (n_rows, n_cols), jnp.float32)
        init = X[:k] * 1.0
        return X, init

    Xd, init = make_data(jax.random.PRNGKey(0))
    Xd.block_until_ready()
    w = shard_array(np.ones((n_rows,), dtype=np.float32), mesh)

    def _sync(*arrays):
        """Force completion by pulling the values to host. Under the axon remote
        tunnel `block_until_ready` can acknowledge dispatch before the device has
        finished executing (observed: a 4096^3 matmul "completing" in 0.02 ms);
        a device->host transfer of the result cannot lie."""
        return [np.asarray(a) for a in arrays]

    def _timed(fn, repeats=3):
        """Median wall-clock of fn() (synced); fn returns arrays to sync on."""
        ts = []
        out = None
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = fn()
            _sync(out[0])
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts)), out

    n_chips = jax.device_count()
    peak_bw = 819e9  # v5e HBM ~819 GB/s per chip

    def _kmeans_rates(X_, w_, init_, n_, d_):
        """THE Lloyd timing recipe (protocol 2): whole-fit throughput (reference
        protocol base.py:232-285 times the whole fit) plus the steady-state
        marginal rate (full fit minus a 1-iter fit cancels per-fit constants)
        and the two-X-read HBM roofline fraction — one helper so the headline
        and the 256-col tier can never drift apart. The Lloyd step reads X twice
        per iteration (distance matmul + one-hot update) plus the (n, k)
        intermediates once each; peak_bw is per-chip HBM."""
        _sync(lloyd_fit(X_, w_, init_, 0.0, 1)[0])  # compile warmups, untimed
        _sync(lloyd_fit(X_, w_, init_, 0.0, iters)[0])
        t_full, (centers_, inertia_, it_) = _timed(
            lambda: lloyd_fit(X_, w_, init_, 0.0, iters)
        )
        t_one, _ = _timed(lambda: lloyd_fit(X_, w_, init_, 0.0, 1))
        it_ = int(it_)
        whole = n_ * it_ / t_full / n_chips
        if it_ > 1:
            marg_t = max(t_full - t_one, 1e-9) / (it_ - 1)
            marginal = n_ / marg_t / n_chips
        else:
            # t_full - t_one is pure timing noise at n_iter=1; no marginal rate
            print(
                "bench: fit converged in <=1 iteration; marginal rate undefined",
                file=sys.stderr,
            )
            marg_t, marginal = None, None
        bytes_per_iter = 2 * n_ * d_ * 4 + 2 * n_ * k * 4
        roof = (
            (bytes_per_iter / peak_bw) / marg_t / n_chips
            if on_tpu and marg_t is not None
            else None
        )
        iter_ceiling = peak_bw / (2 * d_ * 4 + 2 * k * 4)
        return {
            "t_full": t_full,
            "centers": centers_,
            "inertia": inertia_,
            "n_iter": it_,
            "whole": whole,
            "marginal": marginal,
            "roofline_frac": roof,
            "whole_frac": whole / iter_ceiling if on_tpu else None,
        }

    hr = _kmeans_rates(Xd, w, init, n_rows, n_cols)
    fit_time, inertia, n_iter = hr["t_full"], hr["inertia"], hr["n_iter"]
    value = hr["whole"]
    marginal_rate_chip = hr["marginal"]
    roofline_frac = hr["roofline_frac"]

    # estimated MFU: one Lloyd iteration is ~4*n*d*k matmul FLOPs (2ndk distance
    # cross-term + 2nkd one-hot update); peak per chip assumes v5e f32 on MXU
    flops = 4.0 * n_rows * n_cols * k * n_iter
    peak_f32 = 98e12  # v5e ~197 TFLOP/s bf16 -> ~98 TFLOP/s f32-equivalent
    est_mfu = flops / fit_time / n_chips / peak_f32 if on_tpu else None

    # profiler trace AFTER the timed region (trace capture inflates the timed run)
    from spark_rapids_ml_tpu.profiling import trace as xplane_trace

    trace_dir = "/tmp/srml_bench_xplane" if on_tpu else None
    if trace_dir:
        with xplane_trace(trace_dir):
            _sync(lloyd_fit(Xd, w, init, 0.0, iters)[0])

    # secondary metric: the fast-math variant (assignment distances at MXU bf16,
    # model attributes still parity precision — config key fast_math)
    fast_fit = functools.partial(lloyd_fit, fast_math=True)
    _sync(fast_fit(Xd, w, init, 0.0, iters)[0])
    fast_time, (_, _, n_iter_f) = _timed(lambda: fast_fit(Xd, w, init, 0.0, iters))
    fast_rows_per_sec_chip = n_rows * int(n_iter_f) / fast_time / n_chips

    # secondary metrics (TPU only): the fused pallas Lloyd variants at 6-pass
    # parity precision — weighted (measured slower than XLA at this small-k shape,
    # see ops/pallas_kmeans.py header) and masked/no-weight-stream (the (blk,1)-
    # operand elimination that took the Gram kernel 3x; candidate to displace the
    # XLA headline path). Each carries a live parity check (same n_iter, inertia
    # within fp32 tolerance) and is exception-guarded so a Mosaic issue on new
    # hardware can never kill the benchmark line.
    def _pallas_variant(label, **variant_kw):
        try:
            from spark_rapids_ml_tpu.ops.pallas_kmeans import lloyd_fit_pallas

            mesh_obj = getattr(getattr(Xd, "sharding", None), "mesh", None)
            fit = functools.partial(
                lloyd_fit_pallas, mesh=mesh_obj,
                precision=jax.lax.Precision.HIGHEST, **variant_kw,
            )
            _sync(fit(Xd, w, init, 0.0, iters)[0])  # compile warmup
            t, (c_v, in_v, it_v) = _timed(lambda: fit(Xd, w, init, 0.0, iters))
            it_v = int(it_v)
            if it_v <= 1:
                print(
                    f"bench: {label} fit converged in <=1 iteration; "
                    "whole-fit rate reflects per-fit constants only",
                    file=sys.stderr,
                )
            rate = n_rows * it_v / t / n_chips
            parity = bool(
                it_v == n_iter
                and abs(float(in_v) - float(inertia)) <= 1e-4 * abs(float(inertia))
            )
            return rate, parity
        except Exception as e:  # pragma: no cover
            print(f"bench: {label} pallas lloyd unavailable: {e}", file=sys.stderr)
            return None, None

    fused_rows_per_sec_chip = fused_parity_ok = None
    masked_rows_per_sec_chip = masked_parity_ok = None
    if on_tpu:
        fused_rows_per_sec_chip, fused_parity_ok = _pallas_variant("fused")
        masked_rows_per_sec_chip, masked_parity_ok = _pallas_variant(
            "masked", unit_mask=True
        )

    # per-family secondaries: a number AND a quality score for every algorithm
    # family (reference protocol base.py:232-285), deadline-guarded. PCA (the
    # second north-star) now runs the fused pallas Gram kernel with a chained
    # marginal-rate protocol — the old one-warm-one-timed whole pass measured
    # mostly the ~67 ms tunnel dispatch overhead.
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from benchmark.chip_bench import make_ctx, run_families

    ctx = make_ctx(
        Xd, w, mesh, on_tpu, platform,
        repo_root=os.path.dirname(os.path.abspath(__file__)),
    )
    family_secondary = run_families(ctx, deadline_ts=deadline_ts - 45.0)

    # 256-col variants of the two north-star algorithms (BASELINE targets are
    # x256): drop the 128-col matrix first — 6 GiB each, both won't fit
    wide_secondary = {}
    if time.time() < deadline_ts - 30.0:
        try:
            # drop every live reference (ctx holds one) so HBM is actually freed
            ctx = dict(ctx, X=None, w=None)
            del Xd, w
            n256, d256 = (6_000_000, 256) if on_tpu else (50_000, 64)
            rowsh256 = NamedSharding(mesh, P("data", None))

            @functools.partial(jax.jit, out_shardings=(rowsh256, None))
            def make_wide(key):
                k1, k2, k3 = jax.random.split(key, 3)
                c = jax.random.normal(k1, (k, d256), jnp.float32) * 5.0
                a = jax.random.randint(k2, (n256,), 0, k)
                Xw_ = c[a] + jax.random.normal(k3, (n256, d256), jnp.float32)
                return Xw_, Xw_[:k] * 1.0

            X256, init256 = make_wide(jax.random.PRNGKey(1))
            _sync(X256[:1])
            w256 = shard_array(np.ones((n256,), np.float32), mesh)
            wr = _kmeans_rates(X256, w256, init256, n256, d256)
            # key names carry the REAL width: the CPU-fallback tier runs 64 cols
            # and must not masquerade as the 256-col north-star shape
            tag = f"kmeans_{d256}col"
            if wr["marginal"] is not None:
                wide_secondary[f"{tag}_marginal_rows_per_sec_per_chip"] = round(
                    wr["marginal"], 1
                )
                wide_secondary[f"{tag}_frac_of_ceiling"] = (
                    round(wr["roofline_frac"], 3)
                    if wr["roofline_frac"] is not None
                    else None
                )
            if time.time() < deadline_ts - 20.0:
                ctx256 = dict(ctx)
                ctx256.update(X=X256, w=w256)
                from benchmark.chip_bench import bench_pca

                p256 = bench_pca(ctx256)
                wide_secondary[f"pca_{d256}col_rows_per_sec_per_chip"] = p256.get(
                    "pca_cov_rows_per_sec_per_chip"
                )
                wide_secondary[f"pca_{d256}col_roofline_frac"] = p256.get(
                    "pca_roofline_frac"
                )
        except Exception as e:
            print(f"bench: 256-col tier failed: {e}", file=sys.stderr)
            wide_secondary["wide_tier_error"] = str(e)[:200]
    else:
        wide_secondary["skipped_wide"] = True

    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_BASELINE.json")
    vs_baseline = 1.0
    try:
        # protocol 2 = whole-fit timing with a k-real-rows far init (n_iter ≈
        # max_iter); protocol-less baselines were recorded under the old
        # near-optimal init whose n_iter=2 made the same code read ~6x slower —
        # comparing across protocols would report a spurious "speedup", so a
        # mismatched baseline is reseeded instead of compared against
        protocol = 2
        base = None
        if os.path.exists(baseline_path):
            with open(baseline_path) as f:
                base = json.load(f)
            if base.get("protocol") != protocol:
                print(
                    f"bench: baseline protocol {base.get('protocol')} != {protocol}; "
                    "reseeding baseline, vs_baseline reset to 1.0",
                    file=sys.stderr,
                )
                base = None
        if base is not None:
            if base.get("platform") == platform and base.get("value", 0) > 0:
                vs_baseline = value / base["value"]
        elif on_tpu:
            # only a real-TPU run may seed the local baseline; a transient
            # CPU-fallback run must not poison it
            with open(baseline_path, "w") as f:
                json.dump(
                    {
                        "platform": platform,
                        "value": value,
                        "unit": "rows*iters/sec/chip",
                        "protocol": protocol,
                    },
                    f,
                )
    except OSError:
        pass

    # a non-TPU run (watchdog fallback) is labeled in the metric name itself so the
    # recorded number can never masquerade as a TPU result
    metric = "kmeans_lloyd_rows_per_sec_per_chip"
    if not on_tpu:
        metric += f"_{platform}_fallback"
    # whole-fit ceiling: the marginal two-X-read roofline applied to n_iter
    # iterations (per-fit constants excluded — which is why whole-fit frac < the
    # marginal roofline_frac)
    iter_ceiling = peak_bw / (2 * n_cols * 4 + 2 * k * 4)
    secondary = {
        "kmeans_marginal_rows_per_sec_per_chip": (
            round(marginal_rate_chip, 1) if marginal_rate_chip is not None else None
        ),
        "kmeans_n_iter": n_iter,
        "kmeans_frac_of_ceiling": (
            round(value / iter_ceiling, 3) if on_tpu else None
        ),
        "kmeans_fast_math_rows_per_sec_per_chip": round(fast_rows_per_sec_chip, 1),
        "kmeans_fused_pallas_rows_per_sec_per_chip": (
            round(fused_rows_per_sec_chip, 1)
            if fused_rows_per_sec_chip is not None
            else None
        ),
        "fused_parity_ok": fused_parity_ok,
        "kmeans_masked_pallas_rows_per_sec_per_chip": (
            round(masked_rows_per_sec_chip, 1)
            if masked_rows_per_sec_chip is not None
            else None
        ),
        "masked_parity_ok": masked_parity_ok,
        "est_mfu": round(est_mfu, 4) if est_mfu is not None else None,
        "roofline_frac": (
            round(roofline_frac, 3) if roofline_frac is not None else None
        ),
        "xplane_trace": trace_dir,
        "platform": platform,
        "n_rows": n_rows,
        "n_cols": n_cols,
        "kmeans_inertia": float(inertia),
        "bench_budget_s": budget_s,
    }
    secondary.update(family_secondary)
    secondary.update(wide_secondary)
    line = {
        "metric": metric,
        "value": round(value, 1),
        "unit": "rows*iters/sec/chip",
        "vs_baseline": round(vs_baseline, 4),
        "secondary": secondary,
    }
    # cumulative on-disk record (evidence survives even if a later run times out)
    try:
        results_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "benchmark", "results"
        )
        os.makedirs(results_dir, exist_ok=True)
        with open(os.path.join(results_dir, f"chip_bench_{platform}.json"), "w") as f:
            json.dump(line, f, indent=1)
    except OSError:
        pass
    print(json.dumps(line))


if __name__ == "__main__":
    main()
