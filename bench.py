#!/usr/bin/env python
"""Flagship benchmark: distributed KMeans fit throughput on the local device(s).

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Protocol follows the reference harness (reference python/benchmark/benchmark/base.py:
232-285: timed fit with quality score). The metric is Lloyd-iteration row throughput —
rows * iterations / wall-clock — on a dataset sized to the available memory, which is
the quantity the north-star target tracks (BASELINE.json: rows/sec/chip).

`vs_baseline`: the reference publishes no machine-readable numbers (BASELINE.md), so
the ratio is computed against a locally-recorded baseline in BENCH_BASELINE.json when
present (first run writes it), else 1.0.
"""

import functools
import json
import os
import subprocess
import sys
import time

import numpy as np


def _probe_once(timeout_s: float) -> int:
    probe = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices()"],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        return probe.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        probe.kill()
        probe.wait()
        return -1


def _device_init_watchdog(attempts: int = 2, timeout_s: float = 90.0) -> None:
    """The axon TPU tunnel can wedge so hard that `import jax` hangs every process.
    Probe device init in a subprocess with retry+backoff (the tunnel can recover
    between probes); only after all probes fail, re-exec ourselves on the CPU
    backend so the driver still gets a benchmark line (clearly labeled)."""
    if os.environ.get("SRML_BENCH_NO_WATCHDOG") == "1":
        return
    marker = "/tmp/.srml_bench_device_ok"
    try:
        # only trust a recent healthy probe: the tunnel can wedge minutes after a
        # good run (observed), and a stale marker would skip the probe and let the
        # un-watchdogged jax import hang the whole benchmark
        if os.path.exists(marker) and time.time() - os.path.getmtime(marker) < 600:
            return
    except OSError:
        pass
    # budget note: the whole probe sequence must leave room for the CPU-fallback
    # compute inside a ~300 s driver timeout (2 x 90 s + 10 s backoff + ~60 s run)
    rc = -1
    for attempt in range(attempts):
        if attempt:
            time.sleep(10.0 * attempt)  # linear backoff
        rc = _probe_once(timeout_s)
        if rc == 0:
            break
        print(
            f"bench watchdog: device probe attempt {attempt + 1}/{attempts} "
            f"failed (rc={rc})",
            file=sys.stderr,
        )
    if rc == 0:
        try:
            open(marker, "w").close()
        except OSError:
            pass
        return
    if rc != 0:
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS=(env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8").strip(),
            PALLAS_AXON_POOL_IPS="",
            SRML_BENCH_NO_WATCHDOG="1",
        )
        os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)


def main() -> None:
    _device_init_watchdog()
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.kmeans import lloyd_fit
    from spark_rapids_ml_tpu.parallel.mesh import get_mesh, shard_array

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"

    # size to platform: HBM-filling on TPU (~6 GiB f32 design matrix per chip on a
    # 16 GiB v5e, leaving headroom for the one-hot update and compiler scratch),
    # small on CPU
    if on_tpu:
        n_rows, n_cols, k, iters = 12_000_000, 128, 20, 10
    else:
        n_rows, n_cols, k, iters = 100_000, 64, 8, 10

    # synthesize blobs ON DEVICE: host→device transfer is the enemy (and the metric
    # tracks compute, not ingest — the reference times cuML fit after cudf ingest too)
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = get_mesh()
    rowsh = NamedSharding(mesh, P("data", None))

    @functools.partial(jax.jit, out_shardings=(rowsh, None))
    def make_data(key):
        k1, k2, k3 = jax.random.split(key, 3)
        centers_true = jax.random.normal(k1, (k, n_cols), jnp.float32) * 5.0
        assign = jax.random.randint(k2, (n_rows,), 0, k)
        X = centers_true[assign] + jax.random.normal(k3, (n_rows, n_cols), jnp.float32)
        init = centers_true + 0.5 * jax.random.normal(k1, (k, n_cols), jnp.float32)
        return X, init

    Xd, init = make_data(jax.random.PRNGKey(0))
    Xd.block_until_ready()
    w = shard_array(np.ones((n_rows,), dtype=np.float32), mesh)

    def _sync(*arrays):
        """Force completion by pulling the values to host. Under the axon remote
        tunnel `block_until_ready` can acknowledge dispatch before the device has
        finished executing (observed: a 4096^3 matmul "completing" in 0.02 ms);
        a device->host transfer of the result cannot lie."""
        return [np.asarray(a) for a in arrays]

    # compile warmup (excluded from timing)
    centers, inertia, n_iter = lloyd_fit(Xd, w, init, 0.0, iters)
    _sync(centers)

    from spark_rapids_ml_tpu.profiling import trace as xplane_trace

    trace_dir = "/tmp/srml_bench_xplane" if on_tpu else None
    t0 = time.perf_counter()
    with xplane_trace(trace_dir):
        centers, inertia, n_iter = lloyd_fit(Xd, w, init, 0.0, iters)
        _sync(centers)
    fit_time = time.perf_counter() - t0

    rows_per_sec = n_rows * int(n_iter) / fit_time
    n_chips = jax.device_count()
    value = rows_per_sec / n_chips

    # estimated MFU: one Lloyd iteration is ~4*n*d*k matmul FLOPs (2ndk distance
    # cross-term + 2nkd one-hot update); peak per chip assumes v5e f32 on MXU
    flops = 4.0 * n_rows * n_cols * k * int(n_iter)
    peak_f32 = 98e12  # v5e ~197 TFLOP/s bf16 -> ~98 TFLOP/s f32-equivalent
    est_mfu = flops / fit_time / n_chips / peak_f32 if on_tpu else None

    # secondary metric: the fast-math variant (assignment distances at MXU bf16,
    # model attributes still parity precision — config key fast_math)
    fast_fit = functools.partial(lloyd_fit, fast_math=True)
    centers_f, _, n_iter_f = fast_fit(Xd, w, init, 0.0, iters)
    _sync(centers_f)
    t0 = time.perf_counter()
    centers_f, _, n_iter_f = fast_fit(Xd, w, init, 0.0, iters)
    _sync(centers_f)
    fast_time = time.perf_counter() - t0
    fast_rows_per_sec_chip = n_rows * int(n_iter_f) / fast_time / n_chips

    # secondary metric (TPU only): the fused pallas Lloyd step — X streams HBM once
    # per iteration (ops/pallas_kmeans.py); guarded so an unexpected Mosaic issue on
    # new hardware can never kill the benchmark line
    fused_rows_per_sec_chip = None
    if on_tpu:
        try:
            from spark_rapids_ml_tpu.ops.pallas_kmeans import lloyd_fit_pallas

            mesh_obj = getattr(getattr(Xd, "sharding", None), "mesh", None)
            # the fused path converges in ~2 iterations (bf16 freezes centers),
            # so whole-fit timing would amortize the per-fit constants (relay
            # dispatch + the parity-precision final-inertia pass) over almost
            # nothing. Report the MARGINAL per-iteration rate instead: time a
            # 1-iteration fit and a converged fit, divide the difference.
            c_f, _, _ = lloyd_fit_pallas(Xd, w, init, 0.0, 1, mesh=mesh_obj)
            _sync(c_f)  # warm both compile cache entries
            c_f, _, it_f = lloyd_fit_pallas(Xd, w, init, 0.0, iters, mesh=mesh_obj)
            _sync(c_f)
            t0 = time.perf_counter()
            c_f, _, _ = lloyd_fit_pallas(Xd, w, init, 0.0, 1, mesh=mesh_obj)
            _sync(c_f)
            t1 = time.perf_counter()
            c_f, _, it_f = lloyd_fit_pallas(Xd, w, init, 0.0, iters, mesh=mesh_obj)
            _sync(c_f)
            t2 = time.perf_counter()
            it_f = int(it_f)
            if it_f > 1:
                marginal = max((t2 - t1) - (t1 - t0), 1e-9) / (it_f - 1)
                fused_rows_per_sec_chip = n_rows / marginal / n_chips
        except Exception as e:  # pragma: no cover
            print(f"bench: fused pallas lloyd unavailable: {e}", file=sys.stderr)

    # secondary metric: PCA covariance-fit throughput on the same matrix (the second
    # north-star algorithm; one warm + one timed pass, reported in the same line)
    from spark_rapids_ml_tpu.ops.linalg import weighted_covariance

    cov_jit = jax.jit(weighted_covariance)
    cov, mean, wsum = cov_jit(Xd, w)
    _sync(cov)
    t0 = time.perf_counter()
    cov, mean, wsum = cov_jit(Xd, w)
    _sync(cov)
    pca_time = time.perf_counter() - t0
    pca_rows_per_sec_chip = n_rows / pca_time / n_chips

    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_BASELINE.json")
    vs_baseline = 1.0
    try:
        if os.path.exists(baseline_path):
            with open(baseline_path) as f:
                base = json.load(f)
            if base.get("platform") == platform and base.get("value", 0) > 0:
                vs_baseline = value / base["value"]
        elif on_tpu:
            # only a real-TPU run may seed the local baseline; a transient
            # CPU-fallback run must not poison it
            with open(baseline_path, "w") as f:
                json.dump({"platform": platform, "value": value, "unit": "rows*iters/sec/chip"}, f)
    except OSError:
        pass

    # a non-TPU run (watchdog fallback) is labeled in the metric name itself so the
    # recorded number can never masquerade as a TPU result
    metric = "kmeans_lloyd_rows_per_sec_per_chip"
    if not on_tpu:
        metric += f"_{platform}_fallback"
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value, 1),
                "unit": "rows*iters/sec/chip",
                "vs_baseline": round(vs_baseline, 4),
                "secondary": {
                    "kmeans_fast_math_rows_per_sec_per_chip": round(
                        fast_rows_per_sec_chip, 1
                    ),
                    "pca_cov_rows_per_sec_per_chip": round(pca_rows_per_sec_chip, 1),
                    "kmeans_fused_pallas_rows_per_sec_per_chip": (
                        round(fused_rows_per_sec_chip, 1)
                        if fused_rows_per_sec_chip is not None
                        else None
                    ),
                    "est_mfu": round(est_mfu, 4) if est_mfu is not None else None,
                    "xplane_trace": trace_dir,
                    "platform": platform,
                    "n_rows": n_rows,
                    "n_cols": n_cols,
                    "kmeans_inertia": float(inertia),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
