#!/usr/bin/env python
"""Flagship benchmark: distributed KMeans fit throughput on the local device(s).

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Protocol follows the reference harness (reference python/benchmark/benchmark/base.py:
232-285: timed fit with quality score). The metric is Lloyd-iteration row throughput —
rows * iterations / wall-clock — on a dataset sized to the available memory, which is
the quantity the north-star target tracks (BASELINE.json: rows/sec/chip).

`vs_baseline`: the reference publishes no machine-readable numbers (BASELINE.md), so
the ratio is computed against a locally-recorded baseline in BENCH_BASELINE.json when
present (first run writes it), else 1.0.
"""

import functools
import json
import os
import subprocess
import sys
import time

import numpy as np


def _probe_once(timeout_s: float) -> int:
    probe = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices()"],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        return probe.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        probe.kill()
        probe.wait()
        return -1


def _device_init_watchdog(attempts: int = 2, timeout_s: float = 90.0) -> None:
    """The axon TPU tunnel can wedge so hard that `import jax` hangs every process.
    Probe device init in a subprocess with retry+backoff (the tunnel can recover
    between probes); only after all probes fail, re-exec ourselves on the CPU
    backend so the driver still gets a benchmark line (clearly labeled)."""
    if os.environ.get("SRML_BENCH_NO_WATCHDOG") == "1":
        return
    marker = "/tmp/.srml_bench_device_ok"
    try:
        # only trust a recent healthy probe: the tunnel can wedge minutes after a
        # good run (observed), and a stale marker would skip the probe and let the
        # un-watchdogged jax import hang the whole benchmark
        if os.path.exists(marker) and time.time() - os.path.getmtime(marker) < 600:
            return
    except OSError:
        pass
    # budget note: the whole probe sequence must leave room for the CPU-fallback
    # compute inside a ~300 s driver timeout (2 x 90 s + 10 s backoff + ~60 s run)
    rc = -1
    for attempt in range(attempts):
        if attempt:
            time.sleep(10.0 * attempt)  # linear backoff
        rc = _probe_once(timeout_s)
        if rc == 0:
            break
        print(
            f"bench watchdog: device probe attempt {attempt + 1}/{attempts} "
            f"failed (rc={rc})",
            file=sys.stderr,
        )
    if rc == 0:
        try:
            open(marker, "w").close()
        except OSError:
            pass
        return
    if rc != 0:
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS=(env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8").strip(),
            PALLAS_AXON_POOL_IPS="",
            SRML_BENCH_NO_WATCHDOG="1",
        )
        os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)


def main() -> None:
    _device_init_watchdog()
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.kmeans import lloyd_fit
    from spark_rapids_ml_tpu.parallel.mesh import get_mesh, shard_array

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"

    # size to platform: HBM-filling on TPU (~6 GiB f32 design matrix per chip on a
    # 16 GiB v5e, leaving headroom for the one-hot update and compiler scratch),
    # small on CPU
    if on_tpu:
        n_rows, n_cols, k, iters = 12_000_000, 128, 20, 10
    else:
        n_rows, n_cols, k, iters = 100_000, 64, 8, 10

    # synthesize blobs ON DEVICE: host→device transfer is the enemy (and the metric
    # tracks compute, not ingest — the reference times cuML fit after cudf ingest too).
    # The init is k REAL ROWS of X (what k-means|| reduces to), NOT the true centers:
    # a near-optimal init converges in ~2 Lloyd iterations and the whole-fit metric
    # then measures per-fit constants instead of iteration throughput (this exact
    # distortion made the round-2 headline read 101M when the steady-state rate of
    # the same code was ~640M rows*iters/s).
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = get_mesh()
    rowsh = NamedSharding(mesh, P("data", None))

    @functools.partial(jax.jit, out_shardings=(rowsh, None))
    def make_data(key):
        k1, k2, k3 = jax.random.split(key, 3)
        centers_true = jax.random.normal(k1, (k, n_cols), jnp.float32) * 5.0
        assign = jax.random.randint(k2, (n_rows,), 0, k)
        X = centers_true[assign] + jax.random.normal(k3, (n_rows, n_cols), jnp.float32)
        init = X[:k] * 1.0
        return X, init

    Xd, init = make_data(jax.random.PRNGKey(0))
    Xd.block_until_ready()
    w = shard_array(np.ones((n_rows,), dtype=np.float32), mesh)

    def _sync(*arrays):
        """Force completion by pulling the values to host. Under the axon remote
        tunnel `block_until_ready` can acknowledge dispatch before the device has
        finished executing (observed: a 4096^3 matmul "completing" in 0.02 ms);
        a device->host transfer of the result cannot lie."""
        return [np.asarray(a) for a in arrays]

    def _timed(fn, repeats=3):
        """Median wall-clock of fn() (synced); fn returns arrays to sync on."""
        ts = []
        out = None
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = fn()
            _sync(out[0])
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts)), out

    # compile warmup for both cache entries (1-iter and full fit), excluded from
    # timing; the 1-iter fit anchors the marginal (per-iteration) rate below
    _sync(lloyd_fit(Xd, w, init, 0.0, 1)[0])
    centers, inertia, n_iter = lloyd_fit(Xd, w, init, 0.0, iters)
    _sync(centers)

    fit_time, (centers, inertia, n_iter) = _timed(
        lambda: lloyd_fit(Xd, w, init, 0.0, iters)
    )
    t1_time, _ = _timed(lambda: lloyd_fit(Xd, w, init, 0.0, 1))
    n_iter = int(n_iter)

    n_chips = jax.device_count()
    # headline: whole-fit throughput (reference protocol base.py:232-285 times the
    # whole fit); the marginal rate (fit constants cancelled) is a secondary
    value = n_rows * n_iter / fit_time / n_chips
    if n_iter > 1:
        marginal_t = max(fit_time - t1_time, 1e-9) / (n_iter - 1)
        marginal_rate_chip = n_rows / marginal_t / n_chips
    else:
        # fit_time - t1_time is pure timing noise at n_iter=1; no marginal rate
        print(
            "bench: fit converged in <=1 iteration; marginal rate undefined",
            file=sys.stderr,
        )
        marginal_t = None
        marginal_rate_chip = None

    # estimated MFU: one Lloyd iteration is ~4*n*d*k matmul FLOPs (2ndk distance
    # cross-term + 2nkd one-hot update); peak per chip assumes v5e f32 on MXU
    flops = 4.0 * n_rows * n_cols * k * n_iter
    peak_f32 = 98e12  # v5e ~197 TFLOP/s bf16 -> ~98 TFLOP/s f32-equivalent
    est_mfu = flops / fit_time / n_chips / peak_f32 if on_tpu else None
    # HBM roofline fraction of the STEADY-STATE iteration: the XLA Lloyd step
    # reads X twice (distance matmul + one-hot update) plus the (n,k)
    # distance/one-hot intermediates once each; at small k the X reads dominate
    # per-chip: each chip streams its row shard, and peak_bw is per-chip HBM
    bytes_per_iter = 2 * n_rows * n_cols * 4 + 2 * n_rows * k * 4
    peak_bw = 819e9  # v5e HBM ~819 GB/s
    roofline_frac = (
        (bytes_per_iter / peak_bw) / marginal_t / n_chips
        if on_tpu and marginal_t is not None
        else None
    )

    # profiler trace AFTER the timed region (trace capture inflates the timed run)
    from spark_rapids_ml_tpu.profiling import trace as xplane_trace

    trace_dir = "/tmp/srml_bench_xplane" if on_tpu else None
    if trace_dir:
        with xplane_trace(trace_dir):
            _sync(lloyd_fit(Xd, w, init, 0.0, iters)[0])

    # secondary metric: the fast-math variant (assignment distances at MXU bf16,
    # model attributes still parity precision — config key fast_math)
    fast_fit = functools.partial(lloyd_fit, fast_math=True)
    _sync(fast_fit(Xd, w, init, 0.0, iters)[0])
    fast_time, (_, _, n_iter_f) = _timed(lambda: fast_fit(Xd, w, init, 0.0, iters))
    fast_rows_per_sec_chip = n_rows * int(n_iter_f) / fast_time / n_chips

    # secondary metric (TPU only): the fused pallas Lloyd at 6-pass parity
    # precision — measured slower than the XLA path at this small-k shape (see
    # ops/pallas_kmeans.py header), reported to keep tracking it, plus a live
    # parity check (same n_iter, inertia within fp32 tolerance) guarding the
    # SRML_TPU_PALLAS_KMEANS opt-in. Guarded so an unexpected Mosaic issue on new
    # hardware can never kill the benchmark line.
    fused_rows_per_sec_chip = None
    fused_parity_ok = None
    if on_tpu:
        try:
            from spark_rapids_ml_tpu.ops.pallas_kmeans import lloyd_fit_pallas

            mesh_obj = getattr(getattr(Xd, "sharding", None), "mesh", None)
            fused = functools.partial(
                lloyd_fit_pallas, mesh=mesh_obj, precision=jax.lax.Precision.HIGHEST
            )
            c_f, in_f, it_f = fused(Xd, w, init, 0.0, iters)
            _sync(c_f)
            fused_time, (c_f, in_f, it_f) = _timed(
                lambda: fused(Xd, w, init, 0.0, iters)
            )
            it_f = int(it_f)
            if it_f <= 1:
                print(
                    "bench: fused fit converged in <=1 iteration; "
                    "whole-fit rate reflects per-fit constants only",
                    file=sys.stderr,
                )
            fused_rows_per_sec_chip = n_rows * it_f / fused_time / n_chips
            fused_parity_ok = bool(
                it_f == n_iter
                and abs(float(in_f) - float(inertia)) <= 1e-4 * abs(float(inertia))
            )
        except Exception as e:  # pragma: no cover
            print(f"bench: fused pallas lloyd unavailable: {e}", file=sys.stderr)

    # secondary metric: PCA covariance-fit throughput on the same matrix (the second
    # north-star algorithm; one warm + one timed pass, reported in the same line)
    from spark_rapids_ml_tpu.ops.linalg import weighted_covariance

    cov_jit = jax.jit(weighted_covariance)
    cov, mean, wsum = cov_jit(Xd, w)
    _sync(cov)
    pca_time, _ = _timed(lambda: cov_jit(Xd, w))
    pca_rows_per_sec_chip = n_rows / pca_time / n_chips

    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_BASELINE.json")
    vs_baseline = 1.0
    try:
        # protocol 2 = whole-fit timing with a k-real-rows far init (n_iter ≈
        # max_iter); protocol-less baselines were recorded under the old
        # near-optimal init whose n_iter=2 made the same code read ~6x slower —
        # comparing across protocols would report a spurious "speedup", so a
        # mismatched baseline is reseeded instead of compared against
        protocol = 2
        base = None
        if os.path.exists(baseline_path):
            with open(baseline_path) as f:
                base = json.load(f)
            if base.get("protocol") != protocol:
                print(
                    f"bench: baseline protocol {base.get('protocol')} != {protocol}; "
                    "reseeding baseline, vs_baseline reset to 1.0",
                    file=sys.stderr,
                )
                base = None
        if base is not None:
            if base.get("platform") == platform and base.get("value", 0) > 0:
                vs_baseline = value / base["value"]
        elif on_tpu:
            # only a real-TPU run may seed the local baseline; a transient
            # CPU-fallback run must not poison it
            with open(baseline_path, "w") as f:
                json.dump(
                    {
                        "platform": platform,
                        "value": value,
                        "unit": "rows*iters/sec/chip",
                        "protocol": protocol,
                    },
                    f,
                )
    except OSError:
        pass

    # a non-TPU run (watchdog fallback) is labeled in the metric name itself so the
    # recorded number can never masquerade as a TPU result
    metric = "kmeans_lloyd_rows_per_sec_per_chip"
    if not on_tpu:
        metric += f"_{platform}_fallback"
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value, 1),
                "unit": "rows*iters/sec/chip",
                "vs_baseline": round(vs_baseline, 4),
                "secondary": {
                    "kmeans_marginal_rows_per_sec_per_chip": (
                        round(marginal_rate_chip, 1)
                        if marginal_rate_chip is not None
                        else None
                    ),
                    "kmeans_n_iter": n_iter,
                    "kmeans_fast_math_rows_per_sec_per_chip": round(
                        fast_rows_per_sec_chip, 1
                    ),
                    "pca_cov_rows_per_sec_per_chip": round(pca_rows_per_sec_chip, 1),
                    "kmeans_fused_pallas_rows_per_sec_per_chip": (
                        round(fused_rows_per_sec_chip, 1)
                        if fused_rows_per_sec_chip is not None
                        else None
                    ),
                    "fused_parity_ok": fused_parity_ok,
                    "est_mfu": round(est_mfu, 4) if est_mfu is not None else None,
                    "roofline_frac": (
                        round(roofline_frac, 3) if roofline_frac is not None else None
                    ),
                    "xplane_trace": trace_dir,
                    "platform": platform,
                    "n_rows": n_rows,
                    "n_cols": n_cols,
                    "kmeans_inertia": float(inertia),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
