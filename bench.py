#!/usr/bin/env python
"""Flagship benchmark: distributed KMeans fit throughput + per-family secondaries.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Protocol follows the reference harness (reference python/benchmark/benchmark/base.py:
232-285: timed fit with quality score). The headline metric is Lloyd-iteration row
throughput — rows * iterations / wall-clock — which the north-star target tracks
(BASELINE.json: rows/sec/chip); per-family numbers land in `secondary`.

Wedge-proof architecture (round-5): the axon TPU tunnel can wedge so hard that any
jax-importing process hangs forever. All device work therefore runs in a WORKER
subprocess that appends each benchmark unit's result to a progress JSONL file the
moment it completes. The ORCHESTRATOR (this process, never imports jax) probes the
device, spawns the worker, watches for stalls, kills a wedged worker, re-probes and
respawns it with the completed+wedged units skipped, and finally assembles the line
from whatever landed in the progress file:

  * any TPU unit completed  -> platform "tpu" (+ `partial: true` if units are
    missing) — a mid-run wedge can no longer erase captured TPU evidence;
  * zero TPU evidence       -> CPU-fallback worker, metric explicitly suffixed
    `_cpu_fallback` (a CPU number must never masquerade as a TPU result).

`vs_baseline`: the reference publishes no machine-readable numbers (BASELINE.md), so
the ratio is computed against a locally-recorded baseline in BENCH_BASELINE.json when
present (first TPU run writes it), else 1.0.
"""

import functools
import json
import os
import subprocess
import sys
import time

import numpy as np

# Benchmark units, in priority order: cheap/high-value families land before the
# O(n*nq) kNN/ANN scans so a deadline or wedge preserves the most evidence.
# "kmeans_headline" carries the headline metric; the rest merge into `secondary`.
UNITS = [
    "kmeans_headline",
    "pca",
    "logreg",
    "linreg",
    "rf",
    "umap",
    "dbscan",
    "fit_e2e",
    "cache",
    "ingest",
    "telemetry_overhead",
    "serving_qps",
    "serving_failover",
    "tracing_overhead",
    "continual",
    "large_k",
    "autotune",
    "knn",
    "ann",
    "ann_build",
    "wide256",
]

ASSEMBLY_MARGIN_S = 12.0  # orchestrator time reserved to assemble + print
UNIT_START_MARGIN_S = 30.0  # don't start a unit with less than this left


def _stall_window_s() -> float:
    """No progress-file activity for this long => worker is wedged. Scaled to the
    budget so the detector can actually fire inside a default (240 s) run — a
    fixed 330 s window would make the deadline kill always win and report every
    wedge as budget exhaustion — but floored high enough that one legitimately
    long unit (cold-cache compile + fit) isn't mistaken for a wedge."""
    budget = float(os.environ.get("SRML_BENCH_BUDGET_S", "240"))
    return min(330.0, max(90.0, 0.6 * budget))


# --------------------------------------------------------------------- progress IO


def _flush_progress(path: str, entry: dict) -> None:
    """Append one JSON line and fsync so the orchestrator sees it immediately
    even if this process hangs or dies right after."""
    entry = dict(entry, ts=round(time.time(), 2))
    with open(path, "a") as f:
        f.write(json.dumps(entry) + "\n")
        f.flush()
        os.fsync(f.fileno())


def _read_progress(path: str) -> dict:
    """Latest entry per unit (later lines win)."""
    state: dict = {}
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn write from a killed worker
                state[e.get("unit", "?")] = e
    except OSError:
        pass
    return state


# ------------------------------------------------------------------------- worker


def _worker_main() -> None:
    """Device-touching half: build data, run each unit, flush results incrementally.
    Runs under the orchestrator with SRML_BENCH_ROLE=worker; may be killed at any
    moment — every completed unit must already be on disk."""
    progress = os.environ["SRML_BENCH_PROGRESS"]
    skip = set(filter(None, os.environ.get("SRML_BENCH_SKIP", "").split(",")))
    deadline_ts = float(os.environ["SRML_BENCH_DEADLINE_TS"])

    _flush_progress(progress, {"unit": "boot", "status": "start"})

    import jax
    import jax.numpy as jnp

    try:
        # persistent compile cache: family benches compile ~10 programs; repeat
        # runs (and the driver's run after this session's) skip all of it
        jax.config.update("jax_compilation_cache_dir", "/tmp/srml_jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: fence/silent-except (best-effort probe)
        pass

    from jax.sharding import NamedSharding, PartitionSpec as P

    from spark_rapids_ml_tpu.ops.kmeans import lloyd_fit
    from spark_rapids_ml_tpu.parallel.mesh import get_mesh, shard_array

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    n_chips = jax.device_count()

    # size to platform: HBM-filling on TPU (~6 GiB f32 design matrix per chip on a
    # 16 GiB v5e, leaving headroom for the one-hot update and compiler scratch),
    # small on CPU
    if on_tpu:
        n_rows, n_cols, k, iters = 12_000_000, 128, 20, 10
    else:
        n_rows, n_cols, k, iters = 100_000, 64, 8, 10

    # synthesize blobs ON DEVICE: host→device transfer is the enemy (and the metric
    # tracks compute, not ingest — the reference times cuML fit after cudf ingest
    # too). The init is k REAL ROWS of X (what k-means|| reduces to), NOT the true
    # centers: a near-optimal init converges in ~2 Lloyd iterations and the
    # whole-fit metric then measures per-fit constants instead of iteration
    # throughput (this exact distortion made the round-2 headline read 101M when
    # the steady-state rate of the same code was ~640M rows*iters/s).
    mesh = get_mesh()
    rowsh = NamedSharding(mesh, P("data", None))

    # only units in this set read the shared headline design matrix; a respawn
    # whose remaining units all build their own data (rf/umap/dbscan/fit_e2e/
    # wide256) skips the ~6 GiB generation entirely — that time comes straight
    # out of the wedge-recovery budget
    NEED_X = {"kmeans_headline", "pca", "logreg", "linreg", "large_k", "knn",
              "ann", "ann_build"}
    remaining = [
        u for u in UNITS
        if u not in skip and time.time() < deadline_ts - UNIT_START_MARGIN_S
    ]
    need_data = bool(NEED_X & set(remaining))

    @functools.partial(jax.jit, out_shardings=(rowsh, None))
    def make_data(key):
        k1, k2, k3 = jax.random.split(key, 3)
        centers_true = jax.random.normal(k1, (k, n_cols), jnp.float32) * 5.0
        assign = jax.random.randint(k2, (n_rows,), 0, k)
        X = centers_true[assign] + jax.random.normal(k3, (n_rows, n_cols), jnp.float32)
        init = X[:k] * 1.0
        return X, init

    if need_data:
        Xd, init = make_data(jax.random.PRNGKey(0))
        Xd.block_until_ready()
        w = shard_array(np.ones((n_rows,), dtype=np.float32), mesh)
    else:
        Xd = init = w = None

    _flush_progress(
        progress,
        {
            "unit": "boot",
            "status": "done",
            "platform": platform,
            "n_chips": n_chips,
            "result": {"n_rows": n_rows, "n_cols": n_cols},
        },
    )

    def _hb(tag: str) -> None:
        """Heartbeat between a unit's sub-measurements: refreshes the progress
        file's mtime so a HEALTHY-but-slow unit (cold compiles, several timed
        variants in one unit) isn't stall-killed as a tunnel wedge. '_hb' is not
        a UNITS name, so assembly ignores the entries. Only called from points
        the device just returned from (after a sync) — a genuinely wedged
        dispatch reaches no heartbeat, so stall detection still fires."""
        _flush_progress(progress, {"unit": "_hb", "status": "hb", "at": tag})

    def _sync(*arrays):
        """Force completion by pulling the values to host. Under the axon remote
        tunnel `block_until_ready` can acknowledge dispatch before the device has
        finished executing (observed: a 4096^3 matmul "completing" in 0.02 ms);
        a device->host transfer of the result cannot lie."""
        return [np.asarray(a) for a in arrays]

    def _timed(fn, repeats=3):
        """Median wall-clock of fn() (synced); fn returns arrays to sync on."""
        ts = []
        out = None
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = fn()
            _sync(out[0])
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts)), out

    peak_bw = 819e9  # v5e HBM ~819 GB/s per chip

    def _kmeans_rates(X_, w_, init_, n_, d_):
        """THE Lloyd timing recipe (protocol 2): whole-fit throughput (reference
        protocol base.py:232-285 times the whole fit) plus the steady-state
        marginal rate (full fit minus a 1-iter fit cancels per-fit constants)
        and the two-X-read HBM roofline fraction — one helper so the headline
        and the 256-col tier can never drift apart. The Lloyd step reads X twice
        per iteration (distance matmul + one-hot update) plus the (n, k)
        intermediates once each; peak_bw is per-chip HBM."""
        _sync(lloyd_fit(X_, w_, init_, 0.0, 1)[0])  # compile warmups, untimed
        _sync(lloyd_fit(X_, w_, init_, 0.0, iters)[0])
        t_full, (centers_, inertia_, it_) = _timed(
            lambda: lloyd_fit(X_, w_, init_, 0.0, iters)
        )
        t_one, _ = _timed(lambda: lloyd_fit(X_, w_, init_, 0.0, 1))
        it_ = int(it_)
        whole = n_ * it_ / t_full / n_chips
        if it_ > 1:
            marg_t = max(t_full - t_one, 1e-9) / (it_ - 1)
            marginal = n_ / marg_t / n_chips
        else:
            # t_full - t_one is pure timing noise at n_iter=1; no marginal rate
            print(
                "bench: fit converged in <=1 iteration; marginal rate undefined",
                file=sys.stderr,
            )
            marg_t, marginal = None, None
        bytes_per_iter = 2 * n_ * d_ * 4 + 2 * n_ * k * 4
        roof = (
            (bytes_per_iter / peak_bw) / marg_t / n_chips
            if on_tpu and marg_t is not None
            else None
        )
        iter_ceiling = peak_bw / (2 * d_ * 4 + 2 * k * 4)
        return {
            "t_full": t_full,
            "centers": centers_,
            "inertia": inertia_,
            "n_iter": it_,
            "whole": whole,
            "marginal": marginal,
            "roofline_frac": roof,
            "whole_frac": whole / iter_ceiling if on_tpu else None,
        }

    def unit_kmeans_headline():
        hr = _kmeans_rates(Xd, w, init, n_rows, n_cols)
        fit_time, inertia, n_iter = hr["t_full"], hr["inertia"], hr["n_iter"]
        value = hr["whole"]
        _hb("kmeans_rates")

        # MEASURED MFU: analyzed flops of the lloyd executable from the device
        # plane's XLA cost_analysis capture (observability/device.py) over the
        # timed whole-fit window — replaces the round-3 hand-rolled analytic
        # estimate. The analysis runs on the post-partitioning per-device
        # module, so flops are already per-chip (no n_chips division), and
        # XLA counts a dynamic-trip while_loop body once, so this is a stable
        # lower bound; the bench gate tracks its direction.
        from spark_rapids_ml_tpu.observability.device import (
            kernel_cost, platform_peaks,
        )

        lloyd_rec = kernel_cost("kmeans.lloyd_fit")
        peak_flops = platform_peaks()[0]
        mfu = (
            lloyd_rec["flops"] / fit_time / peak_flops
            if lloyd_rec and lloyd_rec.get("flops") and peak_flops > 0
            else None
        )

        # profiler trace AFTER the timed region (trace capture inflates the run)
        from spark_rapids_ml_tpu.profiling import trace as xplane_trace

        trace_dir = "/tmp/srml_bench_xplane" if on_tpu else None
        if trace_dir:
            with xplane_trace(trace_dir):
                _sync(lloyd_fit(Xd, w, init, 0.0, iters)[0])
        _hb("xplane_trace")

        # secondary metric: the fast-math variant (assignment distances at MXU
        # bf16, model attributes still parity precision — config key fast_math)
        fast_fit = functools.partial(lloyd_fit, fast_math=True)
        _sync(fast_fit(Xd, w, init, 0.0, iters)[0])
        fast_time, (_, _, n_iter_f) = _timed(lambda: fast_fit(Xd, w, init, 0.0, iters))
        fast_rate = n_rows * int(n_iter_f) / fast_time / n_chips
        _hb("fast_math")

        # TPU-only: the fused pallas Lloyd variants at 6-pass parity precision —
        # weighted (measured slower than XLA at this small-k shape, see
        # ops/pallas_kmeans.py header) and masked/no-weight-stream (the (blk,1)-
        # operand elimination that took the Gram kernel 3x; candidate to displace
        # the XLA headline path). Each carries a live parity check (same n_iter,
        # inertia within fp32 tolerance) and is exception-guarded so a Mosaic
        # issue on new hardware can never kill the benchmark line.
        def _pallas_variant(label, **variant_kw):
            try:
                import jax as _jax

                from spark_rapids_ml_tpu.ops.pallas_kmeans import lloyd_fit_pallas

                mesh_obj = getattr(getattr(Xd, "sharding", None), "mesh", None)
                fit = functools.partial(
                    lloyd_fit_pallas, mesh=mesh_obj,
                    precision=_jax.lax.Precision.HIGHEST, **variant_kw,
                )
                _sync(fit(Xd, w, init, 0.0, iters)[0])  # compile warmup
                t, (c_v, in_v, it_v) = _timed(lambda: fit(Xd, w, init, 0.0, iters))
                it_v = int(it_v)
                if it_v <= 1:
                    print(
                        f"bench: {label} fit converged in <=1 iteration; "
                        "whole-fit rate reflects per-fit constants only",
                        file=sys.stderr,
                    )
                rate = n_rows * it_v / t / n_chips
                parity = bool(
                    it_v == n_iter
                    and abs(float(in_v) - float(inertia))
                    <= 1e-4 * abs(float(inertia))
                )
                return rate, parity
            except Exception as e:  # pragma: no cover
                print(f"bench: {label} pallas lloyd unavailable: {e}", file=sys.stderr)
                return None, None

        fused_rate = fused_parity = masked_rate = masked_parity = None
        if on_tpu:
            fused_rate, fused_parity = _pallas_variant("fused")
            _hb("pallas_fused")
            masked_rate, masked_parity = _pallas_variant("masked", unit_mask=True)
            _hb("pallas_masked")

        return {
            "_value": round(value, 1),
            "kmeans_marginal_rows_per_sec_per_chip": (
                round(hr["marginal"], 1) if hr["marginal"] is not None else None
            ),
            "kmeans_n_iter": n_iter,
            "kmeans_frac_of_ceiling": (
                round(hr["whole_frac"], 3) if hr["whole_frac"] is not None else None
            ),
            "kmeans_fast_math_rows_per_sec_per_chip": round(fast_rate, 1),
            "kmeans_fused_pallas_rows_per_sec_per_chip": (
                round(fused_rate, 1) if fused_rate is not None else None
            ),
            "fused_parity_ok": fused_parity,
            "kmeans_masked_pallas_rows_per_sec_per_chip": (
                round(masked_rate, 1) if masked_rate is not None else None
            ),
            "masked_parity_ok": masked_parity,
            "mfu": round(mfu, 6) if mfu is not None else None,
            "roofline_frac": (
                round(hr["roofline_frac"], 3)
                if hr["roofline_frac"] is not None
                else None
            ),
            # the north-star anchor: measured per-chip rate vs the A100 cuML
            # roofline estimate (same operational-intensity model; >=0.667
            # clears BASELINE's "within 1.5x of A100" bar — benchmark/a100_model.py).
            # Numerator is the MARGINAL (steady-state) rate, like the x256 tier:
            # the A100 roofline excludes per-fit constants, so dividing the
            # whole-fit rate by it would deflate the ratio by compile/init time.
            **_a100.anchor_fields(
                "kmeans",
                hr["marginal"] if on_tpu else None,
                _a100.kmeans_rows_iters_per_sec(n_cols, k),
                bound="hbm",
            ),
            "xplane_trace": trace_dir,
            "kmeans_inertia": float(inertia),
        }

    repo_root = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, repo_root)
    from benchmark import a100_model as _a100
    from benchmark.chip_bench import FAMILIES, make_ctx

    ctx = make_ctx(Xd, w, mesh, on_tpu, platform, repo_root=repo_root)
    ctx["heartbeat"] = _hb  # long multi-phase families beat between phases
    family_fns = dict(FAMILIES)

    def unit_wide256():
        """256-col variants of the two north-star algorithms (BASELINE targets
        are x256): drop the 128-col matrix first — 6 GiB each, both won't fit."""
        nonlocal ctx, Xd, w
        out = {}
        # drop every live reference (ctx holds one) so HBM is actually freed
        ctx = dict(ctx, X=None, w=None)
        Xd = w = None
        n256, d256 = (6_000_000, 256) if on_tpu else (50_000, 64)
        rowsh256 = NamedSharding(mesh, P("data", None))

        @functools.partial(jax.jit, out_shardings=(rowsh256, None))
        def make_wide(key):
            k1, k2, k3 = jax.random.split(key, 3)
            c = jax.random.normal(k1, (k, d256), jnp.float32) * 5.0
            a = jax.random.randint(k2, (n256,), 0, k)
            Xw_ = c[a] + jax.random.normal(k3, (n256, d256), jnp.float32)
            return Xw_, Xw_[:k] * 1.0

        X256, init256 = make_wide(jax.random.PRNGKey(1))
        _sync(X256[:1])
        w256 = shard_array(np.ones((n256,), np.float32), mesh)
        wr = _kmeans_rates(X256, w256, init256, n256, d256)
        _hb("wide256_kmeans")
        # key names carry the REAL width: the CPU-fallback tier runs 64 cols
        # and must not masquerade as the 256-col north-star shape
        tag = f"kmeans_{d256}col"
        if wr["marginal"] is not None:
            out[f"{tag}_marginal_rows_per_sec_per_chip"] = round(wr["marginal"], 1)
            out[f"{tag}_frac_of_ceiling"] = (
                round(wr["roofline_frac"], 3)
                if wr["roofline_frac"] is not None
                else None
            )
            if on_tpu:
                # the x256 shapes ARE the BASELINE north-star shapes: anchor
                # them too, not just the 128-col headline
                out.update(
                    _a100.anchor_fields(
                        tag, wr["marginal"],
                        _a100.kmeans_rows_iters_per_sec(d256, k), bound="hbm",
                    )
                )
        ctx256 = dict(ctx)
        ctx256.update(X=X256, w=w256)
        from benchmark.chip_bench import bench_pca

        p256 = bench_pca(ctx256)
        out[f"pca_{d256}col_rows_per_sec_per_chip"] = p256.get(
            "pca_cov_rows_per_sec_per_chip"
        )
        out[f"pca_{d256}col_roofline_frac"] = p256.get("pca_roofline_frac")
        for anchor_key in ("pca_vs_a100_est", "pca_vs_a100_est_v5p"):
            if p256.get(anchor_key) is not None:
                out[anchor_key.replace("pca_", f"pca_{d256}col_")] = p256[anchor_key]
        return out

    def run_unit(name):
        if name == "kmeans_headline":
            return unit_kmeans_headline()
        if name == "wide256":
            return unit_wide256()
        return family_fns[name](ctx)

    def _transform_latency(report):
        """p50/p95/p99 transform latency per histogram from a unit's run report
        (observability/inference.py populates transform.batch_s/predict_s;
        quantiles interpolate within the exponential buckets)."""
        from spark_rapids_ml_tpu.observability.registry import (
            interpolate_quantile, split_label_key,
        )

        out = {}
        for key, st in (report["metrics"].get("histograms") or {}).items():
            hname, labels = split_label_key(key)
            if hname not in ("transform.batch_s", "transform.predict_s"):
                continue
            bounds = st.get("bounds") or []
            tag = hname.split(".")[-1]
            if labels.get("model"):
                tag += f"_{labels['model']}"
            out[tag] = {
                "count": st["count"],
                "p50": round(interpolate_quantile(st, 0.50, bounds), 6),
                "p95": round(interpolate_quantile(st, 0.95, bounds), 6),
                "p99": round(interpolate_quantile(st, 0.99, bounds), 6),
            }
        return out

    for name in UNITS:
        if name in skip:
            continue
        if time.time() > deadline_ts - UNIT_START_MARGIN_S:
            _flush_progress(progress, {"unit": name, "status": "deadline_skip"})
            continue
        _flush_progress(progress, {"unit": name, "status": "start"})
        t0 = time.time()
        try:
            # one observability run per scenario: the BENCH json gains
            # per-stage span attribution (`<unit>_stage_s`) and, with
            # SRML_TPU_METRICS_DIR set, each unit appends a full structured
            # run report to fit_reports.jsonl (observability/export.py)
            from spark_rapids_ml_tpu.observability import fit_run

            with fit_run(algo=name, site="bench") as obs_run:
                result = run_unit(name)
            if obs_run is not None:
                obs_report = obs_run.report()
                stage_s = sorted(
                    obs_report["metrics"]["spans"].items(),
                    key=lambda kv: -kv[1],
                )[:8]
                if stage_s:
                    result[f"{name}_stage_s"] = {
                        k: round(v, 4) for k, v in stage_s
                    }
                tlat = _transform_latency(obs_report)
                if tlat:
                    result[f"{name}_transform_latency_s"] = tlat
                # device-performance plane: measured MFU + roofline
                # classification for EVERY scenario from the run's XLA
                # cost-analysis counters (observability/device.py;
                # ci/bench_check.py gates *_mfu direction-aware)
                from spark_rapids_ml_tpu.observability.device import (
                    scenario_summary,
                )

                dev = scenario_summary(obs_report, wall_s=time.time() - t0)
                result[f"{name}_mfu"] = dev["mfu"]
                result[f"{name}_roofline_bound"] = dev["roofline_bound"]
                result[f"{name}_device_flops"] = dev["device_flops"]
                result[f"{name}_device_compiles"] = dev["device_compiles"]
                # communication plane (observability/comm.py, design §6h):
                # analyzed collective bytes over the scenario wall against
                # the ICI peak, plus the worst rank-skew gauge when the
                # scenario exercised the rank-snapshot plane — both gated
                # advisory by ci/bench_check.py (lower is better)
                from spark_rapids_ml_tpu.observability.comm import (
                    scenario_comm_summary,
                )

                cs = scenario_comm_summary(
                    obs_report, wall_s=time.time() - t0
                )
                if cs["comm_frac"] is not None:
                    result[f"{name}_comm_frac"] = cs["comm_frac"]
                    result[f"{name}_comm_bytes"] = cs["comm_bytes"]
                if cs["rank_skew"] is not None:
                    result[f"{name}_rank_skew"] = cs["rank_skew"]
            result[f"{name}_bench_secs"] = round(time.time() - t0, 1)
            _flush_progress(
                progress,
                {
                    "unit": name,
                    "status": "done",
                    "platform": platform,
                    "result": result,
                },
            )
        except Exception as e:  # never kill the remaining units
            _flush_progress(
                progress,
                {
                    "unit": name,
                    "status": "error",
                    "platform": platform,
                    "error": f"{type(e).__name__}: {str(e)[:200]}",
                },
            )


# ------------------------------------------------------------------- orchestrator


def _probe_once(timeout_s: float) -> int:
    probe = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices()"],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        return probe.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        probe.kill()
        probe.wait()
        return -1


MARKER_PATH = "/tmp/.srml_bench_device_ok"


def _probe_device(deadline_ts: float, attempts: int = 2, timeout_s: float = 75.0) -> bool:
    """The axon TPU tunnel can wedge so hard that `import jax` hangs every
    process. Probe device init in a subprocess with retry+backoff (the tunnel can
    recover between probes). Each probe is capped at a quarter of the remaining
    budget so a wedged tunnel cannot eat the CPU-fallback's time."""
    marker = MARKER_PATH
    try:
        # only trust a recent healthy probe: the tunnel can wedge minutes after a
        # good run (observed), and a stale marker would admit a worker spawn that
        # hangs through its whole stall window
        if os.path.exists(marker) and time.time() - os.path.getmtime(marker) < 300:
            return True
    except OSError:
        pass
    for attempt in range(attempts):
        if attempt:
            time.sleep(5.0)
        budget = deadline_ts - time.time() - ASSEMBLY_MARGIN_S
        if budget <= 25.0:
            return False
        rc = _probe_once(min(timeout_s, max(20.0, 0.25 * budget)))
        if rc == 0:
            try:
                open(marker, "w").close()
            except OSError:
                pass
            return True
        print(
            f"bench orchestrator: device probe attempt {attempt + 1}/{attempts} "
            f"failed (rc={rc})",
            file=sys.stderr,
        )
    return False


def _spawn_worker(progress_path: str, skip: set, cpu: bool) -> subprocess.Popen:
    env = dict(os.environ)
    env.update(
        SRML_BENCH_ROLE="worker",
        SRML_BENCH_PROGRESS=progress_path,
        SRML_BENCH_SKIP=",".join(sorted(skip)),
    )
    if cpu:
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS=(
                env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
            ).strip(),
            PALLAS_AXON_POOL_IPS="",
        )
    # worker stdout -> our stderr: diagnostics stay visible, the single JSON
    # line on OUR stdout stays clean. fileno() can RAISE on swapped-in streams
    # (pytest CaptureIO, StringIO) even though the attribute exists.
    try:
        err_fd = sys.stderr.fileno()
    except Exception:
        err_fd = subprocess.DEVNULL
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        env=env,
        stdout=err_fd,
        stderr=None,
    )


def _mark_inflight_killed(progress_path: str, reason: str) -> None:
    state = _read_progress(progress_path)
    for name, e in state.items():
        if e.get("status") == "start" and name != "boot":
            _flush_progress(
                progress_path, {"unit": name, "status": "killed", "reason": reason}
            )


def _monitor_worker(child: subprocess.Popen, progress_path: str, deadline_ts: float) -> str:
    """Wait for the worker; kill it on deadline or stall. Returns how it ended:
    'exit' | 'crash' | 'deadline_kill' | 'stall_kill'. On a kill or crash, the
    in-flight unit gets a 'killed' progress entry recording the reason (a
    deadline kill is budget exhaustion, not tunnel evidence — assembly reports
    the two differently; a crash, e.g. an XLA compile segfault, is respawnable)."""
    stall_s = _stall_window_s()

    def _last_activity() -> float:
        try:
            return os.path.getmtime(progress_path)
        except OSError:
            return time.time()

    def _kill(reason: str) -> str:
        child.kill()
        child.wait()
        _mark_inflight_killed(progress_path, reason)
        return reason

    while True:
        if child.poll() is not None:
            if child.returncode != 0:
                _mark_inflight_killed(progress_path, "crash")
                return "crash"
            return "exit"
        now = time.time()
        if now > deadline_ts - ASSEMBLY_MARGIN_S:
            return _kill("deadline_kill")
        if now - _last_activity() > stall_s:
            return _kill("stall_kill")
        time.sleep(2.0)


def _assemble(progress_path: str, budget_s: float, baseline_dir: str = None) -> dict:
    """Build the one-line result from whatever the workers flushed. Baseline
    read/seed IO only happens when `baseline_dir` is given (the real orchestrator
    passes the repo root; unit tests call with None so a synthetic progress file
    can never poison the repo's recorded baseline)."""
    state = _read_progress(progress_path)
    boot = state.pop("boot", {})
    secondary: dict = {}
    headline_value = None
    headline_platform = None
    unit_platform: dict = {}  # unit -> platform it was MEASURED on (done only)
    wedged, skipped, error_units, crashed = [], [], [], []
    for name in UNITS:
        e = state.get(name)
        if e is None:
            skipped.append(name)
            continue
        st = e.get("status")
        if st == "done":
            unit_platform[name] = e.get("platform")
            result = dict(e.get("result", {}))
            if name == "kmeans_headline":
                headline_value = result.pop("_value", None)
                headline_platform = e.get("platform")
            secondary.update(result)
        elif st == "error":
            error_units.append(name)
            secondary[f"{name}_error"] = e.get("error")
        elif st == "deadline_skip":
            skipped.append(name)
        elif st == "killed" and e.get("reason") == "deadline_kill":
            skipped.append(name)  # ran out of budget mid-unit, not a wedge
        elif st == "killed" and e.get("reason") == "crash":
            crashed.append(name)  # worker died (e.g. XLA segfault) — not tunnel
        else:  # start with no terminal entry, or a stall kill: tunnel wedge
            wedged.append(name)

    metric = "kmeans_lloyd_rows_per_sec_per_chip"
    unit_name = "rows*iters/sec/chip"
    _family_of = {
        "pca_cov_rows_per_sec_per_chip": "pca",
        "logreg_rows_iters_per_sec_per_chip": "logreg",
        "linreg_rows_per_sec_per_chip": "linreg",
        "rf_rows_trees_per_sec_per_chip": "rf",
    }
    if headline_value is None:
        # headline unit never completed: promote the first captured family
        # number so the line still carries a real measurement (clearly named)
        for key, unit_n in (
            ("pca_cov_rows_per_sec_per_chip", "rows/sec/chip"),
            ("logreg_rows_iters_per_sec_per_chip", "rows*iters/sec/chip"),
            ("linreg_rows_per_sec_per_chip", "rows/sec/chip"),
            ("rf_rows_trees_per_sec_per_chip", "rows*trees/sec/chip"),
        ):
            if secondary.get(key) is not None:
                metric, unit_name = key, unit_n
                headline_value = secondary[key]
                headline_platform = unit_platform.get(_family_of[key])
                secondary["headline_fallback"] = True
                break
    # the metric suffix follows the platform the HEADLINE VALUE was measured on
    # (a TPU-attributed error entry or mixed-platform run must never let a
    # CPU-measured number ship under an unsuffixed TPU metric name)
    platform = headline_platform or boot.get("platform") or "none"
    if platform != "tpu":
        metric += f"_{platform}_fallback"
    measured_platforms = sorted(set(unit_platform.values()))
    if len(measured_platforms) > 1:
        secondary["platforms_by_unit"] = unit_platform

    # vs_baseline (protocol 2 = whole-fit timing with a k-real-rows far init;
    # protocol-less baselines were recorded under the old near-optimal init whose
    # n_iter=2 made the same code read ~6x slower — comparing across protocols
    # would report a spurious "speedup", so a mismatched baseline is reseeded)
    vs_baseline = 1.0
    baseline_path = (
        os.path.join(baseline_dir, "BENCH_BASELINE.json") if baseline_dir else None
    )
    is_kmeans_headline = metric.startswith("kmeans_lloyd_rows_per_sec_per_chip")
    try:
        protocol = 2
        base = None
        if baseline_path is None:
            pass
        elif os.path.exists(baseline_path):
            with open(baseline_path) as f:
                base = json.load(f)
            if base.get("protocol") != protocol:
                print(
                    f"bench: baseline protocol {base.get('protocol')} != {protocol}; "
                    "reseeding baseline, vs_baseline reset to 1.0",
                    file=sys.stderr,
                )
                base = None
        if base is not None and is_kmeans_headline and headline_value:
            if base.get("platform") == platform and base.get("value", 0) > 0:
                vs_baseline = headline_value / base["value"]
        elif (
            baseline_path is not None
            and base is None
            and platform == "tpu"
            and is_kmeans_headline
            and headline_value
        ):
            # only a real-TPU run may seed the local baseline; a transient
            # CPU-fallback run must not poison it
            with open(baseline_path, "w") as f:
                json.dump(
                    {
                        "platform": platform,
                        "value": headline_value,
                        "unit": unit_name,
                        "protocol": protocol,
                    },
                    f,
                )
    except OSError:
        pass

    secondary["platform"] = platform
    secondary["bench_budget_s"] = budget_s
    if boot.get("result"):
        secondary.update(
            {f"headline_{k}": v for k, v in boot["result"].items()}
        )
    done_units = [n for n in UNITS if state.get(n, {}).get("status") == "done"]
    partial = "tpu" in measured_platforms and len(done_units) < len(UNITS)
    if partial:
        secondary["partial"] = True
    if wedged:
        secondary["tunnel_wedged_units"] = wedged
    if skipped:
        secondary["skipped"] = skipped
    if error_units:
        secondary["error_units"] = error_units
    if crashed:
        secondary["crashed_units"] = crashed
    return {
        "metric": metric,
        "value": headline_value if headline_value is not None else 0.0,
        "unit": unit_name,
        "vs_baseline": round(vs_baseline, 4),
        "secondary": secondary,
    }


def main() -> None:
    if os.environ.get("SRML_BENCH_ROLE") == "worker":
        _worker_main()
        return

    # total wall budget: anchored at orchestrator start; every probe, worker run
    # and respawn counts against the same driver timeout. Units are
    # deadline-guarded in the worker; unfinished ones land in `skipped`.
    budget_s = float(os.environ.get("SRML_BENCH_BUDGET_S", "240"))
    if "SRML_BENCH_DEADLINE_TS" in os.environ:
        deadline_ts = float(os.environ["SRML_BENCH_DEADLINE_TS"])
    else:
        deadline_ts = time.time() + budget_s
        os.environ["SRML_BENCH_DEADLINE_TS"] = str(deadline_ts)

    progress_path = os.environ.setdefault(
        "SRML_BENCH_PROGRESS", f"/tmp/srml_bench_progress_{os.getpid()}.jsonl"
    )
    # fresh run: a stale progress file would masquerade as this run's evidence
    try:
        if os.path.exists(progress_path):
            os.remove(progress_path)
    except OSError:
        pass

    def _done_and_wedged():
        state = _read_progress(progress_path)
        done = {
            n
            for n in UNITS
            if state.get(n, {}).get("status") in ("done", "error", "deadline_skip")
        }
        wedged = {
            n
            for n in UNITS
            if state.get(n, {}).get("status") in ("start", "killed")
        }
        return done, wedged

    # TPU attempt loop: spawn, monitor, on wedge re-probe + respawn with the
    # completed AND wedged units excluded (a unit that wedged once gets no
    # second chance — it would likely wedge again and burn the budget)
    tpu_attempts = 0
    skip: set = set()
    while time.time() < deadline_ts - ASSEMBLY_MARGIN_S - 30.0 and tpu_attempts < 3:
        done, wedged = _done_and_wedged()
        skip = done | wedged
        if len(skip) >= len(UNITS):
            break
        if not _probe_device(deadline_ts):
            break
        tpu_attempts += 1
        child = _spawn_worker(progress_path, skip, cpu=False)
        ended = _monitor_worker(child, progress_path, deadline_ts)
        print(f"bench orchestrator: worker attempt {tpu_attempts} ended: {ended}",
              file=sys.stderr)
        if ended in ("exit", "deadline_kill"):
            break
        # 'stall_kill' (tunnel wedged mid-run) and 'crash' (e.g. XLA compile
        # segfault) both loop: re-probe, respawn with done+wedged units skipped.
        # A stall is live evidence the tunnel is wedged NOW — drop the healthy-
        # probe marker so the next _probe_device really probes instead of
        # trusting a pre-wedge marker and respawning straight into the hang.
        if ended == "stall_kill":
            try:
                os.remove(MARKER_PATH)
            except OSError:
                pass

    state = _read_progress(progress_path)
    have_tpu = any(
        state.get(n, {}).get("platform") == "tpu"
        and state.get(n, {}).get("status") == "done"
        for n in UNITS
    )
    # a box with no TPU at all boots the first worker straight onto CPU — that is
    # a complete CPU run, not a wedged tunnel; no fallback respawn, no tunnel flag
    booted_cpu = state.get("boot", {}).get("platform") == "cpu"
    tunnel_down = not have_tpu and not booted_cpu
    if tunnel_down and time.time() < deadline_ts - ASSEMBLY_MARGIN_S - 10.0:
        # zero TPU evidence (tunnel down from the start): CPU fallback so the
        # driver still gets a benchmark line (clearly labeled _cpu_fallback).
        # Skip only COMPLETED units: a unit that wedged the TPU worker is
        # tunnel-specific and must be retried on the tunnel-free CPU backend.
        print("bench orchestrator: no TPU evidence; running CPU fallback",
              file=sys.stderr)
        done, _ = _done_and_wedged()
        child = _spawn_worker(progress_path, done, cpu=True)
        _monitor_worker(child, progress_path, deadline_ts)

    line = _assemble(
        progress_path, budget_s,
        baseline_dir=os.path.dirname(os.path.abspath(__file__)),
    )
    if tunnel_down:
        line["secondary"]["tunnel_down"] = True
    # cumulative on-disk record (evidence survives even if a later run times out)
    try:
        results_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "benchmark", "results"
        )
        os.makedirs(results_dir, exist_ok=True)
        plat = line["secondary"].get("platform", "none")
        with open(os.path.join(results_dir, f"chip_bench_{plat}.json"), "w") as f:
            json.dump(line, f, indent=1)
        import shutil

        shutil.copyfile(
            progress_path, os.path.join(results_dir, "bench_progress_last.jsonl")
        )
    except OSError:
        pass
    print(json.dumps(line))


if __name__ == "__main__":
    main()
