#
# Lock-graph pass (docs/design.md §6j): the ~20 locks across the serving
# registry / device cache / observability runs / autotune table planes are
# correct today by convention; this pass makes the two conventions checkable:
#
#   * locks/order-cycle — build a lock-ORDER graph (edge A->B when B is
#     acquired, directly or through a resolved call chain, while A is held)
#     and report every cycle. A cycle is a deadlock waiting for the right
#     thread interleaving — a wedged barrier at pod scale. Self-edges on
#     RLocks are legal re-entry and skipped; a self-edge on a plain Lock is a
#     guaranteed self-deadlock and reported.
#
#   * locks/blocking-under-lock — device execution (calls into
#     compiled_kernel-decorated impls or .block_until_ready()), file I/O,
#     HTTP, sleeps, subprocesses, and queue.get() without a timeout performed
#     while a REGISTRY or CACHE lock is held. These locks sit on the serving
#     hot path and the metric write fan-out; blocking under one turns every
#     concurrent request/emitter into a convoy.
#
# Lock identity is static: module-level `_lock = threading.Lock()` becomes
# `<module>._lock`, `self._lock` in class C becomes `<module>.C._lock`.
# Acquisitions through unresolvable objects (`obj._lock` on a parameter) are
# recorded for blocking checks but excluded from order edges — a guessed
# identity would fabricate cycles.
#

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import FunctionInfo, get_callgraph
from .core import AnalysisContext, register_pass, register_rule

register_rule(
    "locks/order-cycle",
    "lock-order cycle (deadlock) across the threaded planes",
    """
Two (or more) locks are acquired in opposite orders on different code paths —
with the right thread interleaving each thread holds one and waits forever on
the other. Fix by imposing one global order (acquire the cycle's locks in a
single canonical sequence everywhere) or by narrowing one critical section so
the nested acquisition happens after release. A self-cycle on a non-reentrant
Lock means the function (or a callee) re-acquires a lock the caller already
holds: make it an RLock only if re-entry is genuinely intended; usually the
inner acquisition should move to a _locked() variant called under the lock.
""",
)
register_rule(
    "locks/blocking-under-lock",
    "blocking operation while holding a registry/cache lock",
    """
Device execution, file I/O, HTTP, sleeps, or an untimed queue.get() runs
while a registry or cache lock is held. Every other thread that touches that
plane (serving requests, metric emitters, eviction) convoys behind the slow
operation — the §7 serving path budget assumes lock hold times are
microseconds. Move the slow work outside the critical section (snapshot under
the lock, operate after release), or pass a timeout. Suppress a deliberate
case with `# noqa: locks/blocking-under-lock` and a justification.
""",
)

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}

# lock identities (substring match) that guard the serving/metric hot paths
_HOT_LOCK_PATTERNS = (
    "serving.registry.",
    "serving.http.",
    "ops.device_cache",
    "observability.registry.",
    "observability.runs.",
    "observability.device",
    "autotune.table",
)

_BLOCKING_TIME = {"sleep"}


def _short_mod(name: str) -> str:
    return name[len("spark_rapids_ml_tpu."):] if name.startswith(
        "spark_rapids_ml_tpu."
    ) else name


@dataclass
class _LockMeta:
    rlock: bool = False


@dataclass
class _FnLocks:
    # (lock_id, held_before tuple, line)
    acquires: List[Tuple[str, Tuple[str, ...], int]] = field(default_factory=list)
    # (callee qualname, held tuple, line)
    calls: List[Tuple[str, Tuple[str, ...], int]] = field(default_factory=list)
    # (kind, held tuple, line)
    blocking: List[Tuple[str, Tuple[str, ...], int]] = field(default_factory=list)


class _LockPass:
    def __init__(self, ctx: AnalysisContext):
        self.ctx = ctx
        self.cg = get_callgraph(ctx)
        self.locks: Dict[str, _LockMeta] = {}
        self.kernel_fns: Set[str] = set()
        self.fn_locks: Dict[str, _FnLocks] = {}

    # ------------------------------------------------------- lock discovery

    def _discover_locks(self) -> None:
        for mod in self.ctx.index.files:
            if mod.tree is None or not mod.name:
                continue
            short = _short_mod(mod.name)
            cls_stack: List[str] = []

            def visit(node: ast.AST, cls: Optional[str]) -> None:
                for child in ast.iter_child_nodes(node):
                    nxt_cls = cls
                    if isinstance(child, ast.ClassDef):
                        nxt_cls = child.name
                    if isinstance(child, ast.Assign) and isinstance(
                        child.value, ast.Call
                    ):
                        ctor = child.value.func
                        cname = (
                            ctor.attr if isinstance(ctor, ast.Attribute)
                            else ctor.id if isinstance(ctor, ast.Name) else ""
                        )
                        if cname in _LOCK_CTORS:
                            rlock = cname == "RLock"
                            for t in child.targets:
                                if isinstance(t, ast.Name):
                                    owner = f"{short}.{cls}" if cls else short
                                    self.locks[f"{owner}.{t.id}"] = _LockMeta(rlock)
                                elif (
                                    isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"
                                    and cls
                                ):
                                    self.locks[f"{short}.{cls}.{t.attr}"] = (
                                        _LockMeta(rlock)
                                    )
                    visit(child, nxt_cls)

            visit(mod.tree, None)

    def _discover_kernels(self) -> None:
        from .purity import _is_compiled_kernel_deco

        for q, fi in self.cg.functions.items():
            node = fi.node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(_is_compiled_kernel_deco(d) for d in node.decorator_list):
                    self.kernel_fns.add(q)

    # --------------------------------------------------- per-function facts

    def _lock_id(self, fi: FunctionInfo, expr: ast.AST) -> Optional[str]:
        """Identity of a lock-looking with/acquire expression; None when the
        expression isn't lock-shaped; '?<attr>' for lock-shaped but
        unresolvable (counted for blocking, excluded from ordering)."""
        short = _short_mod(fi.module.name or "")
        if isinstance(expr, ast.Name):
            if "lock" not in expr.id.lower():
                return None
            mid = f"{short}.{expr.id}"
            if mid in self.locks:
                return mid
            # not a discovered module lock (a parameter, a local): lock-shaped
            # but unresolvable — counted for blocking, excluded from ordering
            # (a guessed identity with unknown RLock-ness would fabricate
            # self-deadlock findings on legal re-entrant code)
            return f"?{expr.id}"
        if isinstance(expr, ast.Attribute):
            if "lock" not in expr.attr.lower():
                return None
            if isinstance(expr.value, ast.Name) and expr.value.id == "self" and (
                fi.class_name
            ):
                cid = f"{short}.{fi.class_name}.{expr.attr}"
                return cid
            if isinstance(expr.value, ast.Name):
                # Module attr: `_table._lock` style
                target = self.cg.imports.get(fi.module.name or "", {}).get(
                    expr.value.id
                )
                if target:
                    tid = f"{_short_mod(target)}.{expr.attr}"
                    if tid in self.locks:
                        return tid
            return f"?{expr.attr}"
        return None

    def _blocking_kind(self, fi: FunctionInfo, call: ast.Call,
                       resolved: Optional[str]) -> Optional[str]:
        func = call.func
        kwnames = {kw.arg for kw in call.keywords}
        if resolved is not None and resolved in self.kernel_fns:
            return f"device execution ({resolved.split('.')[-1]})"
        if isinstance(func, ast.Name):
            if func.id == "open":
                return "file I/O (open)"
            target = self.cg.imports.get(fi.module.name or "", {}).get(func.id)
            if target in ("urllib.request.urlopen",):
                return "HTTP (urlopen)"
        if isinstance(func, ast.Attribute):
            base = (
                func.value.id if isinstance(func.value, ast.Name) else None
            )
            target = (
                self.cg.imports.get(fi.module.name or "", {}).get(base)
                if base else None
            )
            if func.attr == "sleep" and (target == "time" or base == "time"):
                return "time.sleep"
            if func.attr == "urlopen":
                return "HTTP (urlopen)"
            if func.attr in ("run", "check_output", "check_call", "Popen") and (
                target == "subprocess" or base == "subprocess"
            ):
                return "subprocess"
            if func.attr == "block_until_ready":
                return "device sync (block_until_ready)"
            if (
                func.attr == "get"
                and base is not None
                and ("queue" in base.lower() or base.lower().endswith("_q"))
                and "timeout" not in kwnames
                and not call.args  # q.get(0.5) positional timeout
            ):
                return f"untimed {base}.get()"
        return None

    def _analyze_function(self, q: str, fi: FunctionInfo) -> _FnLocks:
        facts = _FnLocks()

        def walk(stmts: List[ast.stmt], held: Tuple[str, ...]) -> None:
            for node in stmts:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue  # nested defs are their own graph nodes
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    new_held = held
                    for item in node.items:
                        lid = self._lock_id(fi, item.context_expr)
                        if lid is not None:
                            facts.acquires.append((lid, new_held, node.lineno))
                            new_held = new_held + (lid,)
                        else:
                            # `with open(...)` under a lock is still file I/O
                            self._scan_tree(item.context_expr, fi, facts, held)
                    walk(node.body, new_held)
                    continue
                # other compound statements: recurse into bodies with the
                # same held set; scan this statement's own expressions
                self._scan_exprs(node, fi, facts, held)
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(node, attr, None)
                    if sub:
                        walk(sub, held)
                for h in getattr(node, "handlers", []):
                    walk(h.body, held)

        if isinstance(fi.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            walk(fi.node.body, ())
        return facts

    def _scan_exprs(self, stmt: ast.stmt, fi: FunctionInfo, facts: _FnLocks,
                    held: Tuple[str, ...]) -> None:
        """Calls/acquires in the EXPRESSION part of one statement (compound
        statements' bodies are walked separately so held-sets stay right)."""
        blocks = {"body", "orelse", "finalbody", "handlers"}
        stack: List[ast.AST] = []
        for name, value in ast.iter_fields(stmt):
            if name in blocks:
                continue
            if isinstance(value, ast.AST):
                stack.append(value)
            elif isinstance(value, list):
                stack.extend(v for v in value if isinstance(v, ast.AST))
        self._scan_stack(stack, fi, facts, held)

    def _scan_tree(self, root: ast.AST, fi: FunctionInfo, facts: _FnLocks,
                   held: Tuple[str, ...]) -> None:
        self._scan_stack([root], fi, facts, held)

    def _scan_stack(self, stack: List[ast.AST], fi: FunctionInfo,
                    facts: _FnLocks, held: Tuple[str, ...]) -> None:
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, ast.Call):
                func = node.func
                if isinstance(func, ast.Attribute) and func.attr == "acquire":
                    lid = self._lock_id(fi, func.value)
                    if lid is not None:
                        facts.acquires.append((lid, held, node.lineno))
                kind = None
                resolved = self.cg.resolve_call(fi, node)
                kind = self._blocking_kind(fi, node, resolved)
                if kind is not None:
                    facts.blocking.append((kind, held, node.lineno))
                elif resolved is not None:
                    facts.calls.append((resolved, held, node.lineno))
            stack.extend(ast.iter_child_nodes(node))

    # ------------------------------------------------------------ summaries

    def _transitive(self) -> Tuple[
        Dict[str, Dict[str, Tuple[str, ...]]],
        Dict[str, List[Tuple[str, Tuple[str, ...]]]],
    ]:
        """Per function: transitively acquired locks (lock -> witness chain of
        qualnames) and transitive blocking ops (kind, chain). Depth-limited
        fixpoint over the call graph."""
        acq: Dict[str, Dict[str, Tuple[str, ...]]] = {}
        blk: Dict[str, List[Tuple[str, Tuple[str, ...]]]] = {}
        for q, facts in self.fn_locks.items():
            acq[q] = {lid: (q,) for lid, _h, _l in facts.acquires
                      if not lid.startswith("?")}
            blk[q] = [(kind, (q,)) for kind, held, _l in facts.blocking]
        for _round in range(8):  # call chains deeper than 8 don't exist here
            changed = False
            for q, facts in self.fn_locks.items():
                for callee, _held, _line in facts.calls:
                    for lid, chain in acq.get(callee, {}).items():
                        if lid not in acq[q]:
                            acq[q][lid] = (q,) + chain
                            changed = True
                    for kind, chain in blk.get(callee, []):
                        if all(k != kind for k, _c in blk[q]):
                            blk[q].append((kind, (q,) + chain))
                            changed = True
            if not changed:
                break
        return acq, blk

    # ---------------------------------------------------------------- main

    def run(self) -> None:
        self._discover_locks()
        self._discover_kernels()
        for q, fi in self.cg.functions.items():
            if isinstance(fi.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.fn_locks[q] = self._analyze_function(q, fi)
        acq, blk = self._transitive()

        # ---- order edges: (a, b) -> witness (qualname, line, via)
        edges: Dict[Tuple[str, str], Tuple[str, int, Tuple[str, ...]]] = {}
        for q, facts in self.fn_locks.items():
            for lid, held, line in facts.acquires:
                if lid.startswith("?"):
                    continue
                for h in held:
                    if h.startswith("?"):
                        continue
                    if h == lid:
                        if not self.locks.get(lid, _LockMeta()).rlock:
                            self._emit_self_deadlock(q, lid, line)
                        continue
                    edges.setdefault((h, lid), (q, line, (q,)))
            for callee, held, line in facts.calls:
                for lid, chain in acq.get(callee, {}).items():
                    for h in held:
                        if h.startswith("?"):
                            continue
                        if h == lid:
                            if not self.locks.get(lid, _LockMeta()).rlock:
                                self._emit_self_deadlock(q, lid, line,
                                                         via=chain)
                            continue
                        edges.setdefault((h, lid), (q, line, chain))

        self._report_cycles(edges)

        # ---- blocking under hot locks
        reported: Set[Tuple[str, int]] = set()
        for q, facts in self.fn_locks.items():
            fi = self.cg.functions[q]
            for kind, held, line in facts.blocking:
                hot = [h for h in held if _is_hot(h)]
                if hot and (fi.module.rel, line) not in reported:
                    reported.add((fi.module.rel, line))
                    self.ctx.emit(
                        "locks/blocking-under-lock", fi.module, line,
                        f"{kind} while holding {hot[0]} — move the slow "
                        "work outside the critical section",
                    )
            for callee, held, line in facts.calls:
                hot = [h for h in held if _is_hot(h)]
                if not hot:
                    continue
                for kind, chain in blk.get(callee, []):
                    if (fi.module.rel, line) in reported:
                        continue
                    reported.add((fi.module.rel, line))
                    via = " -> ".join(c.split(".")[-1] for c in chain[:4])
                    self.ctx.emit(
                        "locks/blocking-under-lock", fi.module, line,
                        f"call chain performs {kind} while holding "
                        f"{hot[0]} (via {via}) — move the slow work outside "
                        "the critical section",
                    )

    def _emit_self_deadlock(self, q: str, lid: str, line: int,
                            via: Tuple[str, ...] = ()) -> None:
        fi = self.cg.functions[q]
        extra = (
            " (via " + " -> ".join(c.split(".")[-1] for c in via[:4]) + ")"
            if via else ""
        )
        self.ctx.emit(
            "locks/order-cycle", fi.module, line,
            f"non-reentrant lock {lid} re-acquired while already held"
            f"{extra} — self-deadlock; use the _locked() pattern or an RLock",
        )

    def _report_cycles(
        self,
        edges: Dict[Tuple[str, str], Tuple[str, int, Tuple[str, ...]]],
    ) -> None:
        graph: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        # iterative Tarjan SCC
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        counter = [0]
        sccs: List[List[str]] = []

        def strongconnect(v: str) -> None:
            work = [(v, iter(sorted(graph[v])))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(sorted(graph[w]))))
                        advanced = True
                        break
                    elif w in on_stack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    low[work[-1][0]] = min(low[work[-1][0]], low[node])
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) > 1:
                        sccs.append(sorted(comp))

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)

        for comp in sccs:
            # pick a representative edge inside the SCC for the location
            witness = None
            for (a, b), w in sorted(edges.items()):
                if a in comp and b in comp:
                    witness = (a, b, w)
                    break
            if witness is None:
                continue
            a, b, (q, line, chain) = witness
            fi = self.cg.functions[q]
            ctx_chain = " -> ".join(c.split(".")[-1] for c in chain[:4])
            self.ctx.emit(
                "locks/order-cycle", fi.module, line,
                f"lock-order cycle among {{{', '.join(comp)}}}: here "
                f"{a} is held while acquiring {b} (via {ctx_chain}); "
                "another path acquires them in the reverse order — impose "
                "one canonical order",
            )


def _is_hot(lock_id: str) -> bool:
    return any(p in lock_id for p in _HOT_LOCK_PATTERNS)


@register_pass("locks")
def run(ctx: AnalysisContext) -> None:
    _LockPass(ctx).run()
