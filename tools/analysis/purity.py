#
# Trace-purity pass (docs/design.md §6j): the PR-5/PR-13 host-wrapper
# discipline, machine-checked. Config/knob/env/clock/randomness reads execute
# at TRACE time, not run time — inside a `compiled_kernel` impl, a Pallas
# kernel body, or a function handed to lax.map/scan/while_loop/fori_loop/cond
# or shard_map, the value read is BAKED into the cached executable and every
# later call replays the stale choice (the stale-bake hazard; resolution
# belongs in the host wrapper). This pass:
#
#   1. seeds the intra-package call graph with every traced entry point,
#   2. walks reachability through resolved call edges (lambdas handed to the
#      trace constructs are scanned in their enclosing scope),
#   3. flags impure reads and module-global mutation anywhere reachable.
#
# There is no legitimate grandfathering for these findings: the baseline for
# purity/* must stay EMPTY (a stale-baked knob is a silent wrong answer at
# multi-host scale, not a style issue). Fix the wrapper, or scope a noqa with
# a justification on the single line that is provably trace-safe.
#

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import CallGraph, FunctionInfo, get_callgraph, _body_nodes
from .core import AnalysisContext, register_pass, register_rule

register_rule(
    "purity/config-read",
    "config read reachable from traced code",
    """
`config.get()` / `config.source()` executes at trace time inside a
compiled_kernel impl / Pallas body / lax-control-flow function, baking the
current value into the cached executable — later `config.set()` calls are
silently ignored by every cache hit (the PR-13 stale-bake hazard). Resolve
the knob in the HOST wrapper and pass the value in as a (static) argument.
Suppress only a provably trace-safe line with `# noqa: purity/config-read`.
""",
)
register_rule(
    "purity/env-read",
    "os.environ read reachable from traced code",
    """
Environment reads inside traced code bake the process environment at first
trace into the executable cache. Read the env in the host wrapper (or through
config.py, which owns env resolution) and pass the value in.
""",
)
register_rule(
    "purity/autotune-read",
    "autotune table lookup reachable from traced code",
    """
`autotune.lookup()` is a host-side resolution point by contract
(autotune/knobs.py: "the resolution sites are the PR-5 host wrappers, so
cached traces never bake a stale choice"). A lookup inside traced code pins
the tuning-table value at first trace — retuning, mode changes, and config
pins stop working for every cached signature. Hoist to the host wrapper.
""",
)
register_rule(
    "purity/time-read",
    "wall-clock read reachable from traced code",
    """
`time.time()`/`perf_counter()` inside traced code measures TRACE time once,
then replays that constant forever — timings computed from it are fiction
after the first call. Time in the host wrapper, around the compiled call.
""",
)
register_rule(
    "purity/random-read",
    "host randomness reachable from traced code",
    """
`random.*` / `np.random.*` inside traced code draws ONE sample at trace time
and bakes it — every cached call replays the same "random" value, and the
draw is invisible to jax's key discipline. Use `jax.random` with an explicit
key argument, or draw in the host wrapper and pass the value in.
""",
)
register_rule(
    "purity/global-write",
    "module-global mutation reachable from traced code",
    """
A `global` write inside traced code fires once at trace time and never again
on cache hits — state updates silently stop happening, exactly the class of
bug that is a test flake single-host and a pod-wide wrong answer multi-host.
Return the value instead, or move the mutation to the host wrapper.
""",
)

# traced-seed packages: the library itself (tests deliberately poke impure
# paths in host harness code; benchmark drives hosts)
_SEED_PKG = "spark_rapids_ml_tpu"

# host-plane boundary: reachability does NOT descend INTO these modules.
# A traced function calling config.get / autotune.lookup is flagged AT THE
# CALL SITE (that's the finding); walking into the host plane's own
# implementation would re-report the same root cause against config.py's
# internals (os.environ inside config.get) and drown the signal.
_BOUNDARY_PREFIXES = (
    "spark_rapids_ml_tpu.config",
    "spark_rapids_ml_tpu.autotune",
    "spark_rapids_ml_tpu.observability",
    "spark_rapids_ml_tpu.reliability",
    "spark_rapids_ml_tpu.profiling",
    "spark_rapids_ml_tpu.utils",
)


def _crosses_boundary(cg: CallGraph, caller: str, callee: str) -> bool:
    caller_mod = cg.functions[caller].module.name or ""
    callee_fi = cg.functions.get(callee)
    callee_mod = (callee_fi.module.name or "") if callee_fi else callee
    if caller_mod == callee_mod:
        return False  # a boundary module's own seeds still walk themselves
    return callee_mod.startswith(_BOUNDARY_PREFIXES)

_TIME_FNS = {
    "time", "perf_counter", "monotonic", "time_ns", "perf_counter_ns",
    "monotonic_ns", "process_time", "process_time_ns",
}

# jax.lax control-flow constructs and which positional args are traced bodies
_LAX_BODY_ARGS = {
    "map": (0,),
    "scan": (0,),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "cond": (1, 2),
    "switch": None,  # every arg from 1 on is a branch
}


def _attr_base_name(node: ast.Attribute) -> Optional[str]:
    return node.value.id if isinstance(node.value, ast.Name) else None


def _import_target(cg: CallGraph, fi: FunctionInfo, name: str) -> Optional[str]:
    return cg.imports.get(fi.module.name or "", {}).get(name)


class _Hazard:
    __slots__ = ("rule", "line", "what")

    def __init__(self, rule: str, line: int, what: str):
        self.rule, self.line, self.what = rule, line, what


def _function_hazards(cg: CallGraph, fi: FunctionInfo,
                      nodes: Optional[List[ast.AST]] = None) -> List[_Hazard]:
    """Direct impure reads / global writes lexically inside fi (nested defs
    excluded — they are their own graph nodes)."""
    out: List[_Hazard] = []
    body = nodes if nodes is not None else cg.body_nodes(fi)
    assigned: Set[str] = set()
    globals_decl: List[Tuple[ast.Global, Tuple[str, ...]]] = []
    for node in body:
        if isinstance(node, ast.Global):
            globals_decl.append((node, tuple(node.names)))
        elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                if isinstance(t, ast.Name):
                    assigned.add(t.id)
        if isinstance(node, ast.Attribute):
            base = _attr_base_name(node)
            if base == "os" and node.attr in ("environ", "getenv"):
                out.append(_Hazard("purity/env-read", node.lineno,
                                   f"os.{node.attr}"))
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute):
            base = _attr_base_name(func)
            target = _import_target(cg, fi, base) if base else None
            if func.attr in ("get", "source") and (
                target == "spark_rapids_ml_tpu.config"
                or base in ("_config",)
            ):
                out.append(_Hazard("purity/config-read", node.lineno,
                                   f"{base}.{func.attr}(...)"))
            elif func.attr == "lookup" and (
                (target or "").startswith("spark_rapids_ml_tpu.autotune")
                or base in ("_autotune",)
            ):
                out.append(_Hazard("purity/autotune-read", node.lineno,
                                   f"{base}.lookup(...)"))
            elif base is not None and target == "time" and func.attr in _TIME_FNS:
                out.append(_Hazard("purity/time-read", node.lineno,
                                   f"{base}.{func.attr}()"))
            elif base is not None and target == "random":
                out.append(_Hazard("purity/random-read", node.lineno,
                                   f"{base}.{func.attr}()"))
            elif (
                isinstance(func.value, ast.Attribute)
                and func.value.attr == "random"
                and isinstance(func.value.value, ast.Name)
                and _import_target(cg, fi, func.value.value.id) == "numpy"
            ):
                out.append(_Hazard("purity/random-read", node.lineno,
                                   f"{func.value.value.id}.random."
                                   f"{func.attr}()"))
        elif isinstance(func, ast.Name):
            target = _import_target(cg, fi, func.id)
            if target and target.startswith("time.") and (
                target.split(".", 1)[1] in _TIME_FNS
            ):
                out.append(_Hazard("purity/time-read", node.lineno,
                                   f"{func.id}()"))
            elif target and target.startswith("random."):
                out.append(_Hazard("purity/random-read", node.lineno,
                                   f"{func.id}()"))
    for gnode, names in globals_decl:
        written = [n for n in names if n in assigned]
        if written:
            out.append(_Hazard("purity/global-write", gnode.lineno,
                               f"global {', '.join(written)}"))
    return out


def _is_compiled_kernel_deco(deco: ast.AST) -> bool:
    node = deco.func if isinstance(deco, ast.Call) else deco
    name = (
        node.id if isinstance(node, ast.Name)
        else node.attr if isinstance(node, ast.Attribute) else ""
    )
    return name == "compiled_kernel"


def _is_shard_map_partial_deco(deco: ast.AST) -> bool:
    """`@functools.partial(shard_map, mesh=...)` — the tree's idiom for
    shard-mapped local functions."""
    if not (isinstance(deco, ast.Call) and deco.args):
        return False
    fname = (
        deco.func.attr if isinstance(deco.func, ast.Attribute)
        else deco.func.id if isinstance(deco.func, ast.Name) else ""
    )
    if fname != "partial":
        return False
    first = deco.args[0]
    name = (
        first.id if isinstance(first, ast.Name)
        else first.attr if isinstance(first, ast.Attribute) else ""
    )
    return name in ("shard_map", "pallas_call")


def _callee_name(call: ast.Call) -> str:
    f = call.func
    return (
        f.id if isinstance(f, ast.Name)
        else f.attr if isinstance(f, ast.Attribute) else ""
    )


def _traced_fn_args(call: ast.Call) -> List[ast.AST]:
    """Positional args of `call` that are traced function bodies."""
    name = _callee_name(call)
    out: List[ast.AST] = []
    if name == "pallas_call" and call.args:
        out.append(call.args[0])
    elif name == "shard_map" and call.args:
        out.append(call.args[0])
    elif name in _LAX_BODY_ARGS:
        f = call.func
        base = (
            f.value.id if isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name) else
            f.value.attr if isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Attribute) else ""
        )
        if base != "lax":
            return out
        idxs = _LAX_BODY_ARGS[name]
        if idxs is None:  # switch: branches from arg 1 on
            out.extend(call.args[1:])
        else:
            out.extend(call.args[i] for i in idxs if i < len(call.args))
    elif name == "partial" and call.args:
        inner = call.args[0]
        iname = (
            inner.id if isinstance(inner, ast.Name)
            else inner.attr if isinstance(inner, ast.Attribute) else ""
        )
        if iname in ("shard_map", "pallas_call") and len(call.args) > 1:
            out.append(call.args[1])
    return out


def _seed_functions(cg: CallGraph) -> Dict[str, str]:
    """qualname -> seed kind, for every traced entry point in the package."""
    seeds: Dict[str, str] = {}
    for q, fi in cg.functions.items():
        if not (fi.module.name or "").startswith(_SEED_PKG):
            continue
        node = fi.node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if _is_compiled_kernel_deco(deco):
                    seeds[q] = "compiled_kernel impl"
                elif _is_shard_map_partial_deco(deco):
                    seeds[q] = "shard_map body"
    # functions PASSED to trace constructs (pallas_call/lax.*/shard_map)
    for q, fi in cg.functions.items():
        if not (fi.module.name or "").startswith(_SEED_PKG):
            continue
        for call, _resolved in fi.calls:
            for arg in _traced_fn_args(call):
                if isinstance(arg, ast.Name):
                    tq = cg.resolve_name(fi, arg.id)
                    if tq and tq not in seeds:
                        kind = _callee_name(call)
                        seeds[tq] = f"fn passed to {kind}"
    return seeds


@register_pass("purity")
def run(ctx: AnalysisContext) -> None:
    cg = get_callgraph(ctx)
    seeds = _seed_functions(cg)

    hazard_cache: Dict[str, List[_Hazard]] = {}

    def hazards_of(q: str) -> List[_Hazard]:
        if q not in hazard_cache:
            hazard_cache[q] = _function_hazards(cg, cg.functions[q])
        return hazard_cache[q]

    # BFS from each traced seed through resolved call edges; remember the
    # shortest chain for the message. A function reachable from several seeds
    # reports once per distinct hazard site.
    reported: Set[Tuple[str, str, int]] = set()
    for seed_q in sorted(seeds):
        kind = seeds[seed_q]
        chain: Dict[str, Tuple[str, ...]] = {seed_q: (seed_q,)}
        frontier = [seed_q]
        while frontier:
            nxt: List[str] = []
            for q in frontier:
                fi = cg.functions.get(q)
                if fi is None:
                    continue
                # lambdas handed to trace constructs inside this function
                lam_nodes: List[ast.AST] = []
                for call, _r in fi.calls:
                    for arg in _traced_fn_args(call):
                        if isinstance(arg, ast.Lambda):
                            lam_nodes.extend(_body_nodes(arg))
                hs = list(hazards_of(q))
                if lam_nodes:
                    hs.extend(_function_hazards(cg, fi, nodes=lam_nodes))
                for h in hs:
                    key = (h.rule, fi.module.rel, h.line)
                    if key in reported:
                        continue
                    reported.add(key)
                    via = chain[q]
                    path = " -> ".join(p.split(".")[-1] for p in via[-3:])
                    ctx.emit(
                        h.rule, fi.module, h.line,
                        f"{h.what} is reachable from traced seed "
                        f"`{seed_q.split('.', 1)[-1]}` ({kind}"
                        + (f"; via {path}" if len(via) > 1 else "")
                        + ") — resolve in the host wrapper and pass the "
                        "value in",
                    )
                for callee, _line in cg.edges.get(q, ()):
                    if (
                        callee not in chain
                        and callee.startswith(_SEED_PKG)
                        and not _crosses_boundary(cg, q, callee)
                    ):
                        chain[callee] = chain[q] + (callee,)
                        nxt.append(callee)
            frontier = nxt
