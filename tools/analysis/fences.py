#
# The ten plane-fences and the flat hygiene checks, migrated out of
# ci/lint_python.py into the shared rule registry (docs/design.md §6j) so the
# repo has ONE analyzer, one suppression grammar (`# noqa: <rule-id>`), and
# one CI tier. Semantics are the pre-migration ones; what changed is that a
# suppression must now NAME the rule it waives.
#

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from .core import AnalysisContext, ModuleInfo, register_pass, register_rule

# --------------------------------------------------------------- rule catalog

register_rule(
    "hygiene/syntax-error",
    "file does not parse",
    "Every target file must compile. Fix the syntax error; nothing else in "
    "this file was analyzed.",
)
register_rule(
    "hygiene/tab-indent",
    "tab character in indentation",
    "The tree indents with spaces; a stray tab breaks diffs and (in mixed "
    "lines) the parser. Replace with spaces.",
)
register_rule(
    "hygiene/bare-except",
    "bare `except:`",
    "A bare except catches SystemExit/KeyboardInterrupt too. Catch "
    "`Exception` (or the narrow type you mean).",
)
register_rule(
    "hygiene/mutable-default",
    "mutable default argument",
    "A list/dict/set default is created once and shared across calls. "
    "Default to None and construct inside the function.",
)
register_rule(
    "hygiene/undefined-all-export",
    "__all__ name that doesn't resolve",
    "A name exported in __all__ is neither defined nor imported in the "
    "module — `from m import *` would raise. Fix the name or the export.",
)
register_rule(
    "hygiene/unused-import",
    "unused import",
    "The imported name is never referenced. Delete it, or — for deliberate "
    "re-exports — suppress with `# noqa: hygiene/unused-import`.",
)
register_rule(
    "fence/silent-except",
    "broad except whose body only passes",
    """
A broad handler (`except:` / `except Exception:` / `except BaseException:`)
whose body is only pass/... hides failures the reliability subsystem exists
to surface — it must at least log. Narrow typed catches stay legal control
flow; the reliability package (which implements handling policy) is exempt.
Suppress a deliberate best-effort site with `# noqa: fence/silent-except`.
""",
)
register_rule(
    "fence/uncached-stream",
    "_batch_stream in a loop without cache=",
    """
A direct `_batch_stream(...)` call inside a for/while loop re-uploads every
batch on every pass, bypassing the HBM batch cache (ops/device_cache.py).
Pass a `cache=` handle (passes 2..N replay from HBM) or hoist the stream out
of the loop.
""",
)
register_rule(
    "fence/profiling-internals",
    "profiling._counters/_spans poked outside observability",
    """
Those dicts no longer exist — profiling.py is a compat shim over the typed
registry (observability/registry.py); historically direct mutation corrupted
scoped FitRun accounting. Go through the public surface (count/add_time/
counter_totals/...) or the observability API.
""",
)
register_rule(
    "fence/jit-in-models",
    "jax.jit inside spark_rapids_ml_tpu/models/",
    """
Model-layer predict calls must route through
observability.inference.predict_dispatch (uniform metric names,
shape-bucket/recompile-sentinel telemetry); jitted kernels belong in ops/,
where the dispatch helper wraps them.
""",
)
register_rule(
    "fence/topk-off-plane",
    "direct top-k primitive in ops/ outside ops/selection.py",
    """
Every search-plane top-k routes through ops/selection.py (select_topk /
merge_topk / top_k_max) so the strategy knob, the invalid-sentinel
convention, and the selection telemetry can never be bypassed.
""",
)
register_rule(
    "fence/pallas-off-plane",
    "pallas import/pallas_call outside ops/pallas_*.py",
    """
Raw Pallas kernels carry per-toolchain workarounds (Mosaic precision
emulation, ragged-edge masking, VMEM budgets) and parity contracts that live
with the kernel modules — a pallas_call elsewhere bypasses the
interpret-mode gates, the compiled_kernel telemetry routing, and the §5b/§5c
sentinel/tie-order contracts.
""",
)
register_rule(
    "fence/http-off-plane",
    "http.server/ThreadingHTTPServer outside observability/server.py",
    """
The telemetry endpoint is THE driver-resident HTTP plane (refcounted
lifecycle, loopback default, zero threads when disabled, §6g); other planes
mount path-prefix handlers on it via register_mount rather than binding a
second socket.
""",
)
register_rule(
    "fence/device-analysis-off-plane",
    "cost_analysis/memory_analysis/memory_stats outside observability/device.py",
    """
The device-performance plane (docs/design.md §6f) owns XLA cost/memory
capture and HBM sampling — including the graceful degrade when a runtime
lacks them; a direct call elsewhere bypasses the capture contract AND the
no-warning-spam guarantee. Route through compiled_kernel / sample_hbm.
""",
)
register_rule(
    "fence/hlo-parse-off-plane",
    "HLO collective-op text pattern outside observability/comm.py",
    """
The communication plane (docs/design.md §6h) is the ONE HLO-text parser:
ad-hoc regexes drift from the exporter's collective accounting (exactly what
happened to the pre-§6h tests/test_collective_counts.py). Route through
extract_collectives / collectives_of_computation. Prose mentions of the
opcodes don't match.
""",
)
register_rule(
    "fence/host-staging-copy",
    "host staging copy in ops/ outside ops/ingest.py",
    """
`np.ascontiguousarray(...)` or a sliced-block `.astype(...)` in ops/ stages
batch data through a fresh, uncounted host copy, bypassing the zero-copy
ingest plane (ops/ingest.py::stage_block, docs/design.md §6k): contiguous
device-castable slices should upload as views with the dtype conversion
riding the device, and genuine copy fallbacks should go through the counted
staging pool. Suppress a deliberate host copy (e.g. an init slice mutated in
place before upload) with `# noqa: fence/host-staging-copy`.
""",
)
register_rule(
    "fence/hardcoded-tunable",
    "hard-coded tunable tile/block/threshold constant in ops/",
    """
Numeric tile/block/threshold DEFAULTS live in the knob-registry defaults
module (spark_rapids_ml_tpu/autotune/defaults.py, docs/design.md §6i); their
measured per-platform overrides live in tuning tables. A fresh literal in
ops/ is a knob the autotuner can't see and a re-tuning chore on the next
hardware target. Zero-valued sentinels (`BLOCK_ROWS = 0` = adaptive) stay
legal.
""",
)

# ------------------------------------------------------------------ constants

UNUSED_IMPORT_EXEMPT = {"__init__.py"}
SILENT_SWALLOW_EXEMPT_PARTS = ("reliability",)
PROFILING_INTERNALS = {"_counters", "_spans"}
PROFILING_INTERNALS_EXEMPT_PARTS = ("observability", "profiling.py")
_BROAD_EXC_NAMES = {"Exception", "BaseException"}
_TOPK_PRIMS = {"top_k", "approx_max_k"}
_DEVICE_ANALYSIS = {"cost_analysis", "memory_analysis", "memory_stats"}
_HLO_PARSE_RE = re.compile(
    r"(?:all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all)"
    r"(?:-start|\\?\()"  # the checker's own pattern; tools/analysis is rule-exempt
)
_TUNABLE_NAME_RE = re.compile(r"(TILE|BLOCK|MIN_ITEMS|MIN_K|BUCKET)")


def _const_int(node: ast.AST) -> Optional[int]:
    """Evaluate a literal int expression (`2048`, `1 << 16`, `8 * 1024`);
    None for anything else — only plain numeric literals are banned."""
    if isinstance(node, ast.Constant):
        return node.value if (
            isinstance(node.value, int) and not isinstance(node.value, bool)
        ) else None
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _const_int(node.operand)
        return -v if v is not None else None
    if isinstance(node, ast.BinOp):
        left, right = _const_int(node.left), _const_int(node.right)
        if left is None or right is None:
            return None
        try:
            if isinstance(node.op, ast.LShift):
                return left << right
            if isinstance(node.op, ast.Mult):
                return left * right
            if isinstance(node.op, ast.Add):
                return left + right
            if isinstance(node.op, ast.Sub):
                return left - right
            if isinstance(node.op, ast.Pow):
                return left ** right
            if isinstance(node.op, ast.FloorDiv):
                return left // right
        except (OverflowError, ZeroDivisionError, ValueError):
            return None
    return None


def _is_broad_catch(type_node: Optional[ast.AST]) -> bool:
    if type_node is None:
        return True
    if isinstance(type_node, ast.Name):
        return type_node.id in _BROAD_EXC_NAMES
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad_catch(elt) for elt in type_node.elts)
    return False


def _is_silent_body(body: List[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring or `...`
        return False
    return True


def _in_lib(mod: ModuleInfo) -> bool:
    return mod.rel.startswith("spark_rapids_ml_tpu/")


# ------------------------------------------------------------------- the pass


@register_pass("fences")
def run(ctx: AnalysisContext) -> None:
    for mod in ctx.index.files:
        if mod.parse_error is not None:
            ctx.emit("hygiene/syntax-error", mod, 1,
                     f"syntax error: {mod.parse_error}")
            continue
        assert mod.tree is not None
        _check_hygiene(ctx, mod)
        _check_fences(ctx, mod)


def _check_hygiene(ctx: AnalysisContext, mod: ModuleInfo) -> None:
    tree = mod.tree
    for lineno, line in enumerate(mod.lines, 1):
        if line.lstrip(" ").startswith("\t"):
            ctx.emit("hygiene/tab-indent", mod, lineno, "tab in indentation")

    imports: Dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            if isinstance(node, ast.ImportFrom) and node.module == "__future__":
                continue
            for alias in node.names:
                name = alias.asname or alias.name.split(".")[0]
                if name != "*":
                    imports.setdefault(name, node.lineno)
        elif isinstance(node, ast.ExceptHandler):
            if node.type is None:
                ctx.emit("hygiene/bare-except", mod, node.lineno,
                         "bare `except:` (catch Exception)")
            if (
                node.type is not None
                and _is_broad_catch(node.type)
                and _is_silent_body(node.body)
                and not any(p in SILENT_SWALLOW_EXEMPT_PARTS
                            for p in mod.path.parts)
            ):
                ctx.emit(
                    "fence/silent-except", mod, node.lineno,
                    "silent exception swallowing (broad `except ...: pass` "
                    "with no logging)",
                )
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for default in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None
            ]:
                if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                    ctx.emit("hygiene/mutable-default", mod, default.lineno,
                             f"mutable default argument in {node.name}()")

    used: Set[str] = set()
    exported: Set[str] = set()
    export_line = 1
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and any(getattr(t, "id", "") == "__all__" for t in node.targets)
            and isinstance(node.value, (ast.List, ast.Tuple))
        ):
            export_line = node.lineno
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    exported.add(elt.value)

    module_names = {
        n.name for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))
    }
    top_assigned = {
        getattr(t, "id", None)
        for node in tree.body if isinstance(node, ast.Assign)
        for t in node.targets
    }
    for name in sorted(exported):
        if (name not in module_names and name not in top_assigned
                and name not in imports):
            ctx.emit("hygiene/undefined-all-export", mod, export_line,
                     f"__all__ name '{name}' is not defined")

    if mod.path.name not in UNUSED_IMPORT_EXEMPT:
        for name, lineno in imports.items():
            if name not in used and name not in exported:
                ctx.emit("hygiene/unused-import", mod, lineno,
                         f"unused import '{name}'")


def _check_fences(ctx: AnalysisContext, mod: ModuleInfo) -> None:
    tree = mod.tree
    parts = mod.path.parts
    in_lib = _in_lib(mod)

    # uncached multi-pass re-ingest
    class _Stream(ast.NodeVisitor):
        def __init__(self) -> None:
            self.loop_depth = 0

        def _loop(self, node: ast.AST) -> None:
            self.loop_depth += 1
            self.generic_visit(node)
            self.loop_depth -= 1

        visit_For = visit_AsyncFor = visit_While = _loop

        def visit_Call(self, node: ast.Call) -> None:
            func = node.func
            name = (
                func.id if isinstance(func, ast.Name)
                else func.attr if isinstance(func, ast.Attribute) else ""
            )
            if (
                name == "_batch_stream"
                and self.loop_depth > 0
                and not any(kw.arg == "cache" for kw in node.keywords)
            ):
                ctx.emit(
                    "fence/uncached-stream", mod, node.lineno,
                    "_batch_stream call inside a loop without a cache= "
                    "handle (multi-pass re-ingest bypassing ops/device_cache)",
                )
            self.generic_visit(node)

    _Stream().visit(tree)

    # jax.jit in models/
    if "models" in parts and in_lib:
        for node in ast.walk(tree):
            hit = None
            if (
                isinstance(node, ast.Attribute) and node.attr == "jit"
                and isinstance(node.value, ast.Name)
                and node.value.id == "jax"
            ):
                hit = "jax.jit"
            elif (
                isinstance(node, ast.ImportFrom) and node.module
                and node.module.split(".")[0] == "jax"
                and any(a.name == "jit" for a in node.names)
            ):
                hit = "from jax import jit"
            if hit:
                ctx.emit(
                    "fence/jit-in-models", mod, node.lineno,
                    f"{hit} in models/ — route predict calls through "
                    "observability.inference.predict_dispatch (jitted "
                    "kernels belong in ops/)",
                )

    # top-k primitives outside ops/selection.py
    if "ops" in parts and in_lib and mod.path.name != "selection.py":
        for node in ast.walk(tree):
            hit = None
            if (
                isinstance(node, ast.Attribute) and node.attr in _TOPK_PRIMS
                and (
                    (isinstance(node.value, ast.Attribute)
                     and node.value.attr == "lax")
                    or (isinstance(node.value, ast.Name)
                        and node.value.id == "lax")
                )
            ):
                hit = f"direct {node.attr}"
            elif (
                isinstance(node, ast.ImportFrom) and node.module == "jax.lax"
                and any(a.name in _TOPK_PRIMS for a in node.names)
            ):
                hit = "from jax.lax import top_k/approx_max_k"
            if hit:
                ctx.emit(
                    "fence/topk-off-plane", mod, node.lineno,
                    f"{hit} in ops/ — route top-k through ops/selection.py "
                    "(select_topk/merge_topk/top_k_max)",
                )

    # hard-coded tunables in ops/
    if "ops" in parts and in_lib:
        for node in tree.body:
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            names = [
                t.id for t in targets
                if isinstance(t, ast.Name) and _TUNABLE_NAME_RE.search(t.id)
            ]
            if not names:
                continue
            v = _const_int(value)
            if not v:  # zero = adaptive sentinel, None = not a literal
                continue
            ctx.emit(
                "fence/hardcoded-tunable", mod, node.lineno,
                f"hard-coded tunable '{names[0]} = {v}' in ops/ — numeric "
                "tile/threshold defaults live in spark_rapids_ml_tpu/"
                "autotune/defaults.py (knob registry, docs/design.md §6i); "
                "import it or declare a knob",
            )

    # host staging copies in ops/ outside the ingest plane
    if "ops" in parts and in_lib and mod.path.name != "ingest.py":
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            hit = None
            if (
                isinstance(func, ast.Attribute)
                and func.attr == "ascontiguousarray"
            ) or (isinstance(func, ast.Name) and func.id == "ascontiguousarray"):
                hit = "ascontiguousarray(...)"
            elif (
                isinstance(func, ast.Attribute)
                and func.attr == "astype"
                and isinstance(func.value, ast.Subscript)
            ):
                hit = "sliced-block .astype(...)"
            if hit:
                ctx.emit(
                    "fence/host-staging-copy", mod, node.lineno,
                    f"{hit} in ops/ — block staging goes through the counted "
                    "zero-copy ingest plane (ops/ingest.py::stage_block / "
                    "StagingPool, docs/design.md §6k)",
                )

    # pallas outside ops/pallas_*.py
    if not ("ops" in parts and in_lib and mod.path.name.startswith("pallas_")):
        for node in ast.walk(tree):
            hit = None
            if isinstance(node, ast.Import) and any(
                a.name.startswith("jax.experimental.pallas")
                for a in node.names
            ):
                hit = "import jax.experimental.pallas"
            elif isinstance(node, ast.ImportFrom) and (
                (node.module or "").startswith("jax.experimental.pallas")
                or (node.module == "jax.experimental"
                    and any(a.name == "pallas" for a in node.names))
            ):
                hit = "from jax.experimental import pallas"
            elif isinstance(node, ast.Attribute) and node.attr == "pallas_call":
                hit = "direct pallas_call"
            if hit:
                ctx.emit(
                    "fence/pallas-off-plane", mod, node.lineno,
                    f"{hit} outside ops/pallas_*.py — Pallas kernels live in "
                    "the pallas kernel modules (interpret gates, Mosaic "
                    "workarounds, §5c parity contracts); route through their "
                    "host wrappers",
                )

    # http.server outside observability/server.py
    if not (mod.path.name == "server.py" and "observability" in parts):
        for node in ast.walk(tree):
            hit = None
            if isinstance(node, ast.Import) and any(
                a.name == "http.server" or a.name.startswith("http.server.")
                for a in node.names
            ):
                hit = "import http.server"
            elif isinstance(node, ast.ImportFrom) and (
                (node.module or "") == "http.server"
                or (node.module or "").startswith("http.server.")
                or (node.module == "http"
                    and any(a.name == "server" for a in node.names))
            ):
                hit = "from http.server import ..."
            elif (
                isinstance(node, (ast.Name, ast.Attribute))
                and (getattr(node, "id", None) == "ThreadingHTTPServer"
                     or getattr(node, "attr", None) == "ThreadingHTTPServer")
            ):
                hit = "ThreadingHTTPServer reference"
            if hit:
                ctx.emit(
                    "fence/http-off-plane", mod, node.lineno,
                    f"{hit} outside observability/server.py — one HTTP plane "
                    "only; mount handlers on it via observability.server."
                    "register_mount (docs/design.md §6g/§7)",
                )

    # device analysis outside observability/device.py
    if not (mod.path.name == "device.py" and "observability" in parts):
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) and node.attr in _DEVICE_ANALYSIS:
                ctx.emit(
                    "fence/device-analysis-off-plane", mod, node.lineno,
                    f"direct .{node.attr}() outside observability/device.py "
                    "— route through the device-performance plane "
                    "(compiled_kernel / sample_hbm, docs/design.md §6f)",
                )

    # HLO collective text outside observability/comm.py (and the analyzer,
    # which implements this very check)
    if not (
        (mod.path.name == "comm.py" and "observability" in parts)
        or mod.rel.startswith("tools/analysis/")
    ):
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            if not _HLO_PARSE_RE.search(node.value):
                continue
            ctx.emit(
                "fence/hlo-parse-off-plane", mod, node.lineno,
                "HLO collective-op text pattern in a string literal — "
                "collective parsing lives in observability/comm.py only "
                "(extract_collectives / collectives_of_computation, "
                "docs/design.md §6h)",
                noqa_lines=[getattr(node, "end_lineno", node.lineno)],
            )

    # profiling internals outside observability/profiling
    if not any(p in PROFILING_INTERNALS_EXEMPT_PARTS for p in parts):
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Attribute)
                and node.attr in PROFILING_INTERNALS
                and isinstance(node.value, ast.Name)
                and node.value.id == "profiling"
            ):
                ctx.emit(
                    "fence/profiling-internals", mod, node.lineno,
                    f"direct use of profiling.{node.attr} (the dict no "
                    "longer exists — go through the profiling/observability "
                    "public surface)",
                )
