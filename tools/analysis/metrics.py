#
# Metric-contract pass (docs/design.md §6j): the PR 3–13 telemetry arc made
# `name{label=}` metric keys the join surface between the library, CI smokes,
# bench gates, dashboards, and docs — and nothing checked that both sides of
# the join still exist. This pass harvests:
#
#   EMISSIONS — every Counter/Gauge/Histogram/span write with a literal name:
#     the fan-out helpers (counter_inc/gauge_set/gauge_inc/gauge_dec/observe/
#     add_span_total), the legacy shims (count/add_time/legacy_count), the
#     registry getters (.counter("x")/.gauge("x")/.histogram("x")), and
#     span("x"). Label KEYS come from the call's keyword arguments. A dynamic
#     site (non-literal name) can declare itself with a pragma comment:
#     `# srml-metric: name{key1,key2}` on or above the emitting line.
#
#   CONSUMPTIONS — metric-shaped string literals (`ns.name` dotted grammar,
#     first segment restricted to an emitted namespace) in the consumer
#     corpora: tests/, ci/ (bench_check + test.sh heredoc smokes), bench.py,
#     benchmark/, and the docs (docs/*.md, README.md).
#
# and reports three contract breaks:
#   metrics/consumed-unemitted — a consumer references a name no library code
#     emits (the pre-§6h test_collective_counts.py failure mode).
#   metrics/label-mismatch — one name emitted with conflicting label-key sets
#     (neither a subset of the other): the exported series would split.
#   metrics/undocumented — an emitted name appearing in no doc file; the
#     catalog lives in docs/metrics.md.
#

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import AnalysisContext, ModuleInfo, register_pass, register_rule

register_rule(
    "metrics/consumed-unemitted",
    "metric name consumed but never emitted",
    """
A test assertion, CI smoke, bench gate, or doc references a metric name that
no code emits — the consumer is asserting on a key that can never appear
(green-by-vacuity for `sum(v for k if k.startswith(...))` shapes, red forever
for exact-key asserts). Either the metric was renamed (update the consumer)
or the emission was deleted (delete the consumer). Dynamic emission sites can
declare their names with a `# srml-metric: name{label1,label2}` pragma.
""",
)
register_rule(
    "metrics/label-mismatch",
    "one metric name emitted with conflicting label-key sets",
    """
Two emission sites write the same metric name with label-key sets where
neither is a subset of the other. The exported series splits into disjoint
key spaces: `name{a=}` and `name{b=}` never aggregate, dashboards and
bench_check greps silently see half the data. Pick one label schema per name
(a site may ADD labels to a common core, but not swap them).
""",
)
register_rule(
    "metrics/undocumented",
    "emitted metric name documented nowhere",
    """
A metric is emitted but appears in no doc file (docs/*.md, README.md) — the
telemetry surface grew without the catalog. Add the name (with its labels and
one-line meaning) to docs/metrics.md. The catalog is what makes a dashboard
buildable without reading the emitters.
""",
)

# emit helpers: callable terminal name -> kwargs that are NOT labels
_EMIT_FUNCS: Dict[str, Set[str]] = {
    "counter_inc": {"n"},
    "gauge_set": {"value"},
    "gauge_inc": {"n"},
    "gauge_dec": {"n"},
    "observe": {"buckets", "value", "exemplar"},
    "add_span_total": set(),
    "legacy_count": set(),
    "count": set(),
    "add_time": set(),
    "span": set(),
}

# phase-name surfaces: progress() publishes fit.progress{phase=<arg0>} and
# note_rank_phase() feeds the comm plane's per-phase keys — arg0 is the token
# smokes/tests reference. They join the consumed-satisfier vocabulary, NOT
# the metric-name universe (no label schema, no doc-catalog obligation).
_PHASE_FUNCS = ("progress", "note_rank_phase")

# local import aliases of the emit helpers seen in-tree; the `_counter`
# best-effort wrapper (autotune/knobs.py, table.py) forwards to counter_inc
_EMIT_ALIASES = {
    "obs_span": "span",
    "_obs_span": "span",
    "_span": "span",
    "obs_counter_inc": "counter_inc",
    "obs_gauge_set": "gauge_set",
    "obs_observe": "observe",
    "_counter": "counter_inc",
    "obs_progress": "progress",
}


def _canon_fname(fname: str) -> str:
    return _EMIT_ALIASES.get(fname, fname)
_REGISTRY_GETTERS = {"counter", "gauge", "histogram"}

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$")
_PRAGMA_RE = re.compile(
    r"#\s*srml-metric:\s*([a-z][a-z0-9_.]*)(?:\{([a-z0-9_,\s]*)\})?"
)
# a dotted token inside quotes/backticks in non-python corpora
_CORPUS_TOKEN_RE = re.compile(
    r"[\"'`]([a-z][a-z0-9_]*(?:\.[a-z0-9_]+)+)(?:\{[^\"'`]*)?[\"'`]"
)

_DOC_FILES = ("docs/metrics.md", "docs/design.md", "docs/configuration.md",
              "README.md")
_SHELL_CONSUMERS = ("ci/test.sh",)

# consumer python files: anything under these roots reads metrics back
_CONSUMER_PREFIXES = ("tests/", "ci/", "benchmark/")
_CONSUMER_FILES = ("bench.py",)


class _Emission:
    __slots__ = ("name", "labels", "rel", "line", "dynamic_labels")

    def __init__(self, name: str, labels: Optional[Tuple[str, ...]],
                 rel: str, line: int):
        self.name = name
        self.labels = labels  # None == **dynamic, excluded from mismatch
        self.rel = rel
        self.line = line


def _literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _literal_strs(node: ast.AST) -> List[str]:
    """Literal string value(s) of an emission-name argument. A conditional
    name (`"a.x" if cond else "a.y"`, ops/knn.py::_count_x2) emits both."""
    s = _literal_str(node)
    if s is not None:
        return [s]
    if isinstance(node, ast.IfExp):
        return [s for sub in (node.body, node.orelse)
                for s in _literal_strs(sub)]
    return []


def _harvest_emissions(mod: ModuleInfo) -> List[_Emission]:
    out: List[_Emission] = []
    if mod.tree is None:
        return out
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        fname = _canon_fname(
            func.id if isinstance(func, ast.Name)
            else func.attr if isinstance(func, ast.Attribute) else ""
        )
        names: List[str] = []
        labels: Optional[Tuple[str, ...]] = ()
        if fname in _EMIT_FUNCS and node.args:
            names = _literal_strs(node.args[0])
            skip = _EMIT_FUNCS[fname]
            keys: List[str] = []
            dynamic = False
            for kw in node.keywords:
                if kw.arg is None:
                    dynamic = True  # **labels
                elif kw.arg not in skip:
                    keys.append(kw.arg)
            labels = None if dynamic else tuple(sorted(keys))
        elif (
            fname in ("inc", "dec", "set")
            and isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Call)
            and isinstance(func.value.func, ast.Attribute)
            and func.value.func.attr in _REGISTRY_GETTERS
            and func.value.args
        ):
            # reg.counter("x").inc(n, **labels) chained form
            names = _literal_strs(func.value.args[0])
            keys = []
            dynamic = False
            for kw in node.keywords:
                if kw.arg is None:
                    dynamic = True
                elif kw.arg not in ("n", "value"):
                    keys.append(kw.arg)
            labels = None if dynamic else tuple(sorted(keys))
        elif (
            fname in _REGISTRY_GETTERS
            and isinstance(func, ast.Attribute)
            and node.args
        ):
            # bare reg.histogram("x") — name only, labels unknowable
            names = _literal_strs(node.args[0])
            labels = None
        for name in names:
            if _NAME_RE.match(name):
                out.append(_Emission(name, labels, mod.rel, node.lineno))
    # pragma-declared dynamic emissions
    for i, line in enumerate(mod.lines, 1):
        m = _PRAGMA_RE.search(line)
        if m:
            keys = tuple(sorted(
                k.strip() for k in (m.group(2) or "").split(",") if k.strip()
            ))
            out.append(_Emission(m.group(1), keys or (), mod.rel, i))
    return out


def _is_consumer(mod: ModuleInfo) -> bool:
    return mod.rel.startswith(_CONSUMER_PREFIXES) or mod.rel in _CONSUMER_FILES


# dotted vocabularies that share the metric grammar but are NOT metrics:
# config keys (config.py _DEFAULTS/_ENV_KEYS), autotune knob names
# (Knob("...") declarations), and compiled-kernel names (they surface as
# `device.compile{kernel=}` label VALUES and `device.kernels[].kernel`
# records, both legitimately consumed by tests/smokes/docs)
_FILEISH_SUFFIXES = (".py", ".sh", ".md", ".json", ".jsonl", ".txt", ".yaml")


def _harvest_vocab(ctx: AnalysisContext) -> Set[str]:
    vocab: Set[str] = set()
    cfg = ctx.index.by_rel.get("spark_rapids_ml_tpu/config.py")
    if cfg is not None and cfg.tree is not None:
        for node in ast.walk(cfg.tree):
            if isinstance(node, ast.Dict):
                for kn in node.keys:
                    s = _literal_str(kn) if kn is not None else None
                    if s:
                        vocab.add(s)
    for mod in ctx.index.files:
        if mod.tree is None or not mod.rel.startswith("spark_rapids_ml_tpu/"):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = _canon_fname(
                node.func.id if isinstance(node.func, ast.Name)
                else node.func.attr if isinstance(node.func, ast.Attribute)
                else ""
            )
            if fname in ("Knob", "compiled_kernel") + _PHASE_FUNCS and node.args:
                for s in _literal_strs(node.args[0]):
                    vocab.add(s)
            # phase names threaded as keywords (streamed-fit loops pass
            # progress_phase="kmeans.batches" down to the ingest tier)
            for kw in node.keywords:
                if kw.arg in ("phase", "progress_phase"):
                    for s in _literal_strs(kw.value):
                        vocab.add(s)
    return vocab


def _harvest_py_consumptions(mod: ModuleInfo,
                             namespaces: Set[str]) -> List[Tuple[str, int]]:
    """Metric-shaped string literals in a consumer module. The literal may
    carry a `{label=` suffix (prefix-grep form); only the dotted base is
    checked."""
    out: List[Tuple[str, int]] = []
    if mod.tree is None:
        return out
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Constant) and isinstance(node.value, str)):
            continue
        base = node.value.split("{")[0]
        if not _NAME_RE.match(base):
            continue
        if base.split(".")[0] not in namespaces:
            continue
        out.append((base, node.lineno))
    return out


@register_pass("metrics")
def run(ctx: AnalysisContext) -> None:
    emissions: List[_Emission] = []
    lib_mods: List[ModuleInfo] = []
    for mod in ctx.index.files:
        if mod.rel.startswith("spark_rapids_ml_tpu/"):
            lib_mods.append(mod)
            emissions.extend(_harvest_emissions(mod))

    emitted: Dict[str, List[_Emission]] = {}
    for e in emissions:
        emitted.setdefault(e.name, []).append(e)
    namespaces = {n.split(".")[0] for n in emitted}
    vocab = _harvest_vocab(ctx)

    # ---- consumed-but-never-emitted
    def satisfied(base: str) -> bool:
        if base in emitted or base in vocab:
            return True
        if base.endswith(_FILEISH_SUFFIXES):
            return True  # file path, not a metric
        return any(
            name == base or name.startswith(base)
            or base.startswith(name + ".")  # dynamic-suffix families
            for name in emitted
        )

    for mod in ctx.index.files:
        if not _is_consumer(mod):
            continue
        # a test that emits its own fixture metric (span("t.x") then asserts
        # on "t.x") satisfies itself — only names NOBODY emits are drift
        own = {e.name for e in _harvest_emissions(mod)}
        for base, line in _harvest_py_consumptions(mod, namespaces):
            if satisfied(base) or base in own or any(
                n.startswith(base) for n in own
            ):
                continue
            ctx.emit(
                "metrics/consumed-unemitted", mod, line,
                f"`{base}` is consumed here but no library code emits "
                "it (rename drift? add a `# srml-metric:` pragma at a "
                "dynamic emission site if one exists)",
            )
    for rel in _SHELL_CONSUMERS:
        text = ctx.index.read_text(rel)
        mod = ctx.index.by_rel.get(rel)
        if text is None:
            continue
        for i, line in enumerate(text.splitlines(), 1):
            for m in _CORPUS_TOKEN_RE.finditer(line):
                base = m.group(1)
                if base.split(".")[0] in namespaces and not satisfied(base):
                    # shell corpus has no ModuleInfo; report against test.sh
                    # through a synthetic one-off emit
                    from .core import Finding

                    ctx.findings.append(Finding(
                        "metrics/consumed-unemitted", rel, i,
                        f"`{base}` is consumed here but no library code "
                        "emits it",
                        line_text=line,
                    ))

    # ---- label-set conflicts (static sites only; None == dynamic, skipped)
    for name in sorted(emitted):
        sets: Dict[Tuple[str, ...], _Emission] = {}
        for e in emitted[name]:
            if e.labels is not None:
                sets.setdefault(e.labels, e)
        keysets = sorted(sets)
        conflict = None
        for i in range(len(keysets)):
            for j in range(i + 1, len(keysets)):
                a, b = set(keysets[i]), set(keysets[j])
                if not (a <= b or b <= a):
                    conflict = (sets[keysets[i]], sets[keysets[j]])
                    break
            if conflict:
                break
        if conflict:
            e1, e2 = conflict
            mod = ctx.index.by_rel[e2.rel]
            ctx.emit(
                "metrics/label-mismatch", mod, e2.line,
                f"`{name}` emitted here with labels "
                f"{{{', '.join(e2.labels or ())}}} but with "
                f"{{{', '.join(e1.labels or ())}}} at {e1.rel}:{e1.line} — "
                "neither is a subset of the other; pick one label schema",
            )

    # ---- undocumented emissions
    doc_tokens: Set[str] = set()
    for rel in _DOC_FILES:
        text = ctx.index.read_text(rel)
        if text is None:
            continue
        for m in _CORPUS_TOKEN_RE.finditer(text):
            doc_tokens.add(m.group(1))
        # docs also reference names in prose/backticks without quotes
        for m in re.finditer(r"`([a-z][a-z0-9_]*(?:\.[a-z0-9_]+)+)[`{]", text):
            doc_tokens.add(m.group(1))
    for name in sorted(emitted):
        if name in doc_tokens or any(
            t != name and name.startswith(t + ".") for t in doc_tokens
        ):
            continue
        e = min(emitted[name], key=lambda e: (e.rel, e.line))
        mod = ctx.index.by_rel[e.rel]
        ctx.emit(
            "metrics/undocumented", mod, e.line,
            f"emitted metric `{name}` appears in no doc file — add it to "
            "the docs/metrics.md catalog",
        )
