#
# Intra-package call graph over the shared index — the cross-file spine the
# trace-purity and lock-graph passes walk. Deliberately approximate in the
# safe direction for THIS codebase's idioms:
#
#   * `from .m import f` / `from ..pkg import mod` resolve through the package
#     tree; absolute intra-repo imports resolve too. Third-party targets stay
#     opaque (no edges).
#   * a bare Name call resolves lexically: enclosing function's nested defs,
#     then outer functions, then module-level defs, then imports.
#   * `self.m()` resolves to a method `m` on the lexically enclosing class.
#   * `mod.f()` resolves when `mod` is an imported module in the index.
#   * anything else (instance attributes, dynamic dispatch) resolves to
#     nothing — a pass that needs more (e.g. locks on `registry.upload()`)
#     falls back to its own name-based matching.
#
# One graph is built per run and shared via AnalysisContext.shared["callgraph"].
#

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .core import AnalysisContext, ModuleInfo, ProjectIndex


@dataclass
class FunctionInfo:
    qualname: str  # module.Class.fn / module.fn / module.fn.inner
    module: ModuleInfo
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    lineno: int
    class_name: Optional[str] = None
    parent: Optional[str] = None  # qualname of lexically enclosing function
    children: Dict[str, str] = field(default_factory=dict)  # name -> qualname
    calls: List[Tuple[ast.Call, Optional[str]]] = field(default_factory=list)

    @property
    def short(self) -> str:
        return self.qualname


class CallGraph:
    def __init__(self, index: ProjectIndex):
        self.index = index
        self.functions: Dict[str, FunctionInfo] = {}
        # module name -> {local binding -> fully qualified target}
        self.imports: Dict[str, Dict[str, str]] = {}
        # module name -> {top-level def/class name -> qualname}
        self.module_defs: Dict[str, Dict[str, str]] = {}
        # module.Class -> {method name -> qualname}
        self.class_methods: Dict[str, Dict[str, str]] = {}
        self.edges: Dict[str, List[Tuple[str, int]]] = {}
        self._build()

    # ------------------------------------------------------------- indexing

    def _resolve_import(self, mod: ModuleInfo, node: ast.AST) -> Dict[str, str]:
        out: Dict[str, str] = {}
        if isinstance(node, ast.Import):
            for alias in node.names:
                out[alias.asname or alias.name.split(".")[0]] = (
                    alias.name if alias.asname else alias.name.split(".")[0]
                )
                if alias.asname:
                    out[alias.asname] = alias.name
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:
                parts = (mod.name or "").split(".")
                # drop the module leaf + (level-1) packages
                keep = len(parts) - node.level
                if mod.path.name == "__init__.py":
                    keep += 1
                prefix = ".".join(parts[:keep]) if keep > 0 else ""
                base = f"{prefix}.{base}" if base and prefix else (prefix or base)
            for alias in node.names:
                if alias.name == "*":
                    continue
                target = f"{base}.{alias.name}" if base else alias.name
                out[alias.asname or alias.name] = target
        return out

    def _build(self) -> None:
        for mod in self.index.files:
            if mod.tree is None or not mod.name:
                continue
            imap: Dict[str, str] = {}
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.Import, ast.ImportFrom)):
                    imap.update(self._resolve_import(mod, node))
            self.imports[mod.name] = imap
            self.module_defs[mod.name] = {}
            self._index_body(mod, mod.tree.body, prefix=mod.name,
                             class_name=None, parent=None, top_level=True)
        for fi in list(self.functions.values()):
            self._collect_calls(fi)

    def _index_body(self, mod: ModuleInfo, body: List[ast.stmt], prefix: str,
                    class_name: Optional[str], parent: Optional[str],
                    top_level: bool) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}.{node.name}"
                fi = FunctionInfo(
                    qualname=q, module=mod, node=node, lineno=node.lineno,
                    class_name=class_name, parent=parent,
                )
                self.functions[q] = fi
                if top_level:
                    self.module_defs[mod.name][node.name] = q
                if parent and parent in self.functions:
                    self.functions[parent].children[node.name] = q
                if class_name:
                    self.class_methods.setdefault(
                        f"{mod.name}.{class_name}", {}
                    )[node.name] = q
                self._index_body(mod, node.body, prefix=q,
                                 class_name=class_name, parent=q,
                                 top_level=False)
            elif isinstance(node, ast.ClassDef):
                if top_level:
                    self.module_defs[mod.name][node.name] = f"{prefix}.{node.name}"
                self._index_body(mod, node.body, prefix=f"{prefix}.{node.name}",
                                 class_name=node.name, parent=parent,
                                 top_level=False)
            elif isinstance(node, (ast.If, ast.Try)):
                # defs under `if TYPE_CHECKING:` / try-import blocks
                subbodies = [getattr(node, "body", []),
                             getattr(node, "orelse", []),
                             getattr(node, "finalbody", [])]
                for h in getattr(node, "handlers", []):
                    subbodies.append(h.body)
                for sb in subbodies:
                    self._index_body(mod, sb, prefix=prefix,
                                     class_name=class_name, parent=parent,
                                     top_level=top_level)

    # ----------------------------------------------------------- resolution

    def resolve_name(self, fi: FunctionInfo, name: str) -> Optional[str]:
        """Lexical lookup of a bare name to a function qualname."""
        cur: Optional[FunctionInfo] = fi
        while cur is not None:
            q = cur.children.get(name)
            if q:
                return q
            cur = self.functions.get(cur.parent) if cur.parent else None
        mod = fi.module.name or ""
        q = self.module_defs.get(mod, {}).get(name)
        if q and q in self.functions:
            return q
        target = self.imports.get(mod, {}).get(name)
        if target and target in self.functions:
            return target
        # `from .m import f` where f is a method-less module function
        return None

    def resolve_call(self, fi: FunctionInfo, call: ast.Call) -> Optional[str]:
        func = call.func
        mod = fi.module.name or ""
        if isinstance(func, ast.Name):
            return self.resolve_name(fi, func.id)
        if isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name):
                if base.id == "self" and fi.class_name:
                    q = self.class_methods.get(
                        f"{mod}.{fi.class_name}", {}
                    ).get(func.attr)
                    if q:
                        return q
                    return None
                target = self.imports.get(mod, {}).get(base.id)
                if target:
                    # imported module: target.attr may be a function
                    q = f"{target}.{func.attr}"
                    if q in self.functions:
                        return q
                    # imported class: ClassName.method
                    q2 = self.class_methods.get(target, {})
                    if func.attr in q2:
                        return q2[func.attr]
                # Name bound to a top-level class in this module: C.method
                cls_q = self.module_defs.get(mod, {}).get(base.id)
                if cls_q:
                    q = self.class_methods.get(cls_q, {}).get(func.attr)
                    if q:
                        return q
        return None

    def _collect_calls(self, fi: FunctionInfo) -> None:
        """Direct Call nodes in fi's body, excluding nested def/lambda bodies
        (those run when called, not when defined)."""
        own_nodes = _body_nodes(fi.node)
        for node in own_nodes:
            if isinstance(node, ast.Call):
                fi.calls.append((node, self.resolve_call(fi, node)))
        self.edges[fi.qualname] = [
            (q, c.lineno) for c, q in fi.calls if q is not None
        ]

    def body_nodes(self, fi: FunctionInfo) -> List[ast.AST]:
        return _body_nodes(fi.node)


def _body_nodes(fn_node: ast.AST) -> List[ast.AST]:
    """All AST nodes lexically inside fn_node but NOT inside a nested
    FunctionDef/AsyncFunctionDef/Lambda (the nested body belongs to the nested
    function)."""
    out: List[ast.AST] = []
    if isinstance(fn_node, ast.Lambda):
        roots: List[ast.AST] = [fn_node.body]
    else:
        roots = list(fn_node.body)  # type: ignore[attr-defined]
    stack: List[ast.AST] = list(roots)
    while stack:
        node = stack.pop()
        out.append(node)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)
    return out


def get_callgraph(ctx: AnalysisContext) -> CallGraph:
    cg = ctx.shared.get("callgraph")
    if cg is None:
        cg = ctx.shared["callgraph"] = CallGraph(ctx.index)
    return cg
