#
# Framework half of the whole-program analyzer (docs/design.md §6j): ONE
# shared AST parse + module index per run, a rule registry with stable IDs,
# findings that carry file:line + rule + a one-line why, a scoped-suppression
# grammar (`# noqa: <rule-id>`), and a checked-in baseline for grandfathered
# findings. The passes (fences/purity/locks/metrics) are pure consumers of
# this module: they read the index, emit findings, and never re-read a file.
#
# Suppression grammar — exactly one form is legal:
#
#     <code>  # noqa: rule-id[, rule-id...] [— free-text justification]
#
# A bare `# noqa` (no rule id) is itself a finding (noqa/blanket): blanket
# waivers are how dead suppressions rot. A rule id the registry doesn't know
# is a finding (noqa/unknown-rule); a known id that suppresses nothing on its
# line is a finding (noqa/unused). The baseline file plays the same game at
# the repository level: entries are fingerprinted on (rule, file, source-line
# text) — stable across line renumbering — and an entry that no longer
# matches any live finding is a finding (baseline/stale).
#

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

# default analysis targets, relative to the repo root: every python surface CI
# runs plus the analyzer itself (it eats its own dogfood)
DEFAULT_TARGETS = (
    "spark_rapids_ml_tpu",
    "benchmark",
    "tests",
    "ci",
    "tools",
    "bench.py",
    "__graft_entry__.py",
)

DEFAULT_BASELINE = "tools/analysis/baseline.json"

_NOQA_RE = re.compile(
    r"#\s*noqa(?P<scoped>:\s*(?P<ids>[A-Za-z0-9_./-]+(?:\s*,\s*[A-Za-z0-9_./-]+)*))?"
)


# ----------------------------------------------------------------- rule model


@dataclass(frozen=True)
class Rule:
    """One named invariant. `explain` is what `--explain <id>` prints: enough
    for a failing CI line to be actionable without opening the analyzer."""

    id: str
    summary: str  # one line, shown in --list-rules and findings
    explain: str  # paragraph(s): rationale + how to fix + how to suppress


_RULES: Dict[str, Rule] = {}


def register_rule(id: str, summary: str, explain: str) -> Rule:
    if id in _RULES:
        raise ValueError(f"duplicate rule id {id!r}")
    r = Rule(id=id, summary=summary, explain=explain.strip())
    _RULES[id] = r
    return r


def all_rules() -> Dict[str, Rule]:
    return dict(_RULES)


def rule_exists(rule_id: str) -> bool:
    return rule_id in _RULES


# the meta rules live here because core owns the suppression/baseline grammar
register_rule(
    "noqa/blanket",
    "bare `# noqa` without a rule id",
    """
A suppression that names no rule waives every current AND future check on its
line — nobody can tell which finding it was written for, so it can never be
safely removed. Scope it: `# noqa: <rule-id>` (comma-separate several ids).
Run `--list-rules` for the catalog.
""",
)
register_rule(
    "noqa/unknown-rule",
    "`# noqa: <id>` names a rule the registry doesn't know",
    """
The rule id in this suppression doesn't exist (typo, or a rule that was
renamed/retired). An unknown id suppresses nothing, so the comment is dead
weight that READS like a waiver. Fix the id (`--list-rules`) or delete the
comment.
""",
)
register_rule(
    "noqa/unused",
    "scoped `# noqa: <id>` suppresses nothing on its line",
    """
No finding of the named rule fires on this line, so the suppression is dead.
Dead suppressions rot: they survive refactors, migrate onto unrelated code,
and silently waive the rule if the hazard ever comes back somewhere else on
the line. Delete the comment (keep any prose as a plain comment).
""",
)
register_rule(
    "baseline/stale",
    "baseline entry matches no live finding",
    """
A grandfathered finding recorded in the baseline file no longer occurs — the
code was fixed or deleted. Remove the entry (re-run with --write-baseline, or
edit tools/analysis/baseline.json) so the baseline only ever shrinks and a
REINTRODUCED finding can't hide behind a stale entry.
""",
)


# ---------------------------------------------------------------- module index


@dataclass
class Noqa:
    line: int
    rule_ids: Tuple[str, ...]  # empty tuple == a bare (blanket) directive
    used: Set[str] = field(default_factory=set)


@dataclass
class ModuleInfo:
    path: Path  # absolute
    rel: str  # repo-root-relative, '/'-separated
    name: Optional[str]  # dotted module name ('' parts stripped), None for scripts
    src: str
    lines: List[str]
    tree: Optional[ast.AST]  # None when the file doesn't parse
    parse_error: Optional[str]
    noqa: Dict[int, Noqa]

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


def _scan_noqa(src: str, lines: Sequence[str]) -> Dict[int, Noqa]:
    """noqa directives from REAL comment tokens only — a `# noqa` mentioned
    inside a docstring or string literal (rule explanations, documentation of
    the grammar itself) neither suppresses nor counts as a directive. Falls
    back to a raw line scan when the file doesn't tokenize."""
    out: Dict[int, Noqa] = {}
    if "noqa" not in src:
        return out

    def _add(lineno: int, comment: str) -> None:
        m = _NOQA_RE.search(comment)
        if not m:
            return
        ids: Tuple[str, ...] = ()
        if m.group("scoped"):
            ids = tuple(s.strip() for s in m.group("ids").split(",") if s.strip())
        out[lineno] = Noqa(line=lineno, rule_ids=ids)

    import io
    import tokenize

    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type != tokenize.COMMENT or "noqa" not in tok.string:
                continue
            # a directive is a TRAILING comment on a code line; `# noqa`
            # prose on a comment-only line (module headers documenting the
            # grammar) is neither a suppression nor a finding
            lineno, col = tok.start
            before = lines[lineno - 1][:col] if lineno <= len(lines) else ""
            if before.strip():
                _add(lineno, tok.string)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        for i, line in enumerate(lines, 1):
            if "noqa" in line and line.split("#", 1)[0].strip():
                _add(i, line)
    return out


def _module_name(rel: str) -> Optional[str]:
    if not rel.endswith(".py"):
        return None
    parts = rel[: -len(".py")].split("/")
    if parts[-1] == "__init__":
        parts = parts[:-1]
    if not parts:
        return None
    return ".".join(parts)


class ProjectIndex:
    """The single shared parse: every target file read and ast-parsed exactly
    once, keyed by repo-relative path and by dotted module name."""

    def __init__(self, root: Path, targets: Sequence[str] = DEFAULT_TARGETS):
        self.root = Path(root).resolve()
        self.targets = tuple(targets)
        self.files: List[ModuleInfo] = []
        self.by_rel: Dict[str, ModuleInfo] = {}
        self.by_module: Dict[str, ModuleInfo] = {}
        self._parse_all()

    def _iter_paths(self) -> Iterable[Path]:
        for t in self.targets:
            p = self.root / t
            if p.is_file():
                yield p
            elif p.is_dir():
                for f in sorted(p.rglob("*.py")):
                    if "__pycache__" in f.parts:
                        continue
                    yield f

    def _parse_all(self) -> None:
        for path in self._iter_paths():
            rel = path.relative_to(self.root).as_posix()
            src = path.read_text()
            lines = src.splitlines()
            tree: Optional[ast.AST] = None
            err: Optional[str] = None
            try:
                tree = ast.parse(src)
            except SyntaxError as e:
                err = f"line {e.lineno}: {e.msg}"
            info = ModuleInfo(
                path=path,
                rel=rel,
                name=_module_name(rel),
                src=src,
                lines=lines,
                tree=tree,
                parse_error=err,
                noqa=_scan_noqa(src, lines),
            )
            self.files.append(info)
            self.by_rel[rel] = info
            if info.name:
                self.by_module[info.name] = info

    def read_text(self, rel: str) -> Optional[str]:
        """Non-python corpus files (docs, shell) for the metric-contract pass;
        cached so repeated rule access stays one read."""
        cache = getattr(self, "_text_cache", None)
        if cache is None:
            cache = self._text_cache = {}
        if rel not in cache:
            p = self.root / rel
            cache[rel] = p.read_text() if p.is_file() else None
        return cache[rel]


# ------------------------------------------------------------------- findings


@dataclass
class Finding:
    rule: str
    rel: str
    line: int
    message: str
    line_text: str = ""
    baselined: bool = False

    @property
    def fingerprint(self) -> str:
        """Stable identity across line renumbering: rule + file + the exact
        (whitespace-stripped) source line the finding points at."""
        return f"{self.rule}::{self.rel}::{self.line_text.strip()}"

    def render(self) -> str:
        return f"{self.rel}:{self.line}: [{self.rule}] {self.message}"

    def as_json(self) -> Dict[str, Any]:
        return {
            "rule": self.rule,
            "file": self.rel,
            "line": self.line,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }


class AnalysisContext:
    """What a pass sees: the index plus an emit() that applies the scoped
    suppression grammar centrally (passes never parse noqa themselves)."""

    def __init__(self, index: ProjectIndex):
        self.index = index
        self.findings: List[Finding] = []
        # populated lazily by passes that share the call graph
        self.shared: Dict[str, Any] = {}

    def emit(
        self,
        rule: str,
        module: ModuleInfo,
        lineno: int,
        message: str,
        noqa_lines: Optional[Sequence[int]] = None,
    ) -> None:
        """Record a finding unless a scoped noqa with this rule id sits on the
        finding line (or one of `noqa_lines`, for multi-line constructs)."""
        if rule not in _RULES:
            raise ValueError(f"pass emitted unregistered rule {rule!r}")
        for ln in list(noqa_lines or ()) + [lineno]:
            nq = module.noqa.get(ln)
            if nq is not None and rule in nq.rule_ids:
                nq.used.add(rule)
                return
        self.findings.append(
            Finding(
                rule=rule,
                rel=module.rel,
                line=lineno,
                message=message,
                line_text=module.line_text(lineno),
            )
        )


# ------------------------------------------------------------------- baseline


def load_baseline(path: Path) -> Dict[str, str]:
    """fingerprint -> one-line justification. Missing file == empty baseline."""
    if not path.is_file():
        return {}
    doc = json.loads(path.read_text())
    entries = doc.get("entries", {})
    return {str(k): str(v) for k, v in entries.items()}

def write_baseline(path: Path, findings: Sequence[Finding],
                   justifications: Optional[Dict[str, str]] = None) -> None:
    entries = {}
    for f in sorted(findings, key=lambda f: f.fingerprint):
        just = (justifications or {}).get(
            f.fingerprint, "grandfathered by --write-baseline; justify or fix"
        )
        entries[f.fingerprint] = just
    path.write_text(
        json.dumps(
            {
                "comment": (
                    "Grandfathered analyzer findings (tools/analysis). Keyed by "
                    "rule::file::stripped-source-line; values are one-line "
                    "justifications. Entries may only be removed (by fixing the "
                    "finding) — a stale entry is itself a finding "
                    "(baseline/stale). The purity/* section of this file must "
                    "stay EMPTY: trace-purity findings are fixed, never waived."
                ),
                "entries": entries,
            },
            indent=2,
            sort_keys=False,
        )
        + "\n"
    )


# ------------------------------------------------------------------ the driver

PassFn = Callable[[AnalysisContext], None]
_PASSES: List[Tuple[str, PassFn]] = []


def register_pass(name: str) -> Callable[[PassFn], PassFn]:
    def deco(fn: PassFn) -> PassFn:
        _PASSES.append((name, fn))
        return fn

    return deco


def _meta_noqa_pass(ctx: AnalysisContext) -> None:
    """Runs AFTER every rule pass: judge the suppressions themselves."""
    for mod in ctx.index.files:
        for nq in mod.noqa.values():
            if not nq.rule_ids:
                ctx.emit(
                    "noqa/blanket",
                    mod,
                    nq.line,
                    "bare `# noqa` — scope it to a rule id "
                    "(`# noqa: <rule-id>`; see --list-rules)",
                )
                continue
            for rid in nq.rule_ids:
                if not rule_exists(rid):
                    ctx.emit(
                        "noqa/unknown-rule",
                        mod,
                        nq.line,
                        f"`# noqa: {rid}` names an unknown rule id "
                        "(see --list-rules)",
                    )
                elif rid not in nq.used:
                    ctx.emit(
                        "noqa/unused",
                        mod,
                        nq.line,
                        f"`# noqa: {rid}` suppresses nothing on this line — "
                        "delete the dead suppression",
                    )


def run_analysis(
    root: Path,
    targets: Sequence[str] = DEFAULT_TARGETS,
    baseline_path: Optional[Path] = None,
    only_passes: Optional[Set[str]] = None,
) -> Dict[str, Any]:
    """Run every registered pass over one shared index; returns the report
    dict (also the --json payload). Import of the pass modules is the caller's
    job (tools.analysis.__init__ pulls them all in)."""
    import time as _time

    t0 = _time.perf_counter()
    index = ProjectIndex(Path(root), targets)
    ctx = AnalysisContext(index)
    for name, fn in _PASSES:
        if only_passes is not None and name not in only_passes:
            continue
        fn(ctx)
    if only_passes is None or "noqa" in (only_passes or {"noqa"}):
        _meta_noqa_pass(ctx)

    baseline = load_baseline(baseline_path) if baseline_path else {}
    live: List[Finding] = []
    matched: Set[str] = set()
    for f in ctx.findings:
        fp = f.fingerprint
        if fp in baseline:
            f.baselined = True
            matched.add(fp)
        else:
            live.append(f)
    for fp in sorted(set(baseline) - matched):
        rule, rel, _ = fp.split("::", 2)
        mod = index.by_rel.get(rel)
        if mod is None:
            # the whole file is gone; report against the baseline itself
            try:
                rel_b = Path(baseline_path).resolve().relative_to(
                    index.root
                ).as_posix()
            except (ValueError, TypeError):
                rel_b = str(baseline_path)
            live.append(Finding("baseline/stale", rel_b, 1,
                                f"entry {fp!r} matches no live finding"))
        else:
            live.append(
                Finding("baseline/stale", rel, 1,
                        f"entry {fp!r} matches no live finding — remove it")
            )

    live.sort(key=lambda f: (f.rel, f.line, f.rule))
    elapsed = _time.perf_counter() - t0
    return {
        "root": str(index.root),
        "files_analyzed": len(index.files),
        "elapsed_s": round(elapsed, 3),
        "findings": [f.as_json() for f in live],
        "baselined": sorted(matched),
        "ok": not live,
        "_finding_objs": live,  # stripped before JSON serialization
        "_index": index,
    }
