#
# CLI for the static-analysis plane. CI tier 0 runs:
#
#     python -m tools.analysis --max-seconds 10 --out analysis_report.json
#
# Subcommands for humans:
#     --list-rules           rule catalog (id + one-line summary)
#     --explain <rule-id>    full rationale + fix + suppression guidance
#     --json                 machine-readable findings on stdout
#     --write-baseline       grandfather the current findings (purity/* is
#                            refused: stale-bake hazards are fixed, not waived)
#
# Exit codes: 0 clean, 1 findings (or wall-clock budget exceeded), 2 usage.
#

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from . import DEFAULT_BASELINE, DEFAULT_TARGETS, all_rules, run_analysis

_ROOT = Path(__file__).resolve().parent.parent.parent


def _list_rules() -> int:
    rules = all_rules()
    width = max(len(r) for r in rules)
    for rid in sorted(rules):
        print(f"{rid:<{width}}  {rules[rid].summary}")
    return 0


def _explain(rule_id: str) -> int:
    rules = all_rules()
    r = rules.get(rule_id)
    if r is None:
        print(f"unknown rule id {rule_id!r}; run --list-rules", file=sys.stderr)
        return 2
    print(f"{r.id} — {r.summary}\n")
    print(r.explain)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.analysis",
        description="whole-program invariant analyzer (docs/design.md §6j)",
    )
    ap.add_argument("targets", nargs="*",
                    help=f"analysis roots relative to the repo root "
                         f"(default: {' '.join(DEFAULT_TARGETS)})")
    ap.add_argument("--root", default=str(_ROOT),
                    help="repo root (default: this checkout)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--out", metavar="PATH",
                    help="also write the JSON report to PATH")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help=f"baseline file (default: <root>/{DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (show grandfathered findings)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings into the baseline and exit 0")
    ap.add_argument("--max-seconds", type=float, default=None,
                    help="fail if the run exceeds this wall-clock budget")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--explain", metavar="RULE_ID")
    args = ap.parse_args(argv)

    if args.list_rules:
        return _list_rules()
    if args.explain:
        return _explain(args.explain)

    root = Path(args.root).resolve()
    baseline = None
    if not args.no_baseline:
        baseline = Path(args.baseline) if args.baseline else root / DEFAULT_BASELINE
    targets = tuple(args.targets) if args.targets else DEFAULT_TARGETS

    report = run_analysis(root, targets=targets, baseline_path=baseline)
    findings = report.pop("_finding_objs")
    report.pop("_index")

    if args.write_baseline:
        from .core import load_baseline, write_baseline

        target = baseline or root / DEFAULT_BASELINE
        purity = [f for f in findings if f.rule.startswith("purity/")]
        if purity:
            print(
                f"refusing --write-baseline: {len(purity)} purity/* "
                "finding(s) present — trace-purity hazards are fixed, never "
                "grandfathered:"
            )
            for f in purity:
                print("  " + f.render())
            return 1
        keep = [f for f in findings if not f.rule.startswith("baseline/")]
        old = load_baseline(target)
        write_baseline(target, keep, justifications=old)
        print(f"baseline written: {len(keep)} entr(y/ies) -> {target}")
        return 0

    if args.out:
        Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        n = len(findings)
        if n:
            print(f"ANALYSIS: {n} finding(s) in {report['files_analyzed']} "
                  f"files ({report['elapsed_s']}s)")
            for f in findings:
                print("  " + f.render())
            print("\nrun `python -m tools.analysis --explain <rule-id>` for "
                  "rationale and fixes; scoped suppression: "
                  "`# noqa: <rule-id>`")
        else:
            nb = len(report.get("baselined", []))
            print(
                f"ANALYSIS OK: {report['files_analyzed']} files clean in "
                f"{report['elapsed_s']}s"
                + (f" ({nb} baselined)" if nb else "")
            )

    rc = 0 if not findings else 1
    if args.max_seconds is not None and report["elapsed_s"] > args.max_seconds:
        print(
            f"ANALYSIS BUDGET EXCEEDED: {report['elapsed_s']}s > "
            f"{args.max_seconds}s (the shared-parse budget; did a pass "
            "start re-reading files?)"
        )
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
