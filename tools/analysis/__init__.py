#
# Whole-program static analysis plane (docs/design.md §6j): one shared AST
# parse + module index per run, a rule registry with stable IDs, scoped
# `# noqa: <rule-id>` suppression, a checked-in baseline for grandfathered
# findings, and four pass families:
#
#   fences/*  + hygiene/*  — the ci/lint_python.py checks, migrated
#   purity/*               — trace-purity (host-wrapper discipline)
#   locks/*                — lock-order cycles + blocking under hot locks
#   metrics/*              — metric emission/consumption contract
#
# Run `python -m tools.analysis` (CI tier 0), `--list-rules`, or
# `--explain <rule-id>`.
#

# importing the pass modules registers their rules and passes (__init__ is
# exempt from the unused-import check: dynamic re-export module)
from . import fences as _fences
from . import purity as _purity
from . import locks as _locks
from . import metrics as _metrics
from .core import (
    DEFAULT_BASELINE,
    DEFAULT_TARGETS,
    AnalysisContext,
    Finding,
    ProjectIndex,
    all_rules,
    run_analysis,
)

__all__ = [
    "AnalysisContext",
    "DEFAULT_BASELINE",
    "DEFAULT_TARGETS",
    "Finding",
    "ProjectIndex",
    "all_rules",
    "run_analysis",
]
