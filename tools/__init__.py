# Repo tooling namespace (static analysis plane lives in tools/analysis).
