#!/usr/bin/env python
"""Bench regression gate: compare per-scenario wall times across the two newest
recorded benchmark rounds and fail on a >25% regression.

Inputs are the repo's recorded bench artifacts:

  * `BENCH_r*.json` — driver-captured rounds. Each holds the bench.py JSON line
    (sometimes only as a truncated stdout `tail`), whose `secondary` carries one
    `<scenario>_bench_secs` wall time per benchmark unit (bench.py flushes one
    per completed unit). Scenario times are extracted by regex over the raw
    file text, so a truncated tail still yields every scenario it mentions.
  * `BENCH_TPU_SESSION*.json` — real-TPU session captures, same extraction;
    included when present so a TPU-vs-TPU comparison uses real numbers.

Rules:
  * Only rounds measured on the SAME platform compare (a cpu-fallback round vs
    a TPU round is tunnel health, not a regression) — mismatches report and
    pass.
  * A scenario regresses when `new > old * (1 + threshold)`; default threshold
    0.25. Scenarios present in only one round are listed, never failed on.
  * Exit 1 on any regression — unless SRML_BENCH_CHECK_ADVISORY=1, which
    prints the same per-scenario table and always exits 0. ci/test.sh wires
    this gate in as an ADVISORY tier (wall times vary with tunnel health);
    export SRML_BENCH_CHECK_ADVISORY=0 to enforce it strictly.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

DEFAULT_THRESHOLD = 0.25

# optional backslashes before the quotes: inside an artifact whose wrapper JSON
# is truncated (unparseable), the bench line's quotes appear escaped (\") and
# the regex must still sweep the raw text
_SECS_RE = re.compile(r'\\?"(\w+)_bench_secs\\?"\s*:\s*([0-9]+(?:\.[0-9]+)?)')
# selection-plane stage times (bench_knn/bench_ann emit `<unit>_select_s`):
# gated like scenario wall times so a selection regression can't hide inside
# a unit whose total time moved for other reasons
_SELECT_RE = re.compile(r'\\?"(\w+)_select_s\\?"\s*:\s*([0-9]+(?:\.[0-9]+)?)')
# measured MFU per scenario (`<unit>_mfu`, observability/device.py): gated
# DIRECTION-AWARE — mfu is higher-is-better, unlike every wall-time key
_MFU_RE = re.compile(
    r'\\?"(\w+_mfu)\\?"\s*:\s*([0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?)'
)
# communication plane (`<unit>_comm_frac` / `<unit>_rank_skew`,
# observability/comm.py §6h): both lower-is-better like wall times — a rising
# comm_frac means the scenario spends more of its window on the interconnect,
# a rising rank_skew means the barrier is waiting longer on its slowest rank
_COMM_RE = re.compile(
    r'\\?"(\w+_(?:comm_frac|rank_skew))\\?"\s*:\s*'
    r"([0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?)"
)
# live-telemetry overhead (`telemetry_overhead_pct`, §6g): gated against an
# ABSOLUTE budget (default <2%), not a round-over-round ratio — the value sits
# near zero, where ratios of two small noisy numbers are meaningless
_OVERHEAD_RE = re.compile(
    r'\\?"(\w+_overhead_pct)\\?"\s*:\s*(-?[0-9]+(?:\.[0-9]+)?)'
)
# serving plane (`serving_p99_ms` / `serving_failover_p99_ms`, serving/
# design §7/§7c): tail latency of the closed-loop scenarios — lower-is-better
# like wall times, but behind an ABSOLUTE noise floor (see _NOISE_FLOORS:
# single-digit-ms CPU tails are scheduler jitter; ratio-judging two jitter
# samples is noise)
_SERVING_P99_RE = re.compile(
    r'\\?"(serving\w*_p99_ms)\\?"\s*:\s*'
    r"([0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?)"
)
# failover-fleet CONTRACT keys (serving/fleet.py, §7c): judged against
# absolute invariants on the NEWEST artifact carrying them — a mid-run
# replica kill must lose zero requests, the restarted replica must rejoin
# with zero compiles, and fault-window throughput must hold >= the frac
# floor of the no-fault baseline. Never ratio-judged: the contract either
# holds or the fleet is broken.
_FAILOVER_RE = re.compile(
    r'\\?"(serving_failover_(?:failed_requests|rejoin_compiles|qps_frac))'
    r'\\?"\s*:\s*(-?[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?)'
)
DEFAULT_FAILOVER_QPS_FRAC_MIN = 0.8
# autotune plane (`autotune_speedup`, docs/design.md §6i): tuned-vs-default
# ratio of the better-tuned unit — HIGHER is better like mfu, behind an
# absolute noise floor (both rounds hovering at ~1.0 means the table holds
# no real win on this platform; ratio-judging two 1.0-ish samples is noise —
# the gate only engages once a round has shown a genuine tuned win)
_SPEEDUP_RE = re.compile(
    r'\\?"(\w+_speedup)\\?"\s*:\s*([0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?)'
)
# ANN lifecycle plane (`ann_build_rows_per_s`, docs/design.md §7b): pipelined
# out-of-core build throughput — HIGHER is better like mfu (the ISSUE-15 gate:
# pipelined build must not fall back under the serial baseline's rate). The
# regex anchors on the exact `_rows_per_s` suffix, so the legacy
# `*_rows_per_sec_per_chip` keys never match
_ROWS_PER_S_RE = re.compile(
    r'\\?"(\w+_rows_per_s)\\?"\s*:\s*([0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?)'
)
# zero-copy ingest plane (`ingest_gb_per_s_per_chip`, docs/design.md §6k):
# streamed host->device ingest bandwidth of the single-pass moments fit —
# HIGHER is better like mfu. The exact `_gb_per_s_per_chip` suffix anchors
# the match so no wall-time key can collide
_GBPS_RE = re.compile(
    r'\\?"(\w+_gb_per_s_per_chip)\\?"\s*:\s*'
    r"([0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?)"
)
# measurement-noise companion (`*_overhead_noise_pct`, the MAD of the
# scenario's pair deltas): when the noise floor reaches the budget the point
# estimate carries no signal, so the check reports INCONCLUSIVE instead of
# flagging scheduler jitter as a regression
_OVERHEAD_NOISE_RE = re.compile(
    r'\\?"(\w+_overhead_noise_pct)\\?"\s*:\s*(-?[0-9]+(?:\.[0-9]+)?)'
)
DEFAULT_OVERHEAD_BUDGET_PCT = 2.0
_PLATFORM_RE = re.compile(r'\\?"platform\\?"\s*:\s*\\?"(\w+)\\?"')


def _higher_is_better(name: str) -> bool:
    return name.endswith(
        ("_mfu", "_speedup", "_rows_per_s", "_gb_per_s_per_chip")
    )


# absolute noise floors for the comm keys: near zero (CPU-mesh comm_frac sits
# at ~1e-6) a round-over-round ratio compares two noise samples — the same
# rationale as the telemetry-overhead absolute budget above. Values are only
# ratio-judged once EITHER round clears the floor.
_NOISE_FLOORS = (
    ("_comm_frac", 0.01),  # <1% of ICI peak: noise, not a communication story
    ("_rank_skew", 1.5),   # below the straggler threshold: balanced enough
    ("_p99_ms", 5.0),      # single-digit-ms serving tails: scheduler jitter
    ("_speedup", 1.1),     # tuned ~= default on both rounds: nothing to lose
)


def _below_noise_floor(name: str, old: float, new: float) -> bool:
    for suffix, floor in _NOISE_FLOORS:
        if name.endswith(suffix):
            return max(old, new) < floor
    return False


def _round_key(path: str) -> Tuple[int, str]:
    m = re.search(r"BENCH_r(\d+)\.json$", path)
    return (int(m.group(1)) if m else -1, path)


def discover(root: str) -> List[str]:
    """Newest-last list of comparable bench artifacts: all BENCH_r*.json by
    round number, then any BENCH_TPU_SESSION*.json (by name) as the most
    trusted real-hardware captures."""
    rounds = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")), key=_round_key)
    sessions = sorted(glob.glob(os.path.join(root, "BENCH_TPU_SESSION*.json")))
    return rounds + sessions


def extract(path: str) -> Dict[str, object]:
    """Scenario wall times + platform of one bench artifact. Prefers the
    structured `parsed.secondary` when the file carries one; falls back to a
    regex sweep of the raw text (the stdout tail can be truncated mid-line)."""
    with open(path) as f:
        raw = f.read()
    scenarios: Dict[str, float] = {}
    overheads: Dict[str, float] = {}
    overhead_noise: Dict[str, float] = {}
    failover: Dict[str, float] = {}
    platform: Optional[str] = None
    try:
        doc = json.loads(raw)
    except json.JSONDecodeError:
        doc = {}
    parsed = doc.get("parsed") if isinstance(doc, dict) else None
    secondary = (parsed or {}).get("secondary") or {}
    for k, v in secondary.items():
        if k.endswith("_bench_secs") and isinstance(v, (int, float)):
            scenarios[k[: -len("_bench_secs")]] = float(v)
        elif k.endswith("_select_s") and isinstance(v, (int, float)):
            scenarios[k[: -len("_s")]] = float(v)
        elif k.endswith("_mfu") and isinstance(v, (int, float)):
            scenarios[k] = float(v)  # keeps the _mfu suffix: direction marker
        elif k.endswith(("_comm_frac", "_rank_skew")) and isinstance(
            v, (int, float)
        ):
            scenarios[k] = float(v)  # comm plane: lower-is-better default
        elif k.startswith("serving") and k.endswith("_p99_ms") \
                and isinstance(v, (int, float)):
            scenarios[k] = float(v)  # serving tail: lower-is-better + floor
        elif k.startswith("serving_failover_") and k.split("_", 2)[-1] in (
            "failed_requests", "rejoin_compiles", "qps_frac"
        ) and isinstance(v, (int, float)):
            failover[k] = float(v)  # absolute contract keys, never ratios
        elif k.endswith("_speedup") and isinstance(v, (int, float)):
            scenarios[k] = float(v)  # autotune plane: higher-is-better + floor
        elif k.endswith("_rows_per_s") and isinstance(v, (int, float)):
            scenarios[k] = float(v)  # ann build throughput: higher-is-better
        elif k.endswith("_gb_per_s_per_chip") and isinstance(v, (int, float)):
            scenarios[k] = float(v)  # ingest bandwidth: higher-is-better
        elif k.endswith("_overhead_noise_pct") and isinstance(v, (int, float)):
            overhead_noise[k[: -len("_noise_pct")] + "_pct"] = float(v)
        elif k.endswith("_overhead_pct") and isinstance(v, (int, float)):
            overheads[k] = float(v)  # absolute-budget check, never a ratio
    if isinstance(secondary.get("platform"), str):
        platform = secondary["platform"]
    # fall back to regex over DECODED text: inside the artifact the bench line
    # usually lives in the `tail` string field, where every quote is escaped —
    # scanning the raw file would miss it
    texts = [raw]
    if isinstance(doc, dict) and isinstance(doc.get("tail"), str):
        texts.insert(0, doc["tail"])
    for text in texts:
        if scenarios:
            break
        for name, secs in _SECS_RE.findall(text):
            scenarios[name] = float(secs)
        for name, secs in _SELECT_RE.findall(text):
            scenarios[f"{name}_select"] = float(secs)
        for name, v in _MFU_RE.findall(text):
            scenarios[name] = float(v)
        for name, v in _COMM_RE.findall(text):
            scenarios[name] = float(v)
        for name, v in _SERVING_P99_RE.findall(text):
            scenarios[name] = float(v)
        for name, v in _FAILOVER_RE.findall(text):
            failover[name] = float(v)
        for name, v in _SPEEDUP_RE.findall(text):
            scenarios[name] = float(v)
        for name, v in _ROWS_PER_S_RE.findall(text):
            scenarios[name] = float(v)
        for name, v in _GBPS_RE.findall(text):
            scenarios[name] = float(v)
        for name, v in _OVERHEAD_NOISE_RE.findall(text):
            overhead_noise[name[: -len("_noise_pct")] + "_pct"] = float(v)
        for name, v in _OVERHEAD_RE.findall(text):
            overheads[name] = float(v)
    if platform is None:
        for text in texts:
            m = _PLATFORM_RE.findall(text)
            if m:
                platform = m[-1]
                break
    return {
        "path": path,
        "name": os.path.basename(path),
        "platform": platform,
        "scenarios": scenarios,
        "overheads": overheads,
        "overhead_noise": overhead_noise,
        "failover": failover,
    }


def compare(old: Dict[str, object], new: Dict[str, object],
            threshold: float = DEFAULT_THRESHOLD) -> List[Dict[str, object]]:
    """Per-scenario comparison rows, worst regression first."""
    rows: List[Dict[str, object]] = []
    old_s: Dict[str, float] = old["scenarios"]  # type: ignore[assignment]
    new_s: Dict[str, float] = new["scenarios"]  # type: ignore[assignment]
    for name in sorted(set(old_s) | set(new_s)):
        o, n = old_s.get(name), new_s.get(name)
        if o is None or n is None:
            rows.append({"scenario": name, "old_s": o, "new_s": n,
                         "ratio": None, "verdict": "only-one-round"})
            continue
        ratio = n / o if o > 0 else float("inf")
        if _below_noise_floor(name, o, n):
            rows.append({"scenario": name, "old_s": o, "new_s": n,
                         "ratio": ratio, "verdict": "ok (below noise floor)"})
            continue
        if _higher_is_better(name):
            # mfu: new/old BELOW 1-threshold is the regression; above is the win
            verdict = "REGRESSED" if ratio < 1.0 - threshold else (
                "improved" if ratio > 1.0 + threshold else "ok"
            )
        else:
            verdict = "REGRESSED" if ratio > 1.0 + threshold else (
                "improved" if ratio < 1.0 - threshold else "ok"
            )
        rows.append({"scenario": name, "old_s": o, "new_s": n,
                     "ratio": ratio, "verdict": verdict})
    rows.sort(key=lambda r: -(r["ratio"] or 0.0))
    return rows


def render_table(rows: List[Dict[str, object]]) -> str:
    lines = [f"{'scenario':<22} {'old_s':>9} {'new_s':>9} {'ratio':>7}  verdict"]
    for r in rows:
        o = f"{r['old_s']:.1f}" if r["old_s"] is not None else "-"
        n = f"{r['new_s']:.1f}" if r["new_s"] is not None else "-"
        ratio = f"{r['ratio']:.2f}" if r["ratio"] is not None else "-"
        lines.append(
            f"{r['scenario']:<22} {o:>9} {n:>9} {ratio:>7}  {r['verdict']}"
        )
    return "\n".join(lines)


def check_overheads(artifacts: List[Dict[str, object]],
                    advisory: bool = False) -> int:
    """Absolute-budget check for `*_overhead_pct` keys (live-telemetry plane,
    §6g): the NEWEST artifact carrying one is held to the budget (default
    <2%, env SRML_TELEMETRY_OVERHEAD_MAX). One artifact suffices — this is a
    contract check, not a round-over-round comparison."""
    budget = float(os.environ.get(
        "SRML_TELEMETRY_OVERHEAD_MAX", str(DEFAULT_OVERHEAD_BUDGET_PCT)
    ))
    with_overhead = [a for a in artifacts if a.get("overheads")]
    if not with_overhead:
        return 0
    newest = with_overhead[-1]
    noise_by_key = newest.get("overhead_noise") or {}
    n_over = 0
    for name, pct in sorted(newest["overheads"].items()):  # type: ignore[union-attr]
        noise = noise_by_key.get(name)  # type: ignore[union-attr]
        if noise is not None and noise >= budget:
            # the noise floor reached the budget: the point estimate is
            # scheduler jitter, not signal — report, don't judge
            print(
                f"bench_check: {name} = {pct:.2f}% "
                f"(budget {budget:.1f}%, noise ±{noise:.2f}%, {newest['name']})"
                "  INCONCLUSIVE (measurement noise >= budget)"
            )
            continue
        over = pct > budget
        n_over += int(over)
        print(
            f"bench_check: {name} = {pct:.2f}% "
            f"(budget {budget:.1f}%, {newest['name']})"
            + ("  OVER BUDGET" if over else "  ok")
        )
    if n_over and advisory:
        print(
            f"bench_check: ADVISORY — {n_over} overhead key(s) over budget; "
            "not failing (SRML_BENCH_CHECK_ADVISORY=1; set 0 to enforce)"
        )
        return 0
    return n_over


def check_failover(artifacts: List[Dict[str, object]],
                   advisory: bool = False) -> int:
    """Absolute contract check for the failover-fleet keys (serving/fleet.py,
    §7c) on the NEWEST artifact carrying them: a mid-run replica kill must
    lose ZERO requests, the restarted replica must rejoin with ZERO compiles,
    and fault-window qps must hold >= the frac floor (default 0.8, env
    SRML_FAILOVER_QPS_FRAC_MIN) of the no-fault baseline. One artifact
    suffices — the contract either holds or the fleet is broken."""
    frac_min = float(os.environ.get(
        "SRML_FAILOVER_QPS_FRAC_MIN", str(DEFAULT_FAILOVER_QPS_FRAC_MIN)
    ))
    with_failover = [a for a in artifacts if a.get("failover")]
    if not with_failover:
        return 0
    newest = with_failover[-1]
    fo: Dict[str, float] = newest["failover"]  # type: ignore[assignment]
    n_bad = 0
    checks = (
        ("serving_failover_failed_requests", lambda v: v == 0, "== 0"),
        ("serving_failover_rejoin_compiles", lambda v: v == 0, "== 0"),
        ("serving_failover_qps_frac", lambda v: v >= frac_min,
         f">= {frac_min:g}"),
    )
    for name, ok_fn, want in checks:
        v = fo.get(name)
        if v is None:
            continue  # a truncated tail may carry only some of the keys
        ok = ok_fn(v)
        n_bad += int(not ok)
        print(
            f"bench_check: {name} = {v:g} (want {want}, {newest['name']})"
            + ("  ok" if ok else "  CONTRACT VIOLATED")
        )
    if n_bad and advisory:
        print(
            f"bench_check: ADVISORY — {n_bad} failover contract key(s) "
            "violated; not failing (SRML_BENCH_CHECK_ADVISORY=1; set 0 to "
            "enforce)"
        )
        return 0
    return n_bad


def _verdict(overhead_failures: int, failover_failures: int = 0) -> int:
    """Final exit verdict for paths that skipped the wall-time comparison:
    the log's LAST line must agree with the exit code, so an overhead or
    failover failure reported pages earlier is restated here."""
    if overhead_failures or failover_failures:
        parts = []
        if overhead_failures:
            parts.append(
                f"{overhead_failures} telemetry-overhead key(s) over budget"
            )
        if failover_failures:
            parts.append(
                f"{failover_failures} failover contract key(s) violated"
            )
        print(f"bench_check: FAIL — {'; '.join(parts)} (see lines above)")
        return 1
    print("bench_check: OK")
    return 0


def check(root: str, threshold: float = DEFAULT_THRESHOLD,
          advisory: bool = False) -> int:
    artifacts = [extract(p) for p in discover(root)]
    overhead_failures = check_overheads(artifacts, advisory=advisory)
    failover_failures = check_failover(artifacts, advisory=advisory)
    artifacts = [a for a in artifacts if a["scenarios"]]
    if len(artifacts) < 2:
        print(
            "bench_check: fewer than two bench artifacts carry per-scenario "
            f"wall times ({len(artifacts)} found) — skipping wall-time "
            "comparison."
        )
        return _verdict(overhead_failures, failover_failures)
    old, new = artifacts[-2], artifacts[-1]
    print(
        f"bench_check: comparing {old['name']} (platform={old['platform']}) "
        f"-> {new['name']} (platform={new['platform']}), "
        f"threshold +{threshold:.0%}"
    )
    if old["platform"] != new["platform"]:
        print(
            "bench_check: platform mismatch — wall times are not comparable "
            "across backends (tunnel health, not code); skipping wall-time "
            "comparison."
        )
        return _verdict(overhead_failures, failover_failures)
    rows = compare(old, new, threshold)
    print(render_table(rows))
    regressed = [r for r in rows if r["verdict"] == "REGRESSED"]
    if not regressed:
        print("bench_check: no scenario regressed beyond the threshold")
        return _verdict(overhead_failures, failover_failures)
    names = ", ".join(r["scenario"] for r in regressed)
    if advisory:
        print(
            f"bench_check: ADVISORY — {len(regressed)} scenario(s) regressed "
            f">{threshold:.0%} ({names}); not failing "
            "(SRML_BENCH_CHECK_ADVISORY=1; set 0 to enforce)"
        )
        return 0  # advisory covers overhead failures too (already reported)
    print(
        f"bench_check: FAIL — {len(regressed)} scenario(s) regressed "
        f">{threshold:.0%}: {names}"
    )
    return 1


def main(argv: List[str]) -> int:
    root = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(os.path.abspath(__file__)), os.pardir
    )
    threshold = float(os.environ.get("SRML_BENCH_CHECK_THRESHOLD",
                                     str(DEFAULT_THRESHOLD)))
    advisory = os.environ.get("SRML_BENCH_CHECK_ADVISORY", "") == "1"
    return check(os.path.abspath(root), threshold=threshold, advisory=advisory)


if __name__ == "__main__":
    sys.exit(main(sys.argv))
