#!/usr/bin/env bash
#
# CI entry point (role of reference ci/test.sh:20-57: pre-merge = unit tests + small
# benchmark run; nightly adds --runslow).
#
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8"
export PALLAS_AXON_POOL_IPS=""

MODE="${1:-premerge}"

# analysis tier (tools/analysis, docs/design.md §6j — supersedes the flat
# lint): ONE whole-program analyzer runs the migrated fences + hygiene checks
# AND the three cross-file passes (trace-purity, lock-graph, metric
# contracts) off a single shared AST parse, under a hard wall-clock budget.
# The JSON report lands next to the bench artifacts; a failing line is
# self-documenting via `python -m tools.analysis --explain <rule-id>`.
python -m tools.analysis --max-seconds 10 --out analysis_report.json

# native build (non-fatal: pure-python fallback covers it)
./native/build.sh || echo "WARN: native build failed; numpy fallbacks in use"

if [ "$MODE" = "nightly" ]; then
  # the slow tier runs PER-FILE in separate processes: this jaxlib's CPU
  # compiler segfaults probabilistically (backend_compile_and_load) after the
  # thousands of compiles a single-process --runslow pass accumulates —
  # observed at roaming, unrelated compile sites across runs (with and without
  # a compile-serialization lock), while every file passes in isolation and
  # the fast suite is reliably green in one process
  failed=""
  for f in tests/test_*.py; do
    python -m pytest "$f" -q --runslow || failed="$failed $f"
  done
  if [ -n "$failed" ]; then
    echo "NIGHTLY FAILURES:$failed"
    exit 1
  fi
else
  # reliability tier first: fault injection at every named site (streamed-fit
  # checkpoint-resume, barrier retry/degrade) must be green before the full
  # matrix runs — a broken failure path fails fast here
  python -m pytest tests/test_reliability.py -q
  # cache tier next: the HBM batch-cache smoke (cached-replay bit-identity per
  # streamed estimator + exact hit/miss/eviction counter accounting + zero
  # pass-2 uploads) — a wrong cache silently corrupts every multi-pass fit
  python -m pytest tests/test_device_cache.py -q
  # ingest-fusion tier (docs/design.md §6k): staging-pool/Arrow units and the
  # fused-vs-staged bit-parity matrix first, then an end-to-end smoke — an
  # Arrow-backed fused featurize->fit chain on the 8-dev mesh must export a
  # run report whose counters prove the host copied ZERO bytes (every staged
  # block was a view) and that the chain actually fused
  python -m pytest tests/test_ingest_fusion.py -q
  SRML_INGEST_SMOKE_DIR="$(mktemp -d)"
  SRML_TPU_METRICS_DIR="$SRML_INGEST_SMOKE_DIR" \
  SRML_TPU_STREAM_THRESHOLD_BYTES=1024 SRML_TPU_STREAM_BATCH_ROWS=64 \
  SRML_TPU_PIPELINE_FUSE_MIN_ROWS=1 \
  python - <<'PY'
import os
import numpy as np
import pyarrow as pa
from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.feature import StandardScaler
from spark_rapids_ml_tpu.observability import load_run_reports
from spark_rapids_ml_tpu.pipeline import Pipeline

rng = np.random.default_rng(0)
X = rng.normal(size=(600, 8)).astype(np.float32)
tbl = pa.table(
    {"features": pa.FixedSizeListArray.from_arrays(pa.array(X.reshape(-1)), 8)}
)
Pipeline(stages=[
    StandardScaler(inputCol="features", outputCol="scaled", withMean=True),
    KMeans(k=3, seed=2, maxIter=6, featuresCol="scaled"),
]).fit(tbl)
reps = load_run_reports(os.environ["SRML_TPU_METRICS_DIR"])
rep = next(r for r in reversed(reps) if r["algo"] == "Pipeline")
assert rep["status"] == "ok", rep["status"]
c = rep["metrics"]["counters"]
fused = sum(v for k, v in c.items() if k.startswith("pipeline.fused_stages"))
assert fused == 2, c
assert c.get("ingest.bytes_copied", 0) == 0, c  # Arrow path: zero host copies
assert c.get("ingest.bytes_zero_copy", 0) >= X.nbytes, c
ing = rep["ingest"]
assert ing["bytes_per_row_after"] == 0.0 and ing["bytes_per_row_before"] > 0, ing
print("INGEST-FUSION SMOKE OK: chain fused (%d stages), zero host-copy "
      "bytes, %.0f B/row of staging copies avoided"
      % (fused, ing["bytes_per_row_before"]))
PY
  # observability tier: registry/FitRun/exporter units, then an end-to-end
  # smoke — a streamed KMeans fit must append a parseable JSONL run report
  # whose counters prove pass 2+ uploaded ZERO bytes (the cache-tier
  # assertion, migrated onto the report path: what production dashboards
  # will read is what CI verifies)
  python -m pytest tests/test_observability.py tests/test_transform_observability.py -q
  SRML_OBS_SMOKE_DIR="$(mktemp -d)"
  SRML_TPU_METRICS_DIR="$SRML_OBS_SMOKE_DIR" \
  SRML_TPU_STREAM_THRESHOLD_BYTES=1024 SRML_TPU_STREAM_BATCH_ROWS=64 \
  python - <<'PY'
import os
import numpy as np, pandas as pd
from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.observability import load_run_reports
from spark_rapids_ml_tpu.observability.export import iter_spans

rng = np.random.default_rng(0)
X = np.concatenate(
    [rng.normal(-3, 1, (192, 8)), rng.normal(3, 1, (192, 8))]
).astype(np.float32)
KMeans(k=2, maxIter=6, seed=5).fit(pd.DataFrame({"features": list(X)}))
rep = load_run_reports(os.environ["SRML_TPU_METRICS_DIR"])[-1]
assert rep["status"] == "ok" and rep["algo"] == "KMeans", rep["status"]
c = rep["metrics"]["counters"]
n_batches = -(-X.shape[0] // 64)
assert c["stream.upload_batches"] == n_batches, c  # pass 2+ uploaded zero
steps = [s for s in iter_spans(rep) if s["name"] == "kmeans.step"]
assert len(steps) >= 2 and c["cache.hits"] == (len(steps) - 1) * n_batches, c
assert rep["metrics"]["gauges"]["cache.bytes_resident"] == 0
# device-performance plane (docs/design.md §6f): per-span flops/bytes
# attribution + roofline classification + compile accounting + exported cost
# records — all from the JSONL, like a dashboard would read them
for s in steps:
    d = s["attrs"]["device"]
    assert d["flops"] > 0 and d["bytes"] > 0, d
    assert d["roofline_bound"] in ("compute", "memory"), d
assert any(k.startswith("device.compile{") and v >= 1 for k, v in c.items()), c
recs = rep["device"]["kernels"]
assert any(r["kernel"] == "streaming.accum_kmeans" and r["flops"] > 0
           for r in recs), recs
# graceful degrade: no hbm gauges on a CPU runtime without memory_stats
assert not any("hbm" in k for k in rep["metrics"]["gauges"]), rep["metrics"]
print("OBSERVABILITY SMOKE OK: report parses, pass-2 uploads == 0, "
      "spans carry flops/bytes + roofline verdicts")
PY
  # inference-plane smoke (docs/design.md §6e): a fit + transform must export
  # BOTH fit_reports.jsonl and transform_reports.jsonl; the recompile sentinel
  # must fire under deliberately ragged batch sizes and stay silent under
  # bucketed ones — all asserted from the exported JSONL, like a dashboard would
  SRML_TPU_METRICS_DIR="$SRML_OBS_SMOKE_DIR" \
  SRML_TPU_RECOMPILE_WARN_THRESHOLD=4 \
  python - <<'PY'
import os
import numpy as np, pandas as pd
from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.observability.export import (
    load_run_reports, load_transform_reports)
from spark_rapids_ml_tpu.observability.inference import reset_shape_buckets

d = os.environ["SRML_TPU_METRICS_DIR"]
rng = np.random.default_rng(0)
X = np.concatenate(
    [rng.normal(-3, 1, (128, 8)), rng.normal(3, 1, (128, 8))]
).astype(np.float32)
pdf = pd.DataFrame({"features": list(X)})
model = KMeans(k=2, maxIter=6, seed=5).fit(pdf)

def storms(reports):
    return sum(
        v for r in reports
        for k, v in r["metrics"]["counters"].items()
        if k.startswith("transform.recompile_storm")
    )

# bucketed: fixed batch size -> few shape signatures -> sentinel silent
reset_shape_buckets()
for i in range(0, len(pdf), 64):
    model.transform(pdf.iloc[i : i + 64])
bucketed = load_transform_reports(d)
assert storms(bucketed) == 0, "sentinel fired under bucketed batches"
hist = bucketed[-1]["metrics"]["histograms"]
assert any(k.startswith("transform.batch_s") and v["count"] >= 1
           for k, v in hist.items()), hist
# ragged: every batch a new (rows, cols, dtype) signature -> storm fires
reset_shape_buckets()
n_before = len(bucketed)
for n in (7, 11, 13, 17, 19, 23):  # 6 distinct sigs > threshold 4
    model.transform(pdf.head(n))
ragged = load_transform_reports(d)[n_before:]
assert storms(ragged) >= 1, "sentinel silent under ragged batches"
assert len(load_run_reports(d)) >= 1  # fit report exported too
print("INFERENCE SMOKE OK: both JSONLs exported; sentinel fires only on ragged")
PY
  rm -rf "$SRML_OBS_SMOKE_DIR"
  # live-telemetry smoke (docs/design.md §6g): a streamed KMeans fit with an
  # injected DeviceError at a late ingest batch. A poller thread scrapes
  # /metrics and /runs/<id> MID-FIT (batch progress strictly advancing, valid
  # Prometheus exposition), the fault drives the device->CPU degradation rung,
  # and the flight recorder's postmortem bundle must exist, round-trip through
  # json.loads, and carry the fault + degrade events in its ring — with zero
  # server threads or sockets left after fit returns.
  python -m pytest tests/test_telemetry_plane.py -q
  SRML_TELEM_SMOKE_DIR="$(mktemp -d)"
  SRML_TPU_METRICS_DIR="$SRML_TELEM_SMOKE_DIR" \
  SRML_TPU_METRICS_PORT=0 \
  SRML_TPU_STREAM_THRESHOLD_BYTES=1024 SRML_TPU_STREAM_BATCH_ROWS=16 \
  SRML_TPU_FAULT_SPEC="ingest:batch=100:raise=DeviceError" \
  python - <<'PY'
import json, os, threading, time, urllib.request
import numpy as np, pandas as pd
from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.observability import server

samples, metrics_texts, run_ids = [], [], []
stop = threading.Event()

def poll():
    # wait for the fit to open the endpoint, then scrape until it closes
    while not stop.is_set():
        addr = server.server_address()
        if addr is None:
            time.sleep(0.002)
            continue
        port = addr[1]
        try:
            idx = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/runs", timeout=2).read())
            if not idx["runs"]:
                continue
            rid = idx["runs"][0]["run_id"]
            run_ids.append(rid)
            view = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/runs/{rid}", timeout=2).read())
            prog = view.get("progress", {}).get("kmeans.batches")
            if prog:
                samples.append(prog["done"])
            # scrape /metrics only once the progress gauge exists, so the
            # exposition check can require the fit_progress series
            if samples and len(metrics_texts) < 3:
                metrics_texts.append(urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=2
                ).read().decode())
        except OSError:
            pass  # server closing between scrapes: the fit just ended

poller = threading.Thread(target=poll, daemon=True)
poller.start()
rng = np.random.default_rng(0)
X = np.concatenate(
    [rng.normal(-3, 1, (1000, 8)), rng.normal(3, 1, (1000, 8))]
).astype(np.float32)
model = KMeans(k=2, maxIter=6, seed=5).fit(pd.DataFrame({"features": list(X)}))
stop.set(); poller.join(timeout=10)

rep = model.fit_report_
assert rep["status"] == "ok", rep["status"]  # CPU rung absorbed the fault
# mid-fit scrapes: progress gauge strictly advancing across distinct samples
distinct = [s for i, s in enumerate(samples) if i == 0 or s != samples[i - 1]]
assert len(distinct) >= 2, f"too few mid-fit progress samples: {samples}"
assert distinct == sorted(distinct), distinct
assert all(r == rep["run_id"] for r in run_ids)
# /metrics served valid exposition mid-fit: every line is `name{...} value`
assert metrics_texts, "no /metrics scrape landed mid-fit"
for text in metrics_texts:
    assert "srml_tpu_fit_progress" in text
    for ln in text.splitlines():
        if ln.startswith("#") or not ln:
            continue
        float(ln.rsplit(" ", 1)[1])  # value parses
# postmortem bundle: exists, round-trips, ring holds the fault + degrade
d = os.environ["SRML_TPU_METRICS_DIR"]
bundles = [p for p in os.listdir(d) if p.startswith("postmortem_")]
assert len(bundles) == 1, bundles
with open(os.path.join(d, bundles[0])) as f:
    doc = json.loads(f.read())
assert doc["run_id"] == rep["run_id"], (doc["run_id"], rep["run_id"])
kinds = [e["kind"] for e in doc["ring"]]
assert "fault" in kinds, kinds
assert any(e["kind"] == "degrade" and e.get("rung") == "device_to_cpu"
           for e in doc["ring"]), kinds
# zero leaked server threads/sockets after fit returned
assert server.server_address() is None
assert not any(t.name == "srml-telemetry-server" for t in threading.enumerate())
print(f"LIVE TELEMETRY SMOKE OK: {len(distinct)} advancing progress samples, "
      "valid /metrics mid-fit, postmortem carries fault+degrade, no leaks")
PY
  rm -rf "$SRML_TELEM_SMOKE_DIR"
  # communication-plane smoke (docs/design.md §6h): unit tests first, then an
  # end-to-end check on the 8-device virtual mesh — a streamed KMeans fit's
  # exported JSONL must carry per-executable collective ops/bytes and per-span
  # comm_frac (XLA's all-reduces, measured, not assumed), and an artificially
  # delayed rank (the barrier_rank sleep fault) must produce a straggler event
  # visible in the event log, /runs/<id>/ranks, and the postmortem bundle.
  # (test_collective_counts.py stays in the catch-all run below — it carries a
  # known environment-dependent failure on this image's XLA and must not
  # abort the tier before the end-to-end smoke runs.)
  python -m pytest tests/test_comm_plane.py -q
  SRML_COMM_SMOKE_DIR="$(mktemp -d)"
  SRML_TPU_METRICS_DIR="$SRML_COMM_SMOKE_DIR" \
  SRML_TPU_METRICS_PORT=0 \
  SRML_TPU_STREAM_THRESHOLD_BYTES=1024 SRML_TPU_STREAM_BATCH_ROWS=64 \
  SRML_TPU_FAULT_SPEC="barrier_rank:batch=3:sleep=0.3" \
  python - <<'PY'
import json, os, threading, time, urllib.request
import numpy as np, pandas as pd
from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.observability import (
    FitRun, load_run_reports, note_rank_phase, server, worker_scope)
from spark_rapids_ml_tpu.observability import flight
from spark_rapids_ml_tpu.observability.export import iter_spans
from spark_rapids_ml_tpu.reliability import fault_point

d = os.environ["SRML_TPU_METRICS_DIR"]
rng = np.random.default_rng(0)
X = np.concatenate(
    [rng.normal(-3, 1, (192, 8)), rng.normal(3, 1, (192, 8))]
).astype(np.float32)
KMeans(k=2, maxIter=6, seed=5).fit(pd.DataFrame({"features": list(X)}))
rep = load_run_reports(d)[-1]
# collective accounting from the compiled HLO, read back from the JSONL
c = rep["metrics"]["counters"]
assert any(k.startswith("comm.collective_ops{") and "kind=all_reduce" in k
           for k in c), c
assert sum(v for k, v in c.items()
           if k.startswith("comm.collective_bytes")) > 0, c
recs = [r for r in rep["device"]["kernels"] if r.get("collectives")]
assert recs and any("all_reduce" in r["collectives"] for r in recs), recs
assert rep["device"]["peak_ici_bw"] > 0
steps = [s for s in iter_spans(rep) if s["name"] == "kmeans.step"]
assert steps and all(s["attrs"]["device"]["comm_bytes"] > 0 for s in steps)
assert all(s["attrs"]["device"]["comm_frac"] is not None for s in steps)

# injected slow rank -> straggler event + /ranks timeline + postmortem
run = FitRun("KMeans", site="comm-smoke")
snaps, lock = [], threading.Lock()
def task(rank):
    with worker_scope(rank=rank, run_id=run.run_id) as ws:
        t0 = time.perf_counter()
        fault_point("barrier_rank", batch=rank)  # rank 3 sleeps 0.3s
        time.sleep(0.02)
        note_rank_phase("fit_program", wall_s=time.perf_counter() - t0,
                        rows=96, nbytes=96 * 8 * 4)
        with lock:
            snaps.append(ws.snapshot())
with run:
    threads = [threading.Thread(target=task, args=(r,)) for r in range(4)]
    [t.start() for t in threads]; [t.join() for t in threads]
    for s in sorted(snaps, key=lambda s: s["rank"]):
        run.add_worker_snapshot(s)
    port = server.server_address()[1]
    view = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/runs/{run.run_id}/ranks", timeout=5).read())
    pm_path = flight.dump_postmortem(run, reason="degrade:comm_smoke")
rep2 = run.report()
assert view["stragglers"] == [3], view
assert view["skew"]["fit_program"] > 1.5, view
evs = [e for e in rep2["events"] if e["kind"] == "straggler"]
assert len(evs) == 1 and evs[0]["rank"] == 3, rep2["events"]
assert any(e["kind"] == "fault" and e.get("sleep_s") for e in rep2["events"])
pm = flight.load_postmortem(pm_path)
assert pm["ranks"]["stragglers"] == [3], pm["ranks"]
assert any(k.startswith("comm.rank_skew") for k in rep2["metrics"]["gauges"])
print("COMM SMOKE OK: collective ops/bytes + comm_frac in the exported JSONL; "
      "delayed rank 3 flagged in events, /ranks and the postmortem")
PY
  rm -rf "$SRML_COMM_SMOKE_DIR"
  # serving-plane smoke (docs/design.md §7): unit tests first, then the
  # acceptance end-to-end — start the endpoint on port 0, register a fitted
  # KMeans AND a fitted logreg (weights HBM-resident, per-bucket AOT
  # pre-warm), drive concurrent mixed-size HTTP requests, and assert the
  # steady-state contract FROM the plane's own telemetry: zero new
  # device.compile{kernel=} entries after warm-up, zero recompile-storm
  # events, exact per-request row counts, p99 + occupancy present in the
  # exported serving_reports.jsonl, and zero leaked threads/sockets after
  # stop_serving.
  python -m pytest tests/test_serving.py -q
  SRML_SERVING_SMOKE_DIR="$(mktemp -d)"
  SRML_TPU_METRICS_DIR="$SRML_SERVING_SMOKE_DIR" \
  python - <<'PY'
import json, threading, urllib.request
import numpy as np, pandas as pd
from spark_rapids_ml_tpu import serving
from spark_rapids_ml_tpu.classification import LogisticRegression
from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.observability import server as obs_server
from spark_rapids_ml_tpu.observability.export import load_serving_reports
from spark_rapids_ml_tpu.profiling import counter_totals

rng = np.random.default_rng(0)
X = np.concatenate(
    [rng.normal(-3, 1, (128, 8)), rng.normal(3, 1, (128, 8))]
).astype(np.float32)
y = np.concatenate([np.zeros(128), np.ones(128)])
km = KMeans(k=2, maxIter=6, seed=5).fit(pd.DataFrame({"features": list(X)}))
lr = LogisticRegression(maxIter=8).fit(
    pd.DataFrame({"features": list(X), "label": y})
)

addr = serving.start_serving(port=0)
assert addr is not None, "endpoint did not bind"
port = addr[1]
serving.register_model("km", km)   # register = upload + per-bucket pre-warm
serving.register_model("lr", lr)

ref_km = km._serving_predict(X)["prediction"]
compiles = lambda: {k: v for k, v in counter_totals().items()
                    if k.startswith("device.compile{")}
storms = lambda: sum(v for k, v in counter_totals().items()
                     if k.startswith("transform.recompile_storm"))
c0, s0 = compiles(), storms()

def post(name, block):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/{name}:predict",
        data=json.dumps({"instances": block.tolist()}).encode(), method="POST")
    return json.loads(urllib.request.urlopen(req, timeout=15).read())

failures = []
def client(seed):
    r = np.random.default_rng(seed)
    for _ in range(15):
        n = int(r.integers(1, 48)); off = int(r.integers(0, 256 - n))
        doc = post("km", X[off:off + n])
        if doc["rows"] != n or doc["outputs"]["prediction"] != \
                ref_km[off:off + n].tolist():
            failures.append(("km", off, n))
        if post("lr", X[off:off + n])["rows"] != n:
            failures.append(("lr", off, n))

threads = [threading.Thread(target=client, args=(i,)) for i in range(6)]
[t.start() for t in threads]; [t.join() for t in threads]
assert not failures, failures[:5]
new = {k: v - c0.get(k, 0) for k, v in compiles().items() if v != c0.get(k, 0)}
assert not new, f"steady-state serving compiled: {new}"
assert storms() == s0, "recompile sentinel fired on bucketed serving traffic"
rep = serving.stop_serving()
summary = serving.serving_summary(load_serving_reports(
    __import__("os").environ["SRML_TPU_METRICS_DIR"])[-1])
assert summary["km"]["requests"] == 90 and summary["lr"]["requests"] == 90
assert summary["km"]["p99_ms"] > 0 and summary["km"]["batch_occupancy"] > 0
assert summary["km"]["batches"] < summary["km"]["requests"]  # coalesced
# zero leaked threads/sockets after shutdown
assert obs_server.server_address() is None
assert not any(t.name.startswith(("srml-serving", "srml-telemetry"))
               for t in threading.enumerate())
print(f"SERVING SMOKE OK: 180 concurrent HTTP requests exact, 0 warm-path "
      f"compiles, km p99={summary['km']['p99_ms']}ms "
      f"occupancy={summary['km']['batch_occupancy']}, no leaks")
PY
  rm -rf "$SRML_SERVING_SMOKE_DIR"
  # serving chaos smoke (docs/design.md §7c): unit tests first, then the
  # failover acceptance end-to-end — a 2-replica fleet takes a DETERMINISTIC
  # chaos kill (spec-string grammar, times=1) in the middle of a request
  # window and must show ZERO failed client requests (queued + in-flight work
  # replays onto the survivor), the dead replica restarting from the
  # registry's pinned weights and rejoining LIVE with ZERO new
  # device.compile entries, and bounded p99 inflation versus the no-fault
  # window.
  python -m pytest tests/test_serving_fleet.py -q
  python - <<'PY'
import threading, time
import numpy as np, pandas as pd
from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.profiling import counter_totals
from spark_rapids_ml_tpu.reliability import reset_chaos
from spark_rapids_ml_tpu.serving import ModelRegistry
from spark_rapids_ml_tpu.serving.fleet import LIVE

rng = np.random.default_rng(0)
X = np.concatenate(
    [rng.normal(-3, 1, (128, 8)), rng.normal(3, 1, (128, 8))]
).astype(np.float32)
km = KMeans(k=2, maxIter=6, seed=5).fit(pd.DataFrame({"features": list(X)}))

config.set("serving.replicas", 2)
config.set("serving.heartbeat_timeout_s", 0.3)
registry = ModelRegistry()
registry.register("km", km)  # 2 replicas, each HBM-uploaded + pre-warmed
fleet = registry._models["km"].fleet
assert fleet is not None and fleet.live_count() == 2
ref = km._serving_predict(X)["prediction"]
compiles = lambda: {k: v for k, v in counter_totals().items()
                    if k.startswith("device.compile{")}

failed, lat_lock = [], threading.Lock()

def window(tag):
    lats = []
    def client(seed):
        r = np.random.default_rng(seed)
        for i in range(20):
            n = int(r.integers(1, 48)); off = int(r.integers(0, 256 - n))
            t0 = time.perf_counter()
            try:
                out = registry.predict("km", X[off:off + n], timeout=20.0)
                assert np.array_equal(out["prediction"], ref[off:off + n])
            except Exception as e:
                with lat_lock:
                    failed.append((tag, seed, i, type(e).__name__, str(e)))
                continue
            with lat_lock:
                lats.append(time.perf_counter() - t0)
    threads = [threading.Thread(target=client, args=(s,)) for s in range(4)]
    [t.start() for t in threads]; [t.join() for t in threads]
    lats.sort()
    return lats[min(len(lats) - 1, int(0.99 * len(lats)))]

p99_nofault = window("baseline")
c0 = compiles()
# deterministic incident: replica 0's NEXT dispatched batch is killed
config.set("reliability.chaos_spec", "serving_execute:replica=0:action=kill")
reset_chaos()
p99_fault = window("fault")
config.unset("reliability.chaos_spec"); reset_chaos()
assert not failed, f"failover dropped requests: {failed[:5]}"
deadline = time.monotonic() + 15.0
while time.monotonic() < deadline and not (
    fleet.live_count() == 2 and all(r.state == LIVE for r in fleet._replicas)
):
    time.sleep(0.05)
assert fleet.live_count() == 2, registry.stats("km")["replicas"]
assert sum(r.restarts for r in fleet._replicas) >= 1, "no replica restarted"
for i in range(8):  # post-rejoin traffic lands on warm executables
    out = registry.predict("km", X[: 4 + i], timeout=20.0)
    assert np.array_equal(out["prediction"], ref[: 4 + i])
new = {k: v - c0.get(k, 0) for k, v in compiles().items() if v != c0.get(k, 0)}
assert not new, f"replica recovery compiled: {new}"
bound = max(0.5, 20 * p99_nofault)
assert p99_fault <= bound, (
    f"p99 inflated past bound under failover: {p99_fault:.3f}s "
    f"(no-fault {p99_nofault:.3f}s, bound {bound:.3f}s)"
)
registry.close()
config.unset("serving.replicas"); config.unset("serving.heartbeat_timeout_s")
print(f"CHAOS SMOKE OK: mid-run replica kill, 160/160 requests exact, "
      f"restart+rejoin with 0 compiles, p99 {p99_nofault*1e3:.1f}ms -> "
      f"{p99_fault*1e3:.1f}ms (bound {bound*1e3:.0f}ms)")
PY
  # ann-lifecycle smoke (docs/design.md §7b): unit tests first, then the
  # acceptance end-to-end — a pipelined streamed build whose exported run
  # report proves per-batch overlap telemetry, save through the index store,
  # load in a FRESH process with bit-identical search, and incremental
  # adds/deletes on a LIVE served model with zero warm-path compiles — all
  # asserted from exported JSONL counters, like a dashboard would.
  python -m pytest tests/test_ann_lifecycle.py -q
  SRML_ANN_SMOKE_DIR="$(mktemp -d)"
  SRML_TPU_METRICS_DIR="$SRML_ANN_SMOKE_DIR/metrics" \
  SRML_ANN_SMOKE_STATE="$SRML_ANN_SMOKE_DIR" \
  python - <<'PY'
import os
import numpy as np, pandas as pd
from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.knn import ApproximateNearestNeighbors
from spark_rapids_ml_tpu.observability import load_run_reports

state = os.environ["SRML_ANN_SMOKE_STATE"]
rng = np.random.default_rng(0)
X = rng.normal(size=(1200, 16)).astype(np.float32)
df = pd.DataFrame({"features": list(X), "id": np.arange(1200)})
# force the streamed (pipelined) build, then search in-core below
config.set("stream_threshold_bytes", 1024)
config.set("stream_batch_rows", 256)
est = ApproximateNearestNeighbors(
    k=8, algorithm="ivfflat", algoParams={"nlist": 16, "nprobe": 8},
    inputCol="features", idCol="id",
)
model = est.fit(df)
config.unset("stream_threshold_bytes")
config.unset("stream_batch_rows")
rep = load_run_reports(os.environ["SRML_TPU_METRICS_DIR"])[-1]
assert rep["algo"] == "ApproximateNearestNeighbors", rep["algo"]
c = rep["metrics"]["counters"]
n_batches = -(-1200 // 256)
assert c.get("ann.pipeline_batches{site=ann_assign}", 0) == n_batches, c
h = rep["metrics"]["histograms"]
stage = sum(v["count"] for k, v in h.items() if k.startswith("ann.stage_s"))
drain = sum(v["count"] for k, v in h.items() if k.startswith("ann.drain_s"))
assert stage == n_batches and drain == n_batches, (stage, drain)
# batch-as-rank timeline rows exported (§7b straggler surface)
assert rep.get("ranks") and len(rep["ranks"]["ranks"]) == n_batches, rep.get("ranks")
qdf = pd.DataFrame({"features": list(X[:32]), "id": np.arange(32)})
_, _, ref = model.kneighbors(qdf)
model.write().save(os.path.join(state, "index_model"))
np.savez(os.path.join(state, "ref.npz"),
         ids=np.stack(ref["indices"]), dists=np.stack(ref["distances"]), X=X)
print("ANN LIFECYCLE SMOKE (1/2) OK: pipelined build telemetry in the JSONL "
      f"({n_batches} batches with stage/drain overlap records); model saved")
PY
  # FRESH process: load without refit; search must be bit-identical; a live
  # served kNN model absorbs incremental adds/deletes with zero new compiles
  SRML_TPU_METRICS_DIR="$SRML_ANN_SMOKE_DIR/metrics" \
  SRML_ANN_SMOKE_STATE="$SRML_ANN_SMOKE_DIR" \
  python - <<'PY'
import os
import numpy as np, pandas as pd
from spark_rapids_ml_tpu import config, serving
from spark_rapids_ml_tpu.knn import NearestNeighbors
from spark_rapids_ml_tpu.models.knn import ApproximateNearestNeighborsModel
from spark_rapids_ml_tpu.observability import fit_run, load_run_reports

state = os.environ["SRML_ANN_SMOKE_STATE"]
blob = np.load(os.path.join(state, "ref.npz"))
X = blob["X"]
loaded = ApproximateNearestNeighborsModel.load(os.path.join(state, "index_model"))
qdf = pd.DataFrame({"features": list(X[:32]), "id": np.arange(32)})
_, _, got = loaded.kneighbors(qdf)
np.testing.assert_array_equal(np.stack(got["indices"]), blob["ids"])
np.testing.assert_array_equal(np.stack(got["distances"]), blob["dists"])

# live served kNN model: bucketed geometry -> adds/deletes compile nothing
config.set("serving.max_batch_rows", 32)
config.set("serving.bucket_min_rows", 16)
nn = NearestNeighbors(k=3, inputCol="features").fit(
    pd.DataFrame({"features": list(X[:200])})
)
nn.enable_incremental(capacity_rows=512)
reg = serving.ModelRegistry()
with fit_run(algo="AnnServeWarm", site="ci"):
    reg.register("nn", nn)  # per-bucket AOT pre-warm compiles HERE
    reg.predict("nn", X[:8])
with fit_run(algo="AnnServeSteady", site="ci"):
    new_vec = X[:4] + 100.0
    ids = nn.add_items(new_vec)
    reg.refresh_weights("nn")
    out = reg.predict("nn", new_vec)
    assert (out["indices"][:, 0] == ids).all(), (out["indices"], ids)
    nn.delete_items(ids[:2])
    reg.refresh_weights("nn")
    out2 = reg.predict("nn", new_vec[:2])
    assert not np.isin(out2["indices"][:, 0], ids[:2]).any(), out2["indices"]
reg.close()
rep = [r for r in load_run_reports(os.environ["SRML_TPU_METRICS_DIR"])
       if r["algo"] == "AnnServeSteady"][-1]
c = rep["metrics"]["counters"]
compiles = sum(v for k, v in c.items() if k.startswith("device.compile{"))
assert compiles == 0, c
assert c.get("serving.weight_refreshes{model=nn}", 0) == 2, c
assert c.get("ann.items_added", 0) == 4, c
assert c.get("ann.items_deleted", 0) == 2, c
print("ANN LIFECYCLE SMOKE (2/2) OK: fresh-process load searches "
      "bit-identical; live served model absorbed 4 adds + 2 deletes with "
      "0 warm-path compiles and 2 weight refreshes")
PY
  rm -rf "$SRML_ANN_SMOKE_DIR"
  # continual smoke (docs/design.md §7d): unit tests first, then the
  # closed-loop acceptance end-to-end — drifted batches streamed at a LIVE
  # served KMeans must fire the drift detector deterministically, the
  # governed promotion must land through the exec-locked mutate path
  # (generation bump, weight refresh), post-promotion predictions must
  # reflect the shifted centers, and the whole drift->promote cycle must
  # add ZERO device.compile entries — every claim counter-asserted from
  # the exported run-report JSONL, like a dashboard would.
  python -m pytest tests/test_continual.py -q
  SRML_CONTINUAL_SMOKE_DIR="$(mktemp -d)"
  SRML_TPU_METRICS_DIR="$SRML_CONTINUAL_SMOKE_DIR" python - <<'PY'
import os
import numpy as np, pandas as pd
from spark_rapids_ml_tpu import config, serving
from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.continual import ContinualLoop, DriftDetector
from spark_rapids_ml_tpu.observability import fit_run, load_run_reports

OLD = np.array([[0.0, 0.0, 0.0], [6.0, 6.0, 6.0]], np.float32)
NEW = np.array([[12.0, 12.0, 12.0], [-6.0, 9.0, 0.0]], np.float32)

def blob(centers, n, seed):
    r = np.random.default_rng(seed)
    return (r.normal(0, 0.3, (n, centers.shape[1])).astype(np.float32)
            + centers[r.integers(0, len(centers), n)])

km = KMeans(k=2, maxIter=8, seed=3).fit(
    pd.DataFrame({"features": list(blob(OLD, 512, 1))}))
config.set("continual.update_batch_rows", 128)
config.set("continual.decay", 0.5)  # 1-batch half-life: forget the old blobs
reg = serving.ModelRegistry()
holdout = blob(NEW, 256, seed=2)
loop = ContinualLoop(
    "km", km.partial_fit_updater(name="km"), (holdout,), registry=reg,
    # mads=6: the 200-row smoke batches carry ~6% sampling noise against a
    # 4-value MAD baseline, and the drifted signal is ~400x the threshold —
    # headroom costs nothing in discriminative power
    detector=DriftDetector(model="km", signal="inertia", mads=6.0,
                           min_baseline=4),
    promote_every=10**9,  # drift is the ONLY promotion trigger here
)
with fit_run(algo="ContinualWarm", site="ci"):
    reg.register("km", km)  # HBM upload + bucketed pre-warm compiles HERE
    reg.predict("km", blob(OLD, 16, seed=3))
    for i in range(6):  # in-distribution: calibrates the detector, no drift
        out = loop.feed(blob(OLD, 200, seed=10 + i))
        assert out["drift"] is None and out["promotion"] is None, out
with fit_run(algo="ContinualSteady", site="ci"):
    gen = None
    for i in range(4):  # the shifted stream: drift -> promote, repeatedly
        out = loop.feed(blob(NEW, 200, seed=20 + i))
        if i == 0:
            assert out["drift"] is not None, "no drift on the shifted batch"
            assert out["promotion"] and out["promotion"]["promoted"], out
        if out["promotion"] and out["promotion"].get("promoted"):
            gen = out["promotion"]["generation"]
    pred = reg.predict("km", holdout)["prediction"]
reg.close()

# the promoted centers sit on the SHIFTED blobs, and live predictions agree
# with an exact host-side assignment against them
centers = np.asarray(km._model_attributes["cluster_centers"])
d = np.linalg.norm(centers[:, None, :] - NEW[None], axis=-1)
assert (d.min(axis=0) < 1.0).all(), centers
want = np.linalg.norm(
    holdout[:, None, :].astype(np.float64) - centers[None], axis=-1
).argmin(axis=1)
assert np.array_equal(np.asarray(pred), want)

steady = [r for r in load_run_reports(os.environ["SRML_TPU_METRICS_DIR"])
          if r["algo"] == "ContinualSteady"][-1]
c = steady["metrics"]["counters"]
compiles = sum(v for k, v in c.items() if k.startswith("device.compile{"))
assert compiles == 0, c
assert c.get("continual.drift{model=km,signal=inertia}", 0) >= 1, c
promos = c.get("continual.promotions{model=km}", 0)
assert promos >= 1, c
assert c.get("serving.weight_refreshes{model=km}", 0) == promos, c
g = steady["metrics"]["gauges"]
assert g.get("serving.model_generation{model=km}") == gen, g
assert g.get("continual.staleness_s{model=km}", 0) > 0, g
config.unset("continual.update_batch_rows")
config.unset("continual.decay")
print("CONTINUAL SMOKE OK: drift fired on the shifted batch, governed "
      f"promotion landed (generation {gen}) with 0 warm-path compiles, "
      "and live predictions follow the promoted centers")
PY
  rm -rf "$SRML_CONTINUAL_SMOKE_DIR"
  # tracing smoke (docs/design.md §6l): unit tests first, then the causal
  # acceptance end-to-end — a 2-replica served fleet takes a DETERMINISTIC
  # mid-window chaos kill while every request carries a client traceparent.
  # Asserted FROM the exported trace_reports.jsonl (like a trace backend
  # would read it): every request has exactly ONE complete trace
  # (ingress->queue->batch->execute->scatter, status ok), the failed-over
  # traces carry the dead replica's replay link, and a /metrics histogram
  # exemplar resolves to a stored trace at /traces/<id>.
  python -m pytest tests/test_tracing.py -q
  SRML_TRACING_SMOKE_DIR="$(mktemp -d)"
  SRML_TPU_METRICS_DIR="$SRML_TRACING_SMOKE_DIR" python - <<'PY'
import json, os, time, urllib.request
import numpy as np, pandas as pd
from spark_rapids_ml_tpu import config, serving
from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.observability import load_trace_reports
from spark_rapids_ml_tpu.reliability import reset_chaos
from spark_rapids_ml_tpu.serving.fleet import LIVE

rng = np.random.default_rng(0)
X = np.concatenate(
    [rng.normal(-3, 1, (128, 8)), rng.normal(3, 1, (128, 8))]
).astype(np.float32)
km = KMeans(k=2, maxIter=6, seed=5).fit(pd.DataFrame({"features": list(X)}))

config.set("serving.replicas", 2)
config.set("serving.heartbeat_timeout_s", 0.3)
host, port = serving.start_serving(port=0)
serving.register_model("km", km)
entry = serving.get_registry()._models["km"]
# deterministic incident: replica 0's 3rd dispatched batch is killed mid-window
config.set("reliability.chaos_spec",
           "serving_execute:replica=0:after=2:action=kill")
reset_chaos()

trace_ids = []
for i in range(12):
    tid, sid = os.urandom(16).hex(), os.urandom(8).hex()
    req = urllib.request.Request(
        f"http://{host}:{port}/v1/models/km:predict",
        data=json.dumps({"instances": X[: 3 + (i % 5)].tolist()}).encode(),
        headers={"traceparent": f"00-{tid}-{sid}-01"}, method="POST")
    doc = json.loads(urllib.request.urlopen(req, timeout=20).read())
    assert doc["trace_id"] == tid, (doc.get("trace_id"), tid)
    trace_ids.append(tid)
config.unset("reliability.chaos_spec"); reset_chaos()
deadline = time.monotonic() + 15.0
while time.monotonic() < deadline and not (
    entry.fleet.live_count() == 2
    and all(r.state == LIVE for r in entry.fleet._replicas)
):
    time.sleep(0.05)

# /metrics exemplar -> /traces/<id> BEFORE shutdown (live ring answers)
text = urllib.request.urlopen(
    f"http://{host}:{port}/metrics", timeout=10).read().decode()
ex_ids = {ln.split('trace_id="')[1].split('"')[0]
          for ln in text.splitlines()
          if "serving_total_s_bucket" in ln and '# {trace_id="' in ln}
resolved = [t for t in ex_ids if t in trace_ids]
assert resolved, f"no /metrics exemplar from this window: {ex_ids}"
ex_doc = json.loads(urllib.request.urlopen(
    f"http://{host}:{port}/traces/{resolved[0]}", timeout=10).read())
assert ex_doc["trace_id"] == resolved[0]
serving.stop_serving()

# the exported JSONL is the system of record: one complete trace per request
docs = load_trace_reports(os.environ["SRML_TPU_METRICS_DIR"])
by_id = {}
for d in docs:
    by_id.setdefault(d["trace_id"], []).append(d)
for tid in trace_ids:
    assert len(by_id.get(tid, [])) == 1, f"trace {tid}: {len(by_id.get(tid, []))} docs"
    (doc,) = by_id[tid]
    assert doc["status"] == "ok", doc["status"]
    names = {s["name"] for s in doc["spans"]}
    assert {"http.request", "serving.queue", "serving.batch",
            "serving.execute", "serving.scatter"} <= names, names
replayed = [d for tid in trace_ids for d in by_id[tid]
            if any(e["kind"] == "failover_replay" for e in d["events"])]
assert replayed, "chaos kill produced no failover-replay trace"
for d in replayed:
    (ev,) = [e for e in d["events"] if e["kind"] == "failover_replay"]
    assert ev["replica"] == 0 and "failover" in d["flags"], d["events"]
    # the dead attempt AND the survivor's serve are both in the trace
    statuses = {s["status"] for s in d["spans"] if s["name"] == "serving.batch"}
    assert statuses == {"error", "ok"}, statuses
print(f"TRACING SMOKE OK: 12/12 requests each one complete trace in the "
      f"JSONL, {len(replayed)} failed-over trace(s) carry the replica-0 "
      "replay link, /metrics exemplar resolved live")
PY
  rm -rf "$SRML_TRACING_SMOKE_DIR"
  # multihost smoke tier (docs/design.md §10): partitioner units first, then
  # 2 REAL OS processes x 4 CPU devices rendezvous over a local
  # jax.distributed coordinator (SRML_TPU_COORDINATOR env bootstrap). Ragged
  # per-process staging through Partitioner.stage_inputs must be bit-exact
  # (each process holds exactly its own padded rows of the global array), the
  # fit must agree with the single-process moments (bit-identical where the
  # backend runs cross-process programs; via the deterministic partial-moment
  # combine on CPU jaxlibs without multiprocess collectives), and the
  # compiled fit programs must stay allreduce-shaped: collective bytes
  # proportional to model state, invariant to data size, skew-free per rank.
  python -m pytest tests/test_partitioner.py -q
  python - <<'PY'
from benchmark.chip_bench import dryrun_partitioner_multiproc

rep = dryrun_partitioner_multiproc(n_proc=2, devices_per_proc=4)
assert rep["processes"] == 2 and rep["stage_bitexact"], rep
assert rep["parity_ok"], rep
assert rep["allreduce_shaped"] and rep["collective_byte_skew"] == 1.0, rep
assert not rep["stragglers"], rep
print("MULTIHOST SMOKE OK: 2 procs x 4 devices, ragged staging bit-exact, "
      "fit parity %s, collective bytes data-size-invariant (%s)"
      % ("bit-identical" if rep["cross_process_compute"] else
         "via partial-moment combine (no CPU multiprocess collectives)",
         {k: v["bytes_by_rows"] for k, v in
          rep["collectives"]["programs"].items()}))
PY
  python -m pytest tests/ -q --ignore=tests/test_reliability.py --ignore=tests/test_device_cache.py --ignore=tests/test_observability.py --ignore=tests/test_transform_observability.py --ignore=tests/test_telemetry_plane.py --ignore=tests/test_comm_plane.py --ignore=tests/test_serving.py --ignore=tests/test_ann_lifecycle.py --ignore=tests/test_continual.py --ignore=tests/test_tracing.py --ignore=tests/test_partitioner.py
fi

# small benchmark smoke (reference runs a small bench pre-merge)
python benchmark/benchmark_runner.py kmeans --num_rows 2000 --num_cols 32 --k 5 --no_cpu
python benchmark/benchmark_runner.py pca --num_rows 2000 --num_cols 32 --k 3 --no_cpu

# device-observability smoke (docs/design.md §6f): one REAL bench unit through
# the worker path; the assembled bench line must carry measured mfu +
# roofline_bound for the scenario (the keys ci/bench_check.py gates
# direction-aware). Runs the pca unit only — cheap on CPU, and its XLA path
# routes through the compiled_kernel plane.
SRML_DEVICE_SMOKE_DIR="$(mktemp -d)"
SRML_BENCH_ROLE=worker \
SRML_BENCH_PROGRESS="$SRML_DEVICE_SMOKE_DIR/progress.jsonl" \
SRML_BENCH_DEADLINE_TS="$(python -c 'import time; print(time.time() + 600)')" \
SRML_BENCH_SKIP="kmeans_headline,logreg,linreg,rf,umap,dbscan,fit_e2e,cache,telemetry_overhead,serving_qps,tracing_overhead,large_k,autotune,knn,ann,ann_build,wide256" \
python bench.py
SRML_BENCH_PROGRESS="$SRML_DEVICE_SMOKE_DIR/progress.jsonl" python - <<'PY'
import json, os, sys
sys.path.insert(0, ".")
import bench

line = bench._assemble(os.environ["SRML_BENCH_PROGRESS"], 0.0, baseline_dir=None)
sec = line["secondary"]
assert isinstance(sec.get("pca_mfu"), float) and sec["pca_mfu"] > 0.0, sec
assert sec.get("pca_roofline_bound") in ("compute", "memory"), sec
assert sec.get("pca_device_flops", 0) > 0, sec
print("DEVICE BENCH SMOKE OK: scenario carries measured "
      f"mfu={sec['pca_mfu']} roofline_bound={sec['pca_roofline_bound']}")
PY
rm -rf "$SRML_DEVICE_SMOKE_DIR"

# selection-plane smoke (perf tier): the three strategies must agree — tiled
# bit-for-bit with full, approx (+ parity re-rank) above the recall target
# with exact distances — and the strategy/span telemetry must actually land
python - <<'PY'
import numpy as np, jax.numpy as jnp
from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.ops.knn import exact_knn_single
from spark_rapids_ml_tpu.profiling import counter_totals

rng = np.random.default_rng(0)
X = jnp.asarray(rng.normal(size=(5000, 24)).astype(np.float32))
Q, ones = X[:64], jnp.ones((5000,), bool)
res = {}
# pin the tile BELOW n: the CPU auto-tile (max(8192, n/4)) would degrade
# exact_tiled to exact_full at this size and make the parity check vacuous
config.set("knn.select_tile", 512)
for s in ("exact_full", "exact_tiled", "approx"):
    config.set("knn.selection", s)
    try:
        res[s] = [np.asarray(a) for a in exact_knn_single(Q, X, ones, 10)]
    finally:
        config.unset("knn.selection")
config.unset("knn.select_tile")
np.testing.assert_array_equal(res["exact_full"][1], res["exact_tiled"][1])
np.testing.assert_array_equal(res["exact_full"][0], res["exact_tiled"][0])
ef, ea = res["exact_full"][1], res["approx"][1]
recall = float((ea[:, :, None] == ef[:, None, :]).any(-1).mean())
assert recall >= float(config.get("knn.recall_target")), recall
d2_ref = ((np.asarray(Q)[:, None] - np.asarray(X)[ea]) ** 2).sum(-1)
np.testing.assert_allclose(res["approx"][0], d2_ref, rtol=1e-5, atol=1e-5)
tot = counter_totals()
assert any(k.startswith("knn.select_strategy") for k in tot), tot
print(f"SELECTION SMOKE OK: tiled==full bitwise; approx recall {recall:.3f}")
PY

# pallas-parity smoke (perf tier, docs/design.md §5c): the fused Pallas
# distance+select scan in interpret mode on the 8-device CPU mesh —
# per-shard pallas_call under shard_map through the PRODUCTION
# exact_knn_distributed path must be bit-identical to the XLA path (ids AND
# distances), fused KMeans assignment bit-identical to kmeans_predict, and
# the bf16 pool + parity re-rank must leave nonzero `knn.rerank` counters in
# the exported JSONL (the §5b invariant, read back like a dashboard would)
SRML_PALLAS_SMOKE_DIR="$(mktemp -d)"
SRML_TPU_METRICS_DIR="$SRML_PALLAS_SMOKE_DIR" python - <<'PY'
import os
import numpy as np, jax.numpy as jnp
from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.observability import fit_run, load_run_reports
from spark_rapids_ml_tpu.ops.kmeans import kmeans_predict
from spark_rapids_ml_tpu.ops.knn import exact_knn_distributed, exact_knn_single
from spark_rapids_ml_tpu.parallel.mesh import get_mesh, shard_array
from spark_rapids_ml_tpu.parallel.partition import pad_rows

rng = np.random.default_rng(0)
X = rng.normal(size=(4096, 16)).astype(np.float32)
X[100] = X[7]  # a tie the fused extraction must order like lax.top_k
mesh = get_mesh()
Xp, w, _ = pad_rows(X, mesh.devices.size)
Xd, vd = shard_array(Xp, mesh), shard_array(w > 0, mesh)
Q = X[:64]
d_ref, i_ref = exact_knn_distributed(mesh, Q, Xd, vd, 10)
config.set("knn.selection", "pallas_fused")
try:
    with fit_run(algo="PallasSelectSmoke", site="ci"):
        d_f, i_f = exact_knn_distributed(mesh, Q, Xd, vd, 10)
        config.set("knn.pallas_precision", "bfloat16")
        try:
            db, ib = exact_knn_single(
                jnp.asarray(Q), jnp.asarray(X), jnp.ones((len(X),), bool), 10
            )
        finally:
            config.unset("knn.pallas_precision")
finally:
    config.unset("knn.selection")
np.testing.assert_array_equal(np.asarray(i_f), np.asarray(i_ref))
np.testing.assert_array_equal(np.asarray(d_f), np.asarray(d_ref))
# bf16 pool, exact-f32 distances: the §5b re-rank invariant is idempotent —
# re-running parity_rerank_sq on the returned ids reproduces the returned
# (distances, ids) bit-for-bit (full f32 difference form, no bf16 passes)
from spark_rapids_ml_tpu.ops.knn import parity_rerank_sq
db2, ib2 = parity_rerank_sq(
    jnp.asarray(Q), jnp.asarray(X), jnp.ones((len(X),), bool),
    jnp.asarray(np.asarray(ib)), 10,
)
np.testing.assert_array_equal(np.asarray(db2), np.asarray(db))
np.testing.assert_array_equal(np.asarray(ib2), np.asarray(ib))
# fused assignment bit-identical to the XLA kmeans_predict
centers = jnp.asarray(X[:130])
a_ref = np.asarray(kmeans_predict(jnp.asarray(X), centers))
config.set("knn.selection", "pallas_fused")
try:
    a_f = np.asarray(kmeans_predict(jnp.asarray(X), centers))
finally:
    config.unset("knn.selection")
np.testing.assert_array_equal(a_f, a_ref)
rep = load_run_reports(os.environ["SRML_TPU_METRICS_DIR"])[-1]
c = rep["metrics"]["counters"]
rerank = sum(v for k, v in c.items() if k.startswith("knn.rerank"))
assert rerank > 0, c
assert any(
    "pallas_fused" in k for k in c if k.startswith("knn.select_strategy")
), c
print("PALLAS SELECT SMOKE OK: fused scan bit-identical over the 8-device "
      f"mesh; bf16 re-rank exact ({rerank} rerank counts in the JSONL)")
PY
rm -rf "$SRML_PALLAS_SMOKE_DIR"

# autotune smoke (perf tier, docs/design.md §6i): the offline CLI searches
# two selection knobs on the 8-device CPU mesh and must persist a versioned
# tuning table; then a FRESH process in the default `load` mode must resolve
# from that table with ZERO searches and — in steady state — ZERO extra
# compiles, asserted from the exported JSONL run report's counters (and its
# new `autotune` section), read back like a dashboard would. Tuned outputs
# are asserted bit-identical to the default path (the §6i exactness
# contract for bit-class knobs).
SRML_AUTOTUNE_SMOKE_DIR="$(mktemp -d)"
SRML_TPU_TUNE_DIR="$SRML_AUTOTUNE_SMOKE_DIR/tables" \
python -m spark_rapids_ml_tpu.autotune \
  --knobs selection.strategy,selection.tile --shape 20000,24,10 --replicates 3
SRML_TPU_TUNE_DIR="$SRML_AUTOTUNE_SMOKE_DIR/tables" \
SRML_TPU_METRICS_DIR="$SRML_AUTOTUNE_SMOKE_DIR/metrics" python - <<'PY'
import glob, json, os
import numpy as np, jax.numpy as jnp
from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.observability import fit_run, load_run_reports
from spark_rapids_ml_tpu.ops.knn import exact_knn_single

tables = glob.glob(os.path.join(os.environ["SRML_TPU_TUNE_DIR"], "tuning_*.json"))
assert tables, "autotune CLI wrote no tuning table"
doc = json.load(open(tables[0]))
assert doc["version"] == 1 and doc["entries"], doc
knobs = sorted({e["knob"] for e in doc["entries"].values()})
assert knobs == ["selection.strategy", "selection.tile"], knobs
assert all("provenance" in e and e["speedup"] >= 1.0
           for e in doc["entries"].values()), doc["entries"]

rng = np.random.default_rng(0)
X = jnp.asarray(rng.normal(size=(20000, 24)).astype(np.float32))
Q, ones = X[:64], jnp.ones((20000,), bool)
# default-path reference (table ignored) for the bit-parity check
config.set("autotune.mode", "off")
d_ref, i_ref = [np.asarray(a) for a in exact_knn_single(Q, X, ones, 10)]
config.unset("autotune.mode")
# warm pass in load mode: compiles whatever signature the tuned path picked
with fit_run(algo="AutotuneSmokeWarm", site="ci"):
    exact_knn_single(Q, X, ones, 10)
# steady state: table hits, zero searches, zero extra compiles
with fit_run(algo="AutotuneSmoke", site="ci"):
    d_t, i_t = [np.asarray(a) for a in exact_knn_single(Q, X, ones, 10)]
np.testing.assert_array_equal(i_t, i_ref)
np.testing.assert_array_equal(d_t, d_ref)
rep = load_run_reports(os.environ["SRML_TPU_METRICS_DIR"])[-1]
assert rep["algo"] == "AutotuneSmoke", rep["algo"]
c = rep["metrics"]["counters"]
hits = sum(v for k, v in c.items() if k.startswith("autotune.table_hit"))
searches = sum(v for k, v in c.items() if k.startswith("autotune.searches"))
compiles = sum(v for k, v in c.items() if k.startswith("device.compile{"))
assert hits > 0, c
assert searches == 0, c
assert compiles == 0, c
at = rep.get("autotune") or {}
assert at["mode"] == "load" and at["table_version"] == 1, at
assert at["table_status"] == "loaded" and at["searches"] == 0, at
assert any(v.get("source") == "table" for v in at["knobs"].values()), at
print("AUTOTUNE SMOKE OK: table persisted+reloaded; steady-state load run: "
      f"{hits} table hits, 0 searches, 0 extra compiles; tuned == default "
      "bit-for-bit")
PY
rm -rf "$SRML_AUTOTUNE_SMOKE_DIR"

# bench regression gate (ci/bench_check.py): per-scenario wall times of the two
# newest recorded bench rounds, >25% is a regression. ADVISORY by default —
# wall times track tunnel health as much as code — export
# SRML_BENCH_CHECK_ADVISORY=0 to enforce it as a hard premerge gate
SRML_BENCH_CHECK_ADVISORY="${SRML_BENCH_CHECK_ADVISORY:-1}" python ci/bench_check.py

# JVM half: attempt compile+test where a Scala toolchain exists; always record
# the outcome (ci/jvm_build_status.json) — reference CI runs run_plugin_test.sh
# unconditionally (ci/test.sh:46-47)
./jvm/build.sh || echo "WARN: jvm build attempt failed; see ci/jvm_build_status.json"

# driver entry points
python __graft_entry__.py
echo "CI $MODE PASSED"
