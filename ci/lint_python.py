#!/usr/bin/env python
"""Lint tier (role of reference ci/lint_python.py: black/isort/mypy gate). This
image ships no third-party linters, so the gate is stdlib-only but real:

  * syntax: every file must compile (py_compile)
  * AST checks: unused imports, bare `except:`, mutable default arguments,
    `__all__` names that don't resolve, tabs in indentation
  * silent exception swallowing: a BROAD handler (`except:` / `except
    Exception:` / `except BaseException:`) whose body is only `pass`/`...`
    hides failures the reliability subsystem is supposed to surface — it must
    at least log. Narrow typed catches (`except StopIteration: pass`) stay
    legal control flow; the reliability module itself (which implements the
    handling) and `# noqa: silent-except` lines are exempt.
  * uncached multi-pass re-ingest: a direct `_batch_stream(...)` call inside a
    for/while loop re-uploads every batch on every pass, bypassing the HBM
    batch cache (ops/device_cache.py). Such call sites must pass a `cache=`
    handle (the loop replays passes 2..N from HBM) or hoist the stream out of
    the loop; `# noqa` on the call line exempts.
  * profiling internals poking: any reference to `profiling._counters` /
    `profiling._spans` outside the observability package. Those dicts no
    longer exist — profiling.py is a compat shim over the typed registry
    (observability/registry.py) — and historically direct mutation was how
    scoped FitRun accounting got silently corrupted. Go through the public
    surface (count/add_time/counter_totals/...) or the observability API.
  * uninstrumented model predict: any `jax.jit` use inside
    spark_rapids_ml_tpu/models/*.py. Model-layer predict calls must route
    through `observability.inference.predict_dispatch` (uniform metric names,
    shape-bucket/recompile-sentinel telemetry); jitted kernels belong in ops/,
    where the dispatch helper wraps them. `# noqa` on the line exempts.
  * off-plane top-k: any direct `jax.lax.top_k` / `jax.lax.approx_max_k` (or
    `lax.top_k`, or `from jax.lax import top_k` spellings) inside
    spark_rapids_ml_tpu/ops/ outside ops/selection.py. Every search-plane
    top-k must route through ops/selection.py (select_topk / merge_topk /
    top_k_max) so the strategy knob, the invalid-sentinel convention, and the
    selection telemetry can never be bypassed (mirrors the jax.jit-in-models
    ban). `# noqa` on the line exempts.
  * off-plane pallas: any `jax.experimental.pallas` import (either spelling)
    or `.pallas_call` attribute outside `ops/pallas_*.py`. Raw Pallas kernels
    carry per-toolchain workarounds (Mosaic precision emulation, ragged-edge
    masking, VMEM budgets) and parity contracts that live with the kernel
    modules — a pallas_call elsewhere bypasses the interpret-mode gates, the
    compiled_kernel telemetry routing, and the §5b/§5c sentinel/tie-order
    contracts (mirrors the top_k and cost_analysis fences). `# noqa` on the
    line exempts.
  * off-plane HTTP server: any `http.server` import (or `ThreadingHTTPServer`
    reference) outside observability/server.py. The telemetry endpoint is THE
    driver-resident HTTP plane (refcounted lifecycle, loopback default, zero
    threads when disabled, §6g); other planes — the serving endpoints (§7) —
    mount path-prefix handlers on it via `register_mount` rather than binding
    a second socket. `# noqa` on the line exempts.
  * off-plane device analysis: any `.cost_analysis()` / `.memory_analysis()` /
    `.memory_stats()` reference outside observability/device.py. The
    device-performance plane (docs/design.md §6f) owns XLA cost/memory
    capture and HBM sampling — including the graceful degrade when a runtime
    lacks them; a direct call elsewhere bypasses the capture contract AND the
    no-warning-spam guarantee. `# noqa` on the line exempts.
  * off-plane HLO collective parsing: any string literal that pattern-matches
    HLO collective-op text (a dash-spelled opcode — all-reduce / all-gather /
    reduce-scatter / collective-permute / all-to-all — immediately followed
    by `(`, an escaped `\\(`, or `-start`) outside observability/comm.py.
    The communication plane (docs/design.md §6h) is the ONE HLO-text parser:
    ad-hoc regexes drift from the exporter's collective accounting (exactly
    what happened to the pre-§6h tests/test_collective_counts.py). Prose
    mentions of the opcodes (docstrings, comments) don't match; `# noqa` on
    the literal's first or last line exempts.

  * hard-coded tunables: a module-level `SOMETHING_TILE/BLOCK/MIN_ITEMS/
    MIN_K/BUCKET... = <nonzero int literal>` constant inside
    spark_rapids_ml_tpu/ops/. Numeric tile/block/threshold DEFAULTS live in
    the knob-registry defaults module (spark_rapids_ml_tpu/autotune/
    defaults.py, docs/design.md §6i) and their measured per-platform
    overrides live in tuning tables — a fresh literal in ops/ is a knob the
    autotuner can't see and a re-tuning chore on the next hardware target.
    Zero-valued sentinels (`BLOCK_ROWS = 0` = adaptive) stay legal; `# noqa`
    on the line exempts.

Exit code 1 on any finding; CI runs this before the test tiers (ci/test.sh).
"""

from __future__ import annotations

import ast
import py_compile
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
TARGETS = ["spark_rapids_ml_tpu", "benchmark", "tests", "bench.py", "__graft_entry__.py"]

# modules where dynamic re-export makes unused-import analysis meaningless
UNUSED_IMPORT_EXEMPT = {"__init__.py"}

# the module that IMPLEMENTS exception handling policy is exempt from the
# silent-swallow check (it must classify and rethrow freely)
SILENT_SWALLOW_EXEMPT_PARTS = ("reliability",)

# the observability package (and the shim module itself) may touch profiling
# internals; everyone else goes through the public surface
PROFILING_INTERNALS = {"_counters", "_spans"}
PROFILING_INTERNALS_EXEMPT_PARTS = ("observability", "profiling.py")

_BROAD_EXC_NAMES = {"Exception", "BaseException"}

# top-k primitives whose only legal home under ops/ is ops/selection.py
_TOPK_PRIMS = {"top_k", "approx_max_k"}

# XLA device-analysis surfaces whose only legal home is observability/device.py
_DEVICE_ANALYSIS = {"cost_analysis", "memory_analysis", "memory_stats"}

# HLO collective-op TEXT patterns whose only legal home is observability/comm.py:
# a dash-spelled opcode directly followed by a paren (an HLO call site / a regex
# matching one) or the async -start suffix. Prose mentions don't match.
import re as _re  # stdlib-only gate; localized alias keeps the import obvious

_HLO_PARSE_RE = _re.compile(
    r"(?:all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all)"
    r"(?:-start|\\?\()"
)

# tunable-looking constant names whose numeric defaults belong in the knob
# registry's defaults module (spark_rapids_ml_tpu/autotune/defaults.py)
_TUNABLE_NAME_RE = _re.compile(r"(TILE|BLOCK|MIN_ITEMS|MIN_K|BUCKET)")


def _const_int(node):
    """Evaluate a literal int expression (`2048`, `1 << 16`, `8 * 1024`);
    None for anything else — only plain numeric literals are banned."""
    if isinstance(node, ast.Constant):
        return node.value if (
            isinstance(node.value, int) and not isinstance(node.value, bool)
        ) else None
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _const_int(node.operand)
        return -v if v is not None else None
    if isinstance(node, ast.BinOp):
        left, right = _const_int(node.left), _const_int(node.right)
        if left is None or right is None:
            return None
        try:
            if isinstance(node.op, ast.LShift):
                return left << right
            if isinstance(node.op, ast.Mult):
                return left * right
            if isinstance(node.op, ast.Add):
                return left + right
            if isinstance(node.op, ast.Sub):
                return left - right
            if isinstance(node.op, ast.Pow):
                return left ** right
            if isinstance(node.op, ast.FloorDiv):
                return left // right
        except (OverflowError, ZeroDivisionError, ValueError):
            return None
    return None


def _is_broad_catch(type_node) -> bool:
    """True for `except:`, `except Exception:`, `except BaseException:` and
    tuples containing one of those."""
    if type_node is None:
        return True
    if isinstance(type_node, ast.Name):
        return type_node.id in _BROAD_EXC_NAMES
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad_catch(elt) for elt in type_node.elts)
    return False


def _is_silent_body(body) -> bool:
    """Handler body that cannot possibly record the failure: only pass/..."""
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring or `...`
        return False
    return True


def iter_files():
    for t in TARGETS:
        p = ROOT / t
        if p.is_file():
            yield p
        else:
            yield from sorted(p.rglob("*.py"))


def _names_bound_by_import(node):
    for alias in node.names:
        name = alias.asname or alias.name.split(".")[0]
        yield name, alias


class _UncachedStreamVisitor(ast.NodeVisitor):
    """Flags `_batch_stream(...)` calls lexically inside a for/while loop that
    do not pass a `cache=` keyword — the multi-pass re-ingest shape the HBM
    batch cache exists to eliminate (ops/device_cache.py)."""

    def __init__(self, path: Path, src_lines, findings):
        self.path = path
        self.src_lines = src_lines
        self.findings = findings
        self.loop_depth = 0

    def _visit_loop(self, node):
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = visit_AsyncFor = visit_While = _visit_loop

    def visit_Call(self, node):
        func = node.func
        name = (
            func.id
            if isinstance(func, ast.Name)
            else func.attr if isinstance(func, ast.Attribute) else ""
        )
        if (
            name == "_batch_stream"
            and self.loop_depth > 0
            and not any(kw.arg == "cache" for kw in node.keywords)
        ):
            line = (
                self.src_lines[node.lineno - 1]
                if node.lineno - 1 < len(self.src_lines)
                else ""
            )
            if "noqa" not in line:
                self.findings.append(
                    f"{self.path}:{node.lineno}: _batch_stream call inside a "
                    "loop without a cache= handle (multi-pass re-ingest "
                    "bypassing ops/device_cache)"
                )
        self.generic_visit(node)


def check_file(path: Path) -> list:
    findings = []
    src = path.read_text()
    try:
        py_compile.compile(str(path), doraise=True)
    except py_compile.PyCompileError as e:
        return [f"{path}: syntax error: {e.msg}"]
    tree = ast.parse(src)

    for lineno, line in enumerate(src.splitlines(), 1):
        stripped = line.lstrip(" ")
        if stripped.startswith("\t"):
            findings.append(f"{path}:{lineno}: tab in indentation")

    _UncachedStreamVisitor(path, src.splitlines(), findings).visit(tree)

    # models/ may not call jax.jit directly: predict kernels live in ops/ and
    # route through observability.inference.predict_dispatch so every family
    # reports the same transform metrics + recompile-sentinel telemetry
    if "models" in path.parts and "spark_rapids_ml_tpu" in path.parts:
        src_lines = src.splitlines()
        for node in ast.walk(tree):
            hit = None
            if (
                isinstance(node, ast.Attribute)
                and node.attr == "jit"
                and isinstance(node.value, ast.Name)
                and node.value.id == "jax"
            ):
                hit = "jax.jit"
            elif (
                # `from jax import jit` (any alias) bypasses the attribute
                # form above and must not slip past the gate
                isinstance(node, ast.ImportFrom)
                and node.module
                and node.module.split(".")[0] == "jax"
                and any(alias.name == "jit" for alias in node.names)
            ):
                hit = "from jax import jit"
            if hit is None:
                continue
            line = (
                src_lines[node.lineno - 1]
                if node.lineno - 1 < len(src_lines)
                else ""
            )
            if "noqa" not in line:
                findings.append(
                    f"{path}:{node.lineno}: {hit} in models/ — route "
                    "predict calls through observability.inference."
                    "predict_dispatch (jitted kernels belong in ops/)"
                )

    # ops/ may not call the top-k primitives directly: selection lives in
    # ops/selection.py (strategy knob + invalid-sentinel + telemetry); every
    # other kernel routes through select_topk/merge_topk/top_k_max
    if (
        "ops" in path.parts
        and "spark_rapids_ml_tpu" in path.parts
        and path.name != "selection.py"
    ):
        src_lines = src.splitlines()
        for node in ast.walk(tree):
            hit = None
            if (
                isinstance(node, ast.Attribute)
                and node.attr in _TOPK_PRIMS
                and (
                    # jax.lax.top_k
                    (
                        isinstance(node.value, ast.Attribute)
                        and node.value.attr == "lax"
                    )
                    # lax.top_k (from jax import lax)
                    or (
                        isinstance(node.value, ast.Name)
                        and node.value.id == "lax"
                    )
                )
            ):
                hit = f"direct {node.attr}"
            elif (
                isinstance(node, ast.ImportFrom)
                and node.module == "jax.lax"
                and any(alias.name in _TOPK_PRIMS for alias in node.names)
            ):
                hit = "from jax.lax import top_k/approx_max_k"
            if hit is None:
                continue
            line = (
                src_lines[node.lineno - 1]
                if node.lineno - 1 < len(src_lines)
                else ""
            )
            if "noqa" not in line:
                findings.append(
                    f"{path}:{node.lineno}: {hit} in ops/ — route top-k "
                    "through ops/selection.py (select_topk/merge_topk/"
                    "top_k_max)"
                )

    # ops/ may not hard-code tunable tile/block/threshold constants: numeric
    # defaults live in the knob-registry defaults module (autotune/
    # defaults.py) where the autotuner's tuning tables can override them per
    # (platform, shape-bucket); a fresh literal here is invisible to it
    if "ops" in path.parts and "spark_rapids_ml_tpu" in path.parts:
        src_lines = src.splitlines()
        for node in tree.body:
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            names = [
                t.id for t in targets
                if isinstance(t, ast.Name) and _TUNABLE_NAME_RE.search(t.id)
            ]
            if not names:
                continue
            v = _const_int(value)
            if not v:  # zero = adaptive sentinel, None = not a literal
                continue
            line = (
                src_lines[node.lineno - 1]
                if node.lineno - 1 < len(src_lines)
                else ""
            )
            if "noqa" not in line:
                findings.append(
                    f"{path}:{node.lineno}: hard-coded tunable "
                    f"'{names[0]} = {v}' in ops/ — numeric tile/threshold "
                    "defaults live in spark_rapids_ml_tpu/autotune/"
                    "defaults.py (knob registry, docs/design.md §6i); "
                    "import it or declare a knob"
                )

    # pallas lives in ops/pallas_*.py only: kernels there carry the
    # interpret-mode gates, Mosaic workarounds and parity contracts; any
    # other pallas_call / jax.experimental.pallas import bypasses them
    if not (
        "ops" in path.parts
        and "spark_rapids_ml_tpu" in path.parts
        and path.name.startswith("pallas_")
    ):
        src_lines = src.splitlines()
        for node in ast.walk(tree):
            hit = None
            if isinstance(node, ast.Import) and any(
                alias.name.startswith("jax.experimental.pallas")
                for alias in node.names
            ):
                hit = "import jax.experimental.pallas"
            elif isinstance(node, ast.ImportFrom) and (
                (node.module or "").startswith("jax.experimental.pallas")
                or (
                    node.module == "jax.experimental"
                    and any(a.name == "pallas" for a in node.names)
                )
            ):
                hit = "from jax.experimental import pallas"
            elif isinstance(node, ast.Attribute) and node.attr == "pallas_call":
                hit = "direct pallas_call"
            if hit is None:
                continue
            line = (
                src_lines[node.lineno - 1]
                if node.lineno - 1 < len(src_lines)
                else ""
            )
            if "noqa" not in line:
                findings.append(
                    f"{path}:{node.lineno}: {hit} outside ops/pallas_*.py — "
                    "Pallas kernels live in the pallas kernel modules "
                    "(interpret gates, Mosaic workarounds, §5c parity "
                    "contracts); route through their host wrappers"
                )

    # the stdlib HTTP server lives in observability/server.py only: one
    # driver-resident endpoint (refcounted lifecycle, §6g); the serving plane
    # and anything else mount handlers on it via register_mount (§7)
    if not (path.name == "server.py" and "observability" in path.parts):
        src_lines = src.splitlines()
        for node in ast.walk(tree):
            hit = None
            if isinstance(node, ast.Import) and any(
                alias.name == "http.server" or
                alias.name.startswith("http.server.")
                for alias in node.names
            ):
                hit = "import http.server"
            elif isinstance(node, ast.ImportFrom) and (
                (node.module or "") == "http.server"
                or (node.module or "").startswith("http.server.")
                or (
                    node.module == "http"
                    and any(a.name == "server" for a in node.names)
                )
            ):
                hit = "from http.server import ..."
            elif (
                isinstance(node, (ast.Name, ast.Attribute))
                and (getattr(node, "id", None) == "ThreadingHTTPServer"
                     or getattr(node, "attr", None) == "ThreadingHTTPServer")
            ):
                hit = "ThreadingHTTPServer reference"
            if hit is None:
                continue
            line = (
                src_lines[node.lineno - 1]
                if node.lineno - 1 < len(src_lines)
                else ""
            )
            if "noqa" not in line:
                findings.append(
                    f"{path}:{node.lineno}: {hit} outside observability/"
                    "server.py — one HTTP plane only; mount handlers on it "
                    "via observability.server.register_mount (docs/design.md "
                    "§6g/§7)"
                )

    # XLA cost/memory analysis + memory_stats live in observability/device.py
    # only (the device-performance plane owns capture AND graceful degrade)
    if not (path.name == "device.py" and "observability" in path.parts):
        src_lines = src.splitlines()
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Attribute)
                and node.attr in _DEVICE_ANALYSIS
            ):
                line = (
                    src_lines[node.lineno - 1]
                    if node.lineno - 1 < len(src_lines)
                    else ""
                )
                if "noqa" not in line:
                    findings.append(
                        f"{path}:{node.lineno}: direct .{node.attr}() outside "
                        "observability/device.py — route through the "
                        "device-performance plane (compiled_kernel / "
                        "sample_hbm, docs/design.md §6f)"
                    )

    # HLO collective-op text parsing lives in observability/comm.py only (the
    # communication plane owns extraction AND the payload/replica-group
    # accounting the run reports export — one parser, one truth)
    if not (path.name == "comm.py" and "observability" in path.parts):
        src_lines = src.splitlines()
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Constant) and isinstance(node.value, str)
            ):
                continue
            if not _HLO_PARSE_RE.search(node.value):
                continue
            exempt = False
            for ln in (node.lineno, getattr(node, "end_lineno", node.lineno)):
                line = src_lines[ln - 1] if ln - 1 < len(src_lines) else ""
                if "noqa" in line:
                    exempt = True
            if not exempt:
                findings.append(
                    f"{path}:{node.lineno}: HLO collective-op text pattern in "
                    "a string literal — collective parsing lives in "
                    "observability/comm.py only (extract_collectives / "
                    "collectives_of_computation, docs/design.md §6h)"
                )

    if not any(part in PROFILING_INTERNALS_EXEMPT_PARTS for part in path.parts):
        src_lines = src.splitlines()
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Attribute)
                and node.attr in PROFILING_INTERNALS
                and isinstance(node.value, ast.Name)
                and node.value.id == "profiling"
            ):
                line = (
                    src_lines[node.lineno - 1]
                    if node.lineno - 1 < len(src_lines)
                    else ""
                )
                if "noqa" not in line:
                    findings.append(
                        f"{path}:{node.lineno}: direct use of profiling."
                        f"{node.attr} (the dict no longer exists — go through "
                        "the profiling/observability public surface)"
                    )

    # collect import bindings and all referenced names
    imports = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            if isinstance(node, ast.ImportFrom) and node.module == "__future__":
                continue
            for name, alias in _names_bound_by_import(node):
                if name == "*":
                    continue
                imports.setdefault(name, node.lineno)
        elif isinstance(node, ast.ExceptHandler):
            if node.type is None:
                findings.append(
                    f"{path}:{node.lineno}: bare `except:` (catch Exception)"
                )
            if (
                node.type is not None  # bare except already reported above
                and _is_broad_catch(node.type)
                and _is_silent_body(node.body)
                and not any(part in SILENT_SWALLOW_EXEMPT_PARTS for part in path.parts)
            ):
                src_lines = src.splitlines()
                line = (
                    src_lines[node.lineno - 1]
                    if node.lineno - 1 < len(src_lines)
                    else ""
                )
                if "noqa" not in line:
                    findings.append(
                        f"{path}:{node.lineno}: silent exception swallowing "
                        "(broad `except ...: pass` with no logging)"
                    )
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for default in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None
            ]:
                if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                    findings.append(
                        f"{path}:{default.lineno}: mutable default argument in "
                        f"{node.name}()"
                    )

    used = set()
    exported = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            pass  # attribute roots appear as Name nodes anyway
    for node in ast.walk(tree):  # __all__ may live inside try/except re-export blocks
        if (
            isinstance(node, ast.Assign)
            and any(getattr(t, "id", "") == "__all__" for t in node.targets)
            and isinstance(node.value, (ast.List, ast.Tuple))
        ):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    exported.add(elt.value)

    module_names = {
        n.name
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))
    }
    top_assigned = {
        getattr(t, "id", None)
        for node in tree.body
        if isinstance(node, ast.Assign)
        for t in node.targets
    }
    for name in exported:
        if name not in module_names and name not in top_assigned and name not in imports:
            findings.append(f"{path}: __all__ name '{name}' is not defined")

    if path.name not in UNUSED_IMPORT_EXEMPT:
        src_lines = src.splitlines()
        for name, lineno in imports.items():
            if name in used or name in exported:
                continue
            line = src_lines[lineno - 1] if lineno - 1 < len(src_lines) else ""
            if "noqa" in line:
                continue
            findings.append(f"{path}:{lineno}: unused import '{name}'")
    return findings


def main() -> int:
    all_findings = []
    n = 0
    for path in iter_files():
        n += 1
        all_findings.extend(check_file(path))
    if all_findings:
        print(f"LINT: {len(all_findings)} findings in {n} files")
        for f in all_findings:
            print("  " + f)
        return 1
    print(f"LINT OK: {n} files clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
