#!/usr/bin/env python
"""DEPRECATED shim — the lint tier is now the whole-program analyzer.

Everything this file used to check (the ten plane-fences + the flat hygiene
checks) migrated into the rule registry of `tools/analysis` (docs/design.md
§6j) as `fence/*` and `hygiene/*` rules, joined there by the three cross-file
passes (`purity/*` trace-purity, `locks/*` lock-graph, `metrics/*` metric
contracts). ONE analyzer, one scoped-suppression grammar
(`# noqa: <rule-id>`), one CI tier:

    python -m tools.analysis                 # what ci/test.sh runs
    python -m tools.analysis --list-rules    # the rule catalog
    python -m tools.analysis --explain <id>  # rationale + fix per rule

This shim keeps the historical `python ci/lint_python.py` entry point alive
for muscle memory and external callers; it simply delegates.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    from tools.analysis.__main__ import main as analysis_main

    return analysis_main(["--max-seconds", "10"])


if __name__ == "__main__":
    sys.exit(main())
