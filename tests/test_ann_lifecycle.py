"""ANN index lifecycle (docs/design.md §7b): pipelined out-of-core builds
(bit-identical to the serial loop, retry x prefetch under injected faults),
the versioned on-disk index store with lazy mmap/device load, and incremental
add/delete with bucketed list geometry + tombstone compaction.

The load-bearing contracts (ISSUE 15 acceptance):
  * pipelined build == serial build, byte for byte, with and without faults;
  * save -> load -> search == fit -> search, byte for byte;
  * steady-state incremental adds on a served model compile NOTHING new.
"""

import json
import os

import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu import config, profiling
from spark_rapids_ml_tpu.ops import ann_lifecycle as lc
from spark_rapids_ml_tpu.ops.ann_streaming import (
    _strided_sample_indices,
    resolve_build_batch_rows,
    streaming_ivfflat_build,
    streaming_ivfflat_search,
    streaming_ivfpq_build,
)
from spark_rapids_ml_tpu.reliability import reset_faults


@pytest.fixture(autouse=True)
def lifecycle_env():
    config.set("reliability.backoff_base_s", 0.001)
    config.set("reliability.backoff_max_s", 0.002)
    profiling.reset_counters()
    reset_faults()
    yield
    for key in (
        "reliability.fault_spec",
        "reliability.backoff_base_s",
        "reliability.backoff_max_s",
        "ann.prefetch_depth",
        "ann.build_batch_rows",
        "ann.list_bucket_rows",
        "ann.compact_tombstone_pct",
        "observability.straggler_min_wall_s",
        "serving.max_batch_rows",
        "serving.bucket_min_rows",
    ):
        config.unset(key)
    reset_faults()


def _inject(spec: str) -> None:
    config.set("reliability.fault_spec", spec)
    reset_faults()


def _data(n=1200, d=10, seed=7):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(n, d)).astype(np.float32)


# ------------------------------------------------------------ subsample clamp


def test_strided_sample_exactly_clamped():
    """Regression (ISSUE 15 satellite): `step = max(1, n // min(n, s))` kept
    every stride hit and returned up to ~2x sample_rows rows."""
    for n, s in ((10, 6), (1000, 300), (7, 7), (5, 10), (1 << 18, 1 << 16),
                 (1_000_000, 262_144)):
        m = min(n, s)
        idx = _strided_sample_indices(n, s)
        assert len(idx) == m, (n, s, len(idx))
        assert idx[0] == 0 and (np.diff(idx) > 0).all()
        assert idx[-1] < n
        # spans the dataset: the last sample sits within one stride of the
        # end (a truncated-prefix sample would drop the tail distribution)
        assert idx[-1] >= n - (n // m) - 1, (n, s, idx[-1])
    # the old form's worst case: n just under a multiple of the step
    old = np.arange(0, 10, max(1, 10 // min(10, 6)))
    assert len(old) > 6  # documents the bug the clamp fixes
    assert len(_strided_sample_indices(10, 6)) == 6


def test_build_batch_rows_resolution():
    from spark_rapids_ml_tpu.autotune.defaults import ANN_BUILD_BATCH_ROWS

    assert resolve_build_batch_rows(1000, 8) == ANN_BUILD_BATCH_ROWS
    # an EXPLICITLY-configured streamed-fit geometry wins over the build
    # default (a deployment that sized batches keeps them)...
    config.set("stream_batch_rows", 512)
    try:
        assert resolve_build_batch_rows(1000, 8) == 512
    finally:
        config.unset("stream_batch_rows")
    # ...and the dedicated knob's config pin beats everything
    config.set("ann.build_batch_rows", 123)
    assert resolve_build_batch_rows(1000, 8) == 123


# ------------------------------------------------- pipelined build parity


def test_pipelined_ivfflat_build_bit_identical_to_serial():
    X = _data()
    kw = dict(nlist=16, max_iter=6, seed=3, batch_rows=256)
    config.set("ann.prefetch_depth", 0)  # serial baseline
    serial = streaming_ivfflat_build(X, **kw)
    config.set("ann.prefetch_depth", 2)
    piped = streaming_ivfflat_build(X, **kw)
    for key in ("centers", "center_norms", "cells", "cell_ids", "cell_sizes"):
        np.testing.assert_array_equal(serial[key], piped[key], err_msg=key)


def test_pipelined_ivfpq_build_bit_identical_to_serial():
    X = _data(n=900, d=16, seed=11)
    kw = dict(nlist=8, m_subvectors=4, n_bits=5, max_iter=5, seed=5,
              batch_rows=200)
    config.set("ann.prefetch_depth", 0)
    serial = streaming_ivfpq_build(X, **kw)
    config.set("ann.prefetch_depth", 2)
    piped = streaming_ivfpq_build(X, **kw)
    for key in ("centers", "codebooks", "codes", "cell_ids", "cells"):
        np.testing.assert_array_equal(serial[key], piped[key], err_msg=key)


def test_pipelined_search_bit_identical_to_serial():
    X = _data(n=1500, d=12, seed=29)
    index = streaming_ivfflat_build(X, nlist=16, max_iter=8, seed=3,
                                    batch_rows=400)
    config.set("ann.prefetch_depth", 0)
    d0, i0 = streaming_ivfflat_search(X[:96], index, k=8, nprobe=8, block=32)
    config.set("ann.prefetch_depth", 2)
    d1, i1 = streaming_ivfflat_search(X[:96], index, k=8, nprobe=8, block=32)
    np.testing.assert_array_equal(i0, i1)
    np.testing.assert_array_equal(d0, d1)


# ------------------------------------------------------- retry x prefetch


@pytest.mark.parametrize("spec,site", [
    ("ann_assign:batch=1:raise=OSError", "ann_assign"),
    ("ann_encode:batch=2:raise=OSError", "ann_encode"),
])
def test_retry_mid_pipeline_bit_identical(spec, site):
    """A transient raise= fault at a mid-pipeline batch retries just that
    batch; the built index is bit-identical to the fault-free build."""
    X = _data(n=1000, d=16, seed=31)
    kw = dict(nlist=8, m_subvectors=4, n_bits=5, max_iter=6, seed=5,
              batch_rows=200)
    config.set("ann.prefetch_depth", 2)
    clean = streaming_ivfpq_build(X, **kw)
    _inject(spec)
    faulted = streaming_ivfpq_build(X, **kw)
    totals = profiling.counter_totals()
    assert totals.get(f"reliability.retry.{site}", 0) == 1, totals
    for key in ("centers", "codebooks", "codes", "cell_ids", "cells"):
        np.testing.assert_array_equal(clean[key], faulted[key], err_msg=key)


def test_sleep_fault_straggler_batch_in_timeline():
    """A sleep= fault delaying one assignment batch mid-pipeline must surface
    that batch as a straggler rank (rank = batch ordinal, phase = site) in
    the run's §6h rank/phase timeline — and the build still completes with
    the batch's writes intact."""
    from spark_rapids_ml_tpu.observability import fit_run

    X = _data(n=1024, d=8, seed=17)
    config.set("observability.straggler_min_wall_s", 0.01)
    config.set("ann.prefetch_depth", 1)
    _inject("ann_assign:batch=2:sleep=0.4")
    with fit_run(algo="AnnBuild", site="test") as run:
        index = streaming_ivfflat_build(X, nlist=8, max_iter=4, seed=3,
                                        batch_rows=256)
        view = run.rank_view()
    assert index["cells"].shape[0] == 8
    assert 2 in view["stragglers"], view
    ranks = {r["rank"]: r for r in view["ranks"]}
    assert len(ranks) == 4  # 1024 rows / 256-row batches
    assert "ann_assign" in ranks[2]["phases"], ranks[2]
    slow = ranks[2]["phases"]["ann_assign"]["wall_s"]
    others = [ranks[r]["phases"]["ann_assign"]["wall_s"]
              for r in ranks if r != 2]
    assert slow > max(others), (slow, others)
    # overlap telemetry landed: per-batch stage/drain histograms + counters
    counters = run.report()["metrics"]["counters"]
    assert counters.get("ann.pipeline_batches{site=ann_assign}", 0) == 4


# ------------------------------------------------------------- on-disk store


def test_store_roundtrip_and_generations(tmp_path):
    path = str(tmp_path / "idx")
    arrays = {
        "centers": np.arange(12, dtype=np.float32).reshape(4, 3),
        "cell_ids": np.arange(8, dtype=np.int64).reshape(4, 2),
    }
    lc.save_index(path, arrays, algo="ivfflat", meta={"tombstones": 3})
    loaded, manifest = lc.load_index(path)
    assert manifest["version"] == lc.ANN_FORMAT_VERSION
    assert manifest["algo"] == "ivfflat"
    assert manifest["generation"] == 1
    assert manifest["meta"]["tombstones"] == 3
    for k, v in arrays.items():
        np.testing.assert_array_equal(loaded[k], v)
        assert isinstance(np.asarray(loaded[k]).base, np.memmap)  # lazy
    # COW mmap: in-memory mutation never writes back to the files
    np.asarray(loaded["cell_ids"])[0, 0] = -1
    again, _ = lc.load_index(path)
    assert np.asarray(again["cell_ids"])[0, 0] == 0
    # re-save over a live directory = generation bump
    lc.save_index(path, arrays, algo="ivfflat")
    assert lc.read_manifest(path)["generation"] == 2


def test_store_rejects_corrupt_and_stale(tmp_path):
    path = str(tmp_path / "idx")
    lc.save_index(path, {"a": np.zeros((2, 2), np.float32)}, algo="ivfflat")
    mpath = os.path.join(path, lc.MANIFEST_NAME)
    doc = json.load(open(mpath))
    doc["version"] = 999
    with open(mpath, "w") as f:
        json.dump(doc, f)
    with pytest.raises(ValueError, match="format version"):
        lc.load_index(path)
    with open(mpath, "w") as f:
        f.write("{not json")
    with pytest.raises(ValueError, match="corrupt"):
        lc.load_index(path)


def test_bucket_capacity():
    config.set("ann.list_bucket_rows", 8)
    assert lc.bucket_capacity(1) == 8
    assert lc.bucket_capacity(8) == 8
    assert lc.bucket_capacity(9) == 16
    assert lc.bucket_capacity(100) == 128
    config.set("ann.list_bucket_rows", 32)
    assert lc.bucket_capacity(9) == 32


# ------------------------------------------------------ model save / load


def _fit_ann(X, algo="ivfflat", **params):
    from spark_rapids_ml_tpu.knn import ApproximateNearestNeighbors

    est = ApproximateNearestNeighbors(
        k=8, algorithm=algo, inputCol="features", idCol="id",
        algoParams=dict({"nlist": 16, "nprobe": 8}, **params),
    )
    df = pd.DataFrame({"features": list(X), "id": np.arange(len(X))})
    return est.fit(df)


@pytest.mark.parametrize("algo,params", [
    ("ivfflat", {}),
    ("ivfpq", {"M": 4, "n_bits": 5}),
])
def test_model_save_load_search_bit_identical(tmp_path, algo, params):
    from spark_rapids_ml_tpu.models.knn import ApproximateNearestNeighborsModel

    X = _data(n=600, d=12, seed=3)
    model = _fit_ann(X, algo=algo, **params)
    qdf = pd.DataFrame({"features": list(X[:24]), "id": np.arange(24)})
    _, _, ref = model.kneighbors(qdf)
    path = str(tmp_path / "model")
    model.write().save(path)
    loaded = ApproximateNearestNeighborsModel.load(path)
    _, _, got = loaded.kneighbors(qdf)
    np.testing.assert_array_equal(
        np.stack(ref["indices"]), np.stack(got["indices"])
    )
    np.testing.assert_array_equal(
        np.stack(ref["distances"]), np.stack(got["distances"])
    )
    # params round-tripped too (k, algorithm, algoParams drive the search)
    assert loaded.getK() == model.getK()
    assert loaded.getOrDefault("algorithm") == algo


def test_knn_model_save_load(tmp_path):
    from spark_rapids_ml_tpu.knn import NearestNeighbors
    from spark_rapids_ml_tpu.models.knn import NearestNeighborsModel

    X = _data(n=300, d=6, seed=9)
    model = NearestNeighbors(k=4, inputCol="features").fit(
        pd.DataFrame({"features": list(X)})
    )
    path = str(tmp_path / "nn")
    model.write().save(path)
    loaded = NearestNeighborsModel.load(path)
    ref = model._serving_predict(X[:8])
    got = loaded._serving_predict(X[:8])
    np.testing.assert_array_equal(ref["indices"], got["indices"])
    np.testing.assert_array_equal(ref["distances"], got["distances"])


def test_brute_force_model_not_persistable():
    X = _data(n=50, d=4)
    model = _fit_ann(X, algo="brute_force")
    with pytest.raises(NotImplementedError, match="brute_force"):
        model.write()


def test_lazy_device_load_counters(tmp_path):
    """A loaded index uploads segments on FIRST search only (ann.device_loads
    counts once per segment, later searches replay from the device cache)."""
    from spark_rapids_ml_tpu.models.knn import ApproximateNearestNeighborsModel

    X = _data(n=400, d=8, seed=21)
    model = _fit_ann(X)
    path = str(tmp_path / "m")
    model.write().save(path)
    loaded = ApproximateNearestNeighborsModel.load(path)
    profiling.reset_counters()
    qdf = pd.DataFrame({"features": list(X[:8]), "id": np.arange(8)})
    loaded.kneighbors(qdf)
    first = {
        k: v for k, v in profiling.counter_totals().items()
        if k.startswith("ann.device_loads")
    }
    assert any("attr=cells" in k for k in first), first
    loaded.kneighbors(qdf)
    again = {
        k: v for k, v in profiling.counter_totals().items()
        if k.startswith("ann.device_loads")
    }
    assert again == first  # second search uploaded nothing


# ------------------------------------------------- incremental add / delete


def test_incremental_add_delete_compact():
    X = _data(n=500, d=8, seed=5)
    model = _fit_ann(X)
    model.enable_incremental()
    cells_shape = np.asarray(model._model_attributes["cells"]).shape
    rng = np.random.default_rng(1)
    new = rng.normal(size=(6, 8)).astype(np.float32)
    ids = model.add_items(new)
    # in-slack adds keep the bucketed geometry (the zero-compile contract)
    assert np.asarray(model._model_attributes["cells"]).shape == cells_shape
    qdf = pd.DataFrame({"features": list(new), "id": np.arange(6)})
    _, _, got = model.kneighbors(qdf)
    np.testing.assert_array_equal(np.stack(got["indices"])[:, 0], ids)
    assert np.allclose(np.stack(got["distances"])[:, 0], 0.0)

    assert model.delete_items(ids) == 6
    _, _, after = model.kneighbors(qdf)
    assert not np.isin(np.stack(after["indices"]), ids).any()
    assert model.tombstone_fraction() > 0

    # compaction trigger: force the pct low, one more delete compacts
    config.set("ann.compact_tombstone_pct", 0)
    model.delete_items(model._item_row_ids[:1])
    assert model.tombstone_fraction() == 0.0
    totals = profiling.counter_totals()
    assert totals.get("ann.compactions", 0) >= 1, totals
    assert totals.get("ann.items_added", 0) == 6
    assert totals.get("ann.items_deleted", 0) == 7
    # deleted items stay gone after compaction; survivors still found
    _, _, post = model.kneighbors(qdf)
    assert not np.isin(np.stack(post["indices"]), ids).any()
    _, _, live = model.kneighbors(
        pd.DataFrame({"features": list(X[5:9]), "id": np.arange(4)})
    )
    np.testing.assert_array_equal(
        np.stack(live["indices"])[:, 0], np.arange(5, 9)
    )


def test_incremental_ivfpq_adds_encode():
    X = _data(n=400, d=16, seed=13)
    model = _fit_ann(X, algo="ivfpq", M=4, n_bits=5)
    model.enable_incremental()
    new = _data(n=3, d=16, seed=99) + 4.0
    ids = model.add_items(new)
    # ADC search (wide nprobe) must surface the added items at rank 1 —
    # their codes were host-encoded into the lists
    _, _, got = model.kneighbors(
        pd.DataFrame({"features": list(new), "id": np.arange(3)})
    )
    np.testing.assert_array_equal(np.stack(got["indices"])[:, 0], ids)


def test_incremental_list_growth_when_slack_exhausted():
    X = _data(n=200, d=6, seed=3)
    model = _fit_ann(X, nlist=4)
    model.enable_incremental()
    max_cell0 = np.asarray(model._model_attributes["cells"]).shape[1]
    # overflow one cell deliberately: many copies of one vector all assign
    # to the same list
    flood = np.tile(X[:1], (max_cell0 + 4, 1))
    model.add_items(flood)
    grown = np.asarray(model._model_attributes["cells"]).shape[1]
    assert grown > max_cell0
    assert grown == lc.bucket_capacity(grown)  # still bucketed
    assert profiling.counter_totals().get("ann.list_grows", 0) >= 1


def test_incremental_rejected_for_cagra():
    X = _data(n=300, d=8, seed=3)
    model = _fit_ann(X, algo="cagra")
    with pytest.raises(NotImplementedError, match="CAGRA"):
        model.add_items(X[:2])


def test_kneighbors_with_tombstones_is_read_only_across_tiers():
    """kneighbors on a tombstoned incremental model gathers live rows into
    locals — it must NOT mutate (compact) the model, and the gather must stay
    row-aligned when the live set falls back under the stream threshold
    (the in-core tier's x2/valid operands)."""
    from spark_rapids_ml_tpu.knn import NearestNeighbors

    X = _data(n=120, d=8, seed=51)
    model = NearestNeighbors(k=2, inputCol="features").fit(
        pd.DataFrame({"features": list(X)})
    )
    model.enable_incremental()  # bucketed capacity 128
    deleted = np.asarray(model._model_attributes["item_ids"])[:10].copy()
    model.delete_items(deleted)
    full_bytes = np.asarray(model._model_attributes["item_features"]).nbytes
    shape_before = np.asarray(model._model_attributes["item_features"]).shape
    qdf = pd.DataFrame({"features": list(X[:6])})
    for threshold in (64, full_bytes - 1):
        # 64: gathered live rows STAY over threshold -> blocked scan;
        # full_bytes-1: full array is over but the gathered live set falls
        # UNDER -> the in-core tier runs on the gathered locals (the
        # shape-mismatch regression)
        config.set("stream_threshold_bytes", threshold)
        try:
            _, _, kdf = model.kneighbors(qdf)
        finally:
            config.unset("stream_threshold_bytes")
        assert not np.isin(np.stack(kdf["indices"]), deleted).any()
    # read API: the model's arrays are untouched (a registered serving copy
    # would otherwise see its operand shapes change underneath it)
    assert np.asarray(model._model_attributes["item_features"]).shape \
        == shape_before
    assert model._tombstones == 10


# ------------------------------------------ served model: zero new compiles


def test_served_knn_absorbs_adds_with_zero_new_compiles():
    """THE acceptance contract: a live served kNN model absorbs adds/deletes
    with zero new device.compile{kernel=} entries — the bucketed geometry
    keeps every operand shape, so the AOT cache stays warm."""
    from spark_rapids_ml_tpu import serving
    from spark_rapids_ml_tpu.knn import NearestNeighbors

    config.set("serving.max_batch_rows", 32)
    config.set("serving.bucket_min_rows", 16)
    X = _data(n=100, d=8, seed=41)
    model = NearestNeighbors(k=3, inputCol="features").fit(
        pd.DataFrame({"features": list(X)})
    )
    model.enable_incremental(capacity_rows=256)
    reg = serving.ModelRegistry()
    try:
        reg.register("nn", model)
        ref = reg.predict("nn", X[:8])
        assert ref["indices"].shape == (8, 3)

        def compiles():
            return {
                k: v for k, v in profiling.counter_totals().items()
                if k.startswith("device.compile{")
            }

        c0 = compiles()
        new_vec = X[:2] + 50.0
        ids = model.add_items(new_vec)
        reg.refresh_weights("nn")
        out = reg.predict("nn", new_vec)
        np.testing.assert_array_equal(out["indices"][:, 0], ids)
        model.delete_items(ids[:1])
        reg.refresh_weights("nn")
        out2 = reg.predict("nn", new_vec[:1])
        assert out2["indices"][0, 0] != ids[0]
        delta = {k: v - c0.get(k, 0) for k, v in compiles().items()
                 if v != c0.get(k, 0)}
        assert not delta, f"incremental serving compiled: {delta}"
        totals = profiling.counter_totals()
        assert totals.get("serving.weight_refreshes{model=nn}", 0) == 2
    finally:
        reg.close()


def test_registry_mutate_serializes_with_inflight_batches():
    """registry.mutate(fn) runs the mutation under the entry's execution
    lock: concurrent predict traffic never observes a half-applied mutation
    (or raises on read-only installed device views), and every mutation
    refreshes the HBM weights."""
    import threading

    from spark_rapids_ml_tpu import serving
    from spark_rapids_ml_tpu.knn import NearestNeighbors

    config.set("serving.max_batch_rows", 32)
    config.set("serving.bucket_min_rows", 16)
    X = _data(n=80, d=6, seed=77)
    model = NearestNeighbors(k=2, inputCol="features").fit(
        pd.DataFrame({"features": list(X)})
    )
    model.enable_incremental(capacity_rows=256)
    reg = serving.ModelRegistry()
    errors: list = []
    try:
        reg.register("nn", model)
        stop = threading.Event()

        def client():
            while not stop.is_set():
                try:
                    out = reg.predict("nn", X[:4])
                    assert out["indices"].shape == (4, 2)
                except Exception as e:
                    errors.append(e)
                    return

        threads = [threading.Thread(target=client) for _ in range(3)]
        [t.start() for t in threads]
        added: list = []
        for i in range(8):
            vec = X[:1] + 10.0 * (i + 1)
            reg.mutate("nn", lambda m, v=vec: added.append(m.add_items(v)[0]))
        reg.mutate("nn", lambda m: m.delete_items(np.asarray(added[:4])))
        stop.set()
        [t.join(timeout=10) for t in threads]
        assert not errors, errors[:3]
        # every mutation refreshed the weights; the final state serves
        out = reg.predict("nn", (X[:1] + 80.0))
        assert out["indices"][0, 0] == added[7]
        totals = profiling.counter_totals()
        assert totals.get("serving.weight_refreshes{model=nn}", 0) == 9
    finally:
        reg.close()


# --------------------------------------------------------------- autotune


def test_lifecycle_knobs_registered():
    from spark_rapids_ml_tpu.autotune.knobs import KNOBS

    for name in ("ann.build_batch_rows", "ann.list_bucket_rows",
                 "ann.compact_tombstone_pct"):
        assert name in KNOBS, name
        assert KNOBS[name].config_key == name  # config pin always wins
