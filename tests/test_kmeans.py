"""KMeans parity tests vs sklearn (the reference compares GPU vs Spark ML CPU,
tests/test_kmeans.py)."""

import numpy as np
import pandas as pd
import pytest
from sklearn.cluster import KMeans as SkKMeans
from sklearn.datasets import make_blobs

from spark_rapids_ml_tpu.clustering import KMeans, KMeansModel


def _blobs(n=500, d=8, k=5, seed=0, std=0.5):
    X, y = make_blobs(
        n_samples=n, n_features=d, centers=k, cluster_std=std, random_state=seed
    )
    return X.astype(np.float32), y


def _match_centers(got: np.ndarray, expected: np.ndarray) -> float:
    """Max distance between matched center pairs (greedy match)."""
    from scipy.optimize import linear_sum_assignment
    from scipy.spatial.distance import cdist

    cost = cdist(got, expected)
    r, c = linear_sum_assignment(cost)
    return float(cost[r, c].max())


@pytest.mark.parametrize("init", ["k-means||", "random"])
def test_kmeans_recovers_blobs(init, n_devices):
    X, _ = _blobs()
    df = pd.DataFrame({"features": list(X)})
    est = KMeans(k=5, initMode=init, maxIter=50, seed=7, tol=1e-6)
    est.num_workers = n_devices
    model = est.fit(df)

    sk = SkKMeans(n_clusters=5, n_init=10, random_state=0).fit(X)
    # well-separated blobs: both should find essentially the true centers
    assert _match_centers(model.cluster_centers_, sk.cluster_centers_) < 0.15
    # inertia within 2% of sklearn's
    assert model.inertia_ <= sk.inertia_ * 1.02


def test_kmeans_transform_and_predict(n_devices):
    X, y = _blobs(n=300, d=4, k=3, seed=2)
    df = pd.DataFrame({"features": list(X)})
    model = KMeans(k=3, seed=5, maxIter=40).fit(df)
    out = model.transform(df)
    assert "prediction" in out.columns
    pred = out["prediction"].to_numpy()
    # cluster labels must be consistent: same-blob points share a label
    from sklearn.metrics import adjusted_rand_score

    assert adjusted_rand_score(y, pred) > 0.95
    # single-vector predict agrees with transform
    assert model.predict(X[0]) == pred[0]


def test_kmeans_weighted_fit(n_devices):
    """Sample weights shift centers (weightCol support). Spark requires k > 1, so
    the weighted-mean check uses a well-separated far cluster to isolate one
    center's weighted mean."""
    X = np.array([[0.0], [1.0], [1000.0]], dtype=np.float32)
    w = np.array([1.0, 100.0, 1.0], dtype=np.float32)
    df = pd.DataFrame({"features": list(X), "w": w})
    model = KMeans(k=2, weightCol="w", maxIter=20, initMode="random", seed=1).fit(df)
    centers = np.sort(np.asarray(model.cluster_centers_)[:, 0])
    # cluster 0 = weighted mean of the two near points; cluster 1 = the far point
    expected = (0.0 * 1 + 1.0 * 100) / 101
    assert abs(centers[0] - expected) < 1e-3
    assert abs(centers[1] - 1000.0) < 1e-2


def test_kmeans_tol_zero_remap():
    est = KMeans(k=2, tol=0.0)
    assert est.tpu_params["tol"] == 1.0e-16


def test_kmeans_persistence(tmp_path, n_devices):
    X, _ = _blobs(n=100, d=3, k=2, seed=4)
    df = pd.DataFrame({"features": list(X)})
    model = KMeans(k=2, seed=3).fit(df)
    path = str(tmp_path / "kmeans_model")
    model.save(path)
    loaded = KMeansModel.load(path)
    np.testing.assert_allclose(loaded.cluster_centers_, model.cluster_centers_)
    pred_a = model.transform(df)["prediction"].to_numpy()
    pred_b = loaded.transform(df)["prediction"].to_numpy()
    np.testing.assert_array_equal(pred_a, pred_b)


def test_kmeans_uneven_rows(n_devices):
    """Padding must not create phantom points at the origin."""
    X, _ = _blobs(n=97, d=5, k=3, seed=6)
    X += 100.0  # far from origin: a phantom zero-row would grab a center
    df = pd.DataFrame({"features": list(X)})
    model = KMeans(k=3, seed=0, maxIter=30).fit(df)
    # all centers near the data, none at the origin
    assert np.all(np.linalg.norm(model.cluster_centers_, axis=1) > 50)


def test_kmeans_cosine_clusters_by_direction(n_devices):
    """Spherical kmeans groups by direction, ignoring magnitude (Spark's
    distanceMeasure='cosine' semantics)."""
    rng = np.random.default_rng(0)
    dirs = np.array([[1.0, 0.0], [0.0, 1.0], [-1.0, 0.0]], dtype=np.float32)
    y = rng.integers(0, 3, size=240)
    scales = rng.uniform(0.1, 50.0, size=240)[:, None].astype(np.float32)  # magnitudes vary wildly
    X = (dirs[y] + rng.normal(scale=0.05, size=(240, 2)).astype(np.float32)) * scales
    df = pd.DataFrame({"features": list(X)})
    model = KMeans(k=3, distanceMeasure="cosine", seed=2, maxIter=30).fit(df)
    pred = model.transform(df)["prediction"].to_numpy()
    from sklearn.metrics import adjusted_rand_score

    assert adjusted_rand_score(y, pred) > 0.95
    # centers live on the unit sphere
    np.testing.assert_allclose(
        np.linalg.norm(model.cluster_centers_, axis=1), 1.0, atol=1e-4
    )
    assert model.predict(X[0]) == pred[0]


def test_kmeans_cosine_zero_vector_raises(n_devices):
    X = np.zeros((10, 3), dtype=np.float32)
    X[1:] = 1.0
    df = pd.DataFrame({"features": list(X)})
    with pytest.raises(ValueError, match="zero-length"):
        KMeans(k=2, distanceMeasure="cosine").fit(df)


def test_fast_math_config_matches_parity_clusters(n_devices):
    """fast_math runs assignment distances at MXU bf16: same clustering on
    separated data, model attributes still parity-precision floats."""
    from spark_rapids_ml_tpu import config
    from spark_rapids_ml_tpu.clustering import KMeans

    rng = np.random.default_rng(31)
    X = np.concatenate(
        [rng.normal(-5, 0.5, (60, 6)), rng.normal(5, 0.5, (60, 6))]
    ).astype(np.float32)
    df = pd.DataFrame({"features": list(X)})
    parity = KMeans(k=2, seed=1, maxIter=25).fit(df)
    config.set("fast_math", True)
    try:
        fast = KMeans(k=2, seed=1, maxIter=25).fit(df)
    finally:
        config.unset("fast_math")

    def canon(c):
        c = np.asarray(c)
        return c[np.argsort(c[:, 0])]

    np.testing.assert_allclose(
        canon(parity.cluster_centers_), canon(fast.cluster_centers_), atol=1e-3
    )


def test_kmeans_training_summary(n_devices):
    """Freshly-fit models expose a KMeansSummary (clusterSizes/trainingCost/
    numIter); loaded models do not — Spark semantics. The reference produces no
    summary at all (clustering.py:549-553)."""
    import os
    import tempfile

    rng = np.random.default_rng(4)
    X = np.vstack(
        [rng.normal(-4, 0.5, (70, 3)), rng.normal(4, 0.5, (30, 3))]
    ).astype(np.float32)
    df = pd.DataFrame({"features": list(X)})
    m = KMeans(k=2, seed=1, maxIter=20).fit(df)
    assert m.hasSummary
    s = m.summary
    assert s.k == 2
    assert sorted(s.clusterSizes) == [30, 70]
    assert s.trainingCost == pytest.approx(
        m._model_attributes["inertia"]
    )
    assert s.numIter >= 1
    with tempfile.TemporaryDirectory() as td:
        m.save(os.path.join(td, "m"))
        m2 = KMeansModel.load(os.path.join(td, "m"))
        assert not m2.hasSummary
        with pytest.raises(RuntimeError):
            _ = m2.summary
