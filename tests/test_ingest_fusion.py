"""Zero-copy ingest plane + whole-pipeline fusion (docs/design.md §6k).

Two contracts under test:

* ops/ingest.py stages contiguous, device-castable blocks as VIEWS (no host
  copy, no host conversion — the consuming kernels cast on device), with every
  fallback copy counted into the `ingest.*` ledger; the Arrow FixedSizeList
  fast path extracts the whole design matrix as a view of the Arrow buffer.
* Pipeline fuses a featurize->fit suffix chain (StandardScaler / PCA feeding
  KMeans / LinearRegression / LogisticRegression / PCA) into one streamed
  program per batch, BIT-IDENTICAL to the staged transform->refit path —
  equality is exact (assert_array_equal), not approximate, because both paths
  run the same device expressions on the same batches in the same order.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from spark_rapids_ml_tpu import config, profiling
from spark_rapids_ml_tpu.ops import ingest
from spark_rapids_ml_tpu.reliability import reset_faults


@pytest.fixture(autouse=True)
def fusion_env():
    """Streamed-scale thresholds, fusion on at any size, fresh counters."""
    config.set("stream_threshold_bytes", 1024)
    config.set("stream_batch_rows", 64)
    config.set("pipeline.fuse_min_rows", 1)
    profiling.reset_counters()
    reset_faults()
    yield
    for key in (
        "stream_threshold_bytes",
        "stream_batch_rows",
        "pipeline.fuse",
        "pipeline.fuse_min_rows",
        "ingest.zero_copy",
        "ingest.staging_pool_rows",
        "reliability.fault_spec",
        "reliability.checkpoint_batches",
        "reliability.backoff_base_s",
        "reliability.backoff_max_s",
    ):
        config.unset(key)
    reset_faults()


def _totals():
    return profiling.counter_totals()


def _fused_stages():
    """Sum of the labeled pipeline.fused_stages counter across chain shapes."""
    return sum(
        v for k, v in _totals().items() if k.startswith("pipeline.fused_stages")
    )


# ------------------------------------------------------------- stage_block


def test_stage_block_contiguous_is_zero_copy_view():
    X = np.arange(64, dtype=np.float32).reshape(8, 8)
    blk = ingest.stage_block(X, 2, 6, np.float32)
    assert np.shares_memory(blk, X)
    np.testing.assert_array_equal(blk, X[2:6])
    totals = _totals()
    assert totals["ingest.copies_avoided"] == 1
    assert totals["ingest.bytes_zero_copy"] == blk.nbytes
    assert totals.get("ingest.bytes_copied", 0) == 0
    assert totals["ingest.rows_staged"] == 4


def test_stage_block_device_castable_source_stays_in_source_dtype():
    """Small-int / exact-widening sources ride the device cast: the staged
    block keeps its SOURCE dtype (the kernel casts in-program)."""
    X = np.arange(40, dtype=np.int32).reshape(10, 4)
    blk = ingest.stage_block(X, 0, 10, np.float32)
    assert blk.dtype == np.int32
    assert np.shares_memory(blk, X)


def test_stage_block_noncontiguous_takes_counted_copy():
    X = np.asfortranarray(np.arange(64, dtype=np.float32).reshape(8, 8))
    blk = ingest.stage_block(X, 0, 8, np.float32)
    assert not np.shares_memory(blk, X)
    assert blk.flags.c_contiguous
    np.testing.assert_array_equal(blk, X)
    totals = _totals()
    assert totals["ingest.bytes_copied"] == blk.nbytes
    assert totals.get("ingest.copies_avoided", 0) == 0
    assert totals["ingest.host_convert_s"] >= 0.0


def test_stage_block_narrowing_dtype_takes_counted_copy():
    """float64 -> float32 is NOT device-castable (the device cast is not
    bit-equal to the host astype for all values): counted host conversion."""
    X = np.linspace(0, 1, 32, dtype=np.float64).reshape(8, 4)
    blk = ingest.stage_block(X, 0, 8, np.float32)
    assert blk.dtype == np.float32
    assert not np.shares_memory(blk, X)
    assert _totals()["ingest.bytes_copied"] == blk.nbytes


def test_stage_block_force_copy_owns_the_block():
    X = np.ones((6, 3), dtype=np.float32)
    blk = ingest.stage_block(X, 0, 6, np.float32, force_copy=True)
    assert not np.shares_memory(blk, X)
    blk[:] = 7.0  # caller-owned: mutation must not leak into the source
    assert X[0, 0] == 1.0


def test_stage_block_zero_copy_kill_switch():
    config.set("ingest.zero_copy", False)
    X = np.ones((6, 3), dtype=np.float32)
    blk = ingest.stage_block(X, 0, 6, np.float32)
    assert not np.shares_memory(blk, X)
    assert _totals()["ingest.bytes_copied"] == blk.nbytes


@pytest.mark.parametrize(
    "src,dst,ok",
    [
        (np.float32, np.float32, True),
        (np.float16, np.float32, True),  # exact widening
        (np.float32, np.float64, True),
        (np.float64, np.float32, False),  # narrowing
        (np.int32, np.float32, True),  # small int: IEEE RNE both sides
        (np.int64, np.float32, False),  # canonicalization would narrow it
        (np.bool_, np.float32, True),
    ],
)
def test_device_castable_matrix(src, dst, ok):
    assert ingest._device_castable(np.dtype(src), np.dtype(dst)) is ok


# ------------------------------------------------------------- StagingPool


def test_staging_pool_cpu_never_reuses_buffers(monkeypatch):
    """Where device_put ALIASES host memory (CPU), reuse would let a later
    block overwrite an earlier block's HBM-cache-resident tensor — the pool
    must allocate fresh per call."""
    monkeypatch.setattr(ingest, "_device_put_copies_cache", False)
    pool = ingest.StagingPool(pool_rows=16)
    a = pool.buffer((8, 4), np.float32)
    b = pool.buffer((8, 4), np.float32)
    assert not np.shares_memory(a, b)


def test_staging_pool_double_buffer_ring_on_copying_backends(monkeypatch):
    monkeypatch.setattr(ingest, "_device_put_copies_cache", True)
    pool = ingest.StagingPool(pool_rows=16)
    a = pool.buffer((8, 4), np.float32)
    b = pool.buffer((8, 4), np.float32)
    c = pool.buffer((8, 4), np.float32)
    assert not np.shares_memory(a, b)  # consecutive calls alternate buffers
    assert np.shares_memory(a, c)  # ring of two: third call rewraps the first
    assert a.shape == (8, 4)
    # distinct (dtype, tail) keys get distinct rings
    d = pool.buffer((8, 4), np.float64)
    assert not np.shares_memory(a, d)


def test_staging_pool_grows_past_pool_rows(monkeypatch):
    monkeypatch.setattr(ingest, "_device_put_copies_cache", True)
    pool = ingest.StagingPool(pool_rows=4)
    big = pool.buffer((32, 2), np.float32)
    assert big.shape == (32, 2)


def test_resolve_staging_pool_rows_config_pin_wins():
    config.set("ingest.staging_pool_rows", 123)
    assert ingest.resolve_staging_pool_rows() == 123
    config.unset("ingest.staging_pool_rows")
    from spark_rapids_ml_tpu.autotune.defaults import INGEST_STAGING_POOL_ROWS

    assert ingest.resolve_staging_pool_rows() == INGEST_STAGING_POOL_ROWS


# --------------------------------------------------------- Arrow fast path


def _arrow_table(X, **scalar_cols):
    n, d = X.shape
    fsl = pa.FixedSizeListArray.from_arrays(pa.array(X.reshape(-1)), d)
    cols = {"features": fsl}
    cols.update({k: pa.array(v) for k, v in scalar_cols.items()})
    return pa.table(cols)


def test_arrow_fixed_size_list_extracts_zero_copy():
    from spark_rapids_ml_tpu.core.dataset import extract_feature_data

    rng = np.random.default_rng(3)
    X = rng.normal(size=(200, 6)).astype(np.float32)
    fd = extract_feature_data(_arrow_table(X), input_col="features")
    np.testing.assert_array_equal(fd.features, X)
    totals = _totals()
    assert totals["ingest.bytes_zero_copy"] >= X.nbytes
    assert totals.get("ingest.bytes_copied", 0) == 0


def test_arrow_small_int_source_fits_bit_equal_to_host_cast():
    """int32 Arrow features ride the on-device cast; the fit is bit-identical
    to fitting the host-converted float32 matrix."""
    from spark_rapids_ml_tpu.clustering import KMeans

    rng = np.random.default_rng(5)
    X_int = rng.integers(-1000, 1000, size=(400, 5), dtype=np.int32)
    tbl = _arrow_table(X_int.astype(np.float32))
    # same table, int32 storage
    tbl_int = pa.table(
        {
            "features": pa.FixedSizeListArray.from_arrays(
                pa.array(X_int.reshape(-1)), 5
            )
        }
    )
    m_f32 = KMeans(k=3, seed=11, maxIter=8).fit(tbl)
    m_int = KMeans(k=3, seed=11, maxIter=8).fit(tbl_int)
    np.testing.assert_array_equal(
        np.asarray(m_f32.cluster_centers_), np.asarray(m_int.cluster_centers_)
    )


def test_arrow_fused_pipeline_copies_nothing():
    """The ISSUE acceptance path: Arrow in, fused featurize->fit chain, and
    pass-1 host conversion bytes stay at ZERO — every staged block is a view
    of the Arrow buffer."""
    from spark_rapids_ml_tpu.clustering import KMeans
    from spark_rapids_ml_tpu.feature import StandardScaler
    from spark_rapids_ml_tpu.pipeline import Pipeline

    rng = np.random.default_rng(7)
    X = rng.normal(size=(600, 8)).astype(np.float32)
    pipe = Pipeline(
        stages=[
            StandardScaler(inputCol="features", outputCol="scaled", withMean=True),
            KMeans(k=3, seed=2, maxIter=6, featuresCol="scaled"),
        ]
    )
    model = pipe.fit(_arrow_table(X))
    assert _fused_stages() == 2
    totals = _totals()
    assert totals.get("ingest.bytes_copied", 0) == 0
    assert totals["ingest.bytes_zero_copy"] >= X.nbytes
    report = model.stages[-1].pipeline_report_
    ing = report["ingest"]
    assert ing["bytes_per_row_after"] == 0.0
    assert ing["bytes_per_row_before"] > 0.0


# ------------------------------------- fused vs staged (bit-identical) chains


def _fit_pipe(make_stages, df, fuse):
    config.set("pipeline.fuse", fuse)
    try:
        from spark_rapids_ml_tpu.pipeline import Pipeline

        return Pipeline(stages=make_stages()).fit(df)
    finally:
        config.unset("pipeline.fuse")


def _cluster_df(n=500, d=8, seed=17):
    rng = np.random.default_rng(seed)
    X = np.concatenate(
        [
            rng.normal(-2, 1.0, (n // 2, d)),
            rng.normal(2, 1.0, (n - n // 2, d)),
        ]
    ).astype(np.float32)
    return pd.DataFrame({"features": list(X)})


def test_fused_scale_kmeans_bit_identical_to_staged():
    from spark_rapids_ml_tpu.clustering import KMeans
    from spark_rapids_ml_tpu.feature import StandardScaler

    df = _cluster_df()

    def stages():
        return [
            StandardScaler(
                inputCol="features", outputCol="scaled", withMean=True
            ),
            KMeans(k=2, seed=5, maxIter=10, featuresCol="scaled"),
        ]

    staged = _fit_pipe(stages, df, fuse=False)
    assert _fused_stages() == 0
    fused = _fit_pipe(stages, df, fuse=True)
    assert _fused_stages() == 2
    for attr in ("mean", "std"):
        np.testing.assert_array_equal(
            np.asarray(getattr(fused.stages[0], attr)),
            np.asarray(getattr(staged.stages[0], attr)),
            err_msg=attr,
        )
    np.testing.assert_array_equal(
        np.asarray(fused.stages[1].cluster_centers_),
        np.asarray(staged.stages[1].cluster_centers_),
    )
    out_f = fused.transform(df)
    out_s = staged.transform(df)
    np.testing.assert_array_equal(
        np.asarray(out_f["prediction"]), np.asarray(out_s["prediction"])
    )


def test_fused_scale_pca_bit_identical_to_staged():
    from spark_rapids_ml_tpu.feature import PCA, StandardScaler

    rng = np.random.default_rng(19)
    X = (rng.normal(size=(500, 10)) * np.linspace(1, 3, 10)).astype(np.float32)
    df = pd.DataFrame({"features": list(X)})

    def stages():
        return [
            StandardScaler(
                inputCol="features", outputCol="scaled", withMean=True
            ),
            PCA(k=3, inputCol="scaled"),
        ]

    staged = _fit_pipe(stages, df, fuse=False)
    fused = _fit_pipe(stages, df, fuse=True)
    assert _fused_stages() == 2
    for key in ("components", "explained_variance", "mean"):
        np.testing.assert_array_equal(
            np.asarray(fused.stages[1].get_model_attributes()[key]),
            np.asarray(staged.stages[1].get_model_attributes()[key]),
            err_msg=key,
        )


def test_fused_pca_kmeans_bit_identical_to_staged():
    from spark_rapids_ml_tpu.clustering import KMeans
    from spark_rapids_ml_tpu.feature import PCA

    df = _cluster_df(seed=23, d=10)

    def stages():
        return [
            PCA(k=4, inputCol="features", outputCol="pca_features"),
            KMeans(k=2, seed=9, maxIter=10, featuresCol="pca_features"),
        ]

    staged = _fit_pipe(stages, df, fuse=False)
    fused = _fit_pipe(stages, df, fuse=True)
    assert _fused_stages() == 2
    np.testing.assert_array_equal(
        np.asarray(fused.stages[1].cluster_centers_),
        np.asarray(staged.stages[1].cluster_centers_),
    )


def test_fused_three_stage_chain_bit_identical_and_reported():
    from spark_rapids_ml_tpu.clustering import KMeans
    from spark_rapids_ml_tpu.feature import PCA, StandardScaler

    df = _cluster_df(seed=29, d=10)

    def stages():
        return [
            StandardScaler(
                inputCol="features", outputCol="scaled", withMean=True
            ),
            PCA(k=4, inputCol="scaled", outputCol="pca_features"),
            KMeans(k=2, seed=13, maxIter=10, featuresCol="pca_features"),
        ]

    staged = _fit_pipe(stages, df, fuse=False)
    fused = _fit_pipe(stages, df, fuse=True)
    assert (
        _totals().get("pipeline.fused_stages{chain=scale>project>kmeans}", 0)
        == 3
    )
    np.testing.assert_array_equal(
        np.asarray(fused.stages[2].cluster_centers_),
        np.asarray(staged.stages[2].cluster_centers_),
    )
    out_f = fused.transform(df)
    out_s = staged.transform(df)
    np.testing.assert_array_equal(
        np.asarray(out_f["prediction"]), np.asarray(out_s["prediction"])
    )
    # every chain model carries the parent report with the §6f ingest section
    for model in fused.stages:
        report = model.pipeline_report_
        assert report["algo"] == "Pipeline"
        assert report["ingest"]["rows_staged"] > 0
        assert (
            report["ingest"]["bytes_per_row_after"]
            <= report["ingest"]["bytes_per_row_before"]
        )


def test_fused_scale_linreg_bit_identical_to_staged():
    from spark_rapids_ml_tpu.feature import StandardScaler
    from spark_rapids_ml_tpu.regression import LinearRegression

    rng = np.random.default_rng(31)
    X = rng.normal(size=(500, 6)).astype(np.float32)
    y = (X @ rng.normal(size=6)).astype(np.float32)
    df = pd.DataFrame({"features": list(X), "label": y})

    def stages():
        return [
            StandardScaler(
                inputCol="features", outputCol="scaled", withMean=True
            ),
            LinearRegression(regParam=0.1, featuresCol="scaled"),
        ]

    staged = _fit_pipe(stages, df, fuse=False)
    fused = _fit_pipe(stages, df, fuse=True)
    assert _fused_stages() == 2
    np.testing.assert_array_equal(
        np.asarray(fused.stages[1].coefficients),
        np.asarray(staged.stages[1].coefficients),
    )
    np.testing.assert_array_equal(
        np.asarray(fused.stages[1].intercept),
        np.asarray(staged.stages[1].intercept),
    )


def test_cross_validator_inner_loop_fuses_bit_identical():
    """CrossValidator over a fusable Pipeline: every inner fit fuses (sharing
    one extraction memo + one batch-cache scope via fitMultiple) and the best
    model is bit-identical to the staged CV."""
    from spark_rapids_ml_tpu.classification import LogisticRegression
    from spark_rapids_ml_tpu.evaluation import MulticlassClassificationEvaluator
    from spark_rapids_ml_tpu.feature import StandardScaler
    from spark_rapids_ml_tpu.pipeline import Pipeline
    from spark_rapids_ml_tpu.tuning import CrossValidator, ParamGridBuilder

    rng = np.random.default_rng(37)
    X = np.concatenate(
        [rng.normal(-2, 1, (120, 4)), rng.normal(2, 1, (120, 4))]
    ).astype(np.float32)
    y = np.repeat([0.0, 1.0], 120)
    df = pd.DataFrame({"features": list(X), "label": y})

    def run_cv():
        scaler = StandardScaler(
            inputCol="features", outputCol="scaled", withMean=True
        )
        lr = LogisticRegression(maxIter=20, featuresCol="scaled")
        grid = ParamGridBuilder().addGrid(lr.regParam, [0.0, 0.1]).build()
        cv = CrossValidator(
            estimator=Pipeline(stages=[scaler, lr]),
            estimatorParamMaps=grid,
            evaluator=MulticlassClassificationEvaluator(metricName="accuracy"),
            numFolds=2,
            seed=1,
        )
        return cv.fit(df)

    config.set("pipeline.fuse", False)
    staged_cv = run_cv()
    assert _fused_stages() == 0
    config.set("pipeline.fuse", True)
    fused_cv = run_cv()
    # 2 folds x 2 candidates x 2 stages + best-model refit's 2 stages
    assert _fused_stages() == 10
    np.testing.assert_array_equal(
        np.asarray(fused_cv.bestModel.stages[1].coefficients),
        np.asarray(staged_cv.bestModel.stages[1].coefficients),
    )
    np.testing.assert_array_equal(
        np.asarray(fused_cv.avgMetrics), np.asarray(staged_cv.avgMetrics)
    )


# -------------------------------------------- reliability inside the chain


def test_fused_chain_resumes_bit_identical_after_ingest_fault():
    """A transient ingest fault mid-chain resumes from the last checkpoint and
    the fused models are bit-identical to the fault-free fused run."""
    from spark_rapids_ml_tpu.clustering import KMeans
    from spark_rapids_ml_tpu.feature import StandardScaler

    config.set("reliability.checkpoint_batches", 2)
    config.set("reliability.backoff_base_s", 0.001)
    config.set("reliability.backoff_max_s", 0.002)
    df = _cluster_df(seed=41)

    def stages():
        return [
            StandardScaler(
                inputCol="features", outputCol="scaled", withMean=True
            ),
            KMeans(k=2, seed=7, maxIter=10, featuresCol="scaled"),
        ]

    clean = _fit_pipe(stages, df, fuse=True)
    config.set("reliability.fault_spec", "ingest:batch=3:raise=OSError")
    reset_faults()
    faulted = _fit_pipe(stages, df, fuse=True)
    totals = _totals()
    assert totals.get("reliability.fault.ingest", 0) == 1
    assert totals.get("reliability.resume.ingest", 0) >= 1
    assert _fused_stages() == 4  # both runs fused
    for attr in ("mean", "std"):
        np.testing.assert_array_equal(
            np.asarray(getattr(clean.stages[0], attr)),
            np.asarray(getattr(faulted.stages[0], attr)),
            err_msg=attr,
        )
    np.testing.assert_array_equal(
        np.asarray(clean.stages[1].cluster_centers_),
        np.asarray(faulted.stages[1].cluster_centers_),
    )


# ------------------------------------------------------------ fuse gating


def test_fuse_declines_below_min_rows():
    from spark_rapids_ml_tpu.clustering import KMeans
    from spark_rapids_ml_tpu.feature import StandardScaler

    config.set("pipeline.fuse_min_rows", 10**6)
    df = _cluster_df(seed=43)
    model = _fit_pipe(
        lambda: [
            StandardScaler(inputCol="features", outputCol="scaled"),
            KMeans(k=2, seed=3, maxIter=5, featuresCol="scaled"),
        ],
        df,
        fuse=True,
    )
    assert _fused_stages() == 0
    assert np.asarray(model.stages[1].cluster_centers_).shape == (2, 8)


def test_fuse_declines_in_core_scale_then_stages_fit_fine():
    """Below the stream threshold the data-level gate returns None mid-_fit
    and the staged loop carries the SAME stage list to completion."""
    from spark_rapids_ml_tpu.clustering import KMeans
    from spark_rapids_ml_tpu.feature import StandardScaler

    config.set("stream_threshold_bytes", 1 << 30)
    df = _cluster_df(seed=47)
    model = _fit_pipe(
        lambda: [
            StandardScaler(inputCol="features", outputCol="scaled"),
            KMeans(k=2, seed=3, maxIter=5, featuresCol="scaled"),
        ],
        df,
        fuse=True,
    )
    assert _fused_stages() == 0
    assert np.asarray(model.stages[1].cluster_centers_).shape == (2, 8)


def test_fuse_declines_cosine_kmeans():
    from spark_rapids_ml_tpu.clustering import KMeans
    from spark_rapids_ml_tpu.feature import StandardScaler

    df = _cluster_df(seed=53)
    model = _fit_pipe(
        lambda: [
            StandardScaler(inputCol="features", outputCol="scaled"),
            KMeans(
                k=2,
                seed=3,
                maxIter=5,
                featuresCol="scaled",
                distanceMeasure="cosine",
            ),
        ],
        df,
        fuse=True,
    )
    assert _fused_stages() == 0
    assert np.asarray(model.stages[1].cluster_centers_).shape == (2, 8)


def test_fuse_declines_huber_linreg():
    from spark_rapids_ml_tpu.feature import StandardScaler
    from spark_rapids_ml_tpu.regression import LinearRegression

    rng = np.random.default_rng(59)
    X = rng.normal(size=(400, 5)).astype(np.float32)
    y = (X @ rng.normal(size=5)).astype(np.float32)
    df = pd.DataFrame({"features": list(X), "label": y})
    model = _fit_pipe(
        lambda: [
            StandardScaler(inputCol="features", outputCol="scaled"),
            LinearRegression(loss="huber", featuresCol="scaled"),
        ],
        df,
        fuse=True,
    )
    assert _fused_stages() == 0
    assert np.asarray(model.stages[1].coefficients).shape == (5,)


def test_fuse_declines_unlinked_columns():
    """Terminal reading the RAW features column (not the scaler's output) must
    not fuse — the chain op would corrupt its input."""
    from spark_rapids_ml_tpu.clustering import KMeans
    from spark_rapids_ml_tpu.feature import StandardScaler

    df = _cluster_df(seed=61)
    model = _fit_pipe(
        lambda: [
            StandardScaler(inputCol="features", outputCol="scaled"),
            KMeans(k=2, seed=3, maxIter=5, featuresCol="features"),
        ],
        df,
        fuse=True,
    )
    assert _fused_stages() == 0
    assert np.asarray(model.stages[1].cluster_centers_).shape == (2, 8)
