"""End-to-end execution of the barrier FIT data plane (spark/integration.py::
fit_on_spark + _barrier_train_udf) against a protocol mock with real barrier-task
semantics: N partitions run the udf closure in N concurrent threads, the fake
BarrierTaskContext.allGather is a genuine thread barrier exchanging the
encode/decode_partition_info payloads, and the multi-host global-array assembly
(jax.make_array_from_process_local_data) is simulated by a rank-ordered concat
across the threads onto the real 8-device mesh. pyspark itself is uninstallable
here (no network); this mock drives every line of the plane except the real
jax.distributed process bootstrap, which tests/test_multihost_bootstrap.py covers
with real processes.

Reference analog: the `dataset.mapInPandas(_train_udf).rdd.barrier()` fan-out of
reference core.py:1005-1011."""

import sys
import threading
import types

import numpy as np
import pandas as pd
import pytest

import jax

from spark_rapids_ml_tpu import config as srml_config


# ---------------------------------------------------------------- fake pyspark

class FakeTaskInfo:
    def __init__(self, address="127.0.0.1:0"):
        self.address = address


class FakeBarrierTaskContext:
    """Thread-local barrier context: allGather really blocks until every task of
    the stage has contributed, then all see the full payload list — the semantics
    the udf's control plane depends on."""

    _local = threading.local()
    _stage = None  # set by FakeBarrierRDD before launching threads
    _asm_stage = None  # the GlobalAssembler's stage, for abort-on-failure

    @classmethod
    def get(cls):
        return cls._local.ctx

    def __init__(self, rank, stage):
        self._rank = rank
        self._stage_ref = stage

    def partitionId(self):
        return self._rank

    def getTaskInfos(self):
        return [FakeTaskInfo() for _ in range(self._stage_ref.n_tasks)]

    def allGather(self, payload: str):
        st = self._stage_ref
        with st.lock:
            st.gathered[self._rank] = payload
        st.barrier.wait(timeout=120)
        out = [st.gathered[r] for r in range(st.n_tasks)]
        st.barrier.wait(timeout=120)  # don't reuse the dict until all have read
        return out


class _Stage:
    def __init__(self, n_tasks):
        self.n_tasks = n_tasks
        self.barrier = threading.Barrier(n_tasks)
        self.lock = threading.Lock()
        self.gathered = {}


class GlobalAssembler:
    """Simulates jax.make_array_from_process_local_data for N simulated hosts in
    one real process: each thread contributes its local block; blocks concat in
    rank order into the true global array placed on the real mesh. Call sites run
    in the same order in every thread (w, label?, X), so a per-thread call index
    pairs up corresponding calls."""

    def __init__(self, stage):
        self.stage = stage
        self.calls = {}  # call_idx -> {rank: local}
        self.results = {}  # call_idx -> global jax.Array
        self._tls = threading.local()

    def __call__(self, sharding, local, **kw):
        idx = getattr(self._tls, "idx", 0)
        self._tls.idx = idx + 1
        rank = FakeBarrierTaskContext.get().partitionId()
        st = self.stage
        with st.lock:
            self.calls.setdefault(idx, {})[rank] = np.asarray(local)
        st.barrier.wait(timeout=120)
        with st.lock:
            if idx not in self.results:
                blocks = [self.calls[idx][r] for r in range(st.n_tasks)]
                self.results[idx] = jax.device_put(
                    np.concatenate(blocks, axis=0), sharding
                )
        st.barrier.wait(timeout=120)
        return self.results[idx]


class FakeConf:
    def get(self, key, default=None):
        return {"spark.master": "local[8]"}.get(key, default)


class FakeSparkContext:
    def getConf(self):
        return FakeConf()


class FakeSession:
    def __init__(self):
        self.sparkContext = FakeSparkContext()
        self.version = "3.5.1"


class FakeBarrierRDD:
    def __init__(self, udf, pdf, n_partitions):
        self.udf = udf
        self.pdf = pdf
        self.n_partitions = n_partitions

    def barrier(self):
        return self

    def mapPartitions(self, f):
        return self

    def withResources(self, rp):
        return self

    def collect(self):
        """One thread per barrier task; each consumes its partition as an iterator
        of two batches (mirroring Arrow batch streaming) and runs the udf."""
        stage = _Stage(self.n_partitions)
        FakeBarrierTaskContext._stage = stage
        # a retried stage must get a FRESH assembler stage: an aborted barrier
        # stays broken forever, which would fail every re-run spuriously
        reset_asm = getattr(FakeBarrierTaskContext, "_reset_asm", None)
        if reset_asm is not None:
            reset_asm(self.n_partitions)
        chunks = np.array_split(np.arange(len(self.pdf)), self.n_partitions)
        rows, errs = [], []
        lock = threading.Lock()

        def run(rank, idx):
            FakeBarrierTaskContext._local.ctx = FakeBarrierTaskContext(rank, stage)
            part = self.pdf.iloc[idx].reset_index(drop=True)
            # real Arrow streaming yields ZERO batches for an empty partition —
            # that's the case _collect_partition's guard exists for
            batches = (
                iter([])
                if len(part) == 0
                else iter([part.iloc[: len(part) // 2], part.iloc[len(part) // 2:]])
            )
            try:
                for out_pdf in self.udf(batches):
                    with lock:
                        rows.extend(out_pdf.to_dict("records"))
            except Exception as e:  # surface thread failures to pytest
                with lock:
                    errs.append(e)
                # release peers blocked on either barrier so the suite fails
                # fast instead of deadlocking
                stage.barrier.abort()
                asm_stage = FakeBarrierTaskContext._asm_stage
                if asm_stage is not None:
                    asm_stage.barrier.abort()

        threads = [
            threading.Thread(target=run, args=(r, idx))
            for r, idx in enumerate(chunks)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=150)
        if errs:
            raise errs[0]
        return rows


class _MappedDF:
    def __init__(self, rdd):
        self.rdd = rdd


class FakeFitSparkDF:
    """The DataFrame surface fit_on_spark touches: repartition / mapInPandas /
    sparkSession. Module name makes _is_spark_df treat it as a Spark frame."""

    def __init__(self, pdf, n_partitions=2):
        self._pdf = pdf.reset_index(drop=True)
        self._n_partitions = n_partitions
        self.sparkSession = FakeSession()

    def repartition(self, n):
        return FakeFitSparkDF(self._pdf, n)

    def mapInPandas(self, udf, schema):
        from spark_rapids_ml_tpu.spark.integration import BARRIER_FIT_SCHEMA

        assert schema == BARRIER_FIT_SCHEMA
        return _MappedDF(FakeBarrierRDD(udf, self._pdf, self._n_partitions))

    # transform-plane surface, so model.transform on the fake frame also works
    def limit(self, n):
        return FakeFitSparkDF(self._pdf.head(n), 1)

    def toPandas(self):
        return self._pdf


FakeFitSparkDF.__module__ = "pyspark.sql.mock"


@pytest.fixture
def barrier_env(monkeypatch):
    """Injects the fake pyspark module, no-ops the jax.distributed bootstrap
    (single real process), and patches the global-array assembly to the
    rank-ordered thread concat."""
    fake_pyspark = types.ModuleType("pyspark")
    fake_pyspark.BarrierTaskContext = FakeBarrierTaskContext
    monkeypatch.setitem(sys.modules, "pyspark", fake_pyspark)

    from spark_rapids_ml_tpu.parallel import bootstrap

    boot_calls = []
    monkeypatch.setattr(
        bootstrap,
        "init_process_group",
        lambda **kw: boot_calls.append(kw),
    )
    assembler_holder = {}

    real_make = jax.make_array_from_process_local_data

    def fake_make(sharding, local, **kw):
        return assembler_holder["asm"](sharding, local, **kw)

    monkeypatch.setattr(jax, "make_array_from_process_local_data", fake_make)

    def _reset_asm(n_tasks):
        stage = _Stage(n_tasks)
        assembler_holder["asm"] = GlobalAssembler(stage)
        FakeBarrierTaskContext._asm_stage = stage

    monkeypatch.setattr(FakeBarrierTaskContext, "_reset_asm", _reset_asm, raising=False)

    def install(n_tasks):
        _reset_asm(n_tasks)
        return boot_calls

    install.real_make = real_make
    return install


def _blob_pdf(n=256, d=6, seed=0, label=None):
    rng = np.random.default_rng(seed)
    X = np.concatenate(
        [rng.normal(-2, 1, (n // 2, d)), rng.normal(2, 1, (n - n // 2, d))]
    ).astype(np.float32)
    rng.shuffle(X)
    pdf = pd.DataFrame({"features": list(X)})
    if label == "binary":
        w_true = rng.normal(size=(d,))
        p = 1 / (1 + np.exp(-(X @ w_true)))
        pdf["label"] = (rng.random(n) < p).astype(np.float64)
    elif label == "cont":
        w_true = rng.normal(size=(d,))
        pdf["label"] = (X @ w_true + 0.1 * rng.normal(size=n)).astype(np.float64)
    return pdf


def test_kmeans_fit_on_spark_matches_direct(barrier_env):
    """4 simulated barrier hosts; n divisible by every pad boundary so both data
    planes see byte-identical global arrays -> identical centers."""
    from spark_rapids_ml_tpu.clustering import KMeans
    from spark_rapids_ml_tpu.spark.integration import fit_on_spark

    boot_calls = barrier_env(4)
    pdf = _blob_pdf(n=256)
    est = KMeans(k=2, maxIter=10, seed=7)
    direct = est.fit(pdf)

    sdf = FakeFitSparkDF(pdf, n_partitions=4)
    model = fit_on_spark(KMeans(k=2, maxIter=10, seed=7), sdf, num_hosts=4)

    assert len(boot_calls) == 4  # every simulated host bootstrapped
    ranks = sorted(c["process_id"] for c in boot_calls)
    assert ranks == [0, 1, 2, 3]
    # all hosts agreed on one coordinator (rank 0's)
    assert len({c["coordinator_address"] for c in boot_calls}) == 1
    np.testing.assert_allclose(
        np.sort(np.asarray(model.cluster_centers_), axis=0),
        np.sort(np.asarray(direct.cluster_centers_), axis=0),
        rtol=1e-5,
        atol=1e-5,
    )
    # the barrier-fit model transforms identically to the direct model
    got = model.transform(pdf)["prediction"].to_numpy()
    want = direct.transform(pdf)["prediction"].to_numpy()
    assert (got == want).mean() == 1.0


def test_logreg_fit_on_spark_matches_direct(barrier_env):
    from spark_rapids_ml_tpu.classification import LogisticRegression
    from spark_rapids_ml_tpu.spark.integration import fit_on_spark

    barrier_env(3)
    pdf = _blob_pdf(n=240, label="binary")
    est = LogisticRegression(maxIter=30, regParam=0.01)
    direct = est.fit(pdf)

    sdf = FakeFitSparkDF(pdf, n_partitions=3)
    model = fit_on_spark(LogisticRegression(maxIter=30, regParam=0.01), sdf, num_hosts=3)

    np.testing.assert_allclose(
        np.asarray(model.coefficients), np.asarray(direct.coefficients),
        rtol=1e-4, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(model.intercept), np.asarray(direct.intercept),
        rtol=1e-4, atol=1e-5,
    )


def test_estimator_fit_routes_to_barrier_plane(barrier_env):
    """est.fit(spark_df) with spark_fit_mode=barrier goes through fit_on_spark —
    the dispatch the reference performs inside _fit_internal (core.py:1005-1011)."""
    from spark_rapids_ml_tpu.regression import LinearRegression

    barrier_env(2)
    pdf = _blob_pdf(n=128, label="cont")
    direct = LinearRegression(regParam=0.0).fit(pdf)

    srml_config.set("spark_fit_mode", "barrier")
    try:
        est = LinearRegression(regParam=0.0)
        est._num_workers = 2  # num_hosts for the barrier plane
        model = est.fit(FakeFitSparkDF(pdf, n_partitions=2))
    finally:
        srml_config.unset("spark_fit_mode")
    np.testing.assert_allclose(
        np.asarray(model.coefficients), np.asarray(direct.coefficients),
        rtol=1e-4, atol=1e-4,
    )


def test_fit_report_aggregates_barrier_workers(barrier_env):
    """Driver-side aggregation (observability subsystem): every barrier task
    ships its metrics snapshot alongside the fit result, and the driver's
    FitRun folds them into one report — per-worker breakdown with rank + the
    task's own barrier spans, merged=False in the threaded harness (same
    process: its writes already flowed through the live fan-out)."""
    from spark_rapids_ml_tpu.clustering import KMeans
    from spark_rapids_ml_tpu.observability.export import iter_spans

    barrier_env(4)
    pdf = _blob_pdf(n=256)
    srml_config.set("spark_fit_mode", "barrier")
    try:
        est = KMeans(k=2, maxIter=5, seed=7)
        est._num_workers = 4
        model = est.fit(FakeFitSparkDF(pdf, n_partitions=4))
    finally:
        srml_config.unset("spark_fit_mode")
    rep = model.fit_report_
    assert sorted(w["rank"] for w in rep["workers"]) == [0, 1, 2, 3]
    assert all(w["merged"] is False for w in rep["workers"])
    for w in rep["workers"]:
        assert "barrier.collect" in w["metrics"]["spans"]
        assert "barrier.fit_program" in w["metrics"]["spans"]
    # trace context (§6g): every task's snapshot came back stamped with THIS
    # run's id — the driver joins rows by id, and none is an orphan
    assert all(w["run_id"] == rep["run_id"] for w in rep["workers"]), rep["workers"]
    assert all(w["orphan"] is False for w in rep["workers"])
    assert rep["orphan_snapshots"] == 0
    # the run trace saw every task's spans too (process-global fan-out)
    names = [s["name"] for s in iter_spans(rep)]
    assert names.count("barrier.fit_program") == 4
    # communication plane (§6h): every task's snapshot carried per-rank wall
    # time + phase records, and the report assembles the barrier timeline
    assert [e["rank"] for e in rep["ranks"]["ranks"]] == [0, 1, 2, 3]
    for entry in rep["ranks"]["ranks"]:
        assert entry["wall_s"] is not None and entry["wall_s"] > 0
        assert entry["phases"]["collect"]["rows"] == 64  # 256 rows / 4 ranks
        assert entry["phases"]["collect"]["bytes"] > 0
        assert entry["phases"]["fit_program"]["wall_s"] >= 0
    assert "collect" in rep["ranks"]["skew"]  # 4 ranks -> skew defined


def test_barrier_delayed_rank_flagged_as_straggler(barrier_env):
    """An artificially delayed rank (the barrier_rank delay-fault site, §6h)
    must surface as a straggler: skewed fit_program wall in the timeline, a
    `straggler` event in the run's event log, and the flight-recorder ring."""
    from spark_rapids_ml_tpu.clustering import KMeans
    from spark_rapids_ml_tpu.observability import flight
    from spark_rapids_ml_tpu.reliability import reset_faults

    barrier_env(4)
    flight.reset_flight_recorder()
    pdf = _blob_pdf(n=256)
    srml_config.set("reliability.fault_spec", "barrier_rank:batch=2:sleep=0.4")
    srml_config.set("spark_fit_mode", "barrier")
    reset_faults()
    try:
        est = KMeans(k=2, maxIter=5, seed=7)
        est._num_workers = 4
        model = est.fit(FakeFitSparkDF(pdf, n_partitions=4))
    finally:
        srml_config.unset("spark_fit_mode")
        srml_config.unset("reliability.fault_spec")
        reset_faults()
    rep = model.fit_report_
    assert 2 in rep["ranks"]["stragglers"], rep["ranks"]
    slow = next(e for e in rep["ranks"]["ranks"] if e["rank"] == 2)
    assert slow["straggler"] is True
    assert slow["phases"]["fit_program"]["wall_s"] >= 0.4
    evs = [e for e in rep["events"] if e["kind"] == "straggler"]
    assert any(e["rank"] == 2 for e in evs), rep["events"]
    assert any(e["kind"] == "straggler" for e in flight.snapshot())
    assert any(
        k.startswith("comm.rank_skew") for k in rep["metrics"]["gauges"]
    ), rep["metrics"]["gauges"]


def test_empty_partition_raises_actionable_error(barrier_env):
    from spark_rapids_ml_tpu.clustering import KMeans
    from spark_rapids_ml_tpu.spark.integration import fit_on_spark

    barrier_env(4)
    pdf = _blob_pdf(n=2)  # 2 rows over 4 partitions -> empty barrier partitions
    with pytest.raises(RuntimeError, match="Repartition the input"):
        fit_on_spark(KMeans(k=2), FakeFitSparkDF(pdf, 4), num_hosts=4)


# ------------------------------------------------- reliability: barrier ladder


@pytest.fixture
def reliability_env():
    """Fast deterministic retry policy + armed fault harness, reset afterwards."""
    from spark_rapids_ml_tpu import profiling
    from spark_rapids_ml_tpu.reliability import reset_faults

    srml_config.set("reliability.backoff_base_s", 0.001)
    srml_config.set("reliability.backoff_max_s", 0.002)
    profiling.reset_counters()
    reset_faults()
    yield
    for key in (
        "reliability.fault_spec",
        "reliability.backoff_base_s",
        "reliability.backoff_max_s",
        "reliability.max_attempts",
        "reliability.degrade_to_collect",
        "spark_fit_mode",
    ):
        srml_config.unset(key)
    reset_faults()


def test_barrier_stage_retries_transient_collect_fault(barrier_env, reliability_env):
    """One transient OSError during a task's partition collect aborts the stage;
    fit_on_spark re-runs the whole barrier stage and the model matches the
    direct fit — with the retry visible in the profiling counters."""
    from spark_rapids_ml_tpu import profiling
    from spark_rapids_ml_tpu.clustering import KMeans
    from spark_rapids_ml_tpu.spark.integration import fit_on_spark

    barrier_env(4)
    pdf = _blob_pdf(n=256)
    direct = KMeans(k=2, maxIter=10, seed=7).fit(pdf)

    srml_config.set("reliability.fault_spec", "barrier_collect:raise=OSError")
    model = fit_on_spark(
        KMeans(k=2, maxIter=10, seed=7), FakeFitSparkDF(pdf, 4), num_hosts=4
    )
    totals = profiling.counter_totals()
    assert totals.get("reliability.retry.barrier_stage", 0) >= 1
    assert totals.get("reliability.fault.barrier_collect", 0) == 1
    np.testing.assert_allclose(
        np.sort(np.asarray(model.cluster_centers_), axis=0),
        np.sort(np.asarray(direct.cluster_centers_), axis=0),
        rtol=1e-5,
        atol=1e-5,
    )


def test_barrier_init_retries_with_fresh_port(barrier_env, reliability_env):
    """TOCTOU regression: a failed process-group init (stolen ephemeral port)
    must NOT abort the stage — every rank re-gathers against a freshly probed
    coordinator port and the fit completes in the same barrier stage."""
    from spark_rapids_ml_tpu import profiling
    from spark_rapids_ml_tpu.clustering import KMeans
    from spark_rapids_ml_tpu.spark.integration import fit_on_spark

    boot_calls = barrier_env(4)
    pdf = _blob_pdf(n=256)
    direct = KMeans(k=2, maxIter=10, seed=7).fit(pdf)

    srml_config.set("reliability.fault_spec", "barrier_init:raise=OSError")
    model = fit_on_spark(
        KMeans(k=2, maxIter=10, seed=7), FakeFitSparkDF(pdf, 4), num_hosts=4
    )
    totals = profiling.counter_totals()
    # the init round retried IN-stage (not via a whole-stage re-run)
    assert totals.get("reliability.retry.barrier_init", 0) >= 1
    assert totals.get("reliability.retry.barrier_stage", 0) == 0
    # the retry advertised a FRESH coordinator port (the TOCTOU fix)
    coords = {c["coordinator_address"] for c in boot_calls}
    assert len(coords) == 2, coords
    np.testing.assert_allclose(
        np.sort(np.asarray(model.cluster_centers_), axis=0),
        np.sort(np.asarray(direct.cluster_centers_), axis=0),
        rtol=1e-5,
        atol=1e-5,
    )


def test_barrier_degrades_to_collect_mode(barrier_env, reliability_env):
    """A persistently failing barrier plane must degrade the fit to collect mode
    instead of raising (degradation ladder rung 1), with the degrade counted."""
    from spark_rapids_ml_tpu import profiling
    from spark_rapids_ml_tpu.clustering import KMeans

    barrier_env(2)
    pdf = _blob_pdf(n=128)
    direct = KMeans(k=2, maxIter=10, seed=7).fit(pdf)

    # every stage attempt faults -> fit_on_spark exhausts its retries
    srml_config.set("reliability.fault_spec", "barrier_collect:raise=OSError:times=99")
    srml_config.set("reliability.max_attempts", 2)
    srml_config.set("spark_fit_mode", "barrier")
    est = KMeans(k=2, maxIter=10, seed=7)
    est._num_workers = 2
    model = est.fit(FakeFitSparkDF(pdf, n_partitions=2))

    totals = profiling.counter_totals()
    assert totals.get("reliability.degrade.barrier_to_collect", 0) == 1
    np.testing.assert_allclose(
        np.sort(np.asarray(model.cluster_centers_), axis=0),
        np.sort(np.asarray(direct.cluster_centers_), axis=0),
        rtol=1e-5,
        atol=1e-5,
    )


def test_barrier_degrade_disabled_raises(barrier_env, reliability_env):
    """With reliability.degrade_to_collect off, the exhausted barrier failure
    must propagate (no silent mode switch)."""
    from spark_rapids_ml_tpu.clustering import KMeans

    barrier_env(2)
    pdf = _blob_pdf(n=128)
    srml_config.set("reliability.fault_spec", "barrier_collect:raise=OSError:times=99")
    srml_config.set("reliability.max_attempts", 2)
    srml_config.set("reliability.degrade_to_collect", False)
    srml_config.set("spark_fit_mode", "barrier")
    est = KMeans(k=2, maxIter=10, seed=7)
    est._num_workers = 2
    with pytest.raises(OSError):
        est.fit(FakeFitSparkDF(pdf, n_partitions=2))
