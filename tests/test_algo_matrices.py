"""Per-algorithm parity matrices — the reference's deep test axes re-created for the
TPU framework (reference python/tests/test_logistic_regression.py: sparse x dense,
standardization x regularization grids, sample weights; test_random_forest.py: depth/
bins edges; test_approximate_nearest_neighbors.py: recall grids). Each case is small
enough that the whole module stays in the suite's <10 min budget on the 8-device CPU
mesh."""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu.classification import (
    LogisticRegression,
    RandomForestClassifier,
)
from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.feature import PCA
from spark_rapids_ml_tpu.knn import ApproximateNearestNeighbors
from spark_rapids_ml_tpu.regression import LinearRegression, RandomForestRegressor


def _cls_data(n=160, d=5, seed=0):
    rng = np.random.default_rng(seed)
    X = np.concatenate(
        [rng.normal(-1.5, 1.2, (n // 2, d)), rng.normal(1.5, 0.8, (n - n // 2, d))]
    ).astype(np.float32)
    # heterogeneous column scales exercise the standardization interplay
    X *= np.linspace(0.5, 8.0, d, dtype=np.float32)
    y = np.repeat([0.0, 1.0], [n // 2, n - n // 2])
    return X, y


def _reg_data(n=200, d=6, seed=1):
    rng = np.random.default_rng(seed)
    X = (rng.normal(size=(n, d)) * np.linspace(1, 5, d)).astype(np.float32)
    coef = rng.normal(size=d)
    y = X @ coef + 0.5 + rng.normal(0, 0.05, n)
    return X, y.astype(np.float64)


# ---------------------------------------------------------------------------
# LogisticRegression: standardization x regularization grid (reference
# test_logistic_regression.py's main axis), validated on the FULL objective
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("standardization", [True, False])
@pytest.mark.parametrize(
    "reg,l1r",
    [(0.0, 0.0), (0.05, 0.0), (0.05, 1.0), (0.05, 0.5)],
)
def test_logreg_standardization_reg_grid(standardization, reg, l1r, n_devices):
    from sklearn.linear_model import LogisticRegression as SkLR

    from spark_rapids_ml_tpu.metrics.utils import logistic_regression_objective

    X, y = _cls_data()
    df = pd.DataFrame({"features": list(X), "label": y})
    # FISTA on unstandardized heterogeneous scales is poorly conditioned and
    # legitimately needs more iterations (the reference's CD solver has the same
    # sensitivity); give the L1 paths a bigger budget
    iters = 2000 if (l1r > 0 and not standardization) else 200
    model = LogisticRegression(
        regParam=reg,
        elasticNetParam=l1r,
        standardization=standardization,
        maxIter=iters,
        tol=1e-10,
    ).fit(df)

    ours = logistic_regression_objective(df, model)

    # sklearn twin on the same objective (standardize manually when needed)
    Xs = X.astype(np.float64)
    if standardization:
        std = Xs.std(axis=0, ddof=1)
        Xs = Xs / std
    n = len(y)
    if reg == 0.0:
        sk = SkLR(penalty=None, max_iter=2000, tol=1e-12)
    elif l1r == 0.0:
        sk = SkLR(C=1.0 / (reg * n), max_iter=2000, tol=1e-12)
    elif l1r == 1.0:
        sk = SkLR(C=1.0 / (reg * n), penalty="l1", solver="saga", max_iter=5000, tol=1e-12)
    else:
        sk = SkLR(
            C=1.0 / (reg * n), penalty="elasticnet", l1_ratio=l1r, solver="saga",
            max_iter=5000, tol=1e-12,
        )
    sk.fit(Xs, y)
    # evaluate sklearn's solution under the same objective
    z = Xs @ sk.coef_[0] + sk.intercept_[0]
    p1 = 1.0 / (1.0 + np.exp(-z))
    p_true = np.clip(np.where(y > 0.5, p1, 1.0 - p1), 1e-15, 1.0)
    sk_obj = float(np.mean(-np.log(p_true))) + reg * (
        0.5 * (1 - l1r) * np.sum(sk.coef_**2) + l1r * np.sum(np.abs(sk.coef_))
    )
    assert ours <= sk_obj * 1.01 + 1e-6, (ours, sk_obj)


def test_logreg_sample_weight_equals_duplication(n_devices):
    """Integer sample weights must equal literal row duplication (the reference's
    weight-parity axis)."""
    X, y = _cls_data(n=80)
    w = np.ones(len(y))
    w[: len(y) // 4] = 3.0
    df_w = pd.DataFrame({"features": list(X), "label": y, "w": w})
    dup_rows = np.repeat(np.arange(len(y)), w.astype(int))
    df_dup = pd.DataFrame({"features": list(X[dup_rows]), "label": y[dup_rows]})

    kw = dict(regParam=0.01, maxIter=150, tol=1e-10)
    m_w = LogisticRegression(weightCol="w", **kw).fit(df_w)
    m_dup = LogisticRegression(**kw).fit(df_dup)
    np.testing.assert_allclose(
        m_w.coefficients, m_dup.coefficients, rtol=2e-3, atol=2e-4
    )


def test_logreg_feature_layouts_agree(n_devices):
    """vector-cell column vs multi-col scalar features give identical fits
    (reference exercises all three layouts via create_pyspark_dataframe)."""
    X, y = _cls_data(n=100, d=4)
    df_vec = pd.DataFrame({"features": list(X), "label": y})
    cols = {f"f{j}": X[:, j] for j in range(4)}
    df_multi = pd.DataFrame({**cols, "label": y})

    kw = dict(regParam=0.02, maxIter=100, tol=1e-9)
    m_vec = LogisticRegression(**kw).fit(df_vec)
    m_multi = LogisticRegression(featuresCols=[f"f{j}" for j in range(4)], **kw).fit(
        df_multi
    )
    np.testing.assert_allclose(
        m_vec.coefficients, m_multi.coefficients, rtol=1e-5, atol=1e-6
    )


def test_logreg_threshold_moves_predictions(n_devices):
    # overlapping classes so probabilities spread across (0, 1) instead of
    # saturating — a threshold sweep must then move the decision boundary
    rng = np.random.default_rng(12)
    X = np.concatenate(
        [rng.normal(-0.3, 1.0, (60, 4)), rng.normal(0.3, 1.0, (60, 4))]
    ).astype(np.float32)
    y = np.repeat([0.0, 1.0], 60)
    df = pd.DataFrame({"features": list(X), "label": y})
    model = LogisticRegression(maxIter=60).fit(df)
    lo = model.copy({model.getParam("threshold"): 0.05}).transform(df)
    hi = model.copy({model.getParam("threshold"): 0.95}).transform(df)
    assert lo["prediction"].sum() > hi["prediction"].sum()


# ---------------------------------------------------------------------------
# LinearRegression: weight parity + solver grid
# ---------------------------------------------------------------------------


def test_linreg_sample_weight_equals_duplication(n_devices):
    X, y = _reg_data(n=120)
    w = np.ones(len(y))
    w[:30] = 2.0
    df_w = pd.DataFrame({"features": list(X), "label": y, "w": w})
    dup_rows = np.repeat(np.arange(len(y)), w.astype(int))
    df_dup = pd.DataFrame({"features": list(X[dup_rows]), "label": y[dup_rows]})
    m_w = LinearRegression(weightCol="w", regParam=0.1).fit(df_w)
    m_dup = LinearRegression(regParam=0.1).fit(df_dup)
    np.testing.assert_allclose(
        np.asarray(m_w.coefficients), np.asarray(m_dup.coefficients), rtol=1e-4
    )
    assert m_w.intercept == pytest.approx(m_dup.intercept, rel=1e-3, abs=1e-4)


@pytest.mark.parametrize("fit_intercept", [True, False])
@pytest.mark.parametrize("standardization", [True, False])
def test_linreg_ridge_matches_sklearn(fit_intercept, standardization, n_devices):
    from sklearn.linear_model import Ridge

    X, y = _reg_data()
    df = pd.DataFrame({"features": list(X), "label": y})
    reg = 0.5
    model = LinearRegression(
        regParam=reg, fitIntercept=fit_intercept, standardization=standardization
    ).fit(df)
    X64 = X.astype(np.float64)
    n = len(y)
    if standardization:
        std = X64.std(axis=0, ddof=1)
        Xs = X64 / std
        sk = Ridge(alpha=reg * n, fit_intercept=fit_intercept).fit(Xs, y)
        sk_coef = sk.coef_ / std
    else:
        sk = Ridge(alpha=reg * n, fit_intercept=fit_intercept).fit(X64, y)
        sk_coef = sk.coef_
    np.testing.assert_allclose(
        np.asarray(model.coefficients), sk_coef, rtol=5e-3, atol=5e-4
    )


# ---------------------------------------------------------------------------
# RandomForest: depth/bins/feature-subset edges (reference test_random_forest.py)
# ---------------------------------------------------------------------------


def test_rf_depth_zero_is_majority_vote(n_devices):
    X, y = _cls_data(n=90)
    y[:60] = 0.0  # 2:1 majority
    y[60:] = 1.0
    df = pd.DataFrame({"features": list(X), "label": y})
    model = RandomForestClassifier(numTrees=3, maxDepth=0, seed=1, bootstrap=False).fit(df)
    preds = model.transform(df)["prediction"].to_numpy()
    assert (preds == 0.0).all()


@pytest.mark.parametrize("max_bins", [2, 4, 128])
def test_rf_bins_edges(max_bins, n_devices):
    X, y = _cls_data(n=120)
    df = pd.DataFrame({"features": list(X), "label": y})
    model = RandomForestClassifier(
        numTrees=4, maxDepth=4, maxBins=max_bins, seed=2
    ).fit(df)
    acc = (model.transform(df)["prediction"].to_numpy() == y).mean()
    assert acc > 0.85, (max_bins, acc)


@pytest.mark.parametrize("strategy", ["all", "sqrt", "log2", "onethird", "0.5", "2"])
def test_rf_feature_subset_strategies(strategy, n_devices):
    X, y = _cls_data(n=100)
    df = pd.DataFrame({"features": list(X), "label": y})
    model = RandomForestClassifier(
        numTrees=4, maxDepth=4, featureSubsetStrategy=strategy, seed=3
    ).fit(df)
    acc = (model.transform(df)["prediction"].to_numpy() == y).mean()
    assert acc > 0.8, (strategy, acc)


def test_rf_single_feature(n_devices):
    rng = np.random.default_rng(4)
    X = rng.normal(size=(100, 1)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    df = pd.DataFrame({"features": list(X), "label": y})
    model = RandomForestClassifier(numTrees=3, maxDepth=3, seed=1).fit(df)
    acc = (model.transform(df)["prediction"].to_numpy() == y).mean()
    assert acc > 0.95


def test_rf_regressor_r2(n_devices):
    from sklearn.metrics import r2_score

    X, y = _reg_data(n=250)
    df = pd.DataFrame({"features": list(X), "label": y})
    model = RandomForestRegressor(numTrees=8, maxDepth=6, seed=5).fit(df)
    preds = model.transform(df)["prediction"].to_numpy()
    assert r2_score(y, preds) > 0.8


# ---------------------------------------------------------------------------
# ANN recall grid (reference test_approximate_nearest_neighbors.py)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "algo,algo_params,min_recall",
    [
        ("ivfflat", {"nlist": 8, "nprobe": 8}, 1.0),     # all cells probed = exact
        ("ivfflat", {"nlist": 32, "nprobe": 8}, 0.85),
        ("ivfflat", {"nlist": 32, "nprobe": 2}, 0.4),
        ("ivfpq", {"nlist": 16, "nprobe": 8, "M": 4, "n_bits": 8}, 0.85),
        ("cagra", {"graph_degree": 24, "itopk_size": 96}, 0.9),
    ],
)
def test_ann_recall_grid(algo, algo_params, min_recall, n_devices):
    from sklearn.neighbors import NearestNeighbors as SkNN

    rng = np.random.default_rng(6)
    items = rng.normal(size=(700, 8)).astype(np.float32)
    queries = rng.normal(size=(40, 8)).astype(np.float32)
    est = ApproximateNearestNeighbors(
        k=10, inputCol="features", algorithm=algo, algoParams=algo_params
    )
    est.num_workers = n_devices
    model = est.fit(pd.DataFrame({"features": list(items)}))
    _, _, knn_df = model.kneighbors(pd.DataFrame({"features": list(queries)}))
    _, sk_idx = SkNN(n_neighbors=10).fit(items).kneighbors(queries)
    got = np.stack(knn_df["indices"].to_numpy())
    recall = np.mean([len(set(g) & set(s)) / 10.0 for g, s in zip(got, sk_idx)])
    assert recall >= min_recall, (algo, algo_params, recall)


# ---------------------------------------------------------------------------
# KMeans / PCA extra axes
# ---------------------------------------------------------------------------


def test_kmeans_weight_equals_duplication(n_devices):
    rng = np.random.default_rng(7)
    X = np.concatenate(
        [rng.normal(-3, 0.5, (40, 3)), rng.normal(3, 0.5, (40, 3))]
    ).astype(np.float32)
    w = np.ones(80)
    w[:20] = 3.0
    df_w = pd.DataFrame({"features": list(X), "w": w})
    dup = np.repeat(np.arange(80), w.astype(int))
    df_dup = pd.DataFrame({"features": list(X[dup])})
    m_w = KMeans(k=2, weightCol="w", seed=1, maxIter=30).fit(df_w)
    m_dup = KMeans(k=2, seed=1, maxIter=30).fit(df_dup)

    def canon(c):
        c = np.asarray(c)
        return c[np.argsort(c[:, 0])]

    np.testing.assert_allclose(
        canon(m_w.cluster_centers_), canon(m_dup.cluster_centers_), atol=1e-3
    )


def test_kmeans_tol_zero_still_iterates(n_devices):
    X = np.random.default_rng(8).normal(size=(100, 4)).astype(np.float32)
    df = pd.DataFrame({"features": list(X)})
    model = KMeans(k=3, tol=0.0, maxIter=15, seed=2).fit(df)
    # tol=0 is remapped to a tiny epsilon (reference clustering.py:84-141), so the
    # fit converges by movement rather than spinning to maxIter on fp jitter
    assert model.get_model_attributes()["n_iter"] <= 15


def test_pca_multi_col_layout_and_full_rank(n_devices):
    from sklearn.decomposition import PCA as SkPCA

    rng = np.random.default_rng(9)
    X = (rng.normal(size=(150, 5)) * np.linspace(1, 4, 5)).astype(np.float32)
    cols = {f"f{j}": X[:, j] for j in range(5)}
    df_multi = pd.DataFrame(cols)
    model = PCA(k=5, inputCols=[f"f{j}" for j in range(5)]).fit(df_multi)
    sk = SkPCA(n_components=5).fit(X.astype(np.float64))
    np.testing.assert_allclose(
        np.asarray(model.explained_variance_), sk.explained_variance_, rtol=5e-3
    )
    # full-rank projection preserves pairwise distances
    out = model.transform(df_multi)
    Z = np.stack(out[model.getOrDefault("outputCol")].to_numpy())
    d_orig = np.linalg.norm(X[0] - X[1])
    d_proj = np.linalg.norm(Z[0] - Z[1])
    assert d_proj == pytest.approx(d_orig, rel=1e-3)


# ---------------------------------------------------------------------------
# More reference edge axes: RF single-label, UMAP trustworthiness grid,
# kNN feature layouts
# ---------------------------------------------------------------------------


def test_rf_missing_label_raises_with_guidance(n_devices):
    """Reference parity: RF raises an actionable error when a class in 0..k-1 is
    absent (reference tree.py:415-421); re-indexed labels then fit fine."""
    rng = np.random.default_rng(23)
    X = rng.normal(size=(60, 3)).astype(np.float32)
    df = pd.DataFrame({"features": list(X), "label": np.ones(60)})
    with pytest.raises(RuntimeError, match="missing from the dataset"):
        RandomForestClassifier(numTrees=3, maxDepth=3, seed=1).fit(df)
    # zero-indexed single class trains (one-class forest -> constant prediction)
    df0 = pd.DataFrame({"features": list(X), "label": np.zeros(60)})
    model = RandomForestClassifier(numTrees=3, maxDepth=3, seed=1).fit(df0)
    assert (model.transform(df0)["prediction"].to_numpy() == 0.0).all()


def test_logreg_single_label_inf_intercept(n_devices):
    """Reference parity: one-label LogReg fits a degenerate +-inf-intercept model
    (classification.py:1106-1121) instead of crashing."""
    from spark_rapids_ml_tpu.classification import LogisticRegression

    rng = np.random.default_rng(24)
    X = rng.normal(size=(40, 3)).astype(np.float32)
    df = pd.DataFrame({"features": list(X), "label": np.ones(40)})
    model = LogisticRegression(maxIter=10).fit(df)
    preds = model.transform(df)["prediction"].to_numpy()
    assert (preds == 1.0).all()


@pytest.mark.parametrize("n_neighbors,init", [(5, "random"), (15, "spectral")])
def test_umap_trustworthiness_grid(n_neighbors, init, n_devices):
    from sklearn.manifold import trustworthiness

    from spark_rapids_ml_tpu.umap import UMAP

    rng = np.random.default_rng(25)
    X = np.concatenate(
        [rng.normal(i * 4, 0.8, (50, 8)) for i in range(3)]
    ).astype(np.float32)
    df = pd.DataFrame({"features": list(X)})
    model = UMAP(n_neighbors=n_neighbors, n_epochs=80, seed=4, init=init).fit(df)
    emb = np.asarray(model.embedding_)
    t = trustworthiness(X, emb, n_neighbors=10)
    assert t > 0.8, (n_neighbors, init, t)


def test_knn_multi_col_features(n_devices):
    from sklearn.neighbors import NearestNeighbors as SkNN

    from spark_rapids_ml_tpu.knn import NearestNeighbors

    rng = np.random.default_rng(26)
    items = rng.normal(size=(200, 3)).astype(np.float32)
    queries = rng.normal(size=(20, 3)).astype(np.float32)
    item_df = pd.DataFrame({f"f{j}": items[:, j] for j in range(3)})
    query_df = pd.DataFrame({f"f{j}": queries[:, j] for j in range(3)})
    est = NearestNeighbors(k=5, featuresCols=["f0", "f1", "f2"])
    est.num_workers = n_devices
    model = est.fit(item_df)
    _, _, knn_df = model.kneighbors(query_df)
    got = np.stack(knn_df["indices"].to_numpy())
    _, sk_idx = SkNN(n_neighbors=5).fit(items).kneighbors(queries)
    assert np.mean([len(set(g) & set(s)) / 5 for g, s in zip(got, sk_idx)]) == 1.0


def test_model_n_cols_and_dtype(n_devices):
    """Reference models expose n_cols/dtype; ours derive them from fitted arrays."""
    X, y = _cls_data(n=60, d=5)
    df = pd.DataFrame({"features": list(X), "label": y})
    km = KMeans(k=2, seed=1, maxIter=10).fit(df[["features"]])
    assert km.n_cols == 5 and km.dtype == "float32"
    lr = LogisticRegression(maxIter=10).fit(df)
    assert lr.n_cols == 5
    pca = PCA(k=2, inputCol="features").fit(df[["features"]])
    assert pca.n_cols == 5
