#
# Test harness: a virtual 8-device CPU mesh is the cluster simulator, the TPU analog of
# the reference's `local[N]` multi-GPU Spark session (reference tests/conftest.py:45-86).
# Collectives (psum/all_gather) run genuinely across the 8 XLA host devices — multi-chip
# is simulated by forcing the host platform device count, never by mocking.
#
import os
import sys

# tests always run on the virtual 8-device CPU mesh, even when the ambient env points
# jax at a real accelerator platform. Setting env vars here is NOT sufficient on its
# own: this machine's sitecustomize imports jax at *interpreter startup* (before
# pytest loads conftest) whenever PALLAS_AXON_POOL_IPS is non-empty, binding jax to
# the axon TPU platform — and on a wedged tunnel any later jax.devices() hangs the
# whole suite. The only reliable guard is to re-exec pytest with a clean env so the
# next interpreter never registers the axon plugin at all.
_NEEDS_REEXEC = (
    os.environ.get("JAX_PLATFORMS", "").split(",")[0] != "cpu"
    or os.environ.get("PALLAS_AXON_POOL_IPS", "127.0.0.1") != ""
) and os.environ.get("SRML_TESTS_HERMETIC") != "1"

if not _NEEDS_REEXEC:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()


def _hermetic_reexec(config) -> None:
    """Replace this pytest process with one whose env can never touch the axon
    plugin. Must run from pytest_configure (not module import): pytest's global fd
    capture is active while conftest imports, and an execve at that point leaves the
    new process writing to the about-to-be-discarded capture fd — the suite then
    "passes" with zero visible output."""
    _env = dict(os.environ)
    _env["JAX_PLATFORMS"] = "cpu"
    _env["PALLAS_AXON_POOL_IPS"] = ""
    _env["SRML_TESTS_HERMETIC"] = "1"
    import re as _re

    _flags = _re.sub(
        r"--xla_force_host_platform_device_count=\d+", "", _env.get("XLA_FLAGS", "")
    )
    _env["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.stop_global_capturing()
    os.execve(sys.executable, [sys.executable, "-m", "pytest"] + sys.argv[1:], _env)

import pytest


@pytest.fixture(scope="session", autouse=True)
def _serialize_xla_compiles():
    """Serialize native XLA compiles process-wide for the whole test session.

    This jaxlib's CPU backend_compile_and_load has been observed to SEGFAULT
    intermittently when invoked from concurrent Python threads (reproduced twice
    in --runslow runs: once from the barrier-mock's worker threads compiling the
    same logreg program, once at a later unrelated compile after CrossValidator's
    thread pools had raced compiles). A lock around the compile entry point
    removes the race while leaving all other concurrency (thread barriers,
    allGather exchanges, sharded execution) untouched; compiled programs are
    cached, so the lock is uncontended after first compilation."""
    import threading

    from jax._src import compiler as _jax_compiler

    # the entry point was renamed across jax releases; lock whichever exists
    attr = next(
        (
            a
            for a in ("backend_compile_and_load", "backend_compile")
            if hasattr(_jax_compiler, a)
        ),
        None,
    )
    if attr is None:  # pragma: no cover — future rename: run unlocked
        yield
        return
    real = getattr(_jax_compiler, attr)
    lock = threading.Lock()

    def locked(*a, **kw):
        with lock:
            return real(*a, **kw)

    setattr(_jax_compiler, attr, locked)
    try:
        yield
    finally:
        setattr(_jax_compiler, attr, real)


@pytest.fixture(scope="session")
def n_devices() -> int:
    import jax

    return jax.local_device_count()


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False, help="run slow tests"
    )


def pytest_configure(config):
    if _NEEDS_REEXEC:
        _hermetic_reexec(config)
    config.addinivalue_line("markers", "slow: mark test as slow to run")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip_slow = pytest.mark.skip(reason="need --runslow option to run")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip_slow)
