#
# Test harness: a virtual 8-device CPU mesh is the cluster simulator, the TPU analog of
# the reference's `local[N]` multi-GPU Spark session (reference tests/conftest.py:45-86).
# Collectives (psum/all_gather) run genuinely across the 8 XLA host devices — multi-chip
# is simulated by forcing the host platform device count, never by mocking.
#
import os

# tests always run on the virtual CPU mesh, even when the ambient env points jax at a
# real accelerator platform
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np
import pytest


@pytest.fixture(scope="session")
def n_devices() -> int:
    import jax

    return jax.local_device_count()


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False, help="run slow tests"
    )


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: mark test as slow to run")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip_slow = pytest.mark.skip(reason="need --runslow option to run")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip_slow)
